/* libtpukernels.so — embeds CPython and forwards kernel invocations to
 * the tpukernels Python package (SURVEY.md C10; north-star: "a thin
 * ctypes shim" seen from the C side of the ABI).
 */
#include "tpu_shim.h"

#include <Python.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#ifndef TPK_DEFAULT_ROOT
#define TPK_DEFAULT_ROOT "."
#endif
#ifndef TPK_SITE_PACKAGES
#define TPK_SITE_PACKAGES ""
#endif

static PyObject *g_run_from_c = NULL; /* tpukernels.capi.run_from_c */
static int g_initialized = 0;

static int verbose(void) {
    const char *v = getenv("TPU_KERNELS_VERBOSE");
    return v && v[0] && strcmp(v, "0") != 0;
}

int tpu_init(void) {
    if (g_initialized) return 0;

    if (!Py_IsInitialized()) {
        PyConfig config;
        PyConfig_InitPythonConfig(&config);
        /* Leave stdout/stderr and signal handling to the C host. */
        config.install_signal_handlers = 0;
        PyStatus status = Py_InitializeFromConfig(&config);
        PyConfig_Clear(&config);
        if (PyStatus_Exception(status)) {
            fprintf(stderr, "tpu_shim: Py_InitializeFromConfig failed\n");
            return 1;
        }
    }

    /* Make the kernel package and the venv's site-packages importable.
     * Overridable at runtime; defaults baked in by the Makefile. */
    const char *root = getenv("TPU_KERNELS_ROOT");
    if (!root || !root[0]) root = TPK_DEFAULT_ROOT;
    const char *site = getenv("TPU_KERNELS_SITE");
    if (!site || !site[0]) site = TPK_SITE_PACKAGES;

    char buf[2048];
    snprintf(buf, sizeof(buf),
             "import sys\n"
             "for _p in (r'%s', r'%s'):\n"
             "    if _p and _p not in sys.path:\n"
             "        sys.path.insert(0, _p)\n",
             site, root);
    if (PyRun_SimpleString(buf) != 0) {
        fprintf(stderr, "tpu_shim: failed to extend sys.path\n");
        return 1;
    }

    PyObject *mod = PyImport_ImportModule("tpukernels.capi");
    if (!mod) {
        PyErr_Print();
        fprintf(stderr, "tpu_shim: cannot import tpukernels.capi "
                        "(TPU_KERNELS_ROOT=%s)\n",
                root);
        return 1;
    }
    g_run_from_c = PyObject_GetAttrString(mod, "run_from_c");
    Py_DECREF(mod);
    if (!g_run_from_c || !PyCallable_Check(g_run_from_c)) {
        PyErr_Print();
        fprintf(stderr, "tpu_shim: tpukernels.capi.run_from_c missing\n");
        return 1;
    }
    g_initialized = 1;
    /* Flush-on-exit for every C host, including ones that dlopen the
     * ABI directly and never call tpu_shutdown themselves. */
    atexit(tpu_shutdown);
    if (verbose()) fprintf(stderr, "tpu_shim: initialized (root=%s)\n", root);
    return 0;
}

int tpu_run(const char *name, const char *params_json, void **bufs,
            int nbufs) {
    if (!g_initialized && tpu_init() != 0) return 1;

    PyObject *addrs = PyList_New(nbufs);
    if (!addrs) return 1;
    for (int i = 0; i < nbufs; i++) {
        PyList_SET_ITEM(addrs, i,
                        PyLong_FromUnsignedLongLong((unsigned long long)(uintptr_t)bufs[i]));
    }
    PyObject *res =
        PyObject_CallFunction(g_run_from_c, "ssO", name, params_json, addrs);
    Py_DECREF(addrs);
    if (!res) {
        PyErr_Print();
        fprintf(stderr, "tpu_shim: kernel '%s' raised\n", name);
        return 1;
    }
    long rc = PyLong_AsLong(res);
    Py_DECREF(res);
    if (rc == -1 && PyErr_Occurred()) {
        PyErr_Print();
        return 1;
    }
    return (int)rc;
}

void tpu_shutdown(void) {
    /* Intentionally do NOT Py_FinalizeEx: PJRT/runtime threads may
     * still be alive and finalization ordering with the TPU plugin is
     * undefined (SURVEY.md §7 "hard parts"). The OS reclaims memory at
     * exit — but state that only flushes on clean teardown (the
     * profiler trace) is flushed through a Python-side hook, since a
     * never-finalized interpreter never runs Python atexit handlers. */
    /* A Python host (ctypes/dlopen into a normal interpreter) will
     * have finalized the runtime before C atexit handlers run —
     * touching the C-API then aborts the process. Its own Python
     * atexit hook has already flushed (capi registers one).
     * No run-once latch: shutdown_from_c is idempotent, and a host
     * that calls tpu_shutdown explicitly and then keeps dispatching
     * restarts the profiler trace — the atexit flush must still run
     * for it. */
    if (g_initialized && Py_IsInitialized()) {
        /* The exiting thread may not hold the GIL (or any Python
         * thread state at all) — acquire it properly. */
        PyGILState_STATE gil = PyGILState_Ensure();
        PyObject *mod = PyImport_ImportModule("tpukernels.capi");
        if (mod) {
            PyObject *res =
                PyObject_CallMethod(mod, "shutdown_from_c", NULL);
            if (!res) PyErr_Print();
            Py_XDECREF(res);
            Py_DECREF(mod);
        } else {
            PyErr_Print();
        }
        PyGILState_Release(gil);
    }
    if (verbose()) fprintf(stderr, "tpu_shim: shutdown\n");
}
