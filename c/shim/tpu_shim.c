/* libtpukernels.so — embeds CPython and forwards kernel invocations to
 * the tpukernels Python package (SURVEY.md C10; north-star: "a thin
 * ctypes shim" seen from the C side of the ABI).
 */
#ifndef _GNU_SOURCE
#define _GNU_SOURCE /* on_exit (glibc): exit status for the watchdog */
#endif

#include "tpu_shim.h"

#include <Python.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#ifndef TPK_DEFAULT_ROOT
#define TPK_DEFAULT_ROOT "."
#endif
#ifndef TPK_SITE_PACKAGES
#define TPK_SITE_PACKAGES ""
#endif

static PyObject *g_run_from_c = NULL; /* tpukernels.capi.run_from_c */
static int g_initialized = 0;

static void shutdown_on_exit(int status, void *arg);

static int verbose(void) {
    const char *v = getenv("TPU_KERNELS_VERBOSE");
    return v && v[0] && strcmp(v, "0") != 0;
}

int tpu_init(void) {
    if (g_initialized) return 0;

    if (!Py_IsInitialized()) {
        PyConfig config;
        PyConfig_InitPythonConfig(&config);
        /* Leave stdout/stderr and signal handling to the C host. */
        config.install_signal_handlers = 0;
        PyStatus status = Py_InitializeFromConfig(&config);
        PyConfig_Clear(&config);
        if (PyStatus_Exception(status)) {
            fprintf(stderr, "tpu_shim: Py_InitializeFromConfig failed\n");
            return 1;
        }
    }

    /* A Python host reaching here via ctypes does NOT hold the GIL
     * (ctypes releases it around foreign calls) — every C-API touch
     * below needs it. Recursion-safe for the C host, whose main
     * thread still holds the GIL from Py_InitializeFromConfig. */
    PyGILState_STATE gil = PyGILState_Ensure();

    /* Make the kernel package and the venv's site-packages importable.
     * Overridable at runtime; defaults baked in by the Makefile. */
    const char *root = getenv("TPU_KERNELS_ROOT");
    if (!root || !root[0]) root = TPK_DEFAULT_ROOT;
    const char *site = getenv("TPU_KERNELS_SITE");
    if (!site || !site[0]) site = TPK_SITE_PACKAGES;

    char buf[2048];
    snprintf(buf, sizeof(buf),
             "import sys\n"
             "for _p in (r'%s', r'%s'):\n"
             "    if _p and _p not in sys.path:\n"
             "        sys.path.insert(0, _p)\n",
             site, root);
    int rc = 1;
    if (PyRun_SimpleString(buf) != 0) {
        fprintf(stderr, "tpu_shim: failed to extend sys.path\n");
        goto out;
    }

    PyObject *mod = PyImport_ImportModule("tpukernels.capi");
    if (!mod) {
        PyErr_Print();
        fprintf(stderr, "tpu_shim: cannot import tpukernels.capi "
                        "(TPU_KERNELS_ROOT=%s)\n",
                root);
        goto out;
    }
    g_run_from_c = PyObject_GetAttrString(mod, "run_from_c");
    Py_DECREF(mod);
    if (!g_run_from_c || !PyCallable_Check(g_run_from_c)) {
        PyErr_Print();
        fprintf(stderr, "tpu_shim: tpukernels.capi.run_from_c missing\n");
        goto out;
    }
    rc = 0;
out:
    PyGILState_Release(gil);
    if (rc != 0) return rc;
    g_initialized = 1;
    /* Flush-on-exit for every C host, including ones that dlopen the
     * ABI directly and never call tpu_shutdown themselves. on_exit
     * (not atexit) so the handler sees the host's exit status: the
     * wedged-flush watchdog must _exit with the REAL status — a
     * benchmark that exit(1)ed on a failed check must not be turned
     * into rc=0 (nor a pass into a failure) by the flush bailout. */
    on_exit(shutdown_on_exit, NULL);
    if (verbose()) fprintf(stderr, "tpu_shim: initialized (root=%s)\n", root);
    return 0;
}

int tpu_run(const char *name, const char *params_json, void **bufs,
            int nbufs) {
    if (!g_initialized && tpu_init() != 0) return 1;

    /* See tpu_init: a ctypes host calls in without the GIL. */
    PyGILState_STATE gil = PyGILState_Ensure();
    long rc = 1;
    PyObject *addrs = PyList_New(nbufs);
    if (!addrs) goto out;
    for (int i = 0; i < nbufs; i++) {
        PyList_SET_ITEM(addrs, i,
                        PyLong_FromUnsignedLongLong((unsigned long long)(uintptr_t)bufs[i]));
    }
    PyObject *res =
        PyObject_CallFunction(g_run_from_c, "ssO", name, params_json, addrs);
    Py_DECREF(addrs);
    if (!res) {
        PyErr_Print();
        fprintf(stderr, "tpu_shim: kernel '%s' raised\n", name);
        goto out;
    }
    rc = PyLong_AsLong(res);
    Py_DECREF(res);
    if (rc == -1 && PyErr_Occurred()) {
        PyErr_Print();
        rc = 1;
    }
out:
    PyGILState_Release(gil);
    return (int)rc;
}

/* Flush Python-side teardown state (the profiler trace). Caller must
 * hold the GIL. */
static void flush_python_side(void) {
    PyObject *mod = PyImport_ImportModule("tpukernels.capi");
    if (mod) {
        PyObject *res = PyObject_CallMethod(mod, "shutdown_from_c", NULL);
        if (!res) PyErr_Print();
        Py_XDECREF(res);
        Py_DECREF(mod);
    } else {
        PyErr_Print();
    }
}

static struct {
    pthread_mutex_t mu;
    pthread_cond_t cv;
    unsigned gen;  /* bumps per tpu_shutdown attempt: a worker from a
                    * PRIOR (timed-out, detached) attempt that finally
                    * unparks must neither flush during teardown nor
                    * satisfy the current attempt's wait */
    int done;
    int flushing;  /* worker holds the GIL and is running the flush */
    int abandoned; /* timed out: process teardown is underway */
} g_flush = {PTHREAD_MUTEX_INITIALIZER, PTHREAD_COND_INITIALIZER, 0, 0, 0, 0};

/* Exit status the watchdog re-raises when it has to _exit: the real
 * one when we're inside exit() (recorded by shutdown_on_exit), else a
 * distinctive code for an explicit mid-program tpu_shutdown whose
 * flush wedged (the host intended to continue; 86 marks the kill). */
static int g_exit_status = 86;

static void shutdown_on_exit(int status, void *arg) {
    (void)arg;
    g_exit_status = status;
    tpu_shutdown();
}

/* The GIL timeout below bounds *acquiring* the GIL, but the flush
 * itself (jax.profiler.stop_trace fetching trace data) can block
 * forever through a wedged axon tunnel — on the inline path there is
 * no other bound at all. A detached watchdog forces the exit if a
 * flush attempt is still unfinished after the deadline: by then the
 * host's results are printed and an incomplete trace beats a hung
 * process. TPU_KERNELS_FLUSH_TIMEOUT (seconds, default 30) tunes it —
 * primarily so the wedge path is testable without a 30 s wait. */
static int flush_timeout_s(void) {
    const char *v = getenv("TPU_KERNELS_FLUSH_TIMEOUT");
    if (v && v[0]) {
        int t = atoi(v);
        if (t > 0) return t;
    }
    return 30;
}
static struct {
    pthread_mutex_t mu;
    pthread_cond_t cv;
    unsigned armed_gen; /* bumped when a flush attempt starts */
    unsigned done_gen;  /* advanced to armed_gen when it finishes */
} g_wd = {PTHREAD_MUTEX_INITIALIZER, PTHREAD_COND_INITIALIZER, 0, 0};

static void *flush_watchdog(void *arg) {
    unsigned my_gen = (unsigned)(uintptr_t)arg;
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    /* Strictly later than the worker path's GIL bound (min(t,10)):
     * the clean abandon-flush-and-keep-running outcome must win the
     * race against this _exit, so give it a 5 s head start. */
    ts.tv_sec += flush_timeout_s() + 5;
    pthread_mutex_lock(&g_wd.mu);
    int rc = 0;
    while ((int)(g_wd.done_gen - my_gen) < 0 && rc == 0)
        rc = pthread_cond_timedwait(&g_wd.cv, &g_wd.mu, &ts);
    int done = (int)(g_wd.done_gen - my_gen) >= 0;
    pthread_mutex_unlock(&g_wd.mu);
    if (!done) {
        fprintf(stderr, "tpu_shim: shutdown flush wedged for %ds "
                        "(dead tunnel?); forcing exit\n",
                flush_timeout_s() + 5);
        fflush(NULL); /* don't lose the host's buffered results */
        _exit(g_exit_status);
    }
    return NULL;
}

static void *flush_worker(void *arg) {
    unsigned my_gen = (unsigned)(uintptr_t)arg;
    PyGILState_STATE gil = PyGILState_Ensure();
    /* If the main thread gave up waiting (or a later tpu_shutdown
     * call superseded this attempt), exit() may already be running
     * atexit handlers/static destructors — touching Python or JAX now
     * could segfault a process whose results were already printed.
     * Checked AFTER acquiring the GIL so the common race (GIL freed
     * just past the timeout) skips the flush rather than crashing.
     * Setting `flushing` under the same lock makes the states
     * mutually exclusive: either the timeout abandons a worker still
     * parked on the GIL, or the main thread sees `flushing` and
     * waits for the (brief) flush to finish — never both. */
    pthread_mutex_lock(&g_flush.mu);
    int stale = g_flush.gen != my_gen || g_flush.abandoned;
    if (!stale) g_flush.flushing = 1;
    pthread_mutex_unlock(&g_flush.mu);
    if (!stale) flush_python_side();
    PyGILState_Release(gil);
    pthread_mutex_lock(&g_flush.mu);
    if (g_flush.gen == my_gen) {
        g_flush.done = 1;
        pthread_cond_signal(&g_flush.cv);
    }
    pthread_mutex_unlock(&g_flush.mu);
    return NULL;
}

void tpu_shutdown(void) {
    /* Intentionally do NOT Py_FinalizeEx: PJRT/runtime threads may
     * still be alive and finalization ordering with the TPU plugin is
     * undefined (SURVEY.md §7 "hard parts"). The OS reclaims memory at
     * exit — but state that only flushes on clean teardown (the
     * profiler trace) is flushed through a Python-side hook, since a
     * never-finalized interpreter never runs Python atexit handlers. */
    /* A Python host (ctypes/dlopen into a normal interpreter) will
     * have finalized the runtime before C atexit handlers run —
     * touching the C-API then aborts the process. Its own Python
     * atexit hook has already flushed (capi registers one).
     * No run-once latch: shutdown_from_c is idempotent, and a host
     * that calls tpu_shutdown explicitly and then keeps dispatching
     * restarts the profiler trace — the atexit flush must still run
     * for it. */
    if (g_initialized && Py_IsInitialized()) {
        pthread_t wd;
        pthread_mutex_lock(&g_wd.mu);
        unsigned wd_gen = ++g_wd.armed_gen;
        pthread_mutex_unlock(&g_wd.mu);
        if (pthread_create(&wd, NULL, flush_watchdog,
                           (void *)(uintptr_t)wd_gen) == 0)
            pthread_detach(wd);
        if (PyGILState_Check()) {
            /* Common C-host case: the main thread initialized Python,
             * still holds the GIL, and runs atexit — flush inline. */
            flush_python_side();
        } else {
            /* PyGILState_Ensure has no timeout, and a JAX/PJRT
             * background thread holding the GIL at exit would park
             * this exit handler forever. Bound the wait: acquire the
             * GIL on a helper thread and abandon the flush (losing at
             * worst an unflushed profiler trace) if it can't get the
             * GIL in time — the process must exit. */
            pthread_t t;
            pthread_mutex_lock(&g_flush.mu);
            unsigned my_gen = ++g_flush.gen;
            g_flush.done = 0;
            g_flush.flushing = 0;
            g_flush.abandoned = 0;
            pthread_mutex_unlock(&g_flush.mu);
            if (pthread_create(&t, NULL, flush_worker,
                               (void *)(uintptr_t)my_gen) != 0) {
                fprintf(stderr,
                        "tpu_shim: cannot spawn shutdown flush thread; "
                        "exiting without flush\n");
            } else {
                struct timespec ts;
                clock_gettime(CLOCK_REALTIME, &ts);
                int gil_t = flush_timeout_s();
                ts.tv_sec += gil_t < 10 ? gil_t : 10;
                pthread_mutex_lock(&g_flush.mu);
                int rc = 0;
                while (!g_flush.done && rc == 0)
                    rc = pthread_cond_timedwait(&g_flush.cv, &g_flush.mu,
                                                &ts);
                /* The timeout only abandons a worker still parked on
                 * PyGILState_Ensure. If the flush already started,
                 * wait it out (it's brief) — returning into exit()'s
                 * teardown mid-flush is the crash this code exists
                 * to prevent. */
                if (!g_flush.done && !g_flush.flushing)
                    g_flush.abandoned = 1;
                while (!g_flush.done && !g_flush.abandoned)
                    pthread_cond_wait(&g_flush.cv, &g_flush.mu);
                int done = g_flush.done;
                pthread_mutex_unlock(&g_flush.mu);
                if (done) {
                    pthread_join(t, NULL);
                } else {
                    pthread_detach(t);
                    fprintf(stderr,
                            "tpu_shim: shutdown flush timed out (GIL "
                            "held elsewhere); exiting without flush\n");
                }
            }
        }
        pthread_mutex_lock(&g_wd.mu);
        /* Advance monotonically: two overlapping tpu_shutdown calls
         * (explicit shutdown racing the on_exit handler) must never
         * move done_gen backwards, or the newer attempt's watchdog
         * would keep waiting and _exit a healthy process. */
        if ((int)(g_wd.done_gen - wd_gen) < 0) g_wd.done_gen = wd_gen;
        pthread_cond_broadcast(&g_wd.cv);
        pthread_mutex_unlock(&g_wd.mu);
    }
    if (verbose()) fprintf(stderr, "tpu_shim: shutdown\n");
}
