/* C ABI of libtpukernels.so — the C→TPU bridge (SURVEY.md C10).
 *
 * The benchmark driver hands raw host buffers across this boundary;
 * the shim (which embeds CPython 3.12) wraps them as numpy arrays,
 * dispatches the named kernel through tpukernels.capi → registry →
 * JAX/Pallas → PJRT → TPU, blocks until device completion, and copies
 * results back into the driver's buffers before returning — so the
 * driver's wall-clock timing of tpu_run() is honest (includes H2D/D2H,
 * excludes nothing), symmetric with a CUDA variant timing
 * memcpy+kernel+sync.
 */
#ifndef TPK_TPU_SHIM_H
#define TPK_TPU_SHIM_H

#ifdef __cplusplus
extern "C" {
#endif

/* Initialize the embedded interpreter and import tpukernels.
 * Idempotent. Returns 0 on success. */
int tpu_init(void);

/* Run kernel `name`. `params_json` describes buffer shapes/dtypes/roles
 * and scalar parameters; `bufs` are the raw host pointers in the same
 * order as the JSON "buffers" list. Returns 0 on success. */
int tpu_run(const char *name, const char *params_json, void **bufs,
            int nbufs);

/* Finalize the interpreter (optional; safe to skip at exit). */
void tpu_shutdown(void);

#ifdef __cplusplus
}
#endif
#endif /* TPK_TPU_SHIM_H */
