/* Per-kernel dispatch table (SURVEY.md C3): the load-bearing seam.
 *
 * Each driver binary declares a static table of
 * {device-name -> kernel function} rows; the TPU backend is just one
 * more row whose function forwards through the shim (C10). Adding a
 * backend never touches the driver's timing loop or checker.
 */
#ifndef TPK_DISPATCH_H
#define TPK_DISPATCH_H

#include "bench.h"

#ifdef __cplusplus
extern "C" {
#endif

/* A kernel variant: operates in place on the driver-owned buffers.
 * Returns 0 on success. */
typedef int (*tpk_kern_fn)(const bench_params_t *p, void **bufs);

typedef struct {
    const char *device;
    tpk_kern_fn fn;
} tpk_dispatch_entry;

/* Linear lookup; table is terminated by a {NULL, NULL} row.
 * Exits with a clear message listing known devices when not found. */
tpk_kern_fn tpk_dispatch_lookup(const tpk_dispatch_entry *table,
                                const char *device, const char *kernel);

#ifdef __cplusplus
}
#endif
#endif /* TPK_DISPATCH_H */
