#define _GNU_SOURCE
#include "tpu_client.h"

#include <dlfcn.h>
#include <limits.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

typedef int (*tpu_init_fn)(void);
typedef int (*tpu_run_fn)(const char *, const char *, void **, int);

static tpu_run_fn g_run = NULL;

static void *try_open(const char *path) {
    void *h = dlopen(path, RTLD_NOW | RTLD_GLOBAL);
    return h;
}

void tpk_tpu_ensure(void) {
    if (g_run) return;

    void *h = NULL;
    const char *override = getenv("TPU_KERNELS_SHIM");
    if (override && override[0]) h = try_open(override);
    if (!h) h = try_open("libtpukernels.so");
    if (!h) {
        /* next to the binary (c/bin/) */
        char exe[PATH_MAX];
        ssize_t len = readlink("/proc/self/exe", exe, sizeof(exe) - 1);
        if (len > 0) {
            exe[len] = '\0';
            char *slash = strrchr(exe, '/');
            if (slash) {
                *slash = '\0';
                char path[PATH_MAX + 32];
                snprintf(path, sizeof(path), "%s/libtpukernels.so", exe);
                h = try_open(path);
            }
        }
    }
    if (!h) {
        fprintf(stderr,
                "tpu backend unavailable: cannot load libtpukernels.so (%s)\n"
                "build it with `make -C c` or point TPU_KERNELS_SHIM at it\n",
                dlerror());
        exit(2);
    }

    tpu_init_fn init = (tpu_init_fn)dlsym(h, "tpu_init");
    g_run = (tpu_run_fn)dlsym(h, "tpu_run");
    if (!init || !g_run) {
        fprintf(stderr, "libtpukernels.so is missing tpu_init/tpu_run: %s\n",
                dlerror());
        exit(2);
    }
    if (init() != 0) {
        fprintf(stderr, "tpu_init failed\n");
        exit(2);
    }
}

int tpk_tpu_run(const char *kernel, const char *params_json, void **bufs,
                int nbufs) {
    if (!g_run) tpk_tpu_ensure();
    return g_run(kernel, params_json, bufs, nbufs);
}
