/* Benchmark driver common layer (SURVEY.md C1, C2, C12).
 *
 * The reference tree was empty at survey time, so this layer is a
 * clean-room reconstruction of the canonical shape described in
 * SURVEY.md §1–§3: per-kernel driver binaries owning flag parsing,
 * seeded input init, a warm-up + monotonic-clock timing loop, metric
 * computation, and a golden-output correctness check in which the
 * serial variant is the oracle.
 */
#ifndef TPK_BENCH_H
#define TPK_BENCH_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- generic per-run parameters, shared by every kernel driver ---- */
typedef struct {
    long   n;        /* primary problem size (elements / matrix dim / bodies) */
    long   m, k;     /* extra dims (sgemm), grid dims (stencil)               */
    long   z;        /* third stencil dim (3D)                                */
    long   iters;    /* inner iterations (stencil sweeps, nbody steps)        */
    int    reps;     /* timed repetitions                                     */
    int    check;    /* run golden-output check                               */
    int    verbose;
    int    nbins;    /* histogram bins                                        */
    double alpha, beta;
    double dt;       /* nbody timestep                                        */
    char   device[32];
    unsigned long long seed;
} bench_params_t;

void bench_params_default(bench_params_t *p);

/* Parse the common flags (--device=, --n=, --m=, --k=, --z=, --iters=,
 * --reps=, --check, --alpha=, --beta=, --nbins=, --dt=, --seed=,
 * --verbose). Unknown flags abort with usage. Always enforces
 * reps >= 1 and n >= 1 (a 0-rep timing loop reports garbage; no
 * driver treats n==0 as a sentinel). */
void bench_parse_args(bench_params_t *p, int argc, char **argv,
                      const char *kernel_name);

/* Exit(2) with a clear message unless v >= 1 — drivers call this on
 * the extents whose zero/negative forms would otherwise SIGFPE
 * (histogram bound) or print a garbage metric. */
void bench_require_pos(long v, const char *what);

/* ---- timing (C12): monotonic wall clock ---- */
double bench_now_sec(void);

/* ---- seeded deterministic init (same stream on every backend) ---- */
/* splitmix64-based uniform floats in [-1, 1). */
void bench_fill_f32(float *dst, size_t n, unsigned long long seed);
void bench_fill_u32(uint32_t *dst, size_t n, uint32_t bound,
                    unsigned long long seed);

/* ---- golden checker (C2) ---- */
/* Elementwise |a-b| <= atol + rtol*|b|; returns number of mismatches
 * and writes the worst absolute error to *max_err if non-NULL. */
size_t bench_check_f32(const float *got, const float *want, size_t n,
                       double rtol, double atol, double *max_err);

/* Prints "CHECK PASS"/"CHECK FAIL ..." and returns 0 on pass. */
int bench_report_check(const char *kernel, size_t mismatches, size_t n,
                       double max_err);

/* ---- metric reporting (frozen printf format; SURVEY.md §5) ---- */
/* kernel=<k> device=<d> n=<n> time_ms=<t> metric=<name> value=<v> unit=<u> */
void bench_report_metric(const char *kernel, const char *device, long n,
                         double seconds, const char *metric, double value,
                         const char *unit);

#ifdef __cplusplus
}
#endif
#endif /* TPK_BENCH_H */
