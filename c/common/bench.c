#define _POSIX_C_SOURCE 199309L
#include "bench.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

void bench_params_default(bench_params_t *p) {
    memset(p, 0, sizeof(*p));
    p->n = 1 << 20;
    p->m = 0;
    p->k = 0;
    p->z = 0;
    p->iters = 1;
    p->reps = 5;
    p->check = 0;
    p->nbins = 256;
    p->alpha = 2.5;
    p->beta = 0.5;
    p->dt = 1e-3;
    p->seed = 0x243F6A8885A308D3ull; /* pi digits; fixed so golden is stable */
    snprintf(p->device, sizeof(p->device), "serial");
}

static long parse_long(const char *s, const char *flag) {
    char *end;
    long v = strtol(s, &end, 10);
    if (*end != '\0') {
        fprintf(stderr, "bad value for %s: %s\n", flag, s);
        exit(2);
    }
    return v;
}

void bench_parse_args(bench_params_t *p, int argc, char **argv,
                      const char *kernel_name) {
    for (int i = 1; i < argc; i++) {
        const char *a = argv[i];
        if (strncmp(a, "--device=", 9) == 0) {
            snprintf(p->device, sizeof(p->device), "%s", a + 9);
        } else if (strncmp(a, "--n=", 4) == 0) {
            p->n = parse_long(a + 4, "--n");
        } else if (strncmp(a, "--m=", 4) == 0) {
            p->m = parse_long(a + 4, "--m");
        } else if (strncmp(a, "--k=", 4) == 0) {
            p->k = parse_long(a + 4, "--k");
        } else if (strncmp(a, "--z=", 4) == 0) {
            p->z = parse_long(a + 4, "--z");
        } else if (strncmp(a, "--iters=", 8) == 0) {
            p->iters = parse_long(a + 8, "--iters");
        } else if (strncmp(a, "--reps=", 7) == 0) {
            p->reps = (int)parse_long(a + 7, "--reps");
        } else if (strncmp(a, "--nbins=", 8) == 0) {
            p->nbins = (int)parse_long(a + 8, "--nbins");
        } else if (strncmp(a, "--alpha=", 8) == 0) {
            p->alpha = atof(a + 8);
        } else if (strncmp(a, "--beta=", 7) == 0) {
            p->beta = atof(a + 7);
        } else if (strncmp(a, "--dt=", 5) == 0) {
            p->dt = atof(a + 5);
        } else if (strncmp(a, "--seed=", 7) == 0) {
            p->seed = strtoull(a + 7, NULL, 10);
        } else if (strcmp(a, "--check") == 0) {
            p->check = 1;
        } else if (strcmp(a, "--verbose") == 0) {
            p->verbose = 1;
        } else if (strcmp(a, "--help") == 0) {
            printf("usage: %s [--device=serial|omp|tpu] [--n=N] [--m=M] "
                   "[--k=K] [--z=Z] [--iters=I] [--reps=R] [--nbins=B] "
                   "[--alpha=A] [--beta=B] [--dt=DT] [--seed=S] [--check] "
                   "[--verbose]\n",
                   kernel_name);
            exit(0);
        } else {
            fprintf(stderr, "%s: unknown flag %s (try --help)\n", kernel_name,
                    a);
            exit(2);
        }
    }
    bench_require_pos(p->reps, "--reps");
    /* no driver treats n==0 as a sentinel (unlike m/k/z) */
    bench_require_pos(p->n, "--n");
}

void bench_require_pos(long v, const char *what) {
    if (v < 1) {
        fprintf(stderr, "%s must be >= 1 (got %ld)\n", what, v);
        exit(2);
    }
}

double bench_now_sec(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

/* splitmix64: tiny, seedable, identical stream everywhere. */
static inline uint64_t splitmix64(uint64_t *state) {
    uint64_t z = (*state += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
}

void bench_fill_f32(float *dst, size_t n, unsigned long long seed) {
    uint64_t s = seed;
    for (size_t i = 0; i < n; i++) {
        /* top 24 bits → [0,1) → [-1,1) */
        uint64_t r = splitmix64(&s) >> 40;
        dst[i] = (float)((double)r / (double)(1ull << 24) * 2.0 - 1.0);
    }
}

void bench_fill_u32(uint32_t *dst, size_t n, uint32_t bound,
                    unsigned long long seed) {
    uint64_t s = seed;
    if (bound == 0) { /* nothing sensible to draw; avoid % 0 UB */
        for (size_t i = 0; i < n; i++) dst[i] = 0;
        return;
    }
    /* unbiased bounded draw: plain `% bound` on a 64-bit draw carries
     * a ~bound/2^64 modulo bias — immaterial at u32 bounds, but a
     * benchmark suite shouldn't have to argue that. Classic rejection:
     * discard draws below 2^64 mod bound, then reduce. The threshold
     * is 0 for power-of-two bounds and the reject probability is
     * < 2^-32 otherwise, so the emitted stream is unchanged in
     * practice and the loop is still effectively one draw per element. */
    uint64_t t = (0ull - (uint64_t)bound) % (uint64_t)bound;
    for (size_t i = 0; i < n; i++) {
        uint64_t r;
        do {
            r = splitmix64(&s);
        } while (r < t);
        dst[i] = (uint32_t)(r % bound);
    }
}

size_t bench_check_f32(const float *got, const float *want, size_t n,
                       double rtol, double atol, double *max_err) {
    size_t bad = 0;
    double worst = 0.0;
    for (size_t i = 0; i < n; i++) {
        double g = got[i], w = want[i];
        double err = fabs(g - w);
        if (err > worst) worst = err;
        if (!(err <= atol + rtol * fabs(w))) bad++;
    }
    if (max_err) *max_err = worst;
    return bad;
}

int bench_report_check(const char *kernel, size_t mismatches, size_t n,
                       double max_err) {
    if (mismatches == 0) {
        printf("kernel=%s CHECK PASS (n=%zu max_err=%.3e)\n", kernel, n,
               max_err);
        return 0;
    }
    printf("kernel=%s CHECK FAIL (%zu/%zu mismatches, max_err=%.3e)\n", kernel,
           mismatches, n, max_err);
    return 1;
}

void bench_report_metric(const char *kernel, const char *device, long n,
                         double seconds, const char *metric, double value,
                         const char *unit) {
    printf("kernel=%s device=%s n=%ld time_ms=%.3f metric=%s value=%.3f "
           "unit=%s\n",
           kernel, device, n, seconds * 1e3, metric, value, unit);
    fflush(stdout);
}
