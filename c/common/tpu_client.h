/* Lazy dlopen client for libtpukernels.so.
 *
 * Driver binaries stay free of libpython: the shim is only loaded when
 * --device=tpu is actually selected (mirrors how the reference's CUDA
 * variants isolate the CUDA runtime in .cu objects; SURVEY.md C10).
 */
#ifndef TPK_TPU_CLIENT_H
#define TPK_TPU_CLIENT_H

#ifdef __cplusplus
extern "C" {
#endif

/* Load the shim and initialize the embedded interpreter. Exits with a
 * diagnostic on failure (a missing backend is a configuration error,
 * matching the driver's behavior for unknown --device=). */
void tpk_tpu_ensure(void);

/* Forward to tpu_run in the shim. tpk_tpu_ensure must have returned. */
int tpk_tpu_run(const char *kernel, const char *params_json, void **bufs,
                int nbufs);

#ifdef __cplusplus
}
#endif
#endif /* TPK_TPU_CLIENT_H */
