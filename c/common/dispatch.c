#include "dispatch.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

tpk_kern_fn tpk_dispatch_lookup(const tpk_dispatch_entry *table,
                                const char *device, const char *kernel) {
    for (const tpk_dispatch_entry *e = table; e->device; e++) {
        if (strcmp(e->device, device) == 0) return e->fn;
    }
    fprintf(stderr, "%s: unknown device '%s'; known:", kernel, device);
    for (const tpk_dispatch_entry *e = table; e->device; e++)
        fprintf(stderr, " %s", e->device);
    fprintf(stderr, "\n");
    exit(2);
}
