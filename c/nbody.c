/* nbody benchmark driver (SURVEY.md C1+C8): O(N^2) direct all-pairs
 * gravity with Plummer softening, leapfrog-style integration.
 *
 * Config of record: 65 536 bodies (BASELINE.json configs[4]; the
 * multi-device allreduce variant lives behind the same kernel name in
 * the Python package). Metric: Ginter/s = N^2 * steps / t.
 * eps = 1e-2 softening, fixed across all variants.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "common/bench.h"
#include "common/dispatch.h"
#include "common/tpu_client.h"

#define EPS2 (1e-2 * 1e-2)

/* bufs = {px,py,pz,vx,vy,vz (inout), m (in)} */

static void step_host(long n, long steps, float dt, float **b, int omp) {
    float *px = b[0], *py = b[1], *pz = b[2];
    float *vx = b[3], *vy = b[4], *vz = b[5];
    const float *m = b[6];
    for (long t = 0; t < steps; t++) {
        if (!omp) {
            for (long i = 0; i < n; i++) {
                /* double accumulators: the serial run doubles as the
                 * golden oracle (SURVEY.md C2) */
                double ax = 0.0, ay = 0.0, az = 0.0;
                for (long j = 0; j < n; j++) {
                    double dx = (double)px[j] - px[i];
                    double dy = (double)py[j] - py[i];
                    double dz = (double)pz[j] - pz[i];
                    double r2 = dx * dx + dy * dy + dz * dz + EPS2;
                    double inv_r = 1.0 / sqrt(r2);
                    double w = m[j] * inv_r * inv_r * inv_r;
                    ax += w * dx;
                    ay += w * dy;
                    az += w * dz;
                }
                vx[i] += (float)(ax * dt);
                vy[i] += (float)(ay * dt);
                vz[i] += (float)(az * dt);
            }
        } else {
            /* f32 force loop with simd reduction: the double path
             * above can't vectorize (convert+divide per lane). The
             * f32 random-walk bound (~sqrt(n)*2^-24 over n partials)
             * is relative to the sum of |term| magnitudes, NOT the
             * net force — with near-cancelling forces the relative
             * error of the result is unbounded, so correctness rests
             * on the checker's 2e-4 atol (absolute slack sized to the
             * typical |term| scale) plus the fuzz sweep's coverage of
             * random configurations, not on the rtol alone */
#pragma omp parallel for schedule(static)
            for (long i = 0; i < n; i++) {
                float xi = px[i], yi = py[i], zi = pz[i];
                float ax = 0.0f, ay = 0.0f, az = 0.0f;
#pragma omp simd reduction(+ : ax, ay, az)
                for (long j = 0; j < n; j++) {
                    float dx = px[j] - xi;
                    float dy = py[j] - yi;
                    float dz = pz[j] - zi;
                    float r2 = dx * dx + dy * dy + dz * dz + (float)EPS2;
                    float inv_r = 1.0f / sqrtf(r2);
                    float w = m[j] * inv_r * inv_r * inv_r;
                    ax += w * dx;
                    ay += w * dy;
                    az += w * dz;
                }
                vx[i] += ax * dt;
                vy[i] += ay * dt;
                vz[i] += az * dt;
            }
        }
        for (long i = 0; i < n; i++) {
            px[i] += vx[i] * dt;
            py[i] += vy[i] * dt;
            pz[i] += vz[i] * dt;
        }
    }
}

static int nbody_serial(const bench_params_t *p, void **bufs) {
    step_host(p->n, p->iters, (float)p->dt, (float **)bufs, 0);
    return 0;
}

static int nbody_omp(const bench_params_t *p, void **bufs) {
    step_host(p->n, p->iters, (float)p->dt, (float **)bufs, 1);
    return 0;
}

static int nbody_tpu(const bench_params_t *p, void **bufs) {
    char json[1024];
    int off = snprintf(json, sizeof(json),
                       "{\"dt\":%.17g,\"eps\":1e-2,\"steps\":%ld,"
                       "\"buffers\":[",
                       p->dt, p->iters);
    for (int i = 0; i < 7; i++) {
        off += snprintf(json + off, sizeof(json) - off,
                        "%s{\"shape\":[%ld],\"dtype\":\"f32\"}",
                        i ? "," : "", p->n);
    }
    snprintf(json + off, sizeof(json) - off, "]}");
    return tpk_tpu_run("nbody", json, bufs, 7);
}

static const tpk_dispatch_entry TABLE[] = {
    {"serial", nbody_serial},
    {"omp", nbody_omp},
    {"tpu", nbody_tpu},
    {NULL, NULL},
};

int main(int argc, char **argv) {
    bench_params_t p;
    bench_params_default(&p);
    p.n = 65536;
    p.iters = 10;
    bench_parse_args(&p, argc, argv, "nbody");
    bench_require_pos(p.iters, "--iters");

    tpk_kern_fn fn = tpk_dispatch_lookup(TABLE, p.device, "nbody");
    if (strcmp(p.device, "tpu") == 0) tpk_tpu_ensure();

    const size_t n = (size_t)p.n;
    float *state[7];
    for (int i = 0; i < 7; i++) state[i] = malloc(n * sizeof(float));
    /* positions ~U(-1,1); small velocities; masses in (0.5, 1.5) */
    for (int i = 0; i < 3; i++)
        bench_fill_f32(state[i], n, p.seed + i);
    for (int i = 3; i < 6; i++) {
        bench_fill_f32(state[i], n, p.seed + i);
        for (size_t k = 0; k < n; k++) state[i][k] *= 0.1f;
    }
    bench_fill_f32(state[6], n, p.seed + 6);
    for (size_t k = 0; k < n; k++)
        state[6][k] = 1.0f + 0.5f * state[6][k];

    int rc = 0;
    if (p.check) {
        float *gold[7], *run[7];
        for (int i = 0; i < 7; i++) {
            gold[i] = malloc(n * sizeof(float));
            run[i] = malloc(n * sizeof(float));
            memcpy(gold[i], state[i], n * sizeof(float));
            memcpy(run[i], state[i], n * sizeof(float));
        }
        nbody_serial(&p, (void **)gold);
        if (fn(&p, (void **)run) != 0) {
            fprintf(stderr, "kernel failed\n");
            return 1;
        }
        size_t bad = 0;
        double max_err = 0.0, e;
        for (int i = 0; i < 6; i++) {
            bad += bench_check_f32(run[i], gold[i], n, 2e-3, 2e-4, &e);
            if (e > max_err) max_err = e;
        }
        rc = bench_report_check("nbody", bad, 6 * n, max_err);
        for (int i = 0; i < 7; i++) {
            free(gold[i]);
            free(run[i]);
        }
        if (rc) return rc;
    }

    void *bufs[7];
    for (int i = 0; i < 7; i++) bufs[i] = state[i];
    fn(&p, bufs); /* warm-up */
    double best = 1e30;
    for (int r = 0; r < p.reps; r++) {
        double t0 = bench_now_sec();
        fn(&p, bufs);
        double t1 = bench_now_sec();
        if (t1 - t0 < best) best = t1 - t0;
    }
    double ginter =
        (double)n * (double)n * (double)p.iters / best / 1e9;
    bench_report_metric("nbody", p.device, p.n, best, "interactions", ginter,
                        "Ginter/s");

    for (int i = 0; i < 7; i++) free(state[i]);
    return rc;
}
