/* sgemm benchmark driver (SURVEY.md C1+C5): C = alpha*A@B + beta*C.
 *
 * Config of record: 1024x1024x1024 float32 (BASELINE.json configs[1]).
 * Metric of record: GFLOPS = 2*M*N*K / t (BASELINE.md). The serial ijk
 * variant is the golden oracle; the omp variant is cache-tiled.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "common/bench.h"
#include "common/dispatch.h"
#include "common/tpu_client.h"

/* bufs = {A (MxK, in), B (KxN, in), C (MxN, inout)} */

static void dims(const bench_params_t *p, long *M, long *N, long *K) {
    *M = p->m > 0 ? p->m : p->n;
    *N = p->n;
    *K = p->k > 0 ? p->k : p->n;
}

static int sgemm_serial(const bench_params_t *p, void **bufs) {
    long M, N, K;
    dims(p, &M, &N, &K);
    const float *A = bufs[0], *B = bufs[1];
    float *C = bufs[2];
    const float alpha = (float)p->alpha, beta = (float)p->beta;
    for (long i = 0; i < M; i++) {
        for (long j = 0; j < N; j++) {
            /* double accumulator: the golden should be the most
             * accurate variant, not just the slowest */
            double acc = 0.0;
            for (long k = 0; k < K; k++)
                acc += (double)A[i * K + k] * (double)B[k * N + j];
            C[i * N + j] = alpha * (float)acc + beta * C[i * N + j];
        }
    }
    return 0;
}

/* Register-blocked tiled GEMM: MR x NR accumulator tiles held in
 * locals (vector registers once the j-loop vectorizes), K stripped at
 * KC, and the B panel (and alpha-scaled A panel) packed contiguous
 * per (strip, column-panel) — measured ~8% over streaming B straight
 * from row-major at 1024^3, and the packing also removes the
 * stride-N TLB walk for larger N. Threads parallelize over column
 * panels, each packing its own panel. The remainder path (any M/N/K)
 * falls back to the plain axpy loop. */
#define KC 256
#define MR 4
#define NR 64
static void sgemm_omp_edge(long i0, long i1, long j0, long j1, long kk,
                           long kend, long N, long K, float alpha,
                           const float *A, const float *B, float *C) {
    for (long i = i0; i < i1; i++) {
        for (long k = kk; k < kend; k++) {
            float a = alpha * A[i * K + k];
#pragma omp simd
            for (long j = j0; j < j1; j++)
                C[i * N + j] += a * B[k * N + j];
        }
    }
}

static int sgemm_omp(const bench_params_t *p, void **bufs) {
    long M, N, K;
    dims(p, &M, &N, &K);
    const float *A = bufs[0], *B = bufs[1];
    float *C = bufs[2];
    const float alpha = (float)p->alpha, beta = (float)p->beta;
    long Mr = M - M % MR, Nr = N - N % NR;
#pragma omp parallel
    {
        float Bp[KC * NR] __attribute__((aligned(64)));
        float Ap[KC * MR] __attribute__((aligned(64)));
#pragma omp for schedule(static)
        for (long i = 0; i < M; i++) {
#pragma omp simd
            for (long j = 0; j < N; j++) C[i * N + j] *= beta;
        }
        for (long kk = 0; kk < K; kk += KC) {
            long kend = kk + KC < K ? kk + KC : K;
            long kc = kend - kk;
#pragma omp for schedule(static) nowait
            for (long jj = 0; jj < Nr; jj += NR) {
                for (long k = 0; k < kc; k++)
#pragma omp simd
                    for (int j = 0; j < NR; j++)
                        Bp[k * NR + j] = B[(kk + k) * N + jj + j];
                for (long ii = 0; ii < Mr; ii += MR) {
                    for (long k = 0; k < kc; k++)
                        for (int r = 0; r < MR; r++)
                            Ap[k * MR + r] =
                                alpha * A[(ii + r) * K + kk + k];
                    float acc[MR][NR];
                    for (int r = 0; r < MR; r++)
#pragma omp simd
                        for (int j = 0; j < NR; j++)
                            acc[r][j] = C[(ii + r) * N + jj + j];
                    for (long k = 0; k < kc; k++) {
                        const float *brow = &Bp[k * NR];
                        for (int r = 0; r < MR; r++) {
                            float a = Ap[k * MR + r];
#pragma omp simd
                            for (int j = 0; j < NR; j++)
                                acc[r][j] += a * brow[j];
                        }
                    }
                    for (int r = 0; r < MR; r++)
#pragma omp simd
                        for (int j = 0; j < NR; j++)
                            C[(ii + r) * N + jj + j] = acc[r][j];
                }
                /* M remainder for this column panel */
                if (Mr < M)
                    sgemm_omp_edge(Mr, M, jj, jj + NR, kk, kend, N, K,
                                   alpha, A, B, C);
            }
            /* N remainder (at most NR-1 columns), parallel over rows
             * — serializing it would cost ~Amdahl on non-multiple-of-
             * NR sizes. The loop's implicit barrier also fences the
             * strips: no thread starts strip kk+KC while another
             * still owns a panel of strip kk. */
#pragma omp for schedule(static)
            for (long i = 0; i < M; i++)
                if (Nr < N)
                    sgemm_omp_edge(i, i + 1, Nr, N, kk, kend, N, K,
                                   alpha, A, B, C);
        }
    }
    return 0;
}

static int sgemm_tpu(const bench_params_t *p, void **bufs) {
    long M, N, K;
    dims(p, &M, &N, &K);
    char json[512];
    snprintf(json, sizeof(json),
             "{\"alpha\":%.17g,\"beta\":%.17g,\"buffers\":["
             "{\"shape\":[%ld,%ld],\"dtype\":\"f32\"},"
             "{\"shape\":[%ld,%ld],\"dtype\":\"f32\"},"
             "{\"shape\":[%ld,%ld],\"dtype\":\"f32\"}]}",
             p->alpha, p->beta, M, K, K, N, M, N);
    return tpk_tpu_run("sgemm", json, bufs, 3);
}

static const tpk_dispatch_entry TABLE[] = {
    {"serial", sgemm_serial},
    {"omp", sgemm_omp},
    {"tpu", sgemm_tpu},
    {NULL, NULL},
};

int main(int argc, char **argv) {
    bench_params_t p;
    bench_params_default(&p);
    p.n = 1024;
    bench_parse_args(&p, argc, argv, "sgemm");

    /* 0 means "default to n" for --m/--k; negatives are typos, not
     * sentinels. Validate BEFORE dispatch so a bad flag never spins
     * up the TPU runtime. */
    if (p.m != 0) bench_require_pos(p.m, "--m");
    if (p.k != 0) bench_require_pos(p.k, "--k");

    tpk_kern_fn fn = tpk_dispatch_lookup(TABLE, p.device, "sgemm");
    if (strcmp(p.device, "tpu") == 0) tpk_tpu_ensure();

    long M, N, K;
    dims(&p, &M, &N, &K);
    float *A = malloc((size_t)M * K * sizeof(float));
    float *B = malloc((size_t)K * N * sizeof(float));
    float *C = malloc((size_t)M * N * sizeof(float));
    float *C_run = malloc((size_t)M * N * sizeof(float));
    if (!A || !B || !C || !C_run) {
        fprintf(stderr, "alloc failed\n");
        return 1;
    }
    bench_fill_f32(A, (size_t)M * K, p.seed);
    bench_fill_f32(B, (size_t)K * N, p.seed ^ 0xA5A5A5A5ull);
    bench_fill_f32(C, (size_t)M * N, p.seed ^ 0x5A5A5A5Aull);

    int rc = 0;
    if (p.check) {
        float *C_gold = malloc((size_t)M * N * sizeof(float));
        memcpy(C_gold, C, (size_t)M * N * sizeof(float));
        void *gold_bufs[3] = {A, B, C_gold};
        sgemm_serial(&p, gold_bufs);

        memcpy(C_run, C, (size_t)M * N * sizeof(float));
        void *run_bufs[3] = {A, B, C_run};
        if (fn(&p, run_bufs) != 0) {
            fprintf(stderr, "kernel failed\n");
            return 1;
        }
        /* fp32 K-length accumulation differs per backend, and
         * reduced-precision matmul paths (TPU bf16_3x splitting, CUDA
         * TF32 tensor cores) carry a documented ~3e-4 worst-case rel
         * error (tpukernels/kernels/sgemm.py) — rtol gives >3x margin
         * over that at every magnitude (SURVEY.md §4) */
        double rtol = 1e-3, atol = 1e-3;
        double max_err;
        size_t bad = bench_check_f32(C_run, C_gold, (size_t)M * N, rtol,
                                     atol, &max_err);
        rc = bench_report_check("sgemm", bad, (size_t)M * N, max_err);
        free(C_gold);
        if (rc) return rc;
    }

    memcpy(C_run, C, (size_t)M * N * sizeof(float));
    void *bufs[3] = {A, B, C_run};
    fn(&p, bufs); /* warm-up */
    double best = 1e30;
    for (int r = 0; r < p.reps; r++) {
        double t0 = bench_now_sec();
        fn(&p, bufs);
        double t1 = bench_now_sec();
        if (t1 - t0 < best) best = t1 - t0;
    }
    double gflops = 2.0 * (double)M * N * K / best / 1e9;
    bench_report_metric("sgemm", p.device, p.n, best, "gflops", gflops,
                        "GFLOPS");

    free(A);
    free(B);
    free(C);
    free(C_run);
    return rc;
}
