#!/bin/sh
# C acceptance gate (SURVEY.md §4): run every built (kernel x device)
# pair at a small problem size and require CHECK PASS from each.
# Set TPK_TEST_TPU=1 to include the tpu rows (needs a TPU attached).
set -e
cd "$(dirname "$0")"

devices="serial omp"
if [ "${TPK_TEST_TPU:-0}" = "1" ]; then
  devices="$devices tpu"
fi

fail=0
run() {
  # $1 binary, rest: args
  bin="bin/$1"; shift
  [ -x "$bin" ] || return 0
  for dev in $devices; do
    echo "== $bin --device=$dev $*"
    if ! "$bin" --device="$dev" --check --reps=1 "$@"; then
      echo "FAILED: $bin --device=$dev"
      fail=1
    fi
  done
}

if [ "${TPK_TEST_TPU:-0}" = "1" ] && [ -x bin/test_shim_abi ]; then
  echo "== bin/test_shim_abi"
  ./bin/test_shim_abi || fail=1
fi

run vector_add --n=100000
run sgemm --n=256
run stencil --n=256 --iters=10
run stencil --n=64 --z=64 --iters=5
run scan_histogram --n=100000
run nbody --n=1024 --iters=2
run allreduce_bench --n=1048576

# Mesh acceptance rows (SURVEY.md C9): TPK_TEST_MESH=N re-runs the
# distributed-capable kernels with the shim sharding over N fake CPU
# devices — the mpirun-analog path, no pod needed.
if [ -n "${TPK_TEST_MESH:-}" ] && [ "${TPK_TEST_MESH}" != "0" ]; then
  n="${TPK_TEST_MESH}"
  mesh_env="PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu TPK_MESH=$n"
  mesh_env="$mesh_env XLA_FLAGS=--xla_force_host_platform_device_count=$n"
  for cmd in \
      "stencil --n=256 --iters=10" \
      "stencil --n=64 --z=64 --iters=5" \
      "nbody --n=1024 --iters=2" \
      "allreduce_bench --n=1048576"; do
    # shellcheck disable=SC2086
    set -- $cmd
    bin="bin/$1"
    shift
    [ -x "$bin" ] || continue
    echo "== TPK_MESH=$n $bin --device=tpu $*"
    # shellcheck disable=SC2086
    if ! env $mesh_env "$bin" --device=tpu --check --reps=1 "$@"; then
      echo "FAILED (mesh): $bin $*"
      fail=1
    fi
  done
fi

if [ "$fail" = "1" ]; then
  echo "ACCEPTANCE: FAIL"
  exit 1
fi
echo "ACCEPTANCE: PASS"
