#!/bin/sh
# C acceptance gate (SURVEY.md §4): run every built (kernel x device)
# pair at a small problem size and require CHECK PASS from each.
# Set TPK_TEST_TPU=1 to include the tpu rows (needs a TPU attached).
set -e
cd "$(dirname "$0")"

devices="serial omp"
if [ "${TPK_TEST_TPU:-0}" = "1" ]; then
  devices="$devices tpu"
fi

fail=0
run_row() {
  # $1 space-separated env assignments (may be empty), $2 binary,
  # $3 device, rest: args
  row_env="$1"; bin="bin/$2"; dev="$3"; shift 3
  [ -x "$bin" ] || return 0
  echo "== ${row_env:+$row_env }$bin --device=$dev $*"
  # shellcheck disable=SC2086
  if ! env $row_env "$bin" --device="$dev" --check --reps=1 "$@"; then
    echo "FAILED: ${row_env:+$row_env }$bin --device=$dev $*"
    fail=1
  fi
}
run() {
  # $1 binary, rest: args; one row per device in $devices
  b="$1"; shift
  for dev in $devices; do
    run_row "" "$b" "$dev" "$@"
  done
}

if [ "${TPK_TEST_TPU:-0}" = "1" ] && [ -x bin/test_shim_abi ]; then
  echo "== bin/test_shim_abi"
  ./bin/test_shim_abi || fail=1
fi

run vector_add --n=100000
run sgemm --n=256
run sgemm --m=64 --n=192 --k=320   # rectangular + off-tile extents
run sgemm --m=61 --n=67 --k=129    # odd extents: every remainder path
run stencil --n=256 --iters=10
run stencil --n=128 --m=320 --iters=5   # rectangular H x W
run stencil --n=64 --z=64 --iters=5
run scan_histogram --n=100000
run scan_histogram --n=50000 --nbins=64
run nbody --n=1024 --iters=2
run allreduce_bench --n=1048576

# Mesh acceptance rows (SURVEY.md C9): TPK_TEST_MESH=N re-runs the
# distributed-capable kernels with the shim sharding over N fake CPU
# devices — the mpirun-analog path, no pod needed.
if [ -n "${TPK_TEST_MESH:-}" ] && [ "${TPK_TEST_MESH}" != "0" ]; then
  n="${TPK_TEST_MESH}"
  mesh_env="PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu TPK_MESH=$n"
  mesh_env="$mesh_env XLA_FLAGS=--xla_force_host_platform_device_count=$n"
  for cmd in \
      "stencil --n=256 --iters=10" \
      "stencil --n=128 --m=320 --iters=5" \
      "stencil --n=64 --z=64 --iters=5" \
      "scan_histogram --n=100000" \
      "nbody --n=1024 --iters=2" \
      "allreduce_bench --n=1048576"; do
    # shellcheck disable=SC2086
    set -- $cmd
    b="$1"
    shift
    run_row "$mesh_env" "$b" tpu "$@"
  done
  # both N-body formulations (default row above is psum), plus the
  # pre-staged ring tuning knobs (odd per-rank block on the bidir row
  # exercises the uneven half-split)
  run_row "$mesh_env TPK_NBODY_DIST=ring" nbody tpu --n=1024 --iters=2
  run_row "$mesh_env TPK_NBODY_DIST=ring TPK_NBODY_RING_BIDIR=1 TPK_NBODY_RING_SKIP_LAST=1" \
    nbody tpu --n=1000 --iters=2
  # the stencil loop's periodic residual MPI_Allreduce analog
  # (SURVEY.md §3(b)): the full C -> shim -> residual-psum path must
  # pass the golden check AND report the global norm on stderr
  for res_args in "--n=128 --iters=5" "--n=64 --z=64 --iters=5"; do
    echo "== $mesh_env TPK_STENCIL_RESIDUAL=1 bin/stencil --device=tpu $res_args"
    # shellcheck disable=SC2086
    res_err=$(env $mesh_env TPK_STENCIL_RESIDUAL=1 \
        bin/stencil --device=tpu --check --reps=1 $res_args 2>&1 >/dev/null) \
      || { echo "FAILED: residual stencil row $res_args"; fail=1; }
    case "$res_err" in
      *"residual ||x_k+1 - x_k||^2 ="*) ;;
      *) echo "FAILED: residual line missing on stderr ($res_args)"
         printf '%s\n' "$res_err"
         fail=1 ;;
    esac
  done
  # the shim-side bus-bw sweep (SURVEY.md §3(d)): the C binary itself
  # must be able to emit the metric-of-record table
  run_row "$mesh_env TPK_BUSBW_SWEEP=1 TPK_BUSBW_MIN=1K TPK_BUSBW_MAX=16K TPK_BUSBW_REPS=2" \
    allreduce_bench tpu --n=1048576
fi

if [ "$fail" = "1" ]; then
  echo "ACCEPTANCE: FAIL"
  exit 1
fi
echo "ACCEPTANCE: PASS"
