/* scan_histogram benchmark driver (SURVEY.md C1+C7): CUB-style
 * inclusive prefix scan + histogram over the same int32 input stream.
 *
 * Config of record: BASELINE.json configs[3]. Metric: Melem/s = N / t
 * for the combined scan+histogram pass. Integer kernels check exactly
 * (SURVEY.md §4). Values are drawn in [0, nbins).
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "common/bench.h"
#include "common/dispatch.h"
#include "common/tpu_client.h"

/* bufs = {x (n, i32, in), scan_out (n, i32, out), hist (nbins, i32, out)} */

static int sh_serial(const bench_params_t *p, void **bufs) {
    const int32_t *x = bufs[0];
    int32_t *scan_out = bufs[1];
    int32_t *hist = bufs[2];
    memset(hist, 0, (size_t)p->nbins * sizeof(int32_t));
    int32_t run = 0;
    for (long i = 0; i < p->n; i++) {
        run += x[i];
        scan_out[i] = run;
        hist[x[i]]++;
    }
    return 0;
}

static int sh_omp(const bench_params_t *p, void **bufs) {
    const int32_t *x = bufs[0];
    int32_t *scan_out = bufs[1];
    int32_t *hist = bufs[2];
    long n = p->n;
    int nbins = p->nbins;
    memset(hist, 0, (size_t)nbins * sizeof(int32_t));

#pragma omp parallel
    {
        /* histogram: privatized bins + critical merge */
        int32_t *priv = calloc((size_t)nbins, sizeof(int32_t));
#pragma omp for schedule(static) nowait
        for (long i = 0; i < n; i++) priv[x[i]]++;
#pragma omp critical
        for (int b = 0; b < nbins; b++) hist[b] += priv[b];
        free(priv);
    }

    /* scan: two-pass block scan (chunk sums, exclusive chunk prefix,
     * then local rescan) — the classic OpenMP decomposition */
    enum { CHUNKS = 64 };
    int32_t chunk_sum[CHUNKS + 1] = {0};
    long chunk = (n + CHUNKS - 1) / CHUNKS;
#pragma omp parallel for schedule(static)
    for (int c = 0; c < CHUNKS; c++) {
        long lo = c * chunk, hi = lo + chunk < n ? lo + chunk : n;
        int32_t s = 0;
        for (long i = lo; i < hi; i++) s += x[i];
        chunk_sum[c + 1] = s;
    }
    for (int c = 1; c <= CHUNKS; c++) chunk_sum[c] += chunk_sum[c - 1];
#pragma omp parallel for schedule(static)
    for (int c = 0; c < CHUNKS; c++) {
        long lo = c * chunk, hi = lo + chunk < n ? lo + chunk : n;
        int32_t run = chunk_sum[c];
        for (long i = lo; i < hi; i++) {
            run += x[i];
            scan_out[i] = run;
        }
    }
    return 0;
}

static int sh_tpu(const bench_params_t *p, void **bufs) {
    /* one combined dispatch: x crosses the host->device boundary once
     * and feeds both halves (two separate calls would re-upload x and
     * pay the fixed dispatch cost twice per timed rep) */
    char json[512];
    snprintf(json, sizeof(json),
             "{\"nbins\":%d,\"buffers\":[{\"shape\":[%ld],\"dtype\":\"i32\"},"
             "{\"shape\":[%ld],\"dtype\":\"i32\"},"
             "{\"shape\":[%d],\"dtype\":\"i32\"}]}",
             p->nbins, p->n, p->n, p->nbins);
    return tpk_tpu_run("scan_histogram", json, bufs, 3);
}

static const tpk_dispatch_entry TABLE[] = {
    {"serial", sh_serial},
    {"omp", sh_omp},
    {"tpu", sh_tpu},
    {NULL, NULL},
};

int main(int argc, char **argv) {
    bench_params_t p;
    bench_params_default(&p);
    bench_parse_args(&p, argc, argv, "scan_histogram");

    /* before dispatch: a bad flag must never spin up the TPU runtime */
    bench_require_pos(p.nbins, "--nbins"); /* 0 would SIGFPE the fill */

    tpk_kern_fn fn = tpk_dispatch_lookup(TABLE, p.device, "scan_histogram");
    if (strcmp(p.device, "tpu") == 0) tpk_tpu_ensure();

    const size_t n = (size_t)p.n;
    uint32_t *raw = malloc(n * sizeof(uint32_t));
    int32_t *x = malloc(n * sizeof(int32_t));
    int32_t *scan_out = malloc(n * sizeof(int32_t));
    int32_t *hist = malloc((size_t)p.nbins * sizeof(int32_t));
    if (!raw || !x || !scan_out || !hist) {
        fprintf(stderr, "alloc failed\n");
        return 1;
    }
    bench_fill_u32(raw, n, (uint32_t)p.nbins, p.seed);
    for (size_t i = 0; i < n; i++) x[i] = (int32_t)raw[i];
    free(raw);

    int rc = 0;
    if (p.check) {
        int32_t *scan_gold = malloc(n * sizeof(int32_t));
        int32_t *hist_gold = malloc((size_t)p.nbins * sizeof(int32_t));
        void *gold_bufs[3] = {x, scan_gold, hist_gold};
        sh_serial(&p, gold_bufs);

        void *run_bufs[3] = {x, scan_out, hist};
        if (fn(&p, run_bufs) != 0) {
            fprintf(stderr, "kernel failed\n");
            return 1;
        }
        size_t bad = 0;
        for (size_t i = 0; i < n; i++)
            if (scan_out[i] != scan_gold[i]) bad++;
        for (int b = 0; b < p.nbins; b++)
            if (hist[b] != hist_gold[b]) bad++;
        rc = bench_report_check("scan_histogram", bad, n + p.nbins, 0.0);
        free(scan_gold);
        free(hist_gold);
        if (rc) return rc;
    }

    void *bufs[3] = {x, scan_out, hist};
    fn(&p, bufs); /* warm-up */
    double best = 1e30;
    for (int r = 0; r < p.reps; r++) {
        double t0 = bench_now_sec();
        fn(&p, bufs);
        double t1 = bench_now_sec();
        if (t1 - t0 < best) best = t1 - t0;
    }
    double melems = (double)n / best / 1e6;
    bench_report_metric("scan_histogram", p.device, p.n, best, "throughput",
                        melems, "Melem/s");

    free(x);
    free(scan_out);
    free(hist);
    return rc;
}
