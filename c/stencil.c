/* stencil benchmark driver (SURVEY.md C1+C6): Jacobi relaxation.
 *
 * 2D 5-point (default) or 3D 7-point (--z=D). Config of record:
 * 4096^2, 1000 iters (BASELINE.json configs[2]). Metric of record:
 * Mcells/sec = X*Y(*Z)*iters / t. Interior cells become the mean of
 * their face neighbors; boundary cells are held fixed (Dirichlet).
 * Double-buffered sweeps; the serial variant is the golden oracle.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "common/bench.h"
#include "common/dispatch.h"
#include "common/tpu_client.h"

/* bufs = {x (inout)}; 2D: (n rows x m cols); 3D: (z, n, m) */

static void dims(const bench_params_t *p, long *H, long *W, long *D) {
    *H = p->n;
    *W = p->m > 0 ? p->m : p->n;
    *D = p->z; /* 0 → 2D */
}

/* one 2D sweep src→dst */
static void sweep2d(const float *src, float *dst, long H, long W) {
    for (long i = 0; i < H; i++) {
        for (long j = 0; j < W; j++) {
            if (i == 0 || i == H - 1 || j == 0 || j == W - 1) {
                dst[i * W + j] = src[i * W + j];
            } else {
                dst[i * W + j] = 0.25f * (src[(i - 1) * W + j] +
                                          src[(i + 1) * W + j] +
                                          src[i * W + j - 1] +
                                          src[i * W + j + 1]);
            }
        }
    }
}

static void sweep2d_omp(const float *src, float *dst, long H, long W) {
    /* Boundary handling hoisted out of the inner loop so it carries
     * no branch and vectorizes (the serial variant above keeps the
     * branchy form as the plainly-readable oracle). */
#pragma omp parallel for schedule(static)
    for (long i = 0; i < H; i++) {
        const float *rs = src + i * W;
        float *rd = dst + i * W;
        if (i == 0 || i == H - 1) {
            memcpy(rd, rs, (size_t)W * sizeof(float));
            continue;
        }
        const float *up = rs - W, *dn = rs + W;
        rd[0] = rs[0];
#pragma omp simd
        for (long j = 1; j < W - 1; j++)
            rd[j] = 0.25f * (up[j] + dn[j] + rs[j - 1] + rs[j + 1]);
        rd[W - 1] = rs[W - 1];
    }
}

static void sweep3d(const float *src, float *dst, long D, long H, long W,
                    int omp) {
    const float c = 1.0f / 6.0f;
    if (!omp) {
        for (long z = 0; z < D; z++) {
            for (long i = 0; i < H; i++) {
                for (long j = 0; j < W; j++) {
                    size_t idx = ((size_t)z * H + i) * W + j;
                    if (z == 0 || z == D - 1 || i == 0 || i == H - 1 ||
                        j == 0 || j == W - 1) {
                        dst[idx] = src[idx];
                    } else {
                        dst[idx] = c * (src[idx - (size_t)H * W] +
                                        src[idx + (size_t)H * W] +
                                        src[idx - W] + src[idx + W] +
                                        src[idx - 1] + src[idx + 1]);
                    }
                }
            }
        }
        return;
    }
    /* omp path: boundary rows copied whole, interior rows branch-free
     * so the j-loop vectorizes (cf. sweep2d_omp) */
#pragma omp parallel for collapse(2) schedule(static)
    for (long z = 0; z < D; z++) {
        for (long i = 0; i < H; i++) {
            const float *rs = src + ((size_t)z * H + i) * W;
            float *rd = dst + ((size_t)z * H + i) * W;
            if (z == 0 || z == D - 1 || i == 0 || i == H - 1) {
                memcpy(rd, rs, (size_t)W * sizeof(float));
                continue;
            }
            const float *up = rs - W, *dn = rs + W;
            const float *zb = rs - (size_t)H * W, *zf = rs + (size_t)H * W;
            rd[0] = rs[0];
#pragma omp simd
            for (long j = 1; j < W - 1; j++)
                rd[j] = c * (zb[j] + zf[j] + up[j] + dn[j] + rs[j - 1] +
                             rs[j + 1]);
            rd[W - 1] = rs[W - 1];
        }
    }
}

static size_t total_cells(const bench_params_t *p) {
    long H, W, D;
    dims(p, &H, &W, &D);
    return (size_t)(D > 0 ? D : 1) * H * W;
}

static int jacobi_host(const bench_params_t *p, void **bufs, int omp) {
    long H, W, D;
    dims(p, &H, &W, &D);
    size_t cells = total_cells(p);
    float *x = bufs[0];
    float *tmp = malloc(cells * sizeof(float));
    if (!tmp) return 1;
    float *src = x, *dst = tmp;
    for (long t = 0; t < p->iters; t++) {
        if (D > 0)
            sweep3d(src, dst, D, H, W, omp);
        else if (omp)
            sweep2d_omp(src, dst, H, W);
        else
            sweep2d(src, dst, H, W);
        float *s = src;
        src = dst;
        dst = s;
    }
    if (src != x) memcpy(x, src, cells * sizeof(float));
    free(tmp);
    return 0;
}

static int jacobi_serial(const bench_params_t *p, void **bufs) {
    return jacobi_host(p, bufs, 0);
}
static int jacobi_omp(const bench_params_t *p, void **bufs) {
    return jacobi_host(p, bufs, 1);
}

static int jacobi_tpu(const bench_params_t *p, void **bufs) {
    long H, W, D;
    dims(p, &H, &W, &D);
    char json[512];
    if (D > 0) {
        snprintf(json, sizeof(json),
                 "{\"iters\":%ld,\"buffers\":["
                 "{\"shape\":[%ld,%ld,%ld],\"dtype\":\"f32\"}]}",
                 p->iters, D, H, W);
        return tpk_tpu_run("stencil3d", json, bufs, 1);
    }
    snprintf(json, sizeof(json),
             "{\"iters\":%ld,\"buffers\":["
             "{\"shape\":[%ld,%ld],\"dtype\":\"f32\"}]}",
             p->iters, H, W);
    return tpk_tpu_run("stencil2d", json, bufs, 1);
}

static const tpk_dispatch_entry TABLE[] = {
    {"serial", jacobi_serial},
    {"omp", jacobi_omp},
    {"tpu", jacobi_tpu},
    {NULL, NULL},
};

int main(int argc, char **argv) {
    bench_params_t p;
    bench_params_default(&p);
    p.n = 4096;
    p.iters = 1000;
    bench_parse_args(&p, argc, argv, "stencil");
    if (p.m != 0) bench_require_pos(p.m, "--m"); /* 0 = "use n" */
    if (p.z != 0) bench_require_pos(p.z, "--z"); /* 0 = 2D sentinel */
    bench_require_pos(p.iters, "--iters");

    tpk_kern_fn fn = tpk_dispatch_lookup(TABLE, p.device, "stencil");
    if (strcmp(p.device, "tpu") == 0) tpk_tpu_ensure();

    size_t cells = total_cells(&p);
    float *x0 = malloc(cells * sizeof(float));
    float *x_run = malloc(cells * sizeof(float));
    if (!x0 || !x_run) {
        fprintf(stderr, "alloc failed\n");
        return 1;
    }
    bench_fill_f32(x0, cells, p.seed);

    int rc = 0;
    if (p.check) {
        float *x_gold = malloc(cells * sizeof(float));
        memcpy(x_gold, x0, cells * sizeof(float));
        void *gold_bufs[1] = {x_gold};
        jacobi_serial(&p, gold_bufs);

        memcpy(x_run, x0, cells * sizeof(float));
        void *run_bufs[1] = {x_run};
        if (fn(&p, run_bufs) != 0) {
            fprintf(stderr, "kernel failed\n");
            return 1;
        }
        /* Jacobi is a contraction: absolute error shrinks per sweep,
         * so a tight tolerance holds even for 1000 iters */
        double max_err;
        size_t bad = bench_check_f32(x_run, x_gold, cells, 1e-4, 1e-5,
                                     &max_err);
        rc = bench_report_check("stencil", bad, cells, max_err);
        free(x_gold);
        if (rc) return rc;
    }

    memcpy(x_run, x0, cells * sizeof(float));
    void *bufs[1] = {x_run};
    fn(&p, bufs); /* warm-up (absorbs JIT compile on tpu) */
    double best = 1e30;
    for (int r = 0; r < p.reps; r++) {
        memcpy(x_run, x0, cells * sizeof(float));
        double t0 = bench_now_sec();
        fn(&p, bufs);
        double t1 = bench_now_sec();
        if (t1 - t0 < best) best = t1 - t0;
    }
    double mcells =
        (double)cells * (double)p.iters / best / 1e6;
    bench_report_metric("stencil", p.device, p.n, best, "throughput",
                        mcells, "Mcells/s");

    free(x0);
    free(x_run);
    return rc;
}
