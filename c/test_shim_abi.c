/* ABI round-trip unit test (SURVEY.md §4 item 4): buffers must cross
 * the C -> libtpukernels.so -> embedded CPython -> JAX -> back
 * boundary intact, errors must come back as nonzero return codes (not
 * crashes), and repeated calls must reuse the interpreter.
 *
 * Exercises the shim directly, without a benchmark driver on top.
 */
#define _GNU_SOURCE /* RTLD_DEFAULT */
#include <dlfcn.h>
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "common/tpu_client.h"

static int failures = 0;

#define CHECK(cond, msg)                                            \
    do {                                                            \
        if (!(cond)) {                                              \
            fprintf(stderr, "FAIL: %s (%s:%d)\n", msg, __FILE__,    \
                    __LINE__);                                      \
            failures++;                                             \
        } else {                                                    \
            printf("ok: %s\n", msg);                                \
        }                                                           \
    } while (0)

int main(void) {
    tpk_tpu_ensure();

    /* 1. round-trip correctness: saxpy through the full stack */
    enum { N = 1000 };
    float x[N], y[N], y0[N];
    for (int i = 0; i < N; i++) {
        x[i] = (float)i * 0.25f;
        y[i] = y0[i] = 1.0f - (float)i * 0.125f;
    }
    void *bufs[2] = {x, y};
    char json[256];
    snprintf(json, sizeof(json),
             "{\"alpha\":2.0,\"buffers\":[{\"shape\":[%d],\"dtype\":\"f32\"},"
             "{\"shape\":[%d],\"dtype\":\"f32\"}]}",
             N, N);
    int rc = tpk_tpu_run("vector_add", json, bufs, 2);
    CHECK(rc == 0, "vector_add returns 0");
    int bad = 0;
    for (int i = 0; i < N; i++)
        if (fabsf(y[i] - (2.0f * x[i] + y0[i])) > 1e-5f) bad++;
    CHECK(bad == 0, "buffer round-trip values exact");
    bad = 0;
    for (int i = 0; i < N; i++)
        if (x[i] != (float)i * 0.25f) bad++;
    CHECK(bad == 0, "input buffer unmodified");

    /* 2. unknown kernel -> error return, not a crash */
    rc = tpk_tpu_run("no_such_kernel", json, bufs, 2);
    CHECK(rc != 0, "unknown kernel returns nonzero");

    /* 3. buffer-count mismatch -> error return */
    rc = tpk_tpu_run("vector_add", json, bufs, 1);
    CHECK(rc != 0, "buffer count mismatch returns nonzero");

    /* 4. malformed JSON -> error return */
    rc = tpk_tpu_run("vector_add", "{not json", bufs, 2);
    CHECK(rc != 0, "malformed JSON returns nonzero");

    /* 5. interpreter reuse: second good call still works */
    rc = tpk_tpu_run("vector_add", json, bufs, 2);
    CHECK(rc == 0, "shim survives errors and keeps working");

    /* 6. three-buffer int32 kernel (the combined benchmark dispatch) */
    enum { NS = 512, NB = 16 };
    int32_t xi[NS], scan_out[NS], hist[NB];
    for (int i = 0; i < NS; i++) xi[i] = i % NB;
    memset(scan_out, 0, sizeof(scan_out));
    memset(hist, 0, sizeof(hist));
    void *bufs3[3] = {xi, scan_out, hist};
    snprintf(json, sizeof(json),
             "{\"nbins\":%d,\"buffers\":[{\"shape\":[%d],\"dtype\":\"i32\"},"
             "{\"shape\":[%d],\"dtype\":\"i32\"},"
             "{\"shape\":[%d],\"dtype\":\"i32\"}]}",
             NB, NS, NS, NB);
    rc = tpk_tpu_run("scan_histogram", json, bufs3, 3);
    CHECK(rc == 0, "scan_histogram (3 buffers, i32) returns 0");
    bad = 0;
    int32_t run = 0;
    for (int i = 0; i < NS; i++) {
        run += xi[i];
        if (scan_out[i] != run) bad++;
    }
    for (int b = 0; b < NB; b++)
        if (hist[b] != NS / NB) bad++;
    CHECK(bad == 0, "i32 scan + histogram values exact");

    /* 6b. f32 scan round-trip (SURVEY.md §4 item 4): the ABI's other
     * dtype lane. The benchmark drivers only ever send i32 to scan;
     * _DTYPES (tpukernels/capi.py) also carries f32 — prove the
     * full C -> shim -> kernel f32 path, with the float tolerance a
     * blocked f32 prefix sum needs: |err_i| <= sqrt(n)*eps*sum|x| +
     * atol (the random-walk rounding bound; the kernel's matmul
     * formulation re-associates, so exact equality is not the
     * contract -- see tpukernels/kernels/scan.py). */
    enum { NF = 4096 };
    static float xf[NF], scanf_out[NF];
    for (int i = 0; i < NF; i++) {
        xf[i] = 0.5f * sinf((float)i * 0.7f);
        scanf_out[i] = 0.0f;
    }
    void *bufs_f[2] = {xf, scanf_out};
    snprintf(json, sizeof(json),
             "{\"buffers\":[{\"shape\":[%d],\"dtype\":\"f32\"},"
             "{\"shape\":[%d],\"dtype\":\"f32\"}]}",
             NF, NF);
    rc = tpk_tpu_run("scan", json, bufs_f, 2);
    CHECK(rc == 0, "scan (f32) returns 0");
    bad = 0;
    double acc = 0.0, sum_abs = 0.0;
    const double tol_scale = sqrt((double)NF) * 1.1920929e-7; /* eps_f32 */
    for (int i = 0; i < NF; i++) {
        acc += (double)xf[i];
        sum_abs += fabs((double)xf[i]);
        if (fabs((double)scanf_out[i] - acc) > tol_scale * sum_abs + 1e-6)
            bad++;
    }
    CHECK(bad == 0, "f32 scan values within sqrt(n)*eps bound");

    /* 7. explicit tpu_shutdown is safe, idempotent, and does not
     * break later calls (the interpreter stays alive; only the
     * teardown flush runs, once) */
    /* the client loads the shim with RTLD_GLOBAL, so the symbol is
     * visible in the default namespace */
    void (*shutdown_fn)(void) =
        (void (*)(void))dlsym(RTLD_DEFAULT, "tpu_shutdown");
    CHECK(shutdown_fn != NULL, "tpu_shutdown symbol resolvable");
    if (shutdown_fn) {
        shutdown_fn();
        shutdown_fn(); /* idempotent */
        snprintf(json, sizeof(json),
                 "{\"alpha\":2.0,\"buffers\":[{\"shape\":[%d],"
                 "\"dtype\":\"f32\"},{\"shape\":[%d],\"dtype\":\"f32\"}]}",
                 N, N);
        rc = tpk_tpu_run("vector_add", json, bufs, 2);
        CHECK(rc == 0, "calls still work after explicit shutdown");
    }

    if (failures) {
        printf("test_shim_abi: %d FAILURES\n", failures);
        return 1;
    }
    printf("test_shim_abi: ALL PASS\n");
    return 0;
}
