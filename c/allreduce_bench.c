/* allreduce_bench driver (SURVEY.md C9, §3(d)): the measured
 * collective microbenchmark. Every rank contributes the same S-element
 * float32 vector (the standard MPI-benchmark setup); allreduce(SUM)
 * must return nranks * x on every rank.
 *
 * Metric of record: bus bandwidth = 2*(n-1)/n * bytes / t (ring
 * allreduce accounting), swept 8→64 chips on a pod
 * (BASELINE.json metric). On the TPU path nranks = however many chips
 * the mesh has (1 on the dev box — a degenerate but honest check);
 * serial/omp model the single-rank case. The full sweep lives in
 * `python -m tpukernels.parallel.busbw`, and TPK_BUSBW_SWEEP=1 makes
 * THIS binary emit the same swept table once, shim-side, during the
 * untimed --check pass (TPK_BUSBW_MIN/MAX/REPS/OP tune it) — one C
 * invocation per host produces the metric-of-record table on a pod.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "common/bench.h"
#include "common/dispatch.h"
#include "common/tpu_client.h"

/* bufs = {x (n, f32, in), out (n, f32, out)} */

static int ar_serial(const bench_params_t *p, void **bufs) {
    memcpy(bufs[1], bufs[0], (size_t)p->n * sizeof(float));
    return 0;
}

static int ar_omp(const bench_params_t *p, void **bufs) {
    const float *x = bufs[0];
    float *out = bufs[1];
#pragma omp parallel for schedule(static)
    for (long i = 0; i < p->n; i++) out[i] = x[i];
    return 0;
}

static int ar_tpu(const bench_params_t *p, void **bufs) {
    char json[256];
    snprintf(json, sizeof(json),
             "{\"buffers\":[{\"shape\":[%ld],\"dtype\":\"f32\"},"
             "{\"shape\":[%ld],\"dtype\":\"f32\"}]}",
             p->n, p->n);
    return tpk_tpu_run("allreduce", json, bufs, 2);
}

static const tpk_dispatch_entry TABLE[] = {
    {"serial", ar_serial},
    {"omp", ar_omp},
    {"tpu", ar_tpu},
    {NULL, NULL},
};

int main(int argc, char **argv) {
    bench_params_t p;
    bench_params_default(&p);
    p.n = 1 << 22; /* 16 MiB message */
    bench_parse_args(&p, argc, argv, "allreduce_bench");

    tpk_kern_fn fn = tpk_dispatch_lookup(TABLE, p.device, "allreduce_bench");
    if (strcmp(p.device, "tpu") == 0) tpk_tpu_ensure();

    const size_t n = (size_t)p.n;
    float *x = malloc(n * sizeof(float));
    float *out = malloc(n * sizeof(float));
    if (!x || !out) {
        fprintf(stderr, "alloc failed\n");
        return 1;
    }
    bench_fill_f32(x, n, p.seed);
    /* keep values away from 0 so out/x recovers the rank count */
    for (size_t i = 0; i < n; i++) x[i] = 1.0f + 0.5f * x[i];

    void *bufs[2] = {x, out};
    if (fn(&p, bufs) != 0) {
        fprintf(stderr, "kernel failed\n");
        return 1;
    }

    /* infer nranks: allreduce of identical contributions = nranks * x */
    double k = (double)out[0] / (double)x[0];
    long nranks = (long)(k + 0.5);
    int rc = 0;
    if (p.check) {
        size_t bad = 0;
        double max_err = 0.0;
        if (nranks < 1 || fabs(k - (double)nranks) > 1e-3) {
            bad = n;
        } else {
            for (size_t i = 0; i < n; i++) {
                double want = (double)nranks * x[i];
                double err = fabs(out[i] - want);
                if (err > max_err) max_err = err;
                if (err > 1e-5 + 1e-5 * fabs(want)) bad++;
            }
        }
        rc = bench_report_check("allreduce", bad, n, max_err);
        if (rc) return rc;
    }

    fn(&p, bufs); /* warm-up */
    double best = 1e30;
    for (int r = 0; r < p.reps; r++) {
        double t0 = bench_now_sec();
        fn(&p, bufs);
        double t1 = bench_now_sec();
        if (t1 - t0 < best) best = t1 - t0;
    }
    double bytes = (double)n * sizeof(float);
    double busbw =
        (nranks > 1 ? 2.0 * (nranks - 1) / nranks * bytes : bytes) / best /
        1e9;
    printf("kernel=allreduce device=%s n=%ld nranks=%ld time_ms=%.3f "
           "metric=busbw value=%.3f unit=GB/s\n",
           p.device, p.n, nranks, best * 1e3, busbw);

    free(x);
    free(out);
    return rc;
}
