/* vector_add benchmark driver (SURVEY.md C1+C4): SAXPY y = alpha*x + y.
 *
 * Config of record: N = 2^20 float32 (BASELINE.json configs[0]).
 * Metric: effective bandwidth GB/s = 3*4*N bytes / t (read x, read y,
 * write y). The serial variant is the golden oracle (C2).
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "common/bench.h"
#include "common/dispatch.h"
#include "common/tpu_client.h"

/* ---- variants (C4) ---- */

static int saxpy_serial(const bench_params_t *p, void **bufs) {
    const float *x = (const float *)bufs[0];
    float *y = (float *)bufs[1];
    const float a = (float)p->alpha;
    for (long i = 0; i < p->n; i++) y[i] = a * x[i] + y[i];
    return 0;
}

static int saxpy_omp(const bench_params_t *p, void **bufs) {
    const float *x = (const float *)bufs[0];
    float *y = (float *)bufs[1];
    const float a = (float)p->alpha;
#pragma omp parallel for schedule(static)
    for (long i = 0; i < p->n; i++) y[i] = a * x[i] + y[i];
    return 0;
}

static int saxpy_tpu(const bench_params_t *p, void **bufs) {
    char json[256];
    snprintf(json, sizeof(json),
             "{\"alpha\":%.17g,\"buffers\":["
             "{\"shape\":[%ld],\"dtype\":\"f32\"},"
             "{\"shape\":[%ld],\"dtype\":\"f32\"}]}",
             p->alpha, p->n, p->n);
    return tpk_tpu_run("vector_add", json, bufs, 2);
}

static const tpk_dispatch_entry TABLE[] = {
    {"serial", saxpy_serial},
    {"omp", saxpy_omp},
    {"tpu", saxpy_tpu},
    {NULL, NULL},
};

int main(int argc, char **argv) {
    bench_params_t p;
    bench_params_default(&p);
    bench_parse_args(&p, argc, argv, "vector_add");

    tpk_kern_fn fn = tpk_dispatch_lookup(TABLE, p.device, "vector_add");
    if (strcmp(p.device, "tpu") == 0) tpk_tpu_ensure();

    const size_t n = (size_t)p.n;
    float *x = malloc(n * sizeof(float));
    float *y = malloc(n * sizeof(float));
    float *y_run = malloc(n * sizeof(float));
    if (!x || !y || !y_run) {
        fprintf(stderr, "alloc failed\n");
        return 1;
    }
    bench_fill_f32(x, n, p.seed);
    bench_fill_f32(y, n, p.seed ^ 0x9E3779B97F4A7C15ull);

    int rc = 0;
    if (p.check) {
        /* golden: serial run on a fresh copy (C2) */
        float *y_gold = malloc(n * sizeof(float));
        memcpy(y_gold, y, n * sizeof(float));
        void *gold_bufs[2] = {x, y_gold};
        saxpy_serial(&p, gold_bufs);

        memcpy(y_run, y, n * sizeof(float));
        void *run_bufs[2] = {x, y_run};
        if (fn(&p, run_bufs) != 0) {
            fprintf(stderr, "kernel failed\n");
            return 1;
        }
        double max_err;
        size_t bad =
            bench_check_f32(y_run, y_gold, n, 1e-5, 1e-6, &max_err);
        rc = bench_report_check("vector_add", bad, n, max_err);
        free(y_gold);
        if (rc) return rc;
    }

    /* timing: warm-up excluded (absorbs JIT compile on tpu), reps timed
     * individually, best-of reported (C1/C12) */
    memcpy(y_run, y, n * sizeof(float));
    void *bufs[2] = {x, y_run};
    fn(&p, bufs); /* warm-up */
    double best = 1e30;
    for (int r = 0; r < p.reps; r++) {
        double t0 = bench_now_sec();
        fn(&p, bufs);
        double t1 = bench_now_sec();
        if (t1 - t0 < best) best = t1 - t0;
    }
    double gbps = 3.0 * 4.0 * (double)n / best / 1e9;
    bench_report_metric("vector_add", p.device, p.n, best, "bandwidth", gbps,
                        "GB/s");

    free(x);
    free(y);
    free(y_run);
    return rc;
}
