"""Checkpointed revalidation queue CLI (docs/RESILIENCE.md §supervisor).

Usage:
    python tools/revalidate.py                 # one queue attempt
    python tools/revalidate.py --wait [--max-hours H]
    python tools/revalidate.py --plan          # schedule, no execution
    python tools/revalidate.py --whos-holding  # lock diagnosis
    python tools/revalidate.py --queue FILE    # custom step specs

The declarative production queue below is the former body of
``tools/tpu_revalidate.sh`` (both shell scripts are now thin wrappers
that keep the $HOME flock machinery and delegate here). Queue logic —
git-aware per-day stamps, crash-safe checkpoint resume, step
quarantine after repeated wedges, flap-aware value-per-chip-minute
admission, backoff-scheduled probing — lives in
``tpukernels/resilience/supervisor.py``.

Exit codes (the watcher/wrapper contract, unchanged):
    0   — queue fully green (quarantined steps reported loudly);
    2   — incomplete but nothing regressed (deferred steps / bench
          coverage) — retryable next window;
    124 — a step wedged or timed out — retryable;
    3   — (wrapper) lock held by another watcher;
    64  — usage error (NOT 2: the watcher retries rc 2 forever, and a
          bad flag must never be retried as "insufficient coverage");
    else — a gating step failed loudly with that rc.

``--queue FILE`` / ``TPK_SUPERVISOR_QUEUE`` point at a JSON list of
step specs (see supervisor.StepSpec) — how the CPU chaos suite drives
the real supervisor against stub steps. The post-green sgemm-sweep
harvest runs only with the production queue.

``--whos-holding`` automates the orphan-vs-live-watcher diagnosis the
old lock-contention block printed as manual pgrep instructions: reads
the watcher pid from ``$HOME/.tpk_tpu_wait.lock``, tests the flock,
classifies the holder from /proc/<pid>/cmdline, and says what to do.
"""

from __future__ import annotations

import datetime
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels.resilience import supervisor  # noqa: E402

S = supervisor.StepSpec

# The former tpu_revalidate.sh steps, one spec each. Declaration order
# is documentation; EXECUTION order is value-per-chip-minute density
# (supervisor.plan) under the `after` dependency edges — the NEXT.md
# "highest value per chip-minute first" ordering, now enforced in
# code. Step bodies stay the same shell the old queue ran.
PRODUCTION_QUEUE = [
    # 0. suite-wide AOT prewarm (docs/PERF.md §compile discipline):
    #    the old stencil3d-only hand-prewarm generalized to the full
    #    registry — tools/prewarm.py precompiles every registered
    #    kernel config plus both bench loop programs per metric, so
    #    the healthy window after it spends chip minutes measuring,
    #    not compiling. Non-gating, stamped daily on SUCCESS with
    #    git-aware inputs (a kernel/bench commit re-runs it) — but
    #    max_attempts_per_day=2: a deterministic compile failure
    #    (rc 1, which quarantine never catches — that needs wedges)
    #    must not re-eat every flap window the way the old
    #    attempt-stamp contract guarded against. cost_from="prewarm"
    #    re-derives the chip-minute cost from the newest measured
    #    per-kernel compile walls, so once the cache is warm the
    #    admission planner stops budgeting cold-compile minutes for
    #    it. Must precede bench.
    #    Timeout coherence: 7 bench-metric children at --timeout-s 420
    #    each (the per-child watchdog owns wedge classification) plus
    #    the avatar pass must fit under the outer kill, or a SIGKILL
    #    mid-run would swallow prewarm_end and blame the whole step
    #    for one metric's wedge: 7*420 + slack = 3540 < 3600.
    S("prewarm_all", """
set -o pipefail
prewarm_log="docs/logs/prewarm_$(date +%Y-%m-%d_%H%M%S).log"
if timeout -k 10 3540 python tools/prewarm.py --bench all --check \\
    --order traffic --timeout-s 420 >"$prewarm_log" 2>&1; then
  tail -1 "$prewarm_log"
else
  echo "WARN: prewarm_all failed rc=$? (non-gating) -" \\
       "$prewarm_log is the postmortem evidence"
  exit 1
fi
""", gating=False, stamp="daily", timeout_s=3600, cost_min=12,
      value=50, cost_from="prewarm", max_attempts_per_day=2,
      inputs=("tpukernels", "bench.py", "tools/prewarm.py")),
    # 0b. 60-second tail-latency probe (docs/OBSERVABILITY.md
    #     §latency SLOs): open-loop Poisson load through
    #     registry.dispatch at the record avatar shapes, per-request
    #     latency histograms, p99 verdicts persisted to slo.json.
    #     Non-gating at first (obs_check picks up a confirmed breach
    #     as rc 1 WARN); after-edge to prewarm_all so the probe
    #     measures the warm path, never a cold compile; never
    #     stamped and cheap enough (cost 2 min, density just under
    #     bench's) that EVERY healthy window buys a tail-latency
    #     datapoint, not just a slope.
    S("slo_probe", """
set -o pipefail
slo_log="docs/logs/slo_probe_$(date +%Y-%m-%d_%H%M%S).log"
if timeout -k 10 120 python tools/loadgen.py --mix all \\
    --arrivals poisson --duration 60 --rate 8 --requests 0 \\
    --shapes record >"$slo_log" 2>&1; then
  tail -1 "$slo_log"
else
  echo "WARN: slo probe failed rc=$? (non-gating) - $slo_log"
  exit 1
fi
""", gating=False, stamp="never", timeout_s=150, cost_min=2,
      value=12, after=("prewarm_all",),
      inputs=("tpukernels", "tools/loadgen.py")),
    # 0b'. served-path tail probe (docs/SERVING.md): start the kernel
    #      daemon, drive it 60 s with the same open-loop Poisson load
    #      at record shapes THROUGH the socket, shut it down cleanly —
    #      so every healthy window also buys a p99 datapoint for the
    #      real service path (queueing, bucketing, batching windows),
    #      not just in-process dispatch. The 60 s is SPLIT 30+30: the
    #      first half runs TRACED (daemon under TPK_TRACE=1, a fixed
    #      seed) so every healthy window also banks real request
    #      timelines for trace_report/obs_report (docs/OBSERVABILITY
    #      .md §request tracing) at no extra chip cost; the second
    #      half keeps an untraced tail sample. The traced half also
    #      CARRIES DEADLINES (--deadline-ms, generous enough that a
    #      clean run meets 100% — docs/SERVING.md §deadlines), so
    #      every window banks goodput evidence and exercises the
    #      budget propagation end to end, again at no extra chip
    #      cost. Non-gating (obs_check
    #      picks a confirmed breach OR trace_inconsistent up as rc 1
    #      WARN), never stamped, after prewarm_all so the daemon
    #      opens onto a warm manifest; the stop runs whatever the
    #      loadgen rcs so a failed burst cannot leak a daemon into
    #      the next window.
    S("serve_probe", """
set -o pipefail
serve_log="docs/logs/serve_probe_$(date +%Y-%m-%d_%H%M%S).log"
serve_probe_body() {
  env TPK_TRACE=1 python tools/serve_ctl.py start --wait 30 \\
      || return $?
  timeout -k 10 70 env TPK_TRACE=1 python tools/loadgen.py \\
      --serve default --mix all --arrivals poisson --duration 30 \\
      --rate 8 --requests 0 --shapes record --seed 5 \\
      --deadline-ms 30000
  rc_traced=$?
  timeout -k 10 70 python tools/loadgen.py --serve default \\
      --mix all --arrivals poisson --duration 30 --rate 8 \\
      --requests 0 --shapes record
  rc=$?
  python tools/serve_ctl.py stop
  [ $rc_traced -eq 0 ] && [ $rc -eq 0 ]
}
if serve_probe_body >"$serve_log" 2>&1; then
  tail -1 "$serve_log"
else
  echo "WARN: serve probe failed rc=$? (non-gating) - $serve_log"
  exit 1
fi
""", gating=False, stamp="never", timeout_s=200, cost_min=2, value=10,
      after=("prewarm_all",),
      inputs=("tpukernels/serve", "tools/loadgen.py",
              "tools/serve_ctl.py")),
    # 0b''. fleet probe (docs/SERVING.md §fleet): 1 router + 2 worker
    #       daemons, a 60 s skewed-TENANT burst (a hot bursty tenant
    #       beside a steady one — the fairness scenario the router's
    #       token buckets exist for) driven through the front socket,
    #       one worker drained AND restored mid-burst (the rolling-
    #       restart rehearsal: zero accepted requests may drop), one
    #       worker KILLED -9 mid-burst and self-healed (§self-healing:
    #       the health manager must detect the death, sweep, respawn
    #       and rejoin while traffic keeps flowing — `serve_ctl
    #       health --wait` is the convergence gate and its rc part of
    #       the verdict; no new chip-minutes, the healing overlaps
    #       the same burst), then a clean stop whatever the loadgen
    #       rcs so a failed burst cannot leak a fleet into the next
    #       window. The fleet runs under TPK_TRACE=1 and the steady
    #       client is traced too (seeded), so the burst ALSO banks
    #       cross-process request timelines — router spill + drain
    #       hops AND the dead-worker replay gap included — at no
    #       extra chip cost (docs/OBSERVABILITY.md §request tracing).
    #       Non-gating (obs_check picks a confirmed per-tenant breach
    #       OR trace_inconsistent up as rc 1 WARN); never stamped;
    #       after prewarm_all so the workers open onto a warm
    #       manifest.
    S("fleet_probe", """
set -o pipefail
fleet_log="docs/logs/fleet_probe_$(date +%Y-%m-%d_%H%M%S).log"
fleet_probe_body() {
  env TPK_TRACE=1 python tools/serve_ctl.py start-fleet 2 \\
      --wait 60 || return $?
  python tools/serve_ctl.py guardian --wait 30 || return $?
  front=$(python -c "from tpukernels.serve import fleet
print(fleet.front_socket_path())")
  timeout -k 10 120 python tools/loadgen.py --serve "$front" \\
      --mix all --arrivals bursty --duration 60 --rate 10 \\
      --requests 0 --shapes record --tenant hot &
  lg_hot=$!
  timeout -k 10 120 env TPK_TRACE=1 python tools/loadgen.py \\
      --serve "$front" \\
      --mix all --arrivals poisson --duration 60 --rate 2 \\
      --requests 0 --shapes record --tenant steady --seed 3 &
  lg_steady=$!
  sleep 20
  python tools/serve_ctl.py drain 0 --wait 30; rc_drain=$?
  python tools/serve_ctl.py undrain 0 --wait 30; rc_undrain=$?
  # kill -> detect -> respawn -> rejoin, mid-burst: worker 1's pid
  # from its flocked pidfile, then wait for the health manager's
  # convergence (docs/SERVING.md §self-healing)
  w1pid=$(head -1 "$(python -c "from tpukernels.serve import fleet
print(fleet.worker_dir(1))")/serve.pid")
  kill -9 "$w1pid"
  python tools/serve_ctl.py health --wait 90; rc_heal=$?
  # router kill -> guardian respawn -> WAL replay, still mid-burst
  # (docs/SERVING.md §guardian): the LAST single point of failure's
  # recovery rehearsed under the same traffic; `status` rc 0 (router
  # pidfile flocked again + front socket answering) is the gate
  rpid=$(head -1 "$(python -c "from tpukernels.serve import fleet
print(fleet.router_pidfile_path())")")
  kill -9 "$rpid"
  rc_heal2=1
  for _i in $(seq 90); do
    if python tools/serve_ctl.py status >/dev/null 2>&1; then
      rc_heal2=0; break
    fi
    sleep 1
  done
  python tools/serve_ctl.py health --wait 90 || rc_heal2=1
  wait $lg_hot; rc_hot=$?
  wait $lg_steady; rc_steady=$?
  python tools/serve_ctl.py stop-fleet
  # the drain/undrain/heal rcs are part of the verdict: a probe that
  # never actually rehearsed the rolling restart (or whose kills were
  # never self-healed) must not report success
  [ $rc_hot -eq 0 ] && [ $rc_steady -eq 0 ] && \
    [ $rc_drain -eq 0 ] && [ $rc_undrain -eq 0 ] && \
    [ $rc_heal -eq 0 ] && [ $rc_heal2 -eq 0 ]
}
if fleet_probe_body >"$fleet_log" 2>&1; then
  tail -1 "$fleet_log"
else
  echo "WARN: fleet probe failed rc=$? (non-gating) - $fleet_log"
  exit 1
fi
""", gating=False, stamp="never", timeout_s=420, cost_min=3, value=9,
      after=("prewarm_all",),
      inputs=("tpukernels/serve", "tools/loadgen.py",
              "tools/serve_ctl.py")),
    # 0c. bus-bandwidth sweep (docs/OBSERVABILITY.md §scaling): the
    #     paper's multi-chip metric of record, captured as a
    #     structured scaling artifact + busbw_point journal events the
    #     moment a pod window is healthy. After-edge to prewarm_all so
    #     the sweep's own compile is warm-path; cost 3 chip-minutes
    #     (16 MiB max message, 5 reps) so it fits any flap window;
    #     non-gating — the obs_check step picks a validated bus-bw
    #     regression up as rc 1 WARN exactly like a bench regression.
    S("busbw_sweep", """
set -o pipefail
busbw_log="docs/logs/busbw_$(date +%Y-%m-%d_%H%M%S).log"
if timeout -k 10 240 python -m tpukernels.parallel.busbw \\
    --max=16M --reps=5 >"$busbw_log" 2>&1; then
  tail -2 "$busbw_log"
else
  echo "WARN: busbw sweep failed rc=$? (non-gating) - $busbw_log"
  exit 1
fi
# one 2-D mesh point per healthy window (docs/DISTRIBUTED.md §2-D
# meshes): a short 2 x (n/2) allreduce sweep so the torus
# decomposition banks real-topology evidence beside the ring's —
# the mesh_shape-stamped artifact obs_report's per-shape bus-bw
# series reads. Probed in a child so a dead backend costs a WARN
# here, never a wedged supervisor.
ndev=$(python -c "import jax; print(jax.device_count())" 2>/dev/null)
if [ "${ndev:-0}" -ge 4 ]; then
  if timeout -k 10 240 python -m tpukernels.parallel.busbw \\
      --mesh=2x$((ndev / 2)) --max=4M --reps=5 \\
      >>"$busbw_log" 2>&1; then
    tail -2 "$busbw_log"
  else
    echo "WARN: 2-D busbw sweep failed rc=$? (non-gating) - $busbw_log"
    exit 1
  fi
fi
""", gating=False, stamp="never", timeout_s=540, cost_min=3, value=11,
      after=("prewarm_all",),
      inputs=("tpukernels/parallel", "tpukernels/obs/scaling.py")),
    # 1. headline metrics + the 15% self-regression gate; the JSON
    #    line is persisted so an unattended recovery leaves a
    #    committable artifact. Never stamped: its own skip-captured
    #    logic keeps it cheap and the sgemm canary must run every
    #    attempt. TPK_BENCH_SKIP_CAPTURED=1 (watch mode) spends a
    #    short window only on missing metrics and judges the union.
    S("bench", """
set -e -o pipefail
union_flag=""
if [ "${TPK_BENCH_SKIP_CAPTURED:-}" = "1" ]; then
  union_flag="--union-persisted"
fi
bench_out=$(timeout 5400 python bench.py)
printf '%s\\n' "$bench_out"
printf '%s\\n' "$bench_out" | tail -1 \\
  > "docs/logs/bench_$(date +%Y-%m-%d_%H%M%S).json"
printf '%s\\n' "$bench_out" | tail -1 \\
  | python bench.py --check-regression $union_flag
""", stamp="never", timeout_s=5460, cost_min=15, value=100,
      after=("prewarm_all",), inputs=("tpukernels", "bench.py")),
    # 1b. trend tripwire, non-gating (the 15% gate above is the
    #     authority); CPU-only, so it never eats a flap window.
    S("obs_check", """
python tools/obs_report.py --check && echo "obs trend check: OK"
""", gating=False, stamp="never", timeout_s=300, cost_min=1, value=5,
      needs_chip=False, after=("bench",)),
    # 1c. roofline table over the day's committed evidence
    #     (docs/PERF.md §rooflines): achieved vs analytic peak per
    #     kernel, below_roofline flagged non-gating. CPU-only, daily —
    #     the table only changes when bench evidence or the model does.
    S("roofline_report", """
python tools/obs_report.py --roofline && echo "roofline report: OK"
""", gating=False, stamp="daily", timeout_s=300, cost_min=1, value=4,
      needs_chip=False, after=("bench",),
      inputs=("tpukernels/tuning/roofline.py", "tools/obs_report.py")),
    # 2. C acceptance gate: serial/omp + real TPU rows + fake mesh
    S("c_gate", """
set -e -o pipefail
make -C c -s
(cd c && timeout 900 env TPK_TEST_TPU=1 TPK_TEST_MESH=8 \\
  ./run_all.sh | tail -3)
""", timeout_s=1500, cost_min=18, value=60, inputs=("c",)),
    # 2b. C-path scan_histogram throughput (docs/NEXT.md item 2)
    S("c_scan_timing", """
set -e -o pipefail
make -C c -s
(cd c && timeout 600 ./bin/scan_histogram --device=tpu --n=4194304 \\
  --check)
""", timeout_s=660, cost_min=10, value=25, after=("c_gate",),
      inputs=("c",)),
    # 2c. profiler evidence capture, warn-only (a tf schema drift must
    #     not abort a queue whose real gates passed)
    S("profile", """
bash tools/profile_headline.sh
""", gating=False, timeout_s=1200, cost_min=10, value=20,
      inputs=("tools/profile_headline.sh", "tools/profile_summary.py")),
    # 2d. knob sanity re-confirms while the tunnel is warm
    S("knob_sanity", """
set -e -o pipefail
for impl in mxu vpu; do
  timeout 600 env TPK_HIST_IMPL=$impl python -c "
from bench import bench_scan_hist
print('scan_hist $impl:', round(bench_scan_hist(), 1))"
done
timeout 600 env TPK_SGEMM_PRECISION=float32 python -c "
from bench import bench_sgemm
print('sgemm f32 (bf16_6x):', round(bench_sgemm(), 1))"
""", timeout_s=1860, cost_min=10, value=18,
      inputs=("tpukernels", "bench.py")),
    # 3. compiled-path suite in stamped groups (pytest has no resume;
    #    groups let on-chip validation accrue across flap windows).
    #    Values descend so density preserves the kernel-files-first
    #    ordering the compile-cost analysis picked.
]

_PYTEST_GROUPS = [
    ("pytest_vector_add", "tests/test_vector_add.py", 16),
    ("pytest_sgemm", "tests/test_sgemm.py", 15),
    ("pytest_stencil", "tests/test_stencil.py", 14),
    ("pytest_scan_hist", "tests/test_scan_histogram.py", 13),
    ("pytest_nbody", "tests/test_nbody.py", 12),
    ("pytest_determinism",
     "tests/test_determinism.py tests/test_fuzz_shapes.py", 11),
    ("pytest_rest",
     "tests/ --ignore=tests/test_vector_add.py "
     "--ignore=tests/test_sgemm.py --ignore=tests/test_stencil.py "
     "--ignore=tests/test_scan_histogram.py "
     "--ignore=tests/test_nbody.py --ignore=tests/test_determinism.py "
     "--ignore=tests/test_fuzz_shapes.py", 10),
]
for _name, _args, _value in _PYTEST_GROUPS:
    PRODUCTION_QUEUE.append(S(_name, f"""
set -o pipefail
timeout 1200 env TPK_REQUIRE_TPU=1 python -m pytest {_args} -q | tail -2
""", timeout_s=1260, cost_min=15, value=_value,
        inputs=("tpukernels", "tests")))

PRODUCTION_QUEUE += [
    # 3b. autotune pipeline smoke: CPU interpret, scrubbed off the
    #     axon pool — never eats a flap window; non-gating, daily.
    S("autotune_smoke", """
set -o pipefail
autotune_log="docs/logs/autotune_smoke_$(date +%Y-%m-%d_%H%M%S).log"
if timeout -k 10 600 python tools/autotune.py --kernel sgemm --smoke \\
    >"$autotune_log" 2>&1; then
  echo "autotune smoke: OK (pipeline proven; $autotune_log)"
else
  echo "WARN: autotune smoke failed rc=$? (non-gating) - $autotune_log"
  exit 1
fi
""", gating=False, timeout_s=660, cost_min=8, value=4,
      needs_chip=False,
      inputs=("tpukernels/tuning", "tools/autotune.py")),
    # 3c. output-integrity envelope refresh (docs/RESILIENCE.md
    #     §output integrity): re-record every kernel's CPU-oracle
    #     fingerprint envelope daily so the dispatch-time guard's
    #     tier-2 checks judge against current sources (a kernel commit
    #     also re-runs it via the git-aware inputs). CPU-only and
    #     scrubbed off the axon pool — the envelope authority is the
    #     jnp oracle, never the chip. Non-gating: a failed refresh
    #     degrades tier 2 to the live-oracle tier 3, it does not block
    #     the queue.
    S("integrity_envelopes", """
set -o pipefail
if timeout -k 10 600 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \\
    python tools/integrity_envelopes.py --record; then
  echo "integrity envelopes: refreshed"
else
  echo "WARN: integrity envelope refresh failed rc=$? (non-gating) -" \\
       "tier-2 checks degrade to the live oracle"
  exit 1
fi
""", gating=False, stamp="daily", timeout_s=660, cost_min=2, value=4,
      needs_chip=False,
      inputs=("tpukernels/resilience/integrity.py", "tpukernels/kernels",
              "tools/integrity_envelopes.py")),
    # 3d. crash-residue janitor (docs/RESILIENCE.md §atomic state):
    #     reap stale pidfiles, orphaned tpkserve-* shm segments and a
    #     torn fleet.json left by crashed serving processes — counts
    #     journaled as fleet_fsck. CPU-only, daily, non-gating: a
    #     janitor, not a health check.
    S("fleet_fsck", """
python tools/serve_ctl.py fsck
""", gating=False, stamp="daily", timeout_s=120, cost_min=1, value=2,
      needs_chip=False,
      inputs=("tpukernels/serve", "tools/serve_ctl.py")),
    # 3e. daily journal rollup (docs/OBSERVABILITY.md §daily rollups):
    #     compact each day's health journals into a validated
    #     rollup_<date>.json series artifact and prune past retention
    #     — the long-horizon substrate for p99_creep and multi-day
    #     adapt mining. Pure journal arithmetic — CPU-only, daily,
    #     non-gating: losing a day's rollup degrades the trend window,
    #     it does not block the queue.
    S("rollup_daily", """
set -o pipefail
rollup_log="docs/logs/rollup_daily_$(date +%Y-%m-%d_%H%M%S).log"
if timeout -k 10 240 env JAX_PLATFORMS=cpu python \\
    -m tpukernels.obs.rollup >"$rollup_log" 2>&1; then
  tail -1 "$rollup_log"
else
  echo "WARN: daily rollup failed rc=$? (non-gating) - $rollup_log"
  exit 1
fi
""", gating=False, stamp="daily", timeout_s=300, cost_min=1, value=2,
      needs_chip=False,
      inputs=("tpukernels/obs/rollup.py", "tpukernels/obs/metrics.py")),
    # 3f. traffic-adaptive bucket proposal (docs/SERVING.md §adaptive
    #     buckets): mine the day's serve_request shape mix and persist
    #     a split/merge candidate when projected pad waste sits over
    #     TPK_ADAPT_PAD_TARGET. Pure journal arithmetic — CPU-only,
    #     daily, non-gating; after serve_probe so the day's journal
    #     holds at least the probe's own traffic evidence, and after
    #     rollup_daily so a TPK_ADAPT_WINDOW_DAYS>1 miner sees a
    #     fresh prior-day series (docs/SERVING.md §adaptive buckets).
    S("adapt_propose", """
set -o pipefail
adapt_log="docs/logs/adapt_propose_$(date +%Y-%m-%d_%H%M%S).log"
if timeout -k 10 240 env JAX_PLATFORMS=cpu python \\
    tools/serve_optimize.py propose >"$adapt_log" 2>&1; then
  tail -1 "$adapt_log"
else
  echo "WARN: adapt propose failed rc=$? (non-gating) - $adapt_log"
  exit 1
fi
""", gating=False, stamp="daily", timeout_s=300, cost_min=1, value=2,
      needs_chip=False, after=("serve_probe", "rollup_daily"),
      inputs=("tpukernels/serve", "tools/serve_optimize.py")),
    # 3g. adaptive-bucket canary (docs/SERVING.md §adaptive buckets):
    #     re-autotune the candidate table (--autotune quick, the >3%
    #     margin), boot incumbent + candidate daemons off-window and
    #     replay the frozen shape mix at identical seeds; promotion
    #     rewrites buckets.json for the fleet's next undrain. Chip
    #     time, so after prewarm_all (warm manifest) and after the
    #     proposal that feeds it; non-gating — a rejected candidate
    #     is the gate WORKING.
    S("adapt_canary", """
set -o pipefail
adapt_log="docs/logs/adapt_canary_$(date +%Y-%m-%d_%H%M%S).log"
if timeout -k 10 900 python tools/serve_optimize.py canary \\
    --autotune quick >"$adapt_log" 2>&1; then
  tail -2 "$adapt_log"
else
  echo "WARN: adapt canary failed rc=$? (non-gating) - $adapt_log"
  exit 1
fi
""", gating=False, stamp="daily", timeout_s=960, cost_min=6, value=11,
      after=("prewarm_all", "adapt_propose"),
      inputs=("tpukernels/serve", "tpukernels/tuning",
              "tools/serve_optimize.py", "tools/loadgen.py")),
    # 4. sanitizer gates: CPU-only rebuild + full gate, then restore
    #    the normal build; last on purpose (lowest density).
]
for _san, _value in (("asan", 3), ("ubsan", 2)):
    PRODUCTION_QUEUE.append(S(f"san_{_san}", f"""
set -e -o pipefail
make -C c {_san}
(cd c && timeout 1800 env ASAN_OPTIONS=detect_leaks=0 \\
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu TPK_TEST_TPU=1 \\
    TPK_TEST_MESH=8 ./run_all.sh | tail -3)
make -C c -s clean && make -C c -s
""", timeout_s=2100, cost_min=25, value=_value, needs_chip=False,
        inputs=("c",)))


LOCK_PATH = os.path.join(os.environ.get("HOME", ""),
                         ".tpk_tpu_wait.lock")
_WATCHER_MARKS = ("revalidate.py --wait", "tpu_wait_and_revalidate")
_QUEUE_MARKS = ("tpu_revalidate", "revalidate.py", "bench.py",
                "sgemm_tune", "autotune.py")


def classify_holder(cmdline: str) -> str:
    """live-watcher | orphaned-queue | unknown — the decision the old
    lock-contention block left to manual pgrep reading."""
    if any(m in cmdline for m in _WATCHER_MARKS):
        return "live-watcher"
    if any(m in cmdline for m in _QUEUE_MARKS):
        return "orphaned-queue"
    return "unknown"


def whos_holding(lock_path=None) -> int:
    """Diagnose the $HOME watcher lock: is it held, by which pid, and
    is that a live watcher (leave it alone) or an orphaned queue/sweep
    child (kill it and re-run the watcher)? rc 0 = not held, rc 3 =
    held (the wrapper's "already covered" code)."""
    import fcntl

    lock_path = lock_path or LOCK_PATH
    if not os.path.exists(lock_path):
        print(f"whos-holding: no lock file at {lock_path} - no "
              "watcher has run on this machine")
        return 0
    held = False
    try:
        with open(lock_path) as f:
            content = f.readline().strip()
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)
            except OSError:
                held = True
    except OSError as e:
        print(f"whos-holding: cannot open {lock_path}: {e}")
        return 0
    pid = int(content) if content.isdigit() else None
    if not held:
        print(f"whos-holding: lock {lock_path} is NOT held"
              + (f" (stale pid {pid} in file)" if pid else "")
              + " - safe to start a watcher")
        return 0
    if pid is None:
        print(f"whos-holding: lock HELD but no pid recorded (pre-"
              "supervisor watcher?) - fall back to: pgrep -af "
              "tpu_wait_and_revalidate")
        return 3
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().replace(b"\0", b" ").decode(
                errors="replace").strip()
    except OSError:
        cmdline = ""
    if not cmdline:
        # flock held but the recorded pid is gone: a CHILD inherited
        # the lock fd when the watcher died — the orphan case
        print(f"whos-holding: lock HELD but recorded watcher pid "
              f"{pid} is dead - an orphaned child inherited the fd.")
        print("  pgrep -af 'tpu_revalidate|bench.py|sgemm_tune'  "
              "# kill these, then re-run the watcher")
        return 3
    verdict = classify_holder(cmdline)
    print(f"whos-holding: lock HELD by pid {pid}: {cmdline}")
    if verdict == "live-watcher":
        print("  verdict: LIVE WATCHER - leave it alone (it exits on "
              "the first green queue or its deadline)")
    elif verdict == "orphaned-queue":
        print(f"  verdict: ORPHANED queue/sweep child - kill {pid} "
              "and re-run tools/tpu_wait_and_revalidate.sh")
    else:
        print("  verdict: unrecognized holder - inspect before "
              "killing")
    return 3


def _load_specs(queue_file):
    """Returns (specs, is_production) or raises SystemExit(64): a
    malformed queue file is a usage error, not a gating-step rc — and
    NEVER rc 2, which the watch loop would retry until its deadline."""
    if queue_file:
        try:
            return supervisor.load_queue_file(queue_file), False
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"revalidate: bad queue file {queue_file}: {e}",
                  file=sys.stderr)
            raise SystemExit(64)
    return PRODUCTION_QUEUE, True


def _harvest():
    """Post-green best-effort sgemm tile sweep (the old watcher's
    window harvest) — never gates: a wedge mid-sweep must not turn a
    PASSED queue into a failure."""
    ts = datetime.datetime.now().strftime("%Y-%m-%d_%H%M%S")
    log = os.path.join("docs", "logs", f"sgemm_tune_{ts}.log")
    os.system(
        f"python tools/sgemm_tune.py --quick 2>&1 | tee {log} || true"
    )


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    wait = plan_only = holding = False
    max_hours = 10.0
    queue_file = os.environ.get("TPK_SUPERVISOR_QUEUE") or None
    it = iter(argv)
    try:
        for a in it:
            if a == "--wait":
                wait = True
            elif a == "--plan":
                plan_only = True
            elif a == "--whos-holding":
                holding = True
            elif a == "--max-hours":
                max_hours = float(next(it))
            elif a == "--queue":
                queue_file = next(it)
            else:
                print(__doc__, file=sys.stderr)
                print(f"revalidate: unknown argument {a!r}",
                      file=sys.stderr)
                return 64
    except (StopIteration, ValueError):
        print(f"revalidate: {a} needs a value", file=sys.stderr)
        return 64
    if holding:
        return whos_holding()
    # resolve the queue path against the INVOKER's cwd before the
    # chdir below re-bases relative paths onto the repo root
    if queue_file:
        queue_file = os.path.abspath(queue_file)
    os.chdir(_REPO)
    # same routing default as bench.py's CLI entry: an unattended
    # supervisor run must land its events in the day's journal (the
    # step children inherit the same file via the environment)
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        from tpukernels.resilience import journal as _j
        os.environ["TPK_HEALTH_JOURNAL"] = _j.default_path()
    specs, production = _load_specs(queue_file)
    if plan_only:
        sup = supervisor.Supervisor(specs, repo=_REPO, announce=False)
        from tpukernels.resilience import journal as _journal
        events, _bad = _journal.load_events(sup._history_paths())
        est = supervisor.estimate_window_minutes(events)
        print(f"window estimate: {est['minutes']:.1f} min "
              f"({est['basis']}, {est['windows']} observed)")
        print(f"{'step':<22} {'density':>8} {'cost':>6} {'state'}")
        for s in specs:
            st = sup.state["steps"].get(s.name, {})
            state = ("quarantined" if st.get("quarantined")
                     else "green" if st.get("green")
                     else "stamped" if supervisor.stamp_fresh(s, _REPO)
                     else "pending")
            fit = ("" if not s.needs_chip
                   else " (fits)" if s.cost_min <= est["minutes"]
                   else " (exceeds window)")
            print(f"{s.name:<22} {s.density:>8.2f} "
                  f"{s.cost_min:>5.0f}m {state}{fit}")
        return 0
    if wait:
        # the old watcher's queue-attempt env: spend short windows
        # only on missing metrics, don't burn a window on probe
        # patience inside the queue (we JUST probed healthy)
        os.environ["TPK_BENCH_SKIP_CAPTURED"] = "1"
        os.environ["TPK_BENCH_PROBE_ATTEMPTS"] = "1"
        return supervisor.watch(
            lambda: supervisor.Supervisor(specs, repo=_REPO),
            max_hours,
            harvest=_harvest if production else None,
        )
    return supervisor.Supervisor(specs, repo=_REPO).run_queue()


if __name__ == "__main__":
    sys.exit(main())
