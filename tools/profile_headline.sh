#!/bin/bash
# Capture profiler evidence for the two headline kernels (VERDICT r3
# item 5 / SURVEY.md §5 tracing): one XProf trace each for SGEMM
# 1024^3 and 2D stencil 4096^2 on the live chip, then summarize busy %
# and top ops into docs/logs/ so the roofline claims in BASELINE.md
# ("bf16_3x ceiling", "VPU-bound at k=8") rest on captured numbers,
# not slope arithmetic alone. Run on a healthy tunnel; wired into
# tools/tpu_revalidate.sh.
#   tools/profile_headline.sh [outdir]   (default docs/logs)
set -e -o pipefail
cd "$(dirname "$0")/.."

outdir="${1:-docs/logs}"
mkdir -p "$outdir"
stamp=$(date +%Y-%m-%d)

profile_one() {
  # $1 label, $2 python body that runs the warmed kernel a few times
  label="$1"; body="$2"
  tdir=$(mktemp -d "/tmp/tpk_prof_${label}.XXXX")
  echo "== profiling $label -> $tdir"
  TPU_KERNELS_PROFILE="$tdir" timeout 900 python -c "
import os
import numpy as np
import jax, jax.numpy as jnp
from tpukernels import capi
$body
"
  out="$outdir/profile_${label}_${stamp}.log"
  timeout 300 python tools/profile_summary.py "$tdir" | tee "$out"
  echo "== summary saved: $out"
}

# SGEMM 1024^3: warm (compile outside the trace window), then trace a
# handful of dispatches of an R=50 chained-matmul loop — the same
# shape/chaining SCHEME as bench_sgemm's slope loop (bench.py),
# rebuilt here because the trace needs one fixed R, not the two-R
# slope pair. If bench_sgemm's construction changes, mirror it here.
profile_one sgemm "
rng = np.random.default_rng(0)
m = 1024
a = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
b = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
c = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
from tpukernels.kernels.sgemm import sgemm
from jax import lax
f = jax.jit(lambda a, b, c: jnp.sum(
    lax.fori_loop(0, 50, lambda i, cc: sgemm(1.0, a, b, 0.5, cc), c)))
np.asarray(f(a, b, c))  # compile + warm BEFORE the trace
capi._maybe_start_profiler()
for _ in range(3):
    np.asarray(f(a, b, c))
capi.stop_profiler()
"

# 2D stencil 4096^2, k=8 temporal blocking (the config of record)
profile_one stencil "
from tpukernels.kernels.stencil import jacobi2d
rng = np.random.default_rng(1)
x = jnp.asarray(rng.standard_normal((4096, 4096)), jnp.float32)
f = jax.jit(lambda x: jnp.sum(jacobi2d(x, 64)))
np.asarray(f(x))  # compile + warm BEFORE the trace
capi._maybe_start_profiler()
for _ in range(3):
    np.asarray(f(x))
capi.stop_profiler()
"

echo "profile_headline: done — paste the busy % / top-op lines into"
echo "docs/PERF.md next to the roofline claims."
