#!/bin/bash
# Poll the axon tunnel and run the revalidation queue the moment it
# answers (companion to tools/tpu_revalidate.sh; see docs/NEXT.md).
#   tools/tpu_wait_and_revalidate.sh [max_hours]   (default 10)
# Probes every 5 minutes in a killable subprocess (a wedged tunnel
# HANGS, it never errors). On the first healthy probe, runs
# tpu_revalidate.sh and exits with its status; logs to stdout.
set -o pipefail
cd "$(dirname "$0")/.."

# single instance: two watchers (e.g. one left over from a previous
# session, or one per checkout/worktree) would both fire the
# revalidation queue on recovery and interleave timed runs on the one
# chip. The lock dies with the process; it is inherited by the exec'd
# revalidation, which keeps the exclusion through the whole queue.
# $HOME-scoped fixed path on purpose: machine-wide exclusion across
# checkouts (a repo-local lock would let two worktrees fire
# concurrently) without the world-writable-/tmp hazard of any local
# user pre-holding it to silently disable the watcher. No /tmp
# fallback for the same reason — an env without HOME (cron, systemd)
# must fail loudly here, not silently downgrade to a pre-holdable
# lock. Exit 3 is distinct so a chaining caller can tell "already
# covered" from "revalidated OK".
: "${HOME:?tpu_wait: HOME unset - refusing a world-writable /tmp lock}"
exec 9>"$HOME/.tpk_tpu_wait.lock"
if ! flock -n 9; then
  echo "tpu_wait: another watcher already holds the lock; exiting 3"
  exit 3
fi
# transition guard: a watcher from a pre-relocation checkout may still
# hold the LEGACY /tmp lock and would not contend with ours — warn so
# the operator kills it rather than risking two interleaved
# revalidations on the one chip (warn-only: the legacy path is
# world-writable, so a held lock there must not be able to disable us)
if [ -e /tmp/tpk_tpu_wait.lock ] && command -v flock >/dev/null; then
  if ! flock -n -E 99 /tmp/tpk_tpu_wait.lock true 2>/dev/null; then
    echo "tpu_wait: WARNING: legacy /tmp/tpk_tpu_wait.lock is held -" \
         "a pre-relocation watcher may still be running (pgrep" \
         "tpu_wait_and_revalidate)"
  fi
fi

max_hours="${1:-10}"
deadline=$(( $(date +%s) + max_hours * 3600 ))

while [ "$(date +%s)" -lt "$deadline" ]; do
  # the backend assert matters: with the tunnel down in a fail-FAST
  # mode jax silently falls back to CPU, and a bare matmul probe
  # would declare the dead tunnel ALIVE. -k: a wedged tunnel read can
  # ignore SIGTERM — escalate to SIGKILL so the watcher itself can't
  # hang on the exact failure it exists to survive.
  probe_err=$(timeout -k 10 90 python -c \
      "import jax; assert jax.default_backend() != 'cpu', jax.default_backend(); import jax.numpy as jnp; (jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()" \
      2>&1 >/dev/null)
  if [ $? -eq 0 ]; then
    echo "tpu_wait: tunnel ALIVE at $(date -Is); starting revalidation"
    exec bash tools/tpu_revalidate.sh
  fi
  # keep the probe's own error visible: a broken probe (jax missing,
  # snippet bug) must be distinguishable from a dead tunnel
  echo "tpu_wait: tunnel still dead at $(date -Is); retry in 5m"
  [ -n "$probe_err" ] && printf '%s\n' "$probe_err" | tail -3
  sleep 300
done
echo "tpu_wait: gave up after ${max_hours}h"
exit 1
