#!/bin/bash
# Poll the axon tunnel and run the revalidation queue the moment it
# answers (companion to tools/tpu_revalidate.sh; see docs/NEXT.md).
#   tools/tpu_wait_and_revalidate.sh [max_hours]   (default 10)
# Probes every 5 minutes in a killable subprocess (a wedged tunnel
# HANGS, it never errors). On each healthy probe, runs
# tpu_revalidate.sh; exits 0 on the first fully-green queue, otherwise
# resumes probing until the deadline (the tunnel flaps, so a mid-queue
# wedge must not end the watch). Logs to stdout.
set -o pipefail
cd "$(dirname "$0")/.."

# single instance: two watchers (e.g. one left over from a previous
# session, or one per checkout/worktree) would both fire the
# revalidation queue on recovery and interleave timed runs on the one
# chip. The lock dies with the process; the spawned revalidation
# inherits the fd, which keeps the exclusion through the whole queue.
# $HOME-scoped fixed path on purpose: machine-wide exclusion across
# checkouts (a repo-local lock would let two worktrees fire
# concurrently) without the world-writable-/tmp hazard of any local
# user pre-holding it to silently disable the watcher. No /tmp
# fallback for the same reason — an env without HOME (cron, systemd)
# must fail loudly here, not silently downgrade to a pre-holdable
# lock. Exit 3 is distinct so a chaining caller can tell "already
# covered" from "revalidated OK".
: "${HOME:?tpu_wait: HOME unset - refusing a world-writable /tmp lock}"
exec 9>"$HOME/.tpk_tpu_wait.lock"
if ! flock -n 9; then
  # held — by a live watcher (hours) or by a child orphaned when a
  # previous watcher died mid-queue/mid-sweep (bounded: the sweep's
  # worst case is ~21 min). Wait long enough to outlive any orphan
  # before concluding a live watcher owns it; exit 3 stays distinct
  # so a chaining caller can tell "already covered" from "ran".
  echo "tpu_wait: lock held (live watcher or orphaned child); waiting up to 30m"
  if ! flock -w 1800 9; then
    # Most likely a LIVE watcher (hours-long hold) — but an orphaned
    # tpu_revalidate.sh queue child also inherits fd 9 and can hold it
    # past 30m (the queue's worst case is ~2h of stamped steps on a
    # healthy chip; the sweep's is ~21m). Print the commands that
    # distinguish the two so the operator can kill a true orphan
    # instead of silently losing watch coverage.
    echo "tpu_wait: lock still held after 30m; exiting 3. Distinguish the holder:"
    echo "  pgrep -af tpu_wait_and_revalidate    # a LIVE watcher - leave it alone"
    echo "  pgrep -af 'tpu_revalidate|bench.py|sgemm_tune'  # an ORPHANED queue/sweep -"
    echo "  if only the second matches, kill those PIDs and re-run this script"
    exit 3
  fi
  echo "tpu_wait: lock acquired after wait (previous holder exited)"
fi
# transition guard: a watcher from a pre-relocation checkout may still
# hold the LEGACY /tmp lock and would not contend with ours — warn so
# the operator kills it rather than risking two interleaved
# revalidations on the one chip (warn-only: the legacy path is
# world-writable, so a held lock there must not be able to disable us)
if [ -e /tmp/tpk_tpu_wait.lock ] && command -v flock >/dev/null; then
  if ! flock -n -E 99 /tmp/tpk_tpu_wait.lock true 2>/dev/null; then
    echo "tpu_wait: WARNING: legacy /tmp/tpk_tpu_wait.lock is held -" \
         "a pre-relocation watcher may still be running (pgrep" \
         "tpu_wait_and_revalidate)"
  fi
fi

max_hours="${1:-10}"
deadline=$(( $(date +%s) + max_hours * 3600 ))

# one probe, two call sites (liveness poll + post-failure classifier)
# — they must answer the SAME question or the classifier can
# misjudge a wedge. The backend assert matters: with the tunnel down
# in a fail-FAST mode jax silently falls back to CPU, and a bare
# matmul probe would declare the dead tunnel ALIVE. -k: a wedged
# tunnel read can ignore SIGTERM — escalate to SIGKILL so the
# watcher itself can't hang on the exact failure it exists to
# survive. 9>&-: don't hand the lock fd to a killable child.
probe_tunnel() {
  timeout -k 10 90 python -c \
    "import jax; assert jax.default_backend() != 'cpu', jax.default_backend(); import jax.numpy as jnp; (jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()" \
    9>&-
}

while [ "$(date +%s)" -lt "$deadline" ]; do
  probe_err=$(probe_tunnel 2>&1 >/dev/null)
  if [ $? -eq 0 ]; then
    echo "tpu_wait: tunnel ALIVE at $(date -Is); starting revalidation"
    # no exec: the tunnel FLAPS (2-25 healthy minutes, then a wedge),
    # so a mid-queue wedge must put us back on probe duty, not kill
    # the watcher with the queue. Each attempt persists whatever it
    # captured; TPK_BENCH_SKIP_CAPTURED=1 makes the next attempt spend
    # its window only on still-missing metrics and judge the union of
    # the last 24h of artifacts (bench.py --union-persisted). The
    # flock fd is inherited by the child, so exclusion holds through
    # the queue.
    # PROBE_ATTEMPTS=1: we JUST probed healthy — if bench's own probe
    # fails now the tunnel already re-wedged, and its default ~30 min
    # of patience would burn the next flap window inside the queue
    # instead of returning it to this loop.
    env TPK_BENCH_SKIP_CAPTURED=1 TPK_BENCH_PROBE_ATTEMPTS=1 \
        bash tools/tpu_revalidate.sh
    queue_rc=$?  # must be captured from the command itself, not an
                 # if/fi (whose status is 0 when no branch runs)
    if [ "$queue_rc" -eq 0 ]; then
      echo "tpu_wait: revalidation PASSED at $(date -Is)"
      # queue green — spend whatever window remains on the sgemm tile
      # sweep (best-effort harvest, never gates: the chip may wedge
      # mid-sweep and that must not turn a PASSED queue into a
      # failure). Persisted to docs/logs for the session/driver to
      # commit.
      # fd 9 (the machine-wide chip lock) is deliberately INHERITED
      # here: if this watcher dies mid-sweep, the orphaned sweep is
      # still running timed configs on the one chip, and a new
      # watcher must not interleave its queue with it. The orphan's
      # hold is bounded (~21 min worst case: 3 configs x 420 s), and
      # the acquisition path above waits out a held lock rather than
      # exiting immediately, so inheritance cannot dead-lock a
      # replacement watcher.
      python tools/sgemm_tune.py --quick 2>&1 \
        | tee "docs/logs/sgemm_tune_$(date +%Y-%m-%d_%H%M%S).log" \
        || true
      exit 0
    fi
    # wedge vs deterministic failure: if the tunnel still answers
    # right after the queue failed, the failure was NOT a wedge (a
    # real regression, a C-gate bug, a sanitizer abort) — retrying
    # every 5m would re-run the expensive queue for hours against a
    # reproducible failure. Surface it instead. Only a dead/wedged
    # tunnel puts us back on probe duty. Two rcs are ALWAYS
    # retryable, healthy tunnel or not:
    #   124 — a `timeout`-killed step: something HUNG, and with
    #         45-90 min steps the tunnel can wedge and recover before
    #         the step's timeout fires;
    #   2   — bench gate "insufficient coverage": a metric has no
    #         value yet (bench is wedge-tolerant — a mid-bench wedge
    #         surfaces as a PARTIAL line + gate rc 2, never 124).
    #         Nothing regressed; the next window can fill the gap.
    if [ "$queue_rc" -ne 124 ] && [ "$queue_rc" -ne 2 ] \
        && probe_tunnel >/dev/null 2>&1; then
      echo "tpu_wait: queue FAILED (rc=$queue_rc) with the tunnel" \
           "still healthy - deterministic failure, not a wedge;" \
           "exiting $queue_rc"
      exit "$queue_rc"
    fi
    echo "tpu_wait: revalidation attempt FAILED at $(date -Is)" \
         "(rc=$queue_rc: wedge or not-yet-complete coverage);" \
         "back to probing in 5m"
    # 9>&-: a killed watcher must not leave its sleep holding the
    # lock fd for up to 5 min — that window blocks a REPLACEMENT
    # watcher (it sees the lock held and exits 3), leaving no watcher
    # at all (observed 2026-07-31)
    sleep 300 9>&-
    continue
  fi
  # keep the probe's own error visible: a broken probe (jax missing,
  # snippet bug) must be distinguishable from a dead tunnel
  echo "tpu_wait: tunnel still dead at $(date -Is); retry in 5m"
  [ -n "$probe_err" ] && printf '%s\n' "$probe_err" | tail -3
  sleep 300 9>&-  # see the retry-loop sleep: don't orphan the lock
done
echo "tpu_wait: gave up after ${max_hours}h"
exit 1
