#!/bin/bash
# Poll the axon tunnel and run the revalidation queue the moment it
# answers — THIN WRAPPER (see docs/NEXT.md, docs/RESILIENCE.md).
#   tools/tpu_wait_and_revalidate.sh [max_hours]   (default 10)
# The watch loop itself (backoff-scheduled probing, checkpointed
# queue attempts, post-green harvest) lives in tools/revalidate.py
# --wait; what stays HERE is the machine-wide $HOME flock machinery,
# because the lock must be held before any python starts and must die
# with the process tree.
set -o pipefail
cd "$(dirname "$0")/.."

# single instance: two watchers (e.g. one left over from a previous
# session, or one per checkout/worktree) would both fire the
# revalidation queue on recovery and interleave timed runs on the one
# chip. The lock dies with the process; exec below keeps fd 9 (and
# our pid) through the python watcher, which keeps the exclusion
# through the whole watch. $HOME-scoped fixed path on purpose:
# machine-wide exclusion across checkouts without the world-writable-
# /tmp hazard of any local user pre-holding it to silently disable
# the watcher. No /tmp fallback for the same reason — an env without
# HOME (cron, systemd) must fail loudly here, not silently downgrade
# to a pre-holdable lock. Exit 3 is distinct so a chaining caller can
# tell "already covered" from "revalidated OK".
: "${HOME:?tpu_wait: HOME unset - refusing a world-writable /tmp lock}"
# 9>> (append), NOT 9>: a LOSING contender must not truncate the live
# watcher's recorded pid out of the lock file before its flock fails —
# that would blind --whos-holding in exactly the contention case it
# exists for. The winner rewrites the pid below.
exec 9>>"$HOME/.tpk_tpu_wait.lock"
if ! flock -n 9; then
  # held — by a live watcher (hours) or by a child orphaned when a
  # previous watcher died mid-queue/mid-sweep. Wait long enough to
  # outlive any orphan before concluding a live watcher owns it
  # (TPK_LOCK_WAIT_S: tests compress the wait; default 30m).
  echo "tpu_wait: lock held (live watcher or orphaned child); waiting ${TPK_LOCK_WAIT_S:-1800}s"
  if ! flock -w "${TPK_LOCK_WAIT_S:-1800}" 9; then
    echo "tpu_wait: lock still held; exiting 3. Diagnose the holder with:"
    echo "  python tools/revalidate.py --whos-holding"
    echo "(a LIVE watcher - leave it alone; an ORPHANED queue/sweep -"
    echo " kill the listed pids and re-run this script)"
    exit 3
  fi
  echo "tpu_wait: lock acquired after wait (previous holder exited)"
fi
# record the holder for --whos-holding: exec preserves our pid, so $$
# IS the python watcher's pid. Write via the path (fd 9's offset is
# the flock handle, not a log).
echo "$$" > "$HOME/.tpk_tpu_wait.lock"
# transition guard: a watcher from a pre-relocation checkout may still
# hold the LEGACY /tmp lock and would not contend with ours — warn so
# the operator kills it rather than risking two interleaved
# revalidations on the one chip (warn-only: the legacy path is
# world-writable, so a held lock there must not be able to disable us)
if [ -e /tmp/tpk_tpu_wait.lock ] && command -v flock >/dev/null; then
  if ! flock -n -E 99 /tmp/tpk_tpu_wait.lock true 2>/dev/null; then
    echo "tpu_wait: WARNING: legacy /tmp/tpk_tpu_wait.lock is held -" \
         "a pre-relocation watcher may still be running (pgrep" \
         "tpu_wait_and_revalidate)"
  fi
fi

# exec on purpose (unlike the old watcher): the probe/retry loop now
# lives INSIDE revalidate.py --wait, so a mid-queue wedge returns to
# probe duty within the python process; fd 9 rides through exec and
# the lock holds for the watcher's whole life. The supervisor passes
# fd 9 on to its STEP children (and only those — probes close it),
# preserving the old queue's invariant: a step orphaned by a dying
# watcher still holds the lock while it runs timed work on the chip.
exec python tools/revalidate.py --wait --max-hours "${1:-10}"
