"""Suite-wide AOT prewarm CLI (docs/PERF.md §compile discipline).

Usage:
    python tools/prewarm.py                     # precompile every
                                                # registered kernel config
    python tools/prewarm.py --kernels sgemm,scan
    python tools/prewarm.py --bench all         # also pre-warm the bench
                                                # loop programs (killable
                                                # bench.py --prewarm child
                                                # per metric)
    python tools/prewarm.py --check             # machine mode (rc only
                                                # prints failures)
    python tools/prewarm.py --order traffic     # hottest kernels first
                                                # (journal serve_request
                                                # frequency; registry
                                                # order when no traffic
                                                # evidence exists)

Compiles the whole suite OFF-window so a healthy flap window opens
with a hot cache: the registry-level pass lowers every kernel's
benchmark config from ShapeDtypeStruct avatars (nothing allocates,
nothing executes — safe on any host, and on the TPU box it fills the
remote-compile cache without holding the chip); ``--bench`` adds the
two jitted repeat-count loop programs per metric via ``bench.py
--prewarm`` children under the watchdog's hard kill, exactly the old
stencil3d-only step 0 generalized to the full registry.

Every kernel lands a ``prewarm_kernel`` journal event whose measured
walls feed the supervisor's chip-minute cost estimate for the
``prewarm_all`` step (tools/revalidate.py); the run is bracketed by
``prewarm_start`` / ``prewarm_end``.

``--order traffic`` re-ranks the compile queue by live request
frequency (the journal's ``serve_request`` records, via
``tpukernels.serve.adapt.traffic_order``) so a prewarm cut short by
its window still warmed what traffic actually hits; with no traffic
evidence it says so on stderr and keeps registry order.

Exit codes mirror ``tools/obs_report.py --check``:
    0 — everything asked for compiled (warm cache, go measure);
    1 — at least one kernel/metric failed to compile (or the AOT
        layer is disabled — a prewarm that compiles nothing must
        never report success);
    2 — usage error.
"""

from __future__ import annotations

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# env-before-jax-import rule: the cache knobs must be set before the
# registry pulls jax in through the first precompile
from tpukernels._cachedir import ensure_compilation_cache  # noqa: E402

ensure_compilation_cache()

from tpukernels import aot  # noqa: E402
from tpukernels.resilience import journal, watchdog  # noqa: E402


def _prewarm_bench_metric(metric: str, timeout_s: float):
    """One ``bench.py --prewarm <metric>`` child under the watchdog's
    hard kill — the loop-program half of the prewarm. Returns
    (status, wall_s) with the watchdog's ok|timeout|error vocabulary."""
    import subprocess

    t0 = time.monotonic()
    r, status = watchdog.kill_after(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--prewarm", metric],
        timeout_s,
        site=f"prewarm --prewarm {metric}",
        cwd=_REPO,
        stdout=subprocess.DEVNULL,
    )
    if status == "ok" and r.returncode != 0:
        status = "error"
    return status, round(time.monotonic() - t0, 3)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    check = "--check" in argv
    kernels = None
    bench_metrics: list = []
    timeout_s = 900.0
    order = "registry"
    it = iter(argv)
    try:
        for a in it:
            if a == "--kernels":
                kernels = [k.strip() for k in next(it).split(",")
                           if k.strip()]
            elif a == "--bench":
                bench_metrics = [m.strip() for m in next(it).split(",")
                                 if m.strip()]
            elif a == "--timeout-s":
                timeout_s = float(next(it))
            elif a == "--order":
                order = next(it)
            elif a != "--check":
                print(__doc__, file=sys.stderr)
                print(f"prewarm: unknown argument {a!r}", file=sys.stderr)
                return 2
    except StopIteration:
        print(f"prewarm: {a} requires a value", file=sys.stderr)
        return 2
    except ValueError:
        print(f"prewarm: {a} needs a numeric value", file=sys.stderr)
        return 2
    if order not in ("registry", "traffic"):
        print(f"prewarm: --order {order!r} (known: registry, traffic)",
              file=sys.stderr)
        return 2
    if not aot.enabled():
        # a prewarm that silently compiles nothing would read as a hot
        # cache to the supervisor — refuse loudly instead
        print("prewarm: TPK_AOT_CACHE=0 disables the AOT layer - "
              "nothing to prewarm", file=sys.stderr)
        return 1
    # unattended runs land their evidence in the day's journal, same
    # routing default as bench.py's CLI entry
    os.environ.setdefault("TPK_HEALTH_JOURNAL", journal.default_path())

    from tpukernels import registry

    known = registry.precompilable_kernels()
    if kernels is None:
        kernels = known
    else:
        unknown = [k for k in kernels if k not in known]
        if unknown:
            print(f"prewarm: unknown/unprecompilable kernels {unknown}; "
                  f"known: {known}", file=sys.stderr)
            return 2
    if order == "traffic":
        from tpukernels.resilience.journal import load_events
        from tpukernels.serve import adapt

        events, _bad = load_events(
            [journal.path() or journal.default_path()]
        )
        kernels, counts = adapt.traffic_order(events, kernels)
        if counts:
            print("prewarm: traffic order "
                  + ", ".join(f"{k}={counts.get(k, 0)}"
                              for k in kernels))
        else:
            print("prewarm: --order traffic but the journal holds no "
                  "serve_request evidence - keeping registry order",
                  file=sys.stderr)
    from bench import BENCH_METRICS  # noqa: E402 — after cache env setup

    metric_names = [n for n, _f in BENCH_METRICS]
    if bench_metrics == ["all"]:
        bench_metrics = metric_names
    else:
        unknown = [m for m in bench_metrics if m not in metric_names]
        if unknown:
            print(f"prewarm: unknown bench metrics {unknown}; known: "
                  f"{metric_names}", file=sys.stderr)
            return 2

    journal.emit("prewarm_start", kernels=kernels, metrics=bench_metrics)
    t0 = time.monotonic()
    failed = []
    echo = (lambda line: None) if check else print
    echo(f"prewarm: {len(kernels)} kernel config(s)"
         + (f" + {len(bench_metrics)} bench metric(s)"
            if bench_metrics else ""))
    for row in aot.prewarm_all(kernels, echo=echo):
        if "error" in row:
            failed.append(row["kernel"])
            print(f"prewarm: {row['kernel']} FAILED: {row['error']}",
                  file=sys.stderr)
            journal.emit("prewarm_kernel", kernel=row["kernel"],
                         status="error", error=row["error"])
        else:
            journal.emit("prewarm_kernel", kernel=row["kernel"],
                         key=row["key"], expected=row["expected"],
                         wall_s=row["wall_s"], status="ok")
    for metric in bench_metrics:
        status, wall = _prewarm_bench_metric(metric, timeout_s)
        if status != "ok":
            failed.append(metric)
            print(f"prewarm: bench metric {metric} FAILED ({status})",
                  file=sys.stderr)
        else:
            echo(f"  {metric:<22} loop programs cached "
                 f"wall={wall:.1f}s")
        journal.emit("prewarm_kernel", kernel=metric, mode="bench",
                     status=status, wall_s=wall)
    total = round(time.monotonic() - t0, 3)
    journal.emit("prewarm_end",
                 compiled=len(kernels) + len(bench_metrics) - len(failed),
                 failed=sorted(failed), total_wall_s=total)
    n_ok = len(kernels) + len(bench_metrics) - len(failed)
    print(f"prewarm{' --check' if check else ''}: {n_ok} warmed, "
          f"{len(failed)} failed in {total:.1f}s"
          + (f" (failed: {','.join(sorted(failed))})" if failed else ""))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
