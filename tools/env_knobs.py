"""Lint: every ``TPK_*`` env knob must be documented.

Usage:
    python tools/env_knobs.py          # rc 0 clean, rc 1 findings

The knob population is past fifty and undocumented ones were
accumulating: a knob that exists only in the code that reads it is an
operator silently running with a default they cannot discover. This
lint scans every ``TPK_*`` knob referenced in production code —
``bench.py``, ``tests/conftest.py``, ``tpukernels/**``, ``tools/**``
(Python via the AST: string constants that ARE a knob name, which
skips docstring prose; shell via regex, including ``c/**``'s harness
scripts) — and asserts each appears in the catalog table of
docs/KNOBS.md. Runs in tier-1 via
``tests/test_obs.py::test_env_knobs_lint`` (the journal-kind lint's
sibling).

Also warns (without failing) on documented-but-unreferenced knobs —
usually a knob that was removed without its doc row.
"""

from __future__ import annotations

import ast
import glob
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DOC_REL = os.path.join("docs", "KNOBS.md")

_KNOB_RE = re.compile(r"TPK_[A-Z0-9_]+")
_DOC_KNOB_RE = re.compile(r"^\|\s*`(TPK_\w+)`", re.MULTILINE)


def production_files(repo=_REPO):
    """(python_files, shell_files) the lint scans. The lint's own file
    is excluded (its docstring names knobs as prose)."""
    py = [
        os.path.join(repo, "bench.py"),
        os.path.join(repo, "__graft_entry__.py"),
        os.path.join(repo, "tests", "conftest.py"),
    ]
    for sub in ("tpukernels", "tools"):
        py.extend(sorted(glob.glob(
            os.path.join(repo, sub, "**", "*.py"), recursive=True
        )))
    sh = []
    for sub in ("tools", "c"):
        sh.extend(sorted(glob.glob(
            os.path.join(repo, sub, "**", "*.sh"), recursive=True
        )))
    return (
        [f for f in py if os.path.isfile(f)
         and os.path.basename(f) != "env_knobs.py"],
        [f for f in sh if os.path.isfile(f)],
    )


def referenced_knobs(repo=_REPO):
    """{knob: [file:line, ...]} over production references, plus a
    list of unparseable python files (reported, never silently
    skipped)."""
    knobs: dict = {}
    unparseable = []
    py, sh = production_files(repo)
    for path in py:
        rel = os.path.relpath(path, repo)
        try:
            with open(path) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError) as e:
            unparseable.append(f"{rel}: {e}")
            continue
        for node in ast.walk(tree):
            # exact-match string constants only: "TPK_FOO" is a knob
            # reference (env read/write/declaration); a docstring
            # mentioning knobs is a long string and never fullmatches
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _KNOB_RE.fullmatch(node.value)
            ):
                knobs.setdefault(node.value, []).append(
                    f"{rel}:{node.lineno}"
                )
    for path in sh:
        rel = os.path.relpath(path, repo)
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            unparseable.append(f"{rel}: {e}")
            continue
        for i, line in enumerate(text.splitlines(), 1):
            for m in _KNOB_RE.finditer(line):
                knobs.setdefault(m.group(0), []).append(f"{rel}:{i}")
    return knobs, unparseable


def documented_knobs(doc=None):
    doc = doc or os.path.join(_REPO, _DOC_REL)
    try:
        with open(doc) as f:
            return set(_DOC_KNOB_RE.findall(f.read()))
    except OSError:
        return set()


def main(argv=None):
    repo = _REPO
    argv = sys.argv[1:] if argv is None else list(argv)
    it = iter(argv)
    for a in it:
        if a == "--root":
            try:
                repo = next(it)
            except StopIteration:
                print("env_knobs: --root requires a value",
                      file=sys.stderr)
                return 2
        else:
            print(f"env_knobs: unknown argument {a!r}", file=sys.stderr)
            return 2
    knobs, unparseable = referenced_knobs(repo)
    documented = documented_knobs(os.path.join(repo, _DOC_REL))
    rc = 0
    if not documented:
        print(f"env_knobs: {_DOC_REL} has no knob catalog "
              "(| `TPK_...` | rows) - nothing to lint against")
        rc = 1
    undocumented = {k: v for k, v in knobs.items() if k not in documented}
    for knob in sorted(undocumented):
        print(
            f"env_knobs: knob {knob!r} is referenced but not in the "
            f"{_DOC_REL} catalog:"
        )
        for where in undocumented[knob][:6]:
            print(f"  {where}")
        rc = 1
    for msg in unparseable:
        print(f"env_knobs: cannot scan {msg}")
        rc = 1
    unused = documented - set(knobs)
    for knob in sorted(unused):
        print(
            f"env_knobs: WARN documented knob {knob!r} has no "
            "production reference (stale doc row?)"
        )
    if rc == 0:
        print(
            f"env_knobs: OK - {len(knobs)} knobs across "
            f"{sum(len(v) for v in knobs.values())} reference(s), all "
            "documented"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
