#!/bin/bash
# Post-recovery TPU validation queue (run from /root/repo) — THIN
# WRAPPER. The queue logic (step specs, git-aware per-day stamps,
# crash-safe checkpoint resume, step quarantine, flap-aware
# admission) moved to tools/revalidate.py +
# tpukernels/resilience/supervisor.py; this wrapper survives for
# operator muscle memory and for callers scripted against it.
#
# Exit-code contract (unchanged): 0 green; 2 incomplete-but-nothing-
# regressed (retryable); 124 wedge/timeout (retryable); other nonzero
# = a gating step failed loudly.
set -o pipefail
cd "$(dirname "$0")/.."
exec python tools/revalidate.py "$@"
