#!/bin/bash
# Post-recovery TPU validation queue (run from /root/repo).
# Use after the axon tunnel has been down or wedged: re-measures every
# headline metric, then re-proves the compiled path end to end.
#
# ORDERING (2026-07-31): highest value per chip-minute FIRST. The
# tunnel has been observed to flap — answer a probe, serve traffic for
# ~2 minutes, then wedge (hang, not error) — so a healthy window must
# produce the round's headline numbers before anything long-running
# gets a chance to eat it. bench.py is itself wedge-tolerant (one
# killable subprocess per metric, partial results on wedge).
#
# -e: this is a gate — a failed bench, suite, gate row, or sanitizer
# abort must fail the whole queue, not fall through to the next step.
set -e -x -o pipefail
cd "$(dirname "$0")/.."

# Per-day step stamps: the watcher retries the whole queue on every
# healthy probe, and with 2-25 minute flap windows an attempt that
# redoes already-green steps may never REACH the later ones. A step
# that completed today is skipped on retry (set -e means a failed
# step never stamps). Same accepted tradeoff as the bench evidence
# window: stamps are wall-clock-scoped, not git-aware — force a full
# re-run after a same-day code change with TPK_REVALIDATE_FORCE=1.
# The bench step is never stamped: its own skip-captured logic keeps
# it cheap, and the sgemm canary + union gate must run every attempt.
# step_done/stamp/run_step live in the sourced lib so the CPU test
# suite (tests/test_revalidate_stamps.py) proves the exact
# stamp/resume logic this queue runs — not a copy of it.
stamp_dir="docs/logs/.revalidate_stamps"
mkdir -p "$stamp_dir"
source tools/revalidate_lib.sh

# 0. Pre-warm stencil3d's two R-variant compiles into the persistent
#    cache in a throwaway killable subprocess (VERDICT r4: the tunnel
#    wedged mid-stencil3d in two consecutive windows, and whether the
#    trigger is the compile or the execute phase was never pinned).
#    Non-gating and attempted ONCE per day: the attempt stamp lands
#    BEFORE the run, so a wedge here cannot re-eat every subsequent
#    flap window — the next attempt goes straight to bench, which
#    orders stencil3d last anyway. Either way the stderr breadcrumb
#    log (slope phases + jacobi3d slab geometry) is the postmortem
#    evidence: the last line before a wedge names the phase.
if ! step_done prewarm3d_attempt; then
  stamp prewarm3d_attempt
  prewarm_log="docs/logs/prewarm3d_$(date +%Y-%m-%d_%H%M%S).log"
  if timeout -k 10 900 python bench.py --prewarm stencil3d_mcells_s \
      >"$prewarm_log" 2>&1; then
    echo "prewarm stencil3d: OK (compiles cached)"
  else
    echo "WARN: stencil3d prewarm failed rc=$? (non-gating) -" \
         "$prewarm_log is the postmortem evidence"
  fi
fi

# 1. Headline metrics (median-of-slopes; see bench.py docstring),
#    then gate on the self-regression compare: any metric >15% below
#    the BASELINE.json "measured" medians fails the queue loudly.
#    The JSON line is also persisted to docs/logs/ so an unattended
#    recovery (watcher-fired queue) leaves a committable artifact even
#    if the session that started it is gone.
#    Artifact name carries the full timestamp: a same-day re-run (the
#    watcher can fire the queue more than once across tunnel flaps)
#    must not clobber an earlier good run's numbers with a worse or
#    partial line.
#    TPK_BENCH_SKIP_CAPTURED=1 (set by the watcher's retry loop)
#    spends a short flap window only on metrics with no persisted
#    evidence yet; the gate then judges the union of the last 24h of
#    artifacts instead of this run alone.
union_flag=""
if [ "${TPK_BENCH_SKIP_CAPTURED:-}" = "1" ]; then
  # same "= 1" test bench.py uses — any other value (e.g. an intended
  # "0") must neither skip metrics nor weaken the gate to union mode
  union_flag="--union-persisted"
fi
bench_out=$(timeout 5400 python bench.py)
printf '%s\n' "$bench_out"
printf '%s\n' "$bench_out" | tail -1 > "docs/logs/bench_$(date +%Y-%m-%d_%H%M%S).json"
printf '%s\n' "$bench_out" | tail -1 | python bench.py --check-regression $union_flag

# 1b. Observability trend check (docs/OBSERVABILITY.md): machine-reads
#     the whole artifact history (BENCH_r*.json + docs/logs/bench_*)
#     just persisted above and flags >1%-band regressions and
#     physically-impossible captures. Non-gating: the 15% gate in 1 is
#     the pass/fail authority; this is the early-drift tripwire, and a
#     WARN here is a prompt to read `python tools/obs_report.py`
#     before promoting any baseline.
if python tools/obs_report.py --check; then
  echo "obs trend check: OK"
else
  echo "WARN: obs_report --check flagged the bench trend (rc=$?," \
       "non-gating) - run 'python tools/obs_report.py' for the story"
fi

# 2. C acceptance gate: serial/omp + real TPU rows + fake-device mesh
c_gate_step() {
  make -C c -s
  (cd c && timeout 900 env TPK_TEST_TPU=1 TPK_TEST_MESH=8 ./run_all.sh | tail -3)
}
run_step c_gate c_gate_step

# 2b. C-path scan_histogram throughput (docs/NEXT.md item 2): the
#     combined one-dispatch adapter halved per-rep dispatch cost;
#     record this Melem/s in docs/PERF.md next to the kernel-level
#     number.
c_scan_timing_step() {
  make -C c -s
  (cd c && timeout 600 ./bin/scan_histogram --device=tpu --n=4194304 --check)
}
run_step c_scan_timing c_scan_timing_step

# 2c. Profiler evidence for the roofline claims (VERDICT r3 item 5):
#     XProf traces of the two headline kernels, summarized into
#     docs/logs/profile_{sgemm,stencil}_<date>.log — commit these and
#     lift the busy %/top-op numbers into docs/PERF.md. Evidence
#     capture, not a correctness gate: a profiling-only failure (tf
#     schema drift, empty trace) must not abort a queue whose real
#     gates all passed, so it is warn-only (and only stamped on
#     success, so a flap mid-capture retries next window).
if ! step_done profile; then
  if bash tools/profile_headline.sh; then
    stamp profile
  else
    echo "WARN: profile capture failed (non-gating)"
  fi
fi

# 2d. Knob sanity: histogram impls agree, sgemm precisions hold their
#     error contracts (exercised by the suite below too; these are
#     quick re-confirms on the chip while the tunnel is warm)
knob_sanity_step() {
  for impl in mxu vpu; do
    timeout 600 env TPK_HIST_IMPL=$impl python -c "
from bench import bench_scan_hist
print('scan_hist $impl:', round(bench_scan_hist(), 1))"
  done
  timeout 600 env TPK_SGEMM_PRECISION=float32 python -c "
from bench import bench_sgemm
print('sgemm f32 (bf16_6x):', round(bench_sgemm(), 1))"
}
run_step knob_sanity knob_sanity_step

# 3. Compiled-path test suite (axon backend, kernels compile on chip).
# TPK_REQUIRE_TPU=1: a still-wedged tunnel must FAIL here, not slip
# into conftest's silent CPU fallback. Longest step — deliberately
# after every metric capture; the 2026-07-31 cold-cache run needed
# >1800 s of remote compiles (conftest persists the compilation
# cache, but the FIRST post-recovery run still compiles whatever the
# bench steps above didn't). Run in stamped GROUPS, kernel files
# first: pytest has no resume, and one 45-min monolith restarted from
# scratch every retry may never fit inside a 2-25 min flap window —
# groups let on-chip validation accrue across windows. Group borders
# follow compile cost: each kernel file owns its kernel's variants;
# "rest" is the capi/distributed/bench/host machinery, which mostly
# spawns scrubbed-CPU subprocesses and reuses the kernels' cache.
do_pytest_group() {  # pipefail is set, so a failing pytest fails this
  timeout 1200 env TPK_REQUIRE_TPU=1 python -m pytest "$@" -q | tail -2
}
pytest_group() {  # $1 = group name, $2... = pytest file args
  local grp="$1"; shift
  run_step "pytest_$grp" do_pytest_group "$@"
}
pytest_group vector_add tests/test_vector_add.py
pytest_group sgemm      tests/test_sgemm.py
pytest_group stencil    tests/test_stencil.py
pytest_group scan_hist  tests/test_scan_histogram.py
pytest_group nbody      tests/test_nbody.py
pytest_group determinism tests/test_determinism.py tests/test_fuzz_shapes.py
pytest_group rest tests/ \
  --ignore=tests/test_vector_add.py --ignore=tests/test_sgemm.py \
  --ignore=tests/test_stencil.py --ignore=tests/test_scan_histogram.py \
  --ignore=tests/test_nbody.py --ignore=tests/test_determinism.py \
  --ignore=tests/test_fuzz_shapes.py

# 3b. Autotune pipeline smoke (docs/TUNING.md): proves the sweep ->
#     cache -> dispatch path end to end on CPU interpret mode. Needs
#     no tunnel (the --smoke parent scrubs itself and its bench
#     children off the axon pool), so it never eats a flap window;
#     non-gating and once per day, like the profiler capture — a
#     broken TUNER must not block a queue whose measurement gates all
#     passed. The smoke cache entry is keyed device_kind=cpu and can
#     never steer a TPU dispatch.
if ! step_done autotune_smoke; then
  autotune_log="docs/logs/autotune_smoke_$(date +%Y-%m-%d_%H%M%S).log"
  if timeout -k 10 600 python tools/autotune.py --kernel sgemm --smoke \
      >"$autotune_log" 2>&1; then
    stamp autotune_smoke
    echo "autotune smoke: OK (pipeline proven; $autotune_log)"
  else
    echo "WARN: autotune smoke failed rc=$? (non-gating) - $autotune_log"
  fi
fi

# 4. Sanitizer gates (SURVEY.md §5): ASan then UBSan rebuilds, full
#    gate incl. the embedded-CPython shim rows on a scrubbed CPU env
#    (kernels auto-interpret there), then restore the normal build.
#    CPU-only — needs no tunnel; last on purpose.
#    First recorded PASS logs: docs/logs/{asan,ubsan}_gate_2026-07-30.log.
for san in asan ubsan; do
  if ! step_done "san_$san"; then
    make -C c "$san"
    (cd c && timeout 1800 env ASAN_OPTIONS=detect_leaks=0 \
        PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu TPK_TEST_TPU=1 \
        TPK_TEST_MESH=8 ./run_all.sh | tail -3)
    stamp "san_$san"
    make -C c -s clean && make -C c -s
  fi
done
