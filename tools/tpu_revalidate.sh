#!/bin/bash
# Post-recovery TPU validation queue (run from /root/repo)
set -x -o pipefail
cd /root/repo

# 1. Compiled-path test suite (axon backend, kernels compile on chip)
timeout 1200 python -m pytest tests/test_sgemm.py tests/test_stencil.py tests/test_scan_histogram.py -q | tail -2

# 2. SGEMM: measure pre-split win
timeout 600 python -c "
from bench import bench_sgemm
print('sgemm GFLOPS:', round(bench_sgemm(), 1))"

# 3. Stencil 2D confirm + 3D with conservative picker
timeout 900 python -c "
from bench import bench_stencil, bench_stencil3d
print('stencil2d:', round(bench_stencil(), 1))
print('stencil3d:', round(bench_stencil3d(), 1))"

# 3b. Stencil 2D bm experiment: 504-row blocks cut ghost recompute
#     7.7% -> 3% (VPU-bound, so recompute is pure waste). COMPILE-PROBE
#     FIRST with a short timeout — big unrolled slabs can wedge the
#     remote compiler (cf. the 3D incident).
timeout 300 python -c "
import jax, jax.numpy as jnp, numpy as np
from tpukernels.kernels import stencil
stencil._pick_bm = lambda wp: 504
from tpukernels.kernels.stencil import jacobi2d
x = jnp.zeros((4096, 4096), jnp.float32)
r = np.asarray(jax.jit(lambda v: jnp.sum(jacobi2d(v, 8)))(x))
print('bm=504 compiles and runs')" && \
timeout 600 python -c "
from tpukernels.kernels import stencil
stencil._pick_bm = lambda wp: 504
from bench import bench_stencil
print('stencil2d bm=504:', round(bench_stencil(), 1))"

# 4. Histogram acc variants
for acc in i8 f32; do
  timeout 600 env TPK_HIST_ACC=$acc python -c "
from bench import bench_scan_hist
print('scan_hist $acc:', round(bench_scan_hist(), 1))"
done

# 5. C acceptance gate with real TPU rows
cd c && timeout 900 env TPK_TEST_TPU=1 ./run_all.sh | tail -3; cd ..

# 6. Full headline
timeout 3000 python bench.py
