#!/bin/sh
# Weak-scaling table (docs/NEXT.md pod item): per-kernel wall-clock of
# the distributed-capable C drivers as the mesh grows, problem size
# scaled with the mesh so per-chip work is constant.
#
#   tools/weak_scaling.sh "1 2 4 8"     # mesh sizes (default "1 2 4 8")
#
# On a pod: run as-is once per host (chips visible to jax). On the dev
# box: FAKE=1 tools/weak_scaling.sh runs on fake CPU devices — numbers
# are meaningless there, but the harness, shardings and scaled shapes
# are exactly what the pod run will use, so a pod session only has to
# run one command and read the table.
#
# Per-chip work held constant: stencil rows, N-body bodies, scan/hist
# elements and the allreduce message all scale linearly with N (N-body
# is O(N^2) total — linear per chip when i-bodies shard).
set -e
cd "$(dirname "$0")/../c"

sizes="${1:-1 2 4 8}"
base_rows=512        # stencil rows per chip (x 1024 cols)
base_bodies=2048     # N-body bodies per chip
base_elems=1048576   # scan/hist elements per chip
base_msg=4194304     # allreduce floats per chip

for n in $sizes; do
  env_common="TPK_MESH=$n"
  if [ "${FAKE:-0}" = "1" ]; then
    env_common="$env_common PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=$n"
  fi
  echo "== mesh n=$n"
  # shellcheck disable=SC2086
  env $env_common ./bin/stencil --device=tpu --check --reps=3 \
      --n=$((base_rows * n)) --m=1024 --iters=50
  # shellcheck disable=SC2086
  env $env_common ./bin/nbody --device=tpu --check --reps=3 \
      --n=$((base_bodies * n)) --iters=2
  # shellcheck disable=SC2086
  env $env_common ./bin/scan_histogram --device=tpu --check --reps=3 \
      --n=$((base_elems * n))
  # shellcheck disable=SC2086
  env $env_common ./bin/allreduce_bench --device=tpu --check --reps=3 \
      --n=$((base_msg * n))
done
echo "weak_scaling: done (grep 'metric=' lines into the table)"
