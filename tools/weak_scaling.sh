#!/bin/sh
# DEPRECATED thin wrapper (the sgemm_tune.py pattern): the weak-scaling
# sweep now lives in tools/weak_scaling.py, which emits structured
# scaling artifacts + journal events instead of a grep-me stdout table
# (docs/DISTRIBUTED.md §observability). This wrapper keeps the old
# calling convention alive:
#
#   tools/weak_scaling.sh "1 2 4 8"     # mesh sizes (default "1 2 4 8")
#   FAKE=1 tools/weak_scaling.sh ...    # fake CPU devices
#
# Old semantics preserved: no FAKE = the caller's real devices (a pod
# host) = --real; FAKE=1 = the python tool's fake-device default.
echo "weak_scaling.sh: deprecated - delegating to tools/weak_scaling.py" >&2
dir="$(dirname "$0")"
real_flag="--real"
[ "${FAKE:-0}" = "1" ] && real_flag=""
# shellcheck disable=SC2086
exec python "$dir/weak_scaling.py" ${1:+--sizes "$1"} $real_flag
