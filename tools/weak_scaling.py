"""Weak-scaling sweep with structured artifacts (supersedes
tools/weak_scaling.sh's grep-the-stdout table).

Usage:
    python tools/weak_scaling.py [--sizes "1 2 4 8"] [--reps 2]
                                 [--quick] [--real] [--out DIR]

Per mesh size N the sweep times one step of every distributed program
with per-chip work held constant (stencil rows, N-body bodies,
scan/hist elements and the allreduce message all scale linearly with
N — N-body is O(N^2) total, linear per chip when i-bodies shard), so
ideal weak scaling is a FLAT wall-clock line. Each (program, N) point
is journaled as a ``weak_scaling_point`` event and the whole sweep is
persisted as one ``docs/logs/scaling_weak_*.json`` artifact
(``TPK_SCALING_DIR`` / ``--out`` redirect) that ``tools/obs_report.py``
judges: efficiency at the largest mesh under ``TPK_SCALING_MIN_EFF``
earns the NON-GATING ``below_scaling_efficiency`` verdict
(docs/OBSERVABILITY.md §scaling).

Default (and the only mode that runs on the dev box): each mesh size
runs in a fresh subprocess with a scrubbed CPU-backend env and N fake
devices — the same isolation ``__graft_entry__.dryrun_multichip``
uses, so a wedged axon tunnel can never hang the sweep. Artifacts are
then flagged ``fake=true`` and EXCLUDED from gating: all N "chips"
timeshare one physical core here, so the numbers prove harness +
shardings + scaled shapes, never bandwidth. ``--real`` keeps the
caller's env (a pod host: run once per host like the C driver,
coordinator vars exported) and produces the gating-eligible evidence.

``--quick`` shrinks per-chip work ~100x for CI. The program catalog is
``scaling.WEAK_SERIES`` — the completeness lint
(tests/test_scaling_obs.py) pins this module's sweep table to it so a
new distributed program cannot ship observability-dark.

Exit codes: 0 — sweep completed; 1 — a program failed; 2 — usage.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels import _cachedir  # noqa: E402

# env-before-jax-import contract: inner subprocesses compile real XLA
# programs and must share the persistent cache
_cachedir.ensure_compilation_cache()

from tpukernels.obs import metrics as obs_metrics  # noqa: E402
from tpukernels.obs import scaling  # noqa: E402
from tpukernels.resilience import journal  # noqa: E402

# Per-chip work of record (mirrors the superseded weak_scaling.sh):
# (default, --quick) pairs.
WORK = {
    "stencil_rows": (512, 16), "stencil_cols": (1024, 64),
    "stencil_iters": (50, 2),
    "nbody_bodies": (2048, 64), "nbody_steps": (2, 1),
    "elems": (1 << 20, 4096), "nbins": (256, 256),
    "allreduce_floats": (1 << 22, 2048),
}


def _work(name: str, quick: bool) -> int:
    return WORK[name][1 if quick else 0]


# ------------------------------------------------------------------ #
# the program table (names pinned to scaling.WEAK_SERIES by the lint) #
# ------------------------------------------------------------------ #

def _run_stencil2d(n: int, quick: bool, rng):
    import jax
    import jax.numpy as jnp

    from tpukernels.parallel import make_mesh
    from tpukernels.parallel.collectives import jacobi2d_dist

    rows = _work("stencil_rows", quick)
    cols = _work("stencil_cols", quick)
    iters = _work("stencil_iters", quick)
    mesh = make_mesh(n)
    x = jnp.asarray(
        rng.standard_normal((rows * n, cols)), jnp.float32
    )

    def call():
        jax.block_until_ready(jacobi2d_dist(x, iters, mesh))

    return call, rows


def _run_nbody_ring(n: int, quick: bool, rng):
    import jax
    import jax.numpy as jnp

    from tpukernels.parallel import make_mesh
    from tpukernels.parallel.collectives import nbody_dist_ring

    bodies = _work("nbody_bodies", quick)
    steps = _work("nbody_steps", quick)
    mesh = make_mesh(n)
    nb = bodies * n
    state = tuple(
        jnp.asarray(rng.standard_normal(nb), jnp.float32)
        for _ in range(6)
    ) + (jnp.asarray(rng.uniform(0.5, 1.5, nb), jnp.float32),)

    def call():
        jax.block_until_ready(nbody_dist_ring(state, steps, mesh))

    return call, bodies


def _run_scan_hist(n: int, quick: bool, rng):
    import jax
    import jax.numpy as jnp

    from tpukernels.parallel import make_mesh
    from tpukernels.parallel.collectives import histogram_dist, scan_dist

    elems = _work("elems", quick)
    nbins = _work("nbins", quick)
    mesh = make_mesh(n)
    x = jnp.asarray(
        rng.integers(0, nbins, elems * n), jnp.int32
    )

    def call():
        jax.block_until_ready(scan_dist(x, mesh))
        jax.block_until_ready(histogram_dist(x, nbins, mesh))

    return call, elems


def _run_allreduce(n: int, quick: bool, rng):
    import jax
    import numpy as np

    from tpukernels.parallel import make_mesh
    from tpukernels.parallel.collectives import allreduce_sum
    from tpukernels.parallel.mesh import host_to_global, row_sharding

    floats = _work("allreduce_floats", quick)
    mesh = make_mesh(n)
    x = host_to_global(
        np.ones((n, floats), np.float32), row_sharding(mesh)
    )

    def call():
        jax.block_until_ready(allreduce_sum(x, mesh))

    return call, floats


def _mesh2d_shape(n: int):
    """The (r, c) this sweep uses for an n-device 2-D mesh: the
    flattest 2-row split. None when n has no 2-D factorization worth
    sweeping (< 4 devices or odd)."""
    if n < 4 or n % 2:
        return None
    return (2, n // 2)


def _run_allreduce2d(n: int, quick: bool, rng):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from tpukernels.parallel import make_mesh
    from tpukernels.parallel.collectives import allreduce_sum
    from tpukernels.parallel.mesh import host_to_global

    shape = _mesh2d_shape(n)
    if shape is None:
        return None  # inner() skips unbuildable points
    floats = _work("allreduce_floats", quick)
    mesh = make_mesh(shape)
    sharding = NamedSharding(mesh, PartitionSpec(("x", "y"), None))
    x = host_to_global(np.ones((n, floats), np.float32), sharding)

    def call():
        jax.block_until_ready(allreduce_sum(x, mesh))

    return call, floats, {"mesh_shape": list(shape)}


PROGRAMS = {
    "stencil2d": _run_stencil2d,
    "nbody_ring": _run_nbody_ring,
    "scan_hist": _run_scan_hist,
    "allreduce": _run_allreduce,
    "allreduce2d": _run_allreduce2d,
}


# ------------------------------------------------------------------ #
# inner mode: one mesh size, jax-bound                               #
# ------------------------------------------------------------------ #

def inner(n: int, reps: int, quick: bool) -> int:
    """Time every program on an n-device mesh; one JSON line per
    point on stdout (the parent collects them for the artifact) plus
    a ``weak_scaling_point`` journal event each. rc 1 when any
    program failed — the sweep continues past failures so one broken
    program cannot hide the rest."""
    import numpy as np

    from tpukernels.parallel.mesh import maybe_distributed_init

    # Join the multi-host job BEFORE the inventory probe: probe=True
    # runs jax.devices(), initializing the backend, and
    # jax.distributed.initialize must precede any backend init — in
    # --real mode (coordinator vars kept, the only gating-eligible
    # mode) probing first would crash every pod host. Idempotent: the
    # program builders' make_mesh(n) funnels through the same call.
    maybe_distributed_init()
    # probe=True: this process exists to run device code on the mesh
    inv = scaling.emit_inventory("weak_scaling", probe=True)
    print("WEAK-INVENTORY: " + json.dumps(inv), flush=True)
    rng = np.random.default_rng(0)
    failed = 0
    for name, build in PROGRAMS.items():
        point = {"program": name, "n_devices": n, "ok": True}
        try:
            built = build(n, quick, rng)
            if built is None:
                # the program has no build at this mesh size (e.g. no
                # 2-D factorization under 4 devices): skipped, not
                # failed — no point, so the verdict layer never sees
                # a phantom mesh size
                print(
                    f"weak_scaling n={n} {name:<12} skipped "
                    "(no mesh shape at this size)",
                    flush=True,
                )
                continue
            call, per_chip = built[0], built[1]
            if len(built) > 2:
                point.update(built[2])
            point["per_chip_work"] = per_chip
            call()  # warm: compile + first execution, untimed
            best = float("inf")
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                call()
                best = min(best, time.perf_counter() - t0)
            point["wall_s"] = round(best, 6)
        except Exception as e:  # noqa: BLE001 — continue the sweep
            point["ok"] = False
            point["error"] = repr(e)
            failed += 1
        obs_metrics.inc("scaling.weak_points")
        journal.emit("weak_scaling_point", fake=inv.get("fake", True),
                     **point)
        print("WEAK-POINT: " + json.dumps(point), flush=True)
        wall = point.get("wall_s")
        print(
            f"weak_scaling n={n} {name:<12} "
            + (f"wall={wall:9.4f}s" if wall is not None
               else f"FAILED ({point.get('error')})")
            + f" work/chip={point.get('per_chip_work', '?')}",
            flush=True,
        )
    return 1 if failed else 0


# ------------------------------------------------------------------ #
# parent mode: per-size subprocess isolation                         #
# ------------------------------------------------------------------ #

def _scrubbed_cpu_env(n: int) -> dict:
    """The dryrun_multichip scrub: CPU backend, n fake devices, no
    axon pool var, no coordinator join."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for var in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    env["XLA_FLAGS"] = " ".join(flags)
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = _REPO + (os.pathsep + prev if prev else "")
    return env


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    sizes, reps, quick, real = [1, 2, 4, 8], 2, False, False
    out_dir = inner_n = None
    it = iter(argv)
    try:
        for a in it:
            if a == "--sizes":
                sizes = [int(s) for s in next(it).split()]
            elif a == "--reps":
                reps = int(next(it))
            elif a == "--quick":
                quick = True
            elif a == "--real":
                real = True
            elif a == "--out":
                out_dir = next(it)
            elif a == "--inner":
                inner_n = int(next(it))
            else:
                print(__doc__, file=sys.stderr)
                print(f"weak_scaling: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
    except (StopIteration, ValueError):
        print(f"weak_scaling: {a} needs a value", file=sys.stderr)
        return 2
    if inner_n is not None:
        return inner(inner_n, reps, quick)
    if not sizes or any(n < 1 for n in sizes):
        print(f"weak_scaling: bad --sizes {sizes}", file=sys.stderr)
        return 2

    # CLI journal default (the bench/revalidate/loadgen contract); the
    # per-size children inherit the same file through the environment
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    scaling.emit_inventory("weak_scaling:parent")

    points, inv, rc = [], None, 0
    for n in sizes:
        print(f"== mesh n={n}", flush=True)
        cmd = [sys.executable, os.path.abspath(__file__),
               "--inner", str(n), "--reps", str(reps)]
        if quick:
            cmd.append("--quick")
        env = dict(os.environ) if real else _scrubbed_cpu_env(n)
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for line in proc.stdout:
            if line.startswith("WEAK-POINT: "):
                try:
                    points.append(json.loads(line[len("WEAK-POINT: "):]))
                except ValueError:
                    pass
                continue
            if line.startswith("WEAK-INVENTORY: "):
                try:
                    inv = json.loads(line[len("WEAK-INVENTORY: "):])
                except ValueError:
                    pass
                continue
            sys.stdout.write(line)
            sys.stdout.flush()
        proc.wait()
        if proc.returncode != 0:
            rc = 1
    if inv is None:
        # no child printed its probed inventory (children died before
        # the probe): fall back to the env-derived stamp, FORCED fake
        # — gating-eligible artifacts need a probed (source="jax")
        # inventory, and a declared platform (JAX_PLATFORMS=tpu,cpu)
        # must not turn a childless sweep into chip evidence. Say so
        # where the operator will look.
        inv = dict(scaling.inventory(), fake=True,
                   fake_basis="unprobed-fallback")
        # journal the SAME dict the artifact embeds (the
        # emit_inventory contract) — without this the run's only
        # device_inventory event would be the parent's plain env
        # stamp, contradicting the artifact on a declared-TPU host
        journal.emit("device_inventory", site="weak_scaling:fallback",
                     **inv)
        print(
            "weak_scaling: WARNING no child inventory captured - "
            "artifact stamped from the env (unprobed-fallback) and "
            "NOT gating-eligible",
            file=sys.stderr,
        )
    artifact = scaling.write_weak_artifact(points, inv, out_dir)
    ok = sum(1 for p in points if p.get("ok"))
    basis = inv.get("fake_basis")
    note = (
        " (no child inventory - stamped fake, never gates)"
        if basis == "unprobed-fallback" else
        " (platform unknown - stamped fake, never gates)"
        if basis == "unknown-platform" else
        " (FAKE devices - logic proof, never gates)"
    )
    print(
        f"weak_scaling: {ok}/{len(points)} point(s) ok across meshes "
        f"{sizes}"
        + (note if inv.get("fake", True) else "")
        + f" -> {os.path.relpath(artifact)}"
    )
    return rc


if __name__ == "__main__":
    sys.exit(main())
