"""Per-request timelines from the serve journals: waterfalls + the
phase-attribution table (docs/OBSERVABILITY.md §request tracing).

Usage:
    python tools/trace_report.py [journal.jsonl ...]
    python tools/trace_report.py --request lg7-00042
    python tools/trace_report.py --slowest 5

With no journal arguments, reads the newest docs/logs/health_*.jsonl
(the health_report convention). The assembler
(``tpukernels/obs/reqtrace.py``) joins every process's evidence —
the client's ``serve_client_request`` walls, the router's
``serve_route``/``serve_spill`` placements, the workers'
``serve_request`` records and request-tagged spans — on the
client-minted ``request_id``, so one report answers "where did THIS
request's time go" across the whole fleet:

- **aggregate table** — phase-attribution percentiles per (kernel,
  bucket, tenant): queue wait, lock wait, pad, dispatch, compile,
  integrity, unaccounted.
- **waterfalls** — per-request lanes (client / router / worker pids)
  with per-process offsets anchored to each process's own
  ``serve_start`` (clock-skew rule), spill hops, hedge hops (home and
  sibling attempts joined on the shared request_id, the loser's
  cancel as an explicit line), deadline expiries rendered as GAP
  lines that say where the budget went, explicit GAP lines for
  abandoned workers, and the request's critical path.

Degrades loudly: ``serve_request`` events without a request_id (an
old server, tracing off) are counted and announced, never silently
dropped — and never crash the report.

Exit codes: 0 — report rendered (even when nothing assembled: the
loud "no timelines" note IS the report); 1 — no journal found;
2 — usage error.
"""

from __future__ import annotations

import glob
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels.obs import reqtrace  # noqa: E402
from tpukernels.resilience import journal as _journal  # noqa: E402

_BAR_COLS = 36


def _ms(v, width=9):
    if v is None:
        return " " * (width - 1) + "-"
    return f"{v * 1e3:>{width}.3f}"


def _bar(rel0, rel1, scale_s):
    """A fixed-width lane bar for one segment at its per-process
    offset; degenerate scales render position-less."""
    if not scale_s or scale_s <= 0:
        return "." * _BAR_COLS
    a = min(_BAR_COLS, int(rel0 / scale_s * _BAR_COLS))
    b = min(_BAR_COLS, max(a + 1, int(rel1 / scale_s * _BAR_COLS)))
    return " " * a + "=" * (b - a) + " " * (_BAR_COLS - b)


def waterfall(t: dict) -> list:
    """Render one assembled timeline as text lines."""
    rid = t["request_id"]
    cw = t["client_wall_s"]
    out = [
        f"request {rid}  kernel={t['kernel'] or '?'} "
        f"bucket={t['bucket'] or '-'} tenant={t['tenant'] or '-'}"
        + (f" worker={t['worker_id']}" if t["worker_id"] is not None
           else "")
        + (f"  client wall {cw * 1e3:.3f}ms" if cw else "")
        + (f" (coverage {t['coverage']:.0%})"
           if t["coverage"] is not None else "")
    ]
    client = t["client"]
    if client is not None:
        out.append(
            f"  [client pid {client.get('pid')}] "
            + ("warm " if client.get("warm") else "")
            + ("ok" if client.get("ok")
               else f"DROPPED ({client.get('error')})")
            + (f", {t['rejections']} rejection(s)"
               if t["rejections"] else "")
            + (f", {t['throttles']} tenant throttle(s)"
               if t["throttles"] else "")
        )
    for ev in t["route"]:
        out.append(
            f"  [router pid {ev.get('pid')}] -> worker "
            f"{ev.get('worker')}"
            + (f" (spilled from {ev.get('spilled_from')})"
               if ev.get("spilled_from") is not None else "")
        )
    for ev in t["spills"]:
        out.append(
            f"  [router pid {ev.get('pid')}] SPILL worker "
            f"{ev.get('from_worker')} -> {ev.get('to_worker')} "
            f"({ev.get('reason')})"
        )
    for ev in t["hedges"]:
        out.append(
            f"  [router pid {ev.get('pid')}] HEDGE worker "
            f"{ev.get('from_worker')} -> {ev.get('to_worker')} "
            f"(elapsed > {ev.get('threshold_s')}s, "
            "first response wins)"
        )
    # one lane per process, offsets anchored to that process's own
    # serve_start; scale = the widest lane so bars stay comparable
    # within a lane even when clocks are skewed across lanes
    by_pid: dict = {}
    for s in t["segments"]:
        by_pid.setdefault(s["pid"], []).append(s)
    for pid, segs in by_pid.items():
        scale = max(s["rel1"] for s in segs) or None
        for s in segs:
            out.append(
                f"  [worker pid {pid}] "
                f"{_bar(s['rel0'], s['rel1'], scale)} "
                f"{s['name']:<32} {_ms(s['wall_s'])}ms "
                f"@+{s['rel0'] * 1e3:.3f}ms"
                + ("" if s.get("ok", True) else " FAILED")
            )
    for g in t["gaps"]:
        out.append(f"  GAP ({g['kind']}): {g['detail']}")
    if t.get("critical_path"):
        out.append(
            "  critical path: "
            + " > ".join(f"{ph} {v * 1e3:.3f}ms"
                         for ph, v in t["critical_path"])
        )
    return out


def aggregate_table(agg: dict) -> list:
    phases = [p for p in reqtrace.PHASES]
    hdr = (f"{'kernel|bucket|tenant':<40} {'n':>4} "
           f"{'client_p99':>10} "
           + " ".join(f"{p[:9]:>9}" for p in phases))
    out = ["phase attribution (p50 ms in phase) per "
           "(kernel, bucket, tenant); client p99 ms:",
           hdr, "-" * len(hdr)]
    for key, g in agg.items():
        cells = []
        for p in phases:
            ph = g["phases"].get(p)
            cells.append(_ms(ph["p50_s"]) if ph else
                         " " * 8 + "-")
        out.append(
            f"{key:<40} {g['n']:>4} {_ms(g['client_p99_s'], 10)} "
            + " ".join(cells)
            + (f"  {g['gaps']} gap(s)" if g["gaps"] else "")
        )
    return out


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    paths: list = []
    want_request = None
    slowest = 3
    it = iter(argv)
    try:
        for a in it:
            if a == "--request":
                want_request = next(it)
            elif a == "--slowest":
                slowest = int(next(it))
            elif a.startswith("--"):
                print(__doc__, file=sys.stderr)
                print(f"trace_report: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
            else:
                paths.append(a)
    except (StopIteration, ValueError):
        print(f"trace_report: {a} needs a value", file=sys.stderr)
        return 2
    if not paths:
        found = sorted(
            glob.glob(os.path.join(_REPO, "docs", "logs",
                                   "health_*.jsonl")),
            key=os.path.basename,
        )
        if not found:
            print("trace_report: no docs/logs/health_*.jsonl found",
                  file=sys.stderr)
            return 1
        paths = [found[-1]]

    events, bad = _journal.load_events(paths)
    tls = reqtrace.assemble(events)
    untraced = reqtrace.untraced_serve_requests(events)
    print("trace_report: "
          + ", ".join(os.path.relpath(p) for p in paths))
    traced = [t for t in tls.values() if t["segments"]]
    gaps = sum(len(t["gaps"]) for t in tls.values())
    hedged = sum(1 for t in tls.values() if t["hedged"])
    expired = sum(1 for t in tls.values() if t["expiries"])
    print(
        f"{len(tls)} request timeline(s) assembled, {len(traced)} "
        f"with span evidence, {gaps} gap(s)"
        + (f", {hedged} hedged" if hedged else "")
        + (f", {expired} expired/refused on deadline"
           if expired else "")
        + (f", {bad} unparseable line(s)" if bad else "")
    )
    if untraced:
        # degrade LOUDLY: these served requests exist but cannot join
        print(
            f"NOTE: {untraced} serve_request event(s) carry no "
            "request_id (old server or pre-tracing client) - served "
            "but not assembled into timelines"
        )
    if not tls:
        print("no request timelines in this journal - run a traced "
              "loadgen --serve burst (TPK_TRACE=1) to bank some")
        return 0

    print()
    for line in aggregate_table(reqtrace.aggregate(tls)):
        print(line)

    if want_request is not None:
        t = tls.get(want_request)
        if t is None:
            print(f"\ntrace_report: request {want_request!r} not in "
                  "this journal; known ids e.g. "
                  f"{sorted(tls)[:5]}", file=sys.stderr)
            return 2
        chosen = [t]
    else:
        chosen = sorted(
            (t for t in tls.values()
             if t["client_wall_s"] is not None),
            key=lambda t: -(t["client_wall_s"] or 0.0),
        )[:max(0, slowest)]
    for t in chosen:
        print()
        for line in waterfall(t):
            print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
