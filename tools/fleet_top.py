"""Live fleet dashboard over the read-only ``stats`` op
(docs/SERVING.md §stats op; surfaced as ``serve_ctl top [--once]``).

One ``stats`` round trip per frame against the fleet's front socket
(or a lone daemon's socket) renders one row per worker: request rate,
streaming-histogram p50/p99 latency, queue depth, in-flight count,
spills/throttles at the router, bytes copied, and the flusher's
``last_snapshot_age_s`` (docs/OBSERVABILITY.md §live telemetry — a
growing age means the worker's metrics flusher died). Everything on
screen comes from live processes; nothing is read from the journal,
so `top` works against a fleet whose journaling is off.

- ``--once`` prints one frame and exits (0 when the stats plane
  answered, 1 when nothing did) — the scriptable face, and what the
  live-fleet acceptance proof drives.
- Without it, the terminal refreshes every ``--interval`` seconds
  (default 2) until Ctrl-C; rates are computed from the DELTA between
  frames, so an idle fleet shows 0.0 rps no matter how busy its past.

Latency columns merge every ``serve.wall_s.<kernel>`` histogram a
worker carries — same log-bucket geometry fleet-wide, so the merged
p50/p99 go through the one shared ``metrics.percentiles`` arithmetic
(clamped to the exact observed max).

Read-only by design: this tool sends only ``stats`` (and falls back
to nothing else), takes no locks anywhere, and emits no journal
events — watching the fleet must never change it.
"""

from __future__ import annotations

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels import _cachedir  # noqa: E402
from tpukernels.obs import metrics as obs_metrics  # noqa: E402
from tpukernels.serve import client as serve_client  # noqa: E402
from tpukernels.serve import fleet as serve_fleet  # noqa: E402
from tpukernels.serve import health as serve_health  # noqa: E402
from tpukernels.serve import protocol as serve_protocol  # noqa: E402


def _target_socket(socket_path=None) -> str:
    """The front socket when a router holds its pidfile (fleet view),
    else the lone daemon's socket — the `serve_ctl status` resolution
    order, so `top` always watches what `status` reports on."""
    if socket_path:
        return socket_path
    held, _pid = serve_health.pidfile_state(
        serve_fleet.router_pidfile_path()
    )
    if held:
        cfg = serve_fleet.load_config() or {}
        return cfg.get("front") or serve_fleet.front_socket_path()
    return _cachedir.serve_socket_path()


def _fetch(sock: str):
    try:
        with serve_client.ServeClient(sock, timeout_s=5) as c:
            reply = c.stats()
    except (OSError, serve_protocol.ProtocolError):
        return None
    if not isinstance(reply, dict) or not reply.get("ok"):
        return None
    return reply


def _wall_latency_ms(metrics_snap) -> tuple:
    """(count, p50_ms, p99_ms) merged over every serve.wall_s.<kernel>
    histogram in one worker's metrics snapshot, or (0, None, None)."""
    count = 0
    max_v = 0.0
    buckets: dict = {}
    for name, row in (
        (metrics_snap or {}).get("histograms") or {}
    ).items():
        if not name.startswith("serve.wall_s."):
            continue
        if not isinstance(row, dict) or not row.get("count"):
            continue
        count += int(row["count"])
        max_v = max(max_v, float(row.get("max") or 0.0))
        for b, c in (row.get("buckets") or {}).items():
            buckets[b] = buckets.get(b, 0) + int(c)
    if not count:
        return 0, None, None
    p50, p99 = obs_metrics.percentiles(
        count, max_v, buckets, qs=(0.5, 0.99)
    )
    return count, p50 * 1e3, p99 * 1e3


def _rows(reply) -> list:
    """Normalize a stats reply into per-worker row dicts. A router
    reply yields one row per worker (index-aligned ``worker_stats``);
    a lone daemon yields one row for itself."""
    if reply.get("role") == "router":
        rows = []
        meta = reply.get("workers") or []
        for i, ws in enumerate(reply.get("worker_stats") or []):
            m = meta[i] if i < len(meta) else {}
            state = ("DOWN" if ws is None
                     else "draining" if m.get("draining")
                     else "quarantined" if m.get("quarantined")
                     else m.get("state") or "up")
            rows.append({"name": f"worker{i}", "state": state,
                         "stats": ws, "routed": m.get("routed")})
        return rows
    return [{"name": f"daemon:{reply.get('pid')}", "state": "up",
             "stats": reply, "routed": None}]


def _fmt(v, spec="{:.1f}", none="-") -> str:
    return none if v is None else spec.format(v)


def render(reply, prev=None, dt=None, out=sys.stdout) -> dict:
    """Print one frame; returns {worker_name: served} for the next
    frame's rate deltas. ``prev``/``dt`` make rps a frame delta; with
    neither (the --once path) it is lifetime served / uptime."""
    role = reply.get("role") or "daemon"
    if role == "router":
        fleet = reply.get("fleet") or {}
        head = (f"fleet: routed={reply.get('routed')} "
                f"spilled={reply.get('spilled')} "
                f"throttled={reply.get('throttled')} "
                f"rejected={reply.get('rejected')} "
                f"level={reply.get('level')} "
                f"workers={fleet.get('answering')}"
                f"/{reply.get('n_workers')} "
                f"uptime={reply.get('uptime_s')}s")
    else:
        head = (f"daemon: pid {reply.get('pid')} "
                f"uptime={reply.get('uptime_s')}s "
                f"device={reply.get('device_kind')}")
    print(head, file=out)
    print(f"{'WORKER':<12} {'STATE':<11} {'RPS':>7} {'P50MS':>8} "
          f"{'P99MS':>8} {'DEPTH':>7} {'INFL':>5} {'SERVED':>8} "
          f"{'COPIED':>9} {'SNAP_AGE':>8}", file=out)
    served_now: dict = {}
    for row in _rows(reply):
        ws = row["stats"]
        if ws is None:
            print(f"{row['name']:<12} {row['state']:<11} "
                  f"{'-':>7} {'-':>8} {'-':>8} {'-':>7} {'-':>5} "
                  f"{'-':>8} {'-':>9} {'-':>8}", file=out)
            continue
        served = ws.get("served") or 0
        served_now[row["name"]] = served
        if prev is not None and dt:
            rps = max(0.0, served - prev.get(row["name"], served)) / dt
        else:
            up = ws.get("uptime_s") or 0
            rps = (served / up) if up else 0.0
        _n, p50, p99 = _wall_latency_ms(ws.get("metrics"))
        age = ws.get("last_snapshot_age_s")
        depth = f"{ws.get('depth')}/{ws.get('queue_max')}"
        print(f"{row['name']:<12} {row['state']:<11} "
              f"{rps:>7.1f} {_fmt(p50, '{:.2f}'):>8} "
              f"{_fmt(p99, '{:.2f}'):>8} {depth:>7} "
              f"{ws.get('inflight'):>5} {served:>8} "
              f"{ws.get('bytes_copied'):>8}B "
              f"{_fmt(age, '{:.1f}s'):>8}", file=out)
    return served_now


def run(once=False, interval_s=2.0, socket_path=None) -> int:
    sock = _target_socket(socket_path)
    reply = _fetch(sock)
    if reply is None:
        print(f"fleet_top: no stats answer on {sock} - is a "
              "daemon/fleet running (and new enough for the stats "
              "op)?", file=sys.stderr)
        return 1
    if once:
        render(reply)
        return 0
    prev = None
    t_prev = None
    try:
        while True:
            if reply is not None:
                # home + clear: redraw in place, no scrollback spam
                sys.stdout.write("\x1b[H\x1b[2J")
                now = time.monotonic()
                dt = (now - t_prev) if t_prev is not None else None
                prev = render(reply, prev=prev, dt=dt)
                t_prev = now
                sys.stdout.flush()
            else:
                print(f"fleet_top: no stats answer on {sock} - "
                      "retrying", file=sys.stderr)
            time.sleep(interval_s)
            reply = _fetch(sock)
    except KeyboardInterrupt:
        return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    once = False
    interval_s = 2.0
    socket_path = None
    it = iter(argv)
    try:
        for a in it:
            if a == "--once":
                once = True
            elif a == "--interval":
                interval_s = float(next(it))
            elif a == "--socket":
                socket_path = next(it)
            else:
                print(__doc__, file=sys.stderr)
                print(f"fleet_top: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
    except (StopIteration, ValueError):
        print(f"fleet_top: {a} needs a value", file=sys.stderr)
        return 2
    return run(once=once, interval_s=interval_s,
               socket_path=socket_path)


if __name__ == "__main__":
    sys.exit(main())
