"""Operator control of the kernel-serving daemon and fleet
(docs/SERVING.md).

Usage:
    python tools/serve_ctl.py start [--wait S] [--socket PATH]
    python tools/serve_ctl.py stop [--wait S]
    python tools/serve_ctl.py status
    python tools/serve_ctl.py start-fleet N [--wait S]
    python tools/serve_ctl.py stop-fleet [--wait S]
    python tools/serve_ctl.py drain I [--wait S]
    python tools/serve_ctl.py undrain I [--wait S]
    python tools/serve_ctl.py health [--wait S]
    python tools/serve_ctl.py guardian [--wait S]
    python tools/serve_ctl.py fsck
    python tools/serve_ctl.py top [--once] [--interval S] [--socket PATH]

Single daemon: ``start`` spawns ``python -m tpukernels.serve``
detached and waits for a protocol ping; ``stop`` SIGTERMs the pid
the flocked pidfile records and waits for the flock to release;
``status`` tests the flock (a dead daemon's stale pid never reads as
running) and prints the ping payload — queue depth, in-flight count
and per-bucket memo ownership, not bare liveness.

Fleet (docs/SERVING.md §fleet): ``start-fleet N`` spawns N worker
daemons (each on its own socket/pidfile/log under the fleet dir,
tagged ``TPK_SERVE_WORKER_ID``) plus the front-end router on
``front.sock``, records the layout in ``fleet.json``, and waits for
every member to answer a ping — point clients (``TPK_SERVE_SOCKET``,
``loadgen --serve``) at the front socket. ``drain I`` tells the
router to route worker I's buckets to their ring siblings, waits for
its in-flight forwards to empty, then stops the worker — zero
accepted requests drop (requests caught mid-stop fail over through
the router's transport retry). ``undrain I`` restarts the worker if
needed and restores it to the ring — together the supervisor-managed
rolling restart. ``stop-fleet`` stops router then workers.
``status`` detects a fleet (live router pidfile) and prints the
router's routing totals plus one line per worker — including each
worker's liveness state, restart count and quarantine flag from the
router's self-healing manager (docs/SERVING.md §self-healing).

``health`` is the health manager's standalone face: it polls the
fleet (router rows when the router answers, direct pidfile-flock +
ping probes per worker otherwise) until every ring member is live or
``--wait`` expires — the converged-fleet gate chaos probes and the
supervisor's ``fleet_probe`` kill-and-recover phase wait on.

``guardian`` (docs/SERVING.md §guardian) spawns the router's
supervisor detached — the process that closes the fleet's LAST
single point of failure by respawning a crashed router on its
original front socket (``tpukernels/serve/guardian.py``) — and waits
for it to hold its pidfile flock. ``stop-fleet`` stops the guardian
FIRST: stopped any later it would read the intentional router stop
as a crash and respawn it mid-teardown.

``top`` (docs/SERVING.md §stats op) is the live fleet dashboard —
one read-only ``stats`` round trip per frame against the front
socket (or a lone daemon) rendering rps, streaming-histogram
p50/p99, queue depths, spills/throttles, bytes copied and the
metrics flusher's ``last_snapshot_age_s`` per worker. ``--once``
prints a single frame and exits; without it the screen refreshes
every ``--interval`` seconds until Ctrl-C. Delegates to
``tools/fleet_top.py``.

``fsck`` (docs/RESILIENCE.md §atomic state) reaps what crashes leave
behind: pidfiles whose flock nothing holds, ``tpkserve-*`` shm
segments whose creator pid is dead, and a fleet.json that no longer
parses (torn by a mid-write crash on a pre-atomic writer). Counts
are journaled as ``fleet_fsck`` and printed; always exits 0 — it is
a janitor, not a health check (``health`` is the health check).

Exit codes: 0 — done (``status``: up; ``health``: all workers
live); 1 — failed (``status``: down; ``health``: a worker is
dead/quarantined past the wait); 2 — usage error; 3 —
``start``/``start-fleet``/``guardian`` refused because a live
daemon/router/guardian already holds the pidfile.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels import _cachedir  # noqa: E402
from tpukernels.serve import client as serve_client  # noqa: E402
from tpukernels.serve import fleet as serve_fleet  # noqa: E402
from tpukernels.serve import health as serve_health  # noqa: E402
from tpukernels.serve import protocol as serve_protocol  # noqa: E402


def _pidfile_state(path=None):
    """(held, pid_or_None) — the one flock-test helper, shared with
    the fleet health manager (serve/health.py owns the copy: liveness
    is the flock, the recorded pid is the diagnosis)."""
    return serve_health.pidfile_state(
        path or _cachedir.serve_pidfile_path()
    )


def _ping(socket_path):
    # ProtocolError too: a daemon mid-shutdown hangs up before
    # answering, which must read as "not (yet) up", not a traceback
    try:
        with serve_client.ServeClient(socket_path, timeout_s=5) as cli:
            return cli.ping()
    except (OSError, serve_protocol.ProtocolError):
        return None


def _control(socket_path, op, worker):
    """One router control round trip ({"op": drain|undrain,
    "worker": i}); returns the reply header or None on transport
    trouble."""
    import socket as socket_mod

    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.settimeout(10)
    try:
        s.connect(socket_path)
        serve_protocol.send_frame(
            s, {"v": serve_protocol.VERSION, "op": op, "worker": worker}
        )
        frame = serve_protocol.recv_frame(s)
    except (OSError, serve_protocol.ProtocolError):
        return None
    finally:
        try:
            s.close()
        except OSError:
            pass
    return frame[0] if frame else None


def _stats_line(stats) -> str:
    buckets = stats.get("buckets") or []
    # copied/window: the zero-copy + continuous-batching evidence a
    # fleet operator reads here instead of the journal — copied stays
    # 0 while the shm warm path is engaged, window collapses to 0ms
    # when the daemon idles (docs/SERVING.md)
    # snap_age: the metrics flusher's last_snapshot_age_s
    # (docs/OBSERVABILITY.md §live telemetry) — "off" when
    # TPK_METRICS_FLUSH_S is unset; a value growing past the flush
    # interval means the flusher thread died and this worker's
    # journal telemetry is silently going stale
    age = stats.get("last_snapshot_age_s")
    return (f"served={stats.get('served')} "
            f"rejected={stats.get('rejected')} "
            f"requeued={stats.get('requeued')} "
            f"depth={stats.get('depth')}/{stats.get('queue_max')} "
            f"inflight={stats.get('inflight')} "
            f"copied={stats.get('bytes_copied')}B "
            f"window={stats.get('batch_window_ms')}ms "
            f"lanes={','.join(stats.get('lanes') or ['inline'])} "
            f"snap_age={'off' if age is None else f'{age:.1f}s'} "
            f"buckets={len(buckets)}"
            + (f" [{', '.join(buckets)}]" if buckets else ""))


def start(wait_s: float, socket_path) -> int:
    socket_path = socket_path or _cachedir.serve_socket_path()
    held, pid = _pidfile_state()
    if held:
        print(f"serve_ctl: daemon already running (pid {pid}) - "
              "leave it, or stop it first")
        return 3
    d = _cachedir.serve_dir()
    os.makedirs(d, exist_ok=True)
    log_path = os.path.join(d, "serve_daemon.log")
    log = open(log_path, "a")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpukernels.serve",
         "--socket", socket_path],
        cwd=_REPO, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=log,
    )
    log.close()
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            print(f"serve_ctl: daemon exited rc={proc.returncode} "
                  f"before answering - see {log_path}",
                  file=sys.stderr)
            return 1
        stats = _ping(socket_path)
        if stats:
            print(f"serve_ctl: daemon up (pid {stats.get('pid')}, "
                  f"{stats.get('workers')} worker(s)) on {socket_path}")
            return 0
        time.sleep(0.2)
    print(f"serve_ctl: daemon did not answer within {wait_s}s - "
          f"killing it; see {log_path}", file=sys.stderr)
    proc.terminate()
    return 1


def _stop_pidfile(pidfile, what, wait_s: float) -> int:
    held, pid = _pidfile_state(pidfile)
    if not held:
        print(f"serve_ctl: no {what} running"
              + (f" (stale pid {pid} in pidfile)" if pid else ""))
        return 0
    if pid is None:
        print(f"serve_ctl: {what} pidfile flocked but records no pid "
              "- inspect by hand (fuser on the socket)",
              file=sys.stderr)
        return 1
    try:
        os.kill(pid, signal.SIGTERM)
    except OSError as e:
        print(f"serve_ctl: cannot signal pid {pid}: {e}",
              file=sys.stderr)
        return 1
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        held, _pid = _pidfile_state(pidfile)
        if not held:
            print(f"serve_ctl: {what} (pid {pid}) stopped")
            return 0
        time.sleep(0.2)
    print(f"serve_ctl: {what} (pid {pid}) still holds the pidfile "
          f"after {wait_s}s - escalate by hand if it is truly wedged",
          file=sys.stderr)
    return 1


def stop(wait_s: float) -> int:
    return _stop_pidfile(_cachedir.serve_pidfile_path(), "daemon",
                         wait_s)


# ------------------------------------------------------------------ #
# fleet verbs                                                        #
# ------------------------------------------------------------------ #

def start_fleet(n: int, wait_s: float) -> int:
    held, pid = _pidfile_state(serve_fleet.router_pidfile_path())
    if held:
        print(f"serve_ctl: fleet router already running (pid {pid}) "
              "- stop-fleet first")
        return 3
    front = serve_fleet.front_socket_path()
    procs, socks = [], []
    try:
        for i in range(n):
            proc, sock = serve_fleet.spawn_worker(i, _REPO)
            procs.append((f"worker{i}", proc))
            socks.append(sock)
        router = serve_fleet.spawn_router(front, socks, _REPO)
        procs.append(("router", router))
        serve_fleet.write_config(front, socks)
    except OSError as e:
        # a mid-loop spawn failure (full disk, unwritable fleet dir)
        # must not leak the members already running detached
        print(f"serve_ctl: cannot spawn the fleet: {e}",
              file=sys.stderr)
        _abort_fleet(procs)
        return 1
    deadline = time.monotonic() + wait_s
    pending = [("router", front)] + [
        (f"worker{i}", s) for i, s in enumerate(socks)
    ]
    while pending and time.monotonic() < deadline:
        for name, proc in procs:
            if proc.poll() is not None:
                print(f"serve_ctl: {name} exited "
                      f"rc={proc.returncode} before answering - see "
                      f"its log under {serve_fleet.fleet_dir()}",
                      file=sys.stderr)
                _abort_fleet(procs)
                return 1
        pending = [(name, s) for name, s in pending
                   if _ping(s) is None]
        if pending:
            time.sleep(0.2)
    if pending:
        print(f"serve_ctl: {', '.join(n for n, _s in pending)} did "
              f"not answer within {wait_s}s - stopping the fleet",
              file=sys.stderr)
        _abort_fleet(procs)
        return 1
    print(f"serve_ctl: fleet up - router on {front}, "
          f"{n} worker(s)")
    return 0


def _reap(procs):
    for _name, proc in procs:
        if proc.poll() is None:
            proc.terminate()
    for _name, proc in procs:
        try:
            proc.wait(10)
        except subprocess.TimeoutExpired:
            proc.kill()


def _abort_fleet(procs):
    """Failed start: kill what came up AND drop fleet.json — a stale
    config of record would make a later drain/undrain respawn orphan
    workers for a fleet whose router never answered."""
    _reap(procs)
    try:
        os.unlink(serve_fleet.config_path())
    except OSError:
        pass


def stop_fleet(wait_s: float) -> int:
    cfg = serve_fleet.load_config()
    # the guardian FIRST (docs/SERVING.md §guardian): stopped any
    # later it would read the intentional router stop as a crash and
    # respawn the router mid-teardown
    rc = 0
    gpidfile = serve_fleet.guardian_pidfile_path()
    if os.path.exists(gpidfile):
        rc = _stop_pidfile(gpidfile, "guardian", wait_s)
    rrc = _stop_pidfile(serve_fleet.router_pidfile_path(), "router",
                        wait_s)
    rc = rc or rrc
    workers = (cfg or {}).get("workers") or []
    for i, _sock in enumerate(workers):
        wrc = _stop_pidfile(
            os.path.join(serve_fleet.worker_dir(i), "serve.pid"),
            f"worker{i}", wait_s,
        )
        rc = rc or wrc
    if rc == 0:
        # the config of record outlives a FAILED stop on purpose: a
        # wedged member that survived --wait must stay addressable by
        # a retry ('stop-fleet' / 'drain I'), not become an orphan
        # the ctl can no longer name
        try:
            os.unlink(serve_fleet.config_path())
        except OSError:
            pass
    return rc


def drain(idx: int, wait_s: float) -> int:
    cfg = serve_fleet.load_config()
    if not cfg:
        print("serve_ctl: no fleet.json - is a fleet running?",
              file=sys.stderr)
        return 1
    front = cfg["front"]
    reply = _control(front, "drain", idx)
    if not reply or not reply.get("ok"):
        print(f"serve_ctl: drain refused: "
              f"{(reply or {}).get('error') or 'router unreachable'}",
              file=sys.stderr)
        return 1
    # wait for the router's in-flight forwards to that worker to
    # empty, then stop it; a forward still stuck past the wait (a
    # wedge) is rescued by the router's transport failover when the
    # worker dies — zero accepted requests drop either way
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        stats = _ping(front)
        rows = (stats or {}).get("workers") or []
        if idx < len(rows) and rows[idx].get("inflight") == 0:
            break
        time.sleep(0.2)
    rc = _stop_pidfile(
        os.path.join(serve_fleet.worker_dir(idx), "serve.pid"),
        f"worker{idx}", wait_s,
    )
    print(f"serve_ctl: worker {idx} drained - its buckets now route "
          "to their ring siblings")
    return rc


def undrain(idx: int, wait_s: float) -> int:
    cfg = serve_fleet.load_config()
    if not cfg:
        print("serve_ctl: no fleet.json - is a fleet running?",
              file=sys.stderr)
        return 1
    front = cfg["front"]
    if not 0 <= idx < len(cfg["workers"]):
        # validate BEFORE spawning: a daemon for an index outside the
        # fleet would be an orphan stop-fleet can never reach
        print(f"serve_ctl: worker index {idx} out of range "
              f"(fleet has {len(cfg['workers'])})", file=sys.stderr)
        return 2
    pidfile = os.path.join(serve_fleet.worker_dir(idx), "serve.pid")
    held, _pid = _pidfile_state(pidfile)
    if not held:
        proc, sock = serve_fleet.spawn_worker(idx, _REPO)
        deadline = time.monotonic() + wait_s
        while _ping(sock) is None:
            if proc.poll() is not None:
                print(f"serve_ctl: worker{idx} exited "
                      f"rc={proc.returncode} before answering",
                      file=sys.stderr)
                return 1
            if time.monotonic() > deadline:
                print(f"serve_ctl: worker{idx} did not answer within "
                      f"{wait_s}s", file=sys.stderr)
                proc.terminate()
                return 1
            time.sleep(0.2)
    reply = _control(front, "undrain", idx)
    if not reply or not reply.get("ok"):
        print(f"serve_ctl: undrain refused: "
              f"{(reply or {}).get('error') or 'router unreachable'}",
              file=sys.stderr)
        return 1
    print(f"serve_ctl: worker {idx} restored to the ring")
    return 0


def status(socket_path=None) -> int:
    held, pid = _pidfile_state(serve_fleet.router_pidfile_path())
    if held:
        return _fleet_status()
    held, pid = _pidfile_state()
    if not held:
        print("serve_ctl: daemon DOWN"
              + (f" (stale pid {pid} in pidfile)" if pid else ""))
        return 1
    stats = _ping(socket_path or _cachedir.serve_socket_path())
    if stats is None:
        print(f"serve_ctl: pid {pid} holds the pidfile but the "
              "socket does not answer - starting up, or wedged")
        return 1
    print(f"serve_ctl: daemon UP (pid {stats.get('pid')}) - "
          + _stats_line(stats)
          + f" device={stats.get('device_kind')}"
          f" uptime={stats.get('uptime_s')}s")
    return 0


def _fleet_status() -> int:
    cfg = serve_fleet.load_config() or {}
    front = cfg.get("front") or serve_fleet.front_socket_path()
    stats = _ping(front)
    if stats is None:
        print("serve_ctl: router holds its pidfile but the front "
              "socket does not answer - starting up, or wedged")
        return 1
    print(f"serve_ctl: fleet UP - router pid {stats.get('pid')}, "
          f"routed={stats.get('routed')} spilled={stats.get('spilled')}"
          f" throttled={stats.get('throttled')} "
          f"relayed={stats.get('bytes_copied')}B "
          f"lanes={','.join(stats.get('lanes') or ['inline'])} "
          f"device={stats.get('device_kind')} "
          f"uptime={stats.get('uptime_s')}s")
    level = stats.get("level")
    if level and level != "ok":
        print(f"serve_ctl: fleet {str(level).upper()} - shedding "
              "rules active (docs/SERVING.md §self-healing)")
    rows = stats.get("workers") or []
    rc = 0
    for i, row in enumerate(rows):
        wstats = _ping(row.get("socket"))
        state = ("DRAINING" if row.get("draining")
                 else "QUARANTINED" if row.get("quarantined")
                 else "cooling" if row.get("cooling")
                 else row.get("state") or "up")
        # the self-healing columns: liveness state / restart count /
        # quarantine, straight from the router's health manager
        heal = ""
        if row.get("restarts"):
            heal = f" restarts={row.get('restarts')}"
        if wstats is None:
            print(f"  worker{i}: DOWN ({state}; "
                  f"routed={row.get('routed')}{heal})")
            if not row.get("draining"):
                rc = 1
            continue
        print(f"  worker{i}: {state} pid {wstats.get('pid')} "
              f"routed={row.get('routed')} "
              f"inflight_router={row.get('inflight')}{heal} - "
              + _stats_line(wstats))
    return rc


def health(wait_s: float) -> int:
    """Standalone fleet-health face: poll until every ring member is
    live (router rows preferred; direct pidfile+ping probes when the
    router itself is down) or the wait expires. The convergence gate
    chaos probes wait on after a kill."""
    cfg = serve_fleet.load_config()
    if not cfg:
        print("serve_ctl: no fleet.json - is a fleet running?",
              file=sys.stderr)
        return 1
    front = cfg["front"]
    deadline = time.monotonic() + wait_s
    rows = None
    while True:
        stats = _ping(front)
        rows = (stats or {}).get("workers")
        if rows is None:
            # router down: probe the workers directly (the read-only
            # half of the health manager, shared helper)
            rows = [
                {"socket": s,
                 "state": serve_health.probe_worker(s)[0]}
                for s in cfg.get("workers") or []
            ]
        live = [r for r in rows
                if (r.get("state") or "up") == "up"
                or r.get("draining")]
        if len(live) == len(rows) and rows:
            break
        if time.monotonic() >= deadline:
            break
        time.sleep(0.3)
    rc = 0
    for i, row in enumerate(rows or []):
        state = row.get("state") or "up"
        if row.get("draining"):
            state = "draining"
        if row.get("quarantined"):
            state = "quarantined"
        line = f"  worker{i}: {state}"
        if row.get("restarts"):
            line += f" restarts={row.get('restarts')}"
        print(line)
        if state not in ("up", "draining"):
            rc = 1
    if not rows:
        print("serve_ctl: fleet has no workers to probe",
              file=sys.stderr)
        rc = 1
    print("serve_ctl: fleet " + ("CONVERGED - all workers live"
                                 if rc == 0 else
                                 "NOT converged within the wait"))
    return rc


def guardian(wait_s: float) -> int:
    """Spawn the router's guardian detached and wait for its pidfile
    flock (docs/SERVING.md §guardian)."""
    if not serve_fleet.load_config():
        print("serve_ctl: no fleet.json - start a fleet first",
              file=sys.stderr)
        return 1
    gpidfile = serve_fleet.guardian_pidfile_path()
    held, pid = _pidfile_state(gpidfile)
    if held:
        print(f"serve_ctl: guardian already running (pid {pid})")
        return 3
    proc = serve_fleet.spawn_guardian(_REPO)
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            print(f"serve_ctl: guardian exited rc={proc.returncode} "
                  f"before flocking - see guardian.log under "
                  f"{serve_fleet.fleet_dir()}", file=sys.stderr)
            return 1
        held, _pid = _pidfile_state(gpidfile)
        if held:
            print(f"serve_ctl: guardian up (pid {proc.pid}) watching "
                  f"{serve_fleet.router_pidfile_path()}")
            return 0
        time.sleep(0.2)
    print(f"serve_ctl: guardian did not flock within {wait_s}s - "
          "killing it", file=sys.stderr)
    proc.terminate()
    return 1


def fsck() -> int:
    """Reap crash residue (docs/RESILIENCE.md §atomic state): stale
    pidfiles, orphaned shm segments, a torn fleet.json. Journaled as
    ``fleet_fsck``; always 0 — a janitor, not a health check."""
    from tpukernels.resilience import journal

    stale_pidfiles = 0
    pidfiles = [serve_fleet.guardian_pidfile_path(),
                serve_fleet.router_pidfile_path(),
                _cachedir.serve_pidfile_path()]
    fleet_d = serve_fleet.fleet_dir()
    try:
        for entry in sorted(os.listdir(fleet_d)):
            if entry.startswith("worker"):
                pidfiles.append(os.path.join(fleet_d, entry,
                                             "serve.pid"))
    except OSError:
        pass
    for p in pidfiles:
        if not os.path.exists(p):
            continue
        held, pid = _pidfile_state(p)
        if held:
            continue
        try:
            os.unlink(p)
        except OSError:
            continue
        stale_pidfiles += 1
        print(f"serve_ctl: fsck reaped stale pidfile {p}"
              + (f" (dead pid {pid})" if pid else ""))
    swept_segments = serve_protocol.sweep_stale_segments()
    if swept_segments:
        print(f"serve_ctl: fsck swept {swept_segments} orphaned shm "
              "segment(s)")
    torn_configs = 0
    cfg_path = serve_fleet.config_path()
    if os.path.exists(cfg_path) and serve_fleet.load_config() is None:
        # present but unreadable/invalid: a mid-write crash on a
        # pre-atomic writer tore it — reap it so start-fleet starts
        # clean instead of every reader re-rejecting it
        try:
            os.unlink(cfg_path)
            torn_configs += 1
            print(f"serve_ctl: fsck reaped torn {cfg_path}")
        except OSError:
            pass
    journal.emit(
        "fleet_fsck", stale_pidfiles=stale_pidfiles,
        swept_segments=swept_segments, torn_configs=torn_configs,
    )
    print(f"serve_ctl: fsck done - {stale_pidfiles} stale "
          f"pidfile(s), {swept_segments} orphaned segment(s), "
          f"{torn_configs} torn config(s)")
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    verbs = ("start", "stop", "status", "start-fleet", "stop-fleet",
             "drain", "undrain", "health", "guardian", "fsck", "top")
    if not argv or argv[0] not in verbs:
        print(__doc__, file=sys.stderr)
        return 2
    cmd = argv[0]
    rest = argv[1:]
    count = None
    if cmd in ("start-fleet", "drain", "undrain"):
        if not rest or not rest[0].isdigit():
            print(__doc__, file=sys.stderr)
            print(f"serve_ctl: {cmd} needs a count/index",
                  file=sys.stderr)
            return 2
        count = int(rest[0])
        rest = rest[1:]
    wait_s, socket_path = 30.0, None
    once, interval_s = False, 2.0
    it = iter(rest)
    try:
        for a in it:
            if a == "--wait":
                wait_s = float(next(it))
            elif a == "--socket":
                socket_path = next(it)
            elif a == "--once" and cmd == "top":
                once = True
            elif a == "--interval" and cmd == "top":
                interval_s = float(next(it))
            else:
                print(__doc__, file=sys.stderr)
                print(f"serve_ctl: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
    except (StopIteration, ValueError):
        print(f"serve_ctl: {a} needs a value", file=sys.stderr)
        return 2
    if cmd == "start":
        return start(wait_s, socket_path)
    if cmd == "stop":
        return stop(wait_s)
    if cmd == "start-fleet":
        if count < 1:
            print("serve_ctl: start-fleet needs N >= 1",
                  file=sys.stderr)
            return 2
        return start_fleet(count, wait_s)
    if cmd == "stop-fleet":
        return stop_fleet(wait_s)
    if cmd == "drain":
        return drain(count, wait_s)
    if cmd == "undrain":
        return undrain(count, wait_s)
    if cmd == "health":
        return health(wait_s)
    if cmd == "guardian":
        return guardian(wait_s)
    if cmd == "fsck":
        return fsck()
    if cmd == "top":
        # the dashboard lives in its own module (tools/fleet_top.py);
        # loaded by path because tools/ is a script dir, not a package
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import fleet_top

        return fleet_top.run(once=once, interval_s=interval_s,
                             socket_path=socket_path)
    return status(socket_path)


if __name__ == "__main__":
    sys.exit(main())
