"""Operator control of the kernel-serving daemon (docs/SERVING.md).

Usage:
    python tools/serve_ctl.py start [--wait S] [--socket PATH]
    python tools/serve_ctl.py stop [--wait S]
    python tools/serve_ctl.py status

``start`` spawns ``python -m tpukernels.serve`` detached (its own
session; stderr appended to ``serve_daemon.log`` beside the socket)
and waits until the daemon answers a protocol ping. ``stop`` sends
SIGTERM to the pid the flocked pidfile records and waits for the
flock to release — the clean-shutdown path that emits ``serve_stop``.
``status`` is the ``revalidate.py --whos-holding`` idea applied to
the daemon: liveness is the FLOCK on the pidfile (a dead daemon's
stale pid never reads as running), the recorded pid is the
diagnosis, and a live daemon also answers a ping with its stats.

Exit codes: 0 — done (``status``: daemon is up); 1 — failed
(``status``: daemon is down); 2 — usage error; 3 — ``start`` refused
because a live daemon already holds the pidfile (the wrapper's
"already covered" code).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels import _cachedir  # noqa: E402
from tpukernels.serve import client as serve_client  # noqa: E402
from tpukernels.serve import protocol as serve_protocol  # noqa: E402


def _pidfile_state():
    """(held, pid_or_None): held = a live daemon process flocks the
    pidfile (the revalidate_lib convention — test the lock, never
    trust the pid alone)."""
    import fcntl

    path = _cachedir.serve_pidfile_path()
    try:
        f = open(path)
    except OSError:
        return False, None
    with f:
        content = f.readline().strip()
        pid = int(content) if content.isdigit() else None
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)
        except OSError:
            return True, pid
    return False, pid


def _ping(socket_path):
    # ProtocolError too: a daemon mid-shutdown hangs up before
    # answering, which must read as "not (yet) up", not a traceback
    try:
        with serve_client.ServeClient(socket_path, timeout_s=5) as cli:
            return cli.ping()
    except (OSError, serve_protocol.ProtocolError):
        return None


def start(wait_s: float, socket_path) -> int:
    socket_path = socket_path or _cachedir.serve_socket_path()
    held, pid = _pidfile_state()
    if held:
        print(f"serve_ctl: daemon already running (pid {pid}) - "
              "leave it, or stop it first")
        return 3
    d = _cachedir.serve_dir()
    os.makedirs(d, exist_ok=True)
    log_path = os.path.join(d, "serve_daemon.log")
    log = open(log_path, "a")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpukernels.serve",
         "--socket", socket_path],
        cwd=_REPO, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=log,
    )
    log.close()
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            print(f"serve_ctl: daemon exited rc={proc.returncode} "
                  f"before answering - see {log_path}",
                  file=sys.stderr)
            return 1
        stats = _ping(socket_path)
        if stats:
            print(f"serve_ctl: daemon up (pid {stats.get('pid')}, "
                  f"{stats.get('workers')} worker(s)) on {socket_path}")
            return 0
        time.sleep(0.2)
    print(f"serve_ctl: daemon did not answer within {wait_s}s - "
          f"killing it; see {log_path}", file=sys.stderr)
    proc.terminate()
    return 1


def stop(wait_s: float) -> int:
    held, pid = _pidfile_state()
    if not held:
        print("serve_ctl: no daemon running"
              + (f" (stale pid {pid} in pidfile)" if pid else ""))
        return 0
    if pid is None:
        print("serve_ctl: pidfile flocked but records no pid - "
              "inspect by hand (fuser on the socket)", file=sys.stderr)
        return 1
    try:
        os.kill(pid, signal.SIGTERM)
    except OSError as e:
        print(f"serve_ctl: cannot signal pid {pid}: {e}",
              file=sys.stderr)
        return 1
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        held, _pid = _pidfile_state()
        if not held:
            print(f"serve_ctl: daemon (pid {pid}) stopped")
            return 0
        time.sleep(0.2)
    print(f"serve_ctl: daemon (pid {pid}) still holds the pidfile "
          f"after {wait_s}s - escalate by hand if it is truly wedged",
          file=sys.stderr)
    return 1


def status(socket_path=None) -> int:
    held, pid = _pidfile_state()
    if not held:
        print("serve_ctl: daemon DOWN"
              + (f" (stale pid {pid} in pidfile)" if pid else ""))
        return 1
    stats = _ping(socket_path or _cachedir.serve_socket_path())
    if stats is None:
        print(f"serve_ctl: pid {pid} holds the pidfile but the "
              "socket does not answer - starting up, or wedged")
        return 1
    print(
        f"serve_ctl: daemon UP (pid {stats.get('pid')}) - "
        f"served={stats.get('served')} rejected={stats.get('rejected')}"
        f" requeued={stats.get('requeued')} depth={stats.get('depth')}"
        f"/{stats.get('queue_max')} device={stats.get('device_kind')}"
        f" uptime={stats.get('uptime_s')}s"
    )
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] not in ("start", "stop", "status"):
        print(__doc__, file=sys.stderr)
        return 2
    cmd = argv[0]
    wait_s, socket_path = 30.0, None
    it = iter(argv[1:])
    try:
        for a in it:
            if a == "--wait":
                wait_s = float(next(it))
            elif a == "--socket":
                socket_path = next(it)
            else:
                print(__doc__, file=sys.stderr)
                print(f"serve_ctl: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
    except (StopIteration, ValueError):
        print(f"serve_ctl: {a} needs a value", file=sys.stderr)
        return 2
    if cmd == "start":
        return start(wait_s, socket_path)
    if cmd == "stop":
        return stop(wait_s)
    return status(socket_path)


if __name__ == "__main__":
    sys.exit(main())
