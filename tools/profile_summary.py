"""Summarize a jax.profiler (XProf) trace into the numbers the
roofline claims in docs/PERF.md / BASELINE.md need: per-plane busy %,
and the top ops by self time (SURVEY.md §5 tracing/profiling;
VERDICT r3 item 5 — "profiler evidence for the roofline claims").

Usage:
    python tools/profile_summary.py <trace_dir> [--top=10]

<trace_dir> is the directory passed as TPU_KERNELS_PROFILE (the
summarizer finds the newest plugins/profile/<run>/*.xplane.pb under
it, or accepts a direct path to an .xplane.pb file).

Parsing is protobuf-only via tensorflow.tsl's bundled xplane schema —
the tensorboard_plugin_profile converter in this image is broken
(pywrap xspace_to_tools_data missing), so this reads the raw planes
directly. On a TPU trace the interesting planes are
"/device:TPU:N" (one per chip; XLA op events with self duration) and
the host plane; "busy %" is the union of event intervals on a line
divided by the plane's observed span — for the device plane that is
compute occupancy, the number behind "VPU/MXU-bound" claims.
"""

from __future__ import annotations

import glob
import os
import sys


def _load_xspace(path: str):
    # deferred + env-guarded: tf's C++ protobuf descriptors for this
    # schema are stale in this image; pure-python parsing always works
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def find_xplane(trace_dir: str) -> str:
    if trace_dir.endswith(".xplane.pb"):
        return trace_dir
    hits = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        ),
        key=os.path.getmtime,
    )
    if not hits:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    return hits[-1]


def _union_busy_ps(intervals) -> int:
    """Total covered picoseconds of [start, end) intervals (events on
    one line can nest — XLA modules contain ops — so a plain sum
    double-counts)."""
    busy = 0
    last_end = None
    for s, e in sorted(intervals):
        if last_end is None or s >= last_end:
            busy += e - s
            last_end = e
        elif e > last_end:
            busy += e - last_end
            last_end = e
    return busy


def summarize_plane(plane) -> dict:
    names = {m.id: m.name for m in plane.event_metadata.values()}
    op_ps: dict[str, int] = {}
    intervals = []
    t_min, t_max = None, 0
    for line in plane.lines:
        line_iv = []
        for ev in line.events:
            s = line.timestamp_ns * 1000 + ev.offset_ps
            e = s + ev.duration_ps
            line_iv.append((s, e))
            name = names.get(ev.metadata_id, f"id{ev.metadata_id}")
            op_ps[name] = op_ps.get(name, 0) + ev.duration_ps
            t_min = s if t_min is None else min(t_min, s)
            t_max = max(t_max, e)
        # busy union is per line (parallel lines measure different
        # engines; merging them would understate concurrency)
        intervals.append(_union_busy_ps(line_iv))
    span_ps = (t_max - t_min) if t_min is not None else 0
    return {
        "name": plane.name,
        "span_ms": span_ps / 1e9,
        "busiest_line_ms": max(intervals) / 1e9 if intervals else 0.0,
        "busy_pct": 100.0 * max(intervals) / span_ps if span_ps else 0.0,
        "ops": op_ps,
    }


def main(argv) -> int:
    top = 10
    args = []
    for a in argv[1:]:
        if a.startswith("--top="):
            top = int(a[6:])
        else:
            args.append(a)
    if len(args) != 1:
        print(__doc__)
        return 2
    path = find_xplane(args[0])
    print(f"# trace: {path}")
    xs = _load_xspace(path)
    device_seen = False
    for plane in xs.planes:
        is_device = "/device:" in plane.name and "CPU" not in plane.name
        device_seen = device_seen or is_device
        # host planes are noise for roofline claims; list device and
        # TensorCore planes in full, others one-line
        s = summarize_plane(plane)
        if not s["ops"]:
            continue
        print(
            f"plane {s['name']!r}: span={s['span_ms']:.3f}ms "
            f"busiest-line busy={s['busiest_line_ms']:.3f}ms "
            f"({s['busy_pct']:.1f}%)"
        )
        if is_device or "TensorCore" in plane.name or "XLA" in plane.name:
            ranked = sorted(
                s["ops"].items(), key=lambda kv: -kv[1]
            )[:top]
            width = max((len(n) for n, _ in ranked), default=0)
            for name, ps in ranked:
                print(f"    {name:<{width}}  {ps / 1e9:10.3f} ms")
    if not device_seen:
        print(
            "# WARNING: no device plane found — host-only trace "
            "(was the kernel actually dispatched to a TPU?)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
