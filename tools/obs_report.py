"""Observability report: bench trend + span/metric summary + verdicts.

Usage:
    python tools/obs_report.py [--check] [--roofline] [--root DIR]
                               [--journal FILE] [--eps FLOAT]

Sections (docs/OBSERVABILITY.md):

1. **Trend table** — per-metric time series over ``BENCH_r*.json`` +
   ``docs/logs/bench_*.json`` (``tpukernels/obs/trend.py``) judged
   against the BASELINE.json measured medians and physical ceilings.
2. **Roofline table** — achieved vs the analytic per-kernel roofline
   peak (``tpukernels/tuning/roofline.py``: FLOPs + minimum HBM bytes
   per config of record against the device peaks), with % of roofline
   and the binding resource. ``--roofline`` prints this section alone
   (the supervisor's non-gating ``roofline_report`` step).
3. **Span breakdown** — per-phase wall time aggregated from ``span``
   events in the health journal (default: the newest
   ``docs/logs/health_*.jsonl``; spans exist only for runs traced
   with ``TPK_TRACE=1``).
4. **Supervisor step breakdown** — per-step wall time from the
   ``step/<name>`` spans plus attempts/outcomes/quarantine state from
   the supervisor's ``step_*`` events (docs/RESILIENCE.md
   §supervisor).
5. **AOT compile cache** — hit/miss traffic, compile walls on each,
   stale-entry rejections and prewarm outcomes from the ``aot_*`` /
   ``prewarm_*`` events (docs/PERF.md §compile discipline).
6. **Output integrity** — checks run / confirmed corruption events /
   quarantined (kernel, config) entries from the
   ``output_integrity_*`` events plus the persistent quarantine
   ledger (docs/RESILIENCE.md §output integrity).
7. **Latency SLOs** — per-kernel p50/p99 vs target from the
   validated ``slo.json`` verdict artifact the load generator writes
   (``tools/loadgen.py`` + ``tpukernels/obs/slo.py``): the
   tail-latency story the slope trend cannot see.
8. **Scaling** — the distributed-path series
   (``tpukernels/obs/scaling.py``; docs/OBSERVABILITY.md §scaling):
   bus-bandwidth per (op, message size, n_devices) over the committed
   ``docs/logs/scaling_*.json`` / ``SCALING_r*.json`` artifacts,
   weak-scaling efficiency per program, and the MULTICHIP dryrun-wall
   series. Fake-device artifacts render flagged and never gate.
9. **Serve copy budget** — payload bytes the serving daemon copied
   per request by lane, from the ``serve_copy_budget`` events
   ``loadgen --serve`` stamps (docs/SERVING.md §copy accounting).
   The negotiated shm warm path's budget is exactly zero.
10. **Request phases** — phase-attribution percentiles per (kernel,
    bucket, tenant) from the cross-process request timelines
    ``tpukernels/obs/reqtrace.py`` assembles by joining the serve
    journals on the client-minted ``request_id``
    (docs/OBSERVABILITY.md §request tracing; waterfalls via
    ``tools/trace_report.py``), plus the trace-budget verdicts.
11. **Shapes seen** — requested (pre-pad) shape mix per (kernel,
    bucket) with pad waste, from the per-request shape-mix records
    on ``serve_request`` — ROADMAP item 5's optimizer input.
12. **Deadlines** — expiry / infeasibility / hedge / cancel traffic
    from the journal (``serve_request_expired`` /
    ``serve_deadline_infeasible`` / ``serve_hedged`` /
    ``serve_cancelled``; docs/SERVING.md §deadlines) plus the goodput
    counts deadline-carrying ``loadgen --deadline-ms`` runs stamp on
    their ``slo_probe`` events. Absent any deadline evidence the
    section does not render.
13. **Metric snapshots** — per-process metric state reconstructed by
    the one shared ``metrics.merge_journal_metrics`` fold
    (docs/OBSERVABILITY.md §live telemetry): a process's final
    ``metrics`` event is authoritative; a process that died without
    one (SIGKILL) is reconstructed from its ``metrics_snapshot``
    stream, deduped by (pid, seq) — counters (probe retries, watchdog
    kills, tuning-cache traffic), gauges, latency histograms
    (count-weighted p50/p95/p99 + exact max). The two encodings are
    never summed.
14. **Daily rollups** — the long-horizon series
    (``tpukernels/obs/rollup.py``): validated ``rollup_<date>.json``
    artifacts with per-kernel request counts and daily p99s, judged
    by the NON-GATING ``p99_creep`` trend verdict (latest day's p99
    more than ``trend.P99_CREEP_FRAC`` above the prior days' median
    AND the worst day in the window — the slow multi-day tail drift
    the per-run epsilon band structurally misses).

Exit-code signaling (``tools/tpu_revalidate.sh`` runs ``--check``
non-gating and keys a WARN off it):
    0 — every metric ``ok``, ``below_roofline`` or ``no_data``
        (nothing measurable went backwards; tunnel-down nulls are
        retryable, and below-roofline is a headroom signal, not a
        failure), the journal holds no confirmed
        ``output_integrity_failed`` event, no validated non-simulated
        ``slo_breach`` verdict is on record, AND no validated
        (non-fake) scaling series regressed;
    1 — at least one ``regression`` or ``impossible`` verdict (bench
        trend OR validated bus-bw scaling series — the paper's
        multi-chip headline gates exactly like its single-chip
        slopes), a confirmed output-integrity corruption (a wrong
        answer is worse than a slow one), a confirmed p99 SLO
        breach (a degraded tail is a regression users feel before the
        slope moves), a ``copy_regression`` (payload bytes copied
        per request on the serve path's negotiated zero-copy shm
        lane — docs/SERVING.md §copy accounting), or a
        ``trace_inconsistent`` finding (a clean request's accounted
        phases summed past its client-observed wall — the trace
        evidence itself is wrong; docs/OBSERVABILITY.md §request
        tracing) — all of these gate identically;
    2 — usage error (never 1: rc 1 is reserved for real findings).
``below_scaling_efficiency``, ``trace_coverage`` and ``p99_creep``
print as non-gating information, the ``below_roofline`` pattern.

``--check`` prints only the non-ok verdict lines (machine/CI mode;
``below_roofline`` lines print as non-gating information); the
default mode prints the full report. ``--eps`` widens/narrows the
trend band (default: the ceiling epsilon, ``trend.CEILING_EPS``).
"""

from __future__ import annotations

import glob
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels.obs import metrics as _metrics  # noqa: E402
from tpukernels.obs import reqtrace as _reqtrace  # noqa: E402
from tpukernels.obs import rollup as _rollup  # noqa: E402
from tpukernels.obs import scaling as _scaling  # noqa: E402
from tpukernels.obs import slo as _slo  # noqa: E402
from tpukernels.obs import trace, trend  # noqa: E402
from tpukernels.resilience import journal as _journal  # noqa: E402
from tpukernels.tuning import roofline as _roofline  # noqa: E402


def _fmt_val(v):
    if v is None:
        return "-"
    return f"{v:,.2f}" if isinstance(v, float) else f"{v:,}"


def trend_section(verdicts, out):
    out.append("== bench trend "
               "(BENCH_r*.json + docs/logs/bench_*.json) ==")
    hdr = (f"{'metric':<22} {'pts':>3} {'latest':>13} {'best':>13} "
           f"{'baseline':>13}  verdict")
    out.append(hdr)
    out.append("-" * len(hdr))
    for name, v in verdicts.items():
        out.append(
            f"{name:<22} {v['valid_points']:>3} "
            f"{_fmt_val(v['latest']):>13} {_fmt_val(v['best']):>13} "
            f"{_fmt_val(v['baseline']):>13}  {v['verdict']}"
        )
        for flag in v["flags"]:
            out.append(f"    {flag}")


def roofline_section(verdicts, out):
    """Machine-checked roofline table (docs/PERF.md §rooflines):
    achieved = the trend series' newest valid value per metric over
    every committed BENCH artifact; peak = the analytic model at the
    config of record. The % column is the headroom story the
    below_roofline verdict keys on."""
    rows = _roofline.report_rows(verdicts)
    out.append("")
    kind = rows[0]["device_kind"] if rows else "?"
    basis = rows[0]["basis"] if rows else "?"
    out.append(
        f"== roofline (analytic peaks for {kind}, {basis}; "
        f"threshold {_roofline.min_frac():.0%}) =="
    )
    hdr = (f"{'metric':<22} {'achieved':>13} {'analytic peak':>14} "
           f"{'% of roofline':>14}  bound")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        frac = f"{r['frac']:.1%}" if r["frac"] is not None else "-"
        out.append(
            f"{r['metric']:<22} {_fmt_val(r['achieved']):>13} "
            f"{r['peak']:>14,.0f} {frac:>14}  {r['bound']}"
        )
        if r["note"]:
            out.append(f"    {r['note']}")


def span_section(events, out):
    agg = trace.aggregate_spans(events)
    n = sum(a["count"] for a in agg.values())
    out.append("")
    out.append(f"== span breakdown ({n} span events) ==")
    if not agg:
        out.append("(no spans - run with TPK_TRACE=1 to record them)")
        return
    hdr = (f"{'span':<34} {'count':>5} {'total_s':>10} {'mean_s':>9} "
           f"{'max_s':>9}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for name in sorted(agg, key=lambda k: -agg[k]["total_s"]):
        a = agg[name]
        out.append(
            f"{name:<34} {a['count']:>5} {a['total_s']:>10.3f} "
            f"{a['total_s'] / a['count']:>9.3f} {a['max_s']:>9.3f}"
        )


def step_section(events, out):
    """Per-step wall-time breakdown for supervisor runs: the
    `step/<name>` spans (TPK_TRACE=1 runs) give wall time; the
    step_done / step_quarantined events fill in attempts and
    quarantine state even for untraced runs."""
    # spans nest under their parents ("queue/run/step/bench"), so key
    # on the path segment after the last "step/"
    agg = {name.split("step/")[-1]: a
           for name, a in trace.aggregate_spans(events).items()
           if "step/" in name}
    dones: dict = {}
    quarantined = set()
    for e in events:
        if e.get("kind") == "step_done":
            d = dones.setdefault(e.get("step"), {
                "n": 0, "wall_s": 0.0, "outcomes": {}})
            d["n"] += 1
            d["wall_s"] += e.get("wall_s") or 0.0
            oc = e.get("outcome", "?")
            d["outcomes"][oc] = d["outcomes"].get(oc, 0) + 1
        elif e.get("kind") == "step_quarantined":
            quarantined.add(e.get("step"))
    if not agg and not dones:
        return
    out.append("")
    out.append(f"== supervisor step breakdown ({len(dones)} step(s), "
               f"{len(agg)} traced) ==")
    hdr = (f"{'step':<22} {'runs':>4} {'wall_s':>9} {'span_s':>9} "
           "outcomes")
    out.append(hdr)
    out.append("-" * len(hdr))
    for name in sorted(set(agg) | set(dones),
                       key=lambda n: -dones.get(n, {}).get("wall_s",
                                                           0.0)):
        d = dones.get(name, {"n": 0, "wall_s": 0.0, "outcomes": {}})
        span_s = agg.get(name, {}).get("total_s")
        oc = ",".join(f"{k}={v}"
                      for k, v in sorted(d["outcomes"].items()))
        out.append(
            f"{name:<22} {d['n']:>4} {d['wall_s']:>9.3f} "
            + (f"{span_s:>9.3f}" if span_s is not None else
               f"{'-':>9}")
            + f" {oc}"
            + (" QUARANTINED" if name in quarantined else "")
        )


def aot_section(events, out):
    """Compile-discipline evidence (docs/PERF.md): per-program compile
    walls from the ``aot_hit``/``aot_miss`` events plus rejection and
    prewarm traffic — the at-a-glance answer to "did the window spend
    its minutes compiling or measuring"."""
    hits = [e for e in events if e.get("kind") == "aot_hit"]
    misses = [e for e in events if e.get("kind") == "aot_miss"]
    rejected = [e for e in events if e.get("kind") == "aot_rejected"]
    prewarm = [e for e in events if e.get("kind") == "prewarm_end"]
    if not (hits or misses or rejected or prewarm):
        return
    out.append("")
    n = len(hits) + len(misses)
    ratio = f"{len(hits) / n:.0%}" if n else "-"
    hit_wall = sum(e.get("compile_s") or 0.0 for e in hits)
    miss_wall = sum(e.get("compile_s") or 0.0 for e in misses)
    out.append(f"== aot compile cache ({len(hits)} hit(s), "
               f"{len(misses)} miss(es), hit ratio {ratio}) ==")
    out.append(f"compile wall: {hit_wall:.3f}s on hits, "
               f"{miss_wall:.3f}s on misses"
               + (f"; {len(rejected)} stale entr(ies) rejected"
                  if rejected else ""))
    worst = sorted(misses, key=lambda e: -(e.get("compile_s") or 0.0))
    for e in worst[:8]:
        out.append(f"  miss {e.get('key')}: "
                   f"lower {e.get('lower_s')}s + compile "
                   f"{e.get('compile_s')}s")
    for e in rejected:
        out.append(f"  rejected {e.get('key')}: {e.get('reason')}")
    for e in prewarm:
        out.append(f"  prewarm: {e.get('compiled')} warmed, "
                   f"{len(e.get('failed') or [])} failed in "
                   f"{e.get('total_wall_s')}s")


def integrity_section(events, out):
    """Output-integrity evidence (docs/RESILIENCE.md §output
    integrity): guard traffic from the metrics snapshots, confirmed
    corruption events, and today's quarantined (kernel, config)
    ledger entries — the at-a-glance answer to "can the numbers this
    session produced be trusted"."""
    failed = [e for e in events
              if e.get("kind") == "output_integrity_failed"]
    quarantined = [e for e in events
                   if e.get("kind") == "output_integrity_quarantined"]
    checks = deep = errors = 0
    # per-pid states from the shared merge (final `metrics` event
    # authoritative, else the deduped snapshot stream) — summing raw
    # events would double-count a pid that streamed AND exited cleanly
    for st in _metrics.merge_journal_metrics(events).values():
        c = st.get("counters") or {}
        checks += c.get("integrity.checks", 0)
        deep += c.get("integrity.deep_checks", 0)
        errors += c.get("integrity.check_errors", 0)
    try:
        from tpukernels.resilience import integrity as _integrity

        ledger = _integrity.quarantined_entries()
    except Exception:  # noqa: BLE001 — the report must still render
        ledger = {}
    if not (failed or quarantined or checks or ledger):
        return
    out.append("")
    out.append(
        f"== output integrity ({checks} check(s), {deep} canary "
        f"check(s), {len(failed)} confirmed failure(s)) =="
    )
    for e in failed:
        out.append(
            f"  FAILED {e.get('kernel')} at {e.get('site')} "
            f"(tier {e.get('tier')}): {e.get('detail')}"
        )
    if errors:
        out.append(f"  {errors} check error(s) (results not judged - "
                   "see output_integrity_check_error events)")
    for key, ent in sorted(ledger.items()):
        out.append(
            f"  QUARANTINED {key}: {ent.get('failures')} failure(s) "
            f"today - {ent.get('last_detail')}"
        )
    if not failed and not ledger:
        out.append("  all checks passed")


def slo_section(out):
    """Latency-SLO table from the validated ``slo.json`` verdict
    artifact (docs/OBSERVABILITY.md §latency SLOs): per (kernel,
    shape class, device kind) the count-weighted p50/p99 against the
    target — the per-request tail story the slope trend is blind to.
    Simulated rows render flagged; only real breaches gate."""
    try:
        entries = _slo.load_entries()
    except Exception:  # noqa: BLE001 — the report must still render
        entries = {}
    if not entries:
        return
    out.append("")
    out.append(f"== latency SLOs ({len(entries)} verdict(s) in "
               f"{os.path.relpath(_slo.path())}) ==")
    hdr = (f"{'kernel':<16} {'class':<7} {'kind':<12} {'n':>5} "
           f"{'p50_ms':>9} {'p99_ms':>9} {'target':>9}  verdict")
    out.append(hdr)
    out.append("-" * len(hdr))

    def _ms(v):
        return _slo.fmt_ms(v, 9)

    for key, e in sorted(entries.items()):
        kernel = key.split("|", 1)[0]
        out.append(
            f"{kernel:<16} {e.get('shape_class', '?'):<7} "
            f"{e.get('device_kind', '?'):<12} "
            f"{e.get('count', 0):>5} {_ms(e.get('p50_s'))} "
            f"{_ms(e.get('p99_s'))} {_ms(e.get('target_p99_s'))}  "
            f"{e.get('verdict')}"
            + (" (simulated - never gates)" if e.get("simulated")
               else "")
        )


def scaling_section(analysis, out):
    """Distributed-path scaling tables (docs/OBSERVABILITY.md
    §scaling): the bus-bw series, per-program weak-scaling efficiency
    and the MULTICHIP dryrun walls, each row carrying its verdict.
    Fake-only series render as ``no_data`` with the exclusion flag —
    visibly present, never gating."""
    busbw = analysis.get("busbw") or {}
    weak = analysis.get("weak") or {}
    overlap = analysis.get("overlap") or {}
    dryrun = analysis.get("dryrun") or {}
    if not (busbw or weak or overlap or dryrun):
        return
    out.append("")
    out.append(
        f"== scaling ({analysis.get('artifacts', 0)} artifact(s) in "
        "docs/logs/scaling_*.json + SCALING_r*.json; fake-device "
        "series never gate) =="
    )
    if busbw:
        hdr = (f"{'bus-bw series':<28} {'pts':>3} {'valid':>5} "
               f"{'latest GB/s':>12} {'best':>10}  verdict")
        out.append(hdr)
        out.append("-" * len(hdr))
        for name, v in busbw.items():
            out.append(
                f"{name:<28} {v['points']:>3} {v['valid_points']:>5} "
                f"{_fmt_val(v['latest']):>12} {_fmt_val(v['best']):>10}"
                f"  {v['verdict']}"
            )
            for flag in v["flags"]:
                out.append(f"    {flag}")
    if weak:
        out.append(f"weak scaling (efficiency floor "
                   f"{_scaling.min_eff():.0%}, TPK_SCALING_MIN_EFF):")
        for name, v in weak.items():
            walls = " ".join(
                f"n{n}={w:.4f}s" for n, w in v["walls"].items()
            )
            eff = (f"{v['efficiency']:.1%}"
                   if v.get("efficiency") is not None else "-")
            out.append(
                f"  {name:<12} eff={eff:>7} {walls}  {v['verdict']}"
                + (" (fake)" if v.get("fake") else "")
            )
            for flag in v["flags"]:
                out.append(f"    {flag}")
    if overlap:
        out.append(
            f"comm/compute overlap (floor "
            f"{_scaling.overlap_min_frac():.0%}, TPK_OVERLAP_MIN_FRAC;"
            " overlap_low is non-gating):"
        )
        for name, v in overlap.items():
            out.append(
                f"  {name:<24} frac={v['overlap_frac']:.3f} "
                f"comm={_fmt_val(v.get('t_comm_s'))}s "
                f"compute={_fmt_val(v.get('t_compute_s'))}s "
                f"full={_fmt_val(v.get('t_full_s'))}s"
                f"  {v['verdict']}"
                + (" (fake)" if v.get("fake") else "")
            )
            for flag in v["flags"]:
                out.append(f"    {flag}")
    if dryrun:
        out.append("multichip dryrun walls (fake CPU devices - "
                   "liveness/drift series, never gate):")
        for name, v in dryrun.items():
            out.append(
                f"  {name:<18} rounds={v['rounds']} "
                f"latest={v['latest_wall_s']}s best={v['best_wall_s']}s"
            )


def copy_section(events, out):
    """Serve copy-budget table from the ``serve_copy_budget`` events
    ``loadgen --serve`` stamps (docs/SERVING.md §copy accounting):
    payload bytes the daemon copied per request, by lane. The shm
    warm path's budget is exactly zero — a nonzero ``expected_zero``
    row is a ``copy_regression`` and gates like a bench
    regression."""
    verdicts = trend.analyze_copy_budget(events)
    if not verdicts:
        return
    out.append("")
    out.append(f"== serve copy budget ({len(verdicts)} lane "
               "measurement(s)) ==")
    hdr = (f"{'series':<34} {'lane':<7} {'req':>5} "
           f"{'bytes/request':>14}  verdict")
    out.append(hdr)
    out.append("-" * len(hdr))
    for name, v in verdicts.items():
        out.append(
            f"{name:<34} {v['lane']:<7} {v['requests'] or 0:>5} "
            f"{v['bytes_per_request']:>14,.1f}  {v['verdict']}"
            + (" (zero-copy contract)" if v["expected_zero"] else "")
        )
        for flag in v["flags"]:
            out.append(f"    {flag}")


def adapt_section(events, out):
    """Traffic-adaptive bucket loop (docs/SERVING.md §adaptive
    buckets): the latest proposal / canary / promotion evidence from
    the journal, plus the live ``serve.bucket_pad_frac`` aggregate
    judged against ``TPK_ADAPT_PAD_TARGET`` — the operator's one-look
    answer to "is the promoted table still earning its keep"."""
    from tpukernels.serve import adapt as _adapt

    latest = {}
    for e in events:
        if e.get("kind") in ("adapt_proposed", "adapt_canary",
                             "adapt_promoted", "adapt_rejected"):
            latest[e["kind"]] = e
    live = _adapt.histogram_pad_frac(events)
    if not latest and live is None:
        return

    def _pf(v):
        return f"{v:.3f}" if isinstance(v, (int, float)) else "n/a"

    out.append("")
    out.append("== adaptive buckets ==")
    p = latest.get("adapt_proposed")
    if p:
        out.append(
            f"  proposed: {len(p.get('proposals') or [])} action(s) "
            f"over {p.get('requests_mined')} mined request(s), "
            "projected pad_frac "
            f"{_pf((p.get('before') or {}).get('pad_frac'))} -> "
            f"{_pf((p.get('after') or {}).get('pad_frac'))} "
            f"(target {p.get('pad_target')})"
        )
    c = latest.get("adapt_canary")
    if c:
        out.append(f"  canary: promote={c.get('promote')} - "
                   f"{c.get('reason')}")
    pr = latest.get("adapt_promoted")
    if pr:
        out.append(f"  promoted: {pr.get('table')} (measured "
                   f"pad_frac {_pf(pr.get('pad_frac'))})")
    rj = latest.get("adapt_rejected")
    if rj:
        out.append(f"  rejected: {rj.get('reason')}")
    if live is not None:
        try:
            target = _adapt.pad_target()
        except ValueError:
            target = None
        line = f"  live serve.bucket_pad_frac {_pf(live)}"
        if target is not None:
            line += (" below target " if live < target
                     else " AT-OR-OVER target ") + str(target)
        out.append(line)


def reqtrace_section(events, out):
    """Request-phase table from the assembled per-request timelines
    (docs/OBSERVABILITY.md §request tracing): phase-attribution
    percentiles per (kernel, bucket, tenant) plus the trace-budget
    verdicts — where the tail actually lives, per request class.
    Untraced served requests are announced, never silently
    dropped."""
    tls = _reqtrace.assemble(events)
    untraced = _reqtrace.untraced_serve_requests(events)
    verdicts = trend.analyze_trace_budget(events)
    if not (tls or untraced or verdicts):
        return
    traced = sum(1 for t in tls.values() if t["segments"])
    out.append("")
    out.append(f"== request phases ({len(tls)} timeline(s), {traced} "
               "with span evidence; tools/trace_report.py renders "
               "waterfalls) ==")
    if untraced:
        out.append(f"  NOTE: {untraced} serve_request event(s) carry "
                   "no request_id - served but not assembled")
    agg = _reqtrace.aggregate(tls)
    phases = list(_reqtrace.PHASES)
    if agg:
        hdr = (f"{'kernel|bucket|tenant':<40} {'n':>4} "
               f"{'cli_p99_ms':>10}  dominant phases (p50 ms)")
        out.append(hdr)
        out.append("-" * len(hdr))
        for key, g in agg.items():
            tops = sorted(
                ((p, g["phases"][p]["p50_s"])
                 for p in phases if p in g["phases"]),
                key=lambda kv: -(kv[1] or 0.0),
            )[:3]
            cw = g["client_p99_s"]
            out.append(
                f"{key:<40} {g['n']:>4} "
                + (f"{cw * 1e3:>10.3f}" if cw is not None
                   else f"{'-':>10}")
                + "  "
                + " ".join(f"{p}={v * 1e3:.3f}" for p, v in tops)
                + (f"  {g['gaps']} gap(s)" if g["gaps"] else "")
            )
    for name, v in verdicts.items():
        out.append(f"  {name}: {v['verdict']} (traced {v['traced']} "
                   f"of {v['requests']} request(s))")
        for flag in v["flags"]:
            out.append(f"    {flag}")


def shapes_section(events, out):
    """Shapes-seen table from the per-request shape-mix records on
    ``serve_request`` events (docs/OBSERVABILITY.md §request
    tracing): requested (pre-pad) shapes per (kernel, bucket) with
    pad waste — the exact traffic evidence ROADMAP item 5's
    bucket-table optimizer mines."""
    rows: dict = {}
    for ev in events:
        if ev.get("kind") != "serve_request" or not ev.get("shapes"):
            continue
        shapes = "+".join(
            "x".join(str(d) for d in s) or "scalar"
            for s in ev["shapes"]
        )
        key = (ev.get("kernel", "?"), shapes,
               ev.get("bucket") or "-")
        r = rows.setdefault(key, {"n": 0, "pad": 0.0, "tenants": set()})
        r["n"] += 1
        r["pad"] += ev.get("pad_frac") or 0.0
        if ev.get("tenant") not in (None, "-"):
            r["tenants"].add(str(ev.get("tenant")))
    if not rows:
        return
    out.append("")
    out.append(f"== shapes seen ({len(rows)} distinct (kernel, "
               "shapes, bucket) mix(es) from serve_request "
               "records) ==")
    hdr = (f"{'kernel':<16} {'requested shapes':<26} "
           f"{'bucket':<30} {'n':>5} {'mean_pad':>9}  tenants")
    out.append(hdr)
    out.append("-" * len(hdr))
    for (kernel, shapes, bucket), r in sorted(
            rows.items(), key=lambda kv: (-kv[1]["n"], kv[0])):
        out.append(
            f"{kernel:<16} {shapes:<26} {bucket:<30} {r['n']:>5} "
            f"{r['pad'] / r['n']:>9.1%}  "
            + (",".join(sorted(r["tenants"])) or "-")
        )


def deadlines_section(events, out):
    """Deadline evidence (docs/SERVING.md §deadlines): where budgets
    died (expiry site/where counts), admission refusals, hedge pairs
    and cancel phases from the journal, plus the goodput counts
    deadline-carrying loadgen runs stamp on ``slo_probe``. Renders
    only when a run carried deadlines — without them the report stays
    byte-identical to a pre-deadline one."""
    kinds: dict = {"serve_request_expired": [],
                   "serve_deadline_infeasible": [],
                   "serve_hedged": [], "serve_cancelled": []}
    for e in events:
        k = e.get("kind")
        if k in kinds:
            kinds[k].append(e)
    probes = [e for e in events
              if e.get("kind") == "slo_probe" and e.get("goodput")]
    if not any(kinds.values()) and not probes:
        return
    out.append("")
    out.append("== deadlines (docs/SERVING.md §deadlines) ==")
    exp = kinds["serve_request_expired"]
    if exp:
        where: dict = {}
        for e in exp:
            key = f"{e.get('site')}/{e.get('where')}"
            where[key] = where.get(key, 0) + 1
        out.append(
            f"  {len(exp)} request(s) expired before dispatch: "
            + ", ".join(f"{k}={n}" for k, n in sorted(where.items()))
        )
    inf = kinds["serve_deadline_infeasible"]
    if inf:
        out.append(f"  {len(inf)} refused at admission (budget "
                   "already infeasible on arrival)")
    hed = kinds["serve_hedged"]
    if hed:
        pairs: dict = {}
        for e in hed:
            key = f"{e.get('from_worker')}->{e.get('to_worker')}"
            pairs[key] = pairs.get(key, 0) + 1
        out.append(
            f"  {len(hed)} hedged dispatch(es), first-response-wins: "
            + ", ".join(f"worker {k} x{n}"
                        for k, n in sorted(pairs.items()))
        )
    can = kinds["serve_cancelled"]
    if can:
        sites: dict = {}
        for e in can:
            key = str(e.get("site"))
            sites[key] = sites.get(key, 0) + 1
        out.append(
            f"  {len(can)} cancel(s): "
            + ", ".join(f"{k}={n}" for k, n in sorted(sites.items()))
        )
    for e in probes:
        gp = e.get("goodput") or {}
        met = sum(int(v[0]) for v in gp.values())
        total = sum(int(v[1]) for v in gp.values())
        frac = f" ({met / total:.1%})" if total else ""
        out.append(
            f"  goodput {met}/{total}{frac} deadline(s) met "
            f"(seed {e.get('seed')}, "
            f"deadline_ms {e.get('deadline_ms')})"
        )


def metrics_section(events, out):
    # the one shared reconstruction (docs/OBSERVABILITY.md §live
    # telemetry): a pid's atexit `metrics` event is authoritative; a
    # pid that never got one (SIGKILL) is rebuilt from its deduped
    # `metrics_snapshot` stream — the two encodings are never summed
    merged = _metrics.merge_journal_metrics(events)
    n_events = sum(1 for e in events
                   if e.get("kind") in ("metrics", "metrics_snapshot"))
    out.append("")
    out.append(f"== metric snapshots ({n_events} event(s), "
               f"{len(merged)} process(es)) ==")
    if not merged:
        out.append("(no metrics/metrics_snapshot events in the "
                   "journal)")
        return
    for pid, st in sorted(merged.items(), key=lambda kv: str(kv[0])):
        if st.get("final"):
            how = "final"
        else:
            # no atexit flush — this process died hard; what follows
            # is its last streamed snapshot (at most one flush
            # interval stale)
            how = f"last snapshot seq={st.get('seq')}, no final flush"
        out.append(f"[pid {pid}] site={st.get('site')} ({how})")
        for k, v in sorted((st.get("counters") or {}).items()):
            out.append(f"  counter   {k} = {v}")
        for k, v in sorted((st.get("gauges") or {}).items()):
            out.append(f"  gauge     {k} = {v}")
        for k, h in sorted((st.get("histograms") or {}).items()):
            # percentiles come straight off the snapshot (the
            # emitter's count-weighted derivation — never re-derived
            # from buckets here)
            out.append(
                f"  histogram {k}: count={h.get('count')} "
                f"sum={h.get('sum')} min={h.get('min')} "
                f"max={h.get('max')} p50={h.get('p50')} "
                f"p95={h.get('p95')} p99={h.get('p99')}"
            )


def rollup_section(out):
    """Long-horizon health off the daily rollup series
    (docs/OBSERVABILITY.md §daily rollups): one line per rollup day,
    then the NON-GATING ``p99_creep`` verdicts — the slow multi-day
    tail drift the per-run epsilon band structurally misses."""
    try:
        series = _rollup.load_series()
    except Exception as e:  # noqa: BLE001 — the report must render
        out.append("")
        out.append(f"== daily rollups (unreadable: {e!r}) ==")
        return
    if not series:
        return
    out.append("")
    out.append(f"== daily rollups ({len(series)} day(s) in "
               f"{os.path.relpath(_rollup.rollup_dir())}) ==")
    for date, art in series[-7:]:
        reqs = art.get("requests") or {}
        total = sum((r or {}).get("count") or 0 for r in reqs.values())
        out.append(f"  {date}: {art.get('events')} event(s), "
                   f"{art.get('pids') or 0} pid(s), "
                   f"{total} request(s) over {len(reqs)} kernel(s)")
    for name, v in sorted(trend.analyze_p99_creep(series).items()):
        if v["verdict"] == "p99_creep":
            out.append(f"  {name}: p99_creep (non-gating)")
            for flag in v["flags"]:
                out.append(f"    {flag}")
        elif v["verdict"] == "ok":
            out.append(f"  {name}: ok over {v['days']} day(s) "
                       f"(latest p99 {v['latest']}s, baseline "
                       f"{v['baseline']}s)")


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    check = "--check" in argv
    roofline_only = "--roofline" in argv
    root, journal_paths, eps = _REPO, None, trend.CEILING_EPS
    it = iter(argv)
    try:
        for a in it:
            if a == "--root":
                root = next(it)
            elif a == "--journal":
                journal_paths = [next(it)]
            elif a == "--eps":
                eps = float(next(it))
            elif a not in ("--check", "--roofline"):
                print(__doc__, file=sys.stderr)
                print(f"obs_report: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
    except StopIteration:
        # a flag without its value is a usage error (rc 2), never the
        # rc 1 the exit-code contract reserves for a real regression
        print(f"obs_report: {a} requires a value", file=sys.stderr)
        return 2
    except ValueError:
        # same contract for a malformed value (--eps abc)
        print(f"obs_report: {a} needs a numeric value", file=sys.stderr)
        return 2
    if journal_paths is None:
        found = sorted(
            glob.glob(os.path.join(root, "docs", "logs",
                                   "health_*.jsonl")),
            key=os.path.basename,
        )
        journal_paths = found[-1:] if found else []

    verdicts = trend.analyze_repo(root, eps=eps)
    bad = {
        n: v for n, v in verdicts.items()
        if v["verdict"] in ("regression", "impossible")
    }

    if check:
        for name, v in bad.items():
            print(f"{name}: {v['verdict']}")
            for flag in v["flags"]:
                print(f"  {flag}")
        below = {
            n: v for n, v in verdicts.items()
            if v["verdict"] == "below_roofline"
        }
        for name, v in below.items():
            # informational, never part of the rc — a kernel at 20% of
            # roofline is headroom to earn, not a regression to gate on
            print(f"{name}: below_roofline (non-gating)")
        # a CONFIRMED corruption gates like a regression: a wrong
        # answer is strictly worse than a slow one, and the guard
        # already refused to crash the run that detected it — this is
        # where it stops a queue from going green
        # (docs/RESILIENCE.md §output integrity)
        events, _bad_lines = _journal.load_events(journal_paths)
        corrupt = [e for e in events
                   if e.get("kind") == "output_integrity_failed"]
        for e in corrupt:
            print(
                f"output_integrity_failed: {e.get('kernel')} at "
                f"{e.get('site')} (tier {e.get('tier')}): "
                f"{e.get('detail')}"
            )
        # a CONFIRMED p99 breach gates like a regression: users feel
        # a degraded tail before the slope moves, and the validated
        # slo.json artifact is the evidence of record
        # (docs/OBSERVABILITY.md §latency SLOs). Degrade loudly but
        # judge only what validates — an unreadable artifact (e.g.
        # validation's lazy jax import failing on a journal-only
        # host) must not fake the rc 1 this contract reserves for
        # real findings, matching slo_section's tolerance.
        try:
            breaches = _slo.breaches()
        except Exception as e:  # noqa: BLE001 — gate what validates
            print(f"obs_report: slo verdicts unreadable, not judged "
                  f"({e!r})", file=sys.stderr)
            breaches = {}
        for key, e in sorted(breaches.items()):
            print(
                f"{key.split('|', 1)[0]}: slo_breach (p99 "
                f"{_slo.fmt_ms(e.get('p99_s'))} > target "
                f"{_slo.fmt_ms(e.get('target_p99_s'))} over "
                f"{e.get('count')} request(s), "
                f"{e.get('shape_class')} shapes on "
                f"{e.get('device_kind')})"
            )
        # a copied byte on the negotiated zero-copy serve path gates
        # like a regression: the whole point of the shm lane is that
        # steady-state serving copies NOTHING, and the budget is
        # machine-checked from the serve_copy_budget evidence
        # (docs/SERVING.md §copy accounting)
        copy_bad = {
            n: v for n, v in trend.analyze_copy_budget(events).items()
            if v["verdict"] == "copy_regression"
        }
        for name, v in copy_bad.items():
            print(f"{name}: copy_regression")
            for flag in v["flags"]:
                print(f"  {flag}")
        # an inconsistent request timeline gates like the copy
        # budget: phase sums past the client wall mean the trace
        # evidence itself is wrong, and every latency conclusion
        # drawn from it would be too (docs/OBSERVABILITY.md §request
        # tracing); low COVERAGE prints non-gating, the
        # below_roofline pattern
        trace_verdicts = trend.analyze_trace_budget(events)
        trace_bad = {
            n: v for n, v in trace_verdicts.items()
            if v["verdict"] == "trace_inconsistent"
        }
        for name, v in trace_bad.items():
            print(f"{name}: trace_inconsistent")
            for flag in v["flags"]:
                print(f"  {flag}")
        trace_low = {
            n: v for n, v in trace_verdicts.items()
            if v["verdict"] == "trace_coverage"
        }
        for name in trace_low:
            print(f"{name}: trace_coverage (non-gating)")
        # a promoted bucket table that stops delivering its measured
        # pad_frac gates like a regression too: the promotion was a
        # >3%-margin claim about live traffic, and the journal's
        # post-promotion serve_request evidence is the recount
        # (docs/SERVING.md §adaptive buckets)
        pad_bad = {
            n: v for n, v in trend.analyze_pad_waste(events).items()
            if v["verdict"] == "pad_waste_regression"
        }
        for name, v in pad_bad.items():
            print(f"{name}: pad_waste_regression")
            for flag in v["flags"]:
                print(f"  {flag}")
        # validated (non-fake) bus-bw scaling series gate exactly like
        # bench trends — the paper's multi-chip headline must not be
        # the one layer that can regress silently
        # (docs/OBSERVABILITY.md §scaling). Fake-device rehearsal
        # artifacts can only ever reach no_data here.
        scaling_analysis = trend.analyze_scaling_repo(root, eps=eps)
        scaling_bad = _scaling.gating_findings(scaling_analysis)
        for name, v in scaling_bad.items():
            print(f"{name}: {v['verdict']}")
            for flag in v["flags"]:
                print(f"  {flag}")
        below_eff = {
            n: v for n, v in scaling_analysis.get("weak", {}).items()
            if v["verdict"] == "below_scaling_efficiency"
        }
        for name in below_eff:
            # informational, never part of the rc — the below_roofline
            # pattern for the weak-scaling curve
            print(f"weak/{name}: below_scaling_efficiency (non-gating)")
        # a validated overlap point under the TPK_OVERLAP_MIN_FRAC
        # floor prints non-gating too — the depth pipeline not hiding
        # comm under compute is headroom to reclaim, not a broken
        # build (docs/DISTRIBUTED.md §overlap); the rc contract is
        # untouched
        overlap_low = {
            n: v
            for n, v in scaling_analysis.get("overlap", {}).items()
            if v["verdict"] == "overlap_low"
        }
        for name, v in overlap_low.items():
            print(f"{name}: overlap_low (non-gating)")
            for flag in v["flags"]:
                print(f"  {flag}")
        # multi-day tail drift off the rollup series prints as
        # information only: p99_creep is a long-horizon early warning
        # (docs/OBSERVABILITY.md §daily rollups), not a per-run
        # finding, so it never touches the rc — the below_roofline
        # pattern. Judge only what loads: an unreadable series (lazy
        # jax import on a journal-only host) must not fake findings.
        try:
            creep_series = _rollup.load_series()
        except Exception as e:  # noqa: BLE001 — gate what validates
            print(f"obs_report: rollup series unreadable, p99 creep "
                  f"not judged ({e!r})", file=sys.stderr)
            creep_series = []
        creeping = {
            n: v
            for n, v in trend.analyze_p99_creep(creep_series).items()
            if v["verdict"] == "p99_creep"
        }
        for name, v in sorted(creeping.items()):
            print(f"{name}: p99_creep (non-gating)")
            for flag in v["flags"]:
                print(f"  {flag}")
        ok = sum(1 for v in verdicts.values() if v["verdict"] == "ok")
        nodata = sum(
            1 for v in verdicts.values() if v["verdict"] == "no_data"
        )
        print(
            f"obs_report --check: {len(bad)} failing, {ok} ok, "
            f"{len(below)} below-roofline (non-gating), "
            f"{nodata} no-data (no-data is retryable, not a failure), "
            f"{len(corrupt)} confirmed output-integrity failure(s), "
            f"{len(breaches)} confirmed SLO breach(es), "
            f"{len(scaling_bad)} scaling regression(s), "
            f"{len(copy_bad)} copy-budget regression(s), "
            f"{len(pad_bad)} pad-waste regression(s), "
            f"{len(trace_bad)} trace inconsistenc(ies), "
            f"{len(trace_low)} trace-coverage (non-gating), "
            f"{len(below_eff)} below-scaling-efficiency (non-gating), "
            f"{len(overlap_low)} overlap-low (non-gating), "
            f"{len(creeping)} p99-creep (non-gating)"
        )
        return 1 if (bad or corrupt or breaches or scaling_bad
                     or copy_bad or pad_bad or trace_bad) else 0

    if roofline_only:
        out = []
        roofline_section(verdicts, out)
        print("\n".join(line for line in out if line))
        return 1 if bad else 0

    out = []
    events, _bad = _journal.load_events(journal_paths)
    scaling_analysis = trend.analyze_scaling_repo(root, eps=eps)
    scaling_bad = _scaling.gating_findings(scaling_analysis)
    copy_bad = {
        n: v for n, v in trend.analyze_copy_budget(events).items()
        if v["verdict"] == "copy_regression"
    }
    trace_bad = {
        n: v for n, v in trend.analyze_trace_budget(events).items()
        if v["verdict"] == "trace_inconsistent"
    }
    pad_bad = {
        n: v for n, v in trend.analyze_pad_waste(events).items()
        if v["verdict"] == "pad_waste_regression"
    }
    trend_section(verdicts, out)
    roofline_section(verdicts, out)
    span_section(events, out)
    step_section(events, out)
    aot_section(events, out)
    integrity_section(events, out)
    slo_section(out)
    scaling_section(scaling_analysis, out)
    copy_section(events, out)
    adapt_section(events, out)
    reqtrace_section(events, out)
    shapes_section(events, out)
    deadlines_section(events, out)
    metrics_section(events, out)
    rollup_section(out)
    out.append("")
    if bad or scaling_bad or copy_bad or pad_bad or trace_bad:
        out.append(
            "VERDICT: " + "; ".join(
                f"{n} {v['verdict']}"
                for n, v in {**bad, **scaling_bad, **copy_bad,
                             **pad_bad, **trace_bad}.items()
            )
        )
    else:
        out.append("VERDICT: trend clean (no regression, no "
                   "impossible value)")
    if journal_paths:
        out.append(
            "journal: " + ", ".join(
                os.path.relpath(p) for p in journal_paths
            )
        )
    print("\n".join(out))
    return 1 if (bad or scaling_bad or copy_bad or pad_bad
                 or trace_bad) else 0


if __name__ == "__main__":
    sys.exit(main())
