"""Promote captured on-chip evidence into BASELINE.json's "measured"
block (the self-regression gate's reference, bench.py vs_measured).

Usage:
    python tools/promote_baseline.py [--dry-run] [--allow-partial]

Reads the <24h union of docs/logs/bench_*.json artifacts via the SAME
scanner the union gate uses (bench._recent_captured_metrics — newest
artifact wins per metric, invalidated/null values never count), then
rewrites BASELINE.json "measured" and prints old->new lines for the
BASELINE.md table update.

Guard rails:
  - refuses a partial union unless --allow-partial: promoting 3 of 7
    metrics would leave the gate comparing fresh metrics against new
    medians and stale metrics against old ones from DIFFERENT
    sessions, hiding cross-session regressions; an allow-partial
    promotion records which metrics were NOT re-measured (and the
    date their kept values are from) so BASELINE.json cannot
    misrepresent their provenance;
  - refuses to lower a median by more than the regression tolerance
    (bench._REGRESSION_TOL): a capture that much below the median of
    record should fail the gate and be investigated, not silently
    become the new bar;
  - symmetrically, refuses to RAISE a median past its physical
    ceiling (BASELINE.json "ceilings") ever, or by more than
    _JUMP_TOL without --allow-jump: the 2026-07-31 drift-inflated
    sgemm captures (72.7/96.0 TFLOPS vs a ~61 ceiling) showed an
    inflated capture silently raising the bar would make every honest
    future capture fail the regression gate;
  - never runs unattended in tools/tpu_revalidate.sh — promotion is
    a deliberate act recorded in its own commit (BASELINE.json _note).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

# A median may rise this much without --allow-jump; more is treated as
# a suspect measurement (drift, estimator bug) until a human vouches a
# kernel change explains it. Ceilings are refused unconditionally.
_JUMP_TOL = 0.25


def promote(
    root=None,
    allow_partial=False,
    dry_run=False,
    today=None,
    allow_jump=False,
):
    """Returns (new_measured, lines) or raises SystemExit with reason."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    union = {
        name: value
        for name, (value, _path) in bench._recent_captured_metrics(
            root
        ).items()
    }
    names = [n for n, _fn in bench.BENCH_METRICS]
    missing = [n for n in names if n not in union]
    if missing and not allow_partial:
        raise SystemExit(
            f"promote_baseline: union is missing {missing} — capture a "
            "full set first, or pass --allow-partial to promote only "
            "the captured metrics (mixed-session medians)"
        )

    path = os.path.join(root, "BASELINE.json")
    with open(path) as f:
        baseline = json.load(f)
    measured = dict(baseline.get("measured") or {})
    ceilings = baseline.get("ceilings") or {}
    prev_on = measured.get("measured_on")

    lines = []
    for name in names:
        if name not in union:
            lines.append(f"  {name}: (not captured; keeping "
                         f"{measured.get(name)} from {prev_on})")
            continue
        old = measured.get(name)
        new = round(float(union[name]), 2)
        if (
            isinstance(old, (int, float))
            and old
            and new < old * (1.0 - bench._REGRESSION_TOL)
        ):
            raise SystemExit(
                f"promote_baseline: {name} captured {new} is more than "
                f"{bench._REGRESSION_TOL:.0%} below the median of record "
                f"{old} — that is a regression to investigate (the union "
                "gate should have failed), not a new baseline"
            )
        if bench._is_measurement(ceilings.get(name)) and new > ceilings[
            name
        ] * (1.0 + bench._CEILING_EPS):
            # same epsilon band bench.py's capture-time invalidation
            # uses: the sgemm ceiling sits 0.8% above the median of
            # record, so ordinary upward noise on an honest near-peak
            # capture must neither be invalidated nor refused here.
            # Past the band it is drift, never a speedup.
            raise SystemExit(
                f"promote_baseline: {name} captured {new} exceeds its "
                f"physical ceiling {ceilings[name]} by more than "
                f"{bench._CEILING_EPS:.0%} (BASELINE.json ceilings) — a "
                "drift-inflated measurement must be invalidated, never "
                "promoted (bench.py should already have refused to "
                "persist it)"
            )
        if (
            isinstance(old, (int, float))
            and old
            and new > old * (1.0 + _JUMP_TOL)
            and not allow_jump
        ):
            raise SystemExit(
                f"promote_baseline: {name} captured {new} is more than "
                f"{_JUMP_TOL:.0%} above the median of record {old} — a "
                "jump that size is more often a measurement artifact "
                "(the 2026-07-31 drift-inflated sgemm captures) than a "
                "real speedup, and promoting it would make honest "
                "future captures fail the gate; re-capture, or pass "
                "--allow-jump if a kernel change explains it"
            )
        measured[name] = new
        lines.append(f"  {name}: {old} -> {new}")

    if today is None:
        import datetime

        today = datetime.date.today().isoformat()
    measured["measured_on"] = today
    # provenance: measured_on now stamps only the re-measured metrics;
    # values an allow-partial promotion KEPT must say where they are
    # really from, or the date misrepresents a mixed-session table
    kept = sorted(n for n in names if n not in union)
    if kept:
        measured["_not_remeasured"] = (
            f"kept from {prev_on}, not re-measured on {today}: "
            + ", ".join(kept)
        )
    else:
        measured.pop("_not_remeasured", None)
    baseline["measured"] = measured
    if not dry_run:
        with open(path, "w") as f:
            json.dump(baseline, f, indent=1)
            f.write("\n")
    return measured, lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dry-run", action="store_true",
                    help="print the promotion without writing")
    ap.add_argument("--allow-partial", action="store_true",
                    help="promote an incomplete union (mixed-session "
                         "medians; see module docstring)")
    ap.add_argument("--allow-jump", action="store_true",
                    help="permit a median to rise by more than "
                         f"{_JUMP_TOL:.0%} (only when a kernel change "
                         "explains it; ceilings are still enforced)")
    args = ap.parse_args(argv)
    measured, lines = promote(
        allow_partial=args.allow_partial,
        dry_run=args.dry_run,
        allow_jump=args.allow_jump,
    )
    print("promote_baseline:"
          + (" (dry run)" if args.dry_run else "")
          + " measured medians"
          f" (measured_on={measured['measured_on']}):")
    for line in lines:
        print(line)
    if not args.dry_run:
        print("BASELINE.json updated — now update the BASELINE.md table "
              "rows to match and commit both.")


if __name__ == "__main__":
    main()
