# Per-day step-stamp library for the revalidation queue — SOURCED by
# tools/tpu_revalidate.sh and by tests/test_revalidate_stamps.py, so
# the stamp/resume logic the tests prove is the logic the queue runs.
#
# Contract (caller must set $stamp_dir and create it):
#   step_done NAME   -> success iff NAME completed today; always fails
#                       under TPK_REVALIDATE_FORCE=1 so a same-day
#                       code change can force a full re-run
#   stamp NAME       -> mark NAME complete for today (stamps are
#                       wall-clock-scoped per day, not git-aware — the
#                       same accepted tradeoff as the bench evidence
#                       window)
#   run_step NAME CMD [ARGS...]
#                    -> skip when stamped; otherwise run CMD and stamp
#                       ONLY on success. The caller runs under `set -e`
#                       (the queue is a gate), so a failing CMD aborts
#                       the queue BEFORE the stamp line — a failed step
#                       can never stamp, and the retry re-runs it.

step_done() {
  [ "${TPK_REVALIDATE_FORCE:-}" = "1" ] && return 1
  [ -e "$stamp_dir/$1_$(date +%Y-%m-%d).done" ]
}

stamp() {
  touch "$stamp_dir/$1_$(date +%Y-%m-%d).done"
}

run_step() {
  local _rs_name="$1"
  shift
  if step_done "$_rs_name"; then
    echo "revalidate: step '$_rs_name' already completed today - skipping"
    return 0
  fi
  "$@"
  stamp "$_rs_name"
}
