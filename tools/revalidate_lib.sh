# Per-day, GIT-AWARE step-stamp library for the revalidation queue —
# SOURCED by tests/test_revalidate_stamps.py and by any shell caller
# that needs the stamp contract. The python supervisor
# (tpukernels/resilience/supervisor.py stamp_fresh/write_stamp) reads
# and writes the SAME stamp files with the SAME semantics, so a queue
# half-run by either driver resumes under the other — the
# cross-equivalence is test-enforced (tests/test_supervisor.py).
#
# Contract (caller must set $stamp_dir and create it):
#   step_done NAME   -> success iff NAME completed today AND no commit
#                       since the stamp touched the step's inputs
#                       ($step_inputs, default "bench.py tools
#                       tpukernels c"); always fails under
#                       TPK_REVALIDATE_FORCE=1 (kept as the explicit
#                       manual override, no longer the only defense
#                       against the same-day-code-change footgun)
#   stamp NAME       -> mark NAME complete for today; the stamp file
#                       records the HEAD sha so a later commit can
#                       invalidate it. Outside git (or a pre-git-aware
#                       empty stamp) the stamp degrades to the old
#                       wall-clock-only behavior.
#   run_step NAME CMD [ARGS...]
#                    -> skip when stamped-and-fresh; otherwise run CMD
#                       and stamp ONLY on success. The caller runs
#                       under `set -e` (the queue is a gate), so a
#                       failing CMD aborts the queue BEFORE the stamp
#                       line — a failed step can never stamp, and the
#                       retry re-runs it.

step_done() {
  [ "${TPK_REVALIDATE_FORCE:-}" = "1" ] && return 1
  local _sd_file="$stamp_dir/$1_$(date +%Y-%m-%d).done"
  [ -e "$_sd_file" ] || return 1
  local _sd_sha
  _sd_sha=$(head -1 "$_sd_file" 2>/dev/null)
  # legacy (sha-less) stamp, or no git here: wall-clock-only, honored
  [ -n "$_sd_sha" ] || return 0
  local _sd_head
  _sd_head=$(git rev-parse HEAD 2>/dev/null) || return 0
  [ "$_sd_sha" = "$_sd_head" ] && return 0
  # commits landed since the stamp: stale iff one touched this step's
  # inputs. A git error (unknown sha after a history rewrite) means
  # "can't judge" -> re-run, the safe side.
  local _sd_touched
  _sd_touched=$(git log --format=%H "$_sd_sha..$_sd_head" -- \
      ${step_inputs:-bench.py tools tpukernels c} 2>/dev/null) \
    || { echo "revalidate: stamp for '$1' unjudgeable (git log failed) - re-running" >&2
         return 1; }
  if [ -n "$_sd_touched" ]; then
    echo "revalidate: stamp for '$1' predates commits touching" \
         "${step_inputs:-bench.py tools tpukernels c} - re-running" >&2
    return 1
  fi
  return 0
}

stamp() {
  git rev-parse HEAD 2>/dev/null > "$stamp_dir/$1_$(date +%Y-%m-%d).done" \
    || : > "$stamp_dir/$1_$(date +%Y-%m-%d).done"
}

run_step() {
  local _rs_name="$1"
  shift
  if step_done "$_rs_name"; then
    echo "revalidate: step '$_rs_name' already completed today - skipping"
    return 0
  fi
  "$@"
  stamp "$_rs_name"
}
