"""Autotune CLI: sweep a kernel's declarative search space and promote
the winner into the persistent tuning cache (docs/TUNING.md).

Usage (real sweep needs a healthy tunnel window — AFTER
tools/tpu_revalidate.sh has gone green; the queue owns the first chip
minutes):

    python tools/autotune.py --list                    # tunable kernels
    python tools/autotune.py --kernel sgemm            # full sweep
    python tools/autotune.py --kernel sgemm --quick    # 3 candidates
    python tools/autotune.py --kernel sgemm --smoke    # CPU interpret
                                                       # pipeline proof

Each candidate runs through the real metric path (`bench.py --one
<metric>` — slope method, median of samples, CPU-fallback refusal) in
a killable subprocess via the resilience watchdog, so one wedged
candidate costs TPK_TUNE_TIMEOUT_S and nothing more. The axes are no
longer block sizes alone: pipeline depth (sgemm/stencil3d), sgemm
grid order and scan_histogram fusion are ordinary sweep values
(docs/TUNING.md §surface). Candidates whose analytic VMEM need
exceeds the kernel's budget are pruned before any chip time is spent;
a promotion requires beating the shipped-default control row by >3%
on the bench medians (runner.PROMOTE_MARGIN).

--smoke runs the identical sweep/cache/journal machinery on CPU
interpret mode (TPK_BENCH_SMOKE collapses repeat counts; values are
meaningless and the entry is keyed device_kind=cpu so it can never
steer a TPU run) — the CI proof that the tuner's whole pipeline
works, wired non-gating into tools/tpu_revalidate.sh.

The PARENT process never touches the TPU tunnel: it scrubs its own env
to CPU before importing jax-bound modules and hands bench children the
ORIGINAL environment (or the smoke env under --smoke).

Exit codes: 0 = sweep ran (promoted or not); 2 = no candidate produced
a number (tunnel down / all wedged).
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="sweep a kernel's TUNABLES search space"
    )
    ap.add_argument("--kernel", help="registry kernel name (see --list)")
    ap.add_argument("--list", action="store_true",
                    help="list tunable kernels and exit")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU interpret-mode pipeline proof (CI)")
    ap.add_argument("--quick", action="store_true",
                    help="only the 3 most promising candidates")
    ap.add_argument("--max-candidates", type=int, default=None,
                    help="cap the sweep (default: smoke caps at 3)")
    ap.add_argument("--timeout-s", type=float, default=None,
                    help="per-candidate watchdog (default "
                         "TPK_TUNE_TIMEOUT_S, 420 real / 60 smoke)")
    args = ap.parse_args(argv)

    # children must see the environment as the operator launched it;
    # capture BEFORE the parent scrubs itself off the tunnel
    base_env = dict(os.environ)

    # resilience is stdlib-only: safe to import before the scrub. A
    # CLI sweep journals by default, one file per day, shared with its
    # bench children via env inheritance (same convention as bench.py).
    from tpukernels.resilience import journal

    os.environ.setdefault("TPK_HEALTH_JOURNAL", journal.default_path())
    base_env.setdefault(
        "TPK_HEALTH_JOURNAL", os.environ["TPK_HEALTH_JOURNAL"]
    )

    # parent-only scrub: TUNABLES live in kernel modules, which import
    # jax — on this box sitecustomize force-registers the axon TPU
    # backend unless the pool var is empty, and the parent holding the
    # tunnel open would serialize against its own bench children
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"

    from tpukernels import registry
    from tpukernels.tuning import runner

    if args.list:
        for name in registry.tunable_kernels():
            sp = registry.tunables(name)
            knobs = ", ".join(
                f"{t.name}({t.env})" for t in sp.tunables
            )
            print(f"{name:12s} metric={sp.metric}  knobs: {knobs}")
        return 0
    if not args.kernel:
        ap.error("--kernel is required (or --list)")

    summary = runner.tune(
        args.kernel,
        smoke=args.smoke,
        quick=args.quick,
        max_candidates=args.max_candidates,
        timeout_s=args.timeout_s,
        base_env=base_env,
        echo=lambda line: print(line, flush=True),
    )
    best, control = summary["best"], summary["control"]
    if best is None:
        print("no candidate produced a number - tunnel down/wedged?")
        return 2
    line = (
        "best: "
        + " ".join(f"{k}={v}" for k, v in best["params"].items())
        + f" at {best['value']:.2f} {summary['metric']}"
    )
    if control and control["value"]:
        line += f" ({best['value'] / control['value']:.3f}x of default)"
    print(line)
    if summary["promoted"] is not None:
        print(
            f"promoted -> {summary['cache_path']} "
            f"[{summary['cache_key']}]"
            + (" (smoke entry: pipeline proof, not a tuning claim)"
               if args.smoke else "")
        )
    else:
        print(
            "not promoted: best must beat the default control by "
            f">{runner.PROMOTE_MARGIN:.0%} on medians (docs/TUNING.md)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
