#!/bin/bash
# Tier-1 compare-by-failure-SET (ROADMAP.md "Tier-1 verify" note).
#
# The tier-1 suite always exits rc=1 in this container: ~50
# pre-existing failures come from jax pallas API drift and other
# environment facts, not from the change under review. Judging a
# change by the exit code therefore judges the ENVIRONMENT; the honest
# gate is the DIFF of failure sets — "no worse than seed" means no
# test fails now that passed in the seed log.
#
# Usage:
#   tools/t1_diff.sh <seed_t1.log> <current_t1.log>
#
# where each log is the raw `pytest -q` output (the ROADMAP tier-1
# command tees it to /tmp/_t1.log). Lines are matched by test id only
# (`FAILED path::test` / `ERROR path`) — the truncated reason text
# after " - " changes with line numbers and is ignored.
#
# Exit codes: 0 = no new failures (fixed tests are reported, never
# penalized); 1 = at least one NEW failure (listed); 2 = usage/IO.
set -u -o pipefail

if [ $# -ne 2 ] || [ ! -r "$1" ] || [ ! -r "$2" ]; then
  echo "usage: $0 <seed_t1.log> <current_t1.log> (readable files)" >&2
  exit 2
fi

seed_set=$(mktemp) || exit 2
cur_set=$(mktemp) || exit 2
trap 'rm -f "$seed_set" "$cur_set"' EXIT

# test id only: strip the " - <reason>" tail, dedupe, sort for comm
extract() {
  grep -aE '^(FAILED|ERROR) ' "$1" | sed 's/ - .*//' | sort -u
}
extract "$1" > "$seed_set"
extract "$2" > "$cur_set"

new=$(comm -13 "$seed_set" "$cur_set")
fixed=$(comm -23 "$seed_set" "$cur_set")

echo "seed failures:    $(wc -l < "$seed_set")"
echo "current failures: $(wc -l < "$cur_set")"
if [ -n "$fixed" ]; then
  echo "fixed since seed ($(echo "$fixed" | wc -l)):"
  echo "$fixed" | sed 's/^/  /'
fi
if [ -n "$new" ]; then
  echo "NEW failures ($(echo "$new" | wc -l)) - regression:"
  echo "$new" | sed 's/^/  /'
  exit 1
fi
echo "OK: no new failures vs seed"
exit 0
