"""Record / verify the output-integrity fingerprint envelopes.

Usage:
    python tools/integrity_envelopes.py --record [--kernels a,b]
    python tools/integrity_envelopes.py --check  [--kernels a,b]
    python tools/integrity_envelopes.py           # print the manifest

``--record`` runs every kernel's jnp ORACLE at its canary config and
persists the checksum/norm envelope into ``integrity.json``
(docs/RESILIENCE.md §output integrity) — the tier-2 reference the
dispatch-time guard and the AOT first-trust smoke check compare
against. Envelopes are defined as the CPU oracle's fingerprints, so
this tool pins ``JAX_PLATFORMS=cpu`` before jax loads (the
supervisor's daily ``integrity_envelopes`` step additionally scrubs
the axon env, which a sitecustomize-forced backend needs).

``--check`` runs each kernel's canary through the REAL kernel path
and compares against the recorded envelope (tier 2) — the manual
"do I trust this checkout's kernels right now" smoke. rc 0 = all
pass; rc 1 = a mismatch or a failed record; rc 2 = usage.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# the envelope authority is the CPU oracle: pin the backend BEFORE
# anything imports jax (a pre-set JAX_PLATFORMS choice wins — the
# operator may deliberately record TPU-side fingerprints for debug)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tpukernels.resilience import integrity  # noqa: E402


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    record = check = False
    names = None
    it = iter(argv)
    for a in it:
        if a == "--record":
            record = True
        elif a == "--check":
            check = True
        elif a == "--kernels":
            try:
                names = [n.strip() for n in next(it).split(",")
                         if n.strip()]
            except StopIteration:
                print("integrity_envelopes: --kernels needs a value",
                      file=sys.stderr)
                return 2
        else:
            print(__doc__, file=sys.stderr)
            print(f"integrity_envelopes: unknown argument {a!r}",
                  file=sys.stderr)
            return 2
    if names:
        unknown = [n for n in names if n not in integrity.CANARY_CONFIGS]
        if unknown:
            print(
                f"integrity_envelopes: unknown kernel(s) {unknown}; "
                f"known: {sorted(integrity.CANARY_CONFIGS)}",
                file=sys.stderr,
            )
            return 2
    if record and check:
        print("integrity_envelopes: pick ONE of --record/--check",
              file=sys.stderr)
        return 2

    if record:
        print(f"recording oracle envelopes -> {integrity.manifest_path()}")
        rows = integrity.record_all(names, echo=print)
        failed = [r["kernel"] for r in rows if "error" in r]
        print(
            f"integrity envelopes: {len(rows) - len(failed)} recorded, "
            f"{len(failed)} failed"
            + (f" ({','.join(failed)})" if failed else "")
        )
        return 1 if failed else 0

    if check:
        rc = 0
        for name in (names if names is not None
                     else sorted(integrity.CANARY_CONFIGS)):
            ran, failure = integrity.fingerprint_check(name)
            if not ran:
                print(f"  {name:<16} SKIP (no validated envelope - "
                      "run --record first)")
            elif failure:
                print(f"  {name:<16} FAIL: {failure}")
                rc = 1
            else:
                print(f"  {name:<16} ok")
        print("integrity check:", "FAILED" if rc else "OK")
        return rc

    # default: render the manifest
    data = integrity._read_json(integrity.manifest_path())
    entries = data.get("entries") or {}
    print(f"integrity envelope manifest: {integrity.manifest_path()} "
          f"({len(entries)} entr(ies))")
    for key in sorted(entries):
        ent = entries[key]
        print(f"  {key:<48} jax={ent.get('jax')} "
              f"recorded_on={ent.get('recorded_on')} "
              f"leaves={len(ent.get('fingerprints') or [])}")
    quar = integrity.quarantined_entries()
    if quar:
        print(f"quarantined today ({len(quar)}):")
        for key, ent in sorted(quar.items()):
            print(f"  {key}: {ent.get('failures')} failure(s) - "
                  f"{ent.get('last_detail')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
