#!/bin/bash
# Randomized-size C acceptance fuzz: every driver at random extents,
# the omp variant checked against the built-in serial oracle — the
# C-side analog of tests/test_fuzz_shapes.py, aimed at the remainder/
# edge paths fixed-size gate rows can't reach (off-tile M/N/K, tiny
# grids, odd bin counts).
#
#   tools/fuzz_c.sh [rounds]     # default 10 (~1 min)
#
# Reproducible: TPK_FUZZ_SEED seeds bash's RANDOM (default 42); a
# failure line prints the exact driver command to replay.
set -o pipefail
cd "$(dirname "$0")/../c"

rounds="${1:-10}"
if ! [ "$rounds" -ge 1 ] 2>/dev/null; then
  echo "fuzz_c: rounds must be >= 1 (got '${rounds}')" >&2
  exit 2
fi
if [ ! -x ./bin/vector_add ]; then
  echo "fuzz_c: drivers not built - run 'make -C c' first" >&2
  exit 2
fi
RANDOM=$((${TPK_FUZZ_SEED:-42}))

# bash RANDOM is 15-bit (max 32767); compose two draws so ranges past
# 32768 (vector_add, scan_histogram) are actually reachable
rnd() { echo $(( ((RANDOM << 15) | RANDOM) % $1 + 1 )); }

fail=0
run_check() {
  # keep the failing driver's own diagnostics (mismatch indices, max
  # err) — a replay command alone forces a second reproduce-run
  out=$(mktemp)
  if ! "$@" --device=omp --check --reps=1 >"$out" 2>&1; then
    echo "FUZZ FAIL: $* --device=omp --check"
    cat "$out"
    fail=1
  fi
  rm -f "$out"
}

for _ in $(seq 1 "$rounds"); do
  run_check ./bin/vector_add --n=$(rnd 200000)
  run_check ./bin/sgemm --m=$(rnd 317) \
      --n=$(rnd 317) --k=$(rnd 413)
  run_check ./bin/stencil --n=$(($(rnd 200) + 2)) \
      --m=$(($(rnd 200) + 2)) --iters=$(rnd 8)
  run_check ./bin/stencil --n=$(($(rnd 40) + 2)) \
      --m=$(($(rnd 60) + 2)) --z=$(($(rnd 40) + 2)) \
      --iters=$(rnd 5)
  run_check ./bin/scan_histogram --n=$(rnd 100000) \
      --nbins=$(rnd 300)
  run_check ./bin/nbody --n=$(rnd 400) \
      --iters=$(rnd 3)
done

if [ "$fail" = "1" ]; then
  echo "FUZZ: FAIL"
  exit 1
fi
echo "FUZZ: PASS ($rounds rounds x 6 drivers, seed ${TPK_FUZZ_SEED:-42})"
