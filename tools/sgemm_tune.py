"""DEPRECATED thin wrapper: the sgemm tile sweep now lives in the
autotuning subsystem (docs/TUNING.md).

    python tools/sgemm_tune.py [--quick]
        ==  python tools/autotune.py --kernel sgemm [--quick]

This entry point is kept so the revalidation docs (docs/NEXT.md,
BASELINE.md methodology notes) stay valid verbatim. Everything the old
one-off shell documented moved into the subsystem — and the sweep is
no longer block sizes alone: the sgemm space now also searches the
pipeline `depth` (1 = the BlockSpec path of record, 2/3 = the manual
ping-pong DMA variant, `TPK_SGEMM_DEPTH`) and the grid dimension
`order` (`TPK_SGEMM_ORDER` ij/ji), per docs/TUNING.md §surface:

- the grid rationale (bm 128/512 probes the A-reload vs
  accumulator-locality trade, bk 512 probes accumulator turnarounds,
  bn 1024 halves B residency, bk 2048 infeasible with bn 2048) is now
  `kernels/sgemm.py` TUNABLES — the sweep values plus the analytic
  32 MiB VMEM model (shared with the roofline byte arithmetic,
  docs/PERF.md §rooflines) that PRUNES the infeasible combos —
  including over-deep pipelines at wide tiles — instead of burning a
  remote-compile failure on them;
- the killable-subprocess-per-config discipline is
  `tpukernels/tuning/runner.py` on the resilience watchdog;
- the ">3% over the control before promoting" guidance is enforced in
  code (runner.PROMOTE_MARGIN) and the winner lands in the persistent
  tuning cache, where sgemm dispatch reads it per shape/dtype/device
  (precedence env > cache > default) — no more manual default edits
  after a confirming re-run.

Run it (like the old tool) only AFTER tools/tpu_revalidate.sh has gone
green — the queue owns the first chip minutes of any window.
"""

import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    print(
        "# sgemm_tune.py is deprecated: forwarding to "
        "`python tools/autotune.py --kernel sgemm` (docs/TUNING.md)",
        file=sys.stderr,
    )
    import autotune

    return autotune.main(["--kernel", "sgemm", *argv])


if __name__ == "__main__":
    sys.exit(main())
