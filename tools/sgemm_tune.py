"""One-command sgemm tile sweep for a healthy tunnel window.

Usage (AFTER tools/tpu_revalidate.sh has gone green — the queue owns
the first chip minutes of any window):

    python tools/sgemm_tune.py            # default grid, ~2 min/config
    python tools/sgemm_tune.py --quick    # 3 most promising configs

Each config runs in its own killable subprocess via the exact metric
path of record (`bench.py --one sgemm_gflops` — slope method, median
of samples, CPU-fallback refusal), with TPK_SGEMM_{BM,BN,BK}
overriding the tile PREFERENCES (kernels/sgemm.py _env_pref;
alignment and padding stay with _pick_block). A config whose
double-buffered VMEM need exceeds the 32 MiB budget fails at remote
compile — reported as a FAIL row, not a crash, so one bad candidate
can't eat the window.

Grid rationale (config of record is 1024^3, bf16_3x):
  - (256, 2048, 1024) is the shipped default = the control row;
  - bm 128/512 probes the A-reload vs accumulator-locality trade;
  - bk 512 probes whether 2 accumulator turnarounds beat 1 at looser
    VMEM pressure; bk 2048 is infeasible with bn 2048 (B hi+lo pair
    would double past the budget) so it is only paired with bn 1024;
  - bn 1024 halves B residency to make room for the bk/bm probes.
A config beating the control by >3% on this sweep's medians is worth
promoting to the default after a confirming re-run; update the
docstring arithmetic in _sgemm_padded when you do.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GRID = [
    (256, 2048, 1024),  # control: shipped default
    (128, 2048, 1024),
    (512, 2048, 1024),
    (256, 2048, 512),
    (256, 1024, 1024),
    (256, 1024, 2048),
    (512, 1024, 1024),
]
QUICK = GRID[:3]


def run_config(bm, bn, bk, timeout_s=420):
    env = dict(os.environ)
    env.update(
        TPK_SGEMM_BM=str(bm), TPK_SGEMM_BN=str(bn), TPK_SGEMM_BK=str(bk)
    )
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--one",
             "sgemm_gflops"],
            env=env, timeout=timeout_s, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
    except subprocess.TimeoutExpired:
        return None, "timeout (wedge?)"
    if r.returncode != 0:
        return None, f"rc={r.returncode} (compile fail / VMEM budget?)"
    try:
        return json.loads(r.stdout.strip().splitlines()[-1])["value"], "ok"
    except (ValueError, KeyError, IndexError):
        return None, "unparseable output"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="only the 3 most promising configs")
    args = ap.parse_args()
    grid = QUICK if args.quick else GRID

    rows = []
    control = None
    for bm, bn, bk in grid:
        value, status = run_config(bm, bn, bk)
        rows.append((bm, bn, bk, value, status))
        if (bm, bn, bk) == GRID[0] and value:
            control = value
        shown = f"{value:9.1f}" if value else f"    FAIL ({status})"
        print(f"bm={bm:4d} bn={bn:4d} bk={bk:4d}  {shown}", flush=True)

    best = max((r for r in rows if r[3]), key=lambda r: r[3], default=None)
    if best is None:
        print("no config produced a number - tunnel down/wedged?")
        sys.exit(2)
    bm, bn, bk, value, _ = best
    line = f"best: bm={bm} bn={bn} bk={bk} at {value:.1f} GFLOPS"
    if control:
        line += f" ({value / control:.3f}x of the shipped default)"
    print(line)


if __name__ == "__main__":
    main()
