"""Seeded chaos-campaign runner for the serving fleet
(docs/RESILIENCE.md §chaos campaigns).

Usage:
    python tools/chaos.py [--seed N] [--events K] [--workers N]

The single-fault chaos proofs live in the test suite (a kill here, a
wedge there, each against a fresh fleet). This runner composes them:
one SEEDED campaign drives a live fleet — router + guardian + N
workers under continuous client load — through K faults drawn
deterministically from the full vocabulary, asserting the survival
invariants after every single event:

- **no accepted-request drops** — every client dispatch either
  succeeds with a correct result or was honestly shed/throttled and
  retried to success; a hard failure fails the campaign.
- **goodput** — every load request carries a generous deadline
  (docs/SERVING.md §deadlines) and the campaign asserts 100% of
  completed requests met it after every event: a fault may slow the
  fleet, never silently starve a budget.
- **no duplicate dispatch** — after every event, any request_id with
  more than one ``serve_request`` record must carry the honest
  ``replayed`` marker (a WAL replay or a hedge riding the replay
  idempotency header); two unmarked records are a silent double
  dispatch.
- **convergence** — the fleet returns to all-members-live
  (``serve_ctl health``) within the recovery wait after each fault.
- **journal evidence** — every fault leaves its expected kinds
  (``router_dead``/``router_respawned`` after a router kill,
  ``worker_dead``/``worker_respawned`` after a worker kill,
  ``artifact_rejected`` after a torn artifact, ``fault_injected``
  for in-process injections), plus one ``chaos_event`` marker per
  event so the timeline is self-describing.
- **no leaks** — after teardown: no surviving fleet pids, no
  ``tpkserve-*`` shm segments, no flocked pidfiles.
- **observability stays green** — ``obs_report --check`` exits 0
  over the campaign's artifact root.

Event vocabulary (drawn per-seed): ``kill_router`` (SIGKILL the
router from its pidfile — the guardian + WAL recovery path),
``kill_worker`` (SIGKILL a random worker — the health-manager
respawn path), ``torn_write`` (tear a persisted JSON artifact in
place, byte-for-byte half a valid payload — the pre-atomic crash
shape every reader must reject loudly and rebuild), and
``wedge_dispatch`` (armed at fleet start via ``TPK_FAULT_PLAN`` with
a ``once_file``, worker 0 wedges one dispatch mid-campaign — the
watchdog + requeue path; scheduled at most once per campaign), and
``delay_response`` (armed the same way: worker 0 holds one completed
scan response on the floor — the slow-but-alive worker the deadline/
hedging layer exists for; the event observes the ``fault_injected``
``site=response`` evidence and the goodput + duplicate-dispatch
invariants hold through it; at most once per campaign).

Same seed, same schedule, same request ids: a failing campaign
replays exactly. Exit 0 = every invariant held after every event;
1 = a violation (printed); 2 = usage error.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels import _cachedir  # noqa: E402
from tpukernels.resilience import journal  # noqa: E402
from tpukernels.serve import client as serve_client  # noqa: E402
from tpukernels.serve import fleet as serve_fleet  # noqa: E402
from tpukernels.serve import health as serve_health  # noqa: E402
from tpukernels.serve import protocol as serve_protocol  # noqa: E402

# wedge_dispatch is armed once at fleet start (fault plans load at
# import); every other event is an external action this runner takes
EVENTS = ("kill_router", "kill_worker", "torn_write")

RECOVER_WAIT_S = 120.0


class CampaignFailure(Exception):
    pass


def _ctl(*args):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "serve_ctl.py"),
         *args],
        cwd=_REPO, capture_output=True, text=True,
    )


def _journal_events():
    path = journal.path() or journal.default_path()
    evs = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    evs.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return evs


def _wait_for(pred, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.25)
    raise CampaignFailure(f"timed out waiting for {what}")


class _Load:
    """Continuous seeded client load (threads in this process): scan
    dispatches with correctness checks, riding the full backpressure +
    reconnect-budget policy. ``failures`` is the campaign's
    zero-drops invariant."""

    def __init__(self, front: str, seed: int, clients: int = 3):
        self.front = front
        self.seed = seed
        self.clients = clients
        self.ok = 0
        # every request carries a deadline generous enough to ride
        # out a router respawn (reconnect budget 60 s); met counts
        # completions within it — the campaign's goodput invariant
        self.deadline_ms = 90_000.0
        self.met = 0
        self.failures: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list = []

    def start(self):
        for i in range(self.clients):
            t = threading.Thread(target=self._run, args=(i,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=180)

    def _run(self, tid: int):
        import numpy as np

        rng = random.Random(self.seed * 1000 + tid)
        seq = 0
        with serve_client.ServeClient(
            self.front, timeout_s=120, tenant=f"chaos{tid}",
        ) as cli:
            while not self._stop.is_set():
                seq += 1
                n = rng.choice((64, 128, 256))
                x = (np.arange(n) % 7).astype(np.int32)
                want = np.cumsum(x, dtype=np.int64).astype(np.int32)
                cli.next_request_id = f"chaos-{self.seed}-{tid}-{seq}"
                cli.next_deadline_ms = self.deadline_ms
                d0 = time.perf_counter()
                try:
                    out = serve_client.dispatch_with_backpressure(
                        cli, "scan", (x,), {}, jitter=rng)
                except Exception as e:
                    with self._lock:
                        self.failures.append(
                            (cli.next_request_id
                             or f"chaos-{self.seed}-{tid}-{seq}",
                             repr(e)))
                    return  # one drop already fails the campaign
                if not np.array_equal(out, want):
                    with self._lock:
                        self.failures.append(
                            (cli.last_request_id, "WRONG RESULT"))
                    return
                wall = time.perf_counter() - d0
                with self._lock:
                    self.ok += 1
                    if wall * 1000.0 <= self.deadline_ms:
                        self.met += 1
                time.sleep(0.05 + rng.random() * 0.1)


# ------------------------------------------------------------------ #
# events                                                             #
# ------------------------------------------------------------------ #


def _kill_from_pidfile(pidfile: str, what: str) -> int:
    held, pid = serve_health.pidfile_state(pidfile)
    if not held or pid is None:
        raise CampaignFailure(
            f"cannot kill {what}: pidfile {pidfile} not live-flocked")
    os.kill(pid, signal.SIGKILL)
    return pid


def _do_kill_router(rng, counts):
    before = sum(1 for e in _journal_events()
                 if e.get("kind") == "router_respawned")
    pid = _kill_from_pidfile(serve_fleet.router_pidfile_path(),
                             "router")
    _wait_for(
        lambda: sum(1 for e in _journal_events()
                    if e.get("kind") == "router_respawned") > before,
        RECOVER_WAIT_S, "router_respawned after kill_router")
    return {"killed_pid": pid}


def _do_kill_worker(rng, counts):
    cfg = serve_fleet.load_config() or {}
    idx = rng.randrange(len(cfg.get("workers") or [1]))
    before = sum(1 for e in _journal_events()
                 if e.get("kind") == "worker_respawned"
                 and e.get("worker") == idx)
    pid = _kill_from_pidfile(
        os.path.join(serve_fleet.worker_dir(idx), "serve.pid"),
        f"worker{idx}")
    _wait_for(
        lambda: sum(1 for e in _journal_events()
                    if e.get("kind") == "worker_respawned"
                    and e.get("worker") == idx) > before,
        RECOVER_WAIT_S, f"worker_respawned({idx}) after kill_worker")
    return {"worker": idx, "killed_pid": pid}


def _do_torn_write(rng, counts):
    """Tear a persisted artifact IN PLACE (the pre-atomic crash
    shape: half a valid JSON payload, no closing brace) and assert
    the next reader rejects it loudly instead of trusting it."""
    path = _cachedir.tuning_cache_path()
    payload = json.dumps(
        {"scan": {"torn-probe": {"best": {"knob": 1}}}}, indent=1)
    with open(path, "w") as f:
        f.write(payload[: len(payload) // 2])
    before = sum(1 for e in _journal_events()
                 if e.get("kind") == "artifact_rejected")
    probe = subprocess.run(
        [sys.executable, "-c",
         "from tpukernels.tuning import cache; "
         "print(sorted(cache._load(cache.path())))"],
        cwd=_REPO, capture_output=True, text=True,
    )
    if probe.returncode != 0:
        raise CampaignFailure(
            f"torn-artifact reader crashed: {probe.stderr}")
    if "torn artifact rejected" not in probe.stderr:
        raise CampaignFailure(
            "torn tuning.json read silently (no stderr rejection)")
    _wait_for(
        lambda: sum(1 for e in _journal_events()
                    if e.get("kind") == "artifact_rejected") > before,
        10.0, "artifact_rejected after torn_write")
    os.unlink(path)  # rebuildable cache: clean slate, like a reaper
    return {"path": path}


def _delay_response_armed(rng, counts):
    """delay_response is armed via TPK_FAULT_PLAN at fleet start
    (worker 0 holds one completed scan response on the floor); the
    'event' observes that it FIRED — the per-event goodput and
    duplicate-dispatch invariants then prove the fleet absorbed the
    slow-but-alive worker honestly."""
    _wait_for(
        lambda: any(e.get("kind") == "fault_injected"
                    and e.get("site") == "response"
                    for e in _journal_events()),
        RECOVER_WAIT_S, "armed delay_response to fire")
    return {}


def _wedge_armed(once_file: str):
    """wedge_dispatch is armed via TPK_FAULT_PLAN at fleet start; the
    'event' is simply observing that it FIRED (once_file exists) and
    the watchdog abandoned + requeued around it."""
    def check(rng, counts):
        _wait_for(lambda: os.path.exists(once_file),
                  RECOVER_WAIT_S, "armed wedge_dispatch to fire")
        _wait_for(
            lambda: any(e.get("kind") == "serve_request_requeued"
                        for e in _journal_events()),
            RECOVER_WAIT_S, "serve_request_requeued after wedge")
        return {"once_file": once_file}
    return check


# ------------------------------------------------------------------ #
# invariants                                                         #
# ------------------------------------------------------------------ #


def _assert_converged():
    r = _ctl("health", "--wait", str(int(RECOVER_WAIT_S)))
    if r.returncode != 0:
        raise CampaignFailure(
            f"fleet did not converge: {r.stdout}{r.stderr}")


def _assert_artifacts_readable():
    """Every persisted artifact either parses or is absent — a torn
    file SURVIVING an event is an atomic-write regression."""
    paths = [serve_fleet.config_path(),
             _cachedir.tuning_cache_path(),
             _cachedir.aot_manifest_path()]
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p) as f:
            try:
                json.load(f)
            except ValueError as e:
                raise CampaignFailure(
                    f"artifact {p} is torn after recovery: {e}")


def _assert_no_duplicate_dispatch():
    """At-most-once dispatch per request_id: a request_id may appear
    on more than one serve_request record only via the honest replay
    markers — a WAL replay or a hedge rides the replay idempotency
    header and journals ``replayed=True``. Two UNMARKED records for
    one id mean the same work ran twice silently."""
    by_id: dict = {}
    for e in _journal_events():
        if e.get("kind") != "serve_request" \
                or not e.get("request_id"):
            continue
        by_id.setdefault(e["request_id"], []).append(e)
    for rid, evs in sorted(by_id.items()):
        plain = [e for e in evs if not e.get("replayed")]
        if len(plain) > 1:
            raise CampaignFailure(
                f"request {rid} dispatched {len(plain)} time(s) with "
                "no replay/hedge marker (silent duplicate dispatch)")


def _assert_goodput(load):
    with load._lock:
        ok, met = load.ok, load.met
    if met < ok:
        raise CampaignFailure(
            f"goodput violated: only {met}/{ok} completed request(s) "
            f"met the {load.deadline_ms:.0f}ms deadline")


def _assert_no_leaks(n_workers: int):
    leaked = [f for f in os.listdir(serve_protocol.SHM_DIR)
              if serve_protocol._SHM_NAME_RE.match(f)]
    if leaked:
        raise CampaignFailure(f"leaked shm segments: {leaked}")
    pidfiles = [serve_fleet.guardian_pidfile_path(),
                serve_fleet.router_pidfile_path()] + [
        os.path.join(serve_fleet.worker_dir(i), "serve.pid")
        for i in range(n_workers)]
    for p in pidfiles:
        held, pid = serve_health.pidfile_state(p)
        if held:
            raise CampaignFailure(
                f"leaked process: pid {pid} still flocks {p}")


# ------------------------------------------------------------------ #
# the campaign                                                       #
# ------------------------------------------------------------------ #


def run_campaign(seed: int, n_events: int, n_workers: int) -> int:
    rng = random.Random(seed)
    schedule = [EVENTS[rng.randrange(len(EVENTS))]
                for _ in range(n_events)]
    # at most one armed wedge and one armed delay_response per
    # campaign: splice them over non-router slots when the seed
    # allows (plans load at import, so both must be decided before
    # the fleet starts)
    wedge_slot = delay_slot = None
    for i, ev in enumerate(schedule):
        if ev == "kill_router":
            continue
        if wedge_slot is None:
            wedge_slot = i
        elif delay_slot is None:
            delay_slot = i
            break
    once_file = os.path.join(serve_fleet.fleet_dir(), "wedge.once")
    plan: dict = {}
    if wedge_slot is not None:
        schedule[wedge_slot] = "wedge_dispatch"
        plan["wedge_dispatch"] = {
            "kernel": "scan", "times": 1, "once_file": once_file,
            "env": {"TPK_SERVE_WORKER_ID": "0"}}
    if delay_slot is not None:
        schedule[delay_slot] = "delay_response"
        plan["delay_response"] = {
            "kernel": "scan", "delay_s": 2.0, "times": 1,
            "env": {"TPK_SERVE_WORKER_ID": "0"}}
    if plan:
        os.makedirs(serve_fleet.fleet_dir(), exist_ok=True)
        os.environ["TPK_FAULT_PLAN"] = json.dumps(plan)
    print(f"# chaos: seed {seed}, schedule: {', '.join(schedule)}",
          file=sys.stderr)

    # compress the worker watchdog: a wedged request is abandoned at
    # ~3x this (1.5x grace, doubled once by the slow-verdict
    # extension — the CPU backend stays live under a thread wedge),
    # and the production default would outrun RECOVER_WAIT_S
    os.environ.setdefault("TPK_SERVE_REQUEST_TIMEOUT_S", "10")
    # the load clients must outlast a router death end-to-end:
    # detect (flock probe) + backoff + respawn + smoke-gated rejoin
    # routinely beats the 5 s default reconnect budget
    os.environ.setdefault("TPK_CLIENT_RECONNECT_S", "60")
    # the campaign's evidence IS the journal: with routing unset,
    # emits are no-ops fleet-wide and every wait below starves
    os.makedirs(serve_fleet.fleet_dir(), exist_ok=True)
    os.environ.setdefault(
        "TPK_HEALTH_JOURNAL",
        os.path.join(serve_fleet.fleet_dir(), "chaos_journal.jsonl"))
    r = _ctl("start-fleet", str(n_workers), "--wait", "120")
    if r.returncode != 0:
        print(f"chaos: start-fleet failed: {r.stdout}{r.stderr}",
              file=sys.stderr)
        return 1
    r = _ctl("guardian", "--wait", "30")
    if r.returncode != 0:
        print(f"chaos: guardian failed: {r.stdout}{r.stderr}",
              file=sys.stderr)
        _ctl("stop-fleet", "--wait", "60")
        return 1

    front = (serve_fleet.load_config() or {}).get("front")
    load = _Load(front, seed)
    handlers = {"kill_router": _do_kill_router,
                "kill_worker": _do_kill_worker,
                "torn_write": _do_torn_write,
                "wedge_dispatch": _wedge_armed(once_file),
                "delay_response": _delay_response_armed}
    rc = 0
    try:
        load.start()
        time.sleep(1.0)  # traffic flowing before the first fault
        counts: dict = {}
        for i, ev in enumerate(schedule):
            print(f"# chaos: event {i + 1}/{len(schedule)}: {ev}",
                  file=sys.stderr)
            detail = handlers[ev](rng, counts)
            _assert_converged()
            _assert_artifacts_readable()
            _assert_no_duplicate_dispatch()
            _assert_goodput(load)
            if load.failures:
                raise CampaignFailure(
                    f"client drops after {ev}: {load.failures}")
            journal.emit("chaos_event", event=ev, seq=i + 1,
                         of=len(schedule), seed=seed, **detail)
            time.sleep(0.5 + rng.random())  # settle, seeded
    except CampaignFailure as e:
        print(f"chaos: INVARIANT VIOLATED: {e}", file=sys.stderr)
        rc = 1
    finally:
        load.stop()
        stop = _ctl("stop-fleet", "--wait", "60")
        if stop.returncode != 0 and rc == 0:
            print(f"chaos: teardown failed: {stop.stdout}"
                  f"{stop.stderr}", file=sys.stderr)
            rc = 1

    if load.failures and rc == 0:
        print(f"chaos: client drops: {load.failures}", file=sys.stderr)
        rc = 1
    try:
        _assert_no_leaks(n_workers)
    except CampaignFailure as e:
        print(f"chaos: {e}", file=sys.stderr)
        rc = 1
    check = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "obs_report.py"), "--check"],
        cwd=_REPO, capture_output=True, text=True,
    )
    if check.returncode != 0:
        print(f"chaos: obs_report --check failed:\n{check.stdout}"
              f"{check.stderr}", file=sys.stderr)
        rc = 1
    verdict = "SURVIVED" if rc == 0 else "FAILED"
    print(f"chaos: campaign {verdict} - seed {seed}, "
          f"{len(schedule)} event(s), {load.ok} request(s) ok "
          f"({load.met} within deadline), "
          f"{len(load.failures)} dropped")
    return rc


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    seed, n_events, n_workers = 0, 6, 2
    it = iter(argv)
    try:
        for a in it:
            if a == "--seed":
                seed = int(next(it))
            elif a == "--events":
                n_events = int(next(it))
            elif a == "--workers":
                n_workers = int(next(it))
            elif a in ("-h", "--help"):
                print(__doc__, file=sys.stderr)
                return 0
            else:
                print(__doc__, file=sys.stderr)
                print(f"chaos: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
    except (StopIteration, ValueError):
        print(f"chaos: {a} needs an integer value", file=sys.stderr)
        return 2
    if n_events < 1 or n_workers < 2:
        print("chaos: need --events >= 1 and --workers >= 2 (ring "
              "failover requires a sibling)", file=sys.stderr)
        return 2
    return run_campaign(seed, n_events, n_workers)


if __name__ == "__main__":
    sys.exit(main())
