"""Summarize a resilience health journal into a session narrative.

Usage:
    python tools/health_report.py [journal.jsonl ...]

With no arguments, reads the newest docs/logs/health_*.jsonl. The
journal (tpukernels/resilience/journal.py, schema in
docs/RESILIENCE.md) records every probe outcome, watchdog fire,
slow-vs-wedged classification, partial-result decision, invalidation,
evidence rejection and injected fault; this report reconstructs what a
flapping session DID — which metrics were banked before the wedge,
what the watchdogs killed, what the gate rejected and why — from the
journal alone, replacing grep-the-stderr postmortems.
"""

from __future__ import annotations

import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load(paths):
    """Parse events from JSONL files, in file order then line order.
    Unparseable lines are counted, not fatal — a journal truncated by
    a crash is exactly when a postmortem is needed most."""
    events, bad = [], 0
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if isinstance(rec, dict):
                    events.append(rec)
    return events, bad


def _fmt(ev):
    """One narrative line per notable event; None for kinds the
    narrative summarizes only in aggregate."""
    ts = ev.get("ts", "?")
    kind = ev.get("kind")
    pid = ev.get("pid", "?")
    if kind == "run_start":
        return (f"{ts} [pid {pid}] bench run started "
                f"(deadline {ev.get('deadline_s')}s"
                + (", FAULT PLAN ACTIVE" if ev.get("fault_plan_active")
                   else "") + ")")
    if kind == "run_end":
        if ev.get("outcome") == "unreachable":
            return f"{ts} [pid {pid}] run ended: tunnel unreachable"
        parts = [f"{ts} [pid {pid}] run ended: {ev.get('outcome')}"]
        for key in ("measured", "failed", "invalidated", "carried"):
            vals = ev.get(key)
            if vals:
                parts.append(f"{key}={','.join(vals)}")
        return " ".join(parts)
    if kind == "probe":
        src = " (injected)" if ev.get("injected") else ""
        return (f"{ts} [pid {pid}] probe attempt {ev.get('attempt')}: "
                f"{ev.get('outcome')}{src}")
    if kind == "watchdog_fire":
        return (f"{ts} [pid {pid}] WATCHDOG FIRED "
                f"({ev.get('mechanism')}) at {ev.get('site')} after "
                f"{ev.get('timeout_s')}s")
    if kind == "wedge_classification":
        return (f"{ts} [pid {pid}] timeout on "
                f"{ev.get('metric', '?')} classified "
                f"{str(ev.get('verdict', '?')).upper()}"
                + (" - skipping remaining metrics"
                   if ev.get("verdict") == "wedged" else
                   " - tunnel still answers, continuing"))
    if kind == "partial_result":
        return (f"{ts} [pid {pid}] partial result: "
                f"{ev.get('metric')} {ev.get('reason')}")
    if kind == "metric_failed":
        return (f"{ts} [pid {pid}] metric {ev.get('metric')} FAILED "
                f"({ev.get('status')})")
    if kind == "deadline_reached":
        return (f"{ts} [pid {pid}] whole-run deadline reached before "
                f"{ev.get('before_metric')}")
    if kind == "invalidated":
        return (f"{ts} [pid {pid}] INVALIDATED {ev.get('metric')}="
                f"{ev.get('value')} (> ceiling {ev.get('ceiling')} "
                f"+{ev.get('epsilon')})")
    if kind == "epoch_rejected":
        return (f"{ts} [pid {pid}] evidence epoch-rejected: "
                f"{ev.get('metric')} from {ev.get('artifact')} "
                f"(predates commit ts {ev.get('blocking_commit_ts')})")
    if kind == "fault_injected":
        return (f"{ts} [pid {pid}] fault injected at "
                f"{ev.get('site')}: "
                + ", ".join(f"{k}={v}" for k, v in sorted(ev.items())
                            if k not in ("ts", "t", "pid", "git_head",
                                         "kind", "site")))
    if kind == "import_failure":
        return (f"{ts} [pid {pid}] kernel import FAILED for "
                f"{','.join(ev.get('kernels', []))}: {ev.get('error')}")
    if kind == "capi_error":
        return (f"{ts} [pid {pid}] C-shim dispatch error for "
                f"{ev.get('kernel')}: {ev.get('error')}")
    if kind == "skip_captured":
        return (f"{ts} [pid {pid}] skip-captured: carrying "
                f"{','.join(ev.get('carried', []))}; measuring "
                f"{','.join(ev.get('measuring', []))}")
    if kind == "metrics_restricted":
        return (f"{ts} [pid {pid}] TPK_BENCH_ONLY restricts run to "
                f"{','.join(ev.get('only', []))}")
    return f"{ts} [pid {pid}] {kind}"


def summarize(events, bad=0) -> str:
    out = []
    events = sorted(events, key=lambda e: e.get("t", 0.0))
    heads = {e.get("git_head") for e in events if e.get("git_head")}
    out.append(
        f"health report: {len(events)} events"
        + (f", {bad} unparseable lines" if bad else "")
        + (f", git {'/'.join(sorted(h[:12] for h in heads))}"
           if heads else "")
    )
    out.append("-" * 60)
    for ev in events:
        line = _fmt(ev)
        if line:
            out.append(line)
    out.append("-" * 60)
    counts = {}
    for ev in events:
        counts[ev.get("kind")] = counts.get(ev.get("kind"), 0) + 1
    wedges = sum(
        1 for e in events
        if e.get("kind") == "wedge_classification"
        and e.get("verdict") == "wedged"
    )
    fires = counts.get("watchdog_fire", 0)
    out.append(
        "totals: "
        + ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    )
    out.append(
        f"verdict: {wedges} wedge(s), {fires} watchdog fire(s), "
        f"{counts.get('partial_result', 0)} partial-result decision(s), "
        f"{counts.get('fault_injected', 0)} injected fault(s)"
    )
    return "\n".join(out)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    paths = argv
    if not paths:
        found = sorted(
            glob.glob(os.path.join(_REPO, "docs", "logs",
                                   "health_*.jsonl")),
            key=os.path.basename,
        )
        if not found:
            print("health_report: no docs/logs/health_*.jsonl found",
                  file=sys.stderr)
            return 1
        paths = [found[-1]]
    events, bad = load(paths)
    print(f"health_report: {', '.join(os.path.relpath(p) for p in paths)}")
    print(summarize(events, bad))
    return 0


if __name__ == "__main__":
    sys.exit(main())
