"""Summarize a resilience health journal into a session narrative.

Usage:
    python tools/health_report.py [journal.jsonl ...]

With no arguments, reads the newest docs/logs/health_*.jsonl. The
journal (tpukernels/resilience/journal.py, schema in
docs/RESILIENCE.md; kind catalog in docs/OBSERVABILITY.md) records
every probe outcome, watchdog fire, slow-vs-wedged classification,
partial-result decision, invalidation, evidence rejection, injected
fault, tuning decision, span and metrics snapshot; this report
reconstructs what a flapping session DID — which metrics were banked
before the wedge, what the watchdogs killed, what the gate rejected
and why, where the wall time went (per-phase span breakdown), which
SLO probes ran and whether any p99 breached — from the journal
alone, replacing grep-the-stderr postmortems.

Exit codes: 0 — report rendered (its findings, including SLO
breaches, are narrative: gating belongs to ``tools/obs_report.py
--check``); 1 — no journal found.
"""

from __future__ import annotations

import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels.obs import metrics as _metrics  # noqa: E402
from tpukernels.obs import slo as _slo  # noqa: E402
from tpukernels.obs import trace as _trace  # noqa: E402
from tpukernels.resilience import journal as _journal  # noqa: E402


def load(paths):
    """Parse events from JSONL files, in file order then line order.
    Thin alias of journal.load_events (the shared tolerant loader) —
    kept for callers/tests that import this module."""
    return _journal.load_events(paths)


def _fmt(ev):
    """One narrative line per notable event; None for kinds the
    narrative summarizes only in aggregate."""
    ts = ev.get("ts", "?")
    kind = ev.get("kind")
    pid = ev.get("pid", "?")
    if kind == "run_start":
        return (f"{ts} [pid {pid}] bench run started "
                f"(deadline {ev.get('deadline_s')}s"
                + (", FAULT PLAN ACTIVE" if ev.get("fault_plan_active")
                   else "") + ")")
    if kind == "run_end":
        if ev.get("outcome") == "unreachable":
            return f"{ts} [pid {pid}] run ended: tunnel unreachable"
        parts = [f"{ts} [pid {pid}] run ended: {ev.get('outcome')}"]
        for key in ("measured", "failed", "invalidated", "carried"):
            vals = ev.get(key)
            if vals:
                parts.append(f"{key}={','.join(vals)}")
        return " ".join(parts)
    if kind == "probe":
        src = " (injected)" if ev.get("injected") else ""
        return (f"{ts} [pid {pid}] probe attempt {ev.get('attempt')}: "
                f"{ev.get('outcome')}{src}")
    if kind == "probe_failed":
        return (f"{ts} [pid {pid}] {ev.get('label', 'probe')} FAILED "
                f"(attempt {ev.get('attempt')}/{ev.get('attempts')}, "
                f"backoff {ev.get('backoff_s')}s)")
    if kind == "watchdog_fire":
        return (f"{ts} [pid {pid}] WATCHDOG FIRED "
                f"({ev.get('mechanism')}) at {ev.get('site')} after "
                f"{ev.get('timeout_s')}s")
    if kind == "wedge_classification":
        return (f"{ts} [pid {pid}] timeout on "
                f"{ev.get('metric') or ev.get('step') or '?'} classified "
                f"{str(ev.get('verdict', '?')).upper()}"
                + (" - skipping remaining metrics"
                   if ev.get("verdict") == "wedged" else
                   " - tunnel still answers, continuing"))
    if kind == "partial_result":
        return (f"{ts} [pid {pid}] partial result: "
                f"{ev.get('metric')} {ev.get('reason')}")
    if kind == "metric_failed":
        return (f"{ts} [pid {pid}] metric {ev.get('metric')} FAILED "
                f"({ev.get('status')})")
    if kind == "deadline_reached":
        return (f"{ts} [pid {pid}] whole-run deadline reached before "
                f"{ev.get('before_metric')}")
    if kind == "invalidated":
        return (f"{ts} [pid {pid}] INVALIDATED {ev.get('metric')}="
                f"{ev.get('value')} (> ceiling {ev.get('ceiling')} "
                f"+{ev.get('epsilon')})")
    if kind == "epoch_rejected":
        return (f"{ts} [pid {pid}] evidence epoch-rejected: "
                f"{ev.get('metric')} from {ev.get('artifact')} "
                f"(predates commit ts {ev.get('blocking_commit_ts')})")
    if kind == "fault_injected":
        return (f"{ts} [pid {pid}] fault injected at "
                f"{ev.get('site')}: "
                + ", ".join(f"{k}={v}" for k, v in sorted(ev.items())
                            if k not in ("ts", "t", "pid", "git_head",
                                         "kind", "site")))
    if kind == "import_failure":
        return (f"{ts} [pid {pid}] kernel import FAILED for "
                f"{','.join(ev.get('kernels', []))}: {ev.get('error')}")
    if kind == "capi_error":
        return (f"{ts} [pid {pid}] C-shim dispatch error for "
                f"{ev.get('kernel')}: {ev.get('error')}")
    if kind == "skip_captured":
        return (f"{ts} [pid {pid}] skip-captured: carrying "
                f"{','.join(ev.get('carried', []))}; measuring "
                f"{','.join(ev.get('measuring', []))}")
    if kind == "metrics_restricted":
        return (f"{ts} [pid {pid}] TPK_BENCH_ONLY restricts run to "
                f"{','.join(ev.get('only', []))}")
    if kind == "span":
        # spans are high-volume; the narrative stays readable because
        # they render only in the aggregate breakdown (_span_breakdown)
        return None
    if kind == "metrics":
        snap = ev.get("counters") or {}
        return (f"{ts} [pid {pid}] final metrics snapshot "
                f"({ev.get('site')}): {len(snap)} counter(s), "
                f"{len(ev.get('gauges') or {})} gauge(s), "
                f"{len(ev.get('histograms') or {})} histogram(s)")
    if kind == "metrics_snapshot":
        # periodic flusher stream (docs/OBSERVABILITY.md §live
        # telemetry) is high-volume; the per-pid fold renders in the
        # aggregate table (_metrics_table), never line by line — and
        # never summed with the final `metrics` event above
        return None
    if kind == "rollup_written":
        return (f"{ts} [pid {pid}] daily rollup written for "
                f"{ev.get('date')}: {ev.get('events')} event(s), "
                f"{ev.get('requests')} request(s) over "
                f"{ev.get('kernels')} kernel(s)"
                + (f", {ev.get('bad_lines')} unparseable line(s)"
                   if ev.get("bad_lines") else ""))
    if kind == "rollup_rejected":
        return (f"{ts} [pid {pid}] daily rollup REJECTED "
                f"{ev.get('path')}: {ev.get('reason')} - reader "
                "fell back to skipping that day")
    if kind == "supervisor_resume":
        return (f"{ts} [pid {pid}] supervisor RESUMED from checkpoint"
                f" (green={','.join(ev.get('green') or []) or '-'}"
                f" interrupted="
                f"{','.join(ev.get('interrupted') or []) or '-'})")
    if kind == "window_estimate":
        return (f"{ts} [pid {pid}] healthy-window estimate "
                f"{ev.get('minutes')} min ({ev.get('basis')}, "
                f"{ev.get('windows')} observed)")
    if kind == "step_start":
        return (f"{ts} [pid {pid}] step {ev.get('step')} started "
                f"(attempt {ev.get('attempt')}"
                + ("" if ev.get("gating") else ", non-gating")
                + (", FORCED past window" if ev.get("forced") else "")
                + ")")
    if kind == "step_done":
        out = str(ev.get("outcome", "?")).upper()
        return (f"{ts} [pid {pid}] step {ev.get('step')} {out}"
                + (f" rc={ev.get('rc')}"
                   if ev.get("rc") not in (0, None) else "")
                + (f" ({ev.get('wedges_today')} wedge(s) today)"
                   if ev.get("outcome") == "wedged" else ""))
    if kind == "step_skipped":
        return (f"{ts} [pid {pid}] step {ev.get('step')} skipped "
                f"({ev.get('reason')})")
    if kind == "step_quarantined":
        return (f"{ts} [pid {pid}] step {ev.get('step')} QUARANTINED "
                f"after {ev.get('wedges')} wedge(s) (threshold "
                f"{ev.get('threshold')}) - demoted to non-gating")
    if kind == "probe_scheduled":
        return (f"{ts} [pid {pid}] next probe in "
                f"{ev.get('delay_s')}s (attempt {ev.get('attempt')}, "
                f"{ev.get('reason')})")
    if kind == "aot_hit":
        return (f"{ts} [pid {pid}] aot compile HIT {ev.get('key')} "
                f"(compile {ev.get('compile_s')}s, prior "
                f"{ev.get('prior_compile_s')}s)")
    if kind == "aot_miss":
        return (f"{ts} [pid {pid}] aot compile MISS {ev.get('key')} "
                f"(lower {ev.get('lower_s')}s + compile "
                f"{ev.get('compile_s')}s)")
    if kind == "aot_rejected":
        return (f"{ts} [pid {pid}] aot-cache REJECTED "
                f"{ev.get('key')}: {ev.get('reason')}")
    if kind == "prewarm_start":
        return (f"{ts} [pid {pid}] prewarm started: "
                f"{len(ev.get('kernels') or [])} kernel config(s), "
                f"{len(ev.get('metrics') or [])} bench metric(s)")
    if kind == "prewarm_kernel":
        if ev.get("status") not in (None, "ok"):
            return (f"{ts} [pid {pid}] prewarm {ev.get('kernel')} "
                    f"FAILED ({ev.get('status')})"
                    + (f": {ev.get('error')}" if ev.get("error") else ""))
        return (f"{ts} [pid {pid}] prewarm {ev.get('kernel')} warmed "
                f"in {ev.get('wall_s')}s"
                + (f" (expected {ev.get('expected')})"
                   if ev.get("expected") else ""))
    if kind == "prewarm_end":
        return (f"{ts} [pid {pid}] prewarm done: "
                f"{ev.get('compiled')} warmed, "
                f"{len(ev.get('failed') or [])} failed in "
                f"{ev.get('total_wall_s')}s")
    if kind == "step_cost_estimated":
        return (f"{ts} [pid {pid}] step {ev.get('step')} chip-minute "
                f"cost re-estimated {ev.get('prior_cost_min')} -> "
                f"{ev.get('cost_min')} min ({ev.get('basis')})")
    if kind == "roofline_computed":
        mets = ev.get("metrics") or {}
        below = [
            m for m, r in sorted(mets.items())
            if isinstance(r, dict)
            and isinstance(r.get("frac"), (int, float))
            and r["frac"] < (ev.get("min_frac") or 0)
        ]
        return (f"{ts} [pid {pid}] roofline computed for "
                f"{len(mets)} metric(s) on {ev.get('device_kind')} "
                f"({ev.get('basis')}, threshold {ev.get('min_frac')})"
                + (f" - below: {','.join(below)}" if below else ""))
    if kind == "output_integrity_failed":
        return (f"{ts} [pid {pid}] OUTPUT INTEGRITY FAILED: "
                f"{ev.get('kernel')} at {ev.get('site')} "
                f"(tier {ev.get('tier')}: {ev.get('detail')})")
    if kind == "output_integrity_quarantined":
        return (f"{ts} [pid {pid}] output-integrity QUARANTINED "
                f"{ev.get('kernel')} (config {ev.get('config')}) after "
                f"{ev.get('failures')} failure(s) (threshold "
                f"{ev.get('threshold')})")
    if kind == "output_integrity_quarantined_repeat":
        return (f"{ts} [pid {pid}] output-integrity repeat offense on "
                f"already-quarantined {ev.get('kernel')} "
                f"({ev.get('failures')} today)")
    if kind == "output_integrity_envelope":
        return (f"{ts} [pid {pid}] integrity envelope recorded for "
                f"{ev.get('kernel')} ({ev.get('leaves')} leaf "
                "fingerprint(s))")
    if kind == "output_integrity_rejected":
        return (f"{ts} [pid {pid}] integrity-envelope REJECTED "
                f"{ev.get('key')}: {ev.get('reason')}")
    if kind == "output_integrity_check_error":
        return (f"{ts} [pid {pid}] integrity check ERRORED for "
                f"{ev.get('kernel')} at {ev.get('site')}: "
                f"{ev.get('error')} (result NOT judged)")
    if kind == "aot_invalidated":
        return (f"{ts} [pid {pid}] aot executables INVALIDATED for "
                f"{ev.get('kernel')}: {ev.get('memo_dropped')} memo "
                f"entr(ies), {len(ev.get('manifest_dropped') or [])} "
                "manifest entr(ies)")
    if kind == "slo_probe":
        v = ev.get("verdicts") or {}
        breached = sorted(
            k for k, r in v.items()
            if isinstance(r, dict) and r.get("verdict") == "slo_breach"
        )
        return (f"{ts} [pid {pid}] slo probe: {ev.get('requests')} "
                f"request(s), {ev.get('arrivals')} arrivals seed "
                f"{ev.get('seed')}, {ev.get('shape_class')} shapes on "
                f"{ev.get('device_kind')}"
                + (" (SIMULATED)" if ev.get("simulated") else "")
                + (f" - BREACH: {','.join(breached)}" if breached
                   else " - tails within target"))
    if kind == "slo_breach":
        return (f"{ts} [pid {pid}] SLO BREACH: {ev.get('kernel')} p99 "
                f"{_slo.fmt_ms(ev.get('p99_s'))} > target "
                f"{_slo.fmt_ms(ev.get('target_p99_s'))} over "
                f"{ev.get('count')} request(s)"
                + (" (simulated - never gates)"
                   if ev.get("simulated") else ""))
    if kind == "slo_rejected":
        return (f"{ts} [pid {pid}] slo verdict REJECTED "
                f"{ev.get('key')}: {ev.get('reason')}")
    if kind == "serve_start":
        if ev.get("role") == "router":
            return (f"{ts} [pid {pid}] fleet ROUTER started on "
                    f"{ev.get('socket')} over {ev.get('workers')} "
                    f"worker(s)"
                    + (f", tenant quota {ev.get('tenant_rate')}/s "
                       f"burst {ev.get('tenant_burst')}"
                       if ev.get("tenant_rate") else ""))
        return (f"{ts} [pid {pid}] serve daemon STARTED on "
                f"{ev.get('socket')} ({ev.get('workers')} worker(s), "
                f"queue max {ev.get('queue_max')}, batch window "
                f"{ev.get('batch_window_ms')}ms)")
    if kind == "serve_request":
        # per-request events are high-volume; the narrative renders
        # only the notable ones (requeued retries, errors) and the
        # aggregate table (_serve_table) carries the rest
        if ev.get("ok") and not ev.get("requeues"):
            return None
        return (f"{ts} [pid {pid}] serve request {ev.get('request')} "
                f"({ev.get('kernel')}) "
                + ("completed after requeue" if ev.get("ok")
                   else f"FAILED: {ev.get('error')}"))
    if kind == "serve_rejected":
        return (f"{ts} [pid {pid}] serve REJECTED a {ev.get('kernel')} "
                f"request (queue depth {ev.get('depth')} >= "
                f"{ev.get('queue_max')}; retry after "
                f"{ev.get('retry_after_s')}s)")
    if kind == "serve_request_requeued":
        return (f"{ts} [pid {pid}] serve request {ev.get('request')} "
                f"({ev.get('kernel')}) REQUEUED after "
                f"{ev.get('timeout_s')}s - worker abandoned, one "
                "retry")
    if kind == "serve_stop":
        if ev.get("role") == "router":
            return (f"{ts} [pid {pid}] fleet router stopped: "
                    f"{ev.get('routed')} routed, "
                    f"{ev.get('spilled')} spilled, "
                    f"{ev.get('throttled')} throttled over "
                    f"{ev.get('uptime_s')}s")
        return (f"{ts} [pid {pid}] serve daemon stopped: "
                f"{ev.get('served')} served, {ev.get('rejected')} "
                f"rejected, {ev.get('requeued')} requeued over "
                f"{ev.get('uptime_s')}s")
    if kind == "serve_route":
        # per-request routing is high-volume; clean routes render
        # only in the aggregate table (_route_table) — a route that
        # ended in a relayed failure is the notable exception
        if ev.get("ok"):
            return None
        return (f"{ts} [pid {pid}] routed {ev.get('kernel')} request "
                f"{ev.get('request')} to worker {ev.get('worker')} "
                "FAILED downstream")
    if kind == "serve_spill":
        return (f"{ts} [pid {pid}] SPILLED {ev.get('kernel')} bucket "
                f"{ev.get('bucket')} worker {ev.get('from_worker')} "
                f"-> {ev.get('to_worker')} ({ev.get('reason')})")
    if kind == "serve_drain":
        return (f"{ts} [pid {pid}] fleet worker {ev.get('worker')} "
                + ("DRAINING" if ev.get("phase") == "begin"
                   else "restored to the ring")
                + f" ({ev.get('inflight')} in flight)")
    if kind == "adapt_proposed":
        before = (ev.get("before") or {}).get("pad_frac")
        after = (ev.get("after") or {}).get("pad_frac")
        return (f"{ts} [pid {pid}] adaptive buckets: proposed "
                f"{len(ev.get('proposals') or [])} split/merge(s) "
                f"over {ev.get('requests_mined')} mined request(s)"
                + (f", projected pad_frac {before:.3f} -> {after:.3f}"
                   if isinstance(before, (int, float))
                   and isinstance(after, (int, float)) else "")
                + f" (target {ev.get('pad_target')})")
    if kind == "adapt_canary":
        return (f"{ts} [pid {pid}] adaptive buckets: canary "
                f"{'WON' if ev.get('promote') else 'LOST'} at seed "
                f"{ev.get('seed')} - {ev.get('reason')}")
    if kind == "adapt_promoted":
        pf = ev.get("pad_frac")
        return (f"{ts} [pid {pid}] adaptive buckets: PROMOTED "
                f"{ev.get('table')}"
                + (f" (measured pad_frac {pf:.3f})"
                   if isinstance(pf, (int, float)) else "")
                + " - undrain picks it up live")
    if kind == "adapt_rejected":
        return (f"{ts} [pid {pid}] adaptive buckets: candidate "
                f"REJECTED - {ev.get('reason')} (incumbent stays)")
    if kind == "serve_tenant_throttled":
        return (f"{ts} [pid {pid}] tenant {ev.get('tenant')} "
                f"THROTTLED ({ev.get('priority')} {ev.get('kernel')} "
                f"request; retry after {ev.get('retry_after_s')}s)")
    if kind == "serve_client_request":
        # per-request client walls are high-volume; the request-phase
        # story lives in tools/trace_report.py — only drops narrate
        if ev.get("ok"):
            return None
        return (f"{ts} [pid {pid}] client DROPPED {ev.get('kernel')} "
                f"request {ev.get('request_id')}: {ev.get('error')}")
    if kind == "serve_trace_budget":
        return (f"{ts} [pid {pid}] trace budget: {ev.get('traced')} "
                f"of {ev.get('requests')} request(s) traced, "
                f"{ev.get('gaps')} gap(s)"
                + (f", coverage {ev.get('coverage_mean'):.0%}"
                   if isinstance(ev.get("coverage_mean"),
                                 (int, float)) else "")
                + (f", {ev.get('untraced_serve_requests')} served "
                   "request(s) WITHOUT request_id"
                   if ev.get("untraced_serve_requests") else ""))
    if kind == "worker_dead":
        return (f"{ts} [pid {pid}] fleet worker {ev.get('worker')} "
                f"DEAD ({ev.get('via')}, crash {ev.get('crashes')}"
                + (f", pid {ev.get('worker_pid')}"
                   if ev.get("worker_pid") else "")
                + f") - respawn in {ev.get('backoff_s')}s"
                + (f"; swept {ev.get('swept_segments')} shm "
                   f"segment(s) / {ev.get('swept_bytes')}B"
                   if ev.get("swept_segments") else ""))
    if kind == "worker_respawned":
        return (f"{ts} [pid {pid}] fleet worker {ev.get('worker')} "
                f"RESPAWNED and rejoined the ring (pid "
                f"{ev.get('worker_pid')}, restart "
                f"{ev.get('restarts')}, down {ev.get('down_s')}s)")
    if kind == "worker_quarantined":
        return (f"{ts} [pid {pid}] fleet worker {ev.get('worker')} "
                f"QUARANTINED after {ev.get('crashes')} crash(es) "
                f"(threshold {ev.get('threshold')}) - left out of "
                "the ring; `serve_ctl undrain` resets")
    if kind == "serve_request_replayed":
        if ev.get("via") == "wal":
            if ev.get("ok") is False:
                return (f"{ts} [pid {pid}] WAL replay SKIPPED "
                        f"request "
                        f"{ev.get('request_id') or ev.get('request')}"
                        f" ({ev.get('reason')}) - the client's "
                        "reconnect retry owns it")
            return (f"{ts} [pid {pid}] REPLAYED {ev.get('kernel')} "
                    f"request "
                    f"{ev.get('request_id') or ev.get('request')} "
                    f"from the dead router's WAL -> worker "
                    f"{ev.get('to_worker')}")
        return (f"{ts} [pid {pid}] REPLAYED {ev.get('kernel')} "
                f"request {ev.get('request_id') or ev.get('request')}"
                f" off dead worker {ev.get('from_worker')} -> "
                f"{ev.get('to_worker')}")
    if kind == "router_dead":
        return (f"{ts} [pid {pid}] fleet ROUTER DEAD "
                f"({ev.get('via')}, crash {ev.get('crashes')}"
                + (f", pid {ev.get('router_pid')}"
                   if ev.get("router_pid") else "")
                + f") - guardian respawns in {ev.get('backoff_s')}s"
                + (f"; swept {ev.get('swept_segments')} shm "
                   f"segment(s) / {ev.get('swept_bytes')}B"
                   if ev.get("swept_segments") else ""))
    if kind == "router_respawned":
        return (f"{ts} [pid {pid}] fleet ROUTER RESPAWNED by its "
                f"guardian (pid {ev.get('router_pid')}, restart "
                f"{ev.get('restarts')}, down {ev.get('down_s')}s)")
    if kind == "router_quarantined":
        return (f"{ts} [pid {pid}] fleet ROUTER QUARANTINED after "
                f"{ev.get('crashes')} crash(es) (threshold "
                f"{ev.get('threshold')}) - guardian stopped "
                "respawning; `serve_ctl start-fleet` resets")
    if kind == "fleet_fsck":
        return (f"{ts} [pid {pid}] fsck reaped "
                f"{ev.get('stale_pidfiles')} stale pidfile(s), "
                f"{ev.get('swept_segments')} orphaned shm "
                f"segment(s), {ev.get('torn_configs')} torn "
                "config(s)")
    if kind == "chaos_event":
        return (f"{ts} [pid {pid}] CHAOS event {ev.get('seq')}/"
                f"{ev.get('of')}: {ev.get('event')} (seed "
                f"{ev.get('seed')}) - invariants held")
    if kind == "artifact_rejected":
        return (f"{ts} [pid {pid}] TORN artifact rejected: "
                f"{ev.get('path')} ({ev.get('reason')}) - reader "
                "fell back to empty state")
    if kind == "fleet_degraded":
        lvl = str(ev.get("level", "?")).upper()
        if ev.get("level") == "ok":
            return (f"{ts} [pid {pid}] fleet degradation CLEARED - "
                    "all workers restored to the ring")
        return (f"{ts} [pid {pid}] fleet {lvl}: workers "
                f"{ev.get('down')} out of the ring"
                + (f" (quarantined {ev.get('quarantined')})"
                   if ev.get("quarantined") else "")
                + (f" - shedding with retry hint "
                   f"{ev.get('retry_after_s')}s"
                   if ev.get("level") == "critical" else ""))
    if kind == "serve_lane_negotiated":
        return (f"{ts} [pid {pid}] serve shm payload lane ENGAGED "
                f"({ev.get('kernel')} request {ev.get('request')})")
    if kind == "serve_copy_budget":
        return (f"{ts} [pid {pid}] serve copy budget: "
                f"{ev.get('bytes_per_request')}B/request over "
                f"{ev.get('requests')} request(s), {ev.get('lane')} "
                "lane"
                + (" - ZERO-COPY CONTRACT"
                   + ("" if not ev.get("daemon_bytes_copied")
                      else " VIOLATED")
                   if ev.get("expected_zero") else ""))
    if kind == "device_inventory":
        n = ev.get("n_devices")
        return (f"{ts} [pid {pid}] device inventory ({ev.get('site')}, "
                f"{ev.get('source')}): platform={ev.get('platform')}"
                + (f" kind={ev.get('device_kind')}"
                   if ev.get("device_kind") else "")
                + (f" n={n}" if n is not None else "")
                + (f" proc {ev.get('process_index')}/"
                   f"{ev.get('process_count')}"
                   if ev.get("process_count") else "")
                # unknown-platform / unprobed stamps (the normal pod
                # config leaves JAX_PLATFORMS unset; a failed or
                # skipped probe forces fake) are fail-safe fake for
                # gating but are NOT known-fake hardware — don't
                # slander a real pod's telemetry with "FAKE"
                + ((" platform unknown (treated fake for gating)"
                    if ev.get("fake_basis") == "unknown-platform"
                    else " unprobed (treated fake for gating)"
                    if ev.get("fake_basis") == "unprobed-fallback"
                    else " FAKE") if ev.get("fake") else ""))
    if kind == "busbw_point":
        return (f"{ts} [pid {pid}] busbw {ev.get('op')} n="
                f"{ev.get('n_devices')} {ev.get('size_bytes')}B -> "
                f"{ev.get('gb_s')} GB/s"
                + (" (fake)" if ev.get("fake") else ""))
    if kind == "weak_scaling_point":
        ok = ev.get("ok", True)
        return (f"{ts} [pid {pid}] weak-scaling {ev.get('program')} "
                f"n={ev.get('n_devices')} "
                + (f"wall={ev.get('wall_s')}s" if ok
                   else f"FAILED ({ev.get('error')})")
                + (" (fake)" if ev.get("fake") else ""))
    if kind == "scaling_computed":
        busbw = ev.get("busbw") or {}
        weak = ev.get("weak") or {}
        findings = sorted(
            k for k, v in {**busbw, **weak}.items()
            if v in ("regression", "impossible",
                     "below_scaling_efficiency")
        )
        return (f"{ts} [pid {pid}] scaling verdicts computed over "
                f"{ev.get('artifacts')} artifact(s): {len(busbw)} "
                f"bus-bw series, {len(weak)} weak-scaling program(s)"
                + (f" - findings: {','.join(findings)}" if findings
                   else " - clean"))
    if kind == "tuning_resolved":
        return (f"{ts} [pid {pid}] tuning resolved for "
                f"{ev.get('kernel')}: {ev.get('params')} "
                f"(sources {ev.get('sources')})")
    if kind == "tuning_rejected":
        return (f"{ts} [pid {pid}] tuning-cache REJECTED "
                f"{ev.get('key')}: {ev.get('reason')}")
    if kind == "tuning_cache_put":
        return (f"{ts} [pid {pid}] tuning-cache put {ev.get('key')} "
                f"params={ev.get('params')}"
                + (" (smoke)" if ev.get("smoke") else ""))
    if kind == "tuning_sweep_start":
        return (f"{ts} [pid {pid}] autotune sweep: {ev.get('kernel')} "
                f"({ev.get('candidates')} candidate(s), "
                f"{ev.get('pruned')} pruned"
                + (", smoke" if ev.get("smoke") else "") + ")")
    if kind == "tuning_candidate":
        shown = ev.get("value")
        shown = shown if shown is not None else f"FAIL ({ev.get('status')})"
        ratio = ev.get("aot_hit_ratio")
        return (f"{ts} [pid {pid}] candidate {ev.get('params')} -> "
                f"{shown}"
                + (f" (aot hit {ratio:.0%})" if isinstance(
                    ratio, (int, float)) else ""))
    if kind == "tuning_promoted":
        return (f"{ts} [pid {pid}] PROMOTED {ev.get('kernel')} "
                f"{ev.get('params')} (value {ev.get('value')} vs "
                f"control {ev.get('control')})")
    if kind == "tuning_sweep_end":
        return (f"{ts} [pid {pid}] sweep end: {ev.get('measured')} "
                f"measured, {ev.get('failed')} failed, promoted="
                f"{ev.get('promoted')}")
    return f"{ts} [pid {pid}] {kind}"


def _span_breakdown(events):
    """Per-phase wall-time table aggregated from `span` events
    (docs/OBSERVABILITY.md §spans) — where a traced session's wall
    clock went, without replaying the narrative. The aggregation is
    shared with tools/obs_report.py (trace.aggregate_spans)."""
    agg = _trace.aggregate_spans(events)
    if not agg:
        return []
    out = ["per-phase wall time (span events):"]
    for name in sorted(agg, key=lambda n: -agg[n]["total_s"]):
        a = agg[name]
        out.append(
            f"  {name:<36} n={a['count']:<4} "
            f"total={a['total_s']:.3f}s "
            f"mean={a['total_s'] / a['count']:.3f}s"
        )
    return out


def _step_table(events):
    """Per-step attempt/outcome/quarantine table from the supervisor's
    step events (docs/RESILIENCE.md §supervisor) — the at-a-glance
    answer to "which steps keep eating the flap windows"."""
    steps: dict = {}
    for ev in events:
        name = ev.get("step")
        kind = ev.get("kind")
        if not name or kind not in ("step_start", "step_done",
                                    "step_skipped",
                                    "step_quarantined"):
            continue
        s = steps.setdefault(name, {
            "attempts": 0, "green": 0, "failed": 0, "wedged": 0,
            "slow": 0, "skipped": 0, "quarantined": False,
            "wall_s": 0.0,
        })
        if kind == "step_start":
            s["attempts"] += 1
        elif kind == "step_done":
            outcome = ev.get("outcome")
            if outcome in s:
                s[outcome] += 1
            s["wall_s"] += ev.get("wall_s") or 0.0
        elif kind == "step_skipped":
            s["skipped"] += 1
        elif kind == "step_quarantined":
            s["quarantined"] = True
    if not steps:
        return []
    out = ["supervisor steps (attempts / outcomes / quarantine):"]
    for name in sorted(steps):
        s = steps[name]
        flags = []
        for key in ("green", "failed", "wedged", "slow", "skipped"):
            if s[key]:
                flags.append(f"{key}={s[key]}")
        out.append(
            f"  {name:<22} attempts={s['attempts']:<3} "
            f"wall={s['wall_s']:.1f}s "
            + " ".join(flags)
            + (" QUARANTINED" if s["quarantined"] else "")
        )
    return out


def _serve_table(events):
    """Per-(kernel, worker) served-request aggregate from the
    high-volume ``serve_request`` events (docs/SERVING.md) —
    requests, mean wall, mean pad waste, max batch — so the
    narrative stays readable while nothing is dropped. Keyed by
    (kernel, worker_id), not kernel alone: on a fleet a hot worker
    must be VISIBLE, not averaged away. A request a spill or wedge
    made two workers journal (home failure + sibling success) is
    counted ONCE — deduplicated by request_id, keeping the ok (else
    latest) record; requests without a request_id (old clients) each
    count, as before."""
    chosen: dict = {}   # request_id -> event of record
    plain: list = []    # pre-request_id events: no dedupe possible
    dupes = 0
    for ev in events:
        if ev.get("kind") != "serve_request":
            continue
        rid = ev.get("request_id")
        if rid is None:
            plain.append(ev)
            continue
        prev = chosen.get(rid)
        if prev is None:
            chosen[rid] = ev
        else:
            dupes += 1
            if bool(ev.get("ok")) or not prev.get("ok"):
                chosen[rid] = ev
    rows: dict = {}
    for ev in list(chosen.values()) + plain:
        key = (ev.get("kernel", "?"), ev.get("worker_id"))
        r = rows.setdefault(key, {
            "n": 0, "ok": 0, "wall": 0.0, "pad": 0.0, "bucketed": 0,
            "batch_max": 0, "requeued": 0,
        })
        r["n"] += 1
        r["ok"] += 1 if ev.get("ok") else 0
        r["wall"] += ev.get("wall_s") or 0.0
        r["pad"] += ev.get("pad_frac") or 0.0
        r["bucketed"] += 1 if ev.get("bucketed") else 0
        r["batch_max"] = max(r["batch_max"], ev.get("batch_size") or 0)
        r["requeued"] += 1 if ev.get("requeues") else 0
    if not rows:
        return []
    out = ["served requests (from serve_request events, keyed "
           "kernel@worker):"]
    if dupes:
        out.append(f"  ({dupes} spill/wedge duplicate record(s) "
                   "deduped by request_id)")
    for kernel, wid in sorted(rows, key=lambda k: (k[0], str(k[1]))):
        r = rows[(kernel, wid)]
        label = kernel if wid is None else f"{kernel}@w{wid}"
        out.append(
            f"  {label:<16} n={r['n']:<5} ok={r['ok']:<5} "
            f"mean_wall={r['wall'] / r['n']:.4f}s "
            f"bucketed={r['bucketed']} "
            f"mean_pad={r['pad'] / r['n']:.1%} "
            f"batch_max={r['batch_max']}"
            + (f" requeued={r['requeued']}" if r["requeued"] else "")
        )
    return out


def _route_table(events):
    """Per-worker routed-request aggregate from the high-volume
    ``serve_route`` events (docs/SERVING.md §fleet) — where each
    bucket landed, how much spilled, which tenants rode — the
    fleet-side twin of :func:`_serve_table`."""
    rows: dict = {}
    for ev in events:
        if ev.get("kind") != "serve_route":
            continue
        r = rows.setdefault(ev.get("worker", "?"), {
            "n": 0, "ok": 0, "spilled_in": 0, "buckets": set(),
            "tenants": set(),
        })
        r["n"] += 1
        r["ok"] += 1 if ev.get("ok") else 0
        r["spilled_in"] += 1 if ev.get("spilled_from") is not None else 0
        r["buckets"].add(ev.get("bucket"))
        if ev.get("tenant") not in (None, "-"):
            r["tenants"].add(ev.get("tenant"))
    if not rows:
        return []
    out = ["routed requests (from serve_route events):"]
    for worker in sorted(rows, key=str):
        r = rows[worker]
        out.append(
            f"  worker {worker}: n={r['n']:<5} ok={r['ok']:<5} "
            f"spilled_in={r['spilled_in']} "
            f"buckets={len(r['buckets'])}"
            + (f" tenants={','.join(sorted(r['tenants']))}"
               if r["tenants"] else "")
        )
    return out


def _metrics_table(events):
    """Per-process metric state from the one shared
    ``metrics.merge_journal_metrics`` fold (docs/OBSERVABILITY.md
    §live telemetry): the atexit ``metrics`` event is authoritative
    where present; a pid that died without one (SIGKILL) is rebuilt
    from its ``metrics_snapshot`` stream, deduped by (pid, seq). The
    two encodings are never summed — a pid that streamed AND exited
    cleanly counts once."""
    merged = _metrics.merge_journal_metrics(events)
    if not merged:
        return []
    out = ["metric state per process (final metrics event, else "
           "deduped snapshot stream):"]
    for pid, st in sorted(merged.items(), key=lambda kv: str(kv[0])):
        how = ("final" if st.get("final")
               else f"last snapshot seq={st.get('seq')}, NO final "
                    "flush - died hard")
        counters = st.get("counters") or {}
        served = sum(v for k, v in counters.items()
                     if k.startswith("serve.requests.")
                     and isinstance(v, (int, float)))
        out.append(
            f"  pid {pid} ({st.get('site')}, {how}): "
            f"{len(counters)} counter(s), "
            f"{len(st.get('gauges') or {})} gauge(s), "
            f"{len(st.get('histograms') or {})} histogram(s)"
            + (f", {int(served)} served request(s)" if served else "")
        )
    return out


def summarize(events, bad=0) -> str:
    out = []
    events = sorted(events, key=lambda e: e.get("t", 0.0))
    heads = {e.get("git_head") for e in events if e.get("git_head")}
    out.append(
        f"health report: {len(events)} events"
        + (f", {bad} unparseable lines" if bad else "")
        + (f", git {'/'.join(sorted(h[:12] for h in heads))}"
           if heads else "")
    )
    out.append("-" * 60)
    for ev in events:
        line = _fmt(ev)
        if line:
            out.append(line)
    out.append("-" * 60)
    steps = _step_table(events)
    if steps:
        out.extend(steps)
        out.append("-" * 60)
    served = _serve_table(events)
    if served:
        out.extend(served)
        out.append("-" * 60)
    routed = _route_table(events)
    if routed:
        out.extend(routed)
        out.append("-" * 60)
    breakdown = _span_breakdown(events)
    if breakdown:
        out.extend(breakdown)
        out.append("-" * 60)
    mtable = _metrics_table(events)
    if mtable:
        out.extend(mtable)
        out.append("-" * 60)
    counts = {}
    for ev in events:
        counts[ev.get("kind")] = counts.get(ev.get("kind"), 0) + 1
    wedges = sum(
        1 for e in events
        if e.get("kind") == "wedge_classification"
        and e.get("verdict") == "wedged"
    )
    fires = counts.get("watchdog_fire", 0)
    out.append(
        "totals: "
        + ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    )
    out.append(
        f"verdict: {wedges} wedge(s), {fires} watchdog fire(s), "
        f"{counts.get('partial_result', 0)} partial-result decision(s), "
        f"{counts.get('fault_injected', 0)} injected fault(s), "
        f"{counts.get('step_quarantined', 0)} quarantined step(s), "
        f"{counts.get('output_integrity_failed', 0)} output-integrity "
        "failure(s), "
        f"{counts.get('slo_breach', 0)} SLO breach(es), "
        f"{counts.get('serve_rejected', 0)} serve rejection(s), "
        f"{counts.get('serve_request_requeued', 0)} serve requeue(s), "
        f"{counts.get('serve_spill', 0)} fleet spill(s), "
        f"{counts.get('serve_tenant_throttled', 0)} tenant throttle(s), "
        f"{counts.get('worker_dead', 0)} worker death(s), "
        f"{counts.get('worker_respawned', 0)} worker restart(s), "
        f"{counts.get('worker_quarantined', 0)} quarantined worker(s), "
        f"{counts.get('serve_request_replayed', 0)} replayed "
        "request(s), "
        f"{counts.get('fleet_degraded', 0)} degradation change(s), "
        f"{counts.get('router_dead', 0)} router death(s), "
        f"{counts.get('router_respawned', 0)} router restart(s), "
        f"{counts.get('router_quarantined', 0)} router quarantine(s), "
        f"{counts.get('artifact_rejected', 0)} torn artifact(s), "
        f"{counts.get('fleet_fsck', 0)} fsck run(s), "
        f"{counts.get('chaos_event', 0)} chaos event(s), "
        f"{counts.get('adapt_promoted', 0)} bucket promotion(s), "
        f"{counts.get('adapt_rejected', 0)} bucket rejection(s)"
    )
    return "\n".join(out)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    paths = argv
    if not paths:
        found = sorted(
            glob.glob(os.path.join(_REPO, "docs", "logs",
                                   "health_*.jsonl")),
            key=os.path.basename,
        )
        if not found:
            print("health_report: no docs/logs/health_*.jsonl found",
                  file=sys.stderr)
            return 1
        paths = [found[-1]]
    events, bad = load(paths)
    print(f"health_report: {', '.join(os.path.relpath(p) for p in paths)}")
    print(summarize(events, bad))
    return 0


if __name__ == "__main__":
    sys.exit(main())
