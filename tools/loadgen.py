"""Open-loop load generator: drive the serving path, judge the tail.

Usage:
    python tools/loadgen.py --kernel sgemm --arrivals poisson \\
                            --seed 7 --requests 200
    python tools/loadgen.py --mix all --arrivals bursty --duration 60
    python tools/loadgen.py --mix sgemm=3,scan=1 --rate 10 \\
                            --requests 120 --shapes record
    python tools/loadgen.py --requests 200 --simulate 5   # no jax
    python tools/loadgen.py --requests 200 --print-schedule
    python tools/loadgen.py --serve default --kernel scan \\
                            --requests 200            # drive the daemon

bench.py measures steady-state slope throughput; a service is judged
on per-request latency under bursty arrivals — queueing, compile
leaks and cache eviction hide behind a healthy slope and surface in
p99 (docs/OBSERVABILITY.md §latency SLOs). This tool generates a
deterministic OPEN-LOOP arrival schedule (arrivals never wait for
service — when dispatch stalls, later requests queue and their
latency counts the wait, so coordinated omission cannot hide a
stall), drives ``registry.dispatch`` in-process — or the serving
daemon over its socket with ``--serve`` (docs/SERVING.md) — records
per-request latency into the
log-bucketed ``slo.latency_s.<kernel>`` histograms
(``tpukernels/obs/metrics.py``), judges them against the per-kernel
SLO targets (``tpukernels/obs/slo.py``) and persists the verdicts
into the ``slo.json`` artifact that ``tools/obs_report.py --check``
gates on.

Arrival processes (all seeded — ``--seed`` beats ``TPK_LOADGEN_SEED``
beats 0; no wall-clock randomness, so the same seed yields a
byte-identical request schedule):
    poisson — exponential inter-arrival gaps at ``--rate`` req/s.
    bursty  — on/off modulated Poisson: 1 s at 1.8x rate, 1 s at
              0.2x rate (mean ~= rate) — the queueing stressor.
    diurnal — sinusoidally ramped rate (0.25x..1.75x over
              ``--period`` s, default 60) — the slow-swell shape.

Shape classes: ``probe`` (default) uses the integrity layer's small
deterministic canary operands — CPU-fast, the 60-second supervisor
probe and the CI proof; ``record`` materializes the registered
``aot.BENCH_CONFIGS`` avatar shapes — the real serving shapes, for
chip windows. ``--mix all`` spreads requests uniformly over every
registry kernel; ``k1=w1,k2=w2`` weights them. Anything else is a
REPLAY-SPEC file path (requires ``--serve``; ``--kernel``/``--mix``
don't apply): JSON ``{"entries": [{"kernel", "args": [[kind,
shape], ...], "statics", "weight"}, ...]}`` — an OBSERVED shape mix
materialized verbatim, which is how the traffic-adaptive canary
(``tools/serve_optimize.py``; docs/SERVING.md §adaptive buckets)
replays the journal's shape population against candidate vs
incumbent bucket tables at identical seeds. Replay verdicts record
under shape class ``replay``, which has no SLO target row and can
never gate.

``--simulate MS`` replaces dispatch with a deterministic virtual
clock (single-server queue, seeded service times around MS; no jax
import): the plumbing/determinism proof. Simulated verdicts are
persisted flagged ``simulated`` and NEVER gate.

``--serve SOCKET`` (``default`` = the ``TPK_SERVE_SOCKET``/serve-dir
resolution) drives the kernel-serving daemon (docs/SERVING.md) — or
a fleet's front-end router, which speaks the same protocol —
instead of in-process ``registry.dispatch`` — the same schedule, the
same completion-minus-SCHEDULED-arrival latency arithmetic, so the
SLO verdicts judge the real service path end to end: queueing,
bucketing/padding, batching windows and backpressure all land in the
tail. This client process never imports jax (device_kind and jax
version come from the daemon's ping). An admission-control rejection
is retried after the daemon's ``retry_after_s`` hint — the retries'
wait counts in the request's latency, and each retry's sleep is
jittered 0.5x-1.5x by a stream seeded off the run seed so
synchronized clients don't re-stampede a recovering daemon — and
dropped loudly (``slo.dropped.<kernel>``) after 10 rejections.
``--tenant NAME`` / ``--priority interactive|batch`` (serve-only)
ride every request header for the fleet router's per-tenant
admission point (docs/SERVING.md §fleet); a tenant run's series
record as ``<kernel>@<tenant>`` so its p99 verdicts earn their own
``slo.json`` rows under the unchanged gating contract.
``--deadline-ms DIST`` (serve-only; ``250`` fixed or ``200:400``
seeded-uniform per request) stamps a deadline on every scheduled
request (warms ride deadline-free — a cold compile is not a tail
sample) and adds a **goodput** (deadline-met fraction) column beside
the latency columns in the SLO summary: a request counts as met when
it completed ok within its budget, measured from dispatch — the
moment the client stamped the budget (docs/SERVING.md §deadlines).
Expired requests (the daemon's honest ``expired`` /
``deadline_infeasible`` replies) are dropped loudly under their own
``slo.expired.<kernel>`` counter, and low goodput downgrades an
``ok`` verdict to the NON-gating ``goodput_low``
(``tpukernels/obs/slo.py``, the below_roofline pattern).

``--serve`` runs are request-TRACED (docs/OBSERVABILITY.md §request
tracing): every request carries a seeded-deterministic
``lg<seed>-<pid>-<NNNNN>`` request_id (warm requests
``lg<seed>-<pid>-warm-<kernel>``; the pid scopes the RUN so same-day
probe reruns appending to one journal never merge timelines;
backpressure retries keep their id), a
``serve_client_request`` journal record stamps the client-observed
wall per request, and the run ends by assembling its own timelines
from the journal and stamping a ``serve_trace_budget`` event — the
phase-sum-vs-wall evidence ``obs_report --check`` gates
(``trace_inconsistent``) exactly like the copy budget.

This process defaults ``TPK_INTEGRITY=tripwire`` (an explicit env
choice wins): the sampled oracle canary checks would inject periodic
multi-ms outliers into exactly the tail this tool measures.

Exit codes: 0 — run completed (verdicts, including breaches, are the
artifact's job; gating belongs to ``obs_report --check``);
1 — with ``--check``, at least one non-simulated ``slo_breach``
verdict this run; 2 — usage error.
"""

from __future__ import annotations

import math
import os
import random
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels import _cachedir  # noqa: E402

# env-before-jax-import contract (tpukernels/_cachedir.py): the CLI
# may compile on a cold cache; journal routing mirrors bench.py's and
# revalidate.py's CLI default so the slo_probe event lands in the
# day's health journal.
_cachedir.ensure_compilation_cache()

from tpukernels.obs import metrics as obs_metrics  # noqa: E402
from tpukernels.obs import scaling as obs_scaling  # noqa: E402
from tpukernels.obs import slo, trace  # noqa: E402
from tpukernels.resilience import journal  # noqa: E402

ARRIVALS = ("poisson", "bursty", "diurnal")
DEFAULT_RATE = 20.0


def default_seed() -> int:
    """``TPK_LOADGEN_SEED`` (fail-loud parse), else 0 — the
    deterministic-schedule contract forbids wall-clock seeding."""
    raw = os.environ.get("TPK_LOADGEN_SEED")
    if raw is None:
        return 0
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"TPK_LOADGEN_SEED={raw!r}: expected an integer"
        ) from None


def _rate_at(arrivals: str, rate: float, t: float, period: float):
    if arrivals == "bursty":
        # 1 s on at 1.8x, 1 s off at 0.2x: mean ~= rate, tail rich
        return rate * (1.8 if (t % 2.0) < 1.0 else 0.2)
    if arrivals == "diurnal":
        return rate * (0.25 + 1.5 * math.sin(math.pi * t / period) ** 2)
    return rate


def build_schedule(seed: int, arrivals: str, rate: float,
                   requests: int, duration: float | None,
                   mix: dict, period: float = 60.0) -> list:
    """[(t_offset_s, kernel), ...] — the whole run, precomputed from
    the seed alone. Stops at ``requests`` arrivals or ``duration``
    schedule seconds, whichever comes first (requests=0 = unbounded,
    duration must then bound the run)."""
    if arrivals not in ARRIVALS:
        raise ValueError(
            f"unknown arrival process {arrivals!r}; known: {ARRIVALS}"
        )
    if requests <= 0 and not duration:
        raise ValueError(
            "loadgen: --requests 0 needs --duration to bound the run"
        )
    rng = random.Random(seed)
    kernels = sorted(mix)
    weights = [mix[k] for k in kernels]
    out, t = [], 0.0
    while True:
        if requests > 0 and len(out) >= requests:
            break
        t += rng.expovariate(_rate_at(arrivals, rate, t, period))
        if duration and t > duration:
            break
        out.append((t, rng.choices(kernels, weights)[0]))
    return out


# ------------------------------------------------------------------ #
# operand sets per shape class                                       #
# ------------------------------------------------------------------ #

def _probe_operands(kernel):
    """The integrity layer's deterministic small canary operands
    (one authority — the same shapes the guard's oracle checks run),
    converted to device arrays with host scalars canonicalized the
    way the dispatch memo expects."""
    import jax.numpy as jnp
    import numpy as np

    from tpukernels.resilience import integrity

    args = integrity._build_args(kernel)
    statics = dict(integrity.CANARY_CONFIGS[kernel]["statics"])
    jargs = tuple(
        jnp.asarray(a) if isinstance(a, np.ndarray)
        else jnp.float32(a) if isinstance(a, float)
        else jnp.int32(a)
        for a in args
    )
    return jargs, statics


def _record_operands(kernel):
    """The registered BENCH_CONFIGS avatar config materialized as
    concrete operands (values are irrelevant to latency; ones keep
    every kernel's output finite for the tripwire)."""
    import jax.numpy as jnp

    from tpukernels import aot

    spec = aot.BENCH_CONFIGS[kernel]
    dt = {"f32": jnp.float32, "i32": jnp.int32}
    jargs = tuple(
        dt[kind](1) if shape == () else jnp.ones(shape, dt[kind])
        for kind, shape in spec["args"]
    )
    return jargs, dict(spec["statics"])


def _operands(kernel, shape_class):
    return (_record_operands if shape_class == "record"
            else _probe_operands)(kernel)


def _operands_np(kernel, shape_class):
    """Numpy twin of :func:`_operands` for the ``--serve`` client
    path (jax-free by design): host scalars become 0-d arrays, the
    dispatch memo's canonicalization, applied client-side."""
    import numpy as np

    if shape_class == "record":
        from tpukernels import aot

        spec = aot.BENCH_CONFIGS[kernel]
        dt = {"f32": np.float32, "i32": np.int32}
        args = tuple(
            dt[kind](1) if shape == () else np.ones(shape, dt[kind])
            for kind, shape in spec["args"]
        )
        return args, dict(spec["statics"])
    from tpukernels.resilience import integrity

    args = tuple(
        np.float32(a) if isinstance(a, float)
        else np.int32(a) if isinstance(a, int)
        else a
        for a in integrity._build_args(kernel)
    )
    return args, dict(integrity.CANARY_CONFIGS[kernel]["statics"])


# ------------------------------------------------------------------ #
# execution                                                          #
# ------------------------------------------------------------------ #

def run_simulated(schedule, seed: int, service_ms: float) -> None:
    """Deterministic virtual-clock replay: one single-server queue,
    service times drawn from a second seeded stream around
    ``service_ms``. No dispatch, no jax — latency = completion -
    scheduled arrival, exactly the open-loop arithmetic of the real
    path, so two runs with one seed produce identical histogram
    buckets (the determinism proof)."""
    rng = random.Random(seed ^ 0x510510)
    free_at = 0.0
    for t, kernel in schedule:
        service = service_ms / 1000.0 * (0.5 + rng.random())
        start = max(t, free_at)
        free_at = start + service
        obs_metrics.inc(f"slo.requests.{kernel}")
        obs_metrics.observe(f"slo.latency_s.{kernel}", free_at - t)
        obs_metrics.observe(f"slo.service_s.{kernel}", service)


def run_real(schedule, shape_class: str, echo) -> None:
    """Drive ``registry.dispatch`` through the schedule, open-loop:
    sleep until each request's scheduled arrival (never past it —
    when service falls behind, later requests run back-to-back and
    their recorded latency includes the queue wait). Each kernel's
    (operands, statics) is built once and warmed with one untimed
    dispatch: the SLO judges the WARM path of record the AOT layer
    bought (a cold compile is prewarm's job, not a tail sample)."""
    import jax

    from tpukernels import registry

    prepared = {}
    for kernel in sorted({k for _t, k in schedule}):
        prepared[kernel] = _operands(kernel, shape_class)
        jargs, statics = prepared[kernel]
        w0 = time.perf_counter()
        jax.block_until_ready(registry.dispatch(kernel, *jargs, **statics))
        echo(f"# warmed {kernel} in {time.perf_counter() - w0:.3f}s")
    t0 = time.perf_counter()
    for t, kernel in schedule:
        now = time.perf_counter() - t0
        if t > now:
            time.sleep(t - now)
        jargs, statics = prepared[kernel]
        s0 = time.perf_counter()
        jax.block_until_ready(registry.dispatch(kernel, *jargs, **statics))
        s1 = time.perf_counter()
        obs_metrics.inc(f"slo.requests.{kernel}")
        obs_metrics.observe(f"slo.latency_s.{kernel}", (s1 - t0) - t)
        obs_metrics.observe(f"slo.service_s.{kernel}", s1 - s0)


def _replay_operands(entry):
    """Materialize one replay-spec entry's operands: np.ones at the
    OBSERVED shapes (values never matter to pad accounting), host
    scalars as 0-d arrays exactly like :func:`_operands_np`."""
    import numpy as np

    dt = {"f32": np.float32, "i32": np.int32}
    args = tuple(
        dt[kind](1) if not shape
        else np.ones([int(d) for d in shape], dt[kind])
        for kind, shape in (tuple(a) for a in entry["args"])
    )
    return args, dict(entry.get("statics") or {})


def _load_replay(path):
    """Read and validate a replay-spec file (module docstring has the
    format). Returns ``(entries_by_id, mix)`` where the mix keys are
    synthetic entry ids (``e000``...) — the schedule draws over
    ENTRIES (one observed shape population each), while dispatch and
    metrics use each entry's real kernel name."""
    import json as _json

    with open(path) as f:
        spec = _json.load(f)
    entries = spec.get("entries") if isinstance(spec, dict) else None
    if not isinstance(entries, list) or not entries:
        raise ValueError(
            'want {"entries": [...]} with at least one entry'
        )
    replay, mix = {}, {}
    for i, ent in enumerate(entries):
        if not isinstance(ent, dict) \
                or not isinstance(ent.get("kernel"), str):
            raise ValueError(f"entry {i}: needs a kernel name")
        args = ent.get("args")
        if not isinstance(args, list) or not args:
            raise ValueError(
                f"entry {i}: needs args [[kind, [dims]], ...]"
            )
        for a in args:
            if (not isinstance(a, (list, tuple)) or len(a) != 2
                    or a[0] not in ("f32", "i32")
                    or not isinstance(a[1], (list, tuple))):
                raise ValueError(
                    f"entry {i}: bad arg {a!r} (want "
                    '["f32"|"i32", [dims]])'
                )
        w = float(ent.get("weight", 1.0))
        if w <= 0:
            raise ValueError(f"entry {i}: weight must be > 0")
        eid = f"e{i:03d}"
        replay[eid] = ent
        mix[eid] = w
    return replay, mix


def run_serve(schedule, shape_class: str, socket_path: str, echo,
              seed: int = 0, tenant=None, priority=None, replay=None,
              deadline=None):
    """Drive the serving daemon through the schedule, open-loop — the
    ``run_real`` arithmetic with the daemon in place of
    ``registry.dispatch``. Latency stays completion minus SCHEDULED
    arrival, so daemon queueing, batching windows and backpressure
    retries all count; one untimed dispatch per (kernel, shapes)
    warms the daemon's executable memo first. Backpressure retries
    are jittered by a stream seeded off the run seed (0.5x-1.5x the
    hint — docs/SERVING.md §backpressure): N loadgen clients rejected
    together must not sleep identical hints and re-stampede a
    recovering daemon, and seeding keeps the run reproducible.
    ``tenant``/``priority`` ride every request header (the fleet
    router's admission point) and a tenant's series record under
    ``<kernel>@<tenant>`` so its verdicts earn their own slo.json
    rows. With ``replay`` (a ``_load_replay`` entries-by-id map) the
    schedule's keys are entry ids; each entry materializes its
    observed shapes while dispatch and metrics use its real kernel
    name, so two entries of one kernel merge into one latency
    histogram — the canary compares POPULATIONS, not entries.
    ``deadline`` is a ``(lo_ms, hi_ms)`` range: each scheduled
    request samples a per-request deadline from its own seeded stream
    and the dispatch header carries it end to end (docs/SERVING.md
    §deadlines); warms stay deadline-free. Returns ``(stats,
    goodput)`` — the daemon's ping stats (device_kind, jax version)
    for the verdict record, plus ``{series: [met, total]}``
    deadline-met counts (empty without ``deadline``)."""
    import random as random_mod

    from tpukernels.serve import client as serve_client
    from tpukernels.serve import protocol as serve_protocol

    jitter = random_mod.Random(seed ^ 0x7E57ED)
    dl_rng = random_mod.Random(seed ^ 0xDEAD11)
    goodput: dict = {}

    def _mk(kernel):
        return f"{kernel}@{tenant}" if tenant else kernel

    # request tracing (docs/OBSERVABILITY.md §request tracing):
    # seeded-deterministic CLIENT-MINTED ids. The pid component is
    # the RUN scope: the supervisor's probe reruns the same seed into
    # the same daily journal, and without it two runs' events would
    # merge under identical ids — every timeline would look spilled
    # (clean=0, the consistency gate silently empty). The seed still
    # reproduces the schedule and the id suffixes.
    used_ids: list = []

    def _rid(tag) -> str:
        rid = f"lg{seed}-{os.getpid()}-{tag}"
        used_ids.append(rid)
        return rid

    def dispatch_patiently(cli, kernel, args, statics, rid,
                           warm=False, deadline_ms=None) -> bool:
        """One request, honoring backpressure (the shared
        ``dispatch_with_backpressure`` policy; the retry waits count
        in the caller's latency clock): ten rejections, a
        daemon-reported dispatch error, or transport trouble mid-run
        (the client reconnects lazily) drop the request LOUDLY
        (stderr + counter) — one daemon hiccup must never crash the
        remaining schedule or discard the samples already recorded.
        Every attempt journals a ``serve_client_request`` record —
        the client-observed wall the timeline assembler anchors
        phase coverage against."""
        cli.next_request_id = rid
        cli.next_deadline_ms = deadline_ms
        c0 = time.perf_counter()
        ok, err = True, None
        try:
            serve_client.dispatch_with_backpressure(
                cli, kernel, args, statics, jitter=jitter
            )
        except serve_client.ServeExpired as e:
            # the daemon's honest expiry/infeasibility reply: the
            # request missed its deadline — its own counter, NOT a
            # generic drop (goodput accounting below reads it)
            ok, err = False, "expired"
            obs_metrics.inc(f"slo.expired.{_mk(kernel)}")
            print(f"# {kernel} request missed its deadline: {e}",
                  file=sys.stderr)
        except serve_client.ServeRejected:
            ok, err = False, "rejected"
            obs_metrics.inc(f"slo.dropped.{_mk(kernel)}")
            print(f"# dropped {kernel} request after "
                  "10 rejection(s)", file=sys.stderr)
        except serve_client.ServeError as e:
            ok, err = False, f"daemon error: {e}"
            obs_metrics.inc(f"slo.dropped.{_mk(kernel)}")
            print(f"# dropped {kernel} request: daemon error "
                  f"{e}", file=sys.stderr)
        except (OSError, serve_protocol.ProtocolError) as e:
            ok, err = False, f"transport: {e!r}"
            obs_metrics.inc(f"slo.dropped.{_mk(kernel)}")
            print(f"# dropped {kernel} request: transport trouble "
                  f"{e!r}", file=sys.stderr)
        journal.emit(
            "serve_client_request", request_id=rid, kernel=kernel,
            tenant=tenant, warm=warm,
            wall_s=round(time.perf_counter() - c0, 6),
            ok=ok, error=err, deadline_ms=deadline_ms,
        )
        return ok

    cli = serve_client.ServeClient(socket_path, tenant=tenant,
                                   priority=priority)
    # trace-budget scope: only the journal bytes THIS run appends
    # matter (a day of prior probe traffic would otherwise be parsed
    # and assembled just to be filtered back out)
    trace_jp = journal.path()
    trace_jp_off = 0
    if trace_jp is not None:
        try:
            trace_jp_off = os.path.getsize(trace_jp)
        except OSError:
            trace_jp_off = 0
    stats = cli.ping()  # reachability gate: a dead socket aborts HERE
    bytes_before = stats.get("bytes_copied")
    prepared = {}
    for key in sorted({k for _t, k in schedule}):
        if replay is not None:
            kname = replay[key]["kernel"]
            args, statics = _replay_operands(replay[key])
        else:
            kname = key
            args, statics = _operands_np(key, shape_class)
        prepared[key] = (kname, args, statics)
        w0 = time.perf_counter()
        warmed = dispatch_patiently(cli, kname, args, statics,
                                    _rid(f"warm-{key}"), warm=True)
        echo(f"# warmed {kname} in {time.perf_counter() - w0:.3f}s"
             " (served)" + ("" if warmed else " DROPPED"))
    t0 = time.perf_counter()
    for i, (t, key) in enumerate(schedule):
        now = time.perf_counter() - t0
        if t > now:
            time.sleep(t - now)
        kname, args, statics = prepared[key]
        dl = None
        if deadline is not None:
            # per-request deadline off its own seeded stream; met =
            # completed ok within budget, measured from DISPATCH (the
            # moment the client stamps the budget), not the scheduled
            # arrival — open-loop schedule lag is the generator's
            # debt, not the service's
            dl = dl_rng.uniform(deadline[0], deadline[1])
            goodput.setdefault(_mk(kname), [0, 0])[1] += 1
        s0 = time.perf_counter()
        if dispatch_patiently(cli, kname, args, statics,
                              _rid(f"{i:05d}"), deadline_ms=dl):
            s1 = time.perf_counter()
            obs_metrics.inc(f"slo.requests.{_mk(kname)}")
            obs_metrics.observe(f"slo.latency_s.{_mk(kname)}",
                                (s1 - t0) - t)
            obs_metrics.observe(f"slo.service_s.{_mk(kname)}",
                                s1 - s0)
            if dl is not None and (s1 - s0) * 1000.0 <= dl:
                goodput[_mk(kname)][0] += 1
    # re-ping AFTER the dispatches: the daemon resolves device_kind /
    # jax lazily on its first dispatch, and the verdict record should
    # carry them when available — but a daemon that died at the very
    # end must not discard the run (keep the initial stats)
    try:
        stats = cli.ping()
    except (OSError, serve_protocol.ProtocolError):
        pass
    # copy-budget evidence (docs/SERVING.md §copy accounting): the
    # daemon-side serve.bytes_copied delta over this run, per request
    # (warms included — they ride the same lane). ``expected_zero``
    # marks the run the trend checker may GATE on: the shm lane was
    # negotiated, this client staged every operand, and the daemon's
    # threshold shms every response too — on such a run a single
    # copied byte is a zero-copy-path regression, flagged like a
    # bench regression by obs_report --check.
    bytes_after = stats.get("bytes_copied")
    if (isinstance(bytes_before, (int, float))
            and isinstance(bytes_after, (int, float))):
        n_req = len(schedule) + len(prepared)
        delta = max(0, bytes_after - bytes_before)
        lanes = stats.get("lanes") or ["inline"]
        shm_used = cli.staged_payloads > 0
        journal.emit(
            "serve_copy_budget", socket=socket_path,
            lane="shm" if shm_used else "inline", lanes=lanes,
            requests=n_req,
            daemon_bytes_copied=delta,
            bytes_per_request=round(delta / max(1, n_req), 3),
            client_bytes_copied=cli.bytes_copied,
            staged_payloads=cli.staged_payloads,
            inline_payloads=cli.inline_payloads,
            expected_zero=bool(
                "shm" in lanes and shm_used
                and cli.inline_payloads == 0
                and stats.get("shm_min_bytes") == 0
            ),
        )
    # trace-budget evidence (docs/OBSERVABILITY.md §request tracing):
    # assemble THIS run's request timelines from the journal TAIL
    # this run appended (daemon and client share the file in the
    # probe/test setups) and stamp the phase-sum-vs-wall summary the
    # trend checker judges (trace_inconsistent gates like the copy
    # budget; trace_coverage is the non-gating headroom twin). A
    # daemon journaling elsewhere assembles client-only timelines —
    # stamped with traced=0, which can never gate, and the report
    # says so.
    if trace_jp is not None:
        import json as _json

        from tpukernels.obs import reqtrace

        events = []
        try:
            with open(trace_jp) as f:
                f.seek(trace_jp_off)
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = _json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        events.append(rec)
        except OSError:
            events = []
        budget = reqtrace.run_budget(events, request_ids=used_ids)
        if budget is not None:
            journal.emit(
                "serve_trace_budget", socket=socket_path,
                server_traced=bool(stats.get("request_trace")),
                **budget,
            )
    cli.close()
    return stats, goodput


def _parse_deadline_ms(spec: str) -> tuple:
    """``--deadline-ms`` value -> a ``(lo_ms, hi_ms)`` range:
    ``250`` fixed, ``200:400`` uniform-in-range (sampled per request
    from a stream seeded off the run seed)."""
    if ":" in spec:
        lo_raw, hi_raw = spec.split(":", 1)
        lo, hi = float(lo_raw), float(hi_raw)
    else:
        lo = hi = float(spec)
    if lo <= 0 or hi < lo:
        raise ValueError(
            f"{spec!r}: want MS > 0 or LO:HI with 0 < LO <= HI"
        )
    return lo, hi


def _parse_mix(raw: str | None, kernel: str | None) -> dict:
    from tpukernels import aot

    known = sorted(aot.BENCH_CONFIGS)
    if kernel is not None:
        if kernel not in known:
            raise ValueError(
                f"unknown kernel {kernel!r}; known: {known}"
            )
        return {kernel: 1.0}
    if raw in (None, "all"):
        return {k: 1.0 for k in known}
    mix = {}
    for part in raw.split(","):
        name, _, w = part.partition("=")
        name = name.strip()
        if name not in known:
            raise ValueError(
                f"unknown kernel {name!r} in --mix; known: {known}"
            )
        mix[name] = float(w) if w else 1.0
    return mix


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    kernel = mix_raw = None
    arrivals, rate, requests = "poisson", DEFAULT_RATE, 200
    duration = simulate_ms = serve_sock = None
    tenant = priority = deadline = None
    seed = None
    shape_class, period = "probe", 60.0
    print_schedule = check = False
    it = iter(argv)
    try:
        for a in it:
            if a == "--kernel":
                kernel = next(it)
            elif a == "--serve":
                serve_sock = next(it)
            elif a == "--tenant":
                tenant = next(it)
            elif a == "--priority":
                priority = next(it)
            elif a == "--deadline-ms":
                deadline = _parse_deadline_ms(next(it))
            elif a == "--mix":
                mix_raw = next(it)
            elif a == "--arrivals":
                arrivals = next(it)
            elif a == "--rate":
                rate = float(next(it))
            elif a == "--requests":
                requests = int(next(it))
            elif a == "--duration":
                duration = float(next(it))
            elif a == "--period":
                period = float(next(it))
            elif a == "--seed":
                seed = int(next(it))
            elif a == "--shapes":
                shape_class = next(it)
            elif a == "--simulate":
                simulate_ms = float(next(it))
            elif a == "--print-schedule":
                print_schedule = True
            elif a == "--check":
                check = True
            else:
                print(__doc__, file=sys.stderr)
                print(f"loadgen: unknown argument {a!r}", file=sys.stderr)
                return 2
    except StopIteration:
        print(f"loadgen: {a} requires a value", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"loadgen: bad value for {a}: {e}", file=sys.stderr)
        return 2
    replay = None
    if shape_class not in ("probe", "record"):
        # anything else names a replay-spec FILE (docstring has the
        # format) — the adaptive-bucket canary's lane
        if serve_sock is None:
            print("loadgen: a --shapes replay spec requires --serve "
                  "(it replays observed traffic against a daemon's "
                  "bucket table)", file=sys.stderr)
            return 2
        if kernel is not None or mix_raw is not None:
            print("loadgen: --kernel/--mix don't combine with a "
                  "replay spec (the file IS the mix)",
                  file=sys.stderr)
            return 2
        try:
            replay, replay_mix = _load_replay(shape_class)
        except (OSError, ValueError) as e:
            print(f"loadgen: --shapes {shape_class!r}: {e} (known "
                  "classes: probe, record; anything else must be a "
                  "readable replay-spec file)", file=sys.stderr)
            return 2
        shape_class = "replay"
    if rate <= 0:
        print("loadgen: --rate must be > 0", file=sys.stderr)
        return 2
    if period <= 0:
        print("loadgen: --period must be > 0", file=sys.stderr)
        return 2
    if serve_sock is not None and simulate_ms is not None:
        print("loadgen: --serve and --simulate are exclusive (the "
              "virtual clock has no daemon)", file=sys.stderr)
        return 2
    if (tenant or priority) and serve_sock is None:
        print("loadgen: --tenant/--priority only apply to --serve "
              "runs (the router's admission point reads them)",
              file=sys.stderr)
        return 2
    if deadline is not None and serve_sock is None:
        print("loadgen: --deadline-ms only applies to --serve runs "
              "(the dispatch header carries the budget)",
              file=sys.stderr)
        return 2
    if tenant is not None and ("@" in tenant or "|" in tenant
                               or not tenant):
        print(f"loadgen: bad --tenant {tenant!r} (non-empty, no '@' "
              "or '|' - it joins metric and slo.json keys)",
              file=sys.stderr)
        return 2
    if priority is not None and priority not in ("interactive",
                                                 "batch"):
        print(f"loadgen: --priority {priority!r} (known: "
              "interactive, batch)", file=sys.stderr)
        return 2
    if serve_sock == "default":
        from tpukernels.serve import client as _serve_client

        serve_sock = _serve_client.default_socket_path()
    try:
        if seed is None:
            seed = default_seed()
        mix = (replay_mix if replay is not None
               else _parse_mix(mix_raw, kernel))
        schedule = build_schedule(
            seed, arrivals, rate, requests, duration, mix, period
        )
    except ValueError as e:
        print(f"loadgen: {e}", file=sys.stderr)
        return 2

    if print_schedule:
        # the byte-identical determinism surface: microsecond-rounded
        # offsets, no wall clock, no dispatch, no jax
        for t, k in schedule:
            print(f"{t:.6f} {k}")
        return 0

    # CLI journal default (the bench.py/revalidate.py contract) — the
    # slo_probe evidence must land in the day's health journal
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    # sampled oracle canaries are multi-ms outliers in exactly the
    # tail this tool measures; the always-on tripwire stays
    os.environ.setdefault("TPK_INTEGRITY", "tripwire")
    # env-derived hardware stamp (docs/OBSERVABILITY.md §scaling):
    # --simulate must never import jax, so the probe stays off; the
    # slo_probe event below carries the jax-resolved device_kind for
    # real runs
    obs_scaling.emit_inventory("loadgen")

    echo = lambda line: print(line)  # noqa: E731
    serve_stats = None
    goodput: dict = {}
    t_run0 = time.perf_counter()
    with trace.span("loadgen/run", arrivals=arrivals, seed=seed):
        if simulate_ms is not None:
            run_simulated(schedule, seed, simulate_ms)
            kind = "cpu"
        elif serve_sock is not None:
            from tpukernels.serve import protocol as serve_protocol

            try:
                serve_stats, goodput = run_serve(
                    schedule, shape_class, serve_sock, echo,
                    seed=seed, tenant=tenant, priority=priority,
                    replay=replay, deadline=deadline,
                )
            except (OSError, serve_protocol.ProtocolError) as e:
                print(f"loadgen: serve daemon at {serve_sock} "
                      f"unreachable: {e}", file=sys.stderr)
                return 2
            # the daemon is the device-bound process; judge against
            # ITS device kind, not this jax-free client's
            kind = serve_stats.get("device_kind") or "cpu"
        else:
            run_real(schedule, shape_class, echo)
            from tpukernels.tuning import cache as tcache

            kind = tcache.device_kind()
    wall = time.perf_counter() - t_run0

    per_kernel = slo.histograms_by_kernel(
        obs_metrics.snapshot()["histograms"]
    )
    verdicts = slo.judge(
        per_kernel, kind, shape_class,
        simulated=simulate_ms is not None,
        goodput=goodput or None,
    )
    jax_version = None
    if serve_stats is not None:
        jax_version = serve_stats.get("jax")
    elif simulate_ms is None:
        import jax

        jax_version = jax.__version__
    run_info = {
        "arrivals": arrivals, "seed": seed, "rate": rate,
        "requests": len(schedule), "duration": duration,
        "wall_s": round(wall, 3),
        "served": serve_sock is not None,
    }
    if tenant:
        run_info["tenant"] = tenant
        run_info["priority"] = priority or "interactive"
    if deadline is not None:
        run_info["deadline_ms"] = list(deadline)
        run_info["goodput"] = {k: list(v) for k, v in goodput.items()}
    artifact = slo.record(verdicts, run_info, jax_version=jax_version)
    journal.emit(
        "slo_probe", **run_info, device_kind=kind,
        shape_class=shape_class,
        simulated=simulate_ms is not None, artifact=artifact,
        verdicts={
            k: {"verdict": v["verdict"], "count": v["count"],
                "p50_s": v["p50_s"], "p99_s": v["p99_s"],
                "target_p99_s": v["target_p99_s"]}
            for k, v in verdicts.items()
        },
    )

    # the goodput column exists only on deadline-carrying runs: with
    # --deadline-ms unset the table (and every other stdout byte) is
    # identical to a pre-deadline run
    gp_col = deadline is not None
    hdr = (f"{'kernel':<16} {'n':>5} {'p50_ms':>9} {'p95_ms':>9} "
           f"{'p99_ms':>9} {'max_ms':>9} {'target':>9} "
           + (f"{'goodput':>8} " if gp_col else "")
           + " verdict")
    print(hdr)
    print("-" * len(hdr))

    def _ms(v):
        return slo.fmt_ms(v, 9)

    def _gp(v):
        frac = v.get("goodput_frac")
        if frac is None:
            return f"{'-':>8} "
        return f"{frac:>8.1%} "

    breached = []
    for k, v in verdicts.items():
        print(f"{k:<16} {v['count']:>5} {_ms(v['p50_s'])} "
              f"{_ms(v['p95_s'])} {_ms(v['p99_s'])} {_ms(v['max_s'])} "
              f"{_ms(v['target_p99_s'])} "
              + (_gp(v) if gp_col else "")
              + f" {v['verdict']}"
              + (f" ({v['why']})" if v.get("why") else ""))
        if v["verdict"] == "slo_breach" and not v["simulated"]:
            breached.append(k)
    met = sum(v[0] for v in goodput.values())
    total = sum(v[1] for v in goodput.values())
    print(
        f"loadgen: {len(schedule)} request(s), {arrivals} arrivals, "
        f"seed {seed}, {shape_class} shapes on {kind}"
        + (" (SIMULATED)" if simulate_ms is not None else "")
        + (" (SERVED)" if serve_sock is not None else "")
        + (f", goodput {met}/{total}"
           + (f" ({met / total:.1%})" if total else "")
           if gp_col else "")
        + f", wall {wall:.1f}s -> {os.path.relpath(artifact)}"
        + (f"; BREACH: {','.join(breached)}" if breached else "")
    )
    return 1 if (check and breached) else 0


if __name__ == "__main__":
    sys.exit(main())
