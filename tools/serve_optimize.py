"""Traffic-adaptive bucket optimizer CLI (docs/SERVING.md §adaptive
buckets; the control plane over ``tpukernels/serve/adapt.py``).

Usage:
    python tools/serve_optimize.py propose [--journal PATH ...]
                                           [--target F] [--check]
    python tools/serve_optimize.py canary  [--seed N] [--requests N]
                                           [--rate R] [--autotune MODE]
                                           [--margin F] [--check]
    python tools/serve_optimize.py show

``propose`` (CPU-only, jax never dispatches) mines the health
journal's ``serve_request`` shape mix, projects it against the
incumbent ``TPK_SERVE_BUCKETS`` table, and — when the projected mean
pad_frac sits at or above ``TPK_ADAPT_PAD_TARGET`` and at least
``TPK_ADAPT_MIN_REQUESTS`` requests back the evidence — persists the
ranked split/merge candidate as ``adapt.json`` (journal:
``adapt_proposed``). No traffic, no proposal: a quiet journal exits 0
saying so.

``canary`` judges a persisted candidate END TO END, off-window: it
optionally re-autotunes the candidate table's kernels (``--autotune
smoke|quick``; the >3% tuning margin applies there as everywhere),
then boots an INCUMBENT daemon and a CANDIDATE daemon on throwaway
sockets — each with its own side table file, serve dir and journal —
and replays the candidate's frozen shape mix through ``tools/
loadgen.py --shapes <replay>`` against both at IDENTICAL seeds (the
per-entry warm dispatches double as the candidate table's prewarm).
The measured sides meet :func:`adapt.judge_canary`: promotion needs a
pad_frac win over the incumbent beyond the tuning layer's
PROMOTE_MARGIN **and** a strictly better p99. A win atomically
rewrites the stable ``buckets.json`` the fleet's ``TPK_SERVE_BUCKETS``
points at (journal: ``adapt_promoted``) — a running router/daemon
picks it up on ``undrain``, no restart; a loss records
``adapt_rejected`` and the incumbent file is never touched. Either
way the verdict lands in ``adapt.json`` and an ``adapt_canary``
journal event.

Exit codes: 0 — did what the verb asked (including "nothing to do");
1 — with ``--check``, the canary measured and REJECTED the candidate
(or a verb's machinery failed); 2 — usage error.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpukernels.resilience import journal  # noqa: E402
from tpukernels.serve import adapt  # noqa: E402


def _mine_events(paths):
    events, bad = journal.load_events(paths)
    if bad:
        print(f"# serve_optimize: {bad} unparseable journal line(s) "
              "skipped", file=sys.stderr)
    return events


def _cmd_propose(journals, target, check):
    from tpukernels.serve import bucketing

    if target is None:
        target = adapt.pad_target()
    need = adapt.min_requests()
    events = _mine_events(journals)
    days = adapt.window_days()
    mix, days_used = adapt.window_mix(events, days=days)
    seen = adapt.mix_requests(mix)
    if days > 1:
        print(f"serve_optimize: mining a {days}-day window "
              f"(TPK_ADAPT_WINDOW_DAYS): today's journal + "
              f"{days_used - 1} prior rollup day(s), {seen} "
              "request(s) total")
    max_pad = bucketing.max_pad_frac()
    incumbent = bucketing.bucket_configs()
    if seen < need:
        print(f"serve_optimize: {seen} OK serve_request(s) mined < "
              f"TPK_ADAPT_MIN_REQUESTS={need} - no proposal (a table "
              "re-shaped around an anecdote would thrash)")
        return 0
    before = adapt.project(incumbent, mix, max_pad)
    hist = adapt.histogram_pad_frac(events)
    if before["pad_frac"] < target and before["native"] == 0:
        print(f"serve_optimize: projected pad_frac "
              f"{before['pad_frac']:.3f} already below target "
              f"{target} over {seen} request(s) - no proposal")
        return 0
    result = adapt.propose(mix, incumbent, target, max_pad=max_pad)
    if not result["proposals"]:
        print("serve_optimize: no split/merge improves on the "
              f"incumbent (pad_frac {before['pad_frac']:.3f}, "
              f"{before['native']} native) - no proposal")
        return 0
    p = adapt.record_candidate(result, mix, target)
    journal.emit(
        "adapt_proposed", path=p, requests_mined=seen,
        pad_target=target,
        hist_pad_frac=hist,
        window_days=days_used,
        proposals=[
            {"action": a["action"], "kernel": a["kernel"],
             "waste_saved": a["waste_saved"],
             "compiles": a["compiles"]}
            for a in result["proposals"]
        ],
        before=result["before"], after=result["after"],
    )
    splits = sum(a["action"] == "split" for a in result["proposals"])
    merges = len(result["proposals"]) - splits
    print(f"serve_optimize: proposed {splits} split(s), {merges} "
          f"merge(s) over {seen} request(s): projected pad_frac "
          f"{result['before']['pad_frac']:.3f} -> "
          f"{result['after']['pad_frac']:.3f} (target {target}), "
          f"native {result['before']['native']} -> "
          f"{result['after']['native']} -> {os.path.relpath(p)}")
    print("serve_optimize: next: python tools/serve_optimize.py "
          "canary")
    return 0


def _spawn_daemon(sock, table_path, side_dir, side_journal):
    """One canary-side daemon on a throwaway socket: its own table
    file, serve dir and journal, inheriting everything else (platform
    knobs included) from this process."""
    env = dict(os.environ)
    env["TPK_SERVE_BUCKETS"] = table_path
    env["TPK_SERVE_DIR"] = side_dir
    env["TPK_HEALTH_JOURNAL"] = side_journal
    env.pop("TPK_SERVE_SOCKET", None)  # --socket is authoritative
    return subprocess.Popen(
        [sys.executable, "-m", "tpukernels.serve", "--socket", sock],
        cwd=_REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )


def _wait_ready(proc, sock, timeout_s=60.0):
    from tpukernels.serve import client as serve_client

    deadline = time.monotonic() + timeout_s
    while True:
        if proc.poll() is not None:
            raise RuntimeError(
                f"canary daemon died rc={proc.returncode}: "
                f"{(proc.communicate()[1] or '').strip()[-500:]}"
            )
        try:
            with serve_client.ServeClient(sock, timeout_s=5) as c:
                c.ping()
            return
        except OSError:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"canary daemon at {sock} never answered ping"
                )
            time.sleep(0.1)


def _reap(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(20)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(10)


def _run_side(name, table, replay_path, seed, requests, rate, tmp,
              echo):
    """Boot one side's daemon, replay the frozen mix through loadgen
    at ``seed``, reap, and measure the side's isolated journal."""
    side_dir = os.path.join(tmp, name)
    os.makedirs(side_dir, exist_ok=True)
    table_path = os.path.join(side_dir, "buckets.json")
    with open(table_path, "w") as f:
        json.dump(table, f)
    sock = os.path.join(side_dir, "s.sock")
    side_journal = os.path.join(side_dir, "health.jsonl")
    proc = _spawn_daemon(sock, table_path, side_dir, side_journal)
    try:
        _wait_ready(proc, sock)
        env = dict(os.environ)
        env["TPK_HEALTH_JOURNAL"] = side_journal
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "loadgen.py"),
             "--serve", sock, "--shapes", replay_path,
             "--seed", str(seed), "--requests", str(requests),
             "--rate", str(rate)],
            cwd=_REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        if r.returncode != 0:
            raise RuntimeError(
                f"{name} replay loadgen rc={r.returncode}: "
                f"{(r.stdout or '').strip()[-500:]}"
            )
    finally:
        _reap(proc)
    side = adapt.measured_side(_mine_events([side_journal]))
    echo(f"# {name}: pad_frac="
         + (f"{side['pad_frac']:.4f}" if side["pad_frac"] is not None
            else "n/a")
         + " p99="
         + (f"{side['p99_s'] * 1e3:.2f}ms" if side["p99_s"] is not None
            else "n/a")
         + f" over {side['requests']} request(s), "
         f"{side['bucketed']} bucketed")
    return side


def _cmd_canary(seed, requests, rate, autotune, margin, check):
    cand = adapt.load()
    if cand is None:
        print("serve_optimize: no valid adapt.json candidate - run "
              "propose first (a stale/torn one was rejected loudly "
              "above)")
        return 1 if check else 0
    if cand.get("status") != "proposed":
        print(f"serve_optimize: candidate already judged "
              f"(status {cand.get('status')!r}) - propose again for "
              "a fresh one")
        return 0
    replay = cand.get("replay") or []
    if not replay:
        print("serve_optimize: candidate has no replay entries - "
              "nothing measurable", file=sys.stderr)
        return 1
    from tpukernels.serve import bucketing

    incumbent = bucketing.bucket_configs()
    echo = print
    if autotune != "off":
        from tpukernels.tuning import runner

        echo(f"# re-autotuning candidate table kernels "
             f"({autotune})...")
        runner.tune_table(
            cand["table"], smoke=autotune == "smoke",
            quick=autotune == "quick", echo=echo,
        )
    tmp = tempfile.mkdtemp(prefix="tpk_adapt_canary_")
    try:
        replay_path = os.path.join(tmp, "replay.json")
        with open(replay_path, "w") as f:
            json.dump({"entries": replay}, f)
        inc_m = _run_side("incumbent", incumbent, replay_path, seed,
                          requests, rate, tmp, echo)
        cand_m = _run_side("candidate", cand["table"], replay_path,
                           seed, requests, rate, tmp, echo)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    verdict = adapt.judge_canary(cand_m, inc_m, margin=margin)
    journal.emit(
        "adapt_canary", path=adapt.path(), seed=seed,
        requests=requests, promote=verdict["promote"],
        reason=verdict["reason"], pad_win=verdict.get("pad_win"),
        candidate=cand_m, incumbent=inc_m,
    )
    status = "promoted" if verdict["promote"] else "rejected"

    def _stamp(data):
        data["status"] = status
        data["canary"] = {
            "seed": seed, "requests": requests,
            "verdict": dict(verdict),
        }
        return data

    adapt.update(_stamp)
    if verdict["promote"]:
        bp = adapt.promote(cand["table"])
        journal.emit(
            "adapt_promoted", path=adapt.path(), table=bp,
            pad_frac=cand_m["pad_frac"], p99_s=cand_m["p99_s"],
            pad_win=verdict.get("pad_win"), seed=seed,
        )
        print(f"serve_optimize: PROMOTED - {verdict['reason']}")
        print(f"serve_optimize: table -> {os.path.relpath(bp)}; "
              f"point TPK_SERVE_BUCKETS={bp} and undrain "
              "(fleetctl undrain / the daemon's undrain op) to pick "
              "it up live")
        return 0
    journal.emit(
        "adapt_rejected", path=adapt.path(), reason=verdict["reason"],
        pad_win=verdict.get("pad_win"), candidate=cand_m,
        incumbent=inc_m,
    )
    print(f"serve_optimize: REJECTED - {verdict['reason']} "
          "(incumbent stays)")
    return 1 if check else 0


def _cmd_show():
    cand = adapt.load(validate=False)
    if cand is None:
        print("serve_optimize: no adapt.json candidate at "
              + adapt.path())
        return 0
    print(json.dumps(cand, indent=2, sort_keys=True))
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] not in ("propose", "canary", "show"):
        print(__doc__, file=sys.stderr)
        print("serve_optimize: want a verb: propose | canary | show",
              file=sys.stderr)
        return 2
    verb, rest = argv[0], argv[1:]
    journals: list = []
    target = margin = None
    seed, requests, rate = 7, 60, 50.0
    autotune = "off"
    check = False
    it = iter(rest)
    try:
        for a in it:
            if a == "--journal":
                journals.append(next(it))
            elif a == "--target":
                target = float(next(it))
            elif a == "--seed":
                seed = int(next(it))
            elif a == "--requests":
                requests = int(next(it))
            elif a == "--rate":
                rate = float(next(it))
            elif a == "--margin":
                margin = float(next(it))
            elif a == "--autotune":
                autotune = next(it)
            elif a == "--check":
                check = True
            else:
                print(__doc__, file=sys.stderr)
                print(f"serve_optimize: unknown argument {a!r}",
                      file=sys.stderr)
                return 2
    except StopIteration:
        print(f"serve_optimize: {a} requires a value", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"serve_optimize: bad value for {a}: {e}",
              file=sys.stderr)
        return 2
    if autotune not in ("off", "smoke", "quick"):
        print(f"serve_optimize: --autotune {autotune!r} (known: off, "
              "smoke, quick)", file=sys.stderr)
        return 2
    if target is not None and not 0.0 < target <= 1.0:
        print(f"serve_optimize: --target {target} must be in (0, 1]",
              file=sys.stderr)
        return 2
    # unattended runs land their evidence in the day's journal — the
    # loadgen/bench/prewarm CLI routing default
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    if not journals:
        journals = [journal.path() or journal.default_path()]
    try:
        if verb == "propose":
            return _cmd_propose(journals, target, check)
        if verb == "canary":
            return _cmd_canary(seed, requests, rate, autotune, margin,
                               check)
        return _cmd_show()
    except (RuntimeError, ValueError) as e:
        print(f"serve_optimize: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
