"""Lint: every journal event kind must be documented.

Usage:
    python tools/journal_kinds.py          # rc 0 clean, rc 1 findings

Scans every production ``journal.emit(kind=...)`` callsite (bench.py,
``tpukernels/``, ``tools/`` — tests may emit throwaway kinds) and
asserts each kind literal appears in the event-kind catalog of
docs/OBSERVABILITY.md. The catalog is the contract consumers key on —
``tools/health_report.py`` narrative lines, ``tools/obs_report.py``
aggregation, postmortem greps — so an undocumented kind is a consumer
silently skipping events, which is exactly the failure mode the
observability layer exists to remove. Runs in tier-1 via
``tests/test_obs.py::test_journal_kinds_lint``.

Also warns (without failing) on documented-but-unused kinds — usually
a callsite that was deleted without its doc row — and fails on
``journal.emit`` callsites whose kind is not a string literal, which
this lint cannot check (none exist today; keep it that way).
"""

from __future__ import annotations

import glob
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DOC = os.path.join(_REPO, "docs", "OBSERVABILITY.md")

# \s* spans the newline of a wrapped call; the literal must be the
# first argument, matching every callsite idiom in the repo. \w+
# (not [a-z_]+): a kind like "phase2_start" must be linted, not
# silently skipped by a too-narrow character class.
_EMIT_RE = re.compile(r"journal\.emit\(\s*([\"']\w+[\"']|[^\s\"'])")
_DOC_KIND_RE = re.compile(r"^\|\s*`(\w+)`", re.MULTILINE)


def production_files(repo=_REPO):
    files = [os.path.join(repo, "bench.py")]
    for sub in ("tpukernels", "tools"):
        files.extend(
            sorted(
                glob.glob(
                    os.path.join(repo, sub, "**", "*.py"), recursive=True
                )
            )
        )
    # the lint's own docstring mentions journal.emit(kind=...) —
    # scanning itself would flag that prose as an unlintable callsite
    return [
        f for f in files
        if os.path.isfile(f)
        and os.path.basename(f) != "journal_kinds.py"
    ]


def emitted_kinds(repo=_REPO):
    """{kind: [file:line, ...]} over production callsites, plus a list
    of unlintable (non-literal-kind) callsites."""
    kinds, unlintable = {}, []
    for path in production_files(repo):
        with open(path) as f:
            text = f.read()
        rel = os.path.relpath(path, repo)
        for m in _EMIT_RE.finditer(text):
            where = f"{rel}:{text.count(chr(10), 0, m.start()) + 1}"
            tok = m.group(1)
            if tok[0] in "\"'":
                kinds.setdefault(tok.strip("\"'"), []).append(where)
            else:
                unlintable.append(where)
    return kinds, unlintable


def documented_kinds(doc=_DOC):
    try:
        with open(doc) as f:
            return set(_DOC_KIND_RE.findall(f.read()))
    except OSError:
        return set()


def main(argv=None):
    repo = _REPO
    argv = sys.argv[1:] if argv is None else list(argv)
    it = iter(argv)
    for a in it:
        if a == "--root":
            try:
                repo = next(it)
            except StopIteration:
                print("journal_kinds: --root requires a value",
                      file=sys.stderr)
                return 2
        else:
            # an ignored argument must not silently lint the wrong
            # tree and report OK
            print(f"journal_kinds: unknown argument {a!r}",
                  file=sys.stderr)
            return 2
    kinds, unlintable = emitted_kinds(repo)
    documented = documented_kinds(
        os.path.join(repo, "docs", "OBSERVABILITY.md")
    )
    rc = 0
    if not documented:
        print("journal_kinds: docs/OBSERVABILITY.md has no kind "
              "catalog (| `kind` | rows) - nothing to lint against")
        rc = 1
    undocumented = {k: v for k, v in kinds.items() if k not in documented}
    for kind in sorted(undocumented):
        print(
            f"journal_kinds: kind {kind!r} is emitted but not in the "
            "docs/OBSERVABILITY.md catalog:"
        )
        for where in undocumented[kind]:
            print(f"  {where}")
        rc = 1
    for where in unlintable:
        print(
            f"journal_kinds: non-literal kind at {where} - "
            "unlintable; pass the kind as a string literal"
        )
        rc = 1
    unused = documented - set(kinds)
    for kind in sorted(unused):
        print(
            f"journal_kinds: WARN documented kind {kind!r} has no "
            "production callsite (stale doc row?)"
        )
    if rc == 0:
        print(
            f"journal_kinds: OK - {len(kinds)} kinds across "
            f"{sum(len(v) for v in kinds.values())} callsites, all "
            "documented"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
