"""Lint: every journal event kind must be documented.

Usage:
    python tools/journal_kinds.py          # rc 0 clean, rc 1 findings

Scans every production ``journal.emit(kind=...)`` callsite (bench.py,
``tpukernels/``, ``tools/`` — tests may emit throwaway kinds) and
asserts each kind literal appears in the event-kind catalog of
docs/OBSERVABILITY.md. The catalog is the contract consumers key on —
``tools/health_report.py`` narrative lines, ``tools/obs_report.py``
aggregation, postmortem greps — so an undocumented kind is a consumer
silently skipping events, which is exactly the failure mode the
observability layer exists to remove. Runs in tier-1 via
``tests/test_obs.py::test_journal_kinds_lint``.

Also warns (without failing) on documented-but-unused kinds — usually
a callsite that was deleted without its doc row — and fails on
``journal.emit`` callsites whose kind is not a string literal, which
this lint cannot check (none exist today; keep it that way).

Request-id lint (docs/OBSERVABILITY.md §request tracing): the
catalog's "Traced kinds (request-id lint)" line names the serve-path
kinds whose every production emit MUST pass a ``request_id=`` field —
one untagged callsite is a hole in every future timeline, found only
during the incident the tracing layer exists to shorten. Enforced
here (rc 1) and therefore in tier-1 via the same test.

Fault-key lint (docs/RESILIENCE.md §fault injection): every plan key
``resilience/faults.py`` consumes (its literal ``_PLAN.get("...")``
lookups) must have a ``| `key` |`` row in docs/RESILIENCE.md's fault
table — the table is the chaos vocabulary operators and campaign
runners (``tools/chaos.py``) compose from, so an undocumented key is
an injection point nobody can discover. Same rc 1 / tier-1
enforcement.
"""

from __future__ import annotations

import glob
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DOC = os.path.join(_REPO, "docs", "OBSERVABILITY.md")

# \s* spans the newline of a wrapped call; the literal must be the
# first argument, matching every callsite idiom in the repo. \w+
# (not [a-z_]+): a kind like "phase2_start" must be linted, not
# silently skipped by a too-narrow character class.
_EMIT_RE = re.compile(r"journal\.emit\(\s*([\"']\w+[\"']|[^\s\"'])")
_DOC_KIND_RE = re.compile(r"^\|\s*`(\w+)`", re.MULTILINE)
# the doc PARAGRAPH naming the kinds whose emits must carry
# request_id= (markdown wraps it across lines, so the match runs to
# the em dash that ends the kind list, or the blank line before it)
_TRACED_RE = re.compile(
    r"Traced kinds \(request-id lint\):(.*?)(?:—|\n\n)", re.DOTALL
)


def _call_text(text: str, start: int) -> str:
    """The balanced-paren call text from the ``(`` at ``start`` —
    string literals AND ``#`` comments are skipped, so a paren inside
    an error message or an apostrophe in a trailing comment cannot
    desync the scan."""
    depth, i, n, in_str = 0, start, len(text), None
    while i < n:
        c = text[i]
        if in_str is not None:
            if c == "\\":
                i += 2
                continue
            if c == in_str:
                in_str = None
        elif c == "#":
            nl = text.find("\n", i)
            i = n if nl < 0 else nl
        elif c in "\"'":
            in_str = c
        elif c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return text[start:i + 1]
        i += 1
    return text[start:]


def traced_kinds(doc=_DOC):
    """Kinds the catalog marks as request-traced (empty set when the
    doc lacks the marker line — old checkouts and the mini-repo test
    fixtures lint kind documentation only)."""
    try:
        with open(doc) as f:
            m = _TRACED_RE.search(f.read())
    except OSError:
        return set()
    if not m:
        return set()
    return set(re.findall(r"`(\w+)`", m.group(1)))


def production_files(repo=_REPO):
    files = [os.path.join(repo, "bench.py")]
    for sub in ("tpukernels", "tools"):
        files.extend(
            sorted(
                glob.glob(
                    os.path.join(repo, sub, "**", "*.py"), recursive=True
                )
            )
        )
    # the lint's own docstring mentions journal.emit(kind=...) —
    # scanning itself would flag that prose as an unlintable callsite
    return [
        f for f in files
        if os.path.isfile(f)
        and os.path.basename(f) != "journal_kinds.py"
    ]


def emitted_kinds(repo=_REPO):
    """{kind: [file:line, ...]} over production callsites, plus a list
    of unlintable (non-literal-kind) callsites."""
    kinds, unlintable = {}, []
    for path in production_files(repo):
        with open(path) as f:
            text = f.read()
        rel = os.path.relpath(path, repo)
        for m in _EMIT_RE.finditer(text):
            where = f"{rel}:{text.count(chr(10), 0, m.start()) + 1}"
            tok = m.group(1)
            if tok[0] in "\"'":
                kinds.setdefault(tok.strip("\"'"), []).append(where)
            else:
                unlintable.append(where)
    return kinds, unlintable


def untagged_traced_callsites(repo=_REPO, traced=None):
    """``[(kind, file:line), ...]`` — production emits of a traced
    kind whose call text carries no ``request_id=`` field."""
    if traced is None:
        traced = traced_kinds(
            os.path.join(repo, "docs", "OBSERVABILITY.md")
        )
    if not traced:
        return []
    missing = []
    for path in production_files(repo):
        with open(path) as f:
            text = f.read()
        rel = os.path.relpath(path, repo)
        for m in _EMIT_RE.finditer(text):
            tok = m.group(1)
            if tok[0] not in "\"'" or tok.strip("\"'") not in traced:
                continue
            call = _call_text(text, text.index("(", m.start()))
            if "request_id" not in call:
                where = f"{rel}:{text.count(chr(10), 0, m.start()) + 1}"
                missing.append((tok.strip("\"'"), where))
    return missing


def documented_kinds(doc=_DOC):
    try:
        with open(doc) as f:
            return set(_DOC_KIND_RE.findall(f.read()))
    except OSError:
        return set()


# literal plan-key lookups in the fault module; the few
# loop-variable lookups iterate over literal tuples whose members are
# also looked up (or documented) individually
_FAULT_KEY_RE = re.compile(r"_PLAN\.get\(\s*[\"'](\w+)[\"']")


def fault_plan_keys(repo=_REPO):
    """Plan keys resilience/faults.py consumes (empty when the module
    is absent — the mini-repo test fixtures)."""
    path = os.path.join(repo, "tpukernels", "resilience", "faults.py")
    try:
        with open(path) as f:
            return sorted(set(_FAULT_KEY_RE.findall(f.read())))
    except OSError:
        return []


def undocumented_fault_keys(repo=_REPO):
    """Fault plan keys with no ``| `key` |`` row in the
    docs/RESILIENCE.md fault table."""
    doc = os.path.join(repo, "docs", "RESILIENCE.md")
    documented = set()
    try:
        with open(doc) as f:
            for line in f:
                # the row's FIRST cell may name several keys that
                # share one contract (| `fail_capi` / `wedge_capi` |)
                m = re.match(r"\|([^|]*)\|", line)
                if m:
                    documented.update(re.findall(r"`(\w+)`", m.group(1)))
    except OSError:
        pass
    return [k for k in fault_plan_keys(repo) if k not in documented]


def main(argv=None):
    repo = _REPO
    argv = sys.argv[1:] if argv is None else list(argv)
    it = iter(argv)
    for a in it:
        if a == "--root":
            try:
                repo = next(it)
            except StopIteration:
                print("journal_kinds: --root requires a value",
                      file=sys.stderr)
                return 2
        else:
            # an ignored argument must not silently lint the wrong
            # tree and report OK
            print(f"journal_kinds: unknown argument {a!r}",
                  file=sys.stderr)
            return 2
    kinds, unlintable = emitted_kinds(repo)
    documented = documented_kinds(
        os.path.join(repo, "docs", "OBSERVABILITY.md")
    )
    rc = 0
    if not documented:
        print("journal_kinds: docs/OBSERVABILITY.md has no kind "
              "catalog (| `kind` | rows) - nothing to lint against")
        rc = 1
    undocumented = {k: v for k, v in kinds.items() if k not in documented}
    for kind in sorted(undocumented):
        print(
            f"journal_kinds: kind {kind!r} is emitted but not in the "
            "docs/OBSERVABILITY.md catalog:"
        )
        for where in undocumented[kind]:
            print(f"  {where}")
        rc = 1
    for where in unlintable:
        print(
            f"journal_kinds: non-literal kind at {where} - "
            "unlintable; pass the kind as a string literal"
        )
        rc = 1
    traced = traced_kinds(
        os.path.join(repo, "docs", "OBSERVABILITY.md")
    )
    untagged = untagged_traced_callsites(repo, traced)
    for kind, where in untagged:
        print(
            f"journal_kinds: traced kind {kind!r} emitted WITHOUT "
            f"request_id at {where} (docs/OBSERVABILITY.md §request "
            "tracing: every serve-path emit of a traced kind must "
            "carry the causal id)"
        )
        rc = 1
    undoc_faults = undocumented_fault_keys(repo)
    for key in undoc_faults:
        print(
            f"journal_kinds: fault plan key {key!r} is consumed by "
            "resilience/faults.py but has no row in the "
            "docs/RESILIENCE.md fault table (the chaos vocabulary "
            "contract)"
        )
        rc = 1
    unused = documented - set(kinds)
    for kind in sorted(unused):
        print(
            f"journal_kinds: WARN documented kind {kind!r} has no "
            "production callsite (stale doc row?)"
        )
    if rc == 0:
        print(
            f"journal_kinds: OK - {len(kinds)} kinds across "
            f"{sum(len(v) for v in kinds.values())} callsites, all "
            f"documented; {len(traced)} traced kind(s) all carry "
            f"request_id; {len(fault_plan_keys(repo))} fault key(s) "
            "all documented"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
