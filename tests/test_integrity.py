"""CPU chaos suite for the output-integrity guard
(docs/RESILIENCE.md §output integrity; tpukernels/resilience/
integrity.py).

Drives the ``corrupt_output`` / ``nan_output`` fault keys through
every guarded dispatch path — ``registry.dispatch``, bench's measure
phases, ``capi.run_from_c``, autotune sweep candidates, and the AOT
prewarm first-trust smoke — asserting the acceptance contract:
detected within one call, journaled as ``output_integrity_failed``,
the (kernel, config) quarantined with its AOT executable memo
invalidated, NEVER a crash of the surrounding run, and clean-path
bench stdout byte-identical whether the guard is on-and-passing or
``TPK_INTEGRITY=0``. Plus the envelope manifest's tuning-cache-style
staleness rules and the clean canary-vs-oracle proof for every
registered kernel.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events(journal_path, kind=None):
    if not os.path.exists(journal_path):
        return []
    recs = []
    with open(journal_path) as f:
        for line in f:
            if line.strip():
                recs.append(json.loads(line))
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs


class _Rig:
    """Isolated guard state: tmp integrity dir + journal, fault-plan
    control, always-restored module state."""

    def __init__(self, monkeypatch, tmp_path):
        from tpukernels.resilience import faults, integrity

        self.faults = faults
        self.integrity = integrity
        self.dir = tmp_path / "integ"
        self.dir.mkdir(exist_ok=True)
        self.journal = tmp_path / "health.jsonl"
        monkeypatch.setenv("TPK_INTEGRITY_DIR", str(self.dir))
        monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(self.journal))
        monkeypatch.delenv("TPK_INTEGRITY", raising=False)
        monkeypatch.delenv("TPK_FAULT_PLAN", raising=False)
        self._mp = monkeypatch
        faults.reload_plan()
        integrity.reset()

    def set_plan(self, plan):
        self._mp.setenv("TPK_FAULT_PLAN", json.dumps(plan))
        self.faults.reload_plan()

    def clear_plan(self):
        self._mp.delenv("TPK_FAULT_PLAN", raising=False)
        self.faults.reload_plan()

    def events(self, kind=None):
        return _events(self.journal, kind)


@pytest.fixture
def rig(monkeypatch, tmp_path):
    r = _Rig(monkeypatch, tmp_path)
    yield r
    # module-level fault/guard state outlives monkeypatch's env restore
    monkeypatch.delenv("TPK_FAULT_PLAN", raising=False)
    r.faults.reload_plan()
    r.integrity.reset()


# ---------------------------------------------------------------- #
# clean path: every kernel's canary matches its oracle              #
# ---------------------------------------------------------------- #

def test_all_canaries_match_oracles(rig):
    """The guard's authority check, clean: every registry kernel's
    canary run agrees with its jnp oracle within the documented
    tolerance (exact for the int32 kernels). This is what makes a
    guard failure evidence of corruption rather than flakiness."""
    from tpukernels import registry

    for name in registry.names():
        assert rig.integrity.cross_check(name) is None, name
    assert not rig.events("output_integrity_failed")


def test_guard_disabled_is_single_check(rig, monkeypatch):
    monkeypatch.setenv("TPK_INTEGRITY", "0")
    rig.set_plan({"nan_output": {"kernel": "vector_add"}})
    out = rig.integrity.guard(
        "registry", "vector_add", np.ones(4, np.float32)
    )
    # off = untouched passthrough: no corruption applied, no events
    assert np.all(np.isfinite(out))
    assert not rig.events()


# ---------------------------------------------------------------- #
# guarded path 1: registry.dispatch                                  #
# ---------------------------------------------------------------- #

def test_registry_nan_detected_within_one_call(rig):
    """Tier-1 tripwire: a NaN-corrupted dispatch result is detected on
    THAT call, journaled, AOT-invalidated — and returned, not raised
    (the surrounding run must survive)."""
    import jax.numpy as jnp

    from tpukernels import registry

    rig.set_plan({"nan_output": {"kernel": "vector_add",
                                 "site": "registry"}})
    out = registry.dispatch(
        "vector_add", jnp.float32(1.0),
        jnp.asarray(np.ones(256, np.float32)),
        jnp.asarray(np.ones(256, np.float32)),
    )
    assert not bool(jnp.isfinite(out).all())  # corrupted, returned
    fails = rig.events("output_integrity_failed")
    assert len(fails) == 1
    assert fails[0]["kernel"] == "vector_add"
    assert fails[0]["site"] == "registry"
    assert fails[0]["tier"] == 1
    assert rig.events("aot_invalidated")


def test_registry_corrupt_detected_and_quarantined(rig):
    """A FINITE corruption (tier 1 blind) is caught by the first-call
    oracle canary; the second offense quarantines the (kernel, config)
    persistently. scan is exact: one flipped element is proof."""
    import jax.numpy as jnp

    from tpukernels import registry

    rig.set_plan({"corrupt_output": {"kernel": "scan",
                                     "site": "registry"}})
    x = jnp.asarray(np.arange(300, dtype=np.int32))
    out1 = registry.dispatch("scan", x)           # detected: call 1
    assert int(np.asarray(out1)[0]) != 0          # corrupt, returned
    assert not rig.integrity.is_quarantined("scan")
    registry.dispatch("scan", x)                  # offense 2
    fails = rig.events("output_integrity_failed")
    assert len(fails) == 2
    assert all(f["tier"] in (2, 3) for f in fails)
    quar = rig.events("output_integrity_quarantined")
    assert len(quar) == 1 and quar[0]["kernel"] == "scan"
    assert rig.integrity.is_quarantined("scan")
    # persisted ledger, not process memory
    ledger = json.load(open(rig.dir / "integrity_quarantine.json"))
    assert any(k.startswith("scan|") for k in ledger["entries"])
    # dropping the plan: the guard re-checks every call (suspect) and
    # a clean result lifts the per-process escalation without crashing
    rig.clear_plan()
    self_clean = registry.dispatch("scan", x)
    np.testing.assert_array_equal(
        np.asarray(self_clean), np.cumsum(np.arange(300))
    )


def test_aot_memo_invalidated_on_failure(rig):
    """The offending kernel's compiled-executable memo AND manifest
    entries are dropped, so the next call recompiles instead of
    re-trusting a suspect executable."""
    import jax.numpy as jnp

    from tpukernels import aot, registry

    x = jnp.asarray(np.arange(300, dtype=np.int32))
    registry.dispatch("scan", x)  # clean: memo + manifest populated
    assert any(k[0] == "scan" for k in aot._EXEC_MEMO)
    manifest = json.load(open(aot.manifest_path()))
    assert any(k.startswith("scan|") for k in manifest["entries"])
    rig.set_plan({"corrupt_output": {"kernel": "scan",
                                     "site": "registry"}})
    # fresh guard state: the corrupt call is a first-trust check again
    rig.integrity.reset()
    registry.dispatch("scan", x)
    assert rig.events("output_integrity_failed")
    assert not any(k[0] == "scan" for k in aot._EXEC_MEMO)
    manifest = json.load(open(aot.manifest_path()))
    assert not any(
        k.startswith("scan|") for k in manifest.get("entries", {})
    )


# ---------------------------------------------------------------- #
# guarded path 2: capi.run_from_c                                    #
# ---------------------------------------------------------------- #

def test_capi_corruption_detected_never_crashes(rig):
    """The C driver's buffers are guarded after the adapter writes
    them: a NaN in what C is about to read back is journaled at site
    capi and the shim still returns rc 0 (errors are for real
    failures)."""
    from tpukernels import capi

    rig.set_plan({"nan_output": {"kernel": "vector_add",
                                 "site": "capi"}})
    x = np.ones(256, np.float32)
    y = np.ones(256, np.float32)
    params = json.dumps(
        {"alpha": 1.0,
         "buffers": [{"shape": [256], "dtype": "f32"}] * 2}
    )
    rc = capi.run_from_c(
        "vector_add", params, [x.ctypes.data, y.ctypes.data]
    )
    assert rc == 0
    fails = rig.events("output_integrity_failed")
    assert fails and fails[0]["site"] == "capi"
    assert fails[0]["tier"] == 1
    # the corruption landed in the driver-visible buffer (that is the
    # thing being guarded)
    assert not (np.isfinite(x).all() and np.isfinite(y).all())


# ---------------------------------------------------------------- #
# guarded path 3: bench measure phases (subprocess, real CLI)        #
# ---------------------------------------------------------------- #

def _bench_env(tmp_path, plan=None, **extra):
    env = _scrubbed_env(fake_devices=None)
    env["TPK_BENCH_SMOKE"] = "1"
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "health.jsonl")
    integ = tmp_path / "integ"
    integ.mkdir(exist_ok=True)
    env["TPK_INTEGRITY_DIR"] = str(integ)
    env.pop("TPK_FAULT_PLAN", None)
    env.pop("TPK_INTEGRITY", None)
    if plan is not None:
        env["TPK_FAULT_PLAN"] = json.dumps(plan)
    for k, v in extra.items():
        env[k] = str(v)
    return env


def _run_bench(env, args=(), timeout=420):
    return subprocess.run(
        [sys.executable, "bench.py", *args],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )


def test_bench_measure_detects_corruption(tmp_path):
    """A corrupt kernel under bench's measure phase is detected before
    a window is spent timing it: the --one child still emits its JSON
    (never a crash), the journal carries the failure at site bench,
    and the second warm call's repeat offense quarantines the
    config."""
    plan = {"corrupt_output": {"kernel": "vector_add",
                               "site": "bench"}}
    env = _bench_env(tmp_path, plan)
    proc = _run_bench(env, args=("--one", "saxpy_gb_s"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["name"] == "saxpy_gb_s"  # the run survived
    assert "output-integrity FAILED" in proc.stderr
    fails = _events(tmp_path / "health.jsonl",
                    "output_integrity_failed")
    assert fails and all(f["site"] == "bench" for f in fails)
    assert all(f["kernel"] == "vector_add" for f in fails)
    # both R variants' warm results are guarded -> repeat offense ->
    # quarantined within the one child
    assert _events(tmp_path / "health.jsonl",
                   "output_integrity_quarantined")
    # the executables that PRODUCED the corrupt warm results — the
    # compiled loop programs, manifest keys bench_saxpy.R<n>@... —
    # are invalidated too, not just the kernel's dispatch entries
    invalidated = _events(tmp_path / "health.jsonl", "aot_invalidated")
    dropped = [k for e in invalidated
               for k in (e.get("manifest_dropped") or [])]
    assert any(k.startswith("bench_saxpy.") for k in dropped), dropped


def test_bench_nan_tripwire_covers_loop_program(tmp_path):
    """nan_output at the bench site poisons the warm scalar itself —
    tier 1 catches it with no oracle run at all."""
    plan = {"nan_output": {"site": "bench"}}
    env = _bench_env(tmp_path, plan)
    proc = _run_bench(env, args=("--one", "saxpy_gb_s"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    fails = _events(tmp_path / "health.jsonl",
                    "output_integrity_failed")
    assert fails and all(f["tier"] == 1 for f in fails)


def test_clean_path_stdout_byte_identical(tmp_path):
    """The acceptance proof: bench stdout is byte-identical with the
    guard on-and-passing, tier-1-only, and fully off — the guard adds
    checks, never output."""
    outs = []
    for integ in (None, None, "tripwire", "0"):
        env = _bench_env(tmp_path)
        if integ is not None:
            env["TPK_INTEGRITY"] = integ
        proc = _run_bench(env, args=("--one", "saxpy_gb_s"))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert len(set(outs)) == 1


# ---------------------------------------------------------------- #
# guarded path 4: autotune sweep candidates                          #
# ---------------------------------------------------------------- #

def test_tuning_candidate_integrity_discards_value(tmp_path, monkeypatch):
    """A corrupt candidate's measurement is garbage by definition: the
    runner discards it (status "integrity"), nothing promotes, and the
    child's guard quarantined the (kernel, candidate-config) in the
    shared ledger under the candidate's OWN knob values."""
    from tpukernels.tuning import runner

    # the runner's own journal events (tuning_candidate) emit from
    # THIS process; the children journal via base_env — same file
    monkeypatch.setenv(
        "TPK_HEALTH_JOURNAL", str(tmp_path / "health.jsonl")
    )
    env = _bench_env(
        tmp_path,
        {"corrupt_output": {"kernel": "vector_add", "site": "bench"}},
    )
    summary = runner.tune(
        "vector_add", smoke=True, max_candidates=2, base_env=env,
    )
    rows = summary["rows"]
    assert rows, summary
    assert all(r["status"] == "integrity" for r in rows), rows
    assert all(r["value"] is None for r in rows)
    assert summary["promoted"] is None
    cands = _events(tmp_path / "health.jsonl", "tuning_candidate")
    assert cands and all(c["integrity_failed"] for c in cands)
    ledger = json.load(
        open(tmp_path / "integ" / "integrity_quarantine.json")
    )
    keys = list(ledger["entries"])
    assert any(k.startswith("vector_add|") and "TPK_SAXPY_ROWS" in k
               for k in keys), keys


# ---------------------------------------------------------------- #
# AOT first-trust smoke (prewarm path)                               #
# ---------------------------------------------------------------- #

def test_precompile_first_trust_smoke_check(rig):
    """aot.precompile blesses a warm executable with no dispatch
    following — the first-trust canary must run THERE, and a failure
    invalidates what it was about to bless (never raises: prewarm
    reports per kernel)."""
    from tpukernels import registry

    rig.set_plan({"corrupt_output": {"kernel": "scan", "site": "aot"}})
    row = registry.precompile("scan")  # returns normally
    assert row["kernel"] == "scan"
    fails = rig.events("output_integrity_failed")
    assert fails and fails[0]["site"] == "aot"
    assert rig.events("aot_invalidated")


# ---------------------------------------------------------------- #
# envelope manifest: roundtrip, tier-2 checks, staleness             #
# ---------------------------------------------------------------- #

def test_envelope_roundtrip_and_tier2_detection(rig):
    """A recorded envelope turns the exact kernels' deep check into
    the bitwise tier-2 fingerprint compare — corruption is caught
    against the PERSISTED oracle record, no oracle re-run."""
    rig.integrity.record_envelope("scan")
    assert rig.integrity.envelope("scan") is not None
    ran, failure = rig.integrity.fingerprint_check("scan")
    assert ran and failure is None  # clean kernel matches the oracle
    rig.set_plan({"corrupt_output": {"kernel": "scan"}})
    import jax.numpy as jnp

    from tpukernels import registry

    rig.integrity.reset()
    registry.dispatch(
        "scan", jnp.asarray(np.arange(64, dtype=np.int32))
    )
    fails = rig.events("output_integrity_failed")
    assert fails and fails[-1]["tier"] == 2
    assert "checksum" in fails[-1]["detail"]


def test_envelope_staleness_rejected_loudly(rig, monkeypatch):
    """The tuning-cache validation rules verbatim: a jax-version
    mismatch dismisses the envelope with a journal event and stderr
    note, and the guard degrades to the live oracle — never trusts a
    stale record."""
    rig.integrity.record_envelope("scan")
    p = rig.integrity.manifest_path()
    data = json.load(open(p))
    for ent in data["entries"].values():
        ent["jax"] = "0.0.0-stale"
    with open(p, "w") as f:
        json.dump(data, f)
    assert rig.integrity.envelope("scan") is None
    rej = rig.events("output_integrity_rejected")
    assert rej and "0.0.0-stale" in rej[0]["reason"]
    ran, _failure = rig.integrity.fingerprint_check("scan")
    assert ran is False  # caller falls through to tier 3
    assert rig.integrity.cross_check("scan") is None


def test_record_all_covers_registry(rig):
    from tpukernels import registry

    rows = rig.integrity.record_all()
    assert {r["kernel"] for r in rows} >= set(registry.names())
    assert not [r for r in rows if "error" in r], rows
    assert len(rig.events("output_integrity_envelope")) == len(rows)


# ---------------------------------------------------------------- #
# reports narrate the new evidence                                   #
# ---------------------------------------------------------------- #

def test_reports_narrate_integrity_events(rig, tmp_path):
    import jax.numpy as jnp

    from tpukernels import registry

    rig.set_plan({"corrupt_output": {"kernel": "scan",
                                     "site": "registry"}})
    x = jnp.asarray(np.arange(128, dtype=np.int32))
    registry.dispatch("scan", x)
    registry.dispatch("scan", x)  # second offense -> quarantine
    rep = subprocess.run(
        [sys.executable, "tools/health_report.py", str(rig.journal)],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert rep.returncode == 0, rep.stdout + rep.stderr
    for needle in ("OUTPUT INTEGRITY FAILED", "QUARANTINED",
                   "aot executables INVALIDATED",
                   "output-integrity failure(s)"):
        assert needle in rep.stdout, (needle, rep.stdout)
    # obs_report --check gates rc 1 on the confirmed corruption —
    # a wrong answer stops a queue exactly like a regression
    empty_root = tmp_path / "emptyroot"
    (empty_root / "docs" / "logs").mkdir(parents=True)
    check = subprocess.run(
        [sys.executable, "tools/obs_report.py", "--check",
         "--root", str(empty_root), "--journal", str(rig.journal)],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert check.returncode == 1, check.stdout + check.stderr
    assert "output_integrity_failed" in check.stdout
