"""CPU suite for the analytic roofline models (docs/PERF.md
§rooflines) and the below_roofline trend verdict.

Pins the FLOPs/bytes formulas against hand-computed values for each
BASELINE.json benchmark config, the shared sgemm byte arithmetic
(ISSUE 6 satellite: one helper feeds the VMEM feasibility model AND
the roofline byte count), and the verdict rules: below_roofline fires
only from an ok verdict (never no_data / invalidated / regression /
impossible), never gates (`obs_report --check` rc stays 0), and
respects TPK_ROOFLINE_MIN_FRAC.
"""

import json
import os
import subprocess
import sys

import pytest

from tpukernels.tuning import roofline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V5 = roofline.PEAKS["tpu_v5_lite"]


# ---------------------------------------------------------------- #
# the arithmetic, pinned per BASELINE.json config                   #
# ---------------------------------------------------------------- #

def test_sgemm_formulas_and_peak():
    m = roofline.MODELS["sgemm_gflops"]
    assert m.config == (1024, 1024, 1024)
    assert m.flops(1024, 1024, 1024) == 2 * 1024**3        # 2·m·n·k
    assert m.hbm_bytes(1024, 1024, 1024) == 16 * 1024**2   # 4·4·1024²
    p = roofline.peak("sgemm_gflops", kind="tpu_v5_lite")
    # 184 TF / 3 passes over 2.147 GFLOP -> the BASELINE.json ceiling
    assert p["bound"] == "compute"
    assert round(p["peak"]) == 61333
    # at a tiny K the byte leg dominates: bound flips to bandwidth
    tiny = roofline.RooflineModel(
        metric="x", kernel="sgemm", config=(1024, 1024, 8),
        flops=m.flops, hbm_bytes=m.hbm_bytes, work=m.work,
        compute="mxu_f32",
    )
    f = tiny.flops(*tiny.config) / (V5["mxu_flops"] / 3)
    b = tiny.hbm_bytes(*tiny.config) / (V5["hbm_gb_s"] * 1e9)
    assert b > f  # the flip this model class must be able to express


def test_sgemm_bytes_per_block_shared_with_vmem_model():
    """ONE formula, two consumers: the kernels/sgemm.py VMEM model and
    the roofline HBM count both derive from sgemm_bytes_per_block."""
    from tpukernels.kernels.sgemm import _vmem_bytes

    blk = roofline.sgemm_bytes_per_block(256, 2048, 1024)
    assert blk == {
        "a": 4 * 256 * 1024,
        "b": 4 * 1024 * 2048,
        "c": 8 * 256 * 2048,
        "acc": 4 * 256 * 2048,
    }
    # VMEM model = double-buffered a+b pairs + c + acc = the
    # documented 24 MiB control figure
    control = {"bm": 256, "bn": 2048, "bk": 1024, "depth": 1}
    assert _vmem_bytes(control) == 24 * 1024 * 1024
    assert (
        _vmem_bytes(control)
        == 2 * (blk["a"] + blk["b"]) + blk["c"] + blk["acc"]
    )
    # manual-pipeline depth multiplies only the streamed pair
    assert (
        _vmem_bytes({**control, "depth": 3})
        == 3 * (blk["a"] + blk["b"]) + blk["c"] + blk["acc"]
    )
    # roofline HBM = one visit per distinct block, acc excluded
    whole = roofline.sgemm_bytes_per_block(1024, 1024, 1024)
    assert roofline.sgemm_hbm_bytes(1024, 1024, 1024) == (
        whole["a"] + whole["b"] + whole["c"]
    )


@pytest.mark.parametrize(
    "metric,flops,hbm_bytes,peak_value,bound",
    [
        # stencil2d 4096²: 6 VPU ops/cell/sweep, 1 B/cell/sweep (k=8)
        ("stencil2d_mcells_s", 6.0 * 4096**2, 4096**2,
         3.9e12 / 6 / 1e6, "compute"),
        # stencil3d 384³: 8 ops/cell, 1 B/cell
        ("stencil3d_mcells_s", 8.0 * 384**3, 384**3,
         3.9e12 / 8 / 1e6, "compute"),
        # nbody 65536: 20 flops/interaction, j-set VMEM-resident
        ("nbody_ginter_s", 20.0 * 65536**2, 28.0 * 65536,
         3.9e12 / 20 / 1e9, "compute"),
        # scan+hist 2²²: 12 B/elem unfused -> HBM-bound
        ("scan_hist_melem_s", 1536.0 * 2**22, 12.0 * 2**22,
         819e9 / 12 / 1e6, "bandwidth"),
        # saxpy stream 2²⁶: the metric IS GB/s, peak = HBM BW
        ("saxpy_stream_gb_s", 2.0 * 2**26, 12.0 * 2**26,
         819.0, "bandwidth"),
    ],
)
def test_metric_formulas_and_peaks(metric, flops, hbm_bytes,
                                   peak_value, bound):
    m = roofline.MODELS[metric]
    assert m.flops(*m.config) == flops
    assert m.hbm_bytes(*m.config) == hbm_bytes
    p = roofline.peak(metric, kind="tpu_v5_lite")
    assert p["bound"] == bound
    assert p["peak"] == pytest.approx(peak_value, rel=1e-9)


def test_saxpy_config_of_record_is_documented_artifact():
    """The VMEM-resident 2²⁰ config legitimately beats the HBM
    roofline: reported, never verdict-ed."""
    p = roofline.peak("saxpy_gb_s", kind="tpu_v5_lite")
    assert p["artifact"] is True and p["peak"] == pytest.approx(819.0)
    from tpukernels.obs import trend

    # measured median 9,114 GB/s >> 819: no below_roofline, and the
    # artifact flag would suppress it even below threshold
    assert trend._roofline_check("saxpy_gb_s", 9114.0)["below"] is False
    assert trend._roofline_check("saxpy_gb_s", 10.0)["below"] is False


def test_every_registry_kernel_metric_is_modeled():
    # KERNEL_METRIC -> MODELS is closed (the registry lint's other half)
    for kernel, metric in roofline.KERNEL_METRIC.items():
        assert metric in roofline.MODELS, (kernel, metric)


def test_resolve_kind_fallbacks(monkeypatch):
    monkeypatch.delenv("TPK_ROOFLINE_DEVICE", raising=False)
    row, kind, basis = roofline.resolve_kind()
    assert kind == roofline.EVIDENCE_KIND and basis == "exact"
    row, kind, basis = roofline.resolve_kind("tpu_v7_megapod")
    assert row is roofline.PEAKS["tpu_v5_lite"]
    assert basis == "assumed-tpu_v5_lite"
    row, kind, basis = roofline.resolve_kind("gracehopper")
    assert row is roofline.PEAKS["cpu"] and basis == "cpu-fallback"
    monkeypatch.setenv("TPK_ROOFLINE_DEVICE", "cpu")
    row, kind, basis = roofline.resolve_kind()
    assert kind == "cpu" and basis == "exact"


def test_min_frac_env_fail_loud(monkeypatch):
    monkeypatch.delenv("TPK_ROOFLINE_MIN_FRAC", raising=False)
    assert roofline.min_frac() == 0.5
    monkeypatch.setenv("TPK_ROOFLINE_MIN_FRAC", "0.25")
    assert roofline.min_frac() == 0.25
    for bad in ("abc", "-0.1", "1.5"):
        monkeypatch.setenv("TPK_ROOFLINE_MIN_FRAC", bad)
        with pytest.raises(ValueError, match="TPK_ROOFLINE_MIN_FRAC"):
            roofline.min_frac()


# ---------------------------------------------------------------- #
# trend verdict rules (fixtures mirror tests/test_obs.py)           #
# ---------------------------------------------------------------- #

def _fixture_root(tmp_path, baseline=None, logs=None, rounds=None):
    root = tmp_path / "repo"
    (root / "docs" / "logs").mkdir(parents=True)
    (root / "BASELINE.json").write_text(json.dumps(baseline or {}))
    for fname, line in (logs or {}).items():
        (root / "docs" / "logs" / fname).write_text(json.dumps(line))
    for n, rec in (rounds or {}).items():
        (root / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))
    return str(root)


def _line(details, **extra):
    return {"metric": "sgemm_gflops_per_chip", "value": None,
            "unit": "GFLOPS", "details": details, **extra}


def test_below_roofline_fires_only_from_ok(tmp_path, monkeypatch):
    from tpukernels.obs import trend

    monkeypatch.delenv("TPK_ROOFLINE_MIN_FRAC", raising=False)
    root = _fixture_root(
        tmp_path,
        baseline={"measured": {"stencil2d_mcells_s": 129996}},
        logs={"bench_2026-08-01_000000.json": _line(
            {"stencil2d_mcells_s": 129996.0})},
    )
    v = trend.analyze_repo(root)["stencil2d_mcells_s"]
    assert v["verdict"] == "below_roofline"
    assert v["roofline"]["frac"] == pytest.approx(
        129996.0 / 650000.0, rel=1e-6
    )
    assert any("BELOW ROOFLINE" in f and "non-gating" in f
               for f in v["flags"])
    # a loosened threshold turns the same series back to plain ok
    monkeypatch.setenv("TPK_ROOFLINE_MIN_FRAC", "0.1")
    v = trend.analyze_repo(root)["stencil2d_mcells_s"]
    assert v["verdict"] == "ok"
    assert v["roofline"]["below"] is False  # still recorded


def test_below_roofline_never_fires_on_no_data_or_invalidated(tmp_path):
    """The satellite fixture: tunnel-down rounds and
    invalidated-at-source values stay no_data — the roofline check
    must not touch them (there is no value to judge)."""
    from tpukernels.obs import trend

    null_round = {"n": 1, "parsed": _line(
        {"error": "TPU backend unreachable"})}
    root = _fixture_root(
        tmp_path,
        baseline={
            "measured": {"stencil2d_mcells_s": 129996},
            "ceilings": {"sgemm_gflops": 61333},
        },
        logs={"bench_2026-08-01_000000.json": _line(
            {"sgemm_gflops": None},
            invalidated={"sgemm_gflops": [72698.96, "drift"]},
        )},
        rounds={1: null_round, 2: null_round},
    )
    verdicts = trend.analyze_repo(root)
    assert verdicts["stencil2d_mcells_s"]["verdict"] == "no_data"
    assert "roofline" not in verdicts["stencil2d_mcells_s"]
    assert verdicts["sgemm_gflops"]["verdict"] == "no_data"
    assert "roofline" not in verdicts["sgemm_gflops"]


def test_below_roofline_never_masks_regression_or_impossible(tmp_path):
    from tpukernels.obs import trend

    root = _fixture_root(
        tmp_path,
        baseline={
            "measured": {"stencil2d_mcells_s": 129996},
            "ceilings": {"sgemm_gflops": 61333},
        },
        logs={
            "bench_2026-08-01_000000.json": _line(
                {"stencil2d_mcells_s": 129996.0,
                 "sgemm_gflops": 72698.96}),
            "bench_2026-08-02_000000.json": _line(
                {"stencil2d_mcells_s": 100000.0}),
        },
    )
    verdicts = trend.analyze_repo(root)
    # 23% drop: regression wins even though 100000 is also <50% of
    # the roofline
    assert verdicts["stencil2d_mcells_s"]["verdict"] == "regression"
    assert verdicts["sgemm_gflops"]["verdict"] == "impossible"


def test_obs_report_check_rc0_on_below_roofline(tmp_path):
    """The acceptance fixture: a below-roofline-only repo keeps
    --check rc 0 (non-gating), and the real repo's --roofline section
    renders the machine-checked table."""
    root = _fixture_root(
        tmp_path,
        baseline={"measured": {"stencil2d_mcells_s": 129996}},
        logs={"bench_2026-08-01_000000.json": _line(
            {"stencil2d_mcells_s": 129996.0})},
    )
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check", "--root", root],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "below_roofline (non-gating)" in r.stdout


def test_obs_report_roofline_table_covers_baseline_configs(tmp_path):
    """The --roofline table covers every modeled metric — in
    particular all 5 BASELINE.json benchmark configs — with the
    analytic peak and (where evidence exists) % of roofline, and the
    run leaves roofline_computed journal evidence."""
    journal = tmp_path / "health.jsonl"
    env = dict(os.environ, TPK_HEALTH_JOURNAL=str(journal))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--roofline"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    for metric in roofline.MODELS:  # the 5 configs + stream/3d rows
        assert metric in r.stdout
    assert "analytic peak" in r.stdout and "% of roofline" in r.stdout
    events = [json.loads(ln) for ln in
              journal.read_text().splitlines() if ln.strip()]
    (ev,) = [e for e in events if e.get("kind") == "roofline_computed"]
    assert ev["device_kind"] == "tpu_v5_lite"
    assert ev["min_frac"] == 0.5
    assert set(ev["metrics"]) == set(roofline.MODELS)
    assert round(ev["metrics"]["sgemm_gflops"]["peak"]) == 61333
