"""tools/promote_baseline.py — the deliberate promotion step that
turns a captured <24h evidence union into new BASELINE.json medians.
Guard rails matter more than the happy path: a partial or regressed
promotion would quietly re-aim the self-regression gate."""

import datetime
import json

import pytest

import bench
from tools import promote_baseline


def _write_root(tmp_path, details, measured=None):
    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    stamp = datetime.datetime.now().strftime("bench_%Y-%m-%d_%H%M%S.json")
    (logs / stamp).write_text(json.dumps({"details": details}))
    base = {
        "measured": {"measured_on": "2026-07-29", **(measured or {})},
        "published": {},
    }
    (tmp_path / "BASELINE.json").write_text(json.dumps(base))
    return tmp_path


def _full_details(value=100.0):
    return {name: value for name, _fn in bench.BENCH_METRICS}


def test_promotes_full_union_and_stamps_date(tmp_path):
    root = _write_root(
        tmp_path, _full_details(123.456), measured={"sgemm_gflops": 120.0}
    )
    measured, lines = promote_baseline.promote(root=str(root))
    on_disk = json.loads((root / "BASELINE.json").read_text())["measured"]
    assert on_disk["sgemm_gflops"] == 123.46
    assert on_disk["measured_on"] == datetime.date.today().isoformat()
    assert all(
        on_disk[n] == 123.46 for n, _fn in bench.BENCH_METRICS
    )


def test_refuses_partial_union_without_flag(tmp_path):
    details = _full_details()
    del details["stencil3d_mcells_s"]
    root = _write_root(tmp_path, details)
    with pytest.raises(SystemExit, match="stencil3d"):
        promote_baseline.promote(root=str(root))
    # with the flag: promotes what exists, keeps the hole's old value
    measured, lines = promote_baseline.promote(
        root=str(root), allow_partial=True
    )
    assert "stencil3d_mcells_s" not in measured or measured.get(
        "stencil3d_mcells_s"
    ) is None or isinstance(measured.get("stencil3d_mcells_s"), float)


def test_refuses_regressed_promotion(tmp_path):
    # captured 50% below the median of record: the gate should have
    # caught this; promotion must refuse to lower the bar
    root = _write_root(
        tmp_path, _full_details(50.0), measured={"sgemm_gflops": 100.0}
    )
    with pytest.raises(SystemExit, match="regression"):
        promote_baseline.promote(root=str(root))


def test_dry_run_writes_nothing(tmp_path):
    root = _write_root(tmp_path, _full_details(77.0))
    before = (root / "BASELINE.json").read_text()
    promote_baseline.promote(root=str(root), dry_run=True)
    assert (root / "BASELINE.json").read_text() == before
