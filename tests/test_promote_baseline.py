"""tools/promote_baseline.py — the deliberate promotion step that
turns a captured <24h evidence union into new BASELINE.json medians.
Guard rails matter more than the happy path: a partial or regressed
promotion would quietly re-aim the self-regression gate."""

import datetime
import json

import pytest

import bench
from tools import promote_baseline


def _write_root(tmp_path, details, measured=None, ceilings=None):
    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    stamp = datetime.datetime.now().strftime("bench_%Y-%m-%d_%H%M%S.json")
    (logs / stamp).write_text(json.dumps({"details": details}))
    base = {
        "measured": {"measured_on": "2026-07-29", **(measured or {})},
        "published": {},
    }
    if ceilings:
        base["ceilings"] = ceilings
    (tmp_path / "BASELINE.json").write_text(json.dumps(base))
    return tmp_path


def _full_details(value=100.0):
    return {name: value for name, _fn in bench.BENCH_METRICS}


def test_promotes_full_union_and_stamps_date(tmp_path):
    root = _write_root(
        tmp_path, _full_details(123.456), measured={"sgemm_gflops": 120.0}
    )
    measured, lines = promote_baseline.promote(root=str(root))
    on_disk = json.loads((root / "BASELINE.json").read_text())["measured"]
    assert on_disk["sgemm_gflops"] == 123.46
    assert on_disk["measured_on"] == datetime.date.today().isoformat()
    assert all(
        on_disk[n] == 123.46 for n, _fn in bench.BENCH_METRICS
    )


def test_refuses_partial_union_without_flag(tmp_path):
    details = _full_details()
    del details["stencil3d_mcells_s"]
    root = _write_root(
        tmp_path, details, measured={"stencil3d_mcells_s": 83564.0}
    )
    with pytest.raises(SystemExit, match="stencil3d"):
        promote_baseline.promote(root=str(root))
    # with the flag: promotes what exists, keeps the hole's old value
    # EXACTLY on disk, and records the kept metric's real provenance
    # (ADVICE r4: allow-partial used to re-stamp kept values with
    # measured_on=today, misrepresenting where they came from)
    measured, lines = promote_baseline.promote(
        root=str(root), allow_partial=True
    )
    on_disk = json.loads((root / "BASELINE.json").read_text())["measured"]
    assert on_disk["stencil3d_mcells_s"] == 83564.0
    assert on_disk["sgemm_gflops"] == 100.0
    assert "stencil3d_mcells_s" in on_disk["_not_remeasured"]
    assert "2026-07-29" in on_disk["_not_remeasured"]


def test_full_promotion_clears_partial_note(tmp_path):
    root = _write_root(
        tmp_path, _full_details(50.0),
        measured={"_not_remeasured": "stale note from last time"},
    )
    promote_baseline.promote(root=str(root))
    on_disk = json.loads((root / "BASELINE.json").read_text())["measured"]
    assert "_not_remeasured" not in on_disk


def test_refuses_implausible_jump_without_flag(tmp_path):
    """ADVICE r4: the guard must be symmetric — a drift-inflated
    capture promoted UPWARD silently raises the bar so honest future
    captures fail the gate. A jump past _JUMP_TOL needs a human to
    vouch a kernel change explains it."""
    root = _write_root(
        tmp_path, _full_details(130.0), measured={"sgemm_gflops": 100.0}
    )
    with pytest.raises(SystemExit, match="above the median"):
        promote_baseline.promote(root=str(root))
    measured, _ = promote_baseline.promote(root=str(root), allow_jump=True)
    assert measured["sgemm_gflops"] == 130.0


def test_refuses_promotion_above_ceiling_even_with_jump_flag(tmp_path):
    """A capture above the physical ceiling is invalid evidence, full
    stop — no flag may promote it (bench.py should have refused to
    persist it in the first place)."""
    root = _write_root(
        tmp_path, _full_details(95973.82),
        measured={"sgemm_gflops": 60834.0},
        ceilings={"sgemm_gflops": 61333.0},
    )
    with pytest.raises(SystemExit, match="ceiling"):
        promote_baseline.promote(root=str(root), allow_jump=True)


def test_refuses_regressed_promotion(tmp_path):
    # captured 50% below the median of record: the gate should have
    # caught this; promotion must refuse to lower the bar
    root = _write_root(
        tmp_path, _full_details(50.0), measured={"sgemm_gflops": 100.0}
    )
    with pytest.raises(SystemExit, match="regression"):
        promote_baseline.promote(root=str(root))


def test_dry_run_writes_nothing(tmp_path):
    root = _write_root(tmp_path, _full_details(77.0))
    before = (root / "BASELINE.json").read_text()
    promote_baseline.promote(root=str(root), dry_run=True)
    assert (root / "BASELINE.json").read_text() == before
