"""CPU suite for router crash recovery: the guardian, the admission
WAL, crash-consistent artifacts and the fsck sweep (docs/SERVING.md
§guardian; docs/RESILIENCE.md §failure domains; ISSUE 16).

The acceptance headline, all on CPU over Unix sockets: `kill -9` the
ROUTER mid-burst — the guardian declares it dead within a probe
interval (flock-free pidfile), sweeps its shm, respawns it on the
original front socket, the new router replays its admission WAL, and
the clients' `TPK_CLIENT_RECONNECT_S` budget rides out the refused
window — zero failed requests end to end. Plus: the `kill_router`
fault's worst-instant kill (WAL entry durable, forward not sent) with
exactly-once worker delivery, the torn-artifact loud-rejection
contract per persisted family, `serve_ctl fsck`, and the pure units
(WAL append/ack/compaction/torn tail, guardian state machine + knob
parses, the client reconnect budget).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from test_fleet import _ctl, _fleet
from test_serve import _events
from test_fleet_health import _wait_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events_or_empty(journal_path):
    try:
        return _events(journal_path)
    except OSError:
        return []

# compressed windows + inline lane (WAL-replayable payloads) + a
# reconnect budget generously wider than the respawn window so the
# headline's zero-drop claim never races the scheduler
GUARDIAN_ENV = {
    "TPK_FLEET_PROBE_S": "0.3",
    "TPK_FLEET_RESTART_BACKOFF_S": "0.2",
    "TPK_ROUTER_RESTART_BACKOFF_S": "0.2",
    "TPK_SERVE_SHM": "0",
    "TPK_CLIENT_RECONNECT_S": "60",
}


# ---------------------------------------------------------------- #
# pure units: the WAL                                              #
# ---------------------------------------------------------------- #

def test_wal_append_ack_torn_tail_and_close(tmp_path):
    from tpukernels.serve import wal as serve_wal

    path = str(tmp_path / "router.wal")
    assert serve_wal.read_pending(path) == {}

    w = serve_wal.Wal(path)
    w.append("k1", {"h": {"kernel": "scan"}, "n": 1})
    w.append("k2", {"h": {"kernel": "scan"}, "n": 2})
    assert w.depth() == 2
    assert list(serve_wal.read_pending(path)) == ["k1", "k2"]
    w.ack("k1")
    assert serve_wal.read_pending(path) == {
        "k2": {"h": {"kernel": "scan"}, "n": 2}
    }

    # a torn TAIL line is normal crash residue: skipped, never fatal,
    # and the durable prefix still reads back intact
    with open(path, "ab") as f:
        f.write(b'{"op": "req", "key": "k3", "e": {"half')
    assert list(serve_wal.read_pending(path)) == ["k2"]

    # recover-then-append: a new (respawned-router) instance sees
    # exactly the durable pending set
    w2 = serve_wal.Wal(path)
    assert w2.take_pending() == {"k2": {"h": {"kernel": "scan"}, "n": 2}}
    # take_pending is a snapshot: a second crash mid-replay would
    # re-replay the remainder — only the ack settles the entry
    assert w2.depth() == 1
    w2.ack("k2")
    # close with nothing pending unlinks — clean shutdown leaves no
    # stale WAL for the next start to "replay"
    w2.close()
    assert not os.path.exists(path)


def test_wal_compaction_stays_bounded(tmp_path):
    from tpukernels.serve import wal as serve_wal

    path = str(tmp_path / "router.wal")
    w = serve_wal.Wal(path)
    for i in range(300):
        w.append(f"k{i}", {"n": i})
        w.ack(f"k{i}")
    w.append("tail", {"n": -1})
    # steady-state file is O(inflight), not O(traffic): after 600+
    # ops with one pending entry, compaction must have rewritten it
    with open(path, "rb") as f:
        lines = [ln for ln in f.read().split(b"\n") if ln.strip()]
    assert len(lines) < 2 * serve_wal.COMPACT_SLACK + 4
    assert list(serve_wal.read_pending(path)) == ["tail"]
    # pending survives close (there is still something to replay)
    w.close()
    assert os.path.exists(path)


# ---------------------------------------------------------------- #
# pure units: the guardian state machine                           #
# ---------------------------------------------------------------- #

def test_guardian_knob_parse_fail_loud(monkeypatch):
    from tpukernels.serve import guardian

    monkeypatch.setenv("TPK_ROUTER_RESTART_MAX", "banana")
    with pytest.raises(ValueError, match="TPK_ROUTER_RESTART_MAX"):
        guardian.Guardian(repo=REPO)
    monkeypatch.setenv("TPK_ROUTER_RESTART_MAX", "0")
    with pytest.raises(ValueError, match="TPK_ROUTER_RESTART_MAX"):
        guardian.Guardian(repo=REPO)
    monkeypatch.delenv("TPK_ROUTER_RESTART_MAX")
    monkeypatch.setenv("TPK_ROUTER_RESTART_BACKOFF_S", "nope")
    with pytest.raises(ValueError,
                       match="TPK_ROUTER_RESTART_BACKOFF_S"):
        guardian.Guardian(repo=REPO)
    monkeypatch.delenv("TPK_ROUTER_RESTART_BACKOFF_S")
    g = guardian.Guardian(repo=REPO)
    assert g.restart_max == guardian.DEFAULT_RESTART_MAX
    assert g.backoff_s == guardian.DEFAULT_BACKOFF_S


def test_guardian_detects_flock_and_quarantines(tmp_path, monkeypatch):
    """Detection + crash-loop bookkeeping without any real router
    process: WE hold (and release) the router pidfile flock."""
    from tpukernels.serve import fleet, guardian
    from tpukernels.serve import server as serve_server

    monkeypatch.setenv("TPK_SERVE_DIR", str(tmp_path))
    journal_path = str(tmp_path / "j.jsonl")
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", journal_path)

    g = guardian.Guardian(repo=REPO, probe_s=0.1, restart_max=2,
                          backoff_s=0.05)
    # startup grace: no flock yet, but the router may still be binding
    g.probe_pass()
    assert g.state == "up" and g.crashes == 0

    # a HELD flock is life: pid observed, streak grows
    os.makedirs(os.path.dirname(fleet.router_pidfile_path()),
                exist_ok=True)
    pf = serve_server._hold_pidfile(fleet.router_pidfile_path())
    g.probe_pass()
    assert (g.seen_alive, g.pid) == (True, os.getpid())

    # releasing it is a death certificate: crash 1, backoff scheduled
    pf.close()
    g.probe_pass()
    assert g.state == "down"
    assert g.crashes == 1
    assert g.next_attempt > time.perf_counter() - 0.01
    # second confirmed crash at restart_max=2: quarantined, loudly
    g._declare_dead(None, via="probe")
    assert g.state == "quarantined"
    g.probe_pass()  # inert — never respawns out of quarantine
    assert g.state == "quarantined"
    events = _events(journal_path)
    dead = [e for e in events if e["kind"] == "router_dead"]
    assert [e["crashes"] for e in dead] == [1, 2]
    assert dead[0]["via"] == "probe"
    q = [e for e in events if e["kind"] == "router_quarantined"]
    assert len(q) == 1 and q[0]["threshold"] == 2

    # a stable window (STABLE_PROBES clean passes) forgives history
    from tpukernels.serve import health

    g2 = guardian.Guardian(repo=REPO, probe_s=0.1, restart_max=3,
                           backoff_s=0.05)
    g2.crashes = 2
    pf2 = serve_server._hold_pidfile(fleet.router_pidfile_path())
    try:
        for _ in range(health.STABLE_PROBES):
            g2.probe_pass()
        assert g2.crashes == 0
    finally:
        pf2.close()
        os.unlink(fleet.router_pidfile_path())


# ---------------------------------------------------------------- #
# pure units: the client reconnect budget                          #
# ---------------------------------------------------------------- #

def test_client_reconnect_budget(tmp_path, monkeypatch):
    import random

    from tpukernels.serve import client as serve_client

    monkeypatch.setenv("TPK_CLIENT_RECONNECT_S", "oops")
    with pytest.raises(ValueError, match="TPK_CLIENT_RECONNECT_S"):
        serve_client._reconnect_budget_s()
    monkeypatch.setenv("TPK_CLIENT_RECONNECT_S", "-1")
    with pytest.raises(ValueError, match="TPK_CLIENT_RECONNECT_S"):
        serve_client._reconnect_budget_s()

    class _Refusing:
        next_request_id = None

        def __init__(self):
            self.rids = []

        def dispatch(self, kernel, *a, **s):
            self.rids.append(self.next_request_id)
            raise ConnectionRefusedError("gone")

    # inside the budget: retried on the jittered cadence with the
    # SAME request_id (the WAL-replay stash recognizes the retry),
    # then the transport error surfaces — no silent hang
    monkeypatch.setenv("TPK_CLIENT_RECONNECT_S", "0.6")
    cli = _Refusing()
    cli.next_request_id = "one-id"
    t0 = time.monotonic()
    with pytest.raises(ConnectionRefusedError):
        serve_client.dispatch_with_backpressure(
            cli, "scan", (np.zeros(4, np.int32),), {},
            jitter=random.Random(7))
    elapsed = time.monotonic() - t0
    assert 0.4 <= elapsed < 5.0
    assert len(cli.rids) >= 2
    assert set(cli.rids) == {"one-id"}

    # budget 0 restores the old one-shot contract: a refused connect
    # is the immediate hard error it always was
    monkeypatch.setenv("TPK_CLIENT_RECONNECT_S", "0")
    cli0 = _Refusing()
    with pytest.raises(ConnectionRefusedError):
        serve_client.dispatch_with_backpressure(
            cli0, "scan", (np.zeros(4, np.int32),), {})
    assert len(cli0.rids) == 1

    # the real transport: socket GONE entirely (no daemon was ever
    # here) errors within the budget, preserving the error type
    monkeypatch.setenv("TPK_CLIENT_RECONNECT_S", "0.4")
    with serve_client.ServeClient(str(tmp_path / "no.sock"),
                                  timeout_s=5) as real:
        t0 = time.monotonic()
        with pytest.raises((FileNotFoundError, ConnectionRefusedError)):
            serve_client.dispatch_with_backpressure(
                real, "scan", (np.zeros(4, np.int32),), {})
        assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------- #
# crash-consistent artifacts: atomic writes + loud torn rejection  #
# ---------------------------------------------------------------- #

def test_atomic_write_and_torn_write_fault(tmp_path, monkeypatch):
    from tpukernels.resilience import atomic, faults

    path = str(tmp_path / "state.json")
    atomic.dump_json(path, {"v": 1})
    assert json.load(open(path)) == {"v": 1}

    # an injected mid-write crash (mode=raise) leaves the DESTINATION
    # untouched — old bytes, not torn bytes — and strands only a tmp
    monkeypatch.setenv("TPK_FAULT_PLAN", json.dumps(
        {"torn_write": {"path_substr": "state.json"}}))
    faults.reload_plan()
    try:
        with pytest.raises(OSError, match="torn_write"):
            atomic.dump_json(path, {"v": 2})
    finally:
        monkeypatch.delenv("TPK_FAULT_PLAN")
        faults.reload_plan()
    assert json.load(open(path)) == {"v": 1}
    stranded = [f for f in os.listdir(tmp_path) if ".tmp." in f]
    assert stranded, "the torn tmp is the evidence a real crash leaves"
    # the plan key only fires on matching destinations
    atomic.dump_json(str(tmp_path / "other.json"), {"ok": True})


def _assert_torn_rejected(capsys, journal_path, reader, path):
    """Write torn bytes in place, run the family's reader, assert the
    loud-rejection contract: empty/absent result, once-per-path
    stderr note, one ``artifact_rejected`` journal event."""
    from tpukernels import _cachedir

    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write('{"half": [1, 2')  # a pre-atomic writer's crash
    _cachedir._TORN_NOTED.discard(path)
    before = len([e for e in _events_or_empty(journal_path)
                  if e["kind"] == "artifact_rejected"])
    reader()
    err = capsys.readouterr().err
    assert "torn artifact rejected" in err, path
    reader()  # once per path per process, not log spam
    assert "torn artifact rejected" not in capsys.readouterr().err
    rejected = [e for e in _events_or_empty(journal_path)
                if e["kind"] == "artifact_rejected"]
    assert len(rejected) == before + 1
    assert rejected[-1]["path"] == path
    os.unlink(path)


def test_torn_artifacts_reject_loudly_per_family(tmp_path, capsys,
                                                 monkeypatch):
    journal_path = str(tmp_path / "j.jsonl")
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", journal_path)

    # tuning cache (tuning.json): reads as cold, never as garbage
    from tpukernels.tuning import cache

    monkeypatch.setenv("TPK_TUNING_CACHE_DIR", str(tmp_path / "t"))
    _assert_torn_rejected(
        capsys, journal_path,
        lambda: cache._load(cache.path()) == {}, cache.path())

    # AOT manifest (aot.json): same reader discipline, no jax import
    from tpukernels import _cachedir

    monkeypatch.setenv("TPK_AOT_CACHE_DIR", str(tmp_path / "a"))
    memo = {}
    _assert_torn_rejected(
        capsys, journal_path,
        lambda: _cachedir.read_json_memoized(
            _cachedir.aot_manifest_path(), memo) == {},
        _cachedir.aot_manifest_path())

    # fleet config of record (fleet.json): torn reads as "no fleet",
    # loudly — the guardian retries instead of inventing a topology
    from tpukernels.serve import fleet

    monkeypatch.setenv("TPK_SERVE_DIR", str(tmp_path / "s"))
    _assert_torn_rejected(
        capsys, journal_path,
        lambda: fleet.load_config() is None, fleet.config_path())


# ---------------------------------------------------------------- #
# serve_ctl fsck                                                   #
# ---------------------------------------------------------------- #

def test_fsck_reaps_crash_residue(tmp_path):
    from test_distributed import _scrubbed_env

    from tpukernels.serve import protocol

    env = _scrubbed_env(None)
    journal_path = str(tmp_path / "j.jsonl")
    env["TPK_SERVE_DIR"] = str(tmp_path)
    env["TPK_HEALTH_JOURNAL"] = journal_path

    fdir = tmp_path / "fleet"
    fdir.mkdir()
    # a crashed router's stale (flock-free) pidfile
    (fdir / "router.pid").write_text("999999\n")
    # a torn config of record
    (fdir / "fleet.json").write_text('{"workers": [')
    # an orphaned shm segment whose creator pid is dead
    child = subprocess.run(
        [sys.executable, "-c", "import os; print(os.getpid())"],
        capture_output=True, text=True)
    dead = int(child.stdout.strip())
    orphan = f"tpkserve-{dead}-0-cafef00d"
    with open(os.path.join(protocol.SHM_DIR, orphan), "wb") as f:
        f.write(b"\0" * 16)

    try:
        r = _ctl(env, "fsck")
        assert r.returncode == 0, r.stdout + r.stderr
        assert not os.path.exists(fdir / "router.pid")
        assert not os.path.exists(fdir / "fleet.json")
        assert not os.path.exists(
            os.path.join(protocol.SHM_DIR, orphan))
        events = [e for e in _events(journal_path)
                  if e["kind"] == "fleet_fsck"]
        assert len(events) == 1
        assert events[0]["stale_pidfiles"] >= 1
        assert events[0]["torn_configs"] == 1
        assert events[0]["swept_segments"] >= 1
    finally:
        protocol.unlink_shm(orphan)

    # clean state: fsck is a no-op rc 0 (the daily non-gating step)
    r = _ctl(env, "fsck")
    assert r.returncode == 0


# ---------------------------------------------------------------- #
# e2e: the headline — kill -9 the router mid-burst, zero failures  #
# ---------------------------------------------------------------- #

def _burst(front, tid, n, ok, fail, step_s=0.12):
    import random

    from tpukernels.serve import client as serve_client

    jit = random.Random(1000 + tid)
    x = (np.arange(256) % 11).astype(np.int32)
    want = np.cumsum(x, dtype=np.int64).astype(np.int32)
    with serve_client.ServeClient(front, timeout_s=120,
                                  tenant=f"t{tid}") as cli:
        for k in range(n):
            try:
                cli.next_request_id = f"rk-{tid}-{k}"
                out = serve_client.dispatch_with_backpressure(
                    cli, "scan", (x,), {}, jitter=jit)
                assert np.array_equal(out, want), "WRONG RESULT"
                ok.append((tid, k))
            except Exception as e:  # noqa: BLE001 - collected, asserted
                fail.append((tid, k, repr(e)))
            time.sleep(step_s)


def test_router_kill_recovery_zero_drops(tmp_path):
    from tpukernels.serve import health

    with _fleet(tmp_path, n=2, env_extra=GUARDIAN_ENV,
                tag="rk") as (front, journal_path, env):
        r = _ctl(env, "guardian", "--wait", "30")
        assert r.returncode == 0, r.stdout + r.stderr
        # double-start refused on the guardian's own flock (rc 3)
        r = _ctl(env, "guardian")
        assert r.returncode == 3

        fleet_dir = os.path.join(env["TPK_SERVE_DIR"], "fleet")
        rpidfile = os.path.join(fleet_dir, "router.pid")
        held, rpid = health.pidfile_state(rpidfile)
        assert held

        ok, fail = [], []
        threads = [
            threading.Thread(target=_burst,
                             args=(front, tid, 8, ok, fail))
            for tid in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(1.0)  # mid-burst
        os.kill(rpid, signal.SIGKILL)
        for t in threads:
            t.join()

        assert not fail, fail
        assert len(ok) == 24

        _, dead = _wait_events(
            journal_path,
            lambda e: e.get("kind") == "router_dead",
            msg="router_dead")
        assert dead[0]["router_pid"] == rpid
        _, resp = _wait_events(
            journal_path,
            lambda e: e.get("kind") == "router_respawned",
            msg="router_respawned")
        assert resp[0]["down_s"] is not None
        held2, rpid2 = health.pidfile_state(rpidfile)
        assert held2 and rpid2 != rpid

        # the fleet converged behind the new router
        r = _ctl(env, "health", "--wait", "60")
        assert r.returncode == 0, r.stdout + r.stderr

    # stop-fleet (guardian FIRST) left nothing behind to respawn it
    held, _ = health.pidfile_state(
        os.path.join(tmp_path, "rk", "fleet", "guardian.pid"))
    assert not held
    held, _ = health.pidfile_state(
        os.path.join(tmp_path, "rk", "fleet", "router.pid"))
    assert not held


def test_kill_router_fault_wal_replay_exactly_once(tmp_path):
    """The worst-instant crash (`kill_router`: WAL entry durable,
    forward NOT sent): the respawned router replays the entry, the
    client's same-id retry is answered from the replay stash, and the
    worker-side evidence shows EXACTLY one delivery per request_id."""
    once = str(tmp_path / "kill_router.once")
    env_extra = dict(GUARDIAN_ENV)
    env_extra["TPK_FAULT_PLAN"] = json.dumps(
        {"kill_router": {"on_call": 3, "once_file": once}})

    with _fleet(tmp_path, n=2, env_extra=env_extra,
                tag="wal") as (front, journal_path, env):
        r = _ctl(env, "guardian", "--wait", "30")
        assert r.returncode == 0, r.stdout + r.stderr

        ok, fail = [], []
        threads = [
            threading.Thread(target=_burst,
                             args=(front, tid, 6, ok, fail, 0.1))
            for tid in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        assert os.path.exists(once), "the fault never fired"
        assert not fail, fail
        assert len(ok) == 12

        events, _ = _wait_events(
            journal_path,
            lambda e: e.get("kind") == "router_respawned",
            msg="router_respawned")
        fired = [e for e in events if e.get("kind") == "fault_injected"
                 and e.get("fault") == "kill_router"]
        assert fired and fired[0]["site"] == "route"
        # the WAL-replayed request is journaled as via="wal" — either
        # delivered to a worker or skipped LOUDLY with a reason
        replays = [e for e in events
                   if e.get("kind") == "serve_request_replayed"
                   and e.get("via") == "wal"]
        assert replays, "the durable entry must be replayed"
        for e in replays:
            assert e.get("request_id", "").startswith("rk-")
            if not e.get("ok", True):
                assert e.get("reason")
        # exactly-once worker delivery per request_id, replay included
        per = {}
        for e in events:
            if (e.get("kind") == "serve_request"
                    and str(e.get("request_id", "")).startswith("rk-")):
                per[e["request_id"]] = per.get(e["request_id"], 0) + 1
        dups = {k: v for k, v in per.items() if v != 1}
        assert not dups, dups
        assert len(per) == 12

        # the outage reassembles in reqtrace as an explicit
        # dead-router gap on any successfully replayed request
        delivered = [e for e in replays if e.get("to_worker") is not None]
        if delivered:
            from tpukernels.obs import reqtrace

            rid = delivered[0]["request_id"]
            tls = reqtrace.assemble(
                [e for e in events if e.get("request_id") == rid])
            kinds = {g.get("kind") for t in tls.values()
                     for g in t.get("gaps", [])}
            assert "dead-router" in kinds


# ---------------------------------------------------------------- #
# the seeded chaos campaign runner (slow: full fleet, many faults) #
# ---------------------------------------------------------------- #

@pytest.mark.slow
def test_chaos_campaign_seeded(tmp_path):
    from test_distributed import _scrubbed_env

    env = _scrubbed_env(None)
    env["TPK_SERVE_DIR"] = str(tmp_path / "chaos")
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "chaos.jsonl")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--seed", "1", "--events", "3"],
        capture_output=True, text=True, cwd=REPO, env=env,
        timeout=570,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    events = [e for e in _events(env["TPK_HEALTH_JOURNAL"])
              if e.get("kind") == "chaos_event"]
    assert [e["seq"] for e in events] == [1, 2, 3]
    assert all(e["seed"] == 1 for e in events)
