"""CPU chaos suite for the resilience layer (docs/RESILIENCE.md).

Every wedge-handling path in bench.py, registry and capi had only ever
been exercised by REAL tunnel failures on a live chip. These tests
drive each one deterministically through TPK_FAULT_PLAN
(tpukernels/resilience/faults.py) on CPU, asserting the observable
recovery behavior — partial results, skip decisions, surfaced causes,
preserved retry patience — plus the health-journal record and the
clean-path zero-overhead contract.

The bench subprocess tests compress the watchdog windows via
TPK_BENCH_TIMEOUT_S / TPK_BENCH_CHILD_GRACE_S / TPK_BENCH_PROBE_WAIT_S
so the REAL timeout -> hard-kill -> reclassify machinery runs in
seconds; nothing in the handling path itself is stubbed.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_env(journal_path, plan=None, **extra):
    env = _scrubbed_env(fake_devices=None)  # CPU, never the tunnel
    env["TPK_BENCH_SMOKE"] = "1"
    env["TPK_HEALTH_JOURNAL"] = str(journal_path)
    env.pop("TPK_FAULT_PLAN", None)
    if plan is not None:
        env["TPK_FAULT_PLAN"] = json.dumps(plan)
    for k, v in extra.items():
        env[k] = str(v)
    return env


def _run_bench(env, args=(), timeout=420):
    return subprocess.run(
        [sys.executable, "bench.py", *args],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )


def _events(journal_path, kind=None):
    recs = [
        json.loads(line)
        for line in journal_path.read_text().splitlines()
        if line.strip()
    ]
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs


@pytest.fixture
def fault_plan(monkeypatch):
    """Set an in-process fault plan; always restores the no-plan state
    (module-level _PLAN outlives monkeypatch's env restore)."""
    from tpukernels.resilience import faults

    def set_plan(plan):
        monkeypatch.setenv("TPK_FAULT_PLAN", json.dumps(plan))
        faults.reload_plan()
        return faults

    yield set_plan
    monkeypatch.delenv("TPK_FAULT_PLAN", raising=False)
    faults.reload_plan()


# ---------------------------------------------------------------- #
# fault plan 1: mid-metric wedge -> partial results, null headline  #
# ---------------------------------------------------------------- #

def test_wedge_mid_metric_emits_partial_results(tmp_path):
    """The 2026-07-31 signature, now reproducible: the headline child
    hangs C-level-style (immune to its own SIGALRM guard), the parent
    hard-kills it, the re-probe says the tunnel is gone -> WEDGED ->
    every remaining metric is skipped without burning a watchdog
    window, and the emitted line is partial with vs_baseline null —
    never 1.0. The whole story must also be reconstructable from the
    health journal alone via tools/health_report.py."""
    journal = tmp_path / "health.jsonl"
    # phase "operand": the wedge fires before any kernel compile, so
    # the test is independent of which kernels this box's jax version
    # can still compile (the wedge-HANDLING path is what's under test)
    plan = {
        "probe": ["ok", "dead"],
        "wedge_metric": {"metric": "sgemm_gflops", "phase": "operand"},
    }
    proc = _run_bench(
        _bench_env(journal, plan,
                   TPK_BENCH_TIMEOUT_S=15, TPK_BENCH_CHILD_GRACE_S=5)
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wedged mid-bench" in proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["vs_baseline"] is None
    import bench

    assert set(rec["details"]) == {n for n, _f in bench.BENCH_METRICS}
    assert all(v is None for v in rec["details"].values())

    # journal: watchdog fire, wedged classification, partial results
    fires = _events(journal, "watchdog_fire")
    assert any(f["mechanism"] == "subprocess-kill" for f in fires)
    cls = _events(journal, "wedge_classification")
    assert [c["verdict"] for c in cls] == ["wedged"]
    assert cls[0]["metric"] == "sgemm_gflops"
    skipped = {e["metric"] for e in _events(journal, "partial_result")}
    assert skipped == {n for n, _f in bench.BENCH_METRICS} - {
        "sgemm_gflops"}
    ends = _events(journal, "run_end")
    assert ends and ends[-1]["outcome"] == "wedged_partial"

    # the report reproduces the narrative from the journal alone
    rep = subprocess.run(
        [sys.executable, "tools/health_report.py", str(journal)],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert rep.returncode == 0, rep.stdout + rep.stderr
    for needle in ("WATCHDOG FIRED", "classified WEDGED",
                   "partial result", "run ended: wedged_partial",
                   "fault injected"):
        assert needle in rep.stdout, (needle, rep.stdout)


# ---------------------------------------------------------------- #
# fault plan 2: child timeout with a live tunnel -> SLOW, continue  #
# ---------------------------------------------------------------- #

def test_timeout_with_live_tunnel_classified_slow(tmp_path):
    """A hard-kill alone is NOT a wedge: when the post-timeout
    re-probe answers, the verdict is SLOW and the remaining metrics
    still get their windows (the subprocess-timeout recovery path)."""
    journal = tmp_path / "health.jsonl"
    # victims chosen CPU-runnable (saxpy/scan_hist are the metrics
    # whose smoke children finish in seconds on any box); the wedge
    # fires pre-compile so kernel compilability doesn't matter
    plan = {
        "probe": ["ok", "ok"],
        "wedge_metric": {"metric": "saxpy_gb_s", "phase": "operand"},
    }
    proc = _run_bench(
        _bench_env(journal, plan,
                   TPK_BENCH_TIMEOUT_S=15, TPK_BENCH_CHILD_GRACE_S=5,
                   TPK_BENCH_ONLY="saxpy_gb_s,scan_hist_melem_s")
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wedged mid-bench" not in proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["details"]["saxpy_gb_s"] is None        # killed
    assert rec["details"]["scan_hist_melem_s"] > 0     # still measured
    cls = _events(journal, "wedge_classification")
    assert [c["verdict"] for c in cls] == ["slow"]


# ---------------------------------------------------------------- #
# fault plan 3: startup probe wedged -> skip the whole run          #
# ---------------------------------------------------------------- #

def test_wedged_probe_skips_run_with_error_line(tmp_path):
    """A tunnel that hangs every liveness probe must produce the
    null-headline error line (pointing at prior evidence), not a hung
    process waiting for an outer kill."""
    journal = tmp_path / "health.jsonl"
    proc = _run_bench(
        _bench_env(journal, {"probe": ["hang"]},
                   TPK_BENCH_PROBE_ATTEMPTS=2, TPK_BENCH_PROBE_WAIT_S=0)
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["value"] is None
    assert "unreachable" in rec["details"]["error"]
    probes = _events(journal, "probe")
    assert [p["outcome"] for p in probes] == ["hang", "hang"]
    assert all(p.get("injected") for p in probes)
    ends = _events(journal, "run_end")
    assert ends and ends[-1]["outcome"] == "unreachable"


# ---------------------------------------------------------------- #
# fault plan 4: probe hangs then recovers -> patience preserved     #
# ---------------------------------------------------------------- #

def test_probe_hang_then_recover_preserves_patience(tmp_path):
    """Tunnel outages recover (observed 10+ min); two hung probe
    attempts followed by a healthy one must lead to a measuring run,
    not an early bail."""
    journal = tmp_path / "health.jsonl"
    proc = _run_bench(
        _bench_env(journal, {"probe": ["hang", "hang", "ok"]},
                   TPK_BENCH_PROBE_WAIT_S=0,
                   TPK_BENCH_ONLY="saxpy_gb_s")
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["details"]["saxpy_gb_s"] > 0  # the run happened
    probes = _events(journal, "probe")
    assert [p["outcome"] for p in probes] == ["hang", "hang", "ok"]
    ends = _events(journal, "run_end")
    assert ends and ends[-1]["outcome"] == "complete"


# ---------------------------------------------------------------- #
# fault plan 5: kernel import failure -> real cause surfaced        #
# ---------------------------------------------------------------- #

def test_import_failure_surfaces_real_cause(tmp_path):
    """A failed kernel-group import must surface ITS error from
    lookup(), never a bare 'unknown kernel' dispatch-table miss."""
    env = _scrubbed_env(fake_devices=None)
    env["TPK_FAULT_PLAN"] = json.dumps({"fail_import": "nbody"})
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "health.jsonl")
    body = (
        "from tpukernels import registry\n"
        "try:\n"
        "    registry.lookup('nbody')\n"
        "except KeyError as e:\n"
        "    print('LOOKUP-ERR:', e)\n"
        "    print('CAUSE:', repr(e.__cause__))\n"
        "print('CORE-OK:', callable(registry.lookup('vector_add')))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "failed to import" in proc.stdout
    assert "injected fault: fail_import nbody" in proc.stdout
    # the unaffected groups still dispatch
    assert "CORE-OK: True" in proc.stdout
    # the failure is a structured health event, not just a traceback
    fails = _events(tmp_path / "health.jsonl", "import_failure")
    assert any("nbody" in f["kernels"] for f in fails)


def test_required_group_import_failure_fails_loudly(tmp_path):
    """An injected failure in the REQUIRED core group must abort
    population with the injected cause (and stay retryable — the
    transient-TPU-hiccup contract)."""
    env = _scrubbed_env(fake_devices=None)
    env["TPK_FAULT_PLAN"] = json.dumps({"fail_import": "sgemm"})
    proc = subprocess.run(
        [sys.executable, "-c",
         "from tpukernels import registry; registry.lookup('sgemm')"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode != 0
    assert "injected fault: fail_import sgemm" in proc.stderr


# ---------------------------------------------------------------- #
# C-shim entry injection                                            #
# ---------------------------------------------------------------- #

def test_capi_fault_injection(fault_plan):
    from tpukernels import capi

    fault_plan({"fail_capi": "vector_add"})
    x = np.zeros(4, np.float32)
    y = np.zeros(4, np.float32)
    params = json.dumps(
        {"alpha": 1.0,
         "buffers": [{"shape": [4], "dtype": "f32"}] * 2}
    )
    with pytest.raises(RuntimeError, match="injected fault: fail_capi"):
        capi.run_from_c(
            "vector_add", params, [x.ctypes.data, y.ctypes.data]
        )


# ---------------------------------------------------------------- #
# clean-path contract: no plan -> no behavior change                #
# ---------------------------------------------------------------- #

def test_clean_path_output_byte_identical(tmp_path):
    """With TPK_FAULT_PLAN unset the injection points are a single
    guarded check; bench stdout for a fixed seed on CPU must be
    byte-identical with no plan and with an empty (matching nothing)
    plan."""
    journal = tmp_path / "health.jsonl"
    outs = []
    for plan in (None, None, {}):
        proc = _run_bench(
            _bench_env(journal, plan), args=("--one", "saxpy_gb_s")
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1] == outs[2]


def test_no_plan_means_inactive_no_op():
    from tpukernels.resilience import faults

    assert os.environ.get("TPK_FAULT_PLAN") is None
    assert not faults.active()
    # every injection point is a cheap no-op
    assert faults.probe_outcome() is None
    faults.phase_fault("execute")
    faults.import_fault(("sgemm",))
    faults.capi_fault("sgemm")


# ---------------------------------------------------------------- #
# primitive units: plan loading, journal, watchdog                  #
# ---------------------------------------------------------------- #

def test_fault_plan_from_file_and_inline(tmp_path, fault_plan):
    from tpukernels.resilience import faults

    f = fault_plan({"hang_probe": 2})
    assert f.active()
    assert f.probe_outcome() == "hang"
    assert f.probe_outcome() == "hang"
    assert f.probe_outcome() is None  # sugar exhausted: real probe

    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps({"fail_capi": "sgemm"}))
    os.environ["TPK_FAULT_PLAN"] = str(plan_file)
    try:
        assert f.reload_plan() == {"fail_capi": "sgemm"}
    finally:
        del os.environ["TPK_FAULT_PLAN"]
        f.reload_plan()


def test_fault_plan_rejects_non_object(monkeypatch):
    from tpukernels.resilience import faults

    monkeypatch.setenv("TPK_FAULT_PLAN", "[1, 2]")
    with pytest.raises(ValueError, match="JSON object"):
        faults.reload_plan()
    monkeypatch.delenv("TPK_FAULT_PLAN")
    faults.reload_plan()


def test_journal_emit_and_disable(tmp_path, monkeypatch):
    from tpukernels.resilience import journal

    p = tmp_path / "j.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(p))
    journal.emit("probe", attempt=0, outcome="alive")
    journal.emit("watchdog_fire", mechanism="sigalrm")
    recs = [json.loads(x) for x in p.read_text().splitlines()]
    assert [r["kind"] for r in recs] == ["probe", "watchdog_fire"]
    for r in recs:
        # HEAD sha + wall clock on every event (postmortem correlation)
        assert r["ts"] and isinstance(r["t"], float) and r["pid"]
        assert isinstance(r.get("git_head"), str)

    monkeypatch.setenv("TPK_HEALTH_JOURNAL", "0")
    assert journal.path() is None
    journal.emit("probe", attempt=1)  # must be a silent no-op
    assert len(p.read_text().splitlines()) == 2

    # a directory routes to a dated file inside it
    d = tmp_path / "jdir"
    d.mkdir()
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(d))
    journal.emit("probe", attempt=2)
    files = list(d.iterdir())
    assert len(files) == 1 and files[0].name.startswith("health_")


def test_watchdog_alarm_guard():
    import time

    from tpukernels.resilience import watchdog

    with pytest.raises(watchdog.Timeout):
        watchdog.run_with_alarm(lambda: time.sleep(5), 1)
    assert watchdog.run_with_alarm(lambda: 42, 1) == 42
    time.sleep(1.2)  # a stale alarm would fire here


def test_watchdog_kill_after():
    from tpukernels.resilience import watchdog

    proc, status = watchdog.kill_after(
        [sys.executable, "-c", "import time; time.sleep(30)"], 0.5
    )
    assert (proc, status) == (None, "timeout")
    proc, status = watchdog.kill_after(
        [sys.executable, "-c", "print('hi')"], 30,
        stdout=subprocess.PIPE, text=True,
    )
    assert status == "ok" and proc.stdout.strip() == "hi"


def test_watchdog_classify_timeout(tmp_path, monkeypatch):
    from tpukernels.resilience import journal, watchdog

    p = tmp_path / "j.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(p))
    assert watchdog.classify_timeout(True, metric="m") == "slow"
    assert watchdog.classify_timeout(False, metric="m") == "wedged"
    recs = [json.loads(x) for x in p.read_text().splitlines()]
    assert [r["verdict"] for r in recs] == ["slow", "wedged"]


def test_patient_probe_semantics(monkeypatch):
    from tpukernels.resilience import watchdog

    monkeypatch.setattr(watchdog.time, "sleep", lambda s: None)
    seen = []

    def probe(outcomes):
        def once(attempt):
            seen.append(attempt)
            return outcomes[attempt]
        return once

    seen.clear()
    assert watchdog.patient_probe(probe(["retry", "alive"]), 5, 0) is True
    assert seen == [0, 1]
    seen.clear()
    # "dead" is definitive: patience must NOT continue
    assert watchdog.patient_probe(probe(["dead"]), 5, 0) is False
    assert seen == [0]
    seen.clear()
    assert watchdog.patient_probe(probe(["retry"] * 3), 3, 0) is False
    assert seen == [0, 1, 2]
