"""CPU suite for the AOT precompile + persistent executable cache
(docs/PERF.md §compile discipline).

Covers the tentpole contracts without a TPU: one compile per (kernel,
shape, dtype, statics) per process across precompile and dispatch
entry paths, manifest keying/invalidation (a stale kernel-source sha
rejects exactly that kernel's entries, loudly), the warm-start proof
(second-process compile-span wall a fraction of the cold wall, with
aot_hit evidence), `TPK_AOT_CACHE=0` disabling cleanly, byte-identical
clean-path bench stdout with the layer on and off, the prewarm CLI's
exit-code contract, the tuning runner's per-candidate hit-ratio tail
reader, and the supervisor's measured-cost refinement.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events(path, kind=None):
    recs = [
        json.loads(line)
        for line in open(path).read().splitlines()
        if line.strip()
    ]
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs


@pytest.fixture
def aot_env(monkeypatch, tmp_path):
    """Isolated AOT state: manifest in a tmp dir, journal in a tmp
    file, per-process memos cleared on both sides of the test."""
    from tpukernels import aot
    from tpukernels.obs import metrics

    monkeypatch.setenv("TPK_AOT_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(tmp_path / "j.jsonl"))
    monkeypatch.delenv("TPK_AOT_CACHE", raising=False)
    aot.reset()
    metrics.reset()
    yield tmp_path
    aot.reset()
    metrics.reset()


def _aot_compiles():
    from tpukernels.obs import metrics

    return metrics.snapshot()["counters"].get("aot.compiles", 0)


# ---------------------------------------------------------------- #
# keys + spec coverage                                              #
# ---------------------------------------------------------------- #

def test_cache_key_format(aot_env):
    """Keys follow the tuning cache's kernel|shape|dtype|device_kind
    scheme; statics select a different program, so they ride on the
    kernel field."""
    from tpukernels import aot

    x = np.zeros((64, 128), np.float32)
    key = aot.cache_key("sgemm", (x, x), kind="cpu")
    assert key == "sgemm|64x128+64x128|float32|cpu"
    key = aot.cache_key("histogram", (np.zeros(16, np.int32),),
                        statics={"nbins": 256}, kind="cpu")
    assert key == "histogram@nbins=256|16|int32|cpu"


def test_tuning_promotion_changes_cache_key(aot_env, monkeypatch):
    """A tuning-cache promotion selects different compiled programs at
    unchanged shapes, so it must change the AOT key — the manifest
    must never claim aot_hit for a post-promotion compile."""
    from tpukernels import aot

    tdir = aot_env / "tuned"
    tdir.mkdir()
    monkeypatch.setenv("TPK_TUNING_CACHE_DIR", str(tdir))
    x = np.zeros(16, np.float32)
    key_before = aot.cache_key("vector_add", (x,), kind="cpu")
    (tdir / "tuning.json").write_text('{"entries": {"k": 1}}')
    key_after = aot.cache_key("vector_add", (x,), kind="cpu")
    assert key_before != key_after
    assert "tuned=" in key_after
    # same content -> same key (stable across processes)
    aot.reset()
    assert aot.cache_key("vector_add", (x,), kind="cpu") == key_after
    # disabled cache contributes nothing
    monkeypatch.setenv("TPK_TUNING_CACHE", "0")
    assert aot.cache_key("vector_add", (x,), kind="cpu") == key_before


def test_every_registered_config_has_sources():
    """A kernel config without a sources row would validate against
    nothing — its manifest entries could never go stale."""
    from tpukernels import aot

    for name in aot.BENCH_CONFIGS:
        assert aot.KERNEL_SOURCES.get(name), name


def test_registry_precompilable_covers_registry():
    """Every registry kernel must precompile (a new kernel added
    without a BENCH_CONFIGS row silently escapes the prewarm)."""
    from tpukernels import registry

    assert registry.precompilable_kernels() == registry.names()


# ---------------------------------------------------------------- #
# one compile per (kernel, shape, dtype) per process                #
# ---------------------------------------------------------------- #

def test_precompile_then_dispatch_reuses_executable(aot_env):
    """The dedupe contract: registry.precompile compiles the bench
    config ONCE; a later dispatch at the same shapes (the capi path)
    reuses the compiled executable — no second compile anywhere."""
    import jax.numpy as jnp

    from tpukernels import registry

    row = registry.precompile("vector_add")
    assert row["expected"] == "miss"
    assert _aot_compiles() == 1
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(1 << 20), jnp.float32)
    y = jnp.asarray(rng.standard_normal(1 << 20), jnp.float32)
    out = registry.dispatch("vector_add", jnp.float32(2.0), x, y)
    np.testing.assert_allclose(
        np.asarray(out), 2.0 * np.asarray(x) + np.asarray(y), rtol=1e-5
    )
    assert _aot_compiles() == 1  # the dispatch did NOT recompile
    # and a repeat precompile is a memo no-op too
    registry.precompile("vector_add")
    assert _aot_compiles() == 1


def test_dispatch_statics_share_one_compile(aot_env):
    """Static params (nbins) are part of the program: one compile per
    distinct static set, reused across repeat dispatches."""
    import jax.numpy as jnp

    from tpukernels import registry

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 256, 1 << 16), jnp.int32)
    h1 = registry.dispatch("histogram", x, nbins=256)
    assert _aot_compiles() == 1
    h2 = registry.dispatch("histogram", x, nbins=256)
    assert _aot_compiles() == 1
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))
    np.testing.assert_array_equal(
        np.asarray(h1), np.bincount(np.asarray(x), minlength=256)
    )


# ---------------------------------------------------------------- #
# disable knob                                                      #
# ---------------------------------------------------------------- #

def test_disabled_cleanly(aot_env, monkeypatch):
    """TPK_AOT_CACHE=0: dispatch falls through to the plain eager
    wrapper (same numbers), nothing compiles through the choke point,
    no manifest appears, and precompile refuses loudly."""
    import jax.numpy as jnp

    from tpukernels import aot, registry

    monkeypatch.setenv("TPK_AOT_CACHE", "0")
    assert not aot.enabled()
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal(1 << 12), jnp.float32)
    y = jnp.asarray(rng.standard_normal(1 << 12), jnp.float32)
    out = registry.dispatch("vector_add", jnp.float32(0.5), x, y)
    np.testing.assert_allclose(
        np.asarray(out), 0.5 * np.asarray(x) + np.asarray(y), rtol=1e-5
    )
    assert _aot_compiles() == 0
    assert not os.path.exists(os.path.join(str(aot_env), "aot.json"))
    with pytest.raises(RuntimeError, match="TPK_AOT_CACHE"):
        aot.precompile("vector_add")


# ---------------------------------------------------------------- #
# manifest keying / invalidation                                    #
# ---------------------------------------------------------------- #

def test_stale_source_sha_invalidates_exactly_that_kernel(aot_env):
    """Touching one kernel's sources (simulated: its manifest entry
    carries a sha no commit matches) rejects exactly that kernel's
    entries — loudly — while the other kernel's entry still reads as
    a hit."""
    import jax.numpy as jnp

    from tpukernels import aot, registry

    x = jnp.asarray(np.ones(1 << 10), jnp.float32)
    s = jnp.asarray(np.ones(1 << 10), jnp.int32)
    registry.dispatch("vector_add", jnp.float32(1.0), x, x)
    registry.dispatch("scan", s)
    manifest = os.path.join(str(aot_env), "aot.json")
    data = json.load(open(manifest))
    scan_keys = [k for k in data["entries"] if k.startswith("scan|")]
    va_keys = [k for k in data["entries"] if k.startswith("vector_add|")]
    assert scan_keys and va_keys
    for k in scan_keys:
        data["entries"][k]["source_sha"] = "0" * 40  # pre-"commit" sha
    json.dump(data, open(manifest, "w"))

    aot.reset()  # fresh process, same manifest
    registry.dispatch("vector_add", jnp.float32(1.0), x, x)
    registry.dispatch("scan", s)
    jpath = os.path.join(str(aot_env), "j.jsonl")
    rejected = {e["key"] for e in _events(jpath, "aot_rejected")}
    hits = {e["key"] for e in _events(jpath, "aot_hit")}
    assert rejected == set(scan_keys)
    assert set(va_keys) <= hits
    assert not (set(scan_keys) & hits)


# ---------------------------------------------------------------- #
# warm start across processes (the acceptance proof)                #
# ---------------------------------------------------------------- #

def _run_prewarm(tmp_path, tag, kernels):
    env = _scrubbed_env(fake_devices=None)
    env["JAX_COMPILATION_CACHE_DIR"] = str(tmp_path / "cache")
    env["TPK_AOT_CACHE_DIR"] = str(tmp_path / "cache")
    env["TPK_TUNING_CACHE"] = "0"
    env["TPK_TRACE"] = "1"
    journal = tmp_path / f"j_{tag}.jsonl"
    env["TPK_HEALTH_JOURNAL"] = str(journal)
    proc = subprocess.run(
        [sys.executable, "tools/prewarm.py", "--kernels", kernels],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return journal


def _compile_span_total(journal):
    return sum(
        e["wall_s"] for e in _events(journal, "span")
        if e["name"].startswith("aot/compile/")
    )


@pytest.mark.slow
def test_second_process_prewarm_is_warm_full_registry(tmp_path):
    """The acceptance criterion end to end: prewarm the FULL
    registered suite cold, then again in a fresh process — every key
    is an aot_hit and the summed aot/compile span wall lands well
    under the 20% bar (gated at 50% against CI timer noise; measured
    ~7% on this container)."""
    from tpukernels import aot

    kernels = ",".join(sorted(aot.BENCH_CONFIGS))
    cold = _run_prewarm(tmp_path, "cold", kernels)
    warm = _run_prewarm(tmp_path, "warm", kernels)
    n = len(aot.BENCH_CONFIGS)
    assert len(_events(cold, "aot_miss")) == n
    assert len(_events(warm, "aot_hit")) == n
    assert _events(warm, "aot_miss") == []
    cold_s, warm_s = _compile_span_total(cold), _compile_span_total(warm)
    assert cold_s > 0
    assert warm_s < 0.5 * cold_s, (warm_s, cold_s)


def test_second_process_compile_is_cache_hit_small(tmp_path):
    """Fast (not slow-marked) two-kernel version of the warm-start
    proof, so tier-1 always exercises the cross-process hit path."""
    cold = _run_prewarm(tmp_path, "cold", "vector_add,scan")
    warm = _run_prewarm(tmp_path, "warm", "vector_add,scan")
    assert len(_events(cold, "aot_miss")) == 2
    assert len(_events(warm, "aot_hit")) == 2
    assert _events(warm, "aot_miss") == []


# ---------------------------------------------------------------- #
# bench integration: byte-identical stdout, slope evidence          #
# ---------------------------------------------------------------- #

def test_bench_stdout_byte_identical_aot_on_off(tmp_path):
    """Clean-path bench stdout must not change with the AOT layer on
    vs off (same proof style as the fault and trace layers); only the
    enabled run's journal carries aot evidence, keyed by the bench
    loop-program naming (bench_saxpy.R<n>)."""
    outs, journals = [], []
    for i, knob in enumerate((None, "0")):
        env = _scrubbed_env(fake_devices=None)
        env["TPK_BENCH_SMOKE"] = "1"
        env["JAX_COMPILATION_CACHE_DIR"] = str(tmp_path / "cache")
        env["TPK_AOT_CACHE_DIR"] = str(tmp_path / "cache")
        env["TPK_TUNING_CACHE"] = "0"
        journal = tmp_path / f"health_{i}.jsonl"
        journals.append(journal)
        env["TPK_HEALTH_JOURNAL"] = str(journal)
        env.pop("TPK_AOT_CACHE", None)
        env.pop("TPK_FAULT_PLAN", None)
        env.pop("TPK_TRACE", None)
        if knob is not None:
            env["TPK_AOT_CACHE"] = knob
        proc = subprocess.run(
            [sys.executable, "bench.py", "--one", "saxpy_gb_s"],
            env=env, capture_output=True, text=True, timeout=420,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]
    on_keys = [e["key"] for e in _events(journals[0], "aot_miss")]
    assert sorted(on_keys) == [
        "bench_saxpy.R1|1048576+1048576|float32|cpu",
        "bench_saxpy.R2|1048576+1048576|float32|cpu",
    ]
    assert _events(journals[1], "aot_miss") == []
    assert _events(journals[1], "aot_hit") == []


# ---------------------------------------------------------------- #
# prewarm CLI exit-code contract                                    #
# ---------------------------------------------------------------- #

def test_prewarm_cli_usage_and_disabled(tmp_path):
    env = _scrubbed_env(fake_devices=None)
    env["TPK_AOT_CACHE_DIR"] = str(tmp_path)
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "j.jsonl")
    bad = subprocess.run(
        [sys.executable, "tools/prewarm.py", "--kernels", "not_a_kernel"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert bad.returncode == 2, bad.stdout + bad.stderr
    assert "unknown" in bad.stderr
    env["TPK_AOT_CACHE"] = "0"
    off = subprocess.run(
        [sys.executable, "tools/prewarm.py"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert off.returncode == 1, off.stdout + off.stderr
    assert "TPK_AOT_CACHE=0" in off.stderr


# ---------------------------------------------------------------- #
# tuning runner: per-candidate hit-ratio tail reader                #
# ---------------------------------------------------------------- #

def test_runner_aot_hit_ratio_tail(tmp_path):
    """The ratio counts only events appended past the recorded offset
    — candidate N's evidence, not the whole sweep's."""
    from tpukernels.tuning import runner

    j = tmp_path / "j.jsonl"
    j.write_text(json.dumps({"kind": "aot_miss"}) + "\n")
    offset = runner._journal_size(str(j))
    with open(j, "a") as f:
        for kind in ("aot_hit", "aot_hit", "aot_miss", "span"):
            f.write(json.dumps({"kind": kind}) + "\n")
    assert runner._aot_hit_ratio(str(j), offset) == pytest.approx(
        2 / 3, abs=1e-3
    )
    assert runner._aot_hit_ratio(str(j), runner._journal_size(str(j))) \
        is None
    assert runner._aot_hit_ratio(None, 0) is None


# ---------------------------------------------------------------- #
# supervisor: measured prewarm walls refine the admission cost      #
# ---------------------------------------------------------------- #

def test_observed_prewarm_cost_min():
    """Newest wall per kernel inside 24 h, summed, clamped; failures
    and stale events don't count; no evidence -> None (shipped
    cost_min stands)."""
    from tpukernels.resilience import supervisor

    now = 1_000_000.0
    events = [
        {"kind": "prewarm_kernel", "kernel": "sgemm", "status": "ok",
         "wall_s": 300.0, "t": now - 7200},
        # newer sgemm measurement supersedes the older one
        {"kind": "prewarm_kernel", "kernel": "sgemm", "status": "ok",
         "wall_s": 60.0, "t": now - 600},
        {"kind": "prewarm_kernel", "kernel": "scan", "status": "ok",
         "wall_s": 120.0, "t": now - 600},
        {"kind": "prewarm_kernel", "kernel": "nbody", "status": "error",
         "wall_s": 900.0, "t": now - 600},          # failed: ignored
        {"kind": "prewarm_kernel", "kernel": "stencil3d", "status": "ok",
         "wall_s": 900.0, "t": now - 25 * 3600},    # stale: ignored
    ]
    est = supervisor.observed_prewarm_cost_min(events, now=now)
    assert est == pytest.approx((60.0 + 120.0) / 60.0)
    assert supervisor.observed_prewarm_cost_min([], now=now) is None
    # tiny warm walls clamp to the floor, never to zero
    tiny = [{"kind": "prewarm_kernel", "kernel": "scan", "status": "ok",
             "wall_s": 1.0, "t": now - 60}]
    assert supervisor.observed_prewarm_cost_min(tiny, now=now) == 0.5


def test_supervisor_applies_prewarm_cost(tmp_path, monkeypatch):
    """A cost_from="prewarm" step's cost_min is re-derived from the
    journal before admission, and the decision is journaled as
    step_cost_estimated."""
    from tpukernels.resilience import supervisor

    journal_path = tmp_path / "health_x.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    monkeypatch.setenv("TPK_SUPERVISOR_CHECKPOINT",
                       str(tmp_path / "cp.jsonl"))
    monkeypatch.setenv("TPK_REVALIDATE_STAMP_DIR",
                       str(tmp_path / "stamps"))
    monkeypatch.setenv("TPK_SUPERVISOR_WINDOW_MIN", "25")
    import time as _time

    with open(journal_path, "w") as f:
        f.write(json.dumps({
            "kind": "prewarm_kernel", "kernel": "sgemm", "status": "ok",
            "wall_s": 120.0, "t": _time.time() - 60,
            "ts": "x",
        }) + "\n")
    spec = supervisor.StepSpec("prewarm_all", "true", gating=False,
                               cost_min=12, value=50,
                               cost_from="prewarm")
    sup = supervisor.Supervisor([spec], repo=str(tmp_path),
                                announce=False)
    rc = sup.run_queue()
    assert rc == supervisor.RC_GREEN
    # the refinement is per-run, never a mutation of the shared spec:
    # a later Supervisor built from the same module-level queue must
    # still see the shipped cost as its "prior"
    assert sup._cost_min(spec) == pytest.approx(2.0)
    assert spec.cost_min == 12
    ests = _events(journal_path, "step_cost_estimated")
    assert ests and ests[0]["step"] == "prewarm_all"
    assert ests[0]["prior_cost_min"] == 12
