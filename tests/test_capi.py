"""C-shim marshalling layer tests (SURVEY.md C10, Python side).

Exercises tpukernels.capi.run_from_c exactly as the C shim does: raw
host pointers + a JSON buffer description, results copied back into
the caller-owned buffers. Complements c/test_shim_abi.c (the C side of
the ABI) without needing the compiled shim or a TPU.
"""

import json

import numpy as np
import pytest

from tpukernels import capi


def _addr(a: np.ndarray) -> int:
    return a.ctypes.data


def test_vector_add_roundtrip(rng):
    n = 1000
    x = np.ascontiguousarray(rng.standard_normal(n), dtype=np.float32)
    y = np.ascontiguousarray(rng.standard_normal(n), dtype=np.float32)
    want = 2.5 * x + y
    params = json.dumps(
        {
            "alpha": 2.5,
            "buffers": [
                {"shape": [n], "dtype": "f32"},
                {"shape": [n], "dtype": "f32"},
            ],
        }
    )
    assert capi.run_from_c("vector_add", params, [_addr(x), _addr(y)]) == 0
    np.testing.assert_allclose(y, want, rtol=1e-6, atol=1e-6)


def test_scan_and_histogram_roundtrip(rng):
    n, nbins = 5000, 64
    x = np.ascontiguousarray(rng.integers(0, nbins, n), dtype=np.int32)
    scan_out = np.zeros(n, dtype=np.int32)
    params = json.dumps(
        {
            "buffers": [
                {"shape": [n], "dtype": "i32"},
                {"shape": [n], "dtype": "i32"},
            ]
        }
    )
    assert capi.run_from_c("scan", params, [_addr(x), _addr(scan_out)]) == 0
    np.testing.assert_array_equal(scan_out, np.cumsum(x))

    excl_out = np.zeros(n, dtype=np.int32)
    excl_params = json.dumps(
        {
            "exclusive": True,
            "buffers": [
                {"shape": [n], "dtype": "i32"},
                {"shape": [n], "dtype": "i32"},
            ],
        }
    )
    assert capi.run_from_c("scan", excl_params, [_addr(x), _addr(excl_out)]) == 0
    np.testing.assert_array_equal(
        excl_out, np.concatenate([[0], np.cumsum(x)[:-1]])
    )

    counts = np.zeros(nbins, dtype=np.int32)
    params = json.dumps(
        {
            "nbins": nbins,
            "buffers": [
                {"shape": [n], "dtype": "i32"},
                {"shape": [nbins], "dtype": "i32"},
            ],
        }
    )
    assert capi.run_from_c("histogram", params, [_addr(x), _addr(counts)]) == 0
    np.testing.assert_array_equal(counts, np.bincount(x, minlength=nbins))


def test_stencil2d_roundtrip(rng):
    h, w = 64, 128
    x = np.ascontiguousarray(rng.standard_normal((h, w)), dtype=np.float32)
    orig = x.copy()
    params = json.dumps(
        {"iters": 3, "buffers": [{"shape": [h, w], "dtype": "f32"}]}
    )
    assert capi.run_from_c("stencil2d", params, [_addr(x)]) == 0
    # boundary held fixed, interior changed
    np.testing.assert_array_equal(x[0], orig[0])
    np.testing.assert_array_equal(x[-1], orig[-1])
    assert not np.array_equal(x[1:-1, 1:-1], orig[1:-1, 1:-1])


def test_buffer_count_mismatch_raises():
    x = np.zeros(8, dtype=np.float32)
    params = json.dumps({"buffers": [{"shape": [8], "dtype": "f32"}]})
    with pytest.raises(ValueError, match="pointers but"):
        capi.run_from_c("vector_add", params, [_addr(x), _addr(x)])


def test_unknown_kernel_raises():
    params = json.dumps({"buffers": []})
    with pytest.raises(KeyError, match="no C adapter"):
        capi.run_from_c("not_a_kernel", params, [])


def test_unknown_dtype_raises():
    # the ABI carries exactly the dtypes the C drivers emit (f32/i32);
    # anything else must fail loudly naming the supported set
    x = np.zeros(8, dtype=np.float64)
    params = json.dumps({"buffers": [{"shape": [8], "dtype": "f64"}] * 2})
    with pytest.raises(ValueError, match="unsupported buffer dtype"):
        capi.run_from_c("vector_add", params, [_addr(x), _addr(x)])


def test_profiler_trace_flushes_on_exit(tmp_path):
    """TPU_KERNELS_PROFILE traces only reach disk on stop_trace; a
    Python host flushes via the capi atexit hook; C hosts go through
    the shim's tpu_shutdown → shutdown_from_c instead (registered with
    C atexit inside tpu_init, see test_profiler_trace_flushes_c_host)."""
    import os
    import subprocess
    import sys
    import textwrap

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_KERNELS_PROFILE"] = str(tmp_path)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    body = textwrap.dedent("""
        import json
        import numpy as np
        from tpukernels import capi
        n = 256
        x = np.ascontiguousarray(np.arange(n), dtype=np.float32)
        y = np.zeros(n, dtype=np.float32)
        params = json.dumps({"alpha": 1.0, "buffers": [
            {"shape": [n], "dtype": "f32"}] * 2})
        assert capi.run_from_c(
            "vector_add", params, [x.ctypes.data, y.ctypes.data]) == 0
    """)
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    traced = [
        p for p in tmp_path.rglob("*") if p.is_file()
    ]
    assert traced, "no profile trace files were flushed"


def test_profiler_trace_flushes_c_host(tmp_path):
    """The C-host flush path: a built C driver binary exits without
    finalizing the embedded interpreter, so the trace must flush via
    the shim's atexit(tpu_shutdown) → shutdown_from_c chain."""
    import os
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = os.path.join(repo, "c", "bin", "vector_add")
    if not os.path.exists(binary):
        pytest.skip("c/bin not built (run `make -C c`)")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_KERNELS_PROFILE"] = str(tmp_path)
    proc = subprocess.run(
        [binary, "--device=tpu", "--check", "--reps=1", "--n=10000"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.join(repo, "c"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CHECK PASS" in proc.stdout
    traced = [p for p in tmp_path.rglob("*") if p.is_file()]
    assert traced, "C host exited without flushing the profile trace"


def test_scan_histogram_combined_roundtrip(rng):
    """The combined adapter the C driver's tpu row dispatches: one
    upload of x feeding both halves."""
    n, nbins = 3000, 32
    x = np.ascontiguousarray(rng.integers(0, nbins, n), dtype=np.int32)
    scan_out = np.zeros(n, dtype=np.int32)
    counts = np.zeros(nbins, dtype=np.int32)
    params = json.dumps(
        {
            "nbins": nbins,
            "buffers": [
                {"shape": [n], "dtype": "i32"},
                {"shape": [n], "dtype": "i32"},
                {"shape": [nbins], "dtype": "i32"},
            ],
        }
    )
    assert capi.run_from_c(
        "scan_histogram", params, [_addr(x), _addr(scan_out), _addr(counts)]
    ) == 0
    np.testing.assert_array_equal(scan_out, np.cumsum(x))
    np.testing.assert_array_equal(counts, np.bincount(x, minlength=nbins))


def test_registry_reports_broken_kernel_module():
    """A kernel module that fails to import must surface its real
    error from lookup(), not a bare 'unknown kernel' dispatch miss."""
    import os
    import subprocess
    import sys
    import textwrap

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    body = textwrap.dedent("""
        import sys
        import tpukernels.registry as reg
        sys.modules["tpukernels.kernels.scan"] = None  # import raises
        try:
            reg.lookup("scan")
            raise SystemExit("lookup('scan') did not raise")
        except KeyError as e:
            assert "failed to import" in str(e), e
        assert "vector_add" in reg.names() and "scan" not in reg.names()
        try:
            reg.lookup("nope")
            raise SystemExit("lookup('nope') did not raise")
        except KeyError as e:
            assert "unknown kernel" in str(e), e
        print("OK")
    """)
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_suite_falls_back_to_cpu_when_tunnel_dead():
    """A wedged axon tunnel hangs instead of erroring; conftest must
    detect it and re-exec the suite on CPU rather than hang. Forced
    via TPK_FORCE_TPU_PROBE_FAIL (the real probe path runs whenever
    this box's pool var is set)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "203.0.113.1"  # pretend a tunnel is up
    env["TPK_FORCE_TPU_PROBE_FAIL"] = "1"
    env.pop("TPK_TPU_PROBE_DONE", None)
    # the revalidation queue (tools/tpu_revalidate.sh) runs the suite
    # with TPK_REQUIRE_TPU=1; inheriting it here would make the child
    # conftest RAISE on the forced-dead probe instead of exercising
    # the CPU fallback this test is about (seen as the one F in the
    # 2026-07-31 on-chip run)
    env.pop("TPK_REQUIRE_TPU", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "-q",
            "tests/test_capi.py::test_unknown_kernel_raises",
        ],
        env=env, capture_output=True, text=True, timeout=300, cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "re-running the suite on CPU" in proc.stderr
    assert "1 passed" in proc.stdout


def test_require_tpu_refuses_cpu_fallback():
    """TPK_REQUIRE_TPU=1 (the revalidation script's gate) must FAIL
    when the tunnel is dead instead of silently going green on CPU."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "203.0.113.1"
    env["TPK_FORCE_TPU_PROBE_FAIL"] = "1"
    env["TPK_REQUIRE_TPU"] = "1"
    env.pop("TPK_TPU_PROBE_DONE", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "-q",
            "tests/test_capi.py::test_unknown_kernel_raises",
        ],
        env=env, capture_output=True, text=True, timeout=300, cwd=repo,
    )
    assert proc.returncode != 0
    assert "refusing the CPU fallback" in proc.stdout + proc.stderr


def test_profiler_restart_after_shutdown_flushes(tmp_path):
    """A host that flushes (shutdown_from_c) and keeps dispatching
    restarts the trace; the restarted trace must flush too — two
    stop_trace dumps, not one."""
    import os
    import subprocess
    import sys
    import textwrap

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_KERNELS_PROFILE"] = str(tmp_path)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    body = textwrap.dedent("""
        import json, time
        import numpy as np
        from tpukernels import capi
        n = 128
        x = np.ascontiguousarray(np.arange(n), dtype=np.float32)
        y = np.zeros(n, dtype=np.float32)
        params = json.dumps({"alpha": 1.0, "buffers": [
            {"shape": [n], "dtype": "f32"}] * 2})
        for _ in range(2):
            assert capi.run_from_c(
                "vector_add", params, [x.ctypes.data, y.ctypes.data]) == 0
            capi.shutdown_from_c()
            time.sleep(1.1)  # dump dirs are second-granularity stamps
    """)
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    dumps = list(tmp_path.glob("plugins/profile/*"))
    assert len(dumps) == 2, f"expected 2 trace dumps, got {dumps}"
