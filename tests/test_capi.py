"""C-shim marshalling layer tests (SURVEY.md C10, Python side).

Exercises tpukernels.capi.run_from_c exactly as the C shim does: raw
host pointers + a JSON buffer description, results copied back into
the caller-owned buffers. Complements c/test_shim_abi.c (the C side of
the ABI) without needing the compiled shim or a TPU.
"""

import json

import numpy as np
import pytest

from tpukernels import capi


def _addr(a: np.ndarray) -> int:
    return a.ctypes.data


def test_vector_add_roundtrip(rng):
    n = 1000
    x = np.ascontiguousarray(rng.standard_normal(n), dtype=np.float32)
    y = np.ascontiguousarray(rng.standard_normal(n), dtype=np.float32)
    want = 2.5 * x + y
    params = json.dumps(
        {
            "alpha": 2.5,
            "buffers": [
                {"shape": [n], "dtype": "f32"},
                {"shape": [n], "dtype": "f32"},
            ],
        }
    )
    assert capi.run_from_c("vector_add", params, [_addr(x), _addr(y)]) == 0
    np.testing.assert_allclose(y, want, rtol=1e-6, atol=1e-6)


def test_scan_and_histogram_roundtrip(rng):
    n, nbins = 5000, 64
    x = np.ascontiguousarray(rng.integers(0, nbins, n), dtype=np.int32)
    scan_out = np.zeros(n, dtype=np.int32)
    params = json.dumps(
        {
            "buffers": [
                {"shape": [n], "dtype": "i32"},
                {"shape": [n], "dtype": "i32"},
            ]
        }
    )
    assert capi.run_from_c("scan", params, [_addr(x), _addr(scan_out)]) == 0
    np.testing.assert_array_equal(scan_out, np.cumsum(x))

    excl_out = np.zeros(n, dtype=np.int32)
    excl_params = json.dumps(
        {
            "exclusive": True,
            "buffers": [
                {"shape": [n], "dtype": "i32"},
                {"shape": [n], "dtype": "i32"},
            ],
        }
    )
    assert capi.run_from_c("scan", excl_params, [_addr(x), _addr(excl_out)]) == 0
    np.testing.assert_array_equal(
        excl_out, np.concatenate([[0], np.cumsum(x)[:-1]])
    )

    counts = np.zeros(nbins, dtype=np.int32)
    params = json.dumps(
        {
            "nbins": nbins,
            "buffers": [
                {"shape": [n], "dtype": "i32"},
                {"shape": [nbins], "dtype": "i32"},
            ],
        }
    )
    assert capi.run_from_c("histogram", params, [_addr(x), _addr(counts)]) == 0
    np.testing.assert_array_equal(counts, np.bincount(x, minlength=nbins))


def test_stencil2d_roundtrip(rng):
    h, w = 64, 128
    x = np.ascontiguousarray(rng.standard_normal((h, w)), dtype=np.float32)
    orig = x.copy()
    params = json.dumps(
        {"iters": 3, "buffers": [{"shape": [h, w], "dtype": "f32"}]}
    )
    assert capi.run_from_c("stencil2d", params, [_addr(x)]) == 0
    # boundary held fixed, interior changed
    np.testing.assert_array_equal(x[0], orig[0])
    np.testing.assert_array_equal(x[-1], orig[-1])
    assert not np.array_equal(x[1:-1, 1:-1], orig[1:-1, 1:-1])


def test_buffer_count_mismatch_raises():
    x = np.zeros(8, dtype=np.float32)
    params = json.dumps({"buffers": [{"shape": [8], "dtype": "f32"}]})
    with pytest.raises(ValueError, match="pointers but"):
        capi.run_from_c("vector_add", params, [_addr(x), _addr(x)])


def test_unknown_kernel_raises():
    params = json.dumps({"buffers": []})
    with pytest.raises(KeyError, match="no C adapter"):
        capi.run_from_c("not_a_kernel", params, [])
