"""CPU suite for the observability layer (docs/OBSERVABILITY.md).

Covers the tentpole contracts without a TPU: span nesting and the
TPK_TRACE-unset no-op (including the byte-identical clean bench path,
proven the same way the fault layer's is), metric counter semantics,
trend verdicts on fixture series — regression beyond the epsilon
band, the physically-impossible 72,698-GFLOPS class of error, nulls
as no-data — the BENCH_r*.json tunnel-down nesting tolerance, the
probe_failed journal event, health_report's span breakdown, and the
journal-kind lint that keeps docs/OBSERVABILITY.md's catalog honest.
"""

import json
import os
import subprocess
import sys

import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the invalidated figure of record (BASELINE.md 2026-07-31 07:16 note)
# and its ceiling — the exact error class trend.py must catch
SGEMM_DRIFT = 72698.96
SGEMM_CEILING = 61333


def _events(path, kind=None):
    recs = [
        json.loads(line)
        for line in open(path).read().splitlines()
        if line.strip()
    ]
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs


@pytest.fixture
def traced(monkeypatch, tmp_path):
    """TPK_TRACE on + journal routed to a tmp file; always restores
    the module-level enabled flag (it outlives monkeypatch's env
    restore, like the fault layer's _PLAN)."""
    from tpukernels.obs import trace

    journal_path = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_TRACE", "1")
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    trace.reload()
    yield journal_path
    monkeypatch.delenv("TPK_TRACE")
    trace.reload()


# ---------------------------------------------------------------- #
# trace: nesting, fields, disable no-op                             #
# ---------------------------------------------------------------- #

def test_span_nesting_records_paths_and_fields(traced):
    from tpukernels.obs import trace

    with trace.span("measure/sgemm", m=1024):
        assert trace.current_path() == "measure/sgemm"
        with trace.span("slope/compile", r_small=50):
            assert trace.current_path() == "measure/sgemm/slope/compile"
    assert trace.current_path() is None
    spans = _events(traced, "span")
    # inner exits (and emits) first
    assert [s["name"] for s in spans] == [
        "measure/sgemm/slope/compile", "measure/sgemm",
    ]
    inner, outer = spans
    assert inner["depth"] == 2 and outer["depth"] == 1
    assert inner["r_small"] == 50 and outer["m"] == 1024
    assert inner["wall_s"] >= 0 and inner["ok"] is True


def test_span_reserved_field_names_are_prefixed(traced):
    """Tuning spans forward arbitrary tunable names via **params; one
    named like a journal stamp ('t') or an emitter-owned key ('name')
    must neither raise a duplicate-kwarg TypeError out of __exit__
    nor clobber the event's own fields."""
    from tpukernels.obs import trace

    with trace.span("tune/x", name="collides", t=7, bm=256):
        pass
    (ev,) = _events(traced, "span")
    assert ev["name"] == "tune/x"          # emitter wins
    assert ev["param_name"] == "collides"  # caller value preserved
    assert ev["param_t"] == 7 and ev["bm"] == 256
    assert isinstance(ev["t"], float)      # journal stamp intact


def test_span_exception_marks_not_ok_and_unwinds(traced):
    from tpukernels.obs import trace

    with pytest.raises(RuntimeError):
        with trace.span("boom"):
            raise RuntimeError("x")
    (ev,) = _events(traced, "span")
    assert ev["ok"] is False
    assert trace.current_path() is None


def test_span_disabled_is_shared_noop(monkeypatch, tmp_path):
    from tpukernels.obs import trace

    journal_path = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    monkeypatch.delenv("TPK_TRACE", raising=False)
    trace.reload()
    assert not trace.enabled()
    s1 = trace.span("a", x=1)
    s2 = trace.span("b")
    # one shared no-op object: no per-call allocation on the clean path
    assert s1 is s2 is trace._NOOP
    with s1:
        assert trace.current_path() is None
    assert not journal_path.exists()  # nothing emitted
    for off in ("0", "off", "none", ""):
        monkeypatch.setenv("TPK_TRACE", off)
        assert trace.reload() is False
    monkeypatch.delenv("TPK_TRACE")
    trace.reload()


# ---------------------------------------------------------------- #
# metrics: counter/gauge/histogram semantics + snapshot routing     #
# ---------------------------------------------------------------- #

def test_metrics_counter_gauge_histogram_semantics():
    from tpukernels.obs import metrics

    metrics.reset()
    try:
        metrics.inc("c")
        metrics.inc("c")
        metrics.inc("c", 5)
        metrics.gauge("g", 1.0)
        metrics.gauge("g", 3.5)  # last write wins
        for v in (2.0, 0.5, 1.0):
            metrics.observe("h", v)
        snap = metrics.snapshot()
        assert snap["counters"]["c"] == 7
        assert snap["gauges"]["g"] == 3.5
        h = snap["histograms"]["h"]
        assert (h["count"], h["sum"], h["min"], h["max"]) == (
            3, 3.5, 0.5, 2.0)
        # log buckets: one shared boundary scheme (bucket_index), str
        # keys so the in-process shape equals the JSON round trip
        assert h["buckets"] == {
            str(metrics.bucket_index(v)): 1 for v in (2.0, 0.5, 1.0)
        }
        # count-weighted percentiles off the buckets, clamped to the
        # EXACT max: p99 of 3 samples is the worst sample, never a
        # bucket bound above it
        assert h["p99"] == 2.0 == h["p95"]
        assert 1.0 <= h["p50"] <= metrics.bucket_upper(
            metrics.bucket_index(1.0))
        # snapshot is a copy, not a view
        snap["counters"]["c"] = 0
        assert metrics.snapshot()["counters"]["c"] == 7
    finally:
        metrics.reset()
    assert metrics.snapshot() == {
        "counters": {}, "gauges": {}, "histograms": {},
    }


def test_metrics_snapshot_routes_to_journal(monkeypatch, tmp_path):
    from tpukernels.obs import metrics

    journal_path = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    metrics.reset()
    try:
        metrics.emit_snapshot(site="empty")  # nothing recorded: no-op
        assert not journal_path.exists()
        metrics.inc("probe.retries")
        metrics.emit_snapshot(site="t")
        (ev,) = _events(journal_path, "metrics")
        assert ev["site"] == "t"
        assert ev["counters"] == {"probe.retries": 1}
    finally:
        metrics.reset()


# ---------------------------------------------------------------- #
# trend: fixtures for regression / ceiling / null handling          #
# ---------------------------------------------------------------- #

def _fixture_root(tmp_path, baseline=None, logs=None, rounds=None):
    root = tmp_path / "repo"
    (root / "docs" / "logs").mkdir(parents=True)
    (root / "BASELINE.json").write_text(json.dumps(baseline or {}))
    for fname, line in (logs or {}).items():
        (root / "docs" / "logs" / fname).write_text(json.dumps(line))
    for n, rec in (rounds or {}).items():
        (root / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))
    return str(root)


def _line(details, **extra):
    return {"metric": "sgemm_gflops_per_chip", "value": None,
            "unit": "GFLOPS", "details": details, **extra}


def test_trend_flags_regression_beyond_eps_band(tmp_path):
    from tpukernels.obs import trend

    root = _fixture_root(
        tmp_path,
        baseline={"measured": {"m": 100.0}},
        logs={
            "bench_2026-08-01_000000.json": _line({"m": 100.0}),
            "bench_2026-08-02_000000.json": _line({"m": 97.0}),
        },
    )
    v = trend.analyze_repo(root)["m"]
    assert v["verdict"] == "regression"  # 3% drop > 1% band
    assert v["latest"] == 97.0 and v["best"] == 100.0
    assert any("REGRESSION" in f for f in v["flags"])


def test_trend_within_band_is_ok(tmp_path):
    from tpukernels.obs import trend

    root = _fixture_root(
        tmp_path,
        baseline={"measured": {"m": 100.0}},
        logs={
            "bench_2026-08-01_000000.json": _line({"m": 100.0}),
            "bench_2026-08-02_000000.json": _line({"m": 99.5}),
        },
    )
    assert trend.analyze_repo(root)["m"]["verdict"] == "ok"


def test_trend_flags_impossible_sgemm_value(tmp_path):
    """The acceptance fixture: the invalidated 72,698-GFLOPS capture
    as a RAW detail value must be flagged against the 61,333 ceiling —
    the class of error BASELINE.md only caught by hand."""
    from tpukernels.obs import trend

    root = _fixture_root(
        tmp_path,
        baseline={
            "measured": {"sgemm_gflops": 60834},
            "ceilings": {"sgemm_gflops": SGEMM_CEILING, "_note": "x"},
        },
        logs={
            "bench_2026-08-01_000000.json": _line(
                {"sgemm_gflops": SGEMM_DRIFT}
            ),
        },
    )
    v = trend.analyze_repo(root)["sgemm_gflops"]
    assert v["verdict"] == "impossible"
    assert any("IMPOSSIBLE" in f and str(SGEMM_DRIFT) in f
               for f in v["flags"])


def test_trend_invalidated_at_source_is_not_impossible(tmp_path):
    """A raw value the bench already invalidated (nulled in details,
    preserved under 'invalidated') was CAUGHT — report it as such,
    don't fail the verdict for an error the machinery handled."""
    from tpukernels.obs import trend

    root = _fixture_root(
        tmp_path,
        baseline={"ceilings": {"sgemm_gflops": SGEMM_CEILING}},
        logs={
            "bench_2026-08-01_000000.json": _line(
                {"sgemm_gflops": None},
                invalidated={"sgemm_gflops": [SGEMM_DRIFT, "drift"]},
            ),
        },
    )
    v = trend.analyze_repo(root)["sgemm_gflops"]
    assert v["verdict"] == "no_data"
    assert any("already invalidated" in f for f in v["flags"])


def test_trend_tunnel_down_nulls_are_no_data(tmp_path):
    """The five committed BENCH_r*.json all-null rounds: a down tunnel
    must read as 'no data', never as a regression."""
    from tpukernels.obs import trend

    null_round = {
        "n": 1,
        "parsed": _line({"error": "TPU backend unreachable"}),
    }
    root = _fixture_root(
        tmp_path,
        baseline={"measured": {"sgemm_gflops": 60834}},
        rounds={1: null_round, 2: null_round},
    )
    v = trend.analyze_repo(root)["sgemm_gflops"]
    assert v["verdict"] == "no_data"
    assert v["valid_points"] == 0


def test_trend_nested_artifact_contributes_and_dedupes(tmp_path):
    """BENCH_r04/r05-style rounds: details.error +
    last_persisted_artifact nesting must contribute the nested line's
    surviving metrics (the stencil2d 131,799) exactly once, even when
    several rounds AND the committed artifact itself all carry it."""
    from tpukernels.obs import trend

    artifact_line = _line({"stencil2d_mcells_s": 131799.49})
    nested = {
        "parsed": _line({
            "error": "TPU backend unreachable (tunnel down)",
            "last_persisted_artifact": {
                "path": "docs/logs/bench_2026-07-31_033318.json",
                "line": artifact_line,
            },
        }),
    }
    root = _fixture_root(
        tmp_path,
        baseline={"measured": {"stencil2d_mcells_s": 129996}},
        logs={"bench_2026-07-31_033318.json": artifact_line},
        rounds={4: nested, 5: nested},
    )
    v = trend.analyze_repo(root)["stencil2d_mcells_s"]
    assert v["valid_points"] == 1  # three copies, one point
    assert v["latest"] == 131799.49
    # trend-clean, but ~20% of the analytic VPU roofline — the
    # non-gating headroom verdict (tests/test_roofline.py proves the
    # gating/transition rules; here just that real-repo-shaped data
    # lands on it instead of reading "ok" forever)
    assert v["verdict"] == "below_roofline"
    assert v["roofline"]["below"] is True


def test_trend_round_tail_fallback(tmp_path):
    """A round file without 'parsed' still contributes via the last
    JSON line of its 'tail' capture."""
    from tpukernels.obs import trend

    root = _fixture_root(
        tmp_path,
        baseline={"measured": {"m": 100.0}},
        rounds={1: {"n": 1, "tail": "# noise\n"
                    + json.dumps(_line({"m": 100.0})) + "\n"}},
    )
    v = trend.analyze_repo(root)["m"]
    assert v["valid_points"] == 1 and v["latest"] == 100.0


def test_trend_bands_mirror_bench_constants():
    """trend.py cannot import bench (jax would leak into a stdlib-only
    module), so its band constants are mirrors — this is the
    single-source-of-truth enforcement."""
    import bench
    from tpukernels.obs import trend

    assert trend.CEILING_EPS == bench._CEILING_EPS
    assert trend.REGRESSION_TOL == bench._REGRESSION_TOL


# ---------------------------------------------------------------- #
# tools: obs_report exit codes, journal_kinds lint                  #
# ---------------------------------------------------------------- #

def _run_tool(script, *args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), *args],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )


def test_obs_report_check_exit_codes(tmp_path):
    bad = _fixture_root(
        tmp_path,
        baseline={"ceilings": {"sgemm_gflops": SGEMM_CEILING}},
        logs={"bench_2026-08-01_000000.json": _line(
            {"sgemm_gflops": SGEMM_DRIFT})},
    )
    r = _run_tool("obs_report.py", "--check", "--root", bad)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "impossible" in r.stdout

    ok = _fixture_root(
        tmp_path / "ok",
        baseline={"measured": {"m": 100.0}},
        logs={"bench_2026-08-01_000000.json": _line({"m": 100.0})},
    )
    r = _run_tool("obs_report.py", "--check", "--root", ok)
    assert r.returncode == 0, r.stdout + r.stderr


def test_journal_kinds_lint_passes_on_this_repo():
    """The tier-1 enforcement of the satellite: every production
    journal.emit kind is documented in docs/OBSERVABILITY.md."""
    r = _run_tool("journal_kinds.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all documented" in r.stdout


def test_journal_kinds_lint_catches_undocumented(tmp_path):
    root = tmp_path / "mini"
    (root / "docs").mkdir(parents=True)
    (root / "bench.py").write_text(
        'journal.emit(\n    "bogus_kind", x=1)\n'
    )
    (root / "docs" / "OBSERVABILITY.md").write_text(
        "| `real_kind` | somewhere | stuff |\n"
    )
    r = _run_tool("journal_kinds.py", "--root", str(root))
    assert r.returncode == 1
    assert "bogus_kind" in r.stdout
    assert "bench.py:1" in r.stdout
    # kinds with digits/uppercase must be linted too, not silently
    # skipped by a too-narrow character class
    (root / "bench.py").write_text('journal.emit("phase2_Start")\n')
    r = _run_tool("journal_kinds.py", "--root", str(root))
    assert r.returncode == 1
    assert "phase2_Start" in r.stdout


def test_env_knobs_lint_passes_on_this_repo():
    """ISSUE 7 satellite, tier-1: every TPK_* knob referenced in
    production code appears in the docs/KNOBS.md catalog table."""
    r = _run_tool("env_knobs.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all documented" in r.stdout


def test_env_knobs_lint_catches_undocumented(tmp_path):
    root = tmp_path / "mini"
    (root / "docs").mkdir(parents=True)
    (root / "tools").mkdir()
    (root / "bench.py").write_text(
        'import os\nv = os.environ.get("TPK_BOGUS_KNOB")\n'
        '"""prose mentioning TPK_NOT_A_REFERENCE must not count"""\n'
    )
    (root / "tools" / "x.sh").write_text(
        'echo "${TPK_SHELL_KNOB:-}"\n'
    )
    (root / "docs" / "KNOBS.md").write_text(
        "| `TPK_DOCUMENTED_ONLY` | - | - | stale row |\n"
    )
    r = _run_tool("env_knobs.py", "--root", str(root))
    assert r.returncode == 1
    assert "TPK_BOGUS_KNOB" in r.stdout
    assert "bench.py:2" in r.stdout
    assert "TPK_SHELL_KNOB" in r.stdout        # shell reads lint too
    assert "TPK_NOT_A_REFERENCE" not in r.stdout  # docstring prose
    assert "TPK_DOCUMENTED_ONLY" in r.stdout   # stale-row WARN
    # documenting both clears it (the WARN alone never fails)
    (root / "docs" / "KNOBS.md").write_text(
        "| `TPK_BOGUS_KNOB` | - | - | x |\n"
        "| `TPK_SHELL_KNOB` | - | - | x |\n"
        "| `TPK_DOCUMENTED_ONLY` | - | - | stale row |\n"
    )
    r = _run_tool("env_knobs.py", "--root", str(root))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WARN documented knob 'TPK_DOCUMENTED_ONLY'" in r.stdout


# ---------------------------------------------------------------- #
# satellites: probe_failed event, health_report breakdown           #
# ---------------------------------------------------------------- #

def test_patient_probe_emits_probe_failed(monkeypatch, tmp_path):
    from tpukernels.obs import metrics
    from tpukernels.resilience import watchdog

    journal_path = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    metrics.reset()
    try:
        assert (
            watchdog.patient_probe(
                lambda a: "retry", attempts=2, retry_wait_s=0,
                label="TPU liveness probe",
            )
            is False
        )
        evs = _events(journal_path, "probe_failed")
        assert [(e["attempt"], e["attempts"]) for e in evs] == [
            (1, 2), (2, 2),
        ]
        assert all(e["label"] == "TPU liveness probe" for e in evs)
        assert all("backoff_s" in e for e in evs)
        assert metrics.snapshot()["counters"]["probe.retries"] == 2
    finally:
        metrics.reset()


def test_health_report_renders_span_breakdown_and_probe_failed(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import health_report
    finally:
        sys.path.pop(0)
    j = tmp_path / "health.jsonl"
    events = [
        {"ts": "t0", "t": 1.0, "pid": 1, "kind": "probe_failed",
         "label": "TPU liveness probe", "attempt": 1, "attempts": 6,
         "backoff_s": 120},
        {"ts": "t1", "t": 2.0, "pid": 1, "kind": "span",
         "name": "measure/sgemm", "wall_s": 2.5, "depth": 1},
        {"ts": "t2", "t": 3.0, "pid": 1, "kind": "span",
         "name": "measure/sgemm", "wall_s": 1.5, "depth": 1},
        {"ts": "t3", "t": 4.0, "pid": 1, "kind": "metrics",
         "site": "bench.main", "counters": {"c": 1}, "gauges": {},
         "histograms": {}},
    ]
    j.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    loaded, bad = health_report.load([str(j)])
    out = health_report.summarize(loaded, bad)
    assert "per-phase wall time" in out
    assert "measure/sgemm" in out and "n=2" in out and "total=4.000s" in out
    assert "TPU liveness probe FAILED (attempt 1/6" in out
    assert "metrics snapshot" in out


# ---------------------------------------------------------------- #
# acceptance: clean bench path byte-identical with TPK_TRACE unset  #
# ---------------------------------------------------------------- #

def test_clean_bench_path_byte_identical_without_trace(tmp_path):
    """Same proof style as the fault layer's
    test_clean_path_output_byte_identical: bench stdout for a fixed
    seed on CPU must not change with the trace layer present —
    whether TPK_TRACE is unset, explicitly off, or even ON (spans go
    to the journal, never stdout). Only the traced run's journal
    carries span events."""
    outs, journals = [], []
    for i, tr in enumerate((None, "0", "1")):
        env = _scrubbed_env(fake_devices=None)
        env["TPK_BENCH_SMOKE"] = "1"
        journal = tmp_path / f"health_{i}.jsonl"
        journals.append(journal)
        env["TPK_HEALTH_JOURNAL"] = str(journal)
        env.pop("TPK_TRACE", None)
        env.pop("TPK_FAULT_PLAN", None)
        if tr is not None:
            env["TPK_TRACE"] = tr
        proc = subprocess.run(
            [sys.executable, "bench.py", "--one", "saxpy_gb_s"],
            env=env, capture_output=True, text=True, timeout=420,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1] == outs[2]
    assert _events(journals[0], "span") == []
    assert _events(journals[1], "span") == []
    traced_names = [e["name"] for e in _events(journals[2], "span")]
    assert "measure/saxpy_gb_s" in traced_names
