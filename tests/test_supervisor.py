"""CPU chaos suite for the checkpointed revalidation supervisor.

docs/RESILIENCE.md §supervisor: the queue logic that used to live in
~300 lines of bash (and was testable only against a live chip) now
runs in tpukernels/resilience/supervisor.py behind tools/revalidate.py
and is proven here without a second of chip time:

- crash-safe resume: SIGKILL the supervisor mid-step (fault-plan
  injected) and a re-run converges to the same green queue without
  redoing green steps;
- step quarantine: a step that wedges twice in one day is demoted to
  non-gating and the third healthy window goes to the NEXT step;
- flap-aware admission: chip steps whose cost exceeds the estimated
  healthy window are deferred (rc 2, retryable) — unless nothing at
  all fits, where the best-density step is forced;
- deterministic backoff schedule, thin-wrapper exit-code
  compatibility (0 green / 3 lock-held), shell<->python stamp
  equivalence, and a byte-identical clean-path stdout proof in the
  PR 1 / PR 3 style.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpukernels.resilience import supervisor  # noqa: E402

CLI = os.path.join(REPO, "tools", "revalidate.py")
LIB = os.path.join(REPO, "tools", "revalidate_lib.sh")


def _specs(*dicts):
    return [supervisor.StepSpec.from_dict(d) for d in dicts]


def _queue_env(tmp_path, plan=None, **extra):
    env = dict(os.environ)
    for var in ("TPK_FAULT_PLAN", "TPK_REVALIDATE_FORCE",
                "TPK_SUPERVISOR_WINDOW_MIN", "TPK_TRACE"):
        env.pop(var, None)
    env.update(
        TPK_SUPERVISOR_CHECKPOINT=str(tmp_path / "checkpoint.jsonl"),
        TPK_REVALIDATE_STAMP_DIR=str(tmp_path / "stamps"),
        TPK_HEALTH_JOURNAL=str(tmp_path / "health.jsonl"),
    )
    if plan is not None:
        env["TPK_FAULT_PLAN"] = json.dumps(plan)
    for k, v in extra.items():
        env[k] = str(v)
    return env


def _run_cli(env, queue_file, args=(), timeout=120):
    return subprocess.run(
        [sys.executable, CLI, "--queue", str(queue_file), *args],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )


def _events(path, kind=None):
    if not os.path.exists(path):
        return []
    recs = [json.loads(line)
            for line in open(path) if line.strip()]
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs


@pytest.fixture
def stub_queue(tmp_path):
    """A 3-step stub queue whose steps append to a runlog — execution
    (vs skip) is observable from the log, like the old stamp tests."""
    runlog = tmp_path / "runlog"
    runlog.write_text("")

    def make(steps):
        qf = tmp_path / "queue.json"
        qf.write_text(json.dumps(steps))
        return qf

    def ran():
        return runlog.read_text().split()

    default = make([
        {"name": "a", "shell": f"echo a >> {runlog}", "cost_min": 1,
         "value": 10, "needs_chip": False},
        {"name": "b", "shell": f"echo b >> {runlog}", "cost_min": 1,
         "value": 5, "needs_chip": False},
        {"name": "c", "shell": f"echo c >> {runlog}", "cost_min": 1,
         "value": 1, "needs_chip": False},
    ])
    return default, make, ran, runlog


# ---------------------------------------------------------------- #
# chaos proof 1: kill -9 mid-step, resume without redoing greens    #
# ---------------------------------------------------------------- #

def test_sigkill_mid_step_resume_skips_green_steps(tmp_path,
                                                   stub_queue):
    """The acceptance-criteria chaos proof: SIGKILL the supervisor at
    the worst instant (step_start durably checkpointed, no outcome
    yet), re-run, and the checkpoint resumes — green steps are NOT
    re-executed, the interrupted step is."""
    qf, _make, ran, _log = stub_queue
    env = _queue_env(tmp_path, plan={"kill_supervisor": {"step": "b"}})
    proc = _run_cli(env, qf)
    assert proc.returncode == -signal.SIGKILL.value or \
        proc.returncode == 128 + signal.SIGKILL.value
    assert ran() == ["a"]                 # died before b executed
    cp = tmp_path / "checkpoint.jsonl"
    starts = _events(cp, "step_start")
    dones = _events(cp, "step_done")
    assert [s["step"] for s in starts] == ["a", "b"]
    assert [d["step"] for d in dones] == ["a"]    # b has NO outcome

    env2 = _queue_env(tmp_path)           # plan dropped: clean re-run
    proc2 = _run_cli(env2, qf)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "queue GREEN" in proc2.stdout
    assert ran() == ["a", "b", "c"]       # a NOT redone; b, c ran
    resumes = _events(cp, "supervisor_resume")
    assert resumes and resumes[-1]["interrupted"] == ["b"]
    assert resumes[-1]["green"] == ["a"]
    # convergence: a third run executes nothing at all
    assert _run_cli(_queue_env(tmp_path), qf).returncode == 0
    assert ran() == ["a", "b", "c"]


# ---------------------------------------------------------------- #
# chaos proof 2: quarantine after repeated wedges                   #
# ---------------------------------------------------------------- #

def test_wedged_twice_is_quarantined_third_window_moves_on(
        tmp_path, stub_queue):
    """A step that wedges twice in a day (watchdog kill + dead
    re-probe, fault-plan driven) is demoted to non-gating with a loud
    step_quarantined event; the third healthy window goes to the next
    step instead of re-eating the flap window."""
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "w", "shell": "sleep 60", "timeout_s": 1,
         "cost_min": 1, "value": 10, "quarantine_after": 2},
        {"name": "x", "shell": f"echo x >> {runlog}", "cost_min": 1,
         "value": 5, "needs_chip": False},
    ])
    plan = {"probe": ["dead"]}            # post-kill re-probe: tunnel gone
    for attempt in (1, 2):
        proc = _run_cli(
            _queue_env(tmp_path, plan=plan,
                       TPK_SUPERVISOR_WINDOW_MIN=30), qf)
        assert proc.returncode == supervisor.RC_WEDGE
        assert ran() == []                # x deferred: window is gone
    cp = tmp_path / "checkpoint.jsonl"
    q = _events(cp, "step_quarantined")
    assert [e["step"] for e in q] == ["w"] and q[0]["wedges"] == 2
    # third window: w skipped loudly, x runs, queue goes green
    proc3 = _run_cli(
        _queue_env(tmp_path, TPK_SUPERVISOR_WINDOW_MIN=30), qf)
    assert proc3.returncode == 0, proc3.stdout + proc3.stderr
    assert ran() == ["x"]
    assert "skipped (quarantined)" in proc3.stdout
    assert "QUARANTINED" in proc3.stderr
    wedge_dones = [e for e in _events(cp, "step_done")
                   if e["outcome"] == "wedged"]
    assert len(wedge_dones) == 2          # quarantine stopped attempt 3


def test_stamp_never_reruns_every_attempt(tmp_path, stub_queue):
    """The bench contract survives the rewrite: a stamp="never" step
    (sgemm canary + union gate) re-runs on every queue attempt, even
    after a same-day green, while its daily-stamped sibling skips."""
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "canary", "shell": f"echo canary >> {runlog}",
         "stamp": "never", "cost_min": 1, "value": 10,
         "needs_chip": False},
        {"name": "daily", "shell": f"echo daily >> {runlog}",
         "cost_min": 1, "value": 5, "needs_chip": False},
    ])
    assert _run_cli(_queue_env(tmp_path), qf).returncode == 0
    assert _run_cli(_queue_env(tmp_path), qf).returncode == 0
    assert ran() == ["canary", "daily", "canary"]


def test_gating_failure_propagates_rc_nongating_continues(
        tmp_path, stub_queue):
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "soft", "shell": "exit 9", "gating": False,
         "cost_min": 1, "value": 10, "needs_chip": False},
        {"name": "hard", "shell": "exit 7", "cost_min": 1,
         "value": 5, "needs_chip": False},
        {"name": "after", "shell": f"echo z >> {runlog}",
         "cost_min": 1, "value": 1, "needs_chip": False},
    ])
    proc = _run_cli(_queue_env(tmp_path), qf)
    assert proc.returncode == 7           # the gating step's own rc
    assert "FAILED" in proc.stderr
    assert ran() == []                    # "after" never reached
    dones = {e["step"]: e for e in
             _events(tmp_path / "checkpoint.jsonl", "step_done")}
    assert dones["soft"]["outcome"] == "failed"   # recorded, not fatal
    assert dones["hard"]["outcome"] == "failed"


# ---------------------------------------------------------------- #
# flap-aware admission                                              #
# ---------------------------------------------------------------- #

def test_window_deferral_and_density_preference(tmp_path, stub_queue):
    """window=5: the big high-density step is deferred (doesn't fit),
    the small one runs, queue reports incomplete (rc 2, retryable)."""
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "big", "shell": f"echo big >> {runlog}",
         "cost_min": 20, "value": 100},       # density 5, doesn't fit
        {"name": "small", "shell": f"echo small >> {runlog}",
         "cost_min": 3, "value": 10},         # density 3.3, fits
    ])
    proc = _run_cli(
        _queue_env(tmp_path, plan={"probe": ["ok"]},
                   TPK_SUPERVISOR_WINDOW_MIN=5), qf)
    assert proc.returncode == supervisor.RC_INCOMPLETE
    assert ran() == ["small"]
    skips = _events(tmp_path / "checkpoint.jsonl", "step_skipped")
    assert [(e["step"], e["reason"]) for e in skips] == [
        ("big", "deferred-window")]


def test_dependent_of_deferred_step_defers_with_it(tmp_path,
                                                   stub_queue):
    """An `after` edge means "ran first": when c_gate-style work is
    deferred past the window, its c_scan_timing-style dependent must
    NOT run (and stamp green) in the same window."""
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "small", "shell": f"echo small >> {runlog}",
         "cost_min": 3, "value": 1},
        {"name": "gate", "shell": f"echo gate >> {runlog}",
         "cost_min": 18, "value": 60},
        {"name": "timing", "shell": f"echo timing >> {runlog}",
         "cost_min": 1, "value": 25, "after": ["gate"]},
    ])
    proc = _run_cli(
        _queue_env(tmp_path, TPK_SUPERVISOR_WINDOW_MIN=12), qf)
    assert proc.returncode == supervisor.RC_INCOMPLETE
    assert ran() == ["small"]             # neither gate NOR timing
    skips = {e["step"]: e["reason"] for e in
             _events(tmp_path / "checkpoint.jsonl", "step_skipped")}
    assert skips == {"gate": "deferred-window",
                     "timing": "dependency-deferred"}


def test_step_children_inherit_the_watcher_lock_fd(tmp_path,
                                                   stub_queue):
    """The old queue's orphan-exclusion invariant survives the
    rewrite: when the supervisor runs under the wrapper's flock on
    fd 9, STEP children inherit the fd (a step orphaned by a dying
    watcher keeps holding the machine-wide chip lock) — but a plain
    supervisor run passes nothing through."""
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "probe_fd", "shell":
         f"readlink /proc/$$/fd/9 >> {runlog} 2>/dev/null"
         f" || echo none >> {runlog}",
         "cost_min": 1, "value": 1, "needs_chip": False},
    ])
    home = tmp_path / "home"
    home.mkdir()
    env = _queue_env(tmp_path, HOME=str(home))
    # under the wrapper: fd 9 is flocked on $HOME/.tpk_tpu_wait.lock
    lock = home / ".tpk_tpu_wait.lock"
    wrapped = subprocess.run(
        ["bash", "-c",
         f'exec 9>"{lock}"; flock -n 9 || exit 99; '
         f'exec {sys.executable} "{CLI}" --queue "{qf}"'],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=REPO)
    assert wrapped.returncode == 0, wrapped.stdout + wrapped.stderr
    assert ran() == [str(lock)]
    # without the wrapper: nothing rides along (fresh state dirs —
    # the first run's same-day green would otherwise skip the step)
    runlog.write_text("")
    fresh = tmp_path / "plain"
    fresh.mkdir()
    env2 = _queue_env(fresh, HOME=str(home))
    plain = subprocess.run(
        [sys.executable, CLI, "--queue", str(qf)],
        env=env2, capture_output=True, text=True, timeout=120,
        cwd=REPO)
    assert plain.returncode == 0, plain.stdout + plain.stderr
    assert ran() == ["none"]


def test_nothing_fits_forces_best_density_step(tmp_path, stub_queue):
    """A window estimate smaller than every step must not livelock
    the queue: the best value-per-chip-minute step is force-admitted
    and the step_start records forced=true."""
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "only", "shell": f"echo only >> {runlog}",
         "cost_min": 20, "value": 10}])
    proc = _run_cli(
        _queue_env(tmp_path, TPK_SUPERVISOR_WINDOW_MIN=2), qf)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert ran() == ["only"]
    starts = _events(tmp_path / "checkpoint.jsonl", "step_start")
    assert starts[0]["forced"] is True


def test_estimate_window_from_health_events():
    """alive-probe -> wedge pairs become observed windows; the median
    is the estimate; no pairs -> the documented default."""
    t0 = time.time()
    mk = lambda kind, dt, **kw: dict(kind=kind, t=t0 + dt, **kw)
    events = [
        mk("probe", 0, outcome="alive"),
        mk("wedge_classification", 4 * 60, verdict="wedged"),
        mk("probe", 10 * 60, outcome="alive"),
        mk("step_done", 22 * 60, outcome="wedged"),   # 12-min window
        mk("probe", 30 * 60, outcome="alive"),
        mk("wedge_classification", 50 * 60, verdict="wedged"),
    ]
    est = supervisor.estimate_window_minutes(events, now=t0 + 51 * 60)
    assert est["basis"] == "observed" and est["windows"] == 3
    assert est["minutes"] == pytest.approx(12.0)      # median of 4/12/20
    empty = supervisor.estimate_window_minutes([], now=t0)
    assert empty == {"minutes": 25.0, "basis": "default", "windows": 0}
    # events older than 24h never count
    stale = supervisor.estimate_window_minutes(
        events, now=t0 + 25 * 3600)
    assert stale["basis"] == "default"


def test_window_history_spans_the_daily_journal_rotation(
        tmp_path, stub_queue, monkeypatch):
    """A run just after midnight must still see yesterday evening's
    flap evidence: when the journal is the dated per-day file, the
    estimator also reads yesterday's sibling."""
    import datetime as dt

    logs = tmp_path / "logs"
    logs.mkdir()
    today = dt.date.today().isoformat()
    yday = (dt.date.today() - dt.timedelta(days=1)).isoformat()
    monkeypatch.setenv("TPK_HEALTH_JOURNAL",
                       str(logs / f"health_{today}.jsonl"))
    monkeypatch.setenv("TPK_SUPERVISOR_CHECKPOINT",
                       str(tmp_path / "cp.jsonl"))
    sup = supervisor.Supervisor([], checkpoint=supervisor.Checkpoint(
        str(tmp_path / "cp.jsonl")))
    paths = sup._history_paths()
    assert [os.path.basename(p) for p in paths] == [
        f"health_{yday}.jsonl", f"health_{today}.jsonl"]
    # an explicitly-named journal (tests, operators) stays single-file
    monkeypatch.setenv("TPK_HEALTH_JOURNAL",
                       str(logs / "custom.jsonl"))
    assert [os.path.basename(p) for p in sup._history_paths()] == [
        "custom.jsonl"]


def test_dependency_edges_hold_under_density(tmp_path, stub_queue):
    """`after` edges beat density: bench-style high-value steps wait
    for their prewarm-style dependency even when it has lower value
    per chip-minute."""
    _qf, make, ran, runlog = stub_queue
    qf = make([
        {"name": "pre", "shell": f"echo pre >> {runlog}",
         "cost_min": 10, "value": 1},         # density 0.1
        {"name": "main", "shell": f"echo main >> {runlog}",
         "cost_min": 1, "value": 100, "after": ["pre"]},
    ])
    proc = _run_cli(
        _queue_env(tmp_path, TPK_SUPERVISOR_WINDOW_MIN=30), qf)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert ran() == ["pre", "main"]


# ---------------------------------------------------------------- #
# backoff schedule                                                  #
# ---------------------------------------------------------------- #

def test_probe_backoff_deterministic_capped_with_jitter():
    seq = [supervisor.probe_delay_s(n, base_s=30, cap_s=600)
           for n in range(12)]
    # deterministic: the schedule replays identically (a resumed
    # watcher reproduces it)
    assert seq == [supervisor.probe_delay_s(n, base_s=30, cap_s=600)
                   for n in range(12)]
    # exponential-ish rise, never above the cap, jitter <= 25%
    for n, d in enumerate(seq):
        raw = min(600, 30 * 2 ** n)
        assert 0.75 * raw <= d <= raw
    assert seq[0] < 31 and max(seq) <= 600
    # attempts decorrelate (jitter actually varies)
    assert len({round(d / min(600, 30 * 2 ** n), 6)
                for n, d in enumerate(seq)}) > 1


# ---------------------------------------------------------------- #
# watch loop                                                        #
# ---------------------------------------------------------------- #

def test_watch_green_first_probe(tmp_path, stub_queue, monkeypatch):
    qf, _make, ran, _log = stub_queue
    env = _queue_env(tmp_path, plan={"probe": ["ok"]})
    proc = _run_cli(env, qf, args=("--wait", "--max-hours", "0.01"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "tunnel ALIVE" in proc.stdout
    assert ran() == ["a", "b", "c"]


def test_watch_surfaces_deterministic_failure(tmp_path, stub_queue):
    """Queue fails loudly while the tunnel still answers: the watch
    must exit with that rc instead of re-running the expensive queue
    against a reproducible failure for hours."""
    _qf, make, _ran, _log = stub_queue
    qf = make([{"name": "boom", "shell": "exit 7", "cost_min": 1,
                "value": 1, "needs_chip": False}])
    env = _queue_env(tmp_path, plan={"probe": ["ok"]})
    proc = _run_cli(env, qf, args=("--wait", "--max-hours", "0.01"))
    assert proc.returncode == 7
    assert "deterministic failure" in proc.stderr


def test_watch_rides_out_dead_tunnel_until_deadline(tmp_path,
                                                    stub_queue):
    qf, _make, ran, _log = stub_queue
    env = _queue_env(tmp_path, plan={"probe": ["dead"]},
                     TPK_SUPERVISOR_PROBE_BASE_S="0.02",
                     TPK_SUPERVISOR_PROBE_CAP_S="0.05")
    proc = _run_cli(env, qf,
                    args=("--wait", "--max-hours", "0.0001"))
    assert proc.returncode == 1           # deadline, like the old loop
    assert "gave up" in proc.stdout
    assert ran() == []
    sched = _events(tmp_path / "health.jsonl", "probe_scheduled")
    assert sched and all(e["delay_s"] <= 0.05 for e in sched)
    assert sched[0]["reason"] == "tunnel-dead"


# ---------------------------------------------------------------- #
# stamps: shell <-> python equivalence, git-awareness               #
# ---------------------------------------------------------------- #

@pytest.fixture
def git_repo(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "bench.py").write_text("# v1\n")
    (repo / "other.txt").write_text("x\n")

    def git(*args):
        return subprocess.run(
            ["git", "-C", str(repo), *args], capture_output=True,
            text=True, timeout=30, check=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t",
                 "GIT_COMMITTER_EMAIL": "t@t"},
        )

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    return repo, git


def test_stamp_shell_python_equivalence(tmp_path, git_repo,
                                        monkeypatch):
    """A stamp written by the bash lib is honored by the python
    supervisor and vice versa; a commit touching the step's inputs
    invalidates it for BOTH drivers."""
    repo, git = git_repo
    stamps = tmp_path / "stamps"
    stamps.mkdir()
    monkeypatch.setenv("TPK_REVALIDATE_STAMP_DIR", str(stamps))
    monkeypatch.delenv("TPK_REVALIDATE_FORCE", raising=False)
    spec = supervisor.StepSpec("s1", "true", inputs=("bench.py",))

    def shell_step_done(extra=""):
        r = subprocess.run(
            ["bash", "-c",
             f'stamp_dir="{stamps}"; step_inputs="bench.py"; '
             f'source "{LIB}"; {extra} step_done s1'],
            capture_output=True, text=True, timeout=30, cwd=str(repo),
        )
        return r.returncode == 0

    # bash writes -> both honor
    subprocess.run(
        ["bash", "-c",
         f'stamp_dir="{stamps}"; source "{LIB}"; stamp s1'],
        check=True, timeout=30, cwd=str(repo))
    assert shell_step_done()
    assert supervisor.stamp_fresh(spec, repo=str(repo))
    # a commit NOT touching the inputs leaves the stamp fresh
    (repo / "other.txt").write_text("y\n")
    git("commit", "-qam", "unrelated")
    assert shell_step_done()
    assert supervisor.stamp_fresh(spec, repo=str(repo))
    # a commit touching bench.py goes stale for BOTH
    (repo / "bench.py").write_text("# v2\n")
    git("commit", "-qam", "touch bench")
    assert not shell_step_done()
    assert not supervisor.stamp_fresh(spec, repo=str(repo))
    # python writes -> bash honors (and FORCE still overrides)
    supervisor.write_stamp("s1", repo=str(repo))
    assert shell_step_done()
    assert supervisor.stamp_fresh(spec, repo=str(repo))
    assert not shell_step_done("TPK_REVALIDATE_FORCE=1;")
    monkeypatch.setenv("TPK_REVALIDATE_FORCE", "1")
    assert not supervisor.stamp_fresh(spec, repo=str(repo))


# ---------------------------------------------------------------- #
# thin wrappers + lock diagnosis                                    #
# ---------------------------------------------------------------- #

def test_thin_wrappers_parse_and_delegate():
    for script in ("tools/tpu_revalidate.sh",
                   "tools/tpu_wait_and_revalidate.sh",
                   "tools/revalidate_lib.sh"):
        r = subprocess.run(["bash", "-n", os.path.join(REPO, script)],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, (script, r.stderr)
    for script, arg in (("tpu_revalidate.sh", "revalidate.py"),
                        ("tpu_wait_and_revalidate.sh",
                         "revalidate.py --wait")):
        with open(os.path.join(REPO, "tools", script)) as f:
            body = f.read()
        assert f"exec python tools/{arg}" in body
        assert "step_done()" not in body  # queue logic lives in python


def test_wrapper_green_exit_code(tmp_path, stub_queue):
    qf, _make, ran, _log = stub_queue
    env = _queue_env(tmp_path, TPK_SUPERVISOR_QUEUE=str(qf))
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "tpu_revalidate.sh")],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert ran() == ["a", "b", "c"]


def test_wrapper_lock_held_exits_3(tmp_path, stub_queue):
    """The watcher wrapper's exit-3 lock contract survives the
    rewrite, and now points at --whos-holding instead of raw pgrep."""
    qf, _make, _ran, _log = stub_queue
    home = tmp_path / "home"
    home.mkdir()
    lock = home / ".tpk_tpu_wait.lock"
    holder = subprocess.Popen(
        ["bash", "-c",
         f'exec 9>>"{lock}"; flock 9; echo 12345 > "{lock}"; '
         'sleep 60'])
    try:
        time.sleep(0.3)                   # let the holder take it
        env = _queue_env(tmp_path, TPK_SUPERVISOR_QUEUE=str(qf),
                         HOME=str(home), TPK_LOCK_WAIT_S="1")
        proc = subprocess.run(
            ["bash", os.path.join(REPO, "tools",
                                  "tpu_wait_and_revalidate.sh")],
            env=env, capture_output=True, text=True, timeout=120,
            cwd=REPO)
        assert proc.returncode == 3, proc.stdout + proc.stderr
        assert "whos-holding" in proc.stdout
        # the LOSING contender must not have truncated the live
        # holder's recorded pid (the 9>> open) — --whos-holding
        # depends on it in exactly this contention case
        assert lock.read_text().strip() == "12345"
    finally:
        holder.kill()
        holder.wait()


def test_whos_holding_diagnosis(tmp_path):
    lock = tmp_path / ".tpk_tpu_wait.lock"
    # no lock file at all
    assert supervisor is not None
    import tools.revalidate as cli
    assert cli.whos_holding(str(lock)) == 0
    # stale: pid recorded but nobody holds the flock
    lock.write_text("99999999\n")
    assert cli.whos_holding(str(lock)) == 0
    # held by a live "watcher" (argv carries the watcher marker)
    holder = subprocess.Popen(
        ["bash", "-c",
         f'exec 9>"{lock}"; echo $$ > "{lock}"; flock 9; '
         'exec sleep 60'])
    try:
        time.sleep(0.3)
        assert cli.whos_holding(str(lock)) == 3
    finally:
        holder.kill()
        holder.wait()
    assert cli.classify_holder(
        "python tools/revalidate.py --wait --max-hours 10"
    ) == "live-watcher"
    assert cli.classify_holder(
        "python bench.py --one sgemm_gflops") == "orphaned-queue"
    assert cli.classify_holder("sleep 60") == "unknown"


# ---------------------------------------------------------------- #
# clean-path proof + queue definitions                              #
# ---------------------------------------------------------------- #

def test_clean_path_stdout_byte_identical(tmp_path, stub_queue):
    """Journaling/checkpointing must not change what the operator
    sees: the same stub queue run with the health journal disabled
    and enabled produces byte-identical stdout (the PR 1 / PR 3
    clean-path proof, supervisor edition)."""
    _qf, make, _ran, _log = stub_queue
    outs = []
    for i, journal_val in enumerate(("0", str(tmp_path / "h.jsonl"))):
        sub = tmp_path / f"run{i}"
        sub.mkdir()
        runlog = sub / "runlog"
        runlog.write_text("")
        qf = sub / "queue.json"
        qf.write_text(json.dumps([
            {"name": "a", "shell": f"echo out-a", "cost_min": 1,
             "value": 10, "needs_chip": False},
            {"name": "b", "shell": f"echo out-b", "cost_min": 1,
             "value": 5, "needs_chip": False},
        ]))
        env = _queue_env(sub, TPK_SUPERVISOR_WINDOW_MIN=10)
        env["TPK_HEALTH_JOURNAL"] = journal_val
        proc = _run_cli(env, qf)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]


def test_reports_render_supervisor_session(tmp_path, stub_queue):
    """tools/health_report.py and tools/obs_report.py must render the
    new kinds: the per-step attempt/quarantine table and the step
    wall-time breakdown (from the nested step/<name> spans)."""
    _qf, make, _ran, runlog = stub_queue
    qf = make([
        {"name": "w", "shell": "sleep 60", "timeout_s": 1,
         "cost_min": 1, "value": 10, "quarantine_after": 1},
        {"name": "x", "shell": f"echo x >> {runlog}", "cost_min": 1,
         "value": 5, "needs_chip": False},
    ])
    env = _queue_env(tmp_path, plan={"probe": ["dead"]},
                     TPK_SUPERVISOR_WINDOW_MIN=30, TPK_TRACE="1")
    assert _run_cli(env, qf).returncode == supervisor.RC_WEDGE
    env2 = _queue_env(tmp_path, TPK_SUPERVISOR_WINDOW_MIN=30)
    assert _run_cli(env2, qf).returncode == 0
    journal = str(tmp_path / "health.jsonl")
    hr = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "health_report.py"), journal],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert hr.returncode == 0, hr.stderr
    assert "supervisor steps (attempts / outcomes / quarantine):" \
        in hr.stdout
    assert "QUARANTINED" in hr.stdout
    assert "timeout on w classified WEDGED" in hr.stdout
    assert "healthy-window estimate" in hr.stdout
    obs = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--journal", journal],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert "supervisor step breakdown" in obs.stdout
    # the traced run's step/w span survived the queue/run nesting
    step_lines = [ln for ln in obs.stdout.splitlines()
                  if ln.startswith("w ")]
    assert step_lines and "QUARANTINED" in step_lines[0]
    assert "-" not in step_lines[0].split()[3]   # span_s populated


def test_queue_file_validation(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"not": "a list"}))
    with pytest.raises(ValueError, match="JSON list"):
        supervisor.load_queue_file(str(bad))
    bad.write_text(json.dumps([
        {"name": "a", "shell": "true", "after": ["ghost"]}]))
    with pytest.raises(ValueError, match="unknown"):
        supervisor.load_queue_file(str(bad))
    bad.write_text(json.dumps([{"name": "a", "shell": "true"},
                               {"name": "a", "shell": "true"}]))
    with pytest.raises(ValueError, match="duplicate"):
        supervisor.load_queue_file(str(bad))
    # a cycle must be a loud config error here, not a run-time rc 2
    # the watch loop would retry until its deadline
    bad.write_text(json.dumps([
        {"name": "a", "shell": "true", "after": ["b"]},
        {"name": "b", "shell": "true", "after": ["a"]},
        {"name": "c", "shell": "true"}]))
    with pytest.raises(ValueError, match="cycle"):
        supervisor.load_queue_file(str(bad))
    with pytest.raises(ValueError, match="stamp"):
        supervisor.StepSpec("x", "true", stamp="hourly")


def test_production_queue_is_wellformed():
    """Every production step body must at least parse (the queue is
    unattended — a syntax error would surface mid-recovery), names
    are unique, dependencies known, and the NEXT.md value ordering is
    encoded: bench has the highest density, sanitizers the lowest."""
    import tools.revalidate as cli

    q = cli.PRODUCTION_QUEUE
    names = [s.name for s in q]
    assert len(set(names)) == len(names)
    known = set(names)
    for s in q:
        assert all(a in known for a in s.after), s.name
        r = subprocess.run(["bash", "-n", "-c", s.shell],
                           capture_output=True, text=True, timeout=30)
        assert r.returncode == 0, (s.name, r.stderr)
    dens = {s.name: s.density for s in q}
    assert dens["bench"] == max(dens.values())
    assert dens["san_ubsan"] == min(dens.values())
    assert {"prewarm_all"} == set(
        next(s for s in q if s.name == "bench").after)
    # the prewarm step re-derives its chip-minute cost from measured
    # compile walls (docs/PERF.md §compile discipline)
    assert next(s for s in q if s.name == "prewarm_all").cost_from == \
        "prewarm"
    # CPU-only steps must say so (they must never wait on a window)
    for name in ("obs_check", "autotune_smoke", "adapt_propose",
                 "rollup_daily", "san_asan", "san_ubsan"):
        assert not next(s for s in q if s.name == name).needs_chip
    # the adaptive-bucket canary spends chip time on a measured
    # verdict: it must wait for the proposal AND a warm manifest
    assert set(next(s for s in q if s.name == "adapt_canary").after) \
        == {"prewarm_all", "adapt_propose"}


def test_production_plan_order_reproduces_next_md(tmp_path,
                                                  monkeypatch):
    """Fresh day, no flap history (optimistic default window): the
    density-under-dependencies schedule must reproduce the NEXT.md
    highest-value-per-chip-minute ordering the bash queue encoded as
    comment order — headline capture first, sanitizers last."""
    import tools.revalidate as cli

    monkeypatch.setenv("TPK_SUPERVISOR_CHECKPOINT",
                       str(tmp_path / "cp.jsonl"))
    monkeypatch.setenv("TPK_REVALIDATE_STAMP_DIR",
                       str(tmp_path / "stamps"))
    sup = supervisor.Supervisor(cli.PRODUCTION_QUEUE,
                                announce=False)
    order = []
    while True:
        spec, forced = sup.plan(25.0, may_force=False)
        if spec is None:
            break
        assert not forced
        order.append(spec.name)
        sup._settled.add(spec.name)       # pretend it went green
        sup._attempted.add(spec.name)
    # serve_probe (value 10 / 2 min) ties obs_check's density and
    # lands between the in-process slo_probe and the CPU-only checks;
    # fleet_probe (value 9 / 3 min = 3.0) slots between c_gate (3.33)
    # and c_scan_timing (2.5)
    assert order[:11] == ["prewarm_all", "bench", "slo_probe",
                          "serve_probe", "obs_check",
                          "roofline_report", "busbw_sweep", "c_gate",
                          "fleet_probe", "c_scan_timing", "profile"]
    assert order[-2:] == ["san_asan", "san_ubsan"]
    assert len(order) == len(cli.PRODUCTION_QUEUE)
    # fleet_fsck (value 2 / 1 min = 2.0) is cheap housekeeping: it
    # slots with the other density-2.0 CPU steps, after the chip work
    assert order.index("fleet_fsck") > order.index("fleet_probe")
    assert order.index("fleet_fsck") < order.index("san_asan")
    # fleet_probe rehearses the full self-healing cycle mid-burst
    # (docs/SERVING.md §self-healing) at the SAME cost/value — the
    # kill -> detect -> respawn -> rejoin phase and its convergence
    # gate are part of the step body, and its rc part of the verdict.
    # Since the guardian, it ALSO kills the router (§guardian): the
    # rc_heal2 leg proves the front door itself comes back.
    fleet_spec = next(s for s in cli.PRODUCTION_QUEUE
                      if s.name == "fleet_probe")
    assert "kill -9" in fleet_spec.shell
    assert "health --wait" in fleet_spec.shell
    assert "rc_heal" in fleet_spec.shell
    assert "rc_heal2" in fleet_spec.shell
    assert "guardian" in fleet_spec.shell
    assert "router_pidfile_path" in fleet_spec.shell
    fsck_spec = next(s for s in cli.PRODUCTION_QUEUE
                     if s.name == "fleet_fsck")
    assert not fsck_spec.gating
    assert "fsck" in fsck_spec.shell
    # the closed loop schedules in order: the CPU-only proposal rides
    # the density-2.0 housekeeping group, the chip canary follows it
    assert order.index("adapt_propose") > order.index("serve_probe")
    assert order.index("adapt_canary") > order.index("adapt_propose")
    assert order.index("adapt_canary") < order.index("knob_sanity")
    # the daily rollup feeds the multi-day miner, so it must land
    # before adapt_propose (docs/OBSERVABILITY.md §daily rollups)
    assert order.index("rollup_daily") < order.index("adapt_propose")
    assert order.index("rollup_daily") < order.index("san_asan")
    rollup_spec = next(s for s in cli.PRODUCTION_QUEUE
                       if s.name == "rollup_daily")
    assert not rollup_spec.gating
    assert rollup_spec.stamp == "daily"
    assert "tpukernels.obs.rollup" in rollup_spec.shell
    # busbw_sweep banks one 2-D mesh point per healthy window when
    # >= 4 devices are probed (ISSUE 20 satellite) without moving in
    # the density schedule — the 2-D leg rides the same step
    busbw_spec = next(s for s in cli.PRODUCTION_QUEUE
                      if s.name == "busbw_sweep")
    assert "--mesh=2x" in busbw_spec.shell
    assert "device_count()" in busbw_spec.shell
