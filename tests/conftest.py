"""Test harness config (SURVEY.md §4 rebuild test plan).

Intent: run on CPU with 8 fake devices so Pallas kernels exercise
interpret mode without TPU hardware. On a plain machine the env vars
below accomplish that. On this dev box, sitecustomize force-registers
the axon TPU backend at interpreter start (overriding JAX_PLATFORMS),
so the kernel tests actually run COMPILED on the real chip — stricter
coverage, same assertions. To force the CPU path here, launch as:
  PALLAS_AXON_POOL_IPS= python -m pytest tests/ -q
Collective tests always get fake CPU devices: test_distributed.py
spawns subprocesses with a scrubbed env.
"""

import os

# Explicit assignment, not setdefault: the dev/CI shell may have
# JAX_PLATFORMS pre-set to a TPU plugin (e.g. axon), and the contract
# here is that the unit suite runs on CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
