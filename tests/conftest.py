"""Test harness config (SURVEY.md §4 rebuild test plan).

Intent: run on CPU with 8 fake devices so Pallas kernels exercise
interpret mode without TPU hardware. On a plain machine the env vars
below accomplish that. On this dev box, sitecustomize force-registers
the axon TPU backend at interpreter start (overriding JAX_PLATFORMS),
so the kernel tests actually run COMPILED on the real chip — stricter
coverage, same assertions. To force the CPU path here, launch as:
  PALLAS_AXON_POOL_IPS= python -m pytest tests/ -q
Collective tests always get fake CPU devices: test_distributed.py
spawns subprocesses with a scrubbed env.
"""

import os
import subprocess
import sys
import time

# A wedged axon tunnel HANGS (never errors) anything that initializes
# the TPU backend — which on this box is the whole suite, since
# sitecustomize force-registers axon whenever PALLAS_AXON_POOL_IPS is
# set. pytest_configure (below) probes it in a killable subprocess
# before any test module imports jax; if the chip doesn't answer, it
# re-execs pytest with the axon env scrubbed so the suite runs
# CPU-interpret instead of hanging until some outer timeout kills it.
# TPK_FORCE_TPU_PROBE_FAIL=1 forces the dead-tunnel path (used by the
# regression test).
_PROBE_GUARD = "TPK_TPU_PROBE_DONE"
_PROBE_SENTINEL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache",
    "tpu_probe_ok",
)
_PROBE_TTL_S = 600  # healthy probes are cached this long (single-use)


def _tpu_hangs() -> bool:
    """True only when the tunnel HANGS (the wedge mode this guard
    exists for). A fast nonzero exit means the backend errors loudly —
    the suite won't hang, so it proceeds on the TPU path and fails
    honestly; the probe's stderr is surfaced as a warning."""
    if os.environ.get("TPK_FORCE_TPU_PROBE_FAIL") == "1":
        return True
    try:
        if (
            os.path.exists(_PROBE_SENTINEL)
            and time.time() - os.path.getmtime(_PROBE_SENTINEL)
            < _PROBE_TTL_S
        ):
            # single-use: consume the sentinel so the NEXT run
            # re-probes — a tunnel that wedges right after a healthy
            # probe then costs at most one hung suite, not every run
            # inside the TTL window
            os.unlink(_PROBE_SENTINEL)
            return False  # recently proven alive; skip the slow probe
    except OSError:
        pass
    try:
        probe = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, jax.numpy as jnp; "
                "(jnp.ones((8,8)) @ jnp.ones((8,8)))"
                ".block_until_ready()",
            ],
            timeout=120,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return True
    if probe.returncode != 0:
        print(
            "conftest: TPU probe exited nonzero (suite stays on the "
            "TPU path):\n" + probe.stderr[-2000:],
            file=sys.stderr,
        )
        return False
    try:
        os.makedirs(os.path.dirname(_PROBE_SENTINEL), exist_ok=True)
        with open(_PROBE_SENTINEL, "w") as f:
            f.write(str(time.time()))
    except OSError:
        pass
    return False


def pytest_configure(config):
    if not os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get(
        _PROBE_GUARD
    ):
        return
    os.environ[_PROBE_GUARD] = "1"  # never probe (or re-exec) twice
    if not _tpu_hangs():
        return
    if os.environ.get("TPK_REQUIRE_TPU") == "1":
        # the caller (tools/tpu_revalidate.sh) is specifically asking
        # "is the compiled path back?" — a silent CPU fallback would
        # answer yes with the chip still dead
        raise RuntimeError(
            "TPU tunnel unreachable and TPK_REQUIRE_TPU=1 - refusing "
            "the CPU fallback"
        )
    # restore the real stdout/stderr fds before replacing the process:
    # pytest's fd-level capture is already active, and the exec'd
    # pytest would otherwise write into this process's capture files
    capman = config.pluginmanager.get_plugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    print(
        "conftest: TPU tunnel unreachable - re-running the suite "
        "on CPU (interpret mode)",
        file=sys.stderr,
    )
    sys.stderr.flush()
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest"] + sys.argv[1:],
        env,
    )

# Route the resilience health journal (docs/RESILIENCE.md) to a
# throwaway dir for the whole suite: bench.py CLI children default it
# to docs/logs/health_<date>.jsonl, and test-spawned runs (which
# inherit os.environ via _scrubbed_env) must not append test noise to
# the repo's real health logs. Tests that assert journal contents
# override this with their own tmp path.
if "TPK_HEALTH_JOURNAL" not in os.environ:
    import tempfile

    # one fixed per-user dir, reused across runs (mkdtemp here would
    # leak a fresh /tmp dir per pytest invocation)
    _journal_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_health_test_{os.getuid()}"
    )
    os.makedirs(_journal_dir, exist_ok=True)
    os.environ["TPK_HEALTH_JOURNAL"] = os.path.join(
        _journal_dir, "health_suite.jsonl"
    )

# Isolate the tuning cache (docs/TUNING.md) for the same reason: a
# smoke autotune run leaves entries under the repo .jax_cache, and the
# suite's kernel calls (plus its subprocess children, via env
# inheritance) must measure the SHIPPED defaults, not whatever the
# last sweep promoted. Tests that assert cache behavior point
# TPK_TUNING_CACHE_DIR at their own tmp path.
if "TPK_TUNING_CACHE_DIR" not in os.environ:
    import tempfile

    _tuning_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_tuning_test_{os.getuid()}"
    )
    os.makedirs(_tuning_dir, exist_ok=True)
    os.environ["TPK_TUNING_CACHE_DIR"] = _tuning_dir
    try:  # entries a previous suite run promoted must not steer this one
        os.unlink(os.path.join(_tuning_dir, "tuning.json"))
    except OSError:
        pass

# Isolate the AOT executable-cache manifest (docs/PERF.md §compile
# discipline) the same way: every capi/bench test dispatch flows
# through aot._record, and tiny test-shape keys must not pollute the
# repo's real .jax_cache/aot.json — the manifest real prewarm/bench
# runs read as warm-cache evidence. Tests that assert manifest
# behavior point TPK_AOT_CACHE_DIR at their own tmp path.
if "TPK_AOT_CACHE_DIR" not in os.environ:
    import tempfile

    _aot_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_aot_test_{os.getuid()}"
    )
    os.makedirs(_aot_dir, exist_ok=True)
    os.environ["TPK_AOT_CACHE_DIR"] = _aot_dir
    try:  # stale manifests from a previous suite run must not read as
        # warm-cache evidence for this one
        os.unlink(os.path.join(_aot_dir, "aot.json"))
    except OSError:
        pass

# Isolate the output-integrity guard's state (docs/RESILIENCE.md
# §output integrity) the same way: chaos tests inject corruption and
# the guard QUARANTINES the offending (kernel, config) persistently —
# test noise must never land in the repo's real envelope manifest or
# quarantine ledger (a suite run must also start unquarantined, or one
# chaos test's leftovers would escalate every later dispatch to
# every-call canary checks). Tests that assert guard state point
# TPK_INTEGRITY_DIR at their own tmp path.
if "TPK_INTEGRITY_DIR" not in os.environ:
    import tempfile

    _integrity_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_integrity_test_{os.getuid()}"
    )
    os.makedirs(_integrity_dir, exist_ok=True)
    os.environ["TPK_INTEGRITY_DIR"] = _integrity_dir
    for _f in ("integrity.json", "integrity_quarantine.json"):
        try:  # a previous suite run's state must not steer this one
            os.unlink(os.path.join(_integrity_dir, _f))
        except OSError:
            pass

# Isolate the latency-SLO verdict artifact (docs/OBSERVABILITY.md
# §latency SLOs) the same way: chaos tests inject slow-dispatch
# faults and persist slo_breach verdicts into slo.json — the artifact
# obs_report --check GATES on. Test noise must never land in (or gate
# through) the repo's real verdict file, and a previous suite run's
# breach must not flip this run's obs_report assertions. Tests that
# assert verdict state point TPK_SLO_DIR at their own tmp path.
if "TPK_SLO_DIR" not in os.environ:
    import tempfile

    _slo_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_slo_test_{os.getuid()}"
    )
    os.makedirs(_slo_dir, exist_ok=True)
    os.environ["TPK_SLO_DIR"] = _slo_dir
    try:  # a previous suite run's verdicts must not steer this one
        os.unlink(os.path.join(_slo_dir, "slo.json"))
    except OSError:
        pass

# Isolate the scaling-artifact directory (docs/OBSERVABILITY.md
# §scaling) the same way: busbw/weak-scaling CLI runs spawned by tests
# write scaling_*.json artifacts, and rehearsal noise must never land
# beside the repo's committed docs/logs evidence — the files
# obs_report trend-checks. Tests that assert artifact contents point
# TPK_SCALING_DIR at their own tmp path.
if "TPK_SCALING_DIR" not in os.environ:
    import tempfile

    _scaling_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_scaling_test_{os.getuid()}"
    )
    os.makedirs(_scaling_dir, exist_ok=True)
    os.environ["TPK_SCALING_DIR"] = _scaling_dir
    import glob as _glob

    for _f in _glob.glob(os.path.join(_scaling_dir, "scaling_*.json")):
        try:  # a previous suite run's artifacts must not accumulate
            os.unlink(_f)
        except OSError:
            pass

# Isolate the traffic-adaptive optimizer's artifacts (docs/SERVING.md
# §adaptive buckets) the same way: adapt.json candidates and promoted
# buckets.json tables written by tests must never land beside — or be
# canaried/promoted from — the repo's real serving config, and a
# previous suite run's promotion must not steer this one. Tests that
# assert candidate state point TPK_ADAPT_DIR at their own tmp path.
# The knobs are scrubbed too: an operator's exported pad target /
# evidence floor would flip every proposal-threshold test — they pin
# their own values.
os.environ.pop("TPK_ADAPT_PAD_TARGET", None)
os.environ.pop("TPK_ADAPT_MIN_REQUESTS", None)
# An exported multi-day mining window would make every single-journal
# proposal test silently fold an operator's rollup series — tests
# that exercise the window pin their own value.
os.environ.pop("TPK_ADAPT_WINDOW_DAYS", None)
# An exported flush interval would start the periodic metrics flusher
# (docs/OBSERVABILITY.md §live telemetry) in EVERY test process and
# its children, interleaving metrics_snapshot noise into journals the
# tests assert on byte-for-byte — tests that exercise the flusher set
# it explicitly on their own subprocesses.
os.environ.pop("TPK_METRICS_FLUSH_S", None)
if "TPK_ADAPT_DIR" not in os.environ:
    import tempfile

    _adapt_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_adapt_test_{os.getuid()}"
    )
    os.makedirs(_adapt_dir, exist_ok=True)
    os.environ["TPK_ADAPT_DIR"] = _adapt_dir
    for _f in ("adapt.json", "buckets.json"):
        try:  # a previous suite run's candidate must not steer this one
            os.unlink(os.path.join(_adapt_dir, _f))
        except OSError:
            pass

# Isolate the daily-rollup series dir (docs/OBSERVABILITY.md §daily
# rollups) the same way: rollup CLI runs spawned by tests write
# rollup_<date>.json artifacts, and test noise must never land beside
# the repo's committed docs/logs series — the files p99_creep and
# multi-day adapt mining read. Stale artifacts from a previous suite
# run are cleared so determinism/series assertions start clean. Tests
# that assert series contents point TPK_ROLLUP_DIR at their own tmp
# path.
if "TPK_ROLLUP_DIR" not in os.environ:
    import tempfile

    _rollup_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_rollup_test_{os.getuid()}"
    )
    os.makedirs(_rollup_dir, exist_ok=True)
    os.environ["TPK_ROLLUP_DIR"] = _rollup_dir
    import glob as _rollup_glob

    for _f in _rollup_glob.glob(
        os.path.join(_rollup_dir, "rollup_*.json")
    ):
        try:  # a previous suite run's artifacts must not accumulate
            os.unlink(_f)
        except OSError:
            pass

# Isolate the serve daemon's runtime dir (docs/SERVING.md) the same
# way: test-spawned daemons bind their Unix socket and flock their
# pidfile here, and they must never collide with — or be stopped as —
# an operator's real daemon under the repo .jax_cache. Stale
# socket/pidfile leftovers from a killed previous run are cleared so
# serve_ctl's liveness checks start from a clean slate. Tests that
# assert daemon state point TPK_SERVE_DIR (or --socket) at their own
# tmp path.
# An exported TPK_SERVE_SOCKET (the capi routing switch) takes
# precedence over TPK_SERVE_DIR everywhere it is read, so it would
# route every capi/default-socket dispatch into the operator's REAL
# daemon regardless of the isolation below — scrub it; tests that
# want the daemon route set it explicitly on their own socket.
os.environ.pop("TPK_SERVE_SOCKET", None)
# The fleet-dir redirect is scrubbed for the same reason: an exported
# TPK_SERVE_FLEET_DIR would make test-spawned fleets (serve_ctl
# start-fleet) collide with — or drain workers of — an operator's
# real fleet. The default then resolves under the isolated
# TPK_SERVE_DIR below; stale fleet state from a killed previous run
# (fleet.json, front socket, router pidfile) is cleared so
# start-fleet's double-start refusal starts from a clean slate.
os.environ.pop("TPK_SERVE_FLEET_DIR", None)
# Wire-path knobs (docs/SERVING.md §wire format / §continuous
# batching) are scrubbed too: an operator's exported lane/threshold/
# window choices would silently change which lane (and which batch
# policy) the serve tests exercise — the tests pin them explicitly.
os.environ.pop("TPK_SERVE_SHM", None)
os.environ.pop("TPK_SERVE_SHM_MIN_BYTES", None)
os.environ.pop("TPK_SERVE_BATCH_ADAPT", None)
# An exported coverage floor would flip the request-tracing verdict
# tests (docs/OBSERVABILITY.md §request tracing) — they pin their own.
os.environ.pop("TPK_TRACE_COVERAGE_MIN", None)
# Self-healing knobs (docs/SERVING.md §self-healing) are scrubbed for
# the same reason: an operator's exported probe interval / backoff /
# crash threshold would silently retime every fleet-health chaos test
# — they pin their own values.
os.environ.pop("TPK_FLEET_PROBE_S", None)
os.environ.pop("TPK_FLEET_RESTART_MAX", None)
os.environ.pop("TPK_FLEET_RESTART_BACKOFF_S", None)
# Guardian + durable-admission knobs (docs/SERVING.md §guardian):
# same story for the router-crash recovery tests.
os.environ.pop("TPK_ROUTER_RESTART_MAX", None)
os.environ.pop("TPK_ROUTER_RESTART_BACKOFF_S", None)
os.environ.pop("TPK_CLIENT_RECONNECT_S", None)
# Deadline + hedging knobs (docs/SERVING.md §deadlines): an exported
# default deadline would stamp budgets on every test request (and an
# exported hedge percentile would retime the tail-race tests) — they
# pin their own.
os.environ.pop("TPK_DEADLINE_DEFAULT_MS", None)
os.environ.pop("TPK_ROUTE_HEDGE_PCTL", None)
os.environ.pop("TPK_ROUTE_HEDGE_MAX_FRAC", None)
if "TPK_SERVE_DIR" not in os.environ:
    import glob as _serve_glob
    import signal as _serve_signal
    import tempfile

    _serve_dir = os.path.join(
        tempfile.gettempdir(), f"tpk_serve_test_{os.getuid()}"
    )
    os.makedirs(_serve_dir, exist_ok=True)
    os.environ["TPK_SERVE_DIR"] = _serve_dir

    # A previous run killed mid-chaos can leak LIVE daemons: the
    # router's health manager respawns workers detached, and a hard
    # test abort leaves them (and the respawning router) running
    # against this reused per-user dir. Liveness is the pidfile
    # flock; a held flock here can only be a leak — reap it before
    # the stale-file cleanup so this suite's fleets start clean.
    def _reap_stale_daemon(pidfile):
        import fcntl

        try:
            f = open(pidfile)
        except OSError:
            return
        with f:
            content = f.readline().strip()
            try:
                fcntl.flock(f.fileno(),
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)
                return  # not held: just a stale file
            except OSError:
                pass
        if content.isdigit():
            try:
                os.kill(int(content), _serve_signal.SIGKILL)
            except OSError:
                pass

    # the guardian FIRST: reaped any later it would respawn the
    # router between the router's reap and its own
    for _pidfile in (
        [os.path.join(_serve_dir, "fleet", "guardian.pid"),
         os.path.join(_serve_dir, "serve.pid"),
         os.path.join(_serve_dir, "fleet", "router.pid")]
        + _serve_glob.glob(os.path.join(_serve_dir, "fleet",
                                        "worker*", "serve.pid"))
    ):
        _reap_stale_daemon(_pidfile)
    for _f in ("serve.sock", "serve.pid",
               os.path.join("fleet", "fleet.json"),
               os.path.join("fleet", "front.sock"),
               os.path.join("fleet", "router.pid"),
               os.path.join("fleet", "guardian.pid"),
               os.path.join("fleet", "router.wal")):
        try:
            os.unlink(os.path.join(_serve_dir, _f))
        except OSError:
            pass

# Persist compiled executables across suite runs (the shared knob —
# tpukernels/_cachedir.py; `import tpukernels` is deliberately
# jax-free, so this respects the env-before-jax-import rule below).
# Irrelevant on the CPU path (sub-second compiles), decisive on the
# compiled-on-chip path: remote compiles cost 20-40 s each and the
# 2026-07-31 on-chip run burned its entire 1800 s budget recompiling —
# with the cache warm, a revalidation re-run spends that budget
# actually executing tests.
from tpukernels._cachedir import ensure_compilation_cache

ensure_compilation_cache()

# Explicit assignment, not setdefault: the dev/CI shell may have
# JAX_PLATFORMS pre-set to a TPU plugin (e.g. axon), and the contract
# here is that the unit suite runs on CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
