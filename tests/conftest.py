"""Test harness config (SURVEY.md §4 rebuild test plan).

Tests run on CPU with 8 fake devices so Pallas kernels exercise
interpret mode and collective lowering is validated without TPU
hardware (the driver separately compile-checks the real-TPU and
multi-chip paths). These env vars must be set before jax is imported
anywhere in the test process.
"""

import os

# Explicit assignment, not setdefault: the dev/CI shell may have
# JAX_PLATFORMS pre-set to a TPU plugin (e.g. axon), and the contract
# here is that the unit suite runs on CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
