"""CPU suite for the autotuning subsystem (docs/TUNING.md).

Covers the ISSUE-2 acceptance surface without a chip:

- cache key/invalidation round-trips (jax-version and git-epoch
  rejections are loud: journal event + stderr note);
- the analytic VMEM feasibility arithmetic that prunes infeasible
  sgemm candidates before chip time;
- resolution precedence env-override > tuned-cache > shipped-default,
  proven end to end: a cache entry written by `tools/autotune.py
  --kernel sgemm --smoke` is demonstrably READ by a subsequent
  `bench.py --one sgemm_gflops` (the `tuning_resolved` journal event
  records per-knob sources), and a set env knob beats it;
- a fault-injected sweep (TPK_FAULT_PLAN, env-narrowed wedge) proving
  one wedged candidate is hard-killed and cannot eat the sweep.
"""

import json
import os
import subprocess
import sys

import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events(journal_path, kind=None):
    recs = [
        json.loads(line)
        for line in journal_path.read_text().splitlines()
        if line.strip()
    ]
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs


def _tuning_env(tmp_path, **extra):
    """Subprocess env: CPU (never the tunnel), isolated tuning cache
    and journal under tmp_path, smoke-collapsed bench repeats."""
    env = _scrubbed_env(fake_devices=None)
    env["TPK_TUNING_CACHE_DIR"] = str(tmp_path / "tcache")
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "health.jsonl")
    env["TPK_BENCH_SMOKE"] = "1"
    env.pop("TPK_FAULT_PLAN", None)
    env.pop("TPK_TUNING_CACHE", None)
    for k, v in extra.items():
        env[k] = str(v)
    return env


@pytest.fixture
def tuning_cache_dir(tmp_path, monkeypatch):
    """In-process isolated cache dir (conftest already redirects, but
    each test wants its own empty one)."""
    d = tmp_path / "tcache"
    monkeypatch.setenv("TPK_TUNING_CACHE_DIR", str(d))
    return d


# ---------------------------------------------------------------- #
# search space: candidates, VMEM pruning arithmetic, env parsing     #
# ---------------------------------------------------------------- #

def test_sgemm_vmem_arithmetic_and_pruning():
    """The analytic model reproduces the documented budget facts: the
    shipped control needs 24 MiB of a 32 MiB budget, bn=2048 with
    bk=2048 is over budget (the combination the old sgemm_tune grid
    called infeasible), and candidates() prunes exactly those."""
    import itertools

    from tpukernels.kernels.sgemm import TUNABLES, _vmem_bytes

    control = {"bm": 256, "bn": 2048, "bk": 1024}
    assert _vmem_bytes(control) == 24 * 1024 * 1024
    assert TUNABLES.feasible(control)
    bad = {"bm": 128, "bn": 2048, "bk": 2048}
    assert _vmem_bytes(bad) > TUNABLES.vmem_budget_bytes
    assert not TUNABLES.feasible(bad)
    # the widened axes (ISSUE 6): depth multiplies the A/B slab pair
    # residency — triple buffering at the control blocks is over
    # budget (~34.6 MiB), so depth=3 only probes with smaller tiles
    assert not TUNABLES.feasible({**control, "depth": 3})
    assert TUNABLES.feasible(
        {"bm": 256, "bn": 1024, "bk": 512, "depth": 3}
    )

    cands, pruned = TUNABLES.candidates()
    full_control = {**control, "depth": 1, "order": "ij"}
    assert cands[0] == full_control  # defaults first = the control row
    # pruned = the model's own count over the declared product (the
    # bn=bk=2048 combos at every depth/order, plus the depth-3 rows
    # whose slab pair blows the budget at the wide tiles)
    expect_pruned = sum(
        not TUNABLES.feasible(dict(zip(
            ("bm", "bn", "bk", "depth", "order"), combo
        )))
        for combo in itertools.product(
            *(t.values for t in TUNABLES.tunables)
        )
    )
    assert pruned == expect_pruned == 26
    assert all(
        not (c["bn"] == 2048 and c["bk"] == 2048) for c in cands
    )
    # the old tools/sgemm_tune.py documented grid survives as the
    # depth=1/order=ij slice
    old_grid = [
        (256, 2048, 1024), (128, 2048, 1024), (512, 2048, 1024),
        (256, 2048, 512), (256, 1024, 1024), (256, 1024, 2048),
        (512, 1024, 1024),
    ]
    as_tuples = {
        (c["bm"], c["bn"], c["bk"]) for c in cands
        if c["depth"] == 1 and c["order"] == "ij"
    }
    assert set(old_grid) <= as_tuples


def test_env_parse_fail_loud(monkeypatch):
    """TPK_* knob contract: garbage raises a ValueError naming the
    var, for int and choice tunables alike."""
    from tpukernels.kernels.sgemm import TUNABLES as SGEMM
    from tpukernels.kernels.histogram import TUNABLES as HIST
    from tpukernels.tuning import resolve

    for bad in ("0", "-8", "abc"):
        monkeypatch.setenv("TPK_SGEMM_BM", bad)
        with pytest.raises(ValueError, match="TPK_SGEMM_BM"):
            resolve(SGEMM)
    monkeypatch.delenv("TPK_SGEMM_BM")
    monkeypatch.setenv("TPK_HIST_IMPL", "gpu")
    with pytest.raises(ValueError, match="TPK_HIST_IMPL"):
        resolve(HIST)


def test_env_for_skips_kernel_computed_defaults():
    """env_for leaves None (kernel-computed) params unset so a sweep
    control row inherits the kernel's own fallback logic."""
    from tpukernels.kernels.histogram import TUNABLES

    assert TUNABLES.env_for(TUNABLES.defaults()) == {"TPK_HIST_ACC": "i8"}
    assert TUNABLES.env_for({"impl": "vpu", "acc": "f32"}) == {
        "TPK_HIST_IMPL": "vpu", "TPK_HIST_ACC": "f32",
    }


# ---------------------------------------------------------------- #
# cache: round-trip, key shape, invalidation                         #
# ---------------------------------------------------------------- #

def test_cache_roundtrip_and_key(tuning_cache_dir):
    from tpukernels.kernels.sgemm import TUNABLES
    from tpukernels.tuning import cache

    params = {"bm": 128, "bn": 1024, "bk": 512}
    key = cache.put(
        params=params, space=TUNABLES, shape=(1024, 1024, 1024),
        dtype="float32", kind="cpu", value=10.0, control=9.0,
    )
    assert key == "sgemm|1024x1024x1024|float32|cpu"
    got = cache.get(TUNABLES, (1024, 1024, 1024), "float32", kind="cpu")
    assert got == params
    # different shape / dtype / device: a miss, never a fuzzy match
    assert cache.get(TUNABLES, (2048, 2048, 2048), "float32", "cpu") is None
    assert cache.get(TUNABLES, (1024, 1024, 1024), "bfloat16", "cpu") is None
    assert cache.get(TUNABLES, (1024, 1024, 1024), "float32", "tpu_v5") is None


def _corrupt_entry(cache, field, value):
    p = cache.path()
    with open(p) as f:
        data = json.load(f)
    entry = next(iter(data["entries"].values()))
    entry[field] = value
    with open(p, "w") as f:
        json.dump(data, f)


def test_cache_invalidation_is_loud(tuning_cache_dir, tmp_path,
                                    monkeypatch, capsys):
    """Stale entries — tuned under another jax version or before the
    last commit touching the kernel sources — are rejected with a
    tuning_rejected journal event, mirroring bench.py's git-epoch
    evidence rules."""
    from tpukernels.kernels.sgemm import TUNABLES
    from tpukernels.tuning import cache

    journal_path = tmp_path / "j.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    shape, dtype = (64, 64, 64), "float32"
    cache.put(params={"bm": 128}, space=TUNABLES, shape=shape,
              dtype=dtype, kind="cpu")

    # tuned under another jax version: rejected
    _corrupt_entry(cache, "jax", "0.0.1")
    cache._REJECT_NOTED.clear()
    assert cache.get(TUNABLES, shape, dtype, "cpu") is None

    # version healed but a commit touching the sources postdates the
    # entry (sha mismatch): git-epoch rejection
    import jax

    _corrupt_entry(cache, "jax", jax.__version__)
    _corrupt_entry(cache, "source_sha", "f" * 40)
    cache._REJECT_NOTED.clear()
    assert cache.get(TUNABLES, shape, dtype, "cpu") is None
    rejects = _events(journal_path, "tuning_rejected")
    assert len(rejects) >= 2
    reasons = " ".join(r["reason"] for r in rejects)
    assert "jax" in reasons and "stale" in reasons
    err = capsys.readouterr().err
    assert "tuning-cache rejected" in err

    # a matching entry (sha healed) round-trips again
    real_sha = cache.source_sha(TUNABLES.sources)
    _corrupt_entry(cache, "source_sha", real_sha)
    assert cache.get(TUNABLES, shape, dtype, "cpu") == {"bm": 128}


def test_smoke_entries_scoped_to_smoke_mode(tuning_cache_dir,
                                            monkeypatch):
    """A smoke-promoted entry (meaningless collapsed-repeat values)
    must be honored only under TPK_BENCH_SMOKE=1 — a normal dispatch
    at the same key keeps shipped defaults."""
    from tpukernels.kernels.sgemm import TUNABLES
    from tpukernels.tuning import cache

    cache.put(params={"bm": 128}, space=TUNABLES, shape=(32, 32, 32),
              dtype="float32", kind="cpu", smoke=True)
    monkeypatch.delenv("TPK_BENCH_SMOKE", raising=False)
    cache._REJECT_NOTED.clear()
    assert cache.get(TUNABLES, (32, 32, 32), "float32", "cpu") is None
    monkeypatch.setenv("TPK_BENCH_SMOKE", "1")
    assert cache.get(TUNABLES, (32, 32, 32), "float32", "cpu") == {
        "bm": 128
    }


def test_quick_probes_first_tunable():
    """--quick = control + single-axis probes of the first tunable —
    the old sgemm_tune QUICK rows (control, bm=128, bm=512), via the
    same quick_candidates() the runner calls."""
    from tpukernels.kernels.sgemm import TUNABLES

    quick = TUNABLES.quick_candidates()
    assert [(c["bm"], c["bn"], c["bk"]) for c in quick] == [
        (256, 2048, 1024), (128, 2048, 1024), (512, 2048, 1024),
    ]


def test_empty_sweep_reports_not_crashes(tmp_path):
    """--max-candidates 0 (or a fully pruned space) must exit 2 with
    the documented message, not an IndexError traceback."""
    env = _tuning_env(tmp_path)
    proc = subprocess.run(
        [sys.executable, "tools/autotune.py", "--kernel", "vector_add",
         "--smoke", "--max-candidates", "0"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "no candidate produced a number" in proc.stdout


def test_cache_disable_knob(tuning_cache_dir, monkeypatch):
    from tpukernels.kernels.sgemm import TUNABLES
    from tpukernels.tuning import cache

    cache.put(params={"bm": 128}, space=TUNABLES, shape=(8, 8, 8),
              dtype="float32", kind="cpu")
    assert cache.get(TUNABLES, (8, 8, 8), "float32", "cpu") is not None
    monkeypatch.setenv("TPK_TUNING_CACHE", "0")
    assert cache.get(TUNABLES, (8, 8, 8), "float32", "cpu") is None


# ---------------------------------------------------------------- #
# precedence: env > cache > default                                  #
# ---------------------------------------------------------------- #

def test_resolve_precedence(tuning_cache_dir, monkeypatch):
    from tpukernels.kernels.sgemm import TUNABLES
    from tpukernels.tuning import cache, resolve
    from tpukernels.tuning import space as tspace

    shape, dtype = (512, 512, 512), "float32"
    monkeypatch.delenv("TPK_SGEMM_BM", raising=False)
    monkeypatch.delenv("TPK_SGEMM_BN", raising=False)
    monkeypatch.delenv("TPK_SGEMM_BK", raising=False)

    # 1. nothing set, empty cache: shipped defaults
    assert resolve(TUNABLES, shape, dtype) == TUNABLES.defaults()

    # 2. cache entry beats defaults (device kind defaults to the
    # running backend — cpu here)
    cache.put(params={"bm": 128, "bn": 1024, "bk": 512}, space=TUNABLES,
              shape=shape, dtype=dtype, kind=cache.device_kind())
    tspace._JOURNALED.clear()
    # knobs the entry lacks (the widened depth/order axes) fall back
    # to shipped defaults, per tunable
    assert resolve(TUNABLES, shape, dtype) == {
        "bm": 128, "bn": 1024, "bk": 512, "depth": 1, "order": "ij",
    }

    # 3. a set env knob beats the cache for ITS tunable only
    monkeypatch.setenv("TPK_SGEMM_BM", "512")
    assert resolve(TUNABLES, shape, dtype) == {
        "bm": 512, "bn": 1024, "bk": 512, "depth": 1, "order": "ij",
    }

    # registry exposes the same path
    from tpukernels import registry

    assert registry.resolve_params("sgemm", shape, dtype)["bm"] == 512
    monkeypatch.delenv("TPK_SGEMM_BM")
    assert registry.resolve_params("sgemm", shape, dtype)["bm"] == 128


def test_registry_tunables_surface():
    from tpukernels import registry

    assert set(registry.tunable_kernels()) == {
        "sgemm", "vector_add", "scan", "histogram", "scan_histogram",
        "nbody", "stencil2d", "stencil3d",
    }
    assert registry.tunables("sgemm").metric == "sgemm_gflops"
    with pytest.raises(KeyError, match="TUNABLES"):
        registry.tunables("scan_exclusive")


# ---------------------------------------------------------------- #
# end to end: autotune --smoke writes, bench --one reads             #
# ---------------------------------------------------------------- #

def test_autotune_smoke_writes_cache_and_bench_reads_it(tmp_path):
    """The ISSUE-2 acceptance flow: `tools/autotune.py --kernel sgemm
    --smoke` completes on CPU, writes a cache entry; a subsequent
    `bench.py --one sgemm_gflops` resolution demonstrably reads it
    (per-knob sources in the tuning_resolved journal event), and a set
    env knob beats the cache for its tunable only."""
    env = _tuning_env(tmp_path)
    proc = subprocess.run(
        [sys.executable, "tools/autotune.py", "--kernel", "sgemm",
         "--smoke", "--max-candidates", "2"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "promoted ->" in proc.stdout

    cache_file = tmp_path / "tcache" / "tuning.json"
    data = json.loads(cache_file.read_text())
    key = "sgemm|1024x1024x1024|float32|cpu"
    assert key in data["entries"]
    entry = data["entries"][key]
    assert entry["smoke"] is True
    assert set(entry["params"]) == {"bm", "bn", "bk", "depth", "order"}

    journal = tmp_path / "health.jsonl"
    cand = _events(journal, "tuning_candidate")
    assert len(cand) == 2 and all(c["status"] == "ok" for c in cand)
    promoted = _events(journal, "tuning_promoted")
    assert len(promoted) == 1 and promoted[0]["smoke"] is True

    # the read side: bench --one under the same cache dir
    proc = subprocess.run(
        [sys.executable, "bench.py", "--one", "sgemm_gflops"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout.strip().splitlines()[-1])["value"] > 0
    resolved = _events(journal, "tuning_resolved")
    assert resolved, "bench --one did not consult the tuning cache"
    last = resolved[-1]
    assert last["kernel"] == "sgemm"
    assert last["sources"] == {
        "bm": "cache", "bn": "cache", "bk": "cache",
        "depth": "cache", "order": "cache",
    }
    assert last["params"] == entry["params"]

    # env beats cache, per tunable
    env2 = dict(env, TPK_SGEMM_BM="128")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--one", "sgemm_gflops"],
        env=env2, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    last = _events(journal, "tuning_resolved")[-1]
    assert last["sources"]["bm"] == "env" and last["params"]["bm"] == 128
    assert last["sources"]["bn"] == "cache"


# ---------------------------------------------------------------- #
# chaos: one wedged candidate cannot eat the sweep                   #
# ---------------------------------------------------------------- #

def test_wedged_candidate_cannot_eat_sweep(tmp_path):
    """An env-narrowed TPK_FAULT_PLAN wedges exactly the rows=256
    vector_add candidate (C-level-style hang, immune to SIGALRM); the
    runner's watchdog hard-kills it after TPK_TUNE_TIMEOUT_S and the
    sweep continues to a promotion decision — the old tuner's 'one bad
    candidate cannot eat the window' contract, now fault-proven."""
    plan = {
        "wedge_metric": {
            "metric": "saxpy_gb_s",
            "phase": "operand",
            "env": {"TPK_SAXPY_ROWS": "256"},
        }
    }
    env = _tuning_env(
        tmp_path,
        TPK_FAULT_PLAN=json.dumps(plan),
        TPK_TUNE_TIMEOUT_S="20",
    )
    proc = subprocess.run(
        [sys.executable, "tools/autotune.py", "--kernel", "vector_add",
         "--smoke", "--max-candidates", "3"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr

    journal = tmp_path / "health.jsonl"
    cand = _events(journal, "tuning_candidate")
    by_rows = {c["params"]["rows"]: c["status"] for c in cand}
    # candidate order is defaults-first: 512 (control), 256, 1024
    assert by_rows[256] == "timeout"  # wedged -> hard-killed
    assert by_rows[512] == "ok" and by_rows[1024] == "ok"
    fires = _events(journal, "watchdog_fire")
    assert any(f["mechanism"] == "subprocess-kill" for f in fires)
    ends = _events(journal, "tuning_sweep_end")
    assert ends and ends[-1]["measured"] == 2 and ends[-1]["failed"] == 1


def test_fault_env_match_unit(monkeypatch):
    """phase_fault's env narrowing: a spec with an env clause fires
    only in processes whose environment matches."""
    from tpukernels.resilience import faults

    plan = {"fail_metric": {"phase": "execute",
                            "env": {"TPK_X_TEST": "yes"}}}
    monkeypatch.setenv("TPK_FAULT_PLAN", json.dumps(plan))
    faults.reload_plan()
    try:
        faults.phase_fault("execute")  # env absent: must not fire
        monkeypatch.setenv("TPK_X_TEST", "yes")
        with pytest.raises(RuntimeError, match="injected fault"):
            faults.phase_fault("execute")
    finally:
        monkeypatch.delenv("TPK_FAULT_PLAN")
        faults.reload_plan()
