"""Race-surface determinism tests (SURVEY.md §5 "race detection").

The reference's racy kernels are the OpenMP histogram (privatized bins
vs atomics) and block scans; on TPU, XLA compiles deterministic SPMD,
and the remaining race surface is Pallas revisited-output accumulation
(histogram) and sequential-grid carries (scan). These tests pin the
contract: bit-identical results across repeated runs and across block
boundaries.
"""

import jax.numpy as jnp
import numpy as np

from tpukernels.kernels.histogram import histogram
from tpukernels.kernels.scan import inclusive_scan
from tpukernels.kernels.nbody import nbody_step


def test_histogram_deterministic(rng):
    x = jnp.asarray(rng.integers(0, 128, 300000), dtype=jnp.int32)
    a = np.asarray(histogram(x, 128))
    b = np.asarray(histogram(x, 128))
    np.testing.assert_array_equal(a, b)


def test_scan_deterministic(rng):
    x = jnp.asarray(rng.standard_normal(200000), dtype=jnp.float32)
    a = np.asarray(inclusive_scan(x))
    b = np.asarray(inclusive_scan(x))
    np.testing.assert_array_equal(a, b)


def test_scan_carry_across_block_boundary(rng):
    # block is 256 rows x 128 lanes = 32768 elements; values that span
    # exactly one boundary exercise the SMEM carry hand-off
    n = 32768 + 17
    x = jnp.ones(n, dtype=jnp.int32)
    out = np.asarray(inclusive_scan(x))
    np.testing.assert_array_equal(out, np.arange(1, n + 1))


def test_nbody_deterministic(rng):
    n = 512
    args = tuple(
        jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(6)
    ) + (jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),)
    a = nbody_step(*args, steps=2)
    b = nbody_step(*args, steps=2)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
