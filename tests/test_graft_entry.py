"""Driver-faithful tests of __graft_entry__ (VERDICT r1 items 1-2).

Round 1's only failing driver artifact was MULTICHIP_r01.json: the
driver invoked dryrun_multichip(8) under this box's default env, where
sitecustomize force-registers the axon TPU backend and a wedged tunnel
hangs backend init forever (rc=124). These tests invoke the entry
point exactly the way the driver does — same function, default-like
env with the wedge hazard present — so that regression can never ship
silently again.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENTRY = os.path.join(REPO, "__graft_entry__.py")


def _driver_like_env(**overrides) -> dict:
    """The env the driver hands dryrun_multichip: axon pool var SET
    (203.0.113.1 is TEST-NET — anything that actually dials it hangs
    or errors, simulating the wedged tunnel), no JAX_PLATFORMS, no
    fake-device flags. If the entry point fails to scrub, the
    subprocess inherits the hazard and the run times out/fails."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "203.0.113.1"
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env.pop("TPK_TPU_PROBE_DONE", None)
    env.update(overrides)
    return env


def test_dryrun_multichip_survives_wedged_axon_env():
    """The driver's exact call, under the exact env that broke round 1.
    Must finish well under the driver's budget and print per-program
    progress (a stalled compile must be distinguishable from a hang)."""
    proc = subprocess.run(
        [sys.executable, ENTRY, "dryrun", "8"],
        env=_driver_like_env(),
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dryrun_multichip(8): OK" in proc.stdout
    # progress lines: one per program, so the driver sees liveness
    assert proc.stdout.count("[dryrun +") >= 8


@pytest.mark.parametrize("n", [16, 64])
def test_dryrun_multichip_wide_mesh(n):
    """The contract sweeps 8→64 chips (SURVEY.md §3(d)); the ring
    perms, two-level scan offsets and halo wraps must hold on meshes
    wider than the 8 every other test uses — one cheap smoke per
    program via the same dryrun the driver runs. n=64 is the
    envelope's far edge (~100 s on CPU fake devices, mostly XLA
    compiles of 64-way collectives)."""
    # inner bound < outer bound: TPK_DRYRUN_TIMEOUT must fire first so
    # a slow run dies attributably (and reaps its dryrun-inner child)
    # instead of subprocess.run orphaning the grandchild. 600 s, not
    # the ~100 s idle-box typical: this box runs multi-tenant (load
    # avg >25 observed 2026-07-31) and the 64-way collective compiles
    # scale with contention — a 360 s bound flaked under that load.
    # The bound exists for stall ATTRIBUTION, not as a perf gate.
    proc = subprocess.run(
        [sys.executable, ENTRY, "dryrun", str(n)],
        env=_driver_like_env(TPK_DRYRUN_TIMEOUT="600"),
        capture_output=True,
        text=True,
        timeout=660,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"dryrun_multichip({n}): OK" in proc.stdout
    assert proc.stdout.count("[dryrun +") >= 8


def test_dryrun_multichip_timeout_names_last_progress():
    """A genuinely stuck inner run must not hang the driver:
    TPK_DRYRUN_TIMEOUT bounds it, and the error names the last
    program that printed progress so the stall is attributable
    (ADVICE r2)."""
    body = (
        "import __graft_entry__ as g\n"
        "try:\n"
        "    g.dryrun_multichip(8)\n"
        "except RuntimeError as e:\n"
        "    print('GOT:', e)\n"
        "else:\n"
        "    raise SystemExit('expected a timeout RuntimeError')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=_driver_like_env(PYTHONPATH=REPO, TPK_DRYRUN_TIMEOUT="1"),
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "timed out after 1s" in proc.stdout
    assert "last progress:" in proc.stdout


def test_dryrun_multichip_overrides_preexisting_device_count():
    """A caller env that already forces a DIFFERENT fake-device count
    must not leak through: dryrun_multichip(4) needs exactly its own
    count to win."""
    body = (
        "import __graft_entry__ as g; g.dryrun_multichip(4); "
        "print('CALLER-OK')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=_driver_like_env(
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            PYTHONPATH=REPO,
        ),
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dryrun_multichip(4): OK" in proc.stdout
    assert "CALLER-OK" in proc.stdout


def test_dryrun_multichip_after_caller_imported_jax():
    """Calling dryrun_multichip from a process that already initialized
    jax on a 1-device CPU backend must still see n devices — the
    subprocess isolation is the mechanism."""
    body = (
        "import jax; assert len(jax.devices()) == 1, jax.devices(); "
        "import __graft_entry__ as g; g.dryrun_multichip(8); "
        "print('CALLER-OK')"
    )
    env = _driver_like_env(PYTHONPATH=REPO)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # plain 1-device CPU caller
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dryrun_multichip(8): OK" in proc.stdout
    assert "CALLER-OK" in proc.stdout
