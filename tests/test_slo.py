"""CPU suite for the latency-SLO layer (docs/OBSERVABILITY.md
§latency SLOs; ISSUE 8).

Covers the tentpole contracts without a TPU: deterministic arrivals
(same ``TPK_LOADGEN_SEED`` => byte-identical request schedule and
identical histogram buckets across two runs), the log-bucket
percentile arithmetic, SLO verdict rules (ok / slo_breach / no_data
with the min-requests floor), the persisted ``slo.json`` artifact's
loud staleness rejection, the ``obs_report`` rendering + ``--check``
gating, and the headline claim: an injected ``slow_dispatch`` fault
surfaces as a p99 breach while the p50 — the slope-style aggregate —
stays clean, CPU-proven on the real ``registry.dispatch`` path.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOADGEN = os.path.join(REPO, "tools", "loadgen.py")


def _load_loadgen():
    spec = importlib.util.spec_from_file_location("_loadgen", LOADGEN)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run(args, env_extra=None, timeout=120):
    env = _scrubbed_env(None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, LOADGEN, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env,
    )


def _entries(slo_dir):
    with open(os.path.join(slo_dir, "slo.json")) as f:
        return json.load(f)["entries"]


# ---------------------------------------------------------------- #
# deterministic arrivals                                            #
# ---------------------------------------------------------------- #

def test_schedule_byte_identical_per_seed(tmp_path):
    """Same TPK_LOADGEN_SEED => byte-identical request schedule
    (stdout of --print-schedule, literally); a different seed
    differs. No jax, no dispatch."""
    args = ["--mix", "all", "--arrivals", "bursty", "--rate", "40",
            "--requests", "64", "--print-schedule"]
    a = _run(args, {"TPK_LOADGEN_SEED": "7"})
    b = _run(args, {"TPK_LOADGEN_SEED": "7"})
    c = _run(args, {"TPK_LOADGEN_SEED": "8"})
    assert a.returncode == b.returncode == c.returncode == 0, (
        a.stderr, b.stderr, c.stderr)
    assert a.stdout == b.stdout
    assert a.stdout != c.stdout
    assert len(a.stdout.splitlines()) == 64


def test_simulated_buckets_identical_across_runs(tmp_path):
    """Two --simulate runs with one seed land IDENTICAL histogram
    buckets and percentiles in slo.json (virtual clock: the full
    schedule -> histogram -> verdict pipeline is deterministic)."""
    rows = {}
    for tag in ("a", "b"):
        d = tmp_path / tag
        d.mkdir()
        r = _run(
            ["--mix", "scan=1,sgemm=2", "--arrivals", "diurnal",
             "--rate", "80", "--requests", "150", "--simulate", "4"],
            {"TPK_LOADGEN_SEED": "11", "TPK_SLO_DIR": str(d),
             "TPK_HEALTH_JOURNAL": str(d / "health.jsonl")},
        )
        assert r.returncode == 0, r.stderr
        rows[tag] = {
            k: {f: e[f] for f in ("buckets", "count", "p50_s",
                                  "p95_s", "p99_s", "max_s",
                                  "verdict", "simulated")}
            for k, e in _entries(str(d)).items()
        }
    assert rows["a"] == rows["b"]
    # simulated runs live in their own |sim keyspace — they can never
    # overwrite (and thereby un-gate) a real measurement's verdict
    assert set(rows["a"]) == {"scan|probe|cpu|sim",
                              "sgemm|probe|cpu|sim"}
    for e in rows["a"].values():
        assert e["simulated"] is True


def test_arrival_processes_and_mix():
    lg = _load_loadgen()
    mix = {"scan": 3.0, "sgemm": 1.0}
    for arrivals in lg.ARRIVALS:
        sched = lg.build_schedule(5, arrivals, 50.0, 200, None, mix)
        assert len(sched) == 200
        ts = [t for t, _k in sched]
        assert ts == sorted(ts) and ts[0] > 0
        kinds = {k for _t, k in sched}
        assert kinds == set(mix)
        # the 3:1 weight must show (binomial slack is generous)
        n_scan = sum(1 for _t, k in sched if k == "scan")
        assert n_scan > 100
    # duration bounds an unbounded request count
    sched = lg.build_schedule(5, "poisson", 50.0, 0, 2.0, mix)
    assert sched and all(t <= 2.0 for t, _k in sched)
    with pytest.raises(ValueError, match="duration"):
        lg.build_schedule(5, "poisson", 50.0, 0, None, mix)
    with pytest.raises(ValueError, match="unknown arrival"):
        lg.build_schedule(5, "uniform", 50.0, 10, None, mix)


# ---------------------------------------------------------------- #
# log-bucket percentiles                                            #
# ---------------------------------------------------------------- #

def test_percentiles_count_weighted_and_clamped():
    from tpukernels.obs import metrics

    metrics.reset()
    try:
        # 95 fast samples + five 2 s outliers: p50/p95 (ranks 50/95)
        # read the fast bucket's upper bound, p99 (rank 99) lands in
        # the outlier bucket but clamps to the EXACT max
        for _ in range(95):
            metrics.observe("lat", 0.001)
        for _ in range(5):
            metrics.observe("lat", 2.0)
        h = metrics.snapshot()["histograms"]["lat"]
        assert h["count"] == 100 and h["max"] == 2.0
        fast_upper = metrics.bucket_upper(metrics.bucket_index(0.001))
        assert h["p50"] == h["p95"] == round(fast_upper, 6)
        assert h["p50"] < 0.0015
        assert h["p99"] == 2.0
        # non-positive samples collapse into the sentinel bucket and
        # report 0.0, never a math domain error
        metrics.observe("z", 0.0)
        metrics.observe("z", -1.0)
        hz = metrics.snapshot()["histograms"]["z"]
        assert hz["p99"] == 0.0
        assert list(hz["buckets"]) == [str(metrics.bucket_index(0.0))]
    finally:
        metrics.reset()


# ---------------------------------------------------------------- #
# verdict rules + artifact staleness                                #
# ---------------------------------------------------------------- #

def _hists_for(kernel, values):
    from tpukernels.obs import metrics

    metrics.reset()
    for v in values:
        metrics.observe(f"slo.latency_s.{kernel}", v)
    hists = metrics.snapshot()["histograms"]
    metrics.reset()
    from tpukernels.obs import slo

    return slo.histograms_by_kernel(hists)


def test_judge_ok_breach_and_min_requests(monkeypatch, tmp_path):
    from tpukernels.obs import slo

    journal_path = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    target, _basis = slo.resolve_target_s("scan", "cpu", "probe")
    ok = slo.judge(_hists_for("scan", [target / 100] * 50),
                   "cpu", "probe")
    assert ok["scan"]["verdict"] == "ok"
    # p99 over target (every sample breaches) => slo_breach + journal
    bad = slo.judge(_hists_for("scan", [target * 4] * 50),
                    "cpu", "probe")
    assert bad["scan"]["verdict"] == "slo_breach"
    ev = [json.loads(line) for line in
          open(journal_path).read().splitlines()]
    (breach,) = [e for e in ev if e["kind"] == "slo_breach"]
    assert breach["kernel"] == "scan" and not breach["simulated"]
    # a thin tail is no tail: below the min-requests floor => no_data
    # even when every sample breaches
    thin = slo.judge(_hists_for("scan", [target * 4] * 5),
                     "cpu", "probe")
    assert thin["scan"]["verdict"] == "no_data"
    assert "min" in thin["scan"]["why"]
    monkeypatch.setenv("TPK_SLO_MIN_REQUESTS", "5")
    thick = slo.judge(_hists_for("scan", [target * 4] * 5),
                      "cpu", "probe")
    assert thick["scan"]["verdict"] == "slo_breach"


def test_target_resolution_and_knobs(monkeypatch):
    from tpukernels.obs import slo

    exact, basis = slo.resolve_target_s("scan", "cpu", "probe")
    assert basis == "exact"
    # unknown TPU kind borrows the v5-lite row, flagged
    t, basis = slo.resolve_target_s("scan", "tpu_v7", "record")
    assert basis == "assumed-tpu_v5_lite" and t > 0
    # unknown non-TPU kind falls back to the cpu row
    t, basis = slo.resolve_target_s("scan", "gpu_h100", "probe")
    assert basis == "cpu-fallback" and t == exact
    monkeypatch.setenv("TPK_SLO_SCALE", "2.0")
    t2, _ = slo.resolve_target_s("scan", "cpu", "probe")
    assert t2 == pytest.approx(exact * 2)
    monkeypatch.setenv("TPK_SLO_SCALE", "-1")
    with pytest.raises(ValueError, match="TPK_SLO_SCALE"):
        slo.resolve_target_s("scan", "cpu", "probe")
    monkeypatch.delenv("TPK_SLO_SCALE")
    monkeypatch.setenv("TPK_SLO_MIN_REQUESTS", "zero")
    with pytest.raises(ValueError, match="TPK_SLO_MIN_REQUESTS"):
        slo.min_requests()


def test_stale_slo_entries_rejected_loudly(monkeypatch, tmp_path):
    """The tuning/aot contract on slo.json: a non-simulated verdict
    recorded under another jax version is dismissed at read with an
    slo_rejected event — it can neither gate nor clear a queue."""
    from tpukernels.obs import slo

    journal_path = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_SLO_DIR", str(tmp_path))
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(journal_path))
    slo.reset()
    row = {
        "verdict": "slo_breach", "count": 50, "p50_s": 1.0,
        "p95_s": 1.0, "p99_s": 1.0, "max_s": 1.0, "buckets": {},
        "target_p99_s": 0.1, "basis": "exact", "device_kind": "cpu",
        "shape_class": "probe", "simulated": False,
    }
    slo.record({"scan": dict(row)}, jax_version="0.0.0-stale")
    assert slo.load_entries() == {}
    assert slo.breaches() == {}
    ev = [json.loads(line) for line in
          open(journal_path).read().splitlines()]
    (rej,) = [e for e in ev if e["kind"] == "slo_rejected"]
    assert "0.0.0-stale" in rej["reason"]
    # a SIMULATED entry skips the jax check (it never ran jax) but
    # still never gates
    sim = dict(row, simulated=True)
    slo.record({"scan": sim}, jax_version=None)
    entries = slo.load_entries()
    assert list(entries) == ["scan|probe|cpu|sim"]
    assert slo.breaches() == {}
    # and it cannot clear a REAL breach: a current-jax real breach
    # plus a later simulated run of the same (kernel, class, kind)
    # coexist under distinct keys — the real one keeps gating
    import jax

    slo.record({"scan": dict(row)}, jax_version=jax.__version__)
    slo.record({"scan": dict(sim)}, jax_version=None)
    assert set(slo.breaches()) == {"scan|probe|cpu"}
    slo.reset()


# ---------------------------------------------------------------- #
# the headline: slow-dispatch fault => p99 breach, slope clean      #
# ---------------------------------------------------------------- #

def test_slow_dispatch_fault_breaches_p99_p50_clean(tmp_path):
    """An injected latency-tail fault (1 s on every 20th dispatch)
    breaches p99 while p50 — the slope-style aggregate — stays two
    orders of magnitude under target; obs_report --check flips to
    rc 1 via slo_breach. An unfaulted run of the same shape stays
    rc 0. All on the real registry.dispatch path, CPU."""
    from tpukernels.obs import slo

    fault_dir = tmp_path / "faulted"
    clean_dir = tmp_path / "clean"
    fault_dir.mkdir()
    clean_dir.mkdir()
    plan = json.dumps(
        {"slow_dispatch": {"kernel": "scan", "delay_s": 1.0,
                           "every": 20}}
    )
    r = _run(
        ["--kernel", "scan", "--arrivals", "poisson", "--seed", "7",
         "--requests", "60", "--rate", "6", "--check"],
        {"TPK_SLO_DIR": str(fault_dir), "TPK_FAULT_PLAN": plan,
         "TPK_HEALTH_JOURNAL": str(fault_dir / "health.jsonl")},
        timeout=300,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "BREACH: scan" in r.stdout
    entry = _entries(str(fault_dir))["scan|probe|cpu"]
    target = entry["target_p99_s"]
    assert entry["verdict"] == "slo_breach"
    assert entry["p99_s"] > target          # the tail shows the fault
    assert entry["p50_s"] < target / 10     # the "slope" stays clean
    # the fault fired and was journaled (self-describing chaos runs)
    ev = [json.loads(line) for line in
          open(fault_dir / "health.jsonl").read().splitlines()]
    assert any(e["kind"] == "fault_injected"
               and e.get("fault") == "slow_dispatch" for e in ev)
    assert any(e["kind"] == "slo_probe" for e in ev)

    # gating: the breach artifact flips obs_report --check to rc 1...
    env = _scrubbed_env(None)
    env["TPK_SLO_DIR"] = str(fault_dir)
    chk = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert chk.returncode == 1, chk.stdout + chk.stderr
    assert "slo_breach" in chk.stdout

    # ...and an unfaulted run of the same shape stays rc 0
    r = _run(
        ["--kernel", "scan", "--arrivals", "poisson", "--seed", "7",
         "--requests", "30", "--rate", "10", "--check"],
        {"TPK_SLO_DIR": str(clean_dir),
         "TPK_HEALTH_JOURNAL": str(clean_dir / "health.jsonl")},
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert _entries(str(clean_dir))["scan|probe|cpu"]["verdict"] == "ok"
    env["TPK_SLO_DIR"] = str(clean_dir)
    chk = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr


def test_obs_report_renders_slo_section(tmp_path):
    """The full report gains a latency-SLO table sourced from the
    validated artifact; simulated rows are flagged as never gating."""
    d = tmp_path / "slo"
    d.mkdir()
    r = _run(
        ["--kernel", "sgemm", "--requests", "40", "--rate", "100",
         "--simulate", "2"],
        {"TPK_SLO_DIR": str(d),
         "TPK_HEALTH_JOURNAL": str(d / "health.jsonl")},
    )
    assert r.returncode == 0, r.stderr
    env = _scrubbed_env(None)
    env["TPK_SLO_DIR"] = str(d)
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert rep.returncode in (0, 1), rep.stderr
    assert "latency SLOs" in rep.stdout
    assert "sgemm" in rep.stdout
    assert "simulated - never gates" in rep.stdout


def test_loadgen_usage_errors():
    assert _run(["--bogus"]).returncode == 2
    assert _run(["--rate"]).returncode == 2
    assert _run(["--shapes", "tiny"]).returncode == 2
    assert _run(["--arrivals", "diurnal", "--period", "0",
                 "--requests", "5", "--print-schedule"]).returncode == 2
    r = _run(["--kernel", "not_a_kernel", "--print-schedule"])
    assert r.returncode == 2
    assert "unknown kernel" in r.stderr
