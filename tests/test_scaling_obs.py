"""CPU suite for the distributed-path scaling observability layer
(ISSUE 9; docs/OBSERVABILITY.md §scaling, docs/DISTRIBUTED.md
§observability).

Covers the tentpole contracts without a pod: artifact schema
roundtrip through writer → loader → verdict, fake-flag exclusion from
gating (the PR-8 ``|sim`` pattern), the committed degraded bus-bw
fixture series driving ``obs_report --check`` to rc 1 while fake
artifacts alone leave it rc 0, the analytic ICI-ceiling ``impossible``
verdict, weak-scaling efficiency threshold math, MULTICHIP
legacy-tail parsing against a real committed round, the
weak-scaling program catalog lint, and the byte-identical clean-path
stdout proof for the bus-bw sweep with journaling off.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "tests", "data")

from tpukernels.obs import scaling  # noqa: E402


def _events(path, kind=None):
    recs = [
        json.loads(line)
        for line in open(path).read().splitlines()
        if line.strip()
    ]
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    return recs


def _root_with(tmp_path, fixtures, name="repo"):
    """A fixture repo root whose docs/logs holds copies of committed
    tests/data fixture artifacts ({src_name: dst_name})."""
    root = tmp_path / name
    logs = root / "docs" / "logs"
    logs.mkdir(parents=True)
    (root / "BASELINE.json").write_text("{}")
    for src, dst in fixtures.items():
        shutil.copy(os.path.join(DATA, src), logs / dst)
    return str(root)


def _run_tool(script, *args, env=None):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), *args],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=env,
    )


# ---------------------------------------------------------------- #
# artifact schema roundtrip                                         #
# ---------------------------------------------------------------- #

def test_busbw_artifact_schema_roundtrip(tmp_path):
    root = tmp_path / "repo"
    out = root / "docs" / "logs"
    out.mkdir(parents=True)
    inv = {"source": "jax", "platform": "tpu",
           "device_kind": "tpu_v5_lite", "n_devices": 8, "fake": False}
    p = scaling.write_busbw_artifact(
        [(1024, 0.001, 41.5), (4096, 0.002, 44.0)],
        "allreduce", 8, inv, out_dir=str(out),
    )
    assert os.path.basename(p).startswith("scaling_busbw_allreduce_")
    rec = json.load(open(p))
    assert rec["schema"] == scaling.SCHEMA
    assert rec["family"] == "busbw" and rec["fake"] is False
    assert rec["device_inventory"]["device_kind"] == "tpu_v5_lite"

    arts = scaling.load_artifacts(str(root))
    assert len(arts) == 1
    verdicts = scaling.analyze_busbw(arts, eps=0.01)
    v = verdicts["busbw/allreduce/n8/1024B"]
    assert v["verdict"] == "ok"
    assert v["latest"] == 41.5 and v["valid_points"] == 1


def test_weak_artifact_schema_roundtrip(tmp_path):
    out = tmp_path / "repo" / "docs" / "logs"
    out.mkdir(parents=True)
    inv = {"platform": "tpu", "device_kind": "tpu_v5_lite",
           "fake": False}
    pts = [
        {"program": "allreduce", "n_devices": 8, "wall_s": 0.010,
         "per_chip_work": 4194304, "ok": True},
        {"program": "allreduce", "n_devices": 64, "wall_s": 0.013,
         "per_chip_work": 4194304, "ok": True},
    ]
    scaling.write_weak_artifact(pts, inv, out_dir=str(out))
    arts = scaling.load_artifacts(str(tmp_path / "repo"))
    v = scaling.analyze_weak(arts)["allreduce"]
    assert v["verdict"] == "ok"
    assert v["efficiency"] == pytest.approx(0.010 / 0.013, abs=1e-4)


# ---------------------------------------------------------------- #
# verdict rules: regression, ceiling, fake exclusion                #
# ---------------------------------------------------------------- #

DEGRADED = {
    "scaling_busbw_allreduce_2026-08-01_000000_1.json":
        "scaling_busbw_allreduce_2026-08-01_000000_1.json",
    "scaling_busbw_allreduce_2026-08-02_000000_1.json":
        "scaling_busbw_allreduce_2026-08-02_000000_1.json",
}


def test_degraded_busbw_fixture_is_regression(tmp_path):
    """The committed fixture pair: 45 -> 30 GB/s at 1 MiB on 8 real
    chips is a 33% collapse — exactly the class of silent ICI
    degradation this layer exists to catch by machine."""
    root = _root_with(tmp_path, DEGRADED)
    analysis = scaling.analyze_repo(root)
    v = analysis["busbw"]["busbw/allreduce/n8/1048576B"]
    assert v["verdict"] == "regression"
    assert any("REGRESSION" in f for f in v["flags"])
    assert scaling.gating_findings(analysis)


def test_obs_report_check_gates_degraded_busbw_rc1(tmp_path):
    root = _root_with(tmp_path, DEGRADED)
    r = _run_tool("obs_report.py", "--check", "--root", root)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "busbw/allreduce/n8/1048576B: regression" in r.stdout


def test_fake_artifacts_alone_never_gate(tmp_path):
    """Fake-device artifacts (the CPU rehearsals) are loaded and
    reported but can only ever reach no_data — obs_report --check
    stays rc 0 on fake evidence alone, however degraded it looks."""
    root = _root_with(tmp_path, {
        "scaling_busbw_fake_degraded.json":
            "scaling_busbw_fake_2026-08-01_000000_1.json",
    })
    # a second, equally-degraded fake round: even a "trend" across
    # fake artifacts must stay no_data
    shutil.copy(
        os.path.join(DATA, "scaling_busbw_fake_degraded.json"),
        os.path.join(root, "docs", "logs",
                     "scaling_busbw_fake_2026-08-02_000000_1.json"),
    )
    analysis = scaling.analyze_repo(root)
    v = analysis["busbw"]["busbw/allreduce/n8/1048576B"]
    assert v["verdict"] == "no_data"
    assert v["valid_points"] == 0 and v["points"] >= 1
    assert any("excluded from gating" in f for f in v["flags"])
    assert scaling.gating_findings(analysis) == {}
    r = _run_tool("obs_report.py", "--check", "--root", root)
    assert r.returncode == 0, r.stdout + r.stderr


def test_busbw_impossible_above_ici_ceiling(tmp_path):
    """A validated capture above the analytic per-link ICI ceiling is
    flagged impossible — the 72,698-GFLOPS class of drift error,
    bus-bw edition."""
    root = tmp_path / "repo"
    out = root / "docs" / "logs"
    out.mkdir(parents=True)
    inv = {"source": "jax", "platform": "tpu",
           "device_kind": "tpu_v5_lite", "fake": False}
    ceil, _kind, basis = scaling.ceiling_gb_s(
        "allreduce", "tpu_v5_lite"
    )
    assert basis == "exact"
    scaling.write_busbw_artifact(
        [(1 << 20, 1e-6, ceil * 1.5)], "allreduce", 8, inv,
        out_dir=str(out),
    )
    analysis = scaling.analyze_repo(str(root))
    v = analysis["busbw"][f"busbw/allreduce/n8/{1 << 20}B"]
    assert v["verdict"] == "impossible"
    assert any("IMPOSSIBLE" in f for f in v["flags"])
    # the trend-parser escape hatch: the same glitched point marked
    # invalidated at source is reported but never gates — without it
    # one bad committed capture would flip --check to rc 1 forever
    art_path = next(
        (out / f) for f in os.listdir(out) if f.endswith(".json")
    )
    rec = json.load(open(art_path))
    rec["points"][0]["invalidated"] = "clock glitch, caught at source"
    art_path.write_text(json.dumps(rec))
    v2 = scaling.analyze_repo(str(root))["busbw"][
        f"busbw/allreduce/n8/{1 << 20}B"
    ]
    assert v2["verdict"] == "no_data"
    assert any("invalidated at source" in f for f in v2["flags"])
    # within the epsilon band of the ceiling is NOT impossible
    assert scaling.ceiling_gb_s("ppermute", "cpu")[2] == "exact"
    assert scaling.ceiling_gb_s("allreduce", "tpu_v6")[2] \
        == "assumed-tpu_v5_lite"
    assert scaling.ceiling_gb_s("allreduce", "weird")[2] \
        == "cpu-fallback"


# ---------------------------------------------------------------- #
# weak-scaling efficiency threshold math                            #
# ---------------------------------------------------------------- #

def _weak_root(tmp_path, wall_small, wall_big, fake=False,
               name="repo"):
    root = tmp_path / name
    out = root / "docs" / "logs"
    out.mkdir(parents=True)
    inv = {"platform": "cpu" if fake else "tpu",
           "device_kind": "cpu" if fake else "tpu_v5_lite",
           "fake": fake}
    pts = [
        {"program": "stencil2d", "n_devices": 8, "wall_s": wall_small,
         "per_chip_work": 512, "ok": True},
        {"program": "stencil2d", "n_devices": 64, "wall_s": wall_big,
         "per_chip_work": 512, "ok": True},
    ]
    scaling.write_weak_artifact(pts, inv, out_dir=str(out))
    return str(root)


def test_weak_scaling_efficiency_threshold(tmp_path, monkeypatch):
    # eff = 1.0/2.5 = 40% < default 50% floor -> below (non-gating)
    root = _weak_root(tmp_path, 1.0, 2.5)
    analysis = scaling.analyze_repo(root)
    v = analysis["weak"]["stencil2d"]
    assert v["verdict"] == "below_scaling_efficiency"
    assert v["efficiency"] == pytest.approx(0.4)
    # never a gating finding, by construction
    assert scaling.gating_findings(analysis) == {}

    # eff = 1.0/1.9 = 52.6% >= 50% -> ok
    v_ok = scaling.analyze_repo(
        _weak_root(tmp_path, 1.0, 1.9, name="ok")
    )["weak"]["stencil2d"]
    assert v_ok["verdict"] == "ok"
    assert v_ok["efficiency"] == pytest.approx(1.0 / 1.9, abs=1e-4)

    # the knob moves the floor: 40% passes a 0.3 floor
    monkeypatch.setenv("TPK_SCALING_MIN_EFF", "0.3")
    v_knob = scaling.analyze_repo(root)["weak"]["stencil2d"]
    assert v_knob["verdict"] == "ok"

    # fail-loud parse (the TPK_* contract)
    monkeypatch.setenv("TPK_SCALING_MIN_EFF", "abc")
    with pytest.raises(ValueError, match="TPK_SCALING_MIN_EFF"):
        scaling.min_eff()


def test_weak_scaling_fake_never_verdicted(tmp_path):
    v = scaling.analyze_repo(
        _weak_root(tmp_path, 1.0, 99.0, fake=True)
    )["weak"]["stencil2d"]
    assert v["verdict"] == "no_data"
    assert any("fake" in f for f in v["flags"])


# ---------------------------------------------------------------- #
# MULTICHIP legacy rounds as day-one series data                    #
# ---------------------------------------------------------------- #

def test_multichip_legacy_tail_parsing_real_round():
    """Against the real committed MULTICHIP_r02.json: the progress
    lines in its tail are cumulative stamps printed at each program's
    START, so walls are deltas to the next line (jacobi3d at +3.4s,
    scan at +4.0s -> jacobi3d wall 0.6s; the final 'all programs OK'
    stamp closes nbody_dist_psum)."""
    rec = json.load(open(os.path.join(REPO, "MULTICHIP_r02.json")))
    progs = {p["name"]: p["wall_s"]
             for p in scaling.parse_dryrun_tail(rec["tail"])}
    assert progs["jacobi3d_dist"] == pytest.approx(0.6)
    assert progs["scan_dist"] == pytest.approx(0.4)
    assert progs["histogram_dist"] == pytest.approx(0.2)
    assert progs["nbody_dist_ring"] == pytest.approx(0.7)
    assert progs["nbody_dist_psum"] == pytest.approx(0.5)

    # and through the repo-level analyzer: the five committed rounds
    # become series data (round 1's rc-124 tail contributes nothing)
    series = scaling.analyze_dryrun(REPO)
    assert series["jacobi3d_dist"]["rounds"] >= 4
    assert series["nbody_dist_psum"]["latest_wall_s"] > 0


def test_multichip_structured_line_preferred():
    """A tail carrying the MULTICHIP-PROGRAMS JSON line (what
    __graft_entry__ prints now) wins over legacy delta parsing, and a
    structured `programs` key on the artifact wins over the tail."""
    tail = (
        "[dryrun +  1.0s] scan_dist\n"
        "[dryrun +  9.0s] all programs OK\n"
        'MULTICHIP-PROGRAMS: {"n_devices": 8, "programs": '
        '[{"name": "scan_dist", "wall_s": 0.123, "ok": true}]}\n'
        "dryrun_multichip(8): OK\n"
    )
    progs = scaling.parse_dryrun_tail(tail)
    assert progs == [{"name": "scan_dist", "wall_s": 0.123,
                      "ok": True}]


def test_dryrun_emits_structured_artifact(tmp_path):
    """The new writer: dryrun_multichip records structured per-program
    walls beside the tail — the MULTICHIP-PROGRAMS stdout line (which
    the driver's tail capture preserves) plus the full artifact at
    TPK_MULTICHIP_ARTIFACT."""
    art = tmp_path / "multichip.json"
    env = _scrubbed_env(None)
    env["TPK_MULTICHIP_ARTIFACT"] = str(art)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"),
         "dryrun", "8"],
        env=env, capture_output=True, text=True, timeout=240,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "MULTICHIP-PROGRAMS: " in proc.stdout
    progs = scaling.parse_dryrun_tail(proc.stdout)
    names = [p["name"] for p in progs]
    assert names == [
        "allreduce_sum", "bcast", "ring_shift", "jacobi2d_dist",
        "jacobi3d_dist", "scan_dist", "histogram_dist",
        "nbody_dist_ring", "nbody_dist_psum",
    ]
    assert all(p["ok"] and p["wall_s"] >= 0 for p in progs)
    rec = json.load(open(art))
    assert rec["n_devices"] == 8 and rec["ok"] is True
    assert rec["programs"] == progs
    assert rec["device_inventory"]["fake"] is True  # CPU by design


# ---------------------------------------------------------------- #
# catalog lint: no observability-dark distributed program           #
# ---------------------------------------------------------------- #

def test_weak_program_catalog_complete():
    """Every program tools/weak_scaling.py sweeps must have a
    scaling.WEAK_SERIES row (artifact series name + work unit), and
    the bus-bw ops must each have an analytic ceiling row for the
    evidence and fallback device kinds — a new distributed kernel
    cannot ship observability-dark."""
    spec = importlib.util.spec_from_file_location(
        "weak_scaling", os.path.join(REPO, "tools", "weak_scaling.py")
    )
    ws = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ws)
    assert set(ws.PROGRAMS) == set(scaling.WEAK_SERIES), (
        "tools/weak_scaling.py PROGRAMS and scaling.WEAK_SERIES "
        "must list the same programs"
    )
    for name, row in scaling.WEAK_SERIES.items():
        assert row.get("series", "").startswith("weak/"), (name, row)
        assert row.get("work_unit"), (name, row)
    for op in ("allreduce", "ppermute"):
        for kind in ("tpu_v5_lite", "cpu"):
            ceil, _k, basis = scaling.ceiling_gb_s(op, kind)
            assert ceil > 0 and basis == "exact", (op, kind)


def test_device_inventory_event(monkeypatch, tmp_path):
    j = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(j))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    inv = scaling.emit_inventory("test-site")  # env mode: no jax touch
    assert inv["source"] == "env" and inv["fake"] is True
    assert inv["fake_basis"] == "declared-platform"
    (ev,) = _events(j, "device_inventory")
    assert ev["site"] == "test-site"
    assert ev["platform"] == "cpu" and ev["fake"] is True


def test_device_inventory_unknown_platform(monkeypatch):
    """Nothing declares a platform (the NORMAL pod config): the
    env-derived stamp is fail-safe fake=True — unknown must never
    read as chip evidence — but fake_basis='unknown-platform' keeps
    it distinct from known-fake so a real pod's telemetry never
    renders 'FAKE'."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    inv = scaling.inventory()
    assert inv["source"] == "env" and inv["platform"] is None
    assert inv["fake"] is True
    assert inv["fake_basis"] == "unknown-platform"
    # a declared TPU-flavored platform is known-real
    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    inv = scaling.inventory()
    assert inv["platform"] == "tpu" and inv["fake"] is False
    assert inv["fake_basis"] == "declared-platform"


def test_inventory_probe_fallthrough_forced_fake(monkeypatch):
    """A REQUESTED probe that errors must not fall back to whatever
    the env declares: on a JAX_PLATFORMS=tpu,cpu host a flaky runtime
    would otherwise mint a fake=False stamp from an unprobed env."""
    import jax

    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("flaky")),
    )
    inv = scaling.inventory(probe=True)
    assert inv["source"] == "env" and inv["platform"] == "tpu"
    assert inv["fake"] is True
    assert inv["fake_basis"] == "unprobed-fallback"


def test_unprobed_nonfake_artifact_excluded_from_gating(tmp_path):
    """The docs/DISTRIBUTED.md contract, enforced: a fake=False
    artifact whose device_inventory is env-derived (or missing) has
    unattributed topology and must neither fire nor mask a gating
    verdict — analyze_busbw flags it and verdicts no_data."""
    root = tmp_path / "repo"
    out = root / "docs" / "logs"
    out.mkdir(parents=True)
    inv = {"source": "env", "platform": "tpu", "fake": False,
           "fake_basis": "declared-platform"}
    scaling.write_busbw_artifact(
        [(1 << 20, 1e-3, 30.0)], "allreduce", 8, inv,
        out_dir=str(out),
    )
    v = scaling.analyze_repo(str(root))["busbw"][
        "busbw/allreduce/n8/1048576B"
    ]
    assert v["verdict"] == "no_data" and v["valid_points"] == 0
    assert any("unprobed" in f for f in v["flags"])
    assert not scaling.gating_findings(
        {"busbw": {"x": v}, "weak": {}}
    )


def test_weak_scaling_fallback_inventory_forced_fake(tmp_path):
    """Parent fallback when every child dies before its inventory
    probe (a shadowed numpy import crashes inner() at its first
    statement): the artifact must be stamped fake=True with
    fake_basis='unprobed-fallback' EVEN on a declared-TPU host —
    gating-eligible evidence needs a probed (source='jax') inventory,
    and a childless sweep must never read as chip evidence."""
    bad = tmp_path / "badmod"
    bad.mkdir()
    (bad / "numpy.py").write_text('raise ImportError("fault-injected")')
    out = tmp_path / "logs"
    out.mkdir()
    env = _scrubbed_env(None)
    env["JAX_PLATFORMS"] = "tpu,cpu"  # declared-real host
    env["PYTHONPATH"] += os.pathsep + str(bad)
    env["TPK_SCALING_DIR"] = str(out)
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "health.jsonl")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "weak_scaling.py"),
         "--sizes", "1", "--quick", "--reps", "1"],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "NOT gating-eligible" in proc.stderr
    assert "stamped fake, never gates" in proc.stdout
    (art,) = list(out.glob("scaling_weak_*.json"))
    rec = json.load(open(art))
    assert rec["fake"] is True
    assert rec["device_inventory"]["fake_basis"] == "unprobed-fallback"
    assert rec["device_inventory"]["source"] == "env"
    # the forced stamp is journaled too (the emit_inventory contract:
    # artifact writers embed the same dict they stamped) — a journal
    # tailer must not read the parent's plain env stamp (fake=False
    # on this declared-TPU host) as the run's hardware attribution
    (ev,) = [e for e in _events(tmp_path / "health.jsonl",
                                "device_inventory")
             if e["site"] == "weak_scaling:fallback"]
    assert ev["fake"] is True
    assert ev["fake_basis"] == "unprobed-fallback"
    # narration: unknown/unprobed hardware is never labeled "FAKE"
    r = _run_tool("health_report.py", "--journal",
                  str(tmp_path / "health.jsonl"))
    lines = [ln for ln in r.stdout.splitlines()
             if "weak_scaling:fallback" in ln]
    assert lines and "unprobed (treated fake for gating)" in lines[0]
    assert "FAKE" not in lines[0]


# ---------------------------------------------------------------- #
# end-to-end: the CLIs produce schema-valid fake-flagged artifacts  #
# ---------------------------------------------------------------- #

def test_weak_scaling_tool_end_to_end(tmp_path):
    """Acceptance: tools/weak_scaling.py on fake CPU devices produces
    a schema-valid fake-flagged artifact plus weak_scaling_point +
    device_inventory journal events, and the analyzer refuses to
    verdict the fake evidence."""
    out = tmp_path / "repo" / "docs" / "logs"
    out.mkdir(parents=True)
    j = tmp_path / "health.jsonl"
    env = _scrubbed_env(None)
    env["TPK_SCALING_DIR"] = str(out)
    env["TPK_HEALTH_JOURNAL"] = str(j)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "weak_scaling.py"),
         "--sizes", "1 4", "--quick", "--reps", "1"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "FAKE devices" in proc.stdout
    arts = scaling.load_artifacts(str(tmp_path / "repo"))
    assert len(arts) == 1 and arts[0]["fake"] is True
    progs = {p["program"] for p in arts[0]["points"]}
    assert progs == set(scaling.WEAK_SERIES)
    # allreduce2d has no 2-D factorization at n=1: skipped (not
    # failed, no phantom point), while n=4 sweeps it on a (2, 2)
    # mesh and stamps the geometry on the point
    assert "skipped (no mesh shape at this size)" in proc.stdout
    ar2 = [p for p in arts[0]["points"] if p["program"] == "allreduce2d"]
    assert len(ar2) == 1
    assert ar2[0]["n_devices"] == 4 and ar2[0]["mesh_shape"] == [2, 2]
    pts = _events(j, "weak_scaling_point")
    assert len(pts) == 2 * len(scaling.WEAK_SERIES) - 1
    assert all(p["fake"] for p in pts)
    invs = _events(j, "device_inventory")
    sites = {e["site"] for e in invs}
    assert "weak_scaling" in sites and "weak_scaling:parent" in sites
    # fake weak evidence never verdicts (no_data, flagged)
    weak = scaling.analyze_repo(str(tmp_path / "repo"))["weak"]
    assert all(v["verdict"] == "no_data" for v in weak.values())


def test_busbw_cli_writes_fake_flagged_artifact(tmp_path):
    """Acceptance: `python -m tpukernels.parallel.busbw` on 8 fake
    CPU devices writes a schema-valid fake-flagged artifact and
    journals busbw_point + device_inventory events; the artifact path
    goes to stderr, never stdout (the C driver greps stdout)."""
    out = tmp_path / "repo" / "docs" / "logs"
    out.mkdir(parents=True)
    j = tmp_path / "health.jsonl"
    env = _scrubbed_env(8)
    env["TPK_SCALING_DIR"] = str(out)
    env["TPK_HEALTH_JOURNAL"] = str(j)
    proc = subprocess.run(
        [sys.executable, "-m", "tpukernels.parallel.busbw",
         "--min=1K", "--max=4K", "--reps=1"],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "# busbw artifact:" in proc.stderr
    assert "# busbw artifact:" not in proc.stdout
    arts = scaling.load_artifacts(str(tmp_path / "repo"))
    assert len(arts) == 1
    art = arts[0]
    assert art["family"] == "busbw" and art["fake"] is True
    assert art["op"] == "allreduce" and art["n_devices"] == 8
    assert [p["size_bytes"] for p in art["points"]] == [1024, 4096]
    assert art["device_inventory"]["source"] == "jax"
    pts = _events(j, "busbw_point")
    assert len(pts) == 2 and all(p["fake"] for p in pts)
    (inv,) = _events(j, "device_inventory")
    assert inv["site"] == "busbw" and inv["n_devices"] == 8


# ---------------------------------------------------------------- #
# acceptance: clean sweep stdout byte-identical, journaling off     #
# ---------------------------------------------------------------- #

class _FakeTime:
    """A busbw-scoped deterministic clock: each perf_counter() call
    advances 1 ms, so two sweeps print byte-identical timing lines.
    Scoped to the busbw module's `time` name on purpose — patching the
    global would also catch jax-internal clock reads, whose call count
    differs between a cold and a warm run."""

    def __init__(self):
        self.t = 0.0

    def perf_counter(self):
        self.t += 0.001
        return self.t


def test_busbw_sweep_stdout_byte_identical_without_journal(
    tmp_path, capsys,
):
    """The fault/trace layers' proof, scaling edition: with the clock
    mocked deterministic, sweep() stdout must be byte-identical with
    journaling OFF and ON — the structured capture goes to the
    journal and artifact files, never stdout (the C driver and the
    pod operator grep these lines)."""
    from tpukernels.parallel import busbw
    from tpukernels.parallel.mesh import make_mesh

    mesh = make_mesh(8)

    def run_once(journal_value):
        mp = pytest.MonkeyPatch()
        mp.setenv("TPK_HEALTH_JOURNAL", journal_value)
        mp.setattr(busbw, "time", _FakeTime())
        try:
            busbw.sweep(min_bytes=1024, max_bytes=4096, reps=2,
                        mesh=mesh)
        finally:
            mp.undo()
        return capsys.readouterr().out

    out_off = run_once("0")
    j = tmp_path / "health.jsonl"
    out_on = run_once(str(j))

    assert out_off == out_on
    assert "allreduce n=8" in out_off
    assert "{" not in out_off  # no structured payload leaks to stdout
    pts = _events(j, "busbw_point")
    assert len(pts) == 2  # only the journaled run left evidence


# ---------------------------------------------------------------- #
# comm/compute overlap verdicts (ISSUE 20)                          #
# ---------------------------------------------------------------- #

def test_overlap_artifact_schema_roundtrip_and_verdicts(tmp_path,
                                                        monkeypatch):
    """Writer -> loader -> analyze_overlap: a validated non-fake point
    under the TPK_OVERLAP_MIN_FRAC floor earns overlap_low, one above
    earns ok — and NEITHER gates (the below_roofline pattern)."""
    root = tmp_path / "repo"
    out = root / "docs" / "logs"
    out.mkdir(parents=True)
    inv = {"source": "jax", "platform": "tpu",
           "device_kind": "tpu_v5_lite", "n_devices": 8, "fake": False}
    pts = [
        {"op": "nbody_ring", "n_devices": 8, "mesh_shape": None,
         "depth": 2, "t_comm_s": 0.010, "t_compute_s": 0.010,
         "t_full_s": 0.019, "overlap_frac": 0.1},
        {"op": "stencil2d", "n_devices": 8, "mesh_shape": None,
         "depth": 2, "t_comm_s": 0.004, "t_compute_s": 0.010,
         "t_full_s": 0.011, "overlap_frac": 0.75},
    ]
    p = scaling.write_overlap_artifact(pts, inv, out_dir=str(out))
    assert os.path.basename(p).startswith("scaling_overlap_")
    rec = json.load(open(p))
    assert rec["family"] == "overlap" and rec["fake"] is False

    arts = scaling.load_artifacts(str(root))
    v = scaling.analyze_overlap(arts)
    low = v["overlap/nbody_ring/n8/d2"]
    assert low["verdict"] == "overlap_low"
    assert any("OVERLAP LOW" in f for f in low["flags"])
    assert v["overlap/stencil2d/n8/d2"]["verdict"] == "ok"
    # non-gating by construction: the full-repo analysis carries the
    # overlap section but gating_findings never returns it
    analysis = scaling.analyze_repo(str(root))
    assert "overlap/nbody_ring/n8/d2" in analysis["overlap"]
    assert scaling.gating_findings(analysis) == {}

    # the floor is a knob with the fail-loud TPK_* parse contract
    monkeypatch.setenv("TPK_OVERLAP_MIN_FRAC", "0.05")
    v2 = scaling.analyze_overlap(arts)
    assert v2["overlap/nbody_ring/n8/d2"]["verdict"] == "ok"
    monkeypatch.setenv("TPK_OVERLAP_MIN_FRAC", "bogus")
    with pytest.raises(ValueError, match="TPK_OVERLAP_MIN_FRAC"):
        scaling.analyze_overlap(arts)
    monkeypatch.setenv("TPK_OVERLAP_MIN_FRAC", "1.5")
    with pytest.raises(ValueError, match="TPK_OVERLAP_MIN_FRAC"):
        scaling.analyze_overlap(arts)


def test_overlap_fake_evidence_never_verdicted(tmp_path):
    """CPU gloo rehearsals prove the measurement plumbing only: a
    fake-flagged artifact's points verdict no_data, never
    overlap_low."""
    out = tmp_path / "repo" / "docs" / "logs"
    out.mkdir(parents=True)
    inv = {"source": "env", "platform": "cpu", "fake": True}
    scaling.write_overlap_artifact(
        [{"op": "nbody_ring", "n_devices": 8, "mesh_shape": None,
          "depth": 2, "t_comm_s": 0.01, "t_compute_s": 0.01,
          "t_full_s": 0.02, "overlap_frac": 0.0}],
        inv, out_dir=str(out))
    arts = scaling.load_artifacts(str(tmp_path / "repo"))
    v = scaling.analyze_overlap(arts)["overlap/nbody_ring/n8/d2"]
    assert v["verdict"] == "no_data"
    assert any("fake-device" in f for f in v["flags"])


def test_obs_report_prints_overlap_low_without_gating(tmp_path):
    """obs_report full + --check surface overlap_low findings while
    the rc contract stays 0 — the satellite's exact wording."""
    root = _root_with(tmp_path, {})
    inv = {"source": "jax", "platform": "tpu",
           "device_kind": "tpu_v5_lite", "n_devices": 8, "fake": False}
    scaling.write_overlap_artifact(
        [{"op": "nbody_ring", "n_devices": 8, "mesh_shape": None,
          "depth": 2, "t_comm_s": 0.010, "t_compute_s": 0.010,
          "t_full_s": 0.019, "overlap_frac": 0.1}],
        inv, out_dir=os.path.join(root, "docs", "logs"))
    r = _run_tool("obs_report.py", "--root", root)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "overlap_low" in r.stdout
    assert "overlap/nbody_ring/n8/d2" in r.stdout
    r = _run_tool("obs_report.py", "--check", "--root", root)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "overlap_low (non-gating)" in r.stdout
