import jax.numpy as jnp
import numpy as np
import pytest

from tpukernels.kernels.histogram import histogram, histogram_reference
from tpukernels.kernels.scan import (
    exclusive_scan,
    exclusive_scan_reference,
    inclusive_scan,
    inclusive_scan_reference,
)


@pytest.mark.parametrize("n", [128, 1000, 2**17, 7])
def test_scan_f32(rng, n):
    x = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)
    out = np.asarray(inclusive_scan(x))
    ref = np.cumsum(np.asarray(x, dtype=np.float64))
    # float prefix sums accumulate error ~ sqrt(n) * eps * scale
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-2)


@pytest.mark.parametrize("n", [128, 4096, 2**17, 333])
def test_scan_i32_exact(rng, n):
    x = jnp.asarray(rng.integers(-100, 100, n), dtype=jnp.int32)
    out = np.asarray(inclusive_scan(x))
    ref = np.cumsum(np.asarray(x))
    np.testing.assert_array_equal(out, ref)


@pytest.mark.parametrize("n", [128, 4096, 333, 1, 0])
def test_exclusive_scan(rng, n):
    x = jnp.asarray(rng.integers(-100, 100, n), dtype=jnp.int32)
    out = np.asarray(exclusive_scan(x))
    ref = np.asarray(exclusive_scan_reference(x))
    np.testing.assert_array_equal(out, ref)
    assert out.shape == (n,)
    if n:
        assert out[0] == 0
    xf = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)
    # same tolerance as the inclusive f32 contract: prefix sums
    # accumulate ~sqrt(n)*eps of order-dependent error
    np.testing.assert_allclose(
        np.asarray(exclusive_scan(xf)),
        np.asarray(exclusive_scan_reference(xf)),
        rtol=1e-4, atol=1e-2,
    )


def test_scan_matches_jnp_reference(rng):
    x = jnp.asarray(rng.integers(0, 10, 50000), dtype=jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(inclusive_scan(x)), np.asarray(inclusive_scan_reference(x))
    )


@pytest.mark.parametrize("impl", ["mxu", "vpu"])
@pytest.mark.parametrize(
    "n,nbins",
    [
        (100000, 256),
        (2**17, 64),
        (999, 16),
        (4096, 1024),
        # nbins that don't divide the block row count: regression for
        # the in-kernel chunk loop dropping trailing rows
        (300000, 200),
        (2**18, 80),
    ],
)
def test_histogram_exact(rng, monkeypatch, n, nbins, impl):
    if impl == "mxu" and nbins > 256:
        pytest.skip("mxu path is nbins <= 256 by construction")
    monkeypatch.setenv("TPK_HIST_IMPL", impl)
    x = jnp.asarray(rng.integers(0, nbins, n), dtype=jnp.int32)
    out = np.asarray(histogram(x, nbins))
    ref = np.bincount(np.asarray(x), minlength=nbins)
    np.testing.assert_array_equal(out, ref)
    assert out.sum() == n


@pytest.mark.parametrize("acc", ["i8", "f32"])
@pytest.mark.parametrize(
    "n,nbins",
    [
        (100000, 256),
        # f32 acc at large nbins drives _pick_chunk to its floor of 8
        # (the (chunk, 128, nbins) slab budget divides to zero)
        (4096, 1024),
        (2**18, 80),
    ],
)
def test_histogram_vpu_acc_dtypes(rng, monkeypatch, n, nbins, acc):
    monkeypatch.setenv("TPK_HIST_IMPL", "vpu")
    monkeypatch.setenv("TPK_HIST_ACC", acc)
    x = jnp.asarray(rng.integers(0, nbins, n), dtype=jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(histogram(x, nbins)),
        np.bincount(np.asarray(x), minlength=nbins),
    )


def test_histogram_mxu_skewed_and_out_of_range(monkeypatch):
    # all-same-value input stresses single-cell accumulation (the f32
    # per-block exactness bound); out-of-range values count nothing
    monkeypatch.setenv("TPK_HIST_IMPL", "mxu")
    x = np.full(300000, 7, dtype=np.int32)
    x[:100] = -3
    x[100:200] = 256
    out = np.asarray(histogram(jnp.asarray(x), 256))
    assert out[7] == 300000 - 200 and out.sum() == 300000 - 200


def test_histogram_empty_input():
    np.testing.assert_array_equal(
        np.asarray(histogram(jnp.zeros(0, jnp.int32), 64)),
        np.zeros(64, np.int32),
    )


def test_histogram_bad_acc_env_raises(rng, monkeypatch):
    monkeypatch.setenv("TPK_HIST_IMPL", "vpu")
    monkeypatch.setenv("TPK_HIST_ACC", "float32")
    with pytest.raises(ValueError, match="TPK_HIST_ACC"):
        histogram(jnp.zeros(16, jnp.int32), 8)


def test_histogram_bad_impl_env_raises(rng, monkeypatch):
    monkeypatch.setenv("TPK_HIST_IMPL", "gpu")
    with pytest.raises(ValueError, match="TPK_HIST_IMPL"):
        histogram(jnp.zeros(16, jnp.int32), 8)
    monkeypatch.setenv("TPK_HIST_IMPL", "mxu")
    with pytest.raises(ValueError, match="nbins"):
        histogram(jnp.zeros(16, jnp.int32), 1024)


def test_histogram_matches_jnp_reference(rng):
    x = jnp.asarray(rng.integers(0, 32, 10000), dtype=jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(histogram(x, 32)), np.asarray(histogram_reference(x, 32))
    )


# ------------------------------------------------------------------ #
# fused single-pass scan+histogram (kernels/scan_histogram.py)       #
# ------------------------------------------------------------------ #

@pytest.mark.parametrize("fuse", ["off", "on"])
@pytest.mark.parametrize(
    "n,nbins",
    [
        (100000, 256),
        (999, 16),
        (4096, 1024),   # > 256 bins: beyond the MXU path's reach
        (300000, 200),  # nbins not dividing the chunk budget
        (7, 4),         # sub-lane problem: single padded block
        (0, 16),        # empty input
    ],
)
def test_scan_histogram_exact(rng, monkeypatch, n, nbins, fuse):
    from tpukernels.kernels.scan_histogram import (
        scan_histogram,
        scan_histogram_reference,
    )

    monkeypatch.setenv("TPK_SCANHIST_FUSE", fuse)
    x = jnp.asarray(rng.integers(0, nbins, n), dtype=jnp.int32)
    s, h = scan_histogram(x, nbins)
    sr, hr = scan_histogram_reference(x, nbins)
    np.testing.assert_array_equal(np.asarray(s), np.asarray(sr))
    np.testing.assert_array_equal(np.asarray(h), np.asarray(hr))
    assert np.asarray(h).sum() == n


def test_scan_histogram_fused_pad_correction(monkeypatch):
    """The fused path pads with ZEROS (scan-neutral) and subtracts the
    pad count from bin 0 — an all-zeros input is the worst case for
    over/under-correction."""
    from tpukernels.kernels.scan_histogram import scan_histogram

    monkeypatch.setenv("TPK_SCANHIST_FUSE", "on")
    x = jnp.zeros(1000, jnp.int32)
    s, h = scan_histogram(x, 8)
    assert int(np.asarray(h)[0]) == 1000
    assert int(np.asarray(h).sum()) == 1000
    np.testing.assert_array_equal(np.asarray(s), np.zeros(1000))
    # out-of-range and negative values count nothing, scan keeps them
    x = jnp.asarray(np.array([-5, 3, 99, 3, 0], np.int32))
    s, h = scan_histogram(x, 4)
    np.testing.assert_array_equal(np.asarray(h), [1, 0, 0, 2])
    np.testing.assert_array_equal(
        np.asarray(s), np.cumsum([-5, 3, 99, 3, 0])
    )


def test_scan_histogram_fuse_off_is_the_two_kernel_path(rng):
    """fuse=off (the shipped default) must equal the standalone
    kernels exactly — it IS them."""
    from tpukernels.kernels.scan_histogram import scan_histogram

    x = jnp.asarray(rng.integers(0, 32, 5000), dtype=jnp.int32)
    s, h = scan_histogram(x, 32)  # default: no env set
    np.testing.assert_array_equal(
        np.asarray(s), np.asarray(inclusive_scan(x))
    )
    np.testing.assert_array_equal(
        np.asarray(h), np.asarray(histogram(x, 32))
    )


def test_scan_histogram_bad_fuse_env_fails_loud(monkeypatch):
    from tpukernels.kernels.scan_histogram import scan_histogram

    monkeypatch.setenv("TPK_SCANHIST_FUSE", "maybe")
    with pytest.raises(ValueError, match="TPK_SCANHIST_FUSE"):
        scan_histogram(jnp.zeros(16, jnp.int32), 8)


@pytest.mark.parametrize("impl,acc", [("mxu", "i8"), ("vpu", "i8"),
                                      ("vpu", "f32")])
def test_scan_histogram_fused_honors_hist_knobs(rng, monkeypatch,
                                                impl, acc):
    """The fused kernel's histogram half resolves histogram's own
    impl/acc TUNABLES (shared hist_mxu_block/hist_vpu_block helpers),
    so TPK_HIST_IMPL/ACC mean the same thing on both entry points —
    including the fail-loud mxu/nbins validation."""
    from tpukernels.kernels.scan_histogram import (
        scan_histogram,
        scan_histogram_reference,
    )

    monkeypatch.setenv("TPK_SCANHIST_FUSE", "on")
    monkeypatch.setenv("TPK_HIST_IMPL", impl)
    monkeypatch.setenv("TPK_HIST_ACC", acc)
    x = jnp.asarray(rng.integers(0, 200, 50000), dtype=jnp.int32)
    s, h = scan_histogram(x, 200)
    sr, hr = scan_histogram_reference(x, 200)
    np.testing.assert_array_equal(np.asarray(s), np.asarray(sr))
    np.testing.assert_array_equal(np.asarray(h), np.asarray(hr))
    if impl == "mxu":
        with pytest.raises(ValueError, match="nbins"):
            scan_histogram(x, 1024)
