"""CPU suite for the sharded serving fleet (docs/SERVING.md §fleet;
ISSUE 11).

Covers the fleet contracts without a TPU: deterministic md5 bucket
routing with the FLEET-WIDE one-compile proof (3 concurrent clients x
mixed shapes against 1 router + 2 workers -> exactly one
``aot_hit``/``aot_miss`` per (kernel, bucket) across every process),
spill-to-sibling under worker backpressure, live drain + restart
mid-burst with zero dropped requests, wedged-worker failover chaos
via an env-narrowed ``wedge_dispatch`` fault plan, per-tenant
token-bucket quotas with priority classes, front-socket protocol
poisoning isolated to one connection, the seeded retry-jitter
thundering-herd fix, and the ``loadgen --serve --tenant`` ->
per-tenant ``slo.json`` rows -> ``obs_report --check`` e2e with the
rc contract unchanged.
"""

import contextlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from test_distributed import _scrubbed_env
from test_serve import SCAN_BUCKET, _aot_bucket_events, _events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CTL = os.path.join(REPO, "tools", "serve_ctl.py")


def _ctl(env, *args, timeout=120):
    return subprocess.run(
        [sys.executable, CTL, *args], capture_output=True, text=True,
        timeout=timeout, cwd=REPO, env=env,
    )


@contextlib.contextmanager
def _fleet(tmp_path, n=2, env_extra=None, tag="f"):
    """Start a fleet (router + ``n`` workers) via ``serve_ctl
    start-fleet`` in an isolated serve dir; yields (front_socket,
    journal_path, env) and stops the fleet on exit."""
    d = tmp_path / tag
    d.mkdir(exist_ok=True)
    journal = str(d / "health.jsonl")
    env = _scrubbed_env(None)
    env["TPK_SERVE_DIR"] = str(d)
    env["TPK_HEALTH_JOURNAL"] = journal
    env.update(env_extra or {})
    r = _ctl(env, "start-fleet", str(n), "--wait", "90", timeout=150)
    assert r.returncode == 0, r.stdout + r.stderr
    front = str(d / "fleet" / "front.sock")
    try:
        yield front, journal, env
    finally:
        _ctl(env, "stop-fleet", "--wait", "30", timeout=150)


def _scan_case(n=6000):
    x = (np.arange(n) % 17).astype(np.int32)
    return x, np.cumsum(x, dtype=np.int64).astype(np.int32)


# ---------------------------------------------------------------- #
# pure units: ring math, retry jitter, per-tenant SLO keys         #
# ---------------------------------------------------------------- #

def test_ring_order_deterministic_and_complete():
    from tpukernels.serve import router

    for n in (1, 2, 3, 5):
        order = router.ring_order("scan|8192|-", n)
        assert sorted(order) == list(range(n))
        # stable across calls (md5, not python's salted hash)
        assert order == router.ring_order("scan|8192|-", n)
    # distinct buckets spread: with a handful of keys over 2 workers
    # both primaries must occur (md5 uniformity, pinned here so a
    # hash change is a loud test failure, not silent resharding)
    primaries = {
        router.ring_order(b, 2)[0]
        for b in ("scan|8192|-", "vector_add|-+1024+1024|-",
                  "sgemm|-+48x80+80x64+-+48x64|-", "histogram|4128|nbins=256")
    }
    assert primaries == {0, 1}


def test_retry_jitter_deterministic_and_decorrelated(monkeypatch):
    """The thundering-herd fix: jittered backpressure retries are
    0.5x-1.5x the hint, byte-reproducible per seed, and two different
    seeds do NOT sleep in lockstep."""
    import random

    from tpukernels.serve import client as serve_client

    class _Rejecting:
        def dispatch(self, kernel, *a, **s):
            raise serve_client.ServeRejected("full", 0.2)

    def run(seed):
        sleeps = []
        monkeypatch.setattr(
            "tpukernels.serve.client.time.sleep", sleeps.append
        )
        with pytest.raises(serve_client.ServeRejected):
            serve_client.dispatch_with_backpressure(
                _Rejecting(), "scan", (), {}, max_rejections=5,
                jitter=random.Random(seed),
            )
        return sleeps

    a, b, a2 = run(1), run(2), run(1)
    assert a == a2, "same seed must sleep identically"
    assert a != b, "different seeds must decorrelate"
    assert len(a) == 4
    assert all(0.1 <= s < 0.3 for s in a + b)
    # and without a jitter stream, the raw hint is kept (the capi
    # single-client path is unchanged)
    sleeps = []
    monkeypatch.setattr(
        "tpukernels.serve.client.time.sleep", sleeps.append
    )
    with pytest.raises(serve_client.ServeRejected):
        serve_client.dispatch_with_backpressure(
            _Rejecting(), "scan", (), {}, max_rejections=3
        )
    assert sleeps == [0.2, 0.2]


def test_slo_tenant_rows_resolve_base_kernel(monkeypatch, tmp_path):
    """``scan@hot`` series: targets + kernel sources resolve the BASE
    kernel, the verdict keyspace keeps the tenant — per-tenant rows
    ride the unchanged slo.json contract."""
    from tpukernels.obs import slo

    assert slo.base_kernel("scan@hot") == "scan"
    assert slo.base_kernel("scan") == "scan"
    assert (slo.resolve_target_s("scan@hot", "cpu", "probe")
            == slo.resolve_target_s("scan", "cpu", "probe"))
    assert slo.resolve_target_s("scan@hot", "cpu", "probe")[0] is not None
    # unknown base kernel still has no row, tenant or not
    assert slo.resolve_target_s("nope@hot", "cpu", "probe")[0] is None
    assert slo.entry_key("scan@hot", "probe", "cpu") == "scan@hot|probe|cpu"
    # a tenant entry persists and validates like any other
    monkeypatch.setenv("TPK_SLO_DIR", str(tmp_path))
    slo.reset()
    row = {
        "kernel": "scan@hot", "count": 30, "p50_s": 0.001,
        "p95_s": 0.002, "p99_s": 0.003, "max_s": 0.004,
        "buckets": {}, "target_p99_s": 0.4, "basis": "cpu-fallback",
        "device_kind": "cpu", "shape_class": "probe",
        "simulated": True, "verdict": "ok",
    }
    slo.record({"scan@hot": row}, {"tenant": "hot"})
    entries = slo.load_entries()
    assert "scan@hot|probe|cpu|sim" in entries


# ---------------------------------------------------------------- #
# the fleet service loop                                           #
# ---------------------------------------------------------------- #

def test_fleet_one_compile_per_bucket_and_poison_isolation(tmp_path):
    """The acceptance headline: 3 concurrent clients x mixed
    (bucketable) shapes against a 1-router/2-worker fleet — every
    response correct, and EXACTLY ONE aot_hit/aot_miss per (kernel,
    bucket) across the whole fleet (the consistent hash keeps each
    bucket's executable memo on one worker). Afterwards, garbage and
    oversize frames at the front socket poison only their own
    connection — the router and every worker keep serving."""
    import socket as socket_mod

    from tpukernels.serve import client as serve_client
    from tpukernels.serve import protocol, router

    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_BATCH_WINDOW_MS": "0",
    }) as (front, journal, _env):
        lengths = [5000, 6000, 7000, 8000, 8192]
        errors = []

        def client_run(seed):
            rng = np.random.default_rng(seed)
            try:
                with serve_client.ServeClient(
                    front, timeout_s=180, tenant=f"t{seed}"
                ) as c:
                    for n in lengths:
                        x = rng.integers(-50, 50, n).astype(np.int32)
                        out = c.dispatch("scan", x)
                        np.testing.assert_array_equal(
                            out, np.cumsum(x, dtype=np.int64
                                           ).astype(np.int32)
                        )
                    x = rng.standard_normal(1024).astype(np.float32)
                    y = rng.standard_normal(1024).astype(np.float32)
                    out = c.dispatch("vector_add", np.float32(2.0), x, y)
                    np.testing.assert_allclose(out, 2.0 * x + y,
                                               rtol=1e-6, atol=1e-6)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))

        threads = [threading.Thread(target=client_run, args=(s,))
                   for s in (1, 2, 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        assert not errors, errors

        # --- protocol poison: only the poisoned connection dies --- #
        def _hung_up(sock):
            """EOF or RST both mean the router dropped the poisoned
            connection (RST when our junk bytes were still unread at
            its close)."""
            sock.settimeout(10)
            try:
                return sock.recv(1) == b""
            except ConnectionResetError:
                return True

        s = socket_mod.socket(socket_mod.AF_UNIX,
                              socket_mod.SOCK_STREAM)
        s.connect(front)
        s.sendall(b"GET / HTTP/1.1\r\n" + b"\0" * 32)
        assert _hung_up(s)  # router hung up on the poison
        s.close()
        s = socket_mod.socket(socket_mod.AF_UNIX,
                              socket_mod.SOCK_STREAM)
        s.connect(front)
        s.sendall(protocol._PREAMBLE.pack(
            protocol.MAGIC, protocol.MAX_HEADER + 1, 0
        ))
        assert _hung_up(s)  # absurd header length: same fate
        s.close()
        # an unknown op errors politely WITHOUT poisoning the stream
        s = socket_mod.socket(socket_mod.AF_UNIX,
                              socket_mod.SOCK_STREAM)
        s.connect(front)
        protocol.send_frame(s, {"v": 1, "op": "teapot", "id": 9})
        hdr, _p = protocol.recv_frame(s)
        assert hdr["ok"] is False and "unknown op" in hdr["error"]
        protocol.send_frame(s, {"v": 1, "op": "ping"})
        assert protocol.recv_frame(s)[0]["role"] == "router"
        s.close()
        # the fleet still serves real traffic after the abuse
        x, want = _scan_case()
        with serve_client.ServeClient(front, timeout_s=120) as c:
            np.testing.assert_array_equal(c.dispatch("scan", x), want)

    events = _events(journal)
    served = [e for e in events if e.get("kind") == "serve_request"]
    assert len(served) == 3 * (len(lengths) + 1) + 1
    assert all(e.get("ok") for e in served)
    # THE fleet-wide one-compile proof: one compile per (kernel,
    # bucket) across router + both workers + all clients
    assert len(_aot_bucket_events(events, "scan", "8192")) == 1
    assert len(_aot_bucket_events(events, "vector_add", "1024")) == 1
    # routing landed every bucket on its deterministic ring home
    routes = [e for e in events if e.get("kind") == "serve_route"]
    assert routes and all(e.get("ok") for e in routes)
    by_bucket = {}
    for e in routes:
        by_bucket.setdefault(e["bucket"], set()).add(e["worker"])
    for bucket, workers in by_bucket.items():
        assert workers == {router.ring_order(bucket, 2)[0]}, (
            bucket, workers,
        )
    # tenants rode through to the worker-side request evidence
    assert {e.get("tenant") for e in served} >= {"t1", "t2", "t3"}


def test_spill_on_backpressure_to_deterministic_sibling(tmp_path):
    """A slow, depth-1 HOME worker (env-narrowed slow_dispatch: the
    sibling stays fast) under a concurrent same-bucket burst: the
    router absorbs the worker's overload rejections by spilling to
    the bucket's deterministic ring sibling instead of bouncing
    clients, and every request still answers correctly."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import router

    primary, sibling = router.ring_order("scan|8192|-", 2)[:2]
    plan = json.dumps({"slow_dispatch": {
        "kernel": "scan", "delay_s": 1.2,
        "env": {"TPK_SERVE_WORKER_ID": str(primary)},
    }})
    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_WORKERS": "1",
        "TPK_SERVE_BATCH_WINDOW_MS": "0",
        "TPK_SERVE_QUEUE_MAX": "1",
        "TPK_FAULT_PLAN": plan,
    }) as (front, journal, _env):
        x, want = _scan_case()
        errors = []

        def one(seed):
            import random

            try:
                with serve_client.ServeClient(front,
                                              timeout_s=180) as c:
                    # generous retry budget: on a loaded CI host BOTH
                    # depth-1 workers can be transiently full and the
                    # ~0.1 s hints burn through the default 10 tries
                    # before the 1.2 s slow dispatch clears — the
                    # contract under test is the spill, not the
                    # client's give-up threshold
                    out = serve_client.dispatch_with_backpressure(
                        c, "scan", (x,), {}, max_rejections=60,
                        jitter=random.Random(seed),
                    )
                np.testing.assert_array_equal(out, want)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=one, args=(s,))
                   for s in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        assert not errors, errors
    events = _events(journal)
    spills = [e for e in events if e.get("kind") == "serve_spill"]
    assert spills, "a full home worker must spill, not bounce"
    assert all(e["from_worker"] == primary
               and e["to_worker"] == sibling for e in spills)
    assert any(e["reason"] == "overloaded" for e in spills)
    served = [e for e in events if e.get("kind") == "serve_request"
              and e.get("ok")]
    assert len(served) == 5


def test_drain_mid_burst_zero_drops_then_restart(tmp_path):
    """The rolling-restart chaos proof: drain one worker in the
    middle of a concurrent request burst — its buckets fail over to
    the ring sibling, the worker stops, and NOT ONE accepted request
    drops — then ``undrain`` restarts it and restores the ring."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import router

    primary = router.ring_order("scan|8192|-", 2)[0]
    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_BATCH_WINDOW_MS": "0",
    }) as (front, journal, env):
        x, want = _scan_case()
        errors, done = [], []
        stop_burst = threading.Event()

        def stream():
            try:
                with serve_client.ServeClient(front,
                                              timeout_s=180) as c:
                    # warm once, then stream until told to stop
                    np.testing.assert_array_equal(
                        c.dispatch("scan", x), want
                    )
                    while not stop_burst.is_set():
                        np.testing.assert_array_equal(
                            c.dispatch("scan", x), want
                        )
                        done.append(1)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=stream) for _ in range(3)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        while len(done) < 5 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert done, "burst never got going"
        r = _ctl(env, "drain", str(primary), "--wait", "30")
        assert r.returncode == 0, r.stdout + r.stderr
        # the fleet keeps serving while one worker is gone
        mid = len(done)
        deadline = time.monotonic() + 60
        while len(done) < mid + 5 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert len(done) > mid, "fleet stalled after drain"
        r = _ctl(env, "undrain", str(primary), "--wait", "90")
        assert r.returncode == 0, r.stdout + r.stderr
        deadline = time.monotonic() + 60
        post = len(done)
        while len(done) < post + 5 and time.monotonic() < deadline:
            time.sleep(0.1)
        stop_burst.set()
        for t in threads:
            t.join(120)
        assert not errors, errors

    events = _events(journal)
    drains = [e for e in events if e.get("kind") == "serve_drain"]
    assert [e["phase"] for e in drains] == ["begin", "undrain"]
    assert all(e["worker"] == primary for e in drains)
    # zero drops: every routed request answered ok, every
    # worker-served request ok
    routes = [e for e in events if e.get("kind") == "serve_route"]
    assert routes and all(e.get("ok") for e in routes)
    t_drain = next(e["t"] for e in drains if e["phase"] == "begin")
    t_undrain = next(e["t"] for e in drains if e["phase"] == "undrain")
    # +1 s: a forward that STARTED just before the drain op may
    # journal its serve_route just after it — not a violation
    drained_window = [
        e for e in routes if t_drain + 1.0 < e["t"] < t_undrain
    ]
    assert drained_window, "no traffic landed during the drain window"
    assert all(e["worker"] != primary for e in drained_window), (
        "requests routed to a draining worker"
    )


def test_wedged_worker_failover_and_cooldown(tmp_path):
    """The wedged-worker chaos headline: EVERY scan dispatch on the
    bucket's home worker wedges (env-narrowed ``wedge_dispatch``,
    times=0). The worker's own watchdog gives up after the
    requeue-once budget and answers ``kind: "wedged"``; the router
    spills to the sibling (loudly), puts the sick worker on a
    routing cooldown, and the client still gets the right answer —
    and the NEXT request routes straight to the sibling without
    re-feeding the wedge."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import router

    primary, sibling = router.ring_order("scan|8192|-", 2)[:2]
    plan = json.dumps({"wedge_dispatch": {
        "kernel": "scan", "times": 0,
        "env": {"TPK_SERVE_WORKER_ID": str(primary)},
    }})
    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_REQUEST_TIMEOUT_S": "2",
        "TPK_ROUTE_COOLDOWN_S": "120",
        "TPK_FAULT_PLAN": plan,
    }) as (front, journal, _env):
        x, want = _scan_case()
        with serve_client.ServeClient(front, timeout_s=180) as c:
            out = c.dispatch("scan", x)  # rides out the wedge
            np.testing.assert_array_equal(out, want)
            out = c.dispatch("scan", x)  # cooled: direct to sibling
            np.testing.assert_array_equal(out, want)
    events = _events(journal)
    spills = [e for e in events if e.get("kind") == "serve_spill"]
    assert any(e["reason"] == "wedged" and e["from_worker"] == primary
               and e["to_worker"] == sibling for e in spills)
    routes = [e for e in events if e.get("kind") == "serve_route"
              and e.get("kernel") == "scan"]
    assert [e.get("ok") for e in routes] == [True, True]
    # request 1 spilled after the wedge; request 2 routed directly to
    # the sibling (cooldown) — no second trip through the wedge
    assert routes[0]["worker"] == sibling
    assert routes[0]["spilled_from"] == primary
    assert routes[1]["worker"] == sibling
    assert routes[1]["spilled_from"] is None
    # the home worker's watchdog evidence is in the same journal
    assert any(e.get("kind") == "serve_request_requeued"
               for e in events)


def test_tenant_quota_priority_and_fleet_lifecycle(tmp_path):
    """Router admission: with a drained token bucket (tiny refill), a
    tenant's batch-priority requests are throttled FIRST (they must
    leave the 1 + burst/2 reserve) while interactive requests still
    pass, and a second tenant's bucket is untouched. Also the fleet
    operator loop: status shows router totals + per-worker ping
    payloads (depth, inflight, bucket ownership), a double
    start-fleet is refused rc 3, stop-fleet tears down."""
    from tpukernels.serve import client as serve_client

    with _fleet(tmp_path, n=1, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_ROUTE_TENANT_RATE": "0.001",
        "TPK_ROUTE_TENANT_BURST": "4",
    }) as (front, journal, env):
        x, want = _scan_case()
        hot_batch = serve_client.ServeClient(front, timeout_s=180,
                                             tenant="hot",
                                             priority="batch")
        hot_inter = serve_client.ServeClient(front, timeout_s=180,
                                             tenant="hot")
        cold = serve_client.ServeClient(front, timeout_s=180,
                                        tenant="cold")
        # tokens 4 -> batch needs 3: ok (3 left), ok (2 left)...
        np.testing.assert_array_equal(
            hot_batch.dispatch("scan", x), want)
        np.testing.assert_array_equal(
            hot_batch.dispatch("scan", x), want)
        # ...throttled at 2 < 3 — the interactive reserve holds
        with pytest.raises(serve_client.ServeRejected) as exc:
            hot_batch.dispatch("scan", x)
        assert 0 < exc.value.retry_after_s <= 5.0
        # the same tenant's INTERACTIVE request still passes (2 >= 1)
        np.testing.assert_array_equal(
            hot_inter.dispatch("scan", x), want)
        # another tenant's bucket is untouched
        np.testing.assert_array_equal(cold.dispatch("scan", x), want)
        # an unknown priority is a bad request, not a crash
        weird = serve_client.ServeClient(front, timeout_s=60,
                                         priority="urgent")
        with pytest.raises(serve_client.ServeError, match="priority"):
            weird.dispatch("scan", x)
        for c in (hot_batch, hot_inter, cold, weird):
            c.close()

        r = _ctl(env, "status")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "fleet UP" in r.stdout and "throttled=1" in r.stdout
        assert "worker0" in r.stdout and "scan|8192|-" in r.stdout
        assert "inflight=" in r.stdout
        r = _ctl(env, "start-fleet", "1", "--wait", "30")
        assert r.returncode == 3, r.stdout + r.stderr
        assert "already running" in r.stdout
    events = _events(journal)
    throttled = [e for e in events
                 if e.get("kind") == "serve_tenant_throttled"]
    assert len(throttled) == 1
    assert throttled[0]["tenant"] == "hot"
    assert throttled[0]["priority"] == "batch"
    served = [e for e in events if e.get("kind") == "serve_request"]
    assert sorted(e.get("tenant") or "-" for e in served) == [
        "cold", "hot", "hot", "hot"
    ]
    # after stop-fleet, status reports DOWN
    r = _ctl(env, "status")
    assert r.returncode == 1 and "DOWN" in r.stdout


def test_loadgen_fleet_tenants_fairness_slo_e2e(tmp_path):
    """The fairness e2e under a skewed mix: a hot tenant hammering
    the fleet through the front socket gets throttled at the
    router's token buckets while a steady tenant's every request is
    served; the steady tenant's p99 verdict lands as its OWN
    validated ``scan@steady`` row in slo.json, and ``obs_report
    --check`` keeps its rc contract (rc 0 — throttling is pacing,
    not a breach)."""
    slo_dir = tmp_path / "slo"
    slo_dir.mkdir()
    with _fleet(tmp_path, n=2, env_extra={
        "TPK_ROUTE_TENANT_RATE": "3",
        "TPK_ROUTE_TENANT_BURST": "6",
    }) as (front, journal, env):
        lg = os.path.join(REPO, "tools", "loadgen.py")
        lg_env = dict(env)
        lg_env["TPK_SLO_DIR"] = str(slo_dir)
        lg_env["TPK_HEALTH_JOURNAL"] = journal
        hot = subprocess.Popen(
            [sys.executable, lg, "--serve", front, "--kernel", "scan",
             "--arrivals", "poisson", "--seed", "7", "--requests",
             "15", "--rate", "30", "--tenant", "hot"],
            cwd=REPO, env=lg_env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        steady = subprocess.run(
            [sys.executable, lg, "--serve", front, "--kernel", "scan",
             "--arrivals", "poisson", "--seed", "3", "--requests",
             "25", "--rate", "2", "--tenant", "steady"],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env=lg_env,
        )
        hot_out, hot_err = hot.communicate(timeout=300)
        assert steady.returncode == 0, steady.stdout + steady.stderr
        assert hot.returncode == 0, hot_out + hot_err
        assert "(SERVED)" in steady.stdout
    events = _events(journal)
    # the hot tenant was throttled (its retries absorbed the pacing)
    throttled = [e for e in events
                 if e.get("kind") == "serve_tenant_throttled"]
    assert any(e["tenant"] == "hot" for e in throttled)
    # every steady request (25 + 1 warm) was served — zero drops
    steady_served = [e for e in events
                     if e.get("kind") == "serve_request"
                     and e.get("tenant") == "steady"]
    assert len(steady_served) == 26
    assert all(e.get("ok") for e in steady_served)
    # per-tenant rows landed in slo.json under the base kernel's
    # target; the steady tail is clean
    with open(slo_dir / "slo.json") as f:
        entries = json.load(f)["entries"]
    steady_row = entries["scan@steady|probe|cpu"]
    assert steady_row["verdict"] == "ok"
    assert steady_row["run"]["tenant"] == "steady"
    assert steady_row["jax"] is not None
    assert "scan@hot|probe|cpu" in entries
    # the gating surface is unchanged: rc 0
    chk_env = _scrubbed_env(None)
    chk_env["TPK_SLO_DIR"] = str(slo_dir)
    chk = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=chk_env,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr


def test_fleet_mesh_tier_oversized_request(tmp_path):
    """ISSUE 20 acceptance: client -> router -> worker -> sharded
    dispatch -> traced response. An oversized scan (4x the avatar)
    through the FLEET front door lands on a worker whose fake 4-device
    inventory admits the mesh tier: the serve_request carries
    mesh_shape [4], the bucket is the mesh bucket, and with tracing on
    the worker's journal holds the dispatch span stamped with the mesh
    geometry."""
    from tpukernels.serve import client as serve_client

    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_BATCH_WINDOW_MS": "0",
        "TPK_TRACE": "1",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }) as (front, journal, _env):
        x = (np.arange(32768) % 31).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        with serve_client.ServeClient(front, timeout_s=180) as c:
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
            # same mesh bucket again: the executable memo serves it
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
    events = _events(journal)
    served = [e for e in events if e.get("kind") == "serve_request"
              and e.get("kernel") == "scan"]
    assert len(served) == 2, served
    for e in served:
        assert e["ok"], e
        assert e["mesh_shape"] == [4], e
        assert e["bucket"].endswith("|mesh4"), e["bucket"]
        assert not e["bucketed"], e
    # traced response: the worker's dispatch span carries the mesh
    # geometry inside the serve span
    spans = [e for e in events if e.get("kind") == "span"
             and e.get("name", "").endswith("dispatch/scan")]
    assert any(e.get("mesh") == "4" for e in spans), spans
    # exactly one compile for the mesh bucket across the whole fleet
    # (the one-compile-per-bucket fleet rule extends to mesh buckets)
    aot = _aot_bucket_events(events, "scan", "32768")
    assert len([e for e in aot if e["kind"] == "aot_miss"]) == 1, aot
