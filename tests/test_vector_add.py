import jax.numpy as jnp
import numpy as np
import pytest

from tpukernels.kernels.vector_add import saxpy, saxpy_reference


@pytest.mark.parametrize("n", [128, 1024, 2**14, 2**20, 1000, 7])
def test_saxpy_matches_reference(rng, n):
    x = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)
    y = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)
    out = saxpy(2.5, x, y)
    # atol absorbs the 1-ulp FMA-vs-unfused difference between the
    # interpret-mode kernel and the jnp oracle on CPU
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(saxpy_reference(2.5, x, y)),
        rtol=1e-6, atol=1e-6,
    )


def test_saxpy_alpha_zero(rng):
    x = jnp.asarray(rng.standard_normal(512), dtype=jnp.float32)
    y = jnp.asarray(rng.standard_normal(512), dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(saxpy(0.0, x, y)), np.asarray(y))


def test_saxpy_does_not_clobber_live_y(rng):
    # the kernel aliases y into its output (input_output_aliases);
    # functional semantics require the caller's y to survive when it
    # is still live after the call. Real buffer aliasing only happens
    # on the compiled path, so force interpret=False when a TPU is
    # attached (interpret mode on CPU hosts cannot exercise it).
    import jax

    modes = [None]
    if jax.default_backend() != "cpu":
        modes.append(False)
    for interpret in modes:
        x = jnp.asarray(rng.standard_normal(2048), dtype=jnp.float32)
        y = jnp.asarray(rng.standard_normal(2048), dtype=jnp.float32)
        y_before = np.asarray(y).copy()
        out = saxpy(3.0, x, y, interpret=interpret)
        np.testing.assert_array_equal(np.asarray(y), y_before)
        np.testing.assert_allclose(
            np.asarray(out), 3.0 * np.asarray(x) + y_before,
            rtol=1e-6, atol=1e-6,
        )
