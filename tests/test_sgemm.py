import jax.numpy as jnp
import numpy as np
import pytest

from tpukernels.kernels.sgemm import _pick_block, sgemm, sgemm_reference


@pytest.mark.parametrize(
    "dim,preferred,align,expect",
    [
        # benchmark-scale shapes the kernel tests never reach: the
        # picker must neither collapse to degenerate tiles (strict
        # padding minimization) nor pad ~2x (blind preferred blocks)
        (2176, 1024, 128, 768),   # not bk=128 (17 K-steps), pad 6%
        (2176, 2048, 128, 1152),  # not bn=2048 (would pad to 4096)
        (1042, 256, 8, 216),      # not bm=8 (6% MXU row utilization)
        (1023, 1024, 128, 1024),  # one full-K step, not 8x bk=128
        (3072, 2048, 128, 1536),  # exact divisor beats bigger+pad
        # aligned shapes keep full-size blocks
        (1024, 1024, 128, 1024),
        (2048, 2048, 128, 2048),
        (65536, 256, 8, 256),
        # small dims: single (possibly sub-align) block
        (100, 256, 8, 104),
        (100, 2048, 128, 100),
    ],
)
def test_pick_block(dim, preferred, align, expect):
    b = _pick_block(dim, preferred, align)
    assert b == expect
    assert b <= preferred and (b <= align or b % align == 0)


# Tolerances are per-precision contracts: 'float32' (bf16_6x) must be
# fp32-faithful; 'high' (bf16_3x, the default) must sit inside the C
# golden checker's acceptance bar (c/sgemm.c: rtol 1e-4, atol 1e-3).
@pytest.mark.parametrize(
    "precision,rtol,atol",
    [("float32", 2e-5, 2e-4), ("high", 1e-4, 1e-3)],
)
@pytest.mark.parametrize(
    "m,n,k",
    [
        (128, 128, 128),
        (256, 512, 1024),
        (512, 512, 512),
        (100, 200, 300),  # unaligned → padding path
    ],
)
def test_sgemm_matches_reference(rng, m, n, k, precision, rtol, atol):
    a = jnp.asarray(rng.standard_normal((m, k)), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n)), dtype=jnp.float32)
    c = jnp.asarray(rng.standard_normal((m, n)), dtype=jnp.float32)
    out = sgemm(1.5, a, b, 0.5, c, precision=precision)
    ref = sgemm_reference(1.5, a, b, 0.5, c)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=rtol, atol=atol)


def test_sgemm_beta_zero_ignores_c_nans(rng):
    a = jnp.asarray(rng.standard_normal((128, 128)), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal((128, 128)), dtype=jnp.float32)
    c = jnp.full((128, 128), jnp.nan, dtype=jnp.float32)
    out = sgemm(1.0, a, b, 0.0, c)
    # beta==0 still multiplies 0*NaN = NaN under IEEE; the C oracle does
    # the same, so parity means NaN propagates. Check against reference.
    ref = sgemm_reference(1.0, a, b, 0.0, c)
    assert np.isnan(np.asarray(out)).all() == np.isnan(np.asarray(ref)).all()


def test_tile_preference_knobs(rng, monkeypatch):
    """TPK_SGEMM_{BM,BN,BK} override the tile PREFERENCES handed to
    _pick_block (for tools/sgemm_tune.py sweeps): results must stay
    correct under any knob value, alignment stays with the picker,
    and garbage fails loudly like every other TPK_* knob."""
    m, n, k = 96, 160, 130
    a = rng.standard_normal((m, k), dtype=np.float32)
    b = rng.standard_normal((k, n), dtype=np.float32)
    c = rng.standard_normal((m, n), dtype=np.float32)
    want = sgemm_reference(1.5, a, b, -0.5, c)

    monkeypatch.setenv("TPK_SGEMM_BM", "32")
    monkeypatch.setenv("TPK_SGEMM_BN", "128")
    monkeypatch.setenv("TPK_SGEMM_BK", "128")
    got = sgemm(1.5, a, b, -0.5, c)
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)

    for bad in ("0", "-8", "abc"):
        monkeypatch.setenv("TPK_SGEMM_BM", bad)
        with pytest.raises(ValueError, match="TPK_SGEMM_BM"):
            sgemm(1.0, a, b, 0.0, c)


@pytest.mark.parametrize("depth", [2, 3])
@pytest.mark.parametrize("precision,rtol,atol",
                         [("float32", 2e-5, 2e-4), ("high", 1e-4, 1e-3)])
def test_sgemm_pipelined_depth_matches_reference(
    rng, monkeypatch, depth, precision, rtol, atol
):
    """The manual ping-pong DMA pipeline (TPK_SGEMM_DEPTH >= 2) is a
    different program (pl.ANY operands + slab ring) and must meet the
    same per-precision golden contracts as the BlockSpec path — with a
    small bk so the K stream is genuinely multi-block (nk=3) and the
    prologue/prefetch/slot-reuse schedule is exercised."""
    monkeypatch.setenv("TPK_SGEMM_DEPTH", str(depth))
    monkeypatch.setenv("TPK_SGEMM_BK", "128")
    m, k, n = 128, 384, 256
    a = jnp.asarray(rng.standard_normal((m, k)), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n)), dtype=jnp.float32)
    c = jnp.asarray(rng.standard_normal((m, n)), dtype=jnp.float32)
    out = sgemm(1.5, a, b, 0.5, c, precision=precision)
    ref = sgemm_reference(1.5, a, b, 0.5, c)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=rtol, atol=atol
    )


@pytest.mark.parametrize("depth", [1, 2])
def test_sgemm_dimension_order_matches_reference(rng, monkeypatch, depth):
    """TPK_SGEMM_ORDER=ji permutes the grid (which operand
    re-streams); results must be identical on both the BlockSpec and
    the pipelined path, unaligned shapes included."""
    monkeypatch.setenv("TPK_SGEMM_ORDER", "ji")
    monkeypatch.setenv("TPK_SGEMM_DEPTH", str(depth))
    if depth > 1:
        monkeypatch.setenv("TPK_SGEMM_BK", "128")
    m, k, n = 100, 300, 200  # unaligned -> padding path
    a = jnp.asarray(rng.standard_normal((m, k)), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n)), dtype=jnp.float32)
    c = jnp.asarray(rng.standard_normal((m, n)), dtype=jnp.float32)
    out = sgemm(1.0, a, b, -0.5, c)
    ref = sgemm_reference(1.0, a, b, -0.5, c)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-3
    )


def test_sgemm_bad_pipeline_knobs_fail_loud(rng, monkeypatch):
    a = jnp.zeros((16, 16), jnp.float32)
    monkeypatch.setenv("TPK_SGEMM_DEPTH", "abc")
    with pytest.raises(ValueError, match="TPK_SGEMM_DEPTH"):
        sgemm(1.0, a, a, 0.0, a)
    monkeypatch.delenv("TPK_SGEMM_DEPTH")
    monkeypatch.setenv("TPK_SGEMM_ORDER", "kij")
    with pytest.raises(ValueError, match="TPK_SGEMM_ORDER"):
        sgemm(1.0, a, a, 0.0, a)
