import jax.numpy as jnp
import numpy as np
import pytest

from tpukernels.kernels.sgemm import sgemm, sgemm_reference


# Tolerances are per-precision contracts: 'float32' (bf16_6x) must be
# fp32-faithful; 'high' (bf16_3x, the default) must sit inside the C
# golden checker's acceptance bar (c/sgemm.c: rtol 1e-4, atol 1e-3).
@pytest.mark.parametrize(
    "precision,rtol,atol",
    [("float32", 2e-5, 2e-4), ("high", 1e-4, 1e-3)],
)
@pytest.mark.parametrize(
    "m,n,k",
    [
        (128, 128, 128),
        (256, 512, 1024),
        (512, 512, 512),
        (100, 200, 300),  # unaligned → padding path
    ],
)
def test_sgemm_matches_reference(rng, m, n, k, precision, rtol, atol):
    a = jnp.asarray(rng.standard_normal((m, k)), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n)), dtype=jnp.float32)
    c = jnp.asarray(rng.standard_normal((m, n)), dtype=jnp.float32)
    out = sgemm(1.5, a, b, 0.5, c, precision=precision)
    ref = sgemm_reference(1.5, a, b, 0.5, c)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=rtol, atol=atol)


def test_sgemm_beta_zero_ignores_c_nans(rng):
    a = jnp.asarray(rng.standard_normal((128, 128)), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal((128, 128)), dtype=jnp.float32)
    c = jnp.full((128, 128), jnp.nan, dtype=jnp.float32)
    out = sgemm(1.0, a, b, 0.0, c)
    # beta==0 still multiplies 0*NaN = NaN under IEEE; the C oracle does
    # the same, so parity means NaN propagates. Check against reference.
    ref = sgemm_reference(1.0, a, b, 0.0, c)
    assert np.isnan(np.asarray(out)).all() == np.isnan(np.asarray(ref)).all()
