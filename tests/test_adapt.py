"""CPU suite for the traffic-adaptive bucket optimizer
(docs/SERVING.md §adaptive buckets; ROADMAP item 5).

Pure-math units for the proposal model (pad projection mirroring the
bucketing arithmetic, split/merge selection under the
waste-saved-per-compile cost model, the PROMOTE_MARGIN + strict-p99
promotion gate), the fail-loud TPK_ADAPT_* knob parses, the
journal miners (shape mix, pad histogram, traffic order, canary-side
measurement), the adapt.json artifact discipline (atomic write, loud
torn/stale/jax-mismatch rejection), the multi-avatar bucketing +
reload() pickup seam, loadgen's replay-spec lane validation, and the
closed loop END TO END on CPU: seeded loadgen drives a skewed shape
mix at a coarse incumbent table, ``serve_optimize propose`` mines it
into a split candidate, the canary replays the frozen mix against
both tables at identical seeds and PROMOTES, and a second serving run
against the promoted table shows ``serve.bucket_pad_frac`` below
``TPK_ADAPT_PAD_TARGET`` in ``obs_report`` — while a candidate that
cannot win is REJECTED with the incumbent table file untouched byte
for byte.
"""

import json
import os
import subprocess
import sys

import pytest

from test_distributed import _scrubbed_env
from test_serve import _daemon

from tpukernels.serve import adapt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
    return out


def _spec(n, statics=None):
    """A vector_add-shaped avatar spec at array length ``n``."""
    return {
        "args": [["f32", []], ["f32", [n]], ["f32", [n]]],
        "statics": dict(statics or {}),
    }


def _row(shapes, dtypes=None, count=1, pad_sum=0.0, bucketed=None,
         kernel="vector_add"):
    return {
        "kernel": kernel,
        "shapes": [tuple(s) for s in shapes],
        "dtypes": list(dtypes or ["float32"] * len(shapes)),
        "count": count,
        "pad_frac_sum": pad_sum,
        "bucketed": count if bucketed is None else bucketed,
    }


# ---------------------------------------------------------------- #
# pure projection math                                             #
# ---------------------------------------------------------------- #

def test_pad_frac_for_mirrors_bucketing_arithmetic():
    spec = _spec(2048)
    # 1 - (1 + 256 + 256) / (1 + 2048 + 2048), scalar counted as one
    pf = adapt.pad_frac_for([(), (256,), (256,)], ["float32"] * 3, spec)
    assert pf == pytest.approx(1.0 - 513 / 4097)
    # exact fit is 0.0, not merely small
    assert adapt.pad_frac_for(
        [(), (2048,), (2048,)], ["float32"] * 3, spec) == 0.0
    # pad-up never down: any dim over the avatar is a non-match
    assert adapt.pad_frac_for(
        [(), (4096,), (4096,)], ["float32"] * 3, spec) is None
    # rank mismatch
    assert adapt.pad_frac_for(
        [(), (256, 1), (256,)], ["float32"] * 3, spec) is None
    # dtype mismatch
    assert adapt.pad_frac_for(
        [(), (256,), (256,)], ["float32", "int32", "float32"],
        spec) is None
    # arg-count mismatch
    assert adapt.pad_frac_for([(256,)], ["float32"], spec) is None


def test_project_request_weighted_mean_native_and_memo_slots():
    table = {"k": {"args": [["f32", [100]]], "statics": {}}}
    mix = {"k": [
        _row([(50,)], count=3, kernel="k"),    # pad 0.5
        _row([(100,)], count=1, kernel="k"),   # exact
        _row([(200,)], count=2, kernel="k"),   # over: native
    ]}
    out = adapt.project(table, mix, max_pad=0.6)
    assert out["bucketed"] == 4 and out["native"] == 2
    assert out["pad_frac"] == pytest.approx((0.5 * 3) / 4)
    assert out["buckets"] == 1  # one (kernel, avatar) program occupied
    # the TPK_SERVE_MAX_PAD_FRAC cap sends over-padded traffic native
    capped = adapt.project(table, mix, max_pad=0.4)
    assert capped["bucketed"] == 1 and capped["native"] == 5
    assert capped["pad_frac"] == 0.0


def test_propose_splits_hot_shape_and_keeps_carrying_avatar():
    table = {"k": _spec(1024, statics={"s": 1})}
    mix = {"k": [
        _row([(), (128,), (128,)], count=90, kernel="k"),
        _row([(), (1024,), (1024,)], count=10, kernel="k"),  # exact
    ]}
    res = adapt.propose(mix, table, target=0.25, max_pad=1.0)
    splits = [p for p in res["proposals"] if p["action"] == "split"]
    assert len(splits) == 1 and splits[0]["kernel"] == "k"
    # the new avatar sits exactly at the hot observed shapes, statics
    # borrowed from the incumbent avatar
    assert splits[0]["spec"]["args"] == [
        ["f32", []], ["f32", [128]], ["f32", [128]]]
    assert splits[0]["spec"]["statics"] == {"s": 1}
    assert splits[0]["compiles"] == 1 and splits[0]["waste_saved"] > 0
    # the (1024,) avatar still carries the exact-fit traffic: never
    # merged away
    assert [p for p in res["proposals"] if p["action"] == "merge"] == []
    assert len(res["table"]["k"]) == 2
    assert res["before"]["pad_frac"] > 0.25
    assert res["after"]["pad_frac"] < 0.25 and res["after"]["native"] == 0
    # the incumbent was deep-copied, never mutated
    assert isinstance(table["k"], dict)


def test_propose_merges_only_zero_traffic_avatars():
    table = {"k": [_spec(1024), _spec(512)]}
    mix = {"k": [_row([(), (512,), (512,)], count=8, kernel="k")]}
    res = adapt.propose(mix, table, target=0.25, max_pad=1.0)
    merges = [p for p in res["proposals"] if p["action"] == "merge"]
    assert len(merges) == 1 and merges[0]["compiles"] == -1
    assert merges[0]["spec"]["args"][1] == ["f32", [1024]]
    assert res["table"]["k"] == [_spec(512)]
    # a kernel is never left avatar-less, even with zero traffic
    lone = adapt.propose({}, {"k": _spec(1024)}, target=0.25)
    assert lone["proposals"] == []
    assert lone["table"]["k"] == _spec(1024)


def test_split_ranking_is_waste_saved_per_compile():
    table = {"a": _spec(1000), "b": _spec(1000)}
    mix = {
        "a": [_row([(), (10,), (10,)], count=100, kernel="a")],
        "b": [_row([(), (10,), (10,)], count=5, kernel="b")],
    }
    # both kernels pay the same per-request pad; "a" carries 20x the
    # traffic, so its split saves 20x the waste per compile and must
    # be applied first
    cands = adapt._split_candidates(table, mix, max_pad=1.0)
    assert {c["kernel"] for c in cands} == {"a", "b"}
    by = {c["kernel"]: c for c in cands}
    assert by["a"]["score"] > by["b"]["score"]
    res = adapt.propose(mix, table, target=0.001, max_pad=1.0,
                        max_splits=1)
    splits = [p for p in res["proposals"] if p["action"] == "split"]
    assert [p["kernel"] for p in splits] == ["a"]  # budget: best only


def test_judge_canary_promotion_gate():
    m = 0.03
    # measurement missing on either side: never promote
    v = adapt.judge_canary({}, {"pad_frac": 0.5, "p99_s": 0.1},
                           margin=m)
    assert not v["promote"] and v["reason"] == "no-measurement"
    # an incumbent already at zero pad has nothing to save
    v = adapt.judge_canary({"pad_frac": 0.0, "p99_s": 0.1},
                           {"pad_frac": 0.0, "p99_s": 0.2}, margin=m)
    assert not v["promote"] and "nothing-to-save" in v["reason"]
    # pad win at-or-below the margin: rejected
    v = adapt.judge_canary({"pad_frac": 0.98, "p99_s": 0.1},
                           {"pad_frac": 1.0, "p99_s": 0.2}, margin=m)
    assert not v["promote"] and "margin" in v["reason"]
    assert v["pad_win"] == pytest.approx(0.02)
    # pad win but p99 not STRICTLY better: rejected
    v = adapt.judge_canary({"pad_frac": 0.1, "p99_s": 0.2},
                           {"pad_frac": 0.9, "p99_s": 0.2}, margin=m)
    assert not v["promote"] and "p99 did not win" in v["reason"]
    # both gates pass: promoted
    v = adapt.judge_canary({"pad_frac": 0.1, "p99_s": 0.1},
                           {"pad_frac": 0.9, "p99_s": 0.2}, margin=m)
    assert v["promote"] and v["pad_win"] == pytest.approx(8 / 9)
    # the default margin is the tuning layer's — one authority
    from tpukernels.tuning import runner

    v = adapt.judge_canary({"pad_frac": 0.1, "p99_s": 0.1},
                           {"pad_frac": 0.9, "p99_s": 0.2})
    assert v["margin"] == runner.PROMOTE_MARGIN == 0.03


def test_adapt_knobs_fail_loud(monkeypatch):
    monkeypatch.delenv("TPK_ADAPT_PAD_TARGET", raising=False)
    monkeypatch.delenv("TPK_ADAPT_MIN_REQUESTS", raising=False)
    assert adapt.pad_target() == adapt.DEFAULT_PAD_TARGET == 0.25
    assert adapt.min_requests() == adapt.DEFAULT_MIN_REQUESTS == 50
    monkeypatch.setenv("TPK_ADAPT_PAD_TARGET", "0.1")
    assert adapt.pad_target() == 0.1
    for bad in ("0", "1.5", "-0.2", "abc"):
        monkeypatch.setenv("TPK_ADAPT_PAD_TARGET", bad)
        with pytest.raises(ValueError, match="TPK_ADAPT_PAD_TARGET"):
            adapt.pad_target()
    monkeypatch.delenv("TPK_ADAPT_PAD_TARGET", raising=False)
    monkeypatch.setenv("TPK_ADAPT_MIN_REQUESTS", "20")
    assert adapt.min_requests() == 20
    for bad in ("0", "-3", "x"):
        monkeypatch.setenv("TPK_ADAPT_MIN_REQUESTS", bad)
        with pytest.raises(ValueError, match="TPK_ADAPT_MIN_REQUESTS"):
            adapt.min_requests()


# ---------------------------------------------------------------- #
# journal mining                                                   #
# ---------------------------------------------------------------- #

def _req(kernel, shapes, ok=True, pad_frac=0.0, bucketed=True):
    return {"kind": "serve_request", "kernel": kernel, "ok": ok,
            "shapes": [list(s) for s in shapes],
            "dtypes": ["float32"] * len(shapes),
            "pad_frac": pad_frac, "bucketed": bucketed}


def test_shape_mix_counts_ok_requests_only_sorted_by_weight():
    events = (
        [_req("vector_add", [(256,)], pad_frac=0.5)] * 3
        + [_req("vector_add", [(1024,)])]
        + [_req("vector_add", [(256,)], ok=False)] * 5  # tell us nothing
        + [_req("scan", [(64,)])] * 2
        + [{"kind": "bench", "kernel": "vector_add"}]
        + [{"kind": "serve_request", "ok": True}]  # malformed: dropped
    )
    mix = adapt.shape_mix(events)
    assert adapt.mix_requests(mix) == 6
    rows = mix["vector_add"]
    assert [r["count"] for r in rows] == [3, 1]  # heaviest first
    assert rows[0]["shapes"] == [(256,)]
    assert rows[0]["pad_frac_sum"] == pytest.approx(1.5)
    assert mix["scan"][0]["count"] == 2


def test_traffic_order_ranks_by_frequency_with_registry_tail():
    events = ([_req("vector_add", [(8,)])] * 4
              + [_req("scan", [(8,)])] * 2
              + [_req("scan", [(8,)], ok=False)]
              + [_req("unknown_kernel", [(8,)])])
    known = ["scan", "sgemm", "vector_add"]
    ordered, counts = adapt.traffic_order(events, known)
    assert ordered == ["vector_add", "scan", "sgemm"]
    assert counts == {"vector_add": 4, "scan": 3}
    # no evidence: registry order kept, empty counts = fallback cue
    ordered, counts = adapt.traffic_order([], known)
    assert ordered == known and counts == {}


def test_histogram_pad_frac_reads_last_metrics_event():
    hist = {"serve.bucket_pad_frac": {"count": 4, "sum": 2.0}}
    old = {"serve.bucket_pad_frac": {"count": 2, "sum": 1.8}}
    events = [
        {"kind": "metrics", "histograms": old},
        {"kind": "metrics", "histograms": {}},
        {"kind": "metrics", "histograms": hist},
    ]
    assert adapt.histogram_pad_frac(events) == pytest.approx(0.5)
    assert adapt.histogram_pad_frac(events[:1]) == pytest.approx(0.9)
    assert adapt.histogram_pad_frac([]) is None


def test_replay_entries_heaviest_groups_with_avatar_statics():
    table = {"a": _spec(1024, statics={"rows": 8}), "b": _spec(512)}
    mix = {
        "a": [_row([(), (128,), (128,)], count=9, kernel="a"),
              _row([(), (64,), (64,)], count=2, kernel="a")],
        "b": [_row([(), (32,), (32,)], count=5, kernel="b")],
        "orphan": [_row([(7,)], count=99, kernel="orphan")],
    }
    entries = adapt.replay_entries(mix, table, top=2)
    # the orphan kernel has no avatar: it can never bucket, so it is
    # not replay traffic; the top-2 cap keeps the heaviest groups
    assert [(e["kernel"], e["weight"]) for e in entries] == [
        ("a", 9), ("b", 5)]
    assert entries[0]["args"] == [["f32", []], ["f32", [128]],
                                  ["f32", [128]]]
    assert entries[0]["statics"] == {"rows": 8}


def test_measured_side_weighs_slo_probe_p99s():
    events = (
        [_req("a", [(8,)], pad_frac=0.5)]
        + [_req("a", [(8,)], pad_frac=0.0, bucketed=False)]
        + [_req("a", [(8,)], ok=False, pad_frac=0.9)]  # excluded
        + [{"kind": "slo_probe",
            "verdicts": {"x": {"p99_s": 0.3, "count": 1}}},
           {"kind": "slo_probe",
            "verdicts": {"x": {"p99_s": 0.1, "count": 3},
                         "y": {"p99_s": 0.2, "count": 1},
                         "z": {"p99_s": None, "count": 4}}}]
    )
    side = adapt.measured_side(events)
    assert side["requests"] == 2 and side["bucketed"] == 1
    assert side["pad_frac"] == pytest.approx(0.25)
    # last slo_probe wins, request-weighted over measurable verdicts
    assert side["p99_s"] == pytest.approx((0.1 * 3 + 0.2) / 4)
    empty = adapt.measured_side([])
    assert empty["pad_frac"] is None and empty["p99_s"] is None


# ---------------------------------------------------------------- #
# the persisted candidate artifact                                 #
# ---------------------------------------------------------------- #

def _result(table):
    proj = {"pad_frac": 0.5, "bucketed": 6, "native": 0, "buckets": 1}
    return {"before": dict(proj), "after": dict(proj),
            "proposals": [], "table": table}


def _mix_one(kernel="vector_add", n=256, count=6):
    return {kernel: [_row([(), (n,), (n,)], count=count,
                          kernel=kernel)]}


def test_candidate_artifact_validation(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("TPK_ADAPT_DIR", str(tmp_path))
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(tmp_path / "j.jsonl"))
    adapt.reset()
    table = {"vector_add": _spec(512)}
    p = adapt.record_candidate(_result(table), _mix_one(), 0.25,
                               jax_version="not-this-jax")
    assert p == str(tmp_path / "adapt.json")
    data = json.load(open(p))
    assert data["status"] == "proposed" and data["canary"] is None
    assert data["requests_mined"] == 6
    # the frozen replay spec rides in the artifact
    assert data["replay"][0]["kernel"] == "vector_add"
    assert data["replay"][0]["weight"] == 6
    # unvalidated read serves the CLI's `show`
    assert adapt.load(validate=False)["table"] == table
    # jax-version mismatch: rejected loudly, never canaried
    assert adapt.load() is None
    err = capsys.readouterr().err
    assert "adapt candidate rejected" in err and "not-this-jax" in err
    evs = _events(tmp_path / "j.jsonl")
    assert any(e["kind"] == "adapt_rejected" for e in evs)

    import jax

    adapt.reset()
    adapt.record_candidate(_result(table), _mix_one(), 0.25,
                           jax_version=jax.__version__)
    good = adapt.load()
    assert good is not None and good["table"] == table

    # stale: a commit touching the serve sources postdates the sha
    data = json.load(open(p))
    data["source_sha"] = "0" * 40
    with open(p, "w") as f:
        json.dump(data, f)
    adapt.reset()
    assert adapt.load() is None
    assert "stale" in capsys.readouterr().err

    # torn mid-write: reads as absent, cold behavior not a crash
    with open(p, "w") as f:
        f.write('{"status": "propo')
    adapt.reset()
    assert adapt.load() is None

    # malformed (no table): rejected before any validation
    with open(p, "w") as f:
        json.dump({"status": "proposed", "jax": jax.__version__}, f)
    adapt.reset()
    assert adapt.load(validate=False) is None
    assert "malformed" in capsys.readouterr().err


def test_promote_writes_the_stable_buckets_path(tmp_path, monkeypatch):
    monkeypatch.setenv("TPK_ADAPT_DIR", str(tmp_path / "deep" / "d"))
    table = {"vector_add": [_spec(512), _spec(64)]}
    bp = adapt.promote(table)
    assert bp == adapt.buckets_path()
    assert json.load(open(bp)) == table


# ---------------------------------------------------------------- #
# multi-avatar bucketing + the reload() pickup seam                #
# ---------------------------------------------------------------- #

def test_bucket_for_multi_avatar_picks_min_pad(monkeypatch):
    import numpy as np

    from tpukernels.serve import bucketing

    monkeypatch.setenv("TPK_SERVE_BUCKETS", json.dumps(
        {"vector_add": [_spec(2048), _spec(256)]}))
    bucketing.reload()
    ops = (np.float32(1), np.ones(256, np.float32),
           np.ones(256, np.float32))
    spec, pad = bucketing.bucket_for("vector_add", ops, {})
    assert spec is not None and pad == 0.0
    assert spec["args"][1][1] == [256]  # the cheaper avatar won
    ops = (np.float32(1), np.ones(1200, np.float32),
           np.ones(1200, np.float32))
    spec, pad = bucketing.bucket_for("vector_add", ops, {})
    assert spec is not None and spec["args"][1][1] == [2048]
    assert 0.0 < pad <= 0.5  # under the TPK_SERVE_MAX_PAD_FRAC cap
    ops = (np.float32(1), np.ones(4096, np.float32),
           np.ones(4096, np.float32))
    spec, reason = bucketing.bucket_for("vector_add", ops, {})
    assert spec is None and isinstance(reason, str)


def test_bucketing_reload_picks_up_rewritten_file(tmp_path,
                                                  monkeypatch):
    from tpukernels.serve import bucketing

    table_path = tmp_path / "buckets.json"
    table_path.write_text(json.dumps({"vector_add": _spec(512)}))
    monkeypatch.setenv("TPK_SERVE_BUCKETS", str(table_path))
    bucketing.reload()
    assert bucketing.kernel_specs("vector_add") == [_spec(512)]
    # a promotion rewrites the FILE behind the unchanged env value:
    # invisible until reload() — undrain's hook — busts the cache
    table_path.write_text(json.dumps({"vector_add": [_spec(64)]}))
    assert bucketing.kernel_specs("vector_add") == [_spec(512)]
    bucketing.reload()
    assert bucketing.kernel_specs("vector_add") == [_spec(64)]
    # a reload onto a malformed table raises AND keeps serving the
    # last-good table — an undrain must not wedge the fleet
    table_path.write_text("{not json")
    with pytest.raises(ValueError):
        bucketing.reload()
    assert bucketing.kernel_specs("vector_add") == [_spec(64)]


# ---------------------------------------------------------------- #
# loadgen's replay-spec lane (usage + validation)                  #
# ---------------------------------------------------------------- #

def _loadgen(tmp_path, *args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         *args],
        capture_output=True, text=True, timeout=60, cwd=REPO,
        env=_scrubbed_env(None),
    )


def test_loadgen_replay_spec_usage_errors(tmp_path):
    spec = tmp_path / "replay.json"
    spec.write_text(json.dumps({"entries": [
        {"kernel": "vector_add",
         "args": [["f32", []], ["f32", [8]], ["f32", [8]]],
         "statics": {}, "weight": 2}]}))
    # a replay spec only makes sense against a daemon
    r = _loadgen(tmp_path, "--shapes", str(spec), "--simulate", "5",
                 "--requests", "5")
    assert r.returncode == 2 and "requires --serve" in r.stderr
    # the file IS the mix: --kernel/--mix don't combine
    r = _loadgen(tmp_path, "--serve", "/nonexistent.sock", "--shapes",
                 str(spec), "--kernel", "scan", "--requests", "5")
    assert r.returncode == 2 and "don't combine" in r.stderr
    # unknown class / unreadable file
    r = _loadgen(tmp_path, "--serve", "/nonexistent.sock", "--shapes",
                 str(tmp_path / "missing.json"), "--requests", "5")
    assert r.returncode == 2 and "replay-spec" in r.stderr
    # malformed entries are named, not silently skipped
    for bad, hint in (
        ({"entries": []}, "at least one entry"),
        ({"entries": [{"kernel": "k", "args": [["f64", [4]]]}]},
         "bad arg"),
        ({"entries": [{"kernel": "k", "args": [["f32", [4]]],
                       "weight": 0}]}, "weight"),
    ):
        spec.write_text(json.dumps(bad))
        r = _loadgen(tmp_path, "--serve", "/nonexistent.sock",
                     "--shapes", str(spec), "--requests", "5")
        assert r.returncode == 2 and hint in r.stderr, r.stderr


# ---------------------------------------------------------------- #
# the closed loop, end to end on CPU                               #
# ---------------------------------------------------------------- #

def _tool(name, args, env, timeout=300):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", name), *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env,
    )


def test_adaptive_bucket_loop_end_to_end(tmp_path):
    """Skewed traffic at a coarse table -> propose -> canary win at
    identical seeds -> promotion journaled -> the promoted table
    serves the same mix with pad_frac below target in obs_report; the
    incumbent table file is never touched."""
    adapt_dir = tmp_path / "adapt"
    adapt_dir.mkdir()
    incumbent_path = tmp_path / "incumbent.json"
    incumbent_path.write_text(json.dumps(
        {"vector_add": _spec(1 << 20)}))
    incumbent_bytes = incumbent_path.read_bytes()
    # the skewed live mix: everything lands at (4096,), paying
    # ~99.6% pad against the coarse 1M avatar
    traffic = tmp_path / "traffic.json"
    traffic.write_text(json.dumps({"entries": [
        {"kernel": "vector_add",
         "args": [["f32", []], ["f32", [4096]], ["f32", [4096]]],
         "statics": {}, "weight": 1.0}]}))
    base = _scrubbed_env(None)
    base["TPK_ADAPT_DIR"] = str(adapt_dir)
    base["TPK_SERVE_BUCKETS"] = str(incumbent_path)
    base["TPK_SERVE_MAX_PAD_FRAC"] = "1.0"  # let the waste bucket
    base["TPK_ADAPT_MIN_REQUESTS"] = "20"
    base["TPK_SLO_DIR"] = str(tmp_path / "slo")
    daemon_env = {"TPK_SERVE_BUCKETS": str(incumbent_path),
                  "TPK_SERVE_MAX_PAD_FRAC": "1.0"}

    # 1. live traffic against the incumbent leaves the evidence
    with _daemon(tmp_path, env_extra=daemon_env, tag="traffic") as (
            sock, j1, _proc):
        env = dict(base)
        env["TPK_HEALTH_JOURNAL"] = j1
        r = _tool("loadgen.py",
                  ["--serve", sock, "--shapes", str(traffic),
                   "--seed", "7", "--requests", "24", "--rate", "100"],
                  env)
        assert r.returncode == 0, r.stdout + r.stderr
    mined = [e for e in _events(j1)
             if e.get("kind") == "serve_request" and e.get("ok")]
    assert len(mined) >= 20 and all(e["bucketed"] for e in mined)
    assert all(e["pad_frac"] > 0.9 for e in mined)

    # 2. propose: mine the journal, persist the split candidate
    ops_journal = tmp_path / "ops.jsonl"
    env = dict(base)
    env["TPK_HEALTH_JOURNAL"] = str(ops_journal)
    r = _tool("serve_optimize.py", ["propose", "--journal", j1], env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "proposed" in r.stdout, r.stdout
    cand = json.load(open(adapt_dir / "adapt.json"))
    assert cand["status"] == "proposed"
    assert cand["before"]["pad_frac"] > 0.9
    assert cand["after"]["pad_frac"] < 0.25  # the default target
    specs = cand["table"]["vector_add"]
    assert isinstance(specs, list)
    assert any(s["args"][1][1] == [4096] for s in specs)

    # 3. canary: replay the frozen mix against both tables at
    # identical seeds; the exact-fit candidate must win pad AND p99
    r = _tool("serve_optimize.py",
              ["canary", "--seed", "11", "--requests", "16",
               "--rate", "100", "--check"], env, timeout=420)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PROMOTED" in r.stdout, r.stdout
    promoted_path = adapt_dir / "buckets.json"
    assert promoted_path.exists()
    cand = json.load(open(adapt_dir / "adapt.json"))
    assert cand["status"] == "promoted"
    assert cand["canary"]["seed"] == 11
    assert cand["canary"]["verdict"]["promote"] is True
    kinds = [e["kind"] for e in _events(ops_journal)]
    assert {"adapt_proposed", "adapt_canary",
            "adapt_promoted"} <= set(kinds)
    promoted_ev = [e for e in _events(ops_journal)
                   if e["kind"] == "adapt_promoted"][-1]
    assert promoted_ev["pad_frac"] == pytest.approx(0.0)
    # promotion rewrote ONLY the stable buckets.json: the incumbent
    # table file is byte-identical untouched
    assert incumbent_path.read_bytes() == incumbent_bytes

    # 4. the promoted table serves the same mix waste-free, and
    # obs_report's one-look line says so
    with _daemon(tmp_path, env_extra={
            "TPK_SERVE_BUCKETS": str(promoted_path),
            "TPK_SERVE_MAX_PAD_FRAC": "1.0"}, tag="promoted") as (
            sock, j3, _proc):
        env = dict(base)
        env["TPK_HEALTH_JOURNAL"] = j3
        r = _tool("loadgen.py",
                  ["--serve", sock, "--shapes", str(traffic),
                   "--seed", "7", "--requests", "24", "--rate", "100"],
                  env)
        assert r.returncode == 0, r.stdout + r.stderr
    served = [e for e in _events(j3)
              if e.get("kind") == "serve_request" and e.get("ok")]
    assert served and all(e["bucketed"] for e in served)
    assert all(e["pad_frac"] == 0.0 for e in served)
    r = _tool("obs_report.py", ["--journal", j3], base)
    assert "adaptive buckets" in r.stdout, r.stdout + r.stderr
    assert "below target" in r.stdout, r.stdout


def test_canary_rejects_non_winning_candidate(tmp_path, monkeypatch):
    """A candidate that cannot beat the incumbent (identical table:
    pad_win is exactly 0) is measured, REJECTED with evidence, and
    changes nothing: no buckets.json, incumbent bytes untouched."""
    import jax

    adapt_dir = tmp_path / "adapt"
    incumbent_path = tmp_path / "incumbent.json"
    incumbent = {"vector_add": _spec(512)}
    incumbent_path.write_text(json.dumps(incumbent))
    incumbent_bytes = incumbent_path.read_bytes()
    monkeypatch.setenv("TPK_ADAPT_DIR", str(adapt_dir))
    # traffic at (256,) pads ~50% on the 512 avatar — there IS waste,
    # but the candidate table is the incumbent itself, so the canary
    # measures identical pads and the margin gate must hold
    adapt.record_candidate(_result(incumbent), _mix_one(n=256), 0.25,
                           jax_version=jax.__version__)
    ops_journal = tmp_path / "ops.jsonl"
    env = _scrubbed_env(None)
    env["TPK_ADAPT_DIR"] = str(adapt_dir)
    env["TPK_SERVE_BUCKETS"] = str(incumbent_path)
    env["TPK_HEALTH_JOURNAL"] = str(ops_journal)
    env["TPK_SLO_DIR"] = str(tmp_path / "slo")
    r = _tool("serve_optimize.py",
              ["canary", "--seed", "3", "--requests", "6", "--rate",
               "200", "--check"], env, timeout=300)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REJECTED" in r.stdout and "incumbent stays" in r.stdout
    data = json.load(open(adapt_dir / "adapt.json"))
    assert data["status"] == "rejected"
    assert data["canary"]["verdict"]["promote"] is False
    evs = _events(ops_journal)
    canary_ev = [e for e in evs if e["kind"] == "adapt_canary"][-1]
    assert canary_ev["promote"] is False
    assert any(e["kind"] == "adapt_rejected" for e in evs)
    assert not any(e["kind"] == "adapt_promoted" for e in evs)
    # nothing changed: the fleet's table file does not exist, the
    # incumbent is byte-identical
    assert not (adapt_dir / "buckets.json").exists()
    assert incumbent_path.read_bytes() == incumbent_bytes
