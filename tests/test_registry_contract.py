"""Registry completeness lint (ISSUE 6 satellite, tier-1).

Every kernel in the registry must carry the full contract surface —
TUNABLES, an ``aot.BENCH_CONFIGS`` avatar, a ``KERNEL_SOURCES`` row,
a roofline entry, and (ISSUE 7) an output-integrity oracle + canary
fingerprint config — either directly or through
``registry.DERIVED_KERNELS`` (scan_exclusive rides scan's tuning
surface but carries its OWN oracle: its output contract differs). A
new kernel (the fused scan_histogram was the first customer) cannot
silently skip tuning, prewarm, staleness tracking, the roofline
table, or the integrity guard.

Also asserts the widened-TUNABLES acceptance contracts: the AOT
executable-cache key is distinct per pipeline/fuse variant (the
tunable env fingerprint), and a capped --smoke sweep reaches the
pipeline-depth axis.
"""

import numpy as np
import pytest

from tpukernels import aot, registry
from tpukernels.obs import slo
from tpukernels.resilience import integrity
from tpukernels.tuning import roofline


def test_registry_contract_complete():
    names = registry.names()
    assert "scan_histogram" in names  # the newest contract customer
    for name in names:
        base = registry.DERIVED_KERNELS.get(name, name)
        assert base in names, f"{name}: derived base {base} missing"
        space = registry.tunables(base)  # KeyError = contract breach
        assert name in aot.BENCH_CONFIGS, (
            f"{name} has no aot.BENCH_CONFIGS avatar (prewarm skips it)"
        )
        assert aot.KERNEL_SOURCES.get(name), (
            f"{name} has no KERNEL_SOURCES row (manifest staleness "
            "cannot be tracked)"
        )
        metric = roofline.KERNEL_METRIC.get(base)
        assert metric in roofline.MODELS, (
            f"{name} has no roofline entry (its captures would read "
            "'ok' forever)"
        )
        # the spaces' own metric binding must agree with the roofline
        # mapping — one kernel, one metric of record
        if space.metric is not None:
            assert space.metric == metric, (name, space.metric, metric)
        # output-integrity surface (docs/RESILIENCE.md §output
        # integrity): DIRECT entries even for derived kernels —
        # scan_exclusive's output contract is its own
        assert name in integrity.ORACLES, (
            f"{name} has no integrity oracle (its outputs would never "
            "be cross-checked)"
        )
        assert name in integrity.CANARY_CONFIGS, (
            f"{name} has no integrity canary config (no fingerprint "
            "envelope, no first-trust smoke check)"
        )
        kind, rtol, atol = integrity.tolerance(name)
        assert kind == "exact" or (rtol > 0 and atol > 0), (
            name, kind, rtol, atol
        )
        # canary operands must actually build (a stale builder would
        # otherwise surface only when a guard first fires)
        assert integrity._build_args(name)
        assert integrity.canary_key(name).startswith(name + "|")
        # latency-SLO surface (ISSUE 8, docs/OBSERVABILITY.md §latency
        # SLOs): DIRECT rows even for derived kernels — a kernel
        # without a target would load-test to "no_data" forever. Both
        # the chip evidence row and the any-host CPU proof row are
        # required, and each must resolve to a positive target.
        assert name in slo.TARGETS, (
            f"{name} has no SLO target row (its tail latency would "
            "never be judged)"
        )
        for row in slo.REQUIRED_ROWS:
            ms = slo.TARGETS[name].get(row)
            assert isinstance(ms, (int, float)) and ms > 0, (
                name, row, ms
            )
        t, basis = slo.resolve_target_s(name, "cpu", "probe")
        assert t and t > 0 and basis == "exact", (name, t, basis)
        # serve bucketing surface (ISSUE 10, docs/SERVING.md): every
        # kernel must STATE its padding rule — an explicit None (the
        # stencils: padding changes the boundary condition) is a
        # decision, an absent row is a kernel the serving daemon
        # would wrongly refuse (or worse, wrongly pad)
        from tpukernels.serve import bucketing

        assert name in bucketing.PAD_RULES, (
            f"{name} has no serve PAD_RULES row (bucketing cannot "
            "decide whether padding preserves its answer)"
        )
        assert bucketing.PAD_RULES[name] in (None, "zero", "hist0")


def test_derived_kernels_are_registered_and_tunable_through_base():
    for derived, base in registry.DERIVED_KERNELS.items():
        assert derived in registry.names()
        with pytest.raises(KeyError, match="TUNABLES"):
            registry.tunables(derived)
        assert registry.tunables(base) is not None


def test_aot_key_distinct_per_pipeline_variant(monkeypatch):
    """Acceptance: each TUNABLES-selected variant (sgemm depth/order,
    stencil3d depth, scan_histogram fuse) compiles under its OWN
    executable-cache key — the tunable env fingerprint rides the key,
    so a depth-2 candidate can never be served the depth-1
    executable."""
    registry.names()  # populate, so the fingerprint sees all TUNABLES
    x = np.zeros((8, 8), np.float32)
    keys = {}
    for env in (
        None,
        ("TPK_SGEMM_DEPTH", "2"),
        ("TPK_SGEMM_DEPTH", "3"),
        ("TPK_SGEMM_ORDER", "ji"),
        ("TPK_STENCIL_DEPTH", "2"),
        ("TPK_SCANHIST_FUSE", "on"),
    ):
        for var in ("TPK_SGEMM_DEPTH", "TPK_SGEMM_ORDER",
                    "TPK_STENCIL_DEPTH", "TPK_SCANHIST_FUSE"):
            monkeypatch.delenv(var, raising=False)
        if env is not None:
            monkeypatch.setenv(*env)
        aot.reset()
        keys[env] = aot.cache_key("sgemm", (x,), kind="cpu")
        if env is not None:
            assert f"{env[0]}={env[1]}" in keys[env]
    aot.reset()
    assert len(set(keys.values())) == len(keys), keys


def test_smoke_sweep_reaches_pipeline_depth():
    """Acceptance: `autotune --kernel stencil3d --smoke` (capped at 3
    candidates by the runner) sweeps pipeline depth — the depth axis
    is declared right after the control's k, so the first three
    candidates are depth 1/2/3 at the k of record. scan_histogram's
    2-candidate space likewise covers fuse off/on inside the cap."""
    cands, pruned = registry.tunables("stencil3d").candidates()
    assert pruned == 0
    assert cands[:3] == [
        {"k": 8, "depth": 1}, {"k": 8, "depth": 2}, {"k": 8, "depth": 3},
    ]
    fuse_cands, _ = registry.tunables("scan_histogram").candidates()
    assert fuse_cands == [{"fuse": "off"}, {"fuse": "on"}]


def test_weak_series_programs_declare_overlap_capability():
    """ISSUE 20 satellite: every distributed program in
    scaling.WEAK_SERIES must carry an OVERLAP_CAPS row — either
    depth-searchable (rides TPK_DIST_DEPTH) or documented-exempt with
    a stated why — so a future distributed program can't ship
    sync-only silently."""
    from tpukernels.obs import scaling

    assert scaling.WEAK_SERIES, "weak-scaling catalog is empty"
    for prog in scaling.WEAK_SERIES:
        row = scaling.OVERLAP_CAPS.get(prog)
        assert row is not None, (
            f"{prog} is in scaling.WEAK_SERIES but has no OVERLAP_CAPS "
            "row (declare mode='depth' or mode='exempt' with a why)"
        )
        assert row.get("mode") in ("depth", "exempt"), (prog, row)
        assert isinstance(row.get("why"), str) and row["why"].strip(), (
            f"{prog}: OVERLAP_CAPS row needs a non-empty why"
        )
    # no orphan rows: a cap for a program the catalog dropped is stale
    assert set(scaling.OVERLAP_CAPS) <= set(scaling.WEAK_SERIES), (
        set(scaling.OVERLAP_CAPS) - set(scaling.WEAK_SERIES)
    )


def test_mesh_kernels_are_registered():
    """Every serve-over-mesh capable kernel must be a registered
    kernel — the admission tier (bucketing.mesh_tier_for) and the
    dispatch layer (registry.dispatch_mesh) both key off this list."""
    names = registry.names()
    assert registry.MESH_KERNELS, "mesh capability list is empty"
    for name in registry.MESH_KERNELS:
        assert name in names, f"MESH_KERNELS entry {name} unregistered"
    assert len(set(registry.MESH_KERNELS)) == len(registry.MESH_KERNELS)
