import jax.numpy as jnp
import numpy as np
import pytest

from tpukernels.kernels.stencil import (
    jacobi2d,
    jacobi2d_reference,
    jacobi3d,
    jacobi3d_reference,
)


def _numpy_jacobi2d(x, iters):
    x = np.array(x, dtype=np.float64)
    for _ in range(iters):
        out = x.copy()
        out[1:-1, 1:-1] = 0.25 * (
            x[:-2, 1:-1] + x[2:, 1:-1] + x[1:-1, :-2] + x[1:-1, 2:]
        )
        x = out
    return x


@pytest.mark.parametrize("shape,iters", [((64, 128), 3), ((33, 100), 5), ((16, 16), 10)])
def test_jacobi2d_small(rng, shape, iters):
    x = jnp.asarray(rng.standard_normal(shape), dtype=jnp.float32)
    out = jacobi2d(x, iters)
    ref = _numpy_jacobi2d(np.asarray(x), iters)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_jacobi2d_matches_jnp_reference(rng):
    x = jnp.asarray(rng.standard_normal((128, 256)), dtype=jnp.float32)
    out = jacobi2d(x, 4)
    ref = jacobi2d_reference(x, 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_jacobi2d_blocked_path(rng):
    # tall enough to hit the blocked (DMA-slab) kernel: > _BM+2 rows
    # and > 4 MiB
    x = jnp.asarray(rng.standard_normal((1024, 1536)), dtype=jnp.float32)
    out = jacobi2d(x, 2)
    ref = jacobi2d_reference(x, 2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
    )


@pytest.mark.parametrize("k,iters", [(1, 3), (2, 5), (4, 4), (8, 13), (8, 16)])
def test_jacobi2d_temporal_blocking(rng, k, iters):
    # exercises full k-sweep passes AND the iters % k remainder pass;
    # result must be bit-for-bit independent of the fusion depth
    x = jnp.asarray(rng.standard_normal((1024, 1536)), dtype=jnp.float32)
    out = jacobi2d(x, iters, k=k)
    ref = jacobi2d(x, iters, k=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    ref64 = _numpy_jacobi2d(np.asarray(x), iters)
    np.testing.assert_allclose(np.asarray(out), ref64, rtol=1e-4, atol=1e-5)


def _numpy_jacobi3d(x, iters):
    x = np.array(x, dtype=np.float64)
    for _ in range(iters):
        out = x.copy()
        out[1:-1, 1:-1, 1:-1] = (
            x[:-2, 1:-1, 1:-1] + x[2:, 1:-1, 1:-1]
            + x[1:-1, :-2, 1:-1] + x[1:-1, 2:, 1:-1]
            + x[1:-1, 1:-1, :-2] + x[1:-1, 1:-1, 2:]
        ) / 6.0
        x = out
    return x


@pytest.mark.parametrize("shape,iters", [((8, 16, 128), 3), ((12, 10, 50), 4)])
def test_jacobi3d_small(rng, shape, iters):
    x = jnp.asarray(rng.standard_normal(shape), dtype=jnp.float32)
    out = jacobi3d(x, iters)
    ref = _numpy_jacobi3d(np.asarray(x), iters)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_jacobi3d_blocked_path(rng):
    x = jnp.asarray(rng.standard_normal((64, 64, 256)), dtype=jnp.float32)
    out = jacobi3d(x, 2)
    ref = jacobi3d_reference(x, 2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
    )


def test_jacobi3d_wide_plane_bz_floor(rng):
    # a ~4.5 MiB z-plane drives _pick_bz to its floor of 1; k must be
    # clamped to bz so the slab stays at 3 planes instead of the
    # (1 + 2k)-plane slab that blows the 100 MiB vmem limit on chip
    from tpukernels.kernels import stencil as _st

    assert _st._pick_bz(1024, 1152, 8) == 1
    x = jnp.asarray(
        rng.standard_normal((8, 1024, 1150)), dtype=jnp.float32
    )
    out = jacobi3d(x, 2, k=8)
    ref = jacobi3d_reference(x, 2)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
    )


@pytest.mark.parametrize("k,iters", [(2, 3), (4, 9)])
def test_jacobi3d_temporal_blocking(rng, k, iters):
    # 64*64*384*4 B = 6 MiB > _SMALL_BYTES: genuinely exercises the
    # blocked path (incl. the iters % k remainder pass with its fixed
    # ghost depth); 64x64x256 would tie the threshold and silently
    # take the small path, which ignores k
    from tpukernels.kernels import stencil as _st

    x = jnp.asarray(rng.standard_normal((64, 64, 384)), dtype=jnp.float32)
    assert 64 * 64 * 384 * 4 > _st._SMALL_BYTES
    out = jacobi3d(x, iters, k=k)
    ref = jacobi3d(x, iters, k=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    ref64 = _numpy_jacobi3d(np.asarray(x), iters)
    np.testing.assert_allclose(np.asarray(out), ref64, rtol=1e-4, atol=1e-5)


def test_boundary_held_fixed(rng):
    x = jnp.asarray(rng.standard_normal((32, 128)), dtype=jnp.float32)
    out = np.asarray(jacobi2d(x, 7))
    xn = np.asarray(x)
    np.testing.assert_array_equal(out[0], xn[0])
    np.testing.assert_array_equal(out[-1], xn[-1])
    np.testing.assert_array_equal(out[:, 0], xn[:, 0])
    np.testing.assert_array_equal(out[:, -1], xn[:, -1])


@pytest.mark.parametrize("depth", [2, 3])
def test_jacobi3d_pipeline_depth_bitwise_identical(rng, depth):
    """The ring-buffered slab prefetch (TPK_STENCIL_DEPTH >= 2) only
    reorders DMA against compute — results must be BITWISE identical
    to the copy-wait-compute path on a genuinely blocked, multi-block
    grid (the prologue, steady-state prefetch and slot-reuse schedule
    all execute)."""
    from tpukernels.kernels import stencil as _st

    x = jnp.asarray(
        rng.standard_normal((64, 32, 2048)), dtype=jnp.float32
    )
    assert 64 * 32 * 2048 * 4 > _st._SMALL_BYTES  # blocked path
    base = jacobi3d(x, 4, k=2, depth=1)
    out = jacobi3d(x, 4, k=2, depth=depth)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))
    ref = jacobi3d_reference(x, 4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5
    )


def test_jacobi3d_depth_env_knob_and_bz_budget(rng, monkeypatch):
    """TPK_STENCIL_DEPTH resolves through the tuning subsystem, and
    _pick_bz divides the slab budget by depth so depth slabs + out
    blocks stay inside the same 32 MiB that sized depth 1."""
    from tpukernels.kernels import stencil as _st

    for depth in (1, 2, 3):
        bz = _st._pick_bz(384, 384, 8, depth)
        planes_budget = (32 * 1024 * 1024) // (4 * 384 * 384)
        assert depth * (bz + 16) + 2 * bz <= planes_budget + depth
    assert _st._pick_bz(64, 2048, 2, 1) > _st._pick_bz(64, 2048, 2, 3)
    monkeypatch.setenv("TPK_STENCIL_DEPTH", "2")
    x = jnp.asarray(rng.standard_normal((64, 32, 2048)), jnp.float32)
    out = np.asarray(jacobi3d(x, 3))
    monkeypatch.delenv("TPK_STENCIL_DEPTH")
    np.testing.assert_array_equal(out, np.asarray(jacobi3d(x, 3)))
    monkeypatch.setenv("TPK_STENCIL_DEPTH", "abc")
    with pytest.raises(ValueError, match="TPK_STENCIL_DEPTH"):
        jacobi3d(x, 1)
