"""Unit tests for conftest's TPU-liveness probe plumbing (the wedge
fallback itself is covered end-to-end in test_capi.py). The probe
subprocess is faked to always "hang" so the tests prove the sentinel
short-circuit ordering rather than the environment's TPU state."""

import subprocess
import time

import conftest as cft


def _fake_hanging_probe(monkeypatch):
    def fake_run(*args, **kwargs):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(cft.subprocess, "run", fake_run)


def test_fresh_sentinel_skips_the_probe(tmp_path, monkeypatch):
    sentinel = tmp_path / "tpu_probe_ok"
    sentinel.write_text(str(time.time()))
    monkeypatch.setattr(cft, "_PROBE_SENTINEL", str(sentinel))
    monkeypatch.delenv("TPK_FORCE_TPU_PROBE_FAIL", raising=False)
    _fake_hanging_probe(monkeypatch)
    # the fake probe would report a hang; False proves the fresh
    # sentinel short-circuited before probing
    assert cft._tpu_hangs() is False
    # the sentinel is single-use: consumed by the skip, so the next
    # run re-probes — a wedge right after a healthy probe costs at
    # most one hung suite
    assert not sentinel.exists()
    assert cft._tpu_hangs() is True


def test_stale_sentinel_probes(tmp_path, monkeypatch):
    import os

    sentinel = tmp_path / "tpu_probe_ok"
    sentinel.write_text("old")
    old = time.time() - (cft._PROBE_TTL_S + 60)
    os.utime(sentinel, (old, old))
    monkeypatch.setattr(cft, "_PROBE_SENTINEL", str(sentinel))
    monkeypatch.delenv("TPK_FORCE_TPU_PROBE_FAIL", raising=False)
    _fake_hanging_probe(monkeypatch)
    # stale sentinel must NOT short-circuit: the (fake, hanging)
    # probe runs and reports the wedge
    assert cft._tpu_hangs() is True


def test_missing_sentinel_probes(tmp_path, monkeypatch):
    monkeypatch.setattr(
        cft, "_PROBE_SENTINEL", str(tmp_path / "never_written")
    )
    monkeypatch.delenv("TPK_FORCE_TPU_PROBE_FAIL", raising=False)
    _fake_hanging_probe(monkeypatch)
    assert cft._tpu_hangs() is True


def test_forced_fail_wins_over_sentinel(tmp_path, monkeypatch):
    sentinel = tmp_path / "tpu_probe_ok"
    sentinel.write_text(str(time.time()))
    monkeypatch.setattr(cft, "_PROBE_SENTINEL", str(sentinel))
    monkeypatch.setenv("TPK_FORCE_TPU_PROBE_FAIL", "1")
    # fake the probe too so an ordering regression fails fast and
    # deterministically instead of spawning the real 120s probe
    _fake_hanging_probe(monkeypatch)
    assert cft._tpu_hangs() is True
