import jax.numpy as jnp
import numpy as np
import pytest

from tpukernels.kernels.nbody import nbody_step, nbody_reference


def _rand_system(rng, n):
    px, py, pz = (jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(3))
    vx, vy, vz = (
        jnp.asarray(0.1 * rng.standard_normal(n), jnp.float32) for _ in range(3)
    )
    m = jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32)
    return px, py, pz, vx, vy, vz, m


@pytest.mark.parametrize("n,steps", [(256, 1), (1024, 2), (1000, 3)])
def test_nbody_matches_reference(rng, n, steps):
    sys_ = _rand_system(rng, n)
    out = nbody_step(*sys_, dt=1e-3, eps=1e-2, steps=steps)
    ref = nbody_reference(*sys_, dt=1e-3, eps=1e-2, steps=steps)
    for got, want, name in zip(out, ref, ["px", "py", "pz", "vx", "vy", "vz"]):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
            err_msg=name,
        )


def test_nbody_momentum_conserved(rng):
    # equal masses, pairwise antisymmetric forces -> total momentum
    # constant (up to float error)
    n = 512
    px, py, pz, vx, vy, vz, _ = _rand_system(rng, n)
    m = jnp.ones(n, jnp.float32)
    out = nbody_step(px, py, pz, vx, vy, vz, m, dt=1e-3, steps=5)
    p0 = np.asarray(vx).sum()
    p1 = np.asarray(out[3]).sum()
    assert abs(p1 - p0) < 1e-2


def test_nbody_zero_mass_inert(rng):
    # a zero-mass far-away body must not disturb the others
    n = 128
    sys_ = [np.asarray(a) for a in _rand_system(rng, n)]
    sys2 = [np.append(a, 100.0).astype(np.float32) for a in sys_[:3]] + [
        np.append(a, 0.0).astype(np.float32) for a in sys_[3:]
    ]
    out_base = nbody_step(*[jnp.asarray(a) for a in sys_], steps=2)
    out_ext = nbody_step(*[jnp.asarray(a) for a in sys2], steps=2)
    np.testing.assert_allclose(
        np.asarray(out_ext[0])[:n], np.asarray(out_base[0]), rtol=1e-5
    )
