"""CPU suite for fleet-wide request tracing (docs/OBSERVABILITY.md
§request tracing; ISSUE 13).

Covers: the ambient per-thread trace context tagging every in-flight
span with the client-minted request_id, one-id-per-logical-request
across backpressure retries, timeline assembly edge cases — clock
anchoring across skewed processes, a request that spills mid-flight
(home + sibling segments joined), an abandoned-worker gap, a
pre-request_id old-server journal (the assembler degrades loudly,
never crashes) — the trace-budget verdicts (`trace_inconsistent`
gates like the copy budget, `trace_coverage` prints non-gating), the
request-id journal lint, health_report's (kernel, worker_id) served
table with spill dedupe, and the e2e acceptance proof: a traced
loadgen burst against a 2-worker fleet with a wedged worker →
`trace_report` reconstructs every request's timeline with
request_id joins across the router spill, clean phase sums within
the documented tolerance, and the shapes-seen records matching the
seeded mix.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from test_distributed import _scrubbed_env
from test_fleet import _fleet
from test_serve import SCAN_BUCKET, _events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tool(script, *args, env=None):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), *args],
        capture_output=True, text=True, timeout=180, cwd=REPO,
        env=env,
    )


def _ev(kind, rid=None, pid=1, t=100.0, **kw):
    e = {"kind": kind, "pid": pid, "t": t}
    if rid is not None:
        e["request_id"] = rid
    e.update(kw)
    return e


def _span(rid, name, wall, pid=2, t=100.0, depth=1, **kw):
    return _ev("span", rid=rid, pid=pid, t=t, name=name,
               wall_s=wall, depth=depth, ok=True, **kw)


# ---------------------------------------------------------------- #
# trace context + client id discipline                             #
# ---------------------------------------------------------------- #

def test_request_ctx_tags_spans_and_nested_children(
        monkeypatch, tmp_path):
    from tpukernels.obs import trace

    jp = tmp_path / "h.jsonl"
    monkeypatch.setenv("TPK_TRACE", "1")
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(jp))
    trace.reload()
    try:
        assert trace.current_request() is None
        with trace.request_ctx("r9"):
            assert trace.current_request() == "r9"
            trace.emit_span("serve/wait/queue", 0.002, kernel="scan")
            with trace.span("serve/scan"):
                with trace.span("aot/compile/scan"):
                    pass
        assert trace.current_request() is None
        # outside the context: untagged, as before
        with trace.span("probe/liveness"):
            pass
    finally:
        monkeypatch.delenv("TPK_TRACE")
        trace.reload()
    spans = _events(jp)
    by_name = {e["name"]: e for e in spans}
    assert by_name["serve/wait/queue"]["request_id"] == "r9"
    assert by_name["serve/wait/queue"]["depth"] == 1
    assert by_name["serve/scan"]["request_id"] == "r9"
    assert by_name["serve/scan/aot/compile/scan"]["request_id"] == "r9"
    assert "request_id" not in by_name["probe/liveness"]


def test_backpressure_retries_keep_one_request_id():
    """One LOGICAL request keeps one causal id across admission-
    control retries — N fresh ids would shred the timeline into N
    fake one-hop requests."""
    from tpukernels.serve import client as serve_client

    class _RejectTwice:
        def __init__(self):
            self.seen = []
            self.next_request_id = None
            self._n = 0

        def mint_request_id(self):
            self._n += 1
            return f"m-{self._n}"

        def dispatch(self, kernel, *a, **s):
            self.seen.append(self.next_request_id)
            self.next_request_id = None
            if len(self.seen) < 3:
                raise serve_client.ServeRejected("full", 0.0)
            return 42

    cli = _RejectTwice()
    assert serve_client.dispatch_with_backpressure(
        cli, "scan", (), {}) == 42
    assert cli.seen == ["m-1", "m-1", "m-1"]


# ---------------------------------------------------------------- #
# assembly: phases, anchoring, spills, gaps, old journals           #
# ---------------------------------------------------------------- #

def test_phase_decomposition_coverage_and_critical_path():
    from tpukernels.obs import reqtrace

    events = [
        _ev("serve_client_request", "r1", pid=1, t=10.0,
            kernel="scan", wall_s=0.02, ok=True),
        _ev("serve_request", "r1", pid=2, t=10.0, kernel="scan",
            bucket="scan|8192|-", ok=True, wall_s=0.012,
            worker_id="0", shapes=[[4093]], dtypes=["int32"],
            pad_frac=0.5),
        _span("r1", "serve/wait/queue", 0.004, t=9.99),
        _span("r1", "serve/wait/lock", 0.001, t=9.991),
        _span("r1", "serve/pad", 0.001, t=9.992),
        _span("r1", "serve/scan", 0.010, t=10.0),
        _span("r1", "serve/scan/dispatch/scan", 0.009, t=10.0,
              depth=2),
        _span("r1", "serve/scan/dispatch/scan/aot/compile/scan",
              0.006, t=9.999, depth=3),
    ]
    t = reqtrace.assemble(events)["r1"]
    ph = t["phases"]
    assert ph["queue_wait"] == pytest.approx(0.004)
    assert ph["lock_wait"] == pytest.approx(0.001)
    assert ph["pad"] == pytest.approx(0.001)
    assert ph["compile"] == pytest.approx(0.006)
    # dispatch = top-level serve/scan minus its compile child;
    # the interior dispatch/scan span must NOT double-count
    assert ph["dispatch"] == pytest.approx(0.004)
    assert t["accounted_s"] == pytest.approx(0.016)
    assert t["coverage"] == pytest.approx(0.8)
    assert ph["unaccounted"] == pytest.approx(0.004)
    assert t["clean"] is True
    assert t["dominant"] == "compile"
    assert t["worker_id"] == "0"
    agg = reqtrace.aggregate({"r1": t})
    assert list(agg) == ["scan|scan|8192|-|-"]
    row = agg["scan|scan|8192|-|-"]
    assert row["n"] == 1
    assert row["phases"]["compile"]["p50_s"] == pytest.approx(0.006)


def test_clock_anchoring_across_skewed_processes():
    """A worker whose wall clock runs 1000 s ahead must not shift
    the phase arithmetic (durations only) and its lane offsets must
    anchor to its OWN serve_start, not the client's clock."""
    from tpukernels.obs import reqtrace

    skew = 1000.0
    events = [
        _ev("serve_start", pid=7, t=50.0 + skew, socket="s"),
        _ev("serve_client_request", "r1", pid=1, t=10.0,
            kernel="scan", wall_s=0.02, ok=True),
        _ev("serve_request", "r1", pid=7, t=60.0 + skew,
            kernel="scan", bucket="b", ok=True, wall_s=0.01),
        _span("r1", "serve/wait/queue", 0.004, pid=7,
              t=59.99 + skew),
        _span("r1", "serve/scan", 0.010, pid=7, t=60.0 + skew),
    ]
    t = reqtrace.assemble(events)["r1"]
    assert t["coverage"] == pytest.approx(0.7)
    for s in t["segments"]:
        # anchored to pid 7's own serve_start at t=1050: offsets stay
        # ~10 s (its uptime), not ~1050 s of cross-clock nonsense
        assert 9.0 <= s["rel0"] <= 11.0


def test_spill_midflight_joins_home_and_sibling_segments():
    from tpukernels.obs import reqtrace

    events = [
        _ev("serve_client_request", "r1", pid=1, t=30.0,
            kernel="scan", wall_s=12.5, ok=True),
        _ev("serve_route", "r1", pid=5, t=30.0, kernel="scan",
            bucket="scan|8192|-", worker=1, spilled_from=0, ok=True),
        _ev("serve_spill", "r1", pid=5, t=29.0, kernel="scan",
            bucket="scan|8192|-", from_worker=0, to_worker=1,
            reason="wedged"),
        # home attempt: wedged-twice failure record
        _ev("serve_request", "r1", pid=10, t=29.0, kernel="scan",
            bucket="scan|8192|-", ok=False, error="wedged twice",
            wall_s=12.0, worker_id="0", requeues=1),
        _ev("serve_request_requeued", "r1", pid=10, t=23.0,
            kernel="scan", bucket="scan|8192|-", timeout_s=2),
        # sibling serves it
        _ev("serve_request", "r1", pid=11, t=30.0, kernel="scan",
            bucket="scan|8192|-", ok=True, wall_s=0.4,
            worker_id="1"),
        _span("r1", "serve/scan", 0.4, pid=11, t=30.0),
    ]
    t = reqtrace.assemble(events)["r1"]
    assert len(t["server"]) == 2
    assert t["final"]["worker_id"] == "1"     # the ok record wins
    assert t["worker_id"] == "1"
    assert [s["pid"] for s in t["segments"]] == [11]
    assert t["spills"] and t["spills"][0]["reason"] == "wedged"
    assert t["requeued"] is True
    assert t["clean"] is False                # excluded from sum gate
    assert any(g["kind"] == "abandoned-worker" for g in t["gaps"])


def test_missing_server_record_is_an_explicit_gap():
    from tpukernels.obs import reqtrace

    events = [
        _ev("serve_client_request", "r2", pid=1, t=5.0,
            kernel="scan", wall_s=0.01, ok=True),
    ]
    t = reqtrace.assemble(events)["r2"]
    assert [g["kind"] for g in t["gaps"]] == ["missing-server-record"]
    # a dropped (rejected) request is NOT a gap — the rejection is
    # the explanation
    events = [
        _ev("serve_client_request", "r3", pid=1, t=5.0,
            kernel="scan", wall_s=0.01, ok=False, error="rejected"),
        _ev("serve_rejected", "r3", pid=2, t=5.0, kernel="scan",
            depth=9),
    ]
    t = reqtrace.assemble(events)["r3"]
    assert t["gaps"] == [] and t["rejections"] == 1


def test_throttled_request_is_not_clean():
    """A tenant-throttled-then-retried request's client wall includes
    backoff sleeps no span covers — it must not feed the consistency
    gate as a clean timeline."""
    from tpukernels.obs import reqtrace

    events = [
        _ev("serve_client_request", "r4", pid=1, t=9.0,
            kernel="scan", wall_s=2.0, ok=True),
        _ev("serve_tenant_throttled", "r4", pid=5, t=7.5,
            kernel="scan", tenant="hot", retry_after_s=1.0),
        _ev("serve_request", "r4", pid=2, t=9.0, kernel="scan",
            bucket="b", ok=True, wall_s=0.01),
        _span("r4", "serve/scan", 0.01, t=9.0),
    ]
    t = reqtrace.assemble(events)["r4"]
    assert t["throttles"] == 1
    assert t["clean"] is False
    b = reqtrace.run_budget(events)
    assert b["clean"] == 0 and "sum_ratio_max" not in b


def test_pre_request_id_journal_degrades_loudly(tmp_path):
    """An old server's journal (serve_request without request_id)
    assembles to zero timelines, is COUNTED, and crashes nothing —
    including the trace_report CLI."""
    from tpukernels.obs import reqtrace

    events = [
        {"kind": "serve_request", "pid": 2, "t": 1.0,
         "kernel": "scan", "ok": True, "wall_s": 0.01},
        {"kind": "serve_request", "pid": 2, "t": 2.0,
         "kernel": "scan", "ok": True, "wall_s": 0.01},
        {"kind": "span", "pid": 2, "t": 2.0, "name": "serve/scan",
         "wall_s": 0.01, "depth": 1},   # untagged span: not joinable
    ]
    assert reqtrace.assemble(events) == {}
    assert reqtrace.untraced_serve_requests(events) == 2
    assert reqtrace.run_budget(events) is None
    jp = tmp_path / "old.jsonl"
    jp.write_text("".join(json.dumps(e) + "\n" for e in events)
                  + "garbage line\n")
    r = _run_tool("trace_report.py", str(jp))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "2 serve_request event(s) carry no request_id" in r.stdout
    assert "no request timelines" in r.stdout


# ---------------------------------------------------------------- #
# verdicts: budget arithmetic + gating                             #
# ---------------------------------------------------------------- #

def test_trace_budget_verdict_rules(monkeypatch):
    from tpukernels.obs import trend

    def budget(**kw):
        e = {"kind": "serve_trace_budget", "socket": "/tmp/a.sock",
             "requests": 10, "traced": 10, "gaps": 0,
             "untraced_serve_requests": 0, "coverage_floor": 0.5,
             "sum_tol": 0.1}
        e.update(kw)
        return e

    # consistent + covered -> ok
    v = trend.analyze_trace_budget(
        [budget(coverage_mean=0.9, sum_ratio_max=0.95)])
    assert v["trace[a.sock]"]["verdict"] == "ok"
    # phase sum past the wall beyond tolerance -> GATES
    v = trend.analyze_trace_budget(
        [budget(coverage_mean=1.5, sum_ratio_max=1.5)])
    assert v["trace[a.sock]"]["verdict"] == "trace_inconsistent"
    # low coverage -> non-gating flag
    v = trend.analyze_trace_budget(
        [budget(coverage_mean=0.2, sum_ratio_max=0.3)])
    assert v["trace[a.sock]"]["verdict"] == "trace_coverage"
    # nothing traced (daemon journaled elsewhere) can never gate
    v = trend.analyze_trace_budget(
        [budget(traced=0)])
    assert v["trace[a.sock]"]["verdict"] == "ok"
    # only the LATEST event per socket is judged (the copy-budget
    # rule): an old bad run is superseded
    v = trend.analyze_trace_budget([
        budget(coverage_mean=1.5, sum_ratio_max=1.5),
        budget(coverage_mean=0.9, sum_ratio_max=0.95),
    ])
    assert v["trace[a.sock]"]["verdict"] == "ok"


def test_coverage_min_knob(monkeypatch):
    from tpukernels.obs import reqtrace

    assert reqtrace.coverage_min() == 0.5
    monkeypatch.setenv("TPK_TRACE_COVERAGE_MIN", "0.25")
    assert reqtrace.coverage_min() == 0.25
    monkeypatch.setenv("TPK_TRACE_COVERAGE_MIN", "1.5")
    with pytest.raises(ValueError):
        reqtrace.coverage_min()
    monkeypatch.setenv("TPK_TRACE_COVERAGE_MIN", "nope")
    with pytest.raises(ValueError):
        reqtrace.coverage_min()


def test_obs_report_check_gates_trace_inconsistent(tmp_path):
    env = _scrubbed_env(None)
    for var, sub in (("TPK_SLO_DIR", "slo"),
                     ("TPK_SCALING_DIR", "scaling")):
        d = tmp_path / sub
        d.mkdir()
        env[var] = str(d)
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({
        "kind": "serve_trace_budget", "socket": "/tmp/a.sock",
        "requests": 5, "traced": 5, "gaps": 0,
        "untraced_serve_requests": 0, "coverage_floor": 0.5,
        "sum_tol": 0.1, "coverage_mean": 1.4, "sum_ratio_max": 1.4,
    }) + "\n")
    root = tmp_path / "root"
    (root / "docs" / "logs").mkdir(parents=True)
    r = _run_tool("obs_report.py", "--check", "--root", str(root),
                  "--journal", str(bad), env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "trace_inconsistent" in r.stdout

    low = tmp_path / "low.jsonl"
    low.write_text(json.dumps({
        "kind": "serve_trace_budget", "socket": "/tmp/a.sock",
        "requests": 5, "traced": 5, "gaps": 0,
        "untraced_serve_requests": 0, "coverage_floor": 0.5,
        "sum_tol": 0.1, "coverage_mean": 0.2, "sum_ratio_max": 0.3,
    }) + "\n")
    r = _run_tool("obs_report.py", "--check", "--root", str(root),
                  "--journal", str(low), env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trace_coverage (non-gating)" in r.stdout


# ---------------------------------------------------------------- #
# the request-id lint                                              #
# ---------------------------------------------------------------- #

def test_request_id_lint_catches_untagged_traced_emit(tmp_path):
    root = tmp_path / "mini"
    (root / "docs").mkdir(parents=True)
    (root / "docs" / "OBSERVABILITY.md").write_text(
        "| `serve_route` | router | stuff |\n\n"
        "Traced kinds (request-id lint): `serve_route` — every "
        "production `journal.emit` of these kinds MUST carry a "
        "`request_id=` field.\n"
    )
    (root / "bench.py").write_text(
        'journal.emit(\n    "serve_route", kernel="scan",\n'
        '    worker=1)\n'
    )
    r = _run_tool("journal_kinds.py", "--root", str(root))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "WITHOUT" in r.stdout and "serve_route" in r.stdout
    assert "bench.py:1" in r.stdout
    # parens inside an f-string error message and apostrophes in a
    # trailing comment must not desync the call scanner;
    # request_id=None (an untraced old client) passes
    (root / "bench.py").write_text(
        'journal.emit(\n'
        '    "serve_route", kernel="scan",  # the worker\'s id\n'
        '    error=f"bad ({x})", request_id=None)\n'
    )
    r = _run_tool("journal_kinds.py", "--root", str(root))
    assert r.returncode == 0, r.stdout + r.stderr


def test_request_id_lint_green_on_this_repo():
    r = _run_tool("journal_kinds.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "traced kind(s) all carry request_id" in r.stdout


# ---------------------------------------------------------------- #
# health_report: per-worker table + spill dedupe                   #
# ---------------------------------------------------------------- #

def test_serve_table_keyed_by_worker_and_deduped():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import health_report

    events = [
        # r1 wedged on worker 0, served by worker 1: ONE request
        _ev("serve_request", "r1", pid=10, t=1.0, kernel="scan",
            ok=False, error="wedged", wall_s=12.0, worker_id="0"),
        _ev("serve_request", "r1", pid=11, t=2.0, kernel="scan",
            ok=True, wall_s=0.4, worker_id="1"),
        # r2/r3 plain successes on worker 1 (the hot worker)
        _ev("serve_request", "r2", pid=11, t=3.0, kernel="scan",
            ok=True, wall_s=0.1, worker_id="1"),
        _ev("serve_request", "r3", pid=11, t=4.0, kernel="scan",
            ok=True, wall_s=0.1, worker_id="1"),
        # an old client without request_id still counts
        {"kind": "serve_request", "pid": 12, "t": 5.0,
         "kernel": "scan", "ok": True, "wall_s": 0.1},
    ]
    out = "\n".join(health_report._serve_table(events))
    assert "keyed kernel@worker" in out
    assert "1 spill/wedge duplicate record(s) deduped" in out
    # the hot worker is VISIBLE: 3 requests on w1, none on w0
    assert "scan@w1" in out and "n=3" in out
    assert "scan@w0" not in out
    # the plain (worker-less) row keeps its own line
    lines = [ln for ln in out.splitlines() if "scan " in ln]
    assert any("n=1" in ln for ln in lines)


# ---------------------------------------------------------------- #
# e2e acceptance: traced loadgen vs a 2-worker fleet with a wedge  #
# ---------------------------------------------------------------- #

def test_fleet_e2e_traced_timelines_across_wedge(tmp_path):
    """The ISSUE-13 acceptance proof: a seeded traced loadgen burst
    against a 2-worker fleet whose scan-bucket home worker wedges →
    every request's timeline reconstructs with request_id joins
    across the router spill, clean phase sums stay within the
    documented tolerance of the client-observed walls, and the
    shape-mix records match the seeded mix."""
    from tpukernels.obs import reqtrace
    from tpukernels.obs import trend as obs_trend
    from tpukernels.serve import router as serve_router

    primary = serve_router.ring_order("scan|8192|-", 2)[0]
    # both attempts on the home worker wedge -> wedged answer ->
    # router spills to the sibling and cools the home
    plan = json.dumps({"wedge_dispatch": {
        "kernel": "scan", "times": 2,
        "env": {"TPK_SERVE_WORKER_ID": str(primary)},
    }})
    seed = 7
    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_REQUEST_TIMEOUT_S": "2",
        "TPK_ROUTE_COOLDOWN_S": "120",
        "TPK_FAULT_PLAN": plan,
        "TPK_TRACE": "1",
    }) as (front, journal, env):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--serve", front, "--kernel", "scan", "--requests", "6",
             "--rate", "20", "--seed", str(seed), "--shapes", "probe"],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr

    events = _events(journal)
    tls = reqtrace.assemble(events)
    # EVERY request this run minted reconstructs: the warm request
    # plus the 6 scheduled ones — ids are seeded-deterministic
    # suffixes under the run's pid scope (lg<seed>-<pid>-...)
    import re

    want_ids = {e["request_id"] for e in events
                if e.get("kind") == "serve_client_request"}
    assert len(want_ids) == 7
    assert all(
        re.fullmatch(rf"lg{seed}-\d+-(warm-scan|\d{{5}})", rid)
        for rid in want_ids
    ), want_ids
    assert want_ids <= set(tls)
    for rid in want_ids:
        assert tls[rid]["final"] is not None, rid
        assert tls[rid]["final"]["ok"] is True, rid
        assert tls[rid]["segments"], f"{rid} has no span evidence"
    # the wedged request joined HOME and SIBLING across the spill
    spilled = [t for t in tls.values() if t["spills"]]
    assert spilled, "no spilled timeline reconstructed"
    sp = spilled[0]
    assert len(sp["server"]) == 2
    assert {e.get("worker_id") for e in sp["server"]} == {"0", "1"}
    assert any(g["kind"] == "abandoned-worker" for g in sp["gaps"])
    assert sp["clean"] is False
    # clean requests: accounted phases within tolerance of the
    # client-observed wall (the documented consistency contract)
    clean = [t for t in tls.values()
             if t["clean"] and t["coverage"] is not None]
    assert clean, "no clean traced timeline"
    for t in clean:
        assert t["coverage"] <= 1.0 + reqtrace.SUM_TOL, \
            (t["request_id"], t["coverage"])
    # the loadgen-stamped budget exists and does NOT gate
    budgets = [e for e in events
               if e.get("kind") == "serve_trace_budget"]
    assert budgets and budgets[-1]["traced"] >= len(want_ids) - 1
    verdicts = obs_trend.analyze_trace_budget(events)
    assert all(v["verdict"] != "trace_inconsistent"
               for v in verdicts.values()), verdicts
    # shape-mix records match the seeded mix: every serve_request of
    # this run carries scan's requested probe shape (4093), pre-pad
    reqs = [e for e in events if e.get("kind") == "serve_request"
            and e.get("request_id") in want_ids]
    assert reqs
    assert all(e.get("shapes") == [[4093]] for e in reqs)
    assert all(e.get("dtypes") == ["int32"] for e in reqs)
    # trace_report renders the waterfalls from the same journal
    rid3 = next(r for r in want_ids if r.endswith("-00003"))
    r = _run_tool("trace_report.py", str(journal),
                  "--request", rid3)
    assert r.returncode == 0, r.stdout + r.stderr
    assert f"request {rid3}" in r.stdout
    assert "critical path:" in r.stdout
    r = _run_tool("trace_report.py", str(journal))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "phase attribution" in r.stdout
