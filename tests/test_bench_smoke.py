"""CPU smoke of every headline bench function (bench.py).

The metric functions normally run only on a live chip (bench.py exits
early when the tunnel is dead), so Python-level bitrot in them — a
renamed kernel, a signature drift — would otherwise surface for the
first time during UNATTENDED revalidation (tools/tpu_wait_and_
revalidate.sh fires bench.py the moment the tunnel answers).
TPK_BENCH_SMOKE=1 collapses the slope repeat counts; tiny shapes keep
interpret-mode Pallas fast. Values returned are meaningless and only
checked for being positive numbers.
"""

import os
import subprocess
import sys

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_functions_cpu_smoke():
    body = """
import os
os.environ["TPK_BENCH_SMOKE"] = "1"
import bench

for fn, kw in [
    (bench.bench_sgemm, {"m": 128}),
    (bench.bench_stencil, {"n": 128}),
    (bench.bench_stencil3d, {"n": 32}),
    (bench.bench_saxpy, {"n": 1 << 12}),
    (bench.bench_saxpy_stream, {"n": 1 << 12}),
    (bench.bench_nbody, {"n": 256}),
    (bench.bench_scan_hist, {"n": 1 << 12}),
]:
    v = fn(**kw)
    assert isinstance(v, float) and v > 0, (fn.__name__, v)
    print(f"smoke {fn.__name__}: ok")
print("SMOKE-OK")
"""
    env = _scrubbed_env(fake_devices=None)  # CPU, never the tunnel
    proc = subprocess.run(
        [sys.executable, "-c", body],
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "SMOKE-OK" in proc.stdout
