"""Stamp/resume logic of the revalidation queue, proven on CPU.

tools/tpu_revalidate.sh resumes across tunnel flaps via per-day step
stamps; that logic was previously inline (testable only by running the
whole chip-bound queue) and is now sourced from
tools/revalidate_lib.sh, so a stubbed queue here drives the EXACT
step_done/stamp/run_step implementation the real queue runs:
a failed step never stamps, a stamped step is skipped on retry, and
TPK_REVALIDATE_FORCE=1 re-runs everything.
"""

import datetime
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "tools", "revalidate_lib.sh")

QUEUE = """\
#!/bin/bash
# stubbed revalidation queue: same set -e gate discipline as the real
# one, steps log their execution and step_b fails until $FLAG exists
set -e -o pipefail
stamp_dir="$STAMP_DIR"
mkdir -p "$stamp_dir"
source "$LIB"
step_a() { echo a >> "$RUNLOG"; }
step_b() { echo b >> "$RUNLOG"; [ -e "$FLAG" ]; }
step_c() { echo c >> "$RUNLOG"; }
run_step a step_a
run_step b step_b
run_step c step_c
echo QUEUE-GREEN
"""


@pytest.fixture
def queue(tmp_path):
    script = tmp_path / "queue.sh"
    script.write_text(QUEUE)
    runlog = tmp_path / "runlog"
    runlog.write_text("")
    env = dict(os.environ)
    env.update(
        STAMP_DIR=str(tmp_path / "stamps"),
        LIB=LIB,
        RUNLOG=str(runlog),
        FLAG=str(tmp_path / "flag"),
    )
    env.pop("TPK_REVALIDATE_FORCE", None)

    def run(force=False):
        e = dict(env)
        if force:
            e["TPK_REVALIDATE_FORCE"] = "1"
        return subprocess.run(
            ["bash", str(script)], env=e, capture_output=True,
            text=True, timeout=60,
        )

    def ran():
        return runlog.read_text().split()

    return run, ran, tmp_path


def _stamps(tmp_path):
    d = tmp_path / "stamps"
    return sorted(p.name.split("_")[0] for p in d.iterdir()) if d.is_dir() else []


def test_failed_step_never_stamps_and_blocks_the_queue(queue):
    run, ran, tmp = queue
    r = run()
    assert r.returncode != 0          # set -e: the gate fails loudly
    assert ran() == ["a", "b"]        # c never reached
    assert _stamps(tmp) == ["a"]      # the FAILED step did not stamp


def test_stamped_steps_skip_on_retry_until_green(queue):
    run, ran, tmp = queue
    assert run().returncode != 0      # first attempt: b fails
    (tmp / "flag").touch()            # "the tunnel recovered"
    r = run()
    assert r.returncode == 0, r.stdout + r.stderr
    assert "QUEUE-GREEN" in r.stdout
    # a was NOT re-run (stamped); b and c ran on the retry
    assert ran() == ["a", "b", "b", "c"]
    assert _stamps(tmp) == ["a", "b", "c"]
    # fully-green queue: every step skips
    assert run().returncode == 0
    assert ran() == ["a", "b", "b", "c"]


def test_force_reruns_everything(queue):
    run, ran, tmp = queue
    (tmp / "flag").touch()
    assert run().returncode == 0
    assert ran() == ["a", "b", "c"]
    r = run(force=True)               # same-day code change escape hatch
    assert r.returncode == 0, r.stdout + r.stderr
    assert ran() == ["a", "b", "c", "a", "b", "c"]


def test_stamps_are_per_day(queue):
    """A stamp from YESTERDAY must not satisfy today's queue — the
    wall-clock scoping the lib documents."""
    run, ran, tmp = queue
    stamps = tmp / "stamps"
    stamps.mkdir()
    y = (datetime.date.today() - datetime.timedelta(days=1)).isoformat()
    (stamps / f"a_{y}.done").touch()
    (tmp / "flag").touch()
    assert run().returncode == 0
    assert ran() == ["a", "b", "c"]   # yesterday's stamp ignored


def test_real_queue_scripts_parse_and_source_the_lib():
    """bash -n both scripts (the queue is unattended — a syntax error
    would surface mid-recovery) and pin the queue to the sourced lib
    so these tests keep covering the deployed logic."""
    for script in ("tools/tpu_revalidate.sh", "tools/revalidate_lib.sh",
                   "tools/tpu_wait_and_revalidate.sh"):
        r = subprocess.run(
            ["bash", "-n", os.path.join(REPO, script)],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, (script, r.stderr)
    with open(os.path.join(REPO, "tools", "tpu_revalidate.sh")) as f:
        body = f.read()
    assert "source tools/revalidate_lib.sh" in body
    assert "step_done()" not in body  # no drifted inline copy
