"""Stamp/resume logic of the revalidation queue, proven on CPU.

tools/revalidate_lib.sh is the shell face of the per-day step-stamp
contract (the python supervisor reads/writes the same files —
tests/test_supervisor.py proves the cross-equivalence): a failed step
never stamps, a stamped step is skipped on retry, and
TPK_REVALIDATE_FORCE=1 re-runs everything. Since the supervisor PR the
stamps are also GIT-AWARE: each stamp records the HEAD sha, and a
later commit touching the step's inputs re-runs the step
automatically — retiring the documented same-day-code-change footgun
(FORCE survives as the explicit manual override).
"""

import datetime
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "tools", "revalidate_lib.sh")

QUEUE = """\
#!/bin/bash
# stubbed revalidation queue: same set -e gate discipline as the real
# one, steps log their execution and step_b fails until $FLAG exists
set -e -o pipefail
stamp_dir="$STAMP_DIR"
mkdir -p "$stamp_dir"
source "$LIB"
step_a() { echo a >> "$RUNLOG"; }
step_b() { echo b >> "$RUNLOG"; [ -e "$FLAG" ]; }
step_c() { echo c >> "$RUNLOG"; }
run_step a step_a
run_step b step_b
run_step c step_c
echo QUEUE-GREEN
"""


@pytest.fixture
def queue(tmp_path):
    script = tmp_path / "queue.sh"
    script.write_text(QUEUE)
    runlog = tmp_path / "runlog"
    runlog.write_text("")
    env = dict(os.environ)
    env.update(
        STAMP_DIR=str(tmp_path / "stamps"),
        LIB=LIB,
        RUNLOG=str(runlog),
        FLAG=str(tmp_path / "flag"),
    )
    env.pop("TPK_REVALIDATE_FORCE", None)

    def run(force=False):
        e = dict(env)
        if force:
            e["TPK_REVALIDATE_FORCE"] = "1"
        return subprocess.run(
            ["bash", str(script)], env=e, capture_output=True,
            text=True, timeout=60,
        )

    def ran():
        return runlog.read_text().split()

    return run, ran, tmp_path


def _stamps(tmp_path):
    d = tmp_path / "stamps"
    return sorted(p.name.split("_")[0] for p in d.iterdir()) if d.is_dir() else []


def test_failed_step_never_stamps_and_blocks_the_queue(queue):
    run, ran, tmp = queue
    r = run()
    assert r.returncode != 0          # set -e: the gate fails loudly
    assert ran() == ["a", "b"]        # c never reached
    assert _stamps(tmp) == ["a"]      # the FAILED step did not stamp


def test_stamped_steps_skip_on_retry_until_green(queue):
    run, ran, tmp = queue
    assert run().returncode != 0      # first attempt: b fails
    (tmp / "flag").touch()            # "the tunnel recovered"
    r = run()
    assert r.returncode == 0, r.stdout + r.stderr
    assert "QUEUE-GREEN" in r.stdout
    # a was NOT re-run (stamped); b and c ran on the retry
    assert ran() == ["a", "b", "b", "c"]
    assert _stamps(tmp) == ["a", "b", "c"]
    # fully-green queue: every step skips
    assert run().returncode == 0
    assert ran() == ["a", "b", "b", "c"]


def test_force_reruns_everything(queue):
    run, ran, tmp = queue
    (tmp / "flag").touch()
    assert run().returncode == 0
    assert ran() == ["a", "b", "c"]
    r = run(force=True)               # same-day code change escape hatch
    assert r.returncode == 0, r.stdout + r.stderr
    assert ran() == ["a", "b", "c", "a", "b", "c"]


def test_stamps_are_per_day(queue):
    """A stamp from YESTERDAY must not satisfy today's queue — the
    wall-clock scoping the lib documents."""
    run, ran, tmp = queue
    stamps = tmp / "stamps"
    stamps.mkdir()
    y = (datetime.date.today() - datetime.timedelta(days=1)).isoformat()
    (stamps / f"a_{y}.done").touch()
    (tmp / "flag").touch()
    assert run().returncode == 0
    assert ran() == ["a", "b", "c"]   # yesterday's stamp ignored


def test_real_queue_scripts_parse_and_delegate():
    """bash -n all scripts (the queue is unattended — a syntax error
    would surface mid-recovery) and pin the wrappers to the python
    supervisor: the queue logic these tests cover must not silently
    grow a drifted inline copy in shell again."""
    for script in ("tools/tpu_revalidate.sh", "tools/revalidate_lib.sh",
                   "tools/tpu_wait_and_revalidate.sh"):
        r = subprocess.run(
            ["bash", "-n", os.path.join(REPO, script)],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, (script, r.stderr)
    with open(os.path.join(REPO, "tools", "tpu_revalidate.sh")) as f:
        body = f.read()
    assert "exec python tools/revalidate.py" in body
    assert "step_done()" not in body  # no drifted inline copy


@pytest.fixture
def stamp_git_repo(tmp_path):
    """A throwaway git repo for the git-awareness tests (the repo the
    queue runs in is the input source, so the tests need commits)."""
    repo = tmp_path / "gitrepo"
    repo.mkdir()
    (repo / "bench.py").write_text("# v1\n")
    (repo / "README").write_text("r\n")

    def git(*args):
        subprocess.run(
            ["git", "-C", str(repo), *args], check=True, timeout=30,
            capture_output=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t",
                 "GIT_COMMITTER_EMAIL": "t@t"},
        )

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    return repo, git


def _lib_call(repo, stamps, snippet, inputs="bench.py"):
    r = subprocess.run(
        ["bash", "-c",
         f'stamp_dir="{stamps}"; step_inputs="{inputs}"; '
         f'source "{LIB}"; {snippet}'],
        capture_output=True, text=True, timeout=30, cwd=str(repo),
        env={k: v for k, v in os.environ.items()
             if k != "TPK_REVALIDATE_FORCE"},
    )
    return r


def test_stamp_records_head_and_commit_touching_inputs_reruns(
        tmp_path, stamp_git_repo):
    """The retired footgun: a same-day commit touching a step's
    inputs used to leave a stale stamp unless the operator remembered
    TPK_REVALIDATE_FORCE=1. Now the stamp records the HEAD sha and
    step_done goes stale by itself."""
    repo, git = stamp_git_repo
    stamps = tmp_path / "stamps"
    stamps.mkdir()
    assert _lib_call(repo, stamps, "stamp s1").returncode == 0
    day = datetime.date.today().isoformat()
    sha = (stamps / f"s1_{day}.done").read_text().strip()
    assert len(sha) == 40                  # the stamp carries HEAD
    assert _lib_call(repo, stamps, "step_done s1").returncode == 0
    # unrelated commit: stamp stays good
    (repo / "README").write_text("r2\n")
    git("commit", "-qam", "unrelated")
    assert _lib_call(repo, stamps, "step_done s1").returncode == 0
    # commit touching the inputs: stale, loud, re-runs
    (repo / "bench.py").write_text("# v2\n")
    git("commit", "-qam", "touch bench")
    r = _lib_call(repo, stamps, "step_done s1")
    assert r.returncode != 0
    assert "predates commits touching" in r.stderr


def test_legacy_empty_stamp_stays_wall_clock_only(tmp_path,
                                                  stamp_git_repo):
    """A pre-git-aware (sha-less) stamp from earlier today must keep
    skipping — upgrading the lib mid-day must not re-run a morning's
    green steps."""
    repo, git = stamp_git_repo
    stamps = tmp_path / "stamps"
    stamps.mkdir()
    day = datetime.date.today().isoformat()
    (stamps / f"legacy_{day}.done").write_text("")
    (repo / "bench.py").write_text("# v2\n")
    git("commit", "-qam", "touch bench")
    assert _lib_call(repo, stamps, "step_done legacy").returncode == 0


def test_force_still_overrides_fresh_git_stamp(tmp_path,
                                               stamp_git_repo):
    repo, _git = stamp_git_repo
    stamps = tmp_path / "stamps"
    stamps.mkdir()
    assert _lib_call(repo, stamps, "stamp s1").returncode == 0
    r = _lib_call(repo, stamps,
                  "TPK_REVALIDATE_FORCE=1 step_done s1")
    assert r.returncode != 0               # the explicit override
