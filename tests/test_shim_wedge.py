"""Wedged-flush watchdog tests (c/shim/tpu_shim.c).

A dead axon tunnel can wedge jax.profiler.stop_trace forever inside
the shim's exit-time flush; the shim's watchdog must force the exit
after TPU_KERNELS_FLUSH_TIMEOUT seconds instead of hanging the host.
Driven through the real libtpukernels.so with a stub tpukernels.capi
whose shutdown_from_c sleeps past the deadline — no TPU (or jax)
involved, so the wedge is deterministic and fast.
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHIM = os.path.join(REPO, "c", "bin", "libtpukernels.so")

pytestmark = pytest.mark.skipif(
    not os.path.exists(SHIM), reason="C shim not built (make -C c)"
)


def _write_stub(tmp_path, shutdown_body: str) -> str:
    """A stand-in tpukernels package the shim imports instead of the
    real one (TPU_KERNELS_ROOT wins the sys.path race)."""
    pkg = tmp_path / "stub" / "tpukernels"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "capi.py").write_text(textwrap.dedent(f"""
        def run_from_c(kernel, params_json, addrs):
            return 0

        def shutdown_from_c():
            {shutdown_body}
            return 0
    """))
    return str(tmp_path / "stub")


def _run_host(tmp_path, stub_root: str, timeout_s: int):
    """A Python host that dlopens the shim, inits, and calls
    tpu_shutdown explicitly (ctypes releases the GIL around the call,
    so the shim takes the worker-thread flush path)."""
    host = textwrap.dedent(f"""
        import ctypes
        lib = ctypes.CDLL({SHIM!r})
        assert lib.tpu_init() == 0
        lib.tpu_shutdown()
        print("after-shutdown", flush=True)
    """)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PYTHONPATH", None)  # the stub must win the import
    env["TPU_KERNELS_ROOT"] = stub_root
    env["TPU_KERNELS_FLUSH_TIMEOUT"] = str(timeout_s)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c", host],
        env=env,
        capture_output=True,
        text=True,
        timeout=60,
        cwd=str(tmp_path),
    )
    return proc, time.monotonic() - t0


def test_wedged_flush_forces_exit(tmp_path):
    """shutdown_from_c never returns: the watchdog must kill the host
    with the distinctive status 86 (explicit shutdown, real exit code
    unknown) well before the 60s harness timeout."""
    stub = _write_stub(tmp_path, "import time; time.sleep(120)")
    proc, elapsed = _run_host(tmp_path, stub, timeout_s=3)
    assert proc.returncode == 86, proc.stdout + proc.stderr
    assert "wedged" in proc.stderr
    assert "after-shutdown" not in proc.stdout
    assert elapsed < 30


def test_healthy_flush_exits_normally(tmp_path):
    """Control: a prompt flush must not trip the watchdog — the host
    runs to completion with rc=0."""
    stub = _write_stub(tmp_path, "pass")
    proc, _ = _run_host(tmp_path, stub, timeout_s=3)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "after-shutdown" in proc.stdout
    assert "wedged" not in proc.stderr
