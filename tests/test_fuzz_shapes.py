"""Seeded shape-fuzz sweep: every kernel vs its oracle on awkward
shapes (primes, off-by-one from tile boundaries, tiny). Padding and
edge-mask logic is where silent corruption hides; this pins it across
the whole surface with one bounded, deterministic sweep.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpukernels.kernels.histogram import histogram
from tpukernels.kernels.nbody import nbody_reference, nbody_step
from tpukernels.kernels.scan import exclusive_scan, inclusive_scan
from tpukernels.kernels.sgemm import sgemm
from tpukernels.kernels.stencil import (
    jacobi2d,
    jacobi2d_reference,
    jacobi3d,
    jacobi3d_reference,
)
from tpukernels.kernels.vector_add import saxpy

# off tile boundaries on purpose: primes, 128k+-1, sub-tile
_SIZES = [1, 7, 127, 128, 129, 1000, 4093, 65537]


@pytest.mark.parametrize("n", _SIZES)
def test_fuzz_saxpy(rng, n):
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    y = jnp.asarray(rng.standard_normal(n), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(saxpy(0.7, x, y)),
        0.7 * np.asarray(x) + np.asarray(y),
        rtol=1e-6,
        atol=1e-6,
    )


@pytest.mark.parametrize("n", _SIZES)
def test_fuzz_scan_exact(rng, n):
    x = jnp.asarray(rng.integers(-1000, 1000, n), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(inclusive_scan(x)), np.cumsum(np.asarray(x))
    )
    np.testing.assert_array_equal(
        np.asarray(exclusive_scan(x)),
        np.concatenate([[0], np.cumsum(np.asarray(x))[:-1]]),
    )


@pytest.mark.parametrize("n", [1, 129, 4093])
@pytest.mark.parametrize("nbins", [1, 3, 17, 256])
def test_fuzz_histogram_exact(rng, n, nbins):
    x = jnp.asarray(rng.integers(0, nbins, n), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(histogram(x, nbins)),
        np.bincount(np.asarray(x), minlength=nbins),
    )


@pytest.mark.parametrize(
    "m,n,k",
    [(1, 1, 1), (3, 5, 7), (127, 129, 130), (8, 513, 64), (256, 1, 300)],
)
def test_fuzz_sgemm(rng, m, n, k):
    a = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
    c = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    out = np.asarray(sgemm(1.25, a, b, -0.5, c, precision="float32"))
    want = 1.25 * (
        np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    ) - 0.5 * np.asarray(c, np.float64)
    np.testing.assert_allclose(out, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("shape", [(3, 3), (5, 129), (31, 100), (130, 7)])
def test_fuzz_jacobi2d(rng, shape):
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(jacobi2d(x, 3)),
        np.asarray(jacobi2d_reference(x, 3)),
        rtol=1e-5,
        atol=1e-6,
    )


@pytest.mark.parametrize("shape", [(3, 3, 3), (5, 9, 129), (17, 8, 50)])
def test_fuzz_jacobi3d(rng, shape):
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(jacobi3d(x, 2)),
        np.asarray(jacobi3d_reference(x, 2)),
        rtol=1e-5,
        atol=1e-6,
    )


@pytest.mark.parametrize("n", [1, 5, 127, 300])
def test_fuzz_nbody(rng, n):
    state = tuple(
        jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(6)
    ) + (jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),)
    out = nbody_step(*state, steps=2)
    ref = nbody_reference(*state, steps=2)
    for got, want in zip(out, ref):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-4, atol=5e-5
        )


def test_fuzz_sgemm_tile_knobs(rng, monkeypatch):
    """Random tile PREFERENCES (the tools/sgemm_tune.py surface) x
    awkward shapes vs the f64 oracle: whatever TPK_SGEMM_{BM,BN,BK}
    ask for, _pick_block's alignment/padding must keep results exact
    (bf16_6x path, so tolerance is fp32-tight). Seeded and bounded
    like the rest of the sweep."""
    knob_rng = np.random.default_rng(7)
    shapes = [(37, 129, 65), (128, 256, 130), (9, 1000, 17)]
    for m, n, k in shapes:
        a = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
        c = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
        want = 1.25 * (
            np.asarray(a, np.float64) @ np.asarray(b, np.float64)
        ) - 0.5 * np.asarray(c, np.float64)
        for _ in range(3):
            monkeypatch.setenv(
                "TPK_SGEMM_BM", str(int(knob_rng.integers(1, 512))))
            monkeypatch.setenv(
                "TPK_SGEMM_BN", str(int(knob_rng.integers(1, 2048))))
            monkeypatch.setenv(
                "TPK_SGEMM_BK", str(int(knob_rng.integers(1, 2048))))
            out = np.asarray(sgemm(1.25, a, b, -0.5, c,
                                   precision="float32"))
            np.testing.assert_allclose(out, want, rtol=2e-5, atol=2e-5)
