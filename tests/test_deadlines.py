"""CPU suite for end-to-end request deadlines and hedged tail
tolerance (docs/SERVING.md §deadlines, §hedged dispatch; ISSUE 19).

The acceptance headline, all on CPU over Unix sockets: a
``delay_response`` fault holds the scan bucket's home worker's
COMPLETED response on the floor for 5 s — the router's hedge fires at
its own forward-wall percentile, re-issues the SAME request_id to the
ring sibling stamped as a replay, the sibling's response wins the
race, the loser is cancelled best-effort, the client meets a deadline
the faulted worker alone would have blown, and the journal proves
zero duplicate (non-replay) dispatches and zero post-expiry
dispatches. Plus the pure units: skew-free budget arithmetic
(``protocol.deadline_from_header`` / ``stamp_budget``), the batch
coalescing window clamp, WAL-replay expiry and re-stamping, hedge
gating (samples / siblings / budget-cap), cancel phases
(queued / inflight / miss) with their claim_done races, loadgen's
``--deadline-ms`` parse, and the reqtrace assembly of hedge / cancel
/ expiry evidence.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from test_fleet import _fleet, _scan_case
from test_serve import SCAN_BUCKET, _daemon, _events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- #
# pure units: skew-free budget arithmetic                          #
# ---------------------------------------------------------------- #

def test_deadline_header_arithmetic_skew_free():
    """No absolute time crosses the wire: the receiver turns the
    frame's remaining budget into ITS OWN monotonic deadline, and a
    forwarder re-stamps exactly the budget net of time spent here —
    clock skew between processes cannot expire (or resurrect) a
    request."""
    from tpukernels.serve import protocol

    # budget_ms (the per-hop remainder) wins over deadline_ms (total)
    assert protocol.deadline_from_header(
        {"budget_ms": 500.0, "deadline_ms": 9999.0}, now=100.0
    ) == pytest.approx(100.5)
    # a minimal client that only ever stamps the total still works
    assert protocol.deadline_from_header(
        {"deadline_ms": 250.0}, now=10.0
    ) == pytest.approx(10.25)
    # malformed wire values are no-deadline, never a crash surface
    for bad in ("x", True, -1, None, [5], {}):
        assert protocol.deadline_from_header(
            {"budget_ms": bad}, now=0.0) is None
    assert protocol.deadline_from_header({}, now=0.0) is None

    # the one subtraction every expiry check shares, clamped at 0
    assert protocol.budget_ms_remaining(10.25, now=10.0) == (
        pytest.approx(250.0))
    assert protocol.budget_ms_remaining(9.0, now=10.0) == 0.0

    # stamp_budget: a copy with the remainder recomputed; the
    # original header is never mutated, and no deadline = no stamp
    h = {"kernel": "scan"}
    assert protocol.stamp_budget(h, None) is h
    out = protocol.stamp_budget(h, 10.2, now=10.0)
    assert out["budget_ms"] == pytest.approx(200.0)
    assert "budget_ms" not in h

    # three hops, 50 ms spent at each: the budget shrinks by exactly
    # the wall time spent, hop by hop, regardless of any per-process
    # clock offset (each hop re-derives from its OWN now)
    budget = 1000.0
    for hop_clock in (5.0, 10_000.0, 3.0):  # wildly skewed clocks
        dl = protocol.deadline_from_header(
            {"budget_ms": budget}, now=hop_clock)
        sent = protocol.stamp_budget({}, dl, now=hop_clock + 0.05)
        assert sent["budget_ms"] == pytest.approx(budget - 50.0)
        budget = sent["budget_ms"]
    assert budget == pytest.approx(850.0)


def test_default_deadline_knob_parse(monkeypatch):
    from tpukernels.serve import client as serve_client

    monkeypatch.delenv("TPK_DEADLINE_DEFAULT_MS", raising=False)
    assert serve_client.default_deadline_ms() is None
    monkeypatch.setenv("TPK_DEADLINE_DEFAULT_MS", "  ")
    assert serve_client.default_deadline_ms() is None
    monkeypatch.setenv("TPK_DEADLINE_DEFAULT_MS", "0")
    assert serve_client.default_deadline_ms() is None
    monkeypatch.setenv("TPK_DEADLINE_DEFAULT_MS", "2500")
    assert serve_client.default_deadline_ms() == 2500.0
    for bad in ("nope", "-5"):
        monkeypatch.setenv("TPK_DEADLINE_DEFAULT_MS", bad)
        with pytest.raises(ValueError):
            serve_client.default_deadline_ms()


def test_loadgen_deadline_spec_parse():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_loadgen_dl", os.path.join(REPO, "tools", "loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    assert lg._parse_deadline_ms("250") == (250.0, 250.0)
    assert lg._parse_deadline_ms("200:400") == (200.0, 400.0)
    for bad in ("0", "-3", "400:200", "0:100"):
        with pytest.raises(ValueError):
            lg._parse_deadline_ms(bad)


# ---------------------------------------------------------------- #
# pure units: the batch coalescing window clamp                    #
# ---------------------------------------------------------------- #

def test_clamp_window_never_dooms_the_tightest_deadline():
    """Coalescing may delay a deadline-carrying request but never
    doom it: the window is clamped to HALF the tightest remaining
    budget (the other half is the dispatch's), and deadline-free
    members leave the window alone."""
    import types

    from tpukernels.serve import server

    clamp = server.Server._clamp_window
    free = types.SimpleNamespace(deadline_at=None)
    now = time.monotonic()
    tight = types.SimpleNamespace(deadline_at=now + 0.1)
    dead = types.SimpleNamespace(deadline_at=now - 1.0)

    # window 0 stays 0 (nothing to clamp)
    assert clamp(0.0, (tight,)) == 0.0
    # deadline-free batch: untouched
    assert clamp(0.25, (free, free)) == 0.25
    # the tightest member halves it: ~100 ms remaining -> <= 50 ms
    w = clamp(0.25, (free, tight))
    assert 0.0 < w <= 0.051
    # an already-dead member zeroes the wait outright (the dequeue
    # check answers the expiry; waiting longer helps nobody)
    assert clamp(0.25, (tight, dead)) == 0.0


# ---------------------------------------------------------------- #
# pure units: WAL-replay expiry + re-stamp                         #
# ---------------------------------------------------------------- #

def test_wal_replay_expires_dead_budget_and_restamps_live(
        tmp_path, monkeypatch):
    """The WAL bridges a router crash with EPOCH time (``t_wal``):
    at replay, an entry whose budget drained away across the outage
    is skipped as doomed work (journaled ``serve_request_expired``
    where=wal_replay), and a live one is forwarded with its budget
    re-stamped net of the time spent in the WAL."""
    from tpukernels.serve import router as router_mod
    from tpukernels.serve import wal as serve_wal

    monkeypatch.setenv("TPK_SERVE_BUCKETS", SCAN_BUCKET)
    jpath = str(tmp_path / "j.jsonl")
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", jpath)
    r = router_mod.Router(
        str(tmp_path / "front.sock"),
        [str(tmp_path / "w0.sock"), str(tmp_path / "w1.sock")],
    )
    w = serve_wal.Wal(str(tmp_path / "router.wal"))
    r.attach_wal(w)
    w.append("dead", {
        "header": {"v": 1, "op": "dispatch", "id": 1,
                   "request_id": "dead-1", "budget_ms": 50.0},
        "kernel": "scan", "bucket": "scan|8192|-",
        "t_wal": time.time() - 10.0, "p64": [],
    })
    w.append("live", {
        "header": {"v": 1, "op": "dispatch", "id": 2,
                   "request_id": "live-1", "budget_ms": 60_000.0},
        "kernel": "scan", "bucket": "scan|8192|-",
        "t_wal": time.time() - 1.0, "p64": [],
    })
    forwarded = []

    def fake_forward(idx, header, payloads):
        forwarded.append((idx, dict(header)))
        return {"v": 1, "id": header.get("id"), "ok": True}, ()

    monkeypatch.setattr(r, "_forward", fake_forward)
    assert r.replay_wal() == 2

    # the dead entry never reached a worker
    assert [h["request_id"] for _i, h in forwarded] == ["live-1"]
    hdr = forwarded[0][1]
    # re-stamped: net of ~1 s spent in the WAL, and marked a replay
    assert 50_000.0 < hdr["budget_ms"] < 60_000.0
    assert hdr["replay"] == 1
    # the live result is stashed for the reconnecting client's retry
    assert "live-1" in r._stash
    events = _events(jpath)
    exp = [e for e in events if e["kind"] == "serve_request_expired"]
    assert len(exp) == 1
    assert exp[0]["where"] == "wal_replay"
    assert exp[0]["site"] == "router"
    assert exp[0]["request_id"] == "dead-1"
    # both entries settled: nothing left to replay
    assert w.depth() == 0


# ---------------------------------------------------------------- #
# pure units: hedge gating                                         #
# ---------------------------------------------------------------- #

def test_hedge_gating_needs_siblings_samples_and_budget(
        tmp_path, monkeypatch):
    """A hedge only fires on real evidence: >= 2 workers, >=
    HEDGE_MIN_SAMPLES completed forward walls, pctl > 0 — and the
    max-frac budget keeps a fleet-wide slowdown from doubling its
    own load."""
    from tpukernels.serve import router as router_mod

    monkeypatch.setenv("TPK_SERVE_BUCKETS", SCAN_BUCKET)
    w0 = str(tmp_path / "w0.sock")
    w1 = str(tmp_path / "w1.sock")

    # one worker: no sibling to hedge to, ever
    solo = router_mod.Router(str(tmp_path / "f1.sock"), [w0])
    assert solo._hedge_threshold_s() is None

    # pctl 0 = off even with siblings and samples
    monkeypatch.setenv("TPK_ROUTE_HEDGE_PCTL", "0")
    off = router_mod.Router(str(tmp_path / "f2.sock"), [w0, w1])
    for _ in range(router_mod.HEDGE_MIN_SAMPLES + 5):
        off._note_fwd_wall(0.01)
    assert off._hedge_threshold_s() is None
    monkeypatch.delenv("TPK_ROUTE_HEDGE_PCTL")

    r = router_mod.Router(str(tmp_path / "f3.sock"), [w0, w1])
    # not enough samples to trust a tail estimate yet
    for _ in range(router_mod.HEDGE_MIN_SAMPLES - 1):
        r._note_fwd_wall(0.01)
    assert r._hedge_threshold_s() is None
    r._note_fwd_wall(0.01)
    thr = r._hedge_threshold_s()
    assert thr is not None and thr > 0.0

    # the hedge-budget cap (default 0.1 of routed traffic)
    assert not r._hedge_frac_ok()       # no routed traffic yet
    r._routed = 100
    assert r._hedge_frac_ok()
    r._hedged = 9
    assert r._hedge_frac_ok()           # 10 <= 0.1 * 100
    r._hedged = 10
    assert not r._hedge_frac_ok()       # 11 > 0.1 * 100


# ---------------------------------------------------------------- #
# pure units: cancel phases and their races                        #
# ---------------------------------------------------------------- #

class _DummyConn:
    def __init__(self):
        self.sent = []

    def send(self, header, payloads=()):
        self.sent.append(header)
        return 0


def _mk_request(server_mod, serial, request_id):
    return server_mod._Request(
        serial=serial, rid=serial, kernel="scan", statics={},
        arrays=[np.zeros(4, np.int32)], spec=None, pad_frac=0.0,
        bucket="scan|8192|-", conn=_DummyConn(),
        request_id=request_id,
    )


def test_cancel_phases_queued_inflight_miss(tmp_path, monkeypatch):
    """The best-effort ``cancel`` op: a queued loser is dropped
    before it wastes a dispatch, an in-flight one has its answer
    suppressed via the claim_done race (a running dispatch cannot be
    interrupted), and a miss is success too — cancel is advisory,
    never load-bearing."""
    from tpukernels.serve import server as server_mod

    monkeypatch.setenv("TPK_SERVE_BUCKETS", SCAN_BUCKET)
    jpath = str(tmp_path / "j.jsonl")
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", jpath)
    srv = server_mod.Server(socket_path=str(tmp_path / "s.sock"),
                            workers=1)

    # miss: unknown id is still ok (advisory), nothing journaled
    resp = srv._cancel({"request_id": "ghost", "id": 7})
    assert resp["ok"] is True
    assert srv._cancelled == 0

    # queued: dropped before dispatch
    q1 = _mk_request(server_mod, 1, "c-queued")
    srv._q.put_nowait(q1)
    resp = srv._cancel({"request_id": "c-queued"})
    assert resp["ok"] is True
    assert srv._q.depth() == 0
    assert q1.done is True
    assert srv._cancelled == 1

    # inflight: the done flag is claimed, so the worker's eventual
    # answer loses the claim_done race and is discarded unsent
    q2 = _mk_request(server_mod, 2, "c-inflight")
    srv._inflight[q2.serial] = q2
    resp = srv._cancel({"request_id": "c-inflight"})
    assert resp["ok"] is True
    assert q2.claim_done() is False     # the worker's side of the race
    assert srv._cancelled == 2

    # double-cancel race: the second claim finds done already taken
    # and degrades to a miss — the counter moves exactly once
    srv._inflight[3] = q3 = _mk_request(server_mod, 3, "c-race")
    assert srv._cancel({"request_id": "c-race"})["ok"] is True
    assert srv._cancel({"request_id": "c-race"})["ok"] is True
    assert q3.done is True
    assert srv._cancelled == 3

    events = _events(jpath)
    phases = [e.get("phase") for e in events
              if e["kind"] == "serve_cancelled"]
    assert phases == ["queued", "inflight", "inflight"]


# ---------------------------------------------------------------- #
# hedge race: first response wins, loser cancelled                 #
# ---------------------------------------------------------------- #

class _FakeWorker:
    """A protocol-speaking worker avatar: answers dispatches after
    ``delay_s`` (tagged so the test can see who won) and records the
    cancel ops it receives — the router under test is real, the
    workers are scripted."""

    def __init__(self, path, delay_s=0.0, tag="w"):
        self.path, self.delay_s, self.tag = path, delay_s, tag
        self.dispatches: list = []
        self.cancels: list = []
        self.lock = threading.Lock()
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(path)
        self._srv.listen(16)
        threading.Thread(target=self._accept, daemon=True,
                         name=f"fake-{tag}").start()

    def _accept(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        from tpukernels.serve import protocol
        try:
            while True:
                frame = protocol.recv_frame(conn)
                if frame is None:
                    return
                header, _payloads = frame
                if header.get("op") == "cancel":
                    with self.lock:
                        self.cancels.append(header.get("request_id"))
                    protocol.send_frame(conn, {
                        "v": protocol.VERSION, "op": "cancel",
                        "ok": True, "id": header.get("id")})
                    continue
                with self.lock:
                    self.dispatches.append(dict(header))
                if self.delay_s:
                    time.sleep(self.delay_s)
                protocol.send_frame(conn, {
                    "v": protocol.VERSION, "id": header.get("id"),
                    "ok": True, "kind": "result",
                    "served_by": self.tag, "specs": []})
        except (OSError, protocol.ProtocolError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        try:
            self._srv.close()
        except OSError:
            pass


def test_hedge_first_response_wins_and_cancels_loser(
        tmp_path, monkeypatch):
    """The race itself, against scripted workers: the primary sits on
    its answer for 3 s, the hedge fires at the (tiny) forward-wall
    percentile, the sibling's response wins, the loser gets a
    best-effort cancel — and the hedge leg carries the SAME
    request_id stamped as a replay with a shrunken budget (the
    dedupe evidence)."""
    from tpukernels.serve import protocol
    from tpukernels.serve import router as router_mod

    monkeypatch.setenv("TPK_SERVE_BUCKETS", SCAN_BUCKET)
    jpath = str(tmp_path / "j.jsonl")
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", jpath)
    slow = _FakeWorker(str(tmp_path / "w0.sock"), delay_s=3.0,
                       tag="slow")
    fast = _FakeWorker(str(tmp_path / "w1.sock"), delay_s=0.0,
                       tag="fast")
    try:
        r = router_mod.Router(str(tmp_path / "front.sock"),
                              [slow.path, fast.path])
        for _ in range(router_mod.HEDGE_MIN_SAMPLES + 5):
            r._note_fwd_wall(0.005)
        r._routed = 100                  # hedge budget available
        deadline_at = time.monotonic() + 30.0
        header = {"v": protocol.VERSION, "op": "dispatch", "id": 1,
                  "kernel": "scan", "request_id": "hx-1",
                  "deadline_ms": 30_000.0, "budget_ms": 30_000.0}
        t0 = time.perf_counter()
        resp, _payloads, widx, hedged = r._forward_hedged(
            0, [0, 1], header, (), deadline_at, "scan",
            "scan|8192|-", 1, "hx-1", "-")
        wall = time.perf_counter() - t0

        assert hedged is True
        assert widx == 1
        assert resp["ok"] is True and resp["served_by"] == "fast"
        assert wall < 3.0, "winner must not wait for the slow primary"

        # the hedge leg: same request_id, replay-stamped, budget net
        # of the time already burned waiting on the primary
        with fast.lock:
            assert len(fast.dispatches) == 1
            hl = fast.dispatches[0]
        assert hl["request_id"] == "hx-1"
        assert hl["replay"] == 1
        assert 0.0 < hl["budget_ms"] < 30_000.0
        # exactly ONE non-replay dispatch ever left the router
        with slow.lock:
            primaries = list(slow.dispatches)
        assert len(primaries) == 1
        assert not primaries[0].get("replay")

        # the loser got the best-effort cancel
        end = time.monotonic() + 10.0
        while time.monotonic() < end:
            with slow.lock:
                if slow.cancels:
                    break
            time.sleep(0.02)
        with slow.lock:
            assert slow.cancels == ["hx-1"]

        events = _events(jpath)
        hedges = [e for e in events if e["kind"] == "serve_hedged"]
        assert len(hedges) == 1
        assert hedges[0]["from_worker"] == 0
        assert hedges[0]["to_worker"] == 1
        assert hedges[0]["request_id"] == "hx-1"
        assert hedges[0]["threshold_s"] > 0.0
        cancels = [e for e in events
                   if e["kind"] == "serve_cancelled"]
        assert any(e["site"] == "router" and e["to_worker"] == 0
                   for e in cancels)
    finally:
        slow.close()
        fast.close()


# ---------------------------------------------------------------- #
# reqtrace assembly of the new evidence                            #
# ---------------------------------------------------------------- #

def test_reqtrace_assembles_hedge_cancel_and_expiry():
    from tpukernels.obs import reqtrace

    def ev(kind, rid, **kw):
        e = {"kind": kind, "pid": 1, "t": 100.0, "request_id": rid}
        e.update(kw)
        return e

    events = [
        ev("serve_client_request", "h1", kernel="scan", wall_s=0.4,
           ok=True),
        ev("serve_hedged", "h1", kernel="scan", from_worker=0,
           to_worker=1, threshold_s=0.05),
        ev("serve_cancelled", "h1", site="router", to_worker=0,
           kernel="scan"),
        ev("serve_request", "h1", kernel="scan", bucket="scan|8192|-",
           ok=True, wall_s=0.01, worker_id="1", replayed=1),
        ev("serve_client_request", "x1", kernel="scan", wall_s=0.2,
           ok=False, error="expired"),
        ev("serve_request_expired", "x1", site="server",
           where="worker", kernel="scan"),
        ev("serve_client_request", "x2", kernel="scan", wall_s=0.01,
           ok=False, error="deadline infeasible"),
        ev("serve_deadline_infeasible", "x2", kernel="scan",
           bucket="scan|8192|-"),
    ]
    tls = reqtrace.assemble(events)

    h = tls["h1"]
    assert h["hedged"] is True
    assert h["hedges"][0]["to_worker"] == 1
    assert h["cancels"] and h["cancels"][0]["site"] == "router"
    kinds = {g["kind"] for g in h["gaps"]}
    assert "hedged" in kinds and "cancelled" in kinds
    # a hedged request is EXCLUDED from the clean-sum gate, exactly
    # like a replayed one: two server segments are expected, not an
    # inconsistency
    assert h["clean"] is False

    x1 = tls["x1"]
    assert x1["expiries"] and x1["expiries"][0]["where"] == "worker"
    assert any(g["kind"] == "deadline-expired" for g in x1["gaps"])
    assert x1["clean"] is False
    x2 = tls["x2"]
    assert any(g["kind"] == "deadline-infeasible"
               for g in x2["gaps"])


# ---------------------------------------------------------------- #
# e2e: dequeue-time expiry on a live daemon                        #
# ---------------------------------------------------------------- #

def test_daemon_expires_dead_budget_before_dispatch(tmp_path):
    """A budget that dies in the queue is answered ``expired`` — the
    pad/dispatch phases are skipped entirely (zero post-expiry
    dispatch), the client raises ServeExpired, and the SAME
    connection immediately serves a fresh-budget request."""
    from tpukernels.serve import client as serve_client

    with _daemon(tmp_path, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
    }) as (sock, journal, _proc):
        x, want = _scan_case()
        with serve_client.ServeClient(sock, timeout_s=60) as c:
            c.next_request_id = "exp-1"
            c.next_deadline_ms = 0.01    # dead on arrival
            with pytest.raises(serve_client.ServeExpired):
                c.dispatch("scan", x)
            # deliberately NOT a ServeRejected: backpressure retries
            # must not absorb a doomed budget
            c.next_request_id = "ok-1"
            c.next_deadline_ms = 60_000.0
            out = c.dispatch("scan", x)
            np.testing.assert_array_equal(out, want)

    events = _events(journal)
    exp = [e for e in events if e.get("kind") == "serve_request_expired"]
    assert any(e["request_id"] == "exp-1" and e["site"] == "server"
               for e in exp)
    served = [e for e in events if e.get("kind") == "serve_request"]
    # zero post-expiry dispatch: the expired id never reached a worker
    assert not any(e.get("request_id") == "exp-1" for e in served)
    assert any(e.get("request_id") == "ok-1" and e.get("ok")
               for e in served)


# ---------------------------------------------------------------- #
# e2e headline: hedged tail tolerance on a live fleet              #
# ---------------------------------------------------------------- #

def test_fleet_hedge_rides_out_slow_worker_100pct_goodput(tmp_path):
    """The ISSUE 19 acceptance headline: ``delay_response`` holds the
    scan home worker's COMPLETED response for 5 s (dispatch done,
    delivery late — the slow-but-alive tail), the router hedges to
    the sibling at its own forward-wall percentile, first response
    wins, and EVERY deadline-carrying request in the run meets its
    budget — with zero duplicate (non-replay) dispatches and zero
    post-expiry dispatches in the journal. Also the admission
    triage: a budget that is already dust is refused at the front
    door (``serve_deadline_infeasible``) without a WAL fsync or a
    worker queue slot."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import router as router_mod

    primary, sibling = router_mod.ring_order("scan|8192|-", 2)[:2]
    # every=2 + times=1: the direct compile-warming scan below is
    # response call 1 (no fault) so the fault fires on exactly the
    # headline dispatch — a WARM worker held 5 s at the response,
    # not a cold compile the hedge would cancel mid-flight
    plan = json.dumps({"delay_response": {
        "kernel": "scan", "delay_s": 5.0, "every": 2, "times": 1,
        "env": {"TPK_SERVE_WORKER_ID": str(primary)},
    }})
    goodput = []  # (deadline_ms, wall_ms) per deadline-carrying req
    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_SHM": "0",
        # roomy hedge budget: a stray hedge on a priming request
        # (cold-compile wall vs a warm-walls percentile) must not
        # starve the headline dispatch of its hedge
        "TPK_ROUTE_HEDGE_MAX_FRAC": "0.5",
        "TPK_FAULT_PLAN": plan,
    }) as (front, journal, _env):
        x, want = _scan_case()
        va_x = np.arange(1024, dtype=np.float32)
        va_y = np.ones(1024, dtype=np.float32)
        # warm the scan compile on BOTH workers directly (the fleet
        # spawner's per-worker sockets), so the headline measures the
        # delayed RESPONSE of a healthy worker — the slow-but-alive
        # tail — not compile latency
        fleet_d = os.path.dirname(front)
        for i in (0, 1):
            wsock = os.path.join(fleet_d, f"worker{i}", "serve.sock")
            with serve_client.ServeClient(wsock, timeout_s=120) as w:
                # explicit ids: the default mint is pid-scoped with a
                # per-CLIENT sequence, and these two clients share
                # this test's pid
                w.next_request_id = f"warm-{i}"
                np.testing.assert_array_equal(
                    w.dispatch("scan", x), want)
        with serve_client.ServeClient(front, timeout_s=120) as c:
            # admission triage: a dust budget is refused, not queued
            c.next_request_id = "inf-1"
            c.next_deadline_ms = 0.001
            with pytest.raises(serve_client.ServeExpired):
                c.dispatch("scan", x)

            # prime the router's forward-wall histogram past
            # HEDGE_MIN_SAMPLES with a kernel the fault ignores
            for i in range(router_mod.HEDGE_MIN_SAMPLES + 5):
                c.next_request_id = f"prime-{i}"
                c.next_deadline_ms = 60_000.0
                t0 = time.perf_counter()
                out = c.dispatch("vector_add", np.float32(2.0),
                                 va_x, va_y)
                goodput.append(
                    (60_000.0, (time.perf_counter() - t0) * 1e3))
                np.testing.assert_allclose(out, 2.0 * va_x + va_y,
                                           rtol=1e-6)

            # the headline dispatch: the home worker's response is
            # held 5 s; the hedge must land the answer well inside
            # the 60 s budget (and well under the fault delay —
            # first response wins, nobody waited for the loser)
            c.next_request_id = "hedge-1"
            c.next_deadline_ms = 60_000.0
            t0 = time.perf_counter()
            out = c.dispatch("scan", x)
            wall = time.perf_counter() - t0
            goodput.append((60_000.0, wall * 1e3))
            np.testing.assert_array_equal(out, want)
            assert wall < 5.0, (
                "the hedge winner must not wait out the delayed "
                f"primary (wall {wall:.2f}s)")

    # 100% goodput: every completed deadline-carrying request met
    # its budget
    assert goodput and all(w <= dl for dl, w in goodput)

    events = _events(journal)
    hedges = [e for e in events if e.get("kind") == "serve_hedged"]
    assert any(e["from_worker"] == primary
               and e["to_worker"] == sibling
               and e["request_id"] == "hedge-1" for e in hedges)
    # the loser was cancelled best-effort (router side always
    # journals; the worker side may also record its phase)
    assert any(e.get("kind") == "serve_cancelled" for e in events)

    served = [e for e in events if e.get("kind") == "serve_request"]
    # zero duplicate side effects: at most one NON-replay dispatch
    # per request_id, fleet-wide (the hedge leg rides the replay
    # idempotency contract)
    by_id: dict = {}
    for e in served:
        if e.get("request_id"):
            by_id.setdefault(e["request_id"], []).append(e)
    for rid, recs in by_id.items():
        assert sum(1 for e in recs if not e.get("replayed")) <= 1, (
            f"duplicate non-replay dispatch for {rid}")
    # the hedged request has both legs: one primary, one replay
    legs = by_id.get("hedge-1", [])
    assert len(legs) == 2
    assert sorted(bool(e.get("replayed")) for e in legs) == [
        False, True]
    # zero post-expiry dispatch: the refused id never reached a worker
    assert "inf-1" not in by_id
    assert any(e.get("kind") == "serve_deadline_infeasible"
               and e.get("request_id") == "inf-1" for e in events)
