"""CPU suite for live fleet telemetry (docs/OBSERVABILITY.md §live
telemetry / §daily rollups; ISSUE 18).

Covers the streaming-snapshot plane end to end: the periodic flusher's
delta/seq encoding and the one shared ``merge_journal_metrics`` fold
(final ``metrics`` event authoritative, deduped by (pid, seq), the
two encodings NEVER summed), the byte-identical-stdout proof with the
flusher on vs off, the read-only ``stats`` op against a live daemon
and a live 2-worker fleet mid-burst (``serve_ctl top --once`` renders
nonzero rows for every worker), the kill -9 acceptance (a SIGKILLed
worker's last snapshot — at most one flush interval old — survives
into ``obs_report``), daily-rollup determinism + torn/stale/date
rejection, the NON-GATING ``p99_creep`` long-horizon verdict, and
multi-day adapt mining (``TPK_ADAPT_WINDOW_DAYS``) including a
``serve_optimize propose`` that mines a valid candidate from a 3-day
rollup window with no same-day serve traffic.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from test_distributed import _scrubbed_env
from test_fleet import _ctl, _fleet, _scan_case
from test_serve import SCAN_BUCKET, _daemon, _events

from tpukernels.obs import metrics as obs_metrics
from tpukernels.resilience import journal as _journal
from tpukernels.serve import adapt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _snap(pid, seq, counters, hists=None, gauges=None):
    return {"kind": "metrics_snapshot", "pid": pid, "seq": seq,
            "site": "flush:t", "counters": counters,
            "gauges": gauges or {}, "histograms": hists or {}}


def _req_line(kernel, n, pad_frac, wall_s, pid=101, ok=True):
    """One synthetic journal serve_request line (vector_add-shaped:
    scalar + two length-n operands)."""
    return {"kind": "serve_request", "pid": pid, "kernel": kernel,
            "ok": ok, "shapes": [[], [n], [n]],
            "dtypes": ["float32"] * 3, "pad_frac": pad_frac,
            "bucketed": True, "wall_s": wall_s, "t": 0.0}


# ---------------------------------------------------------------- #
# snapshot encoding: seq, deltas, the shared merge fold            #
# ---------------------------------------------------------------- #

def test_snapshot_delta_and_seq_arithmetic(tmp_path, monkeypatch):
    """Counters ride as DELTAS, histograms full-cumulative only when
    moved, seq is monotonic, and the merge fold reconstructs the
    exact totals."""
    jp = tmp_path / "health.jsonl"
    monkeypatch.setenv("TPK_HEALTH_JOURNAL", str(jp))
    obs_metrics.reset()
    try:
        # nothing ever recorded: no event, no age
        assert obs_metrics.emit_periodic_snapshot("t") is None
        assert obs_metrics.last_flush_age_s() is None
        obs_metrics.inc("t.a", 2)
        obs_metrics.inc("t.b")
        obs_metrics.observe("t.h", 0.5)
        assert obs_metrics.emit_periodic_snapshot("t") == 1
        assert obs_metrics.last_flush_age_s() < 5.0
        obs_metrics.inc("t.a", 3)
        assert obs_metrics.emit_periodic_snapshot("t") == 2
        obs_metrics.observe("t.h", 1.0)
        assert obs_metrics.emit_periodic_snapshot("t") == 3
        # no movement at all still emits (the heartbeat), empty deltas
        assert obs_metrics.emit_periodic_snapshot("t") == 4
        obs_metrics.emit_snapshot("atexit:test")  # the final word
        events = _events(jp)
        snaps = [e for e in events if e["kind"] == "metrics_snapshot"]
        assert [e["seq"] for e in snaps] == [1, 2, 3, 4]
        assert snaps[0]["counters"] == {"t.a": 2, "t.b": 1}
        # second flush: only the moved counter, as a delta; the
        # unmoved histogram is omitted entirely
        assert snaps[1]["counters"] == {"t.a": 3}
        assert snaps[1]["histograms"] == {}
        # moved histogram rides full-cumulative: latest row stands
        # alone
        assert snaps[0]["histograms"]["t.h"]["count"] == 1
        assert snaps[2]["histograms"]["t.h"]["count"] == 2
        assert snaps[2]["histograms"]["t.h"]["sum"] == \
            pytest.approx(1.5)
        assert snaps[3]["counters"] == {}
        merged = obs_metrics.merge_journal_metrics(events)
        st = merged[os.getpid()]
        assert st["final"]
        assert st["counters"]["t.a"] == 5
        assert st["counters"]["t.b"] == 1
        assert st["histograms"]["t.h"]["count"] == 2
    finally:
        obs_metrics.reset()


def test_merge_dedupes_by_pid_seq_and_never_sums_final():
    """The double-count seam, pinned: a pid's final ``metrics`` event
    SUPERSEDES its snapshot stream (never summed with it), replayed
    (pid, seq) duplicates fold once, and a pid with no final flush is
    reconstructed from its deduped stream in seq order."""
    events = [
        _snap(1, 1, {"a": 2}),
        _snap(1, 2, {"a": 3, "b": 1}),
        _snap(1, 2, {"a": 3, "b": 1}),  # replayed line: folded ONCE
        {"kind": "metrics", "pid": 1, "site": "atexit:x",
         "counters": {"a": 100}, "gauges": {}, "histograms": {}},
        # out-of-order delivery folds in seq order
        _snap(2, 2, {"a": 30}),
        _snap(2, 1, {"a": 4}, gauges={"g": 7.0}),
    ]
    merged = obs_metrics.merge_journal_metrics(events)
    # pid 1 streamed AND exited cleanly: the final word wins outright
    # (2+3+100 == 105 would be the double-count bug)
    assert merged[1]["final"]
    assert merged[1]["counters"] == {"a": 100}
    # pid 2 died hard: deltas summed once each, dedup by (pid, seq)
    assert not merged[2]["final"]
    assert merged[2]["seq"] == 2
    assert merged[2]["counters"] == {"a": 34}
    assert merged[2]["gauges"] == {"g": 7.0}


def test_histogram_pad_frac_pools_across_processes():
    """The adapt miner's pad histogram reads through the merge fold:
    sum-of-sums over sum-of-counts across pids, final-vs-snapshot
    encodings never summed for one pid."""
    row_a = {"count": 4, "sum": 1.0}
    events = [
        _snap(1, 1, {}, hists={"serve.bucket_pad_frac":
                               {"count": 2, "sum": 0.9}}),
        {"kind": "metrics", "pid": 1, "site": "atexit:x",
         "counters": {}, "gauges": {},
         "histograms": {"serve.bucket_pad_frac": row_a}},
        _snap(2, 1, {}, hists={"serve.bucket_pad_frac":
                               {"count": 1, "sum": 0.5}}),
    ]
    assert adapt.histogram_pad_frac(events) == pytest.approx(1.5 / 5)
    assert adapt.histogram_pad_frac([]) is None


def test_flush_interval_knob_fail_loud():
    assert obs_metrics.flush_interval_s({}) is None
    for raw in ("", " ", "0", "off", "none", "false", "OFF"):
        assert obs_metrics.flush_interval_s(
            {"TPK_METRICS_FLUSH_S": raw}) is None
    assert obs_metrics.flush_interval_s(
        {"TPK_METRICS_FLUSH_S": "0.25"}) == 0.25
    for bad in ("-1", "abc", "0x2"):
        with pytest.raises(ValueError, match="TPK_METRICS_FLUSH_S"):
            obs_metrics.flush_interval_s({"TPK_METRICS_FLUSH_S": bad})


# ---------------------------------------------------------------- #
# the flusher thread: byte-identical stdout, journal evidence      #
# ---------------------------------------------------------------- #

def test_flusher_stdout_byte_identical_on_vs_off(tmp_path):
    """The TPK_TRACE proof pattern: a clean run's stdout is
    byte-identical with the flusher on vs off — only the journal
    grows ``metrics_snapshot`` events (auto-started at import from
    the env knob, no code opt-in)."""
    body = textwrap.dedent("""
        import time
        from tpukernels.obs import metrics
        for _ in range(8):
            metrics.inc("proof.ticks")
            metrics.observe("proof.wall_s", 0.01)
            time.sleep(0.05)
        print("proof:", metrics.snapshot()["counters"]["proof.ticks"])
    """)
    outs, journals = [], []
    for tag, extra in (("off", {}),
                       ("on", {"TPK_METRICS_FLUSH_S": "0.1"})):
        jp = tmp_path / f"health_{tag}.jsonl"
        env = _scrubbed_env(None)
        env["TPK_HEALTH_JOURNAL"] = str(jp)
        env.update(extra)
        r = subprocess.run([sys.executable, "-c", body], cwd=REPO,
                           env=env, capture_output=True, timeout=120)
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
        journals.append(_events(jp))
    assert outs[0] == outs[1], "flusher must not perturb stdout"
    off_ev, on_ev = journals
    assert [e for e in off_ev if e["kind"] == "metrics_snapshot"] == []
    snaps = [e for e in on_ev if e["kind"] == "metrics_snapshot"]
    assert len(snaps) >= 2, "0.4s run at 0.1s interval must flush"
    assert [e["seq"] for e in snaps] == \
        list(range(1, len(snaps) + 1))
    assert all(e["site"].startswith("flush:") for e in snaps)
    # both runs still carry the unchanged atexit final
    for evs in journals:
        final = [e for e in evs if e["kind"] == "metrics"]
        assert len(final) == 1
        assert final[0]["counters"]["proof.ticks"] == 8
    # and the merge agrees with the final on both
    for evs in journals:
        (st,) = obs_metrics.merge_journal_metrics(evs).values()
        assert st["final"] and st["counters"]["proof.ticks"] == 8


# ---------------------------------------------------------------- #
# the stats op: daemon, fleet, serve_ctl top                       #
# ---------------------------------------------------------------- #

def test_stats_op_daemon_live(tmp_path):
    """A lone daemon answers the read-only stats op with its live
    metric snapshot, pad-pool state and flusher age; the ping pong
    carries ``last_snapshot_age_s`` for ``serve_ctl status``."""
    from tpukernels.serve import client as serve_client

    extra = {"TPK_SERVE_BUCKETS": SCAN_BUCKET,
             "TPK_METRICS_FLUSH_S": "0.2"}
    with _daemon(tmp_path, env_extra=extra) as (sock, journal, proc):
        x, want = _scan_case()
        with serve_client.ServeClient(sock, timeout_s=30) as c:
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
            time.sleep(0.5)  # past one flush interval
            pong = c.ping()
            st = c.stats()
        assert "last_snapshot_age_s" in pong
        assert st["ok"] and st["op"] == "stats"
        assert st["role"] == "daemon"
        assert st["served"] >= 1
        counters = st["metrics"]["counters"]
        assert counters["serve.requests.scan"] >= 1
        wall = st["metrics"]["histograms"]["serve.wall_s.scan"]
        assert wall["count"] >= 1 and wall["p99"] > 0
        # the 6000-element request padded up into the 8192 avatar:
        # the staging pool holds that bucket's buffer
        assert any(v["bufs"] >= 1 and v["bytes"] > 0
                   for v in st["pad_pool"].values())
        # flusher alive: age bounded by the interval (+ scheduling
        # slack), never None
        assert st["last_snapshot_age_s"] is not None
        assert st["last_snapshot_age_s"] < 5.0


def test_fleet_stats_top_and_kill9_snapshot_survival(tmp_path):
    """The live-fleet acceptance: mid-burst, the router's stats op
    aggregates both workers and ``serve_ctl top --once`` renders a
    nonzero rps/p50/p99/served row for EVERY worker; after a kill -9
    the dead worker's telemetry — its last ``metrics_snapshot``, at
    most one flush interval old — survives into ``obs_report``."""
    from tpukernels.serve import client as serve_client

    interval = 0.2
    extra = {"TPK_SERVE_BUCKETS": SCAN_BUCKET,
             "TPK_METRICS_FLUSH_S": str(interval)}
    with _fleet(tmp_path, n=2, env_extra=extra) as (front, journal,
                                                    env):
        x, want = _scan_case()
        va = np.arange(1024, dtype=np.float32)
        stop = threading.Event()
        errors: list = []

        def burst():
            try:
                with serve_client.ServeClient(front,
                                              timeout_s=30) as c:
                    while not stop.is_set():
                        # scan|8192 primaries on worker0,
                        # vector_add|1024 on worker1 (ring math
                        # pinned in test_fleet) - every worker earns
                        # nonzero rows
                        np.testing.assert_array_equal(
                            c.dispatch("scan", x), want)
                        c.dispatch("vector_add", np.float32(2.0),
                                   va, va)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=burst) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            # mid-burst: poll the fleet view until BOTH workers have
            # served traffic
            deadline = time.monotonic() + 60
            while True:
                with serve_client.ServeClient(front,
                                              timeout_s=10) as c:
                    st = c.stats()
                assert st["ok"] and st["role"] == "router"
                ws = st.get("worker_stats") or []
                if (len(ws) == 2 and all(w for w in ws)
                        and all(w["served"] >= 3 for w in ws)):
                    break
                assert time.monotonic() < deadline, \
                    f"fleet never warmed both workers: {ws}"
                time.sleep(0.2)
            fleet_row = st["fleet"]
            assert fleet_row["answering"] == 2
            assert fleet_row["served"] == sum(w["served"] for w in ws)
            # mid-burst dashboard: one frame, rc 0, nonzero rows for
            # every worker
            r = _ctl(env, "top", "--once")
            assert r.returncode == 0, r.stdout + r.stderr
            assert "workers=2/2" in r.stdout
            rows = {}
            for line in r.stdout.splitlines():
                parts = line.split()
                if parts and parts[0] in ("worker0", "worker1"):
                    rows[parts[0]] = parts
            assert set(rows) == {"worker0", "worker1"}
            for name, parts in rows.items():
                rps, p50, p99 = parts[2], parts[3], parts[4]
                depth, served = parts[5], parts[7]
                assert float(rps) > 0, (name, parts)
                assert float(p50) > 0 and float(p99) > 0
                assert "/" in depth  # depth/queue_max rendered
                assert int(served) >= 3
                assert parts[9] != "-"  # snap_age: flusher alive
            # the status satellite: snap_age per worker from the pong
            r = _ctl(env, "status")
            assert r.returncode == 0
            assert "snap_age=" in r.stdout
        finally:
            stop.set()
            for t in threads:
                t.join(30)
        assert not errors, errors
        # ---- kill -9: the snapshot survives the worker ---------- #
        time.sleep(3 * interval)  # a post-burst flush lands
        pidfile = os.path.join(str(tmp_path / "f"), "fleet",
                               "worker0", "serve.pid")
        with open(pidfile) as f:
            wpid = int(f.readline().strip())
        t_kill = time.time()
        os.kill(wpid, signal.SIGKILL)
        events = _events(journal)
        snaps = [e for e in events
                 if e.get("kind") == "metrics_snapshot"
                 and e.get("pid") == wpid]
        assert snaps, "killed worker never flushed a snapshot"
        # bounded loss: the last snapshot is at most one interval old
        # (generous scheduling slack for a loaded CI box)
        assert t_kill - snaps[-1]["t"] <= interval + 2.0
        merged = obs_metrics.merge_journal_metrics(events)
        st = merged[wpid]
        assert not st["final"], "SIGKILL cannot have flushed atexit"
        assert st["seq"] == max(e["seq"] for e in snaps)
        assert st["counters"].get("serve.requests.scan", 0) >= 3
        # and obs_report renders the dead worker from its stream
        r = subprocess.run(
            [sys.executable, os.path.join("tools", "obs_report.py"),
             "--journal", journal],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=300,
        )
        assert f"[pid {wpid}]" in r.stdout, r.stdout + r.stderr
        line = next(ln for ln in r.stdout.splitlines()
                    if f"[pid {wpid}]" in ln)
        assert "no final flush" in line


# ---------------------------------------------------------------- #
# daily rollups: determinism, rejection, retention                 #
# ---------------------------------------------------------------- #

def _write_journal(path, lines):
    with open(path, "w") as f:
        for e in lines:
            f.write(json.dumps(e) + "\n")


def test_rollup_determinism_and_rejection(tmp_path, monkeypatch,
                                          capsys):
    from tpukernels.obs import rollup

    monkeypatch.setenv("TPK_ROLLUP_DIR", str(tmp_path / "roll"))
    monkeypatch.setenv("TPK_HEALTH_JOURNAL",
                       str(tmp_path / "health_t.jsonl"))
    rollup.reset()
    day = "2026-08-01"
    jp = tmp_path / f"health_{day}.jsonl"
    _write_journal(jp, (
        [_req_line("vector_add", 128, 0.875, 0.002)] * 3
        + [_req_line("vector_add", 64, 0.9, 0.001, ok=False)]
        + [_snap(101, 1, {"serve.requests.vector_add": 2})]
        + [{"kind": "metrics", "pid": 101, "site": "atexit:x",
            "counters": {"serve.requests.vector_add": 3},
            "gauges": {}, "histograms": {}}]
        + [{"kind": "serve_start", "pid": 101}]
    ))
    p = rollup.write_day(day, paths=[str(jp)])
    assert os.path.basename(p) == f"rollup_{day}.json"
    b1 = open(p, "rb").read()
    rollup.reset()
    assert open(rollup.write_day(day, paths=[str(jp)]),
                "rb").read() == b1, "re-rolling must be byte-identical"
    art = rollup.load_day(day)
    assert art["date"] == day and art["schema"] == rollup.SCHEMA
    assert art["kinds"]["serve_request"] == 4
    # only OK requests feed the latency rows
    assert art["requests"]["vector_add"]["count"] == 3
    assert art["requests"]["vector_add"]["p99"] > 0
    # counters through the merge fold: final supersedes the
    # snapshot stream, never summed (3, not 5)
    assert art["counters"]["serve.requests.vector_add"] == 3
    mix = art["shape_mix"]["vector_add"]
    assert mix[0]["count"] == 3
    # a rollup_written event landed in the live journal
    ev, _ = _journal.load_events([str(tmp_path / "health_t.jsonl")])
    assert any(e["kind"] == "rollup_written" and e["date"] == day
               for e in ev)
    capsys.readouterr()
    # stale jax: rejected loudly, read as absent
    stale = json.load(open(p))
    stale["jax"] = "0.0.0-stale"
    sp = str(tmp_path / "roll" / "rollup_2026-08-02.json")
    json.dump(dict(stale, date="2026-08-02"), open(sp, "w"))
    assert rollup.load_day("2026-08-02") is None
    assert "rollup rejected" in capsys.readouterr().err
    # torn file: rejected, never parsed as empty state
    tp = str(tmp_path / "roll" / "rollup_2026-08-03.json")
    open(tp, "w").write('{"schema": 1, "date": "2026-08-0')
    assert rollup.load_day("2026-08-03") is None
    assert "rollup rejected" in capsys.readouterr().err
    # filename/date mismatch: a renamed artifact must not impersonate
    # another day
    mp = str(tmp_path / "roll" / "rollup_2026-08-04.json")
    open(mp, "w").write(b1.decode())
    assert rollup.load_day("2026-08-04") is None
    assert "rollup rejected" in capsys.readouterr().err
    # the series loader skips the bad days and keeps the good one
    series = rollup.load_series()
    assert [d for d, _ in series] == [day]
    # and obs_report's full-report section renders the day (pids is
    # a COUNT in the artifact — regression pin for the len() crash)
    env = _scrubbed_env(None)
    env["TPK_ROLLUP_DIR"] = str(tmp_path / "roll")
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "health_t.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "obs_report.py"),
         "--journal", str(jp)],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "== daily rollups (1 day(s)" in r.stdout
    assert f"{day}: 7 event(s), 1 pid(s), 3 request(s)" in r.stdout
    # retention: an ancient artifact is pruned, recent ones kept
    old = str(tmp_path / "roll" / "rollup_2020-01-01.json")
    open(old, "w").write(b1.decode())
    gone = rollup.prune(retention_days=90, today="2026-08-06")
    assert [os.path.basename(g) for g in gone] == \
        ["rollup_2020-01-01.json"]
    assert os.path.exists(p) and not os.path.exists(old)
    rollup.reset()


# ---------------------------------------------------------------- #
# p99_creep: the long-horizon verdict                              #
# ---------------------------------------------------------------- #

def _day(d, p99, count=50):
    return (d, {"requests": {"scan": {"count": count, "p99": p99}}})


def test_p99_creep_fires_on_drift_quiet_on_flat_and_spikes():
    from tpukernels.obs import trend

    drifting = [_day(f"2026-08-0{i}", v) for i, v in
                enumerate([1.0, 1.01, 1.02, 1.03, 1.2], start=1)]
    v = trend.analyze_p99_creep(drifting)["p99_creep[scan]"]
    assert v["verdict"] == "p99_creep"
    assert v["days"] == 5 and v["latest_date"] == "2026-08-05"
    assert any("non-gating" in f for f in v["flags"])
    # flat series: quiet — each day inside the 5% band
    flat = [_day(f"2026-08-0{i}", v) for i, v in
            enumerate([1.0, 1.0, 1.01, 1.0, 1.02], start=1)]
    assert trend.analyze_p99_creep(flat)["p99_creep[scan]"][
        "verdict"] == "ok"
    # a mid-window spike that RECOVERED must not flag the flat tail
    spike = [_day(f"2026-08-0{i}", v) for i, v in
             enumerate([1.0, 1.5, 1.0, 1.0, 1.02], start=1)]
    assert trend.analyze_p99_creep(spike)["p99_creep[scan]"][
        "verdict"] == "ok"
    # latest over the median but NOT the worst day in the window:
    # still recovering from day 1, not creeping
    recover = [_day(f"2026-08-0{i}", v) for i, v in
               enumerate([2.0, 1.0, 1.0, 1.5], start=1)]
    assert trend.analyze_p99_creep(recover)["p99_creep[scan]"][
        "verdict"] == "ok"
    # under the evidence floor: no_data, never a finding
    thin = trend.analyze_p99_creep(drifting[:2])["p99_creep[scan]"]
    assert thin["verdict"] == "no_data" and thin["days"] == 2
    # zero-count rows contribute nothing
    empty = trend.analyze_p99_creep(
        [_day("2026-08-01", 1.0, count=0)])
    assert empty == {}


# ---------------------------------------------------------------- #
# multi-day adapt mining (TPK_ADAPT_WINDOW_DAYS)                   #
# ---------------------------------------------------------------- #

def test_window_days_knob_fail_loud(monkeypatch):
    monkeypatch.delenv("TPK_ADAPT_WINDOW_DAYS", raising=False)
    assert adapt.window_days() == 1
    monkeypatch.setenv("TPK_ADAPT_WINDOW_DAYS", "3")
    assert adapt.window_days() == 3
    for bad in ("0", "-1", "1.5", "abc"):
        monkeypatch.setenv("TPK_ADAPT_WINDOW_DAYS", bad)
        with pytest.raises(ValueError, match="TPK_ADAPT_WINDOW_DAYS"):
            adapt.window_days()


def test_window_mix_folds_prior_rollups_never_today(tmp_path,
                                                    monkeypatch):
    """days=N mines today's journal + the N-1 prior rollup days; a
    rollup dated today is SKIPPED (today's live journal already
    carries that traffic — folding both would double-count)."""
    from tpukernels.obs import rollup

    monkeypatch.setenv("TPK_ROLLUP_DIR", str(tmp_path / "roll"))
    monkeypatch.setenv("TPK_HEALTH_JOURNAL",
                       str(tmp_path / "health_t.jsonl"))
    rollup.reset()
    today = "2026-08-07"
    for day, n in (("2026-08-05", 4), ("2026-08-06", 6)):
        jp = tmp_path / f"health_{day}.jsonl"
        _write_journal(
            jp, [_req_line("vector_add", 128, 0.5, 0.001)] * n)
        rollup.write_day(day, paths=[str(jp)])
    # a same-day rollup exists too — it must NOT be folded
    jp = tmp_path / f"health_{today}.jsonl"
    _write_journal(jp, [_req_line("vector_add", 128, 0.5, 0.001)] * 9)
    rollup.write_day(today, paths=[str(jp)])
    live = [_req_line("vector_add", 128, 0.5, 0.001)] * 2
    mix, used = adapt.window_mix(live, days=3, end_date=today)
    assert used == 3
    assert adapt.mix_requests(mix) == 2 + 4 + 6
    row = mix["vector_add"][0]
    assert row["count"] == 12
    assert row["pad_frac_sum"] == pytest.approx(6.0)
    # days=1: today's journal alone, rollups untouched
    mix1, used1 = adapt.window_mix(live, days=1, end_date=today)
    assert used1 == 1 and adapt.mix_requests(mix1) == 2
    # a window larger than the series uses what exists, reported
    # honestly
    mix9, used9 = adapt.window_mix(live, days=9, end_date=today)
    assert used9 == 3 and adapt.mix_requests(mix9) == 12
    rollup.reset()


def test_propose_mines_3day_rollup_window_without_today_traffic(
        tmp_path, monkeypatch):
    """The acceptance proof: with ZERO same-day serve traffic,
    ``serve_optimize propose`` under TPK_ADAPT_WINDOW_DAYS=3 mines
    the prior days' rollup shape mix into a valid split candidate."""
    from tpukernels.obs import rollup

    roll_dir = str(tmp_path / "roll")
    adapt_dir = str(tmp_path / "adapt")
    monkeypatch.setenv("TPK_ROLLUP_DIR", roll_dir)
    monkeypatch.setenv("TPK_HEALTH_JOURNAL",
                       str(tmp_path / "health_t.jsonl"))
    rollup.reset()
    # two prior days of hot (128,) traffic against a 1024 avatar:
    # 60 requests >= the 50-request evidence floor, pad ~0.875
    for day in ("2026-08-05", "2026-08-06"):
        jp = tmp_path / f"health_{day}.jsonl"
        _write_journal(
            jp, [_req_line("vector_add", 128, 0.875, 0.001)] * 30)
        assert rollup.write_day(day, paths=[str(jp)])
    today_journal = str(tmp_path / "health_today.jsonl")
    open(today_journal, "w").close()  # no same-day traffic at all
    env = _scrubbed_env(None)
    env["TPK_ROLLUP_DIR"] = roll_dir
    env["TPK_ADAPT_DIR"] = adapt_dir
    env["TPK_ADAPT_WINDOW_DAYS"] = "3"
    env["TPK_HEALTH_JOURNAL"] = today_journal
    env["TPK_SERVE_BUCKETS"] = json.dumps(
        {"vector_add": {"args": [["f32", []], ["f32", [1024]],
                                 ["f32", [1024]]], "statics": {}}})
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "serve_optimize.py"),
         "propose", "--journal", today_journal],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "3-day window" in r.stdout
    assert "2 prior rollup day(s)" in r.stdout
    assert "proposed 1 split(s)" in r.stdout
    # the candidate validates through the standard artifact read
    monkeypatch.setenv("TPK_ADAPT_DIR", adapt_dir)
    cand = adapt.load()
    assert cand is not None
    splits = [a for a in cand["proposals"] if a["action"] == "split"]
    assert len(splits) == 1 and splits[0]["kernel"] == "vector_add"
    assert splits[0]["spec"]["args"][1] == ["f32", [128]]
    # the evidence trail records the window that fed it
    ev, _ = _journal.load_events([today_journal])
    prop = next(e for e in ev if e["kind"] == "adapt_proposed")
    assert prop["window_days"] == 3
    assert prop["requests_mined"] == 60
    rollup.reset()
