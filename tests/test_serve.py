"""CPU suite for the kernel-serving daemon (docs/SERVING.md;
ISSUE 10).

Covers the tentpole contracts without a TPU: protocol framing
roundtrips, shape-bucket math (pad-up never pad-down, waste cap,
pad/unpad correctness against the integrity oracles), the service
loop itself — concurrent clients served through the daemon's SHARED
per-process executable memo with exactly one compile per (kernel,
bucket) asserted from ``aot_hit``/``aot_miss`` journal evidence —
batching-window coalescing, backpressure rejection under a full
queue, the wedged-worker → abandon → requeue-once chaos path via
``TPK_FAULT_PLAN``, a byte-identical clean-path proof (responses and
daemon stdout identical with journaling/tracing on vs off), the capi
client route, and the e2e ``loadgen --serve`` → slo.json →
``obs_report --check`` proof.
"""

import contextlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from test_distributed import _scrubbed_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a small scan avatar so the CPU tests prove the pad math without
# materializing the 4M-element record shape
SCAN_BUCKET = json.dumps(
    {"scan": {"args": [["i32", [8192]]], "statics": {}},
     "vector_add": {
         "args": [["f32", []], ["f32", [1024]], ["f32", [1024]]],
         "statics": {}}}
)


def _events(journal_path):
    with open(journal_path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _aot_bucket_events(events, kernel, dim):
    """aot_hit/aot_miss events whose key is ``kernel`` compiled at a
    shape containing ``dim`` (the key's base field may carry
    ``@tuned=``/statics suffixes)."""
    out = []
    for e in events:
        if e.get("kind") not in ("aot_hit", "aot_miss"):
            continue
        parts = (e.get("key") or "").split("|")
        if len(parts) < 2:
            continue
        if parts[0].split("@")[0] == kernel and dim in parts[1]:
            out.append(e)
    return out


@contextlib.contextmanager
def _daemon(tmp_path, env_extra=None, tag="d"):
    """Spawn ``python -m tpukernels.serve`` on a tmp socket with an
    isolated journal; yields (sock, journal_path, proc) and reaps the
    daemon (SIGTERM — the clean ``serve_stop`` path) on exit."""
    d = tmp_path / tag
    d.mkdir(exist_ok=True)
    sock = str(d / "s.sock")
    journal = str(d / "health.jsonl")
    env = _scrubbed_env(None)
    env["TPK_SERVE_DIR"] = str(d)
    env["TPK_HEALTH_JOURNAL"] = journal
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpukernels.serve", "--socket", sock],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    from tpukernels.serve import client as serve_client

    try:
        deadline = time.monotonic() + 60
        while True:
            if proc.poll() is not None:
                raise AssertionError(
                    f"daemon died rc={proc.returncode}: "
                    f"{proc.communicate()[1]}"
                )
            try:
                with serve_client.ServeClient(sock, timeout_s=5) as c:
                    c.ping()
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        yield sock, journal, proc
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(20)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(10)


# ---------------------------------------------------------------- #
# protocol                                                         #
# ---------------------------------------------------------------- #

def test_protocol_roundtrip():
    import socket as socket_mod

    from tpukernels.serve import protocol

    a, b = socket_mod.socketpair()
    try:
        arrays = [np.float32(2.5), np.arange(7, dtype=np.int32),
                  np.ones((3, 4), np.float32)]
        specs, payloads = protocol.pack_arrays(arrays)
        protocol.send_frame(
            a, {"op": "dispatch", "id": 3, "kernel": "x",
                "statics": {"iters": 2}, "args": specs},
            payloads,
        )
        header, got_payloads = protocol.recv_frame(b)
        assert header == {"op": "dispatch", "id": 3, "kernel": "x",
                          "statics": {"iters": 2}, "args": specs}
        got = protocol.unpack_arrays(header["args"], got_payloads)
        for orig, back in zip(arrays, got):
            np.testing.assert_array_equal(np.asarray(orig), back)
            assert np.asarray(orig).dtype == back.dtype
        # a zero-payload frame (ping) roundtrips too
        protocol.send_frame(b, {"op": "ping"})
        header, payloads = protocol.recv_frame(a)
        assert header == {"op": "ping"} and payloads == []
        # clean EOF at a frame boundary is None, not an error
        b.close()
        assert protocol.recv_frame(a) is None
    finally:
        a.close()
        with contextlib.suppress(OSError):
            b.close()


def test_protocol_rejects_garbage():
    import socket as socket_mod

    from tpukernels.serve import protocol

    a, b = socket_mod.socketpair()
    try:
        a.sendall(b"GET / HTTP/1.1\r\n" + b"\0" * 16)
        with pytest.raises(protocol.ProtocolError, match="magic"):
            protocol.recv_frame(b)
        with pytest.raises(protocol.ProtocolError, match="dtype"):
            protocol.pack_arrays([np.ones(3, np.float64)])
        with pytest.raises(protocol.ProtocolError, match="needs"):
            protocol.unpack_arrays(
                [{"shape": [8], "dtype": "int32"}], [b"\0" * 4]
            )
    finally:
        a.close()
        b.close()


def test_protocol_rejects_malformed_lens():
    """A frame whose ``_lens`` is not a list of non-negative ints must
    raise ProtocolError (the poisoned-connection contract) — not a
    TypeError that would escape the daemon's client loop and kill the
    handler thread."""
    import socket as socket_mod

    from tpukernels.serve import protocol

    for lens in (None, "xx", {"n": 4}, [-4, 4], [2.5], [True]):
        a, b = socket_mod.socketpair()
        try:
            hb = json.dumps({"op": "ping", "_lens": lens}).encode()
            a.sendall(
                protocol._PREAMBLE.pack(protocol.MAGIC, len(hb), 0) + hb
            )
            with pytest.raises(protocol.ProtocolError,
                               match="_lens|disagree"):
                protocol.recv_frame(b)
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------- #
# bucket math                                                      #
# ---------------------------------------------------------------- #

def test_bucket_pad_up_never_down(monkeypatch):
    from tpukernels.serve import bucketing

    monkeypatch.setenv("TPK_SERVE_BUCKETS", SCAN_BUCKET)
    monkeypatch.setenv("TPK_SERVE_MAX_PAD_FRAC", "0.9")
    # under the avatar: buckets, with the right waste fraction
    spec, frac = bucketing.bucket_for(
        "scan", [np.zeros(6000, np.int32)], {}
    )
    assert spec is not None
    assert frac == pytest.approx(1.0 - 6000 / 8192)
    # exact fit: pad_frac 0
    spec, frac = bucketing.bucket_for(
        "scan", [np.zeros(8192, np.int32)], {}
    )
    assert spec is not None and frac == 0.0
    # OVER the avatar: never padded down
    spec, why = bucketing.bucket_for(
        "scan", [np.zeros(10000, np.int32)], {}
    )
    assert spec is None and why == "over-avatar"
    # waste over the cap: dispatch natively
    monkeypatch.setenv("TPK_SERVE_MAX_PAD_FRAC", "0.1")
    spec, why = bucketing.bucket_for(
        "scan", [np.zeros(6000, np.int32)], {}
    )
    assert spec is None and why == "pad-over-cap"
    # alien statics select a different program: no bucket
    monkeypatch.setenv("TPK_SERVE_MAX_PAD_FRAC", "0.9")
    spec, why = bucketing.bucket_for(
        "scan", [np.zeros(6000, np.int32)], {"iters": 3}
    )
    assert spec is None and why == "statics-mismatch"
    # wrong dtype never buckets
    spec, why = bucketing.bucket_for(
        "scan", [np.zeros(6000, np.float32)], {}
    )
    assert spec is None and why == "layout-mismatch"
    # fail-loud knob parse
    monkeypatch.setenv("TPK_SERVE_MAX_PAD_FRAC", "1.5")
    with pytest.raises(ValueError, match="TPK_SERVE_MAX_PAD_FRAC"):
        bucketing.bucket_for("scan", [np.zeros(6000, np.int32)], {})


def test_inconsistent_operands_never_bucket(monkeypatch):
    """Cross-operand shape disagreements registry.dispatch would
    reject (sgemm inner dims, mismatched vector lengths) must not be
    padded into a plausible-but-wrong answer: they dispatch natively
    and fail honestly."""
    from tpukernels.serve import bucketing

    monkeypatch.setenv("TPK_SERVE_BUCKETS", json.dumps({
        "vector_add": {"args": [["f32", []], ["f32", [1024]],
                                ["f32", [1024]]], "statics": {}},
        "sgemm": {"args": [["f32", []], ["f32", [64, 64]],
                           ["f32", [64, 64]], ["f32", []],
                           ["f32", [64, 64]]], "statics": {}},
    }))
    monkeypatch.setenv("TPK_SERVE_MAX_PAD_FRAC", "0.9")
    spec, why = bucketing.bucket_for(
        "vector_add",
        [np.float32(1.0), np.zeros(900, np.float32),
         np.zeros(1000, np.float32)], {},
    )
    assert spec is None and why == "inconsistent-args"
    spec, why = bucketing.bucket_for(
        "sgemm",
        [np.float32(1.0), np.zeros((48, 40), np.float32),
         np.zeros((32, 48), np.float32), np.float32(0.0),
         np.zeros((48, 48), np.float32)], {},
    )
    assert spec is None and why == "inconsistent-args"
    # consistent non-exact shapes still bucket
    spec, frac = bucketing.bucket_for(
        "sgemm",
        [np.float32(1.0), np.zeros((48, 40), np.float32),
         np.zeros((40, 48), np.float32), np.float32(0.0),
         np.zeros((48, 48), np.float32)], {},
    )
    assert spec is not None and frac > 0


def test_stencil_has_no_pad_rule(monkeypatch):
    """Padding a stencil changes its boundary condition — only an
    exact avatar fit may bucket."""
    from tpukernels.serve import bucketing

    monkeypatch.setenv(
        "TPK_SERVE_BUCKETS",
        json.dumps({"stencil2d": {"args": [["f32", [64, 256]]],
                                  "statics": {"iters": 2}}}),
    )
    spec, why = bucketing.bucket_for(
        "stencil2d", [np.zeros((40, 200), np.float32)], {"iters": 2}
    )
    assert spec is None and why == "no-pad-rule"
    spec, frac = bucketing.bucket_for(
        "stencil2d", [np.zeros((64, 256), np.float32)], {"iters": 2}
    )
    assert spec is not None and frac == 0.0


def test_pad_unpad_matches_oracles(monkeypatch):
    """Pad + dispatch-at-avatar + unpad must equal dispatch-at-native
    for every kernel with a pad rule — proven against the integrity
    layer's jnp oracles (the golden authority) at the canary shapes,
    with avatars a few elements larger."""
    import importlib

    from tpukernels.resilience import integrity
    from tpukernels.serve import bucketing

    grown = {
        "vector_add": {"args": [["f32", []], ["f32", [1037]],
                                ["f32", [1037]]], "statics": {}},
        "sgemm": {"args": [["f32", []], ["f32", [48, 80]],
                           ["f32", [80, 64]], ["f32", []],
                           ["f32", [48, 64]]], "statics": {}},
        "scan": {"args": [["i32", [4128]]], "statics": {}},
        "scan_exclusive": {"args": [["i32", [4128]]], "statics": {}},
        "histogram": {"args": [["i32", [4128]]],
                      "statics": {"nbins": 256}},
        "scan_histogram": {"args": [["i32", [4128]]],
                           "statics": {"nbins": 256}},
        "nbody": {"args": [["f32", [224]]] * 7,
                  "statics": {"dt": 1e-3, "eps": 1e-2, "steps": 1}},
    }
    monkeypatch.setenv("TPK_SERVE_BUCKETS", json.dumps(grown))
    monkeypatch.setenv("TPK_SERVE_MAX_PAD_FRAC", "0.9")
    for kernel, spec in grown.items():
        mod_name, attr = integrity.ORACLES[kernel].split(":")
        oracle = getattr(importlib.import_module(mod_name), attr)
        args = integrity._build_args(kernel)
        statics = dict(integrity.CANARY_CONFIGS[kernel]["statics"])
        np_args = [
            np.float32(a) if isinstance(a, float)
            else np.int32(a) if isinstance(a, int) else a
            for a in args
        ]
        matched, frac = bucketing.bucket_for(kernel, np_args, statics)
        assert matched is not None and 0.0 < frac <= 0.9, (kernel, frac)
        padded, meta = bucketing.pad_args(kernel, matched, np_args)
        out_pad = oracle(*padded, **statics)
        outs = tuple(
            np.asarray(o)
            for o in (out_pad if isinstance(out_pad, (tuple, list))
                      else (out_pad,))
        )
        unpadded = bucketing.unpad_outputs(kernel, meta, outs)
        want = oracle(*args, **statics)
        wants = tuple(
            np.asarray(o)
            for o in (want if isinstance(want, (tuple, list))
                      else (want,))
        )
        assert len(unpadded) == len(wants), kernel
        kind, rtol, atol = integrity.tolerance(kernel)
        for got, ref in zip(unpadded, wants):
            assert got.shape == ref.shape, (kernel, got.shape, ref.shape)
            if kind == "exact":
                np.testing.assert_array_equal(got, ref, err_msg=kernel)
            else:
                np.testing.assert_allclose(
                    got, ref, rtol=rtol, atol=atol, err_msg=kernel
                )


# ---------------------------------------------------------------- #
# the service loop                                                 #
# ---------------------------------------------------------------- #

def test_concurrent_clients_share_one_compile_per_bucket(tmp_path):
    """Three concurrent clients, two kernels, mixed (bucketable)
    shapes: every response is correct and the daemon compiled each
    (kernel, bucket) EXACTLY once — the shared executable memo,
    asserted from aot_hit/aot_miss journal evidence. The capi client
    route rides the same daemon."""
    from tpukernels.serve import client as serve_client

    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_WORKERS": "3",
        "TPK_SERVE_BATCH_WINDOW_MS": "0",
    }) as (sock, journal, proc):
        lengths = [5000, 6000, 7000, 8000, 8192]
        errors = []

        def client_run(seed):
            rng = np.random.default_rng(seed)
            try:
                with serve_client.ServeClient(sock, timeout_s=120) as c:
                    for n in lengths:
                        x = rng.integers(-50, 50, n).astype(np.int32)
                        out = c.dispatch("scan", x)
                        np.testing.assert_array_equal(
                            out, np.cumsum(x, dtype=np.int64
                                           ).astype(np.int32)
                        )
                        assert out.shape == (n,)
                    x = rng.standard_normal(1024).astype(np.float32)
                    y = rng.standard_normal(1024).astype(np.float32)
                    out = c.dispatch("vector_add", np.float32(2.0), x, y)
                    np.testing.assert_allclose(out, 2.0 * x + y,
                                               rtol=1e-6, atol=1e-6)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))

        threads = [threading.Thread(target=client_run, args=(s,))
                   for s in (1, 2, 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
    events = _events(journal)
    served = [e for e in events if e.get("kind") == "serve_request"]
    assert len(served) == 3 * (len(lengths) + 1)
    assert all(e.get("ok") for e in served)
    # the headline: one compile per (kernel, bucket) across ALL
    # requests from all clients
    assert len(_aot_bucket_events(events, "scan", "8192")) == 1
    assert len(_aot_bucket_events(events, "vector_add", "1024")) == 1
    # padding waste was recorded for the non-exact scans
    fracs = [e.get("pad_frac") for e in served
             if e.get("kernel") == "scan" and e.get("bucketed")]
    assert any(f and f > 0 for f in fracs)
    assert proc.poll() is None or proc.returncode == 0


def test_batch_window_coalesces_same_bucket(tmp_path):
    """With one worker and a generous window, a concurrent burst of
    same-bucket requests is served as one coalesced batch."""
    from tpukernels.serve import client as serve_client

    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_WORKERS": "1",
        "TPK_SERVE_BATCH_WINDOW_MS": "400",
        # fixed-window mode: this test pins the WINDOW's coalescing
        # semantics; the adaptive policy has its own tests
        # (tests/test_serve_zero_copy.py)
        "TPK_SERVE_BATCH_ADAPT": "0",
    }) as (sock, journal, _proc):
        x = (np.arange(6000) % 17).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        # warm first so the burst is not serialized behind a compile
        with serve_client.ServeClient(sock, timeout_s=120) as c:
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
        errors = []

        def one():
            try:
                with serve_client.ServeClient(sock, timeout_s=120) as c:
                    np.testing.assert_array_equal(
                        c.dispatch("scan", x), want
                    )
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=one) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
    served = [e for e in _events(journal)
              if e.get("kind") == "serve_request"]
    assert len(served) == 7
    assert max(e.get("batch_size") or 0 for e in served) >= 2


def test_backpressure_rejects_with_retry_after(tmp_path):
    """Queue depth 1, one worker, every dispatch slowed 1 s: a burst
    of 8 concurrent requests gets mostly rejected-with-retry-after
    (the admitted ones still answer correctly), and every rejection
    is journaled."""
    from tpukernels.serve import client as serve_client

    plan = json.dumps({"slow_dispatch": {"kernel": "scan",
                                         "delay_s": 1.0}})
    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_WORKERS": "1",
        "TPK_SERVE_BATCH_WINDOW_MS": "0",
        "TPK_SERVE_QUEUE_MAX": "1",
        "TPK_SERVE_REQUEST_TIMEOUT_S": "60",
        "TPK_FAULT_PLAN": plan,
    }) as (sock, journal, _proc):
        x = (np.arange(6000) % 13).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        ok, rejected, errors = [], [], []
        lock = threading.Lock()

        def one():
            try:
                with serve_client.ServeClient(sock, timeout_s=180) as c:
                    out = c.dispatch("scan", x)
                np.testing.assert_array_equal(out, want)
                with lock:
                    ok.append(1)
            except serve_client.ServeRejected as e:
                assert e.retry_after_s > 0
                with lock:
                    rejected.append(e.retry_after_s)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(repr(e))

        threads = [threading.Thread(target=one) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        assert not errors, errors
        assert rejected, "a full queue must reject, not stretch latency"
        assert ok, "admitted requests must still be served"
        assert len(ok) + len(rejected) == 8
    events = _events(journal)
    assert (sum(1 for e in events if e.get("kind") == "serve_rejected")
            == len(rejected))


def test_wedged_worker_abandoned_and_request_requeued(tmp_path):
    """The chaos headline: the FIRST dispatch wedges (SIGALRM-immune,
    via TPK_FAULT_PLAN wedge_dispatch) — the watchdog abandons the
    worker, classifies the timeout, re-queues the request ONCE, and
    the retry answers the client correctly. The daemon stays healthy
    for later requests."""
    from tpukernels.serve import client as serve_client

    plan = json.dumps({"wedge_dispatch": {"kernel": "scan",
                                          "times": 1}})
    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_WORKERS": "2",
        "TPK_SERVE_REQUEST_TIMEOUT_S": "2",
        "TPK_FAULT_PLAN": plan,
    }) as (sock, journal, _proc):
        x = (np.arange(6000) % 11).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        with serve_client.ServeClient(sock, timeout_s=120) as c:
            out = c.dispatch("scan", x)  # survives its own wedge
            np.testing.assert_array_equal(out, want)
            # the daemon still serves after abandoning a worker
            out = c.dispatch("scan", x)
            np.testing.assert_array_equal(out, want)
    events = _events(journal)
    requeued = [e for e in events
                if e.get("kind") == "serve_request_requeued"]
    assert len(requeued) == 1 and requeued[0]["kernel"] == "scan"
    assert any(e.get("kind") == "wedge_classification"
               and e.get("site") == "serve" for e in events)
    assert any(e.get("kind") == "fault_injected"
               and e.get("fault") == "wedge_dispatch" for e in events)
    served = [e for e in events if e.get("kind") == "serve_request"]
    assert [e.get("ok") for e in served] == [True, True]
    assert served[0].get("requeues") == 1


def test_batch_members_behind_wedge_are_rescued(tmp_path):
    """Requests coalesced into a batch BEHIND a wedged request must
    not be stranded in the abandoned worker's thread: the watchdog
    rescues the unstarted remainder back to the queue when it abandons
    the worker, so every client still gets its answer."""
    from tpukernels.serve import client as serve_client

    plan = json.dumps({"wedge_dispatch": {"kernel": "scan",
                                          "times": 1}})
    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_WORKERS": "1",
        "TPK_SERVE_BATCH_WINDOW_MS": "500",
        # fixed window: the rescue path needs members COALESCED
        # behind the wedge — the adaptive window would dispatch the
        # lone first request immediately and never form the batch
        "TPK_SERVE_BATCH_ADAPT": "0",
        "TPK_SERVE_REQUEST_TIMEOUT_S": "2",
        "TPK_FAULT_PLAN": plan,
    }) as (sock, journal, _proc):
        x = (np.arange(6000) % 7).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        errors = []

        def one(delay):
            time.sleep(delay)
            try:
                # 30 s is far past wedge+rescue (~5 s) but far short
                # of a stranded-forever hang
                with serve_client.ServeClient(sock, timeout_s=30) as c:
                    np.testing.assert_array_equal(
                        c.dispatch("scan", x), want
                    )
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        # first request wedges; the next two arrive inside the batch
        # window and coalesce behind it on the single worker
        threads = [threading.Thread(target=one, args=(d,))
                   for d in (0.0, 0.15, 0.25)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors
    events = _events(journal)
    served = [e for e in events if e.get("kind") == "serve_request"]
    assert len(served) == 3 and all(e.get("ok") for e in served)
    assert (sum(1 for e in events
                if e.get("kind") == "serve_request_requeued") == 1)


def test_bucket_lock_waits_out_slow_holder_replaces_wedged():
    """The one-compile-per-bucket lock discipline: a legitimately slow
    holder (a cold compile) is waited out — the lock is NEVER replaced
    from elapsed time alone — while a holder the watchdog abandoned as
    wedged is replaced promptly so the bucket is not poisoned
    forever."""
    from tpukernels.serve import server as serve_server

    srv = serve_server.Server(
        socket_path="/nonexistent/unused.sock", queue_max=1, workers=1,
        batch_window_ms=0.0, request_timeout_s=0.4,
    )
    held = {}
    release = threading.Event()

    def slow_holder():
        cell = srv._acquire_bucket("b1")
        held["cell"] = cell
        time.sleep(1.2)  # slow but alive: never abandoned
        with srv._lock:
            cell[1] = None
        cell[0].release()

    t = threading.Thread(target=slow_holder)
    t.start()
    time.sleep(0.2)
    t0 = time.monotonic()
    cell = srv._acquire_bucket("b1")
    waited = time.monotonic() - t0
    t.join(10)
    assert cell is held["cell"], "slow holder's lock must not be replaced"
    assert waited > 0.6, f"must wait out the slow holder ({waited:.2f}s)"
    with srv._lock:
        cell[1] = None
    cell[0].release()

    def wedged_holder():
        srv._acquire_bucket("b2")
        held["wedged_ident"] = threading.get_ident()
        release.wait(30)  # holds the lock past any timeout

    t2 = threading.Thread(target=wedged_holder, daemon=True)
    t2.start()
    deadline = time.monotonic() + 10
    while "wedged_ident" not in held:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    with srv._lock:
        srv._abandoned.add(held["wedged_ident"])
    old = srv._bucket_locks["b2"]
    t0 = time.monotonic()
    fresh = srv._acquire_bucket("b2")
    assert fresh is not old, "wedged holder's lock must be replaced"
    assert time.monotonic() - t0 < 5
    release.set()
    t2.join(10)


def test_clean_path_responses_byte_identical(tmp_path):
    """Observability must not perturb the service: a fixed request
    sequence yields byte-identical response payloads whether the
    daemon journals+traces or runs fully dark — and the daemon's
    stdout is EMPTY both ways."""
    from tpukernels.serve import client as serve_client

    def run(tag, env_extra):
        with _daemon(tmp_path, dict(env_extra, **{
            "TPK_SERVE_BUCKETS": SCAN_BUCKET,
            "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        }), tag=tag) as (sock, _journal, proc):
            outs = []
            with serve_client.ServeClient(sock, timeout_s=120) as c:
                for n in (5000, 8192):
                    x = (np.arange(n) % 23).astype(np.int32)
                    out = c.dispatch("scan", x)
                    outs.append((out.shape, out.dtype.name,
                                 out.tobytes()))
            proc.terminate()
            proc.wait(20)
            return outs, proc.stdout.read()

    dark, dark_stdout = run("dark", {"TPK_HEALTH_JOURNAL": "0"})
    lit, lit_stdout = run("lit", {"TPK_TRACE": "1"})
    assert dark == lit
    assert dark_stdout == lit_stdout == ""


def test_capi_routes_through_daemon(tmp_path, monkeypatch):
    """With TPK_SERVE_SOCKET set, capi.run_from_c is one client among
    many: the C-buffer roundtrip answers bit-identically and the
    request lands in the DAEMON's journal; with the daemon gone, the
    in-process fallback still answers."""
    from tpukernels import capi

    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
    }) as (sock, journal, _proc):
        monkeypatch.setenv("TPK_SERVE_SOCKET", sock)
        monkeypatch.setenv("TPK_INTEGRITY", "tripwire")
        capi._SERVE_TLS.client = None
        n = 6000
        x = np.ascontiguousarray(np.arange(n) % 19, dtype=np.int32)
        out = np.zeros(n, dtype=np.int32)
        params = json.dumps({"buffers": [
            {"shape": [n], "dtype": "i32"},
            {"shape": [n], "dtype": "i32"},
        ]})
        assert capi.run_from_c(
            "scan", params, [x.ctypes.data, out.ctypes.data]
        ) == 0
        np.testing.assert_array_equal(
            out, np.cumsum(x, dtype=np.int64).astype(np.int32)
        )
        daemon_pid = _events(journal)[-1]["pid"]
        served = [e for e in _events(journal)
                  if e.get("kind") == "serve_request"]
        assert served and served[-1]["kernel"] == "scan"
        assert served[-1]["pid"] != os.getpid()
    # daemon gone: the retained in-process fallback answers
    capi._SERVE_TLS.client = None
    out2 = np.zeros(n, dtype=np.int32)
    assert capi.run_from_c(
        "scan", params, [x.ctypes.data, out2.ctypes.data]
    ) == 0
    np.testing.assert_array_equal(out2, out)
    monkeypatch.delenv("TPK_SERVE_SOCKET")
    capi._SERVE_TLS.client = None
    del daemon_pid


# ---------------------------------------------------------------- #
# loadgen --serve -> slo.json -> obs_report --check                #
# ---------------------------------------------------------------- #

def test_loadgen_serve_slo_verdict_e2e(tmp_path):
    """The full service-path SLO loop: daemon up, `loadgen --serve`
    drives it open-loop, the verdict lands validated in slo.json, and
    `obs_report --check` gates it with the unchanged rc contract."""
    slo_dir = tmp_path / "slo"
    slo_dir.mkdir()
    with _daemon(tmp_path) as (sock, journal, _proc):
        env = _scrubbed_env(None)
        env["TPK_SLO_DIR"] = str(slo_dir)
        env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "lg.jsonl")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--serve", sock, "--kernel", "scan", "--arrivals",
             "poisson", "--seed", "7", "--requests", "30", "--rate",
             "10", "--check"],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "(SERVED)" in r.stdout
        served = [e for e in _events(journal)
                  if e.get("kind") == "serve_request"]
        assert len(served) == 31  # 30 scheduled + 1 untimed warm
    with open(slo_dir / "slo.json") as f:
        entries = json.load(f)["entries"]
    entry = entries["scan|probe|cpu"]
    assert entry["verdict"] == "ok" and not entry["simulated"]
    assert entry["run"]["served"] is True
    assert entry["jax"] is not None  # the daemon's version, via ping
    env = _scrubbed_env(None)
    env["TPK_SLO_DIR"] = str(slo_dir)
    chk = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr


def test_loadgen_serve_usage_errors():
    env = _scrubbed_env(None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--serve", "/nonexistent.sock", "--simulate", "5",
         "--requests", "5"],
        capture_output=True, text=True, timeout=60, cwd=REPO, env=env,
    )
    assert r.returncode == 2
    assert "exclusive" in r.stderr
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--serve", "/nonexistent.sock", "--kernel", "scan",
         "--requests", "5"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert r.returncode == 2
    assert "unreachable" in r.stderr


# ---------------------------------------------------------------- #
# serve_ctl lifecycle                                              #
# ---------------------------------------------------------------- #

def test_serve_ctl_start_status_stop(tmp_path):
    """The operator loop: start answers a ping, a second start is
    refused rc 3 (flocked pidfile — the revalidate_lib convention),
    stop releases cleanly, status reports DOWN after."""
    ctl = os.path.join(REPO, "tools", "serve_ctl.py")
    env = _scrubbed_env(None)
    env["TPK_SERVE_DIR"] = str(tmp_path)
    env["TPK_HEALTH_JOURNAL"] = str(tmp_path / "health.jsonl")

    def run(*args, timeout=120):
        return subprocess.run(
            [sys.executable, ctl, *args], capture_output=True,
            text=True, timeout=timeout, cwd=REPO, env=env,
        )

    try:
        r = run("start", "--wait", "60")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "daemon up" in r.stdout
        r = run("status")
        assert r.returncode == 0 and "UP" in r.stdout, r.stdout
        r = run("start", "--wait", "60")
        assert r.returncode == 3, r.stdout + r.stderr
        assert "already running" in r.stdout
    finally:
        r = run("stop")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stopped" in r.stdout
    r = run("status")
    assert r.returncode == 1 and "DOWN" in r.stdout
    events = _events(str(tmp_path / "health.jsonl"))
    assert any(e.get("kind") == "serve_start" for e in events)
    assert any(e.get("kind") == "serve_stop" for e in events)


# ---------------------------------------------------------------- #
# serve-over-mesh tier (ISSUE 20)                                   #
# ---------------------------------------------------------------- #

def test_mesh_tier_admission_rules(monkeypatch):
    """bucketing.mesh_tier_for is env-only (admission must never init
    a backend): it offers the mesh tier exactly when the kernel is
    mesh-capable, the env inventory shows > 1 device, and the leading
    dim divides across them."""
    from tpukernels.serve import bucketing

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    big = np.zeros(1 << 15, np.int32)
    assert bucketing.mesh_tier_for("scan", [big], {}) == (4,)
    # non-mesh kernel: no tier, however big the request
    assert bucketing.mesh_tier_for(
        "sgemm", [np.zeros((512, 512), np.float32)] * 2, {}) is None
    # leading dim must divide across the inventory
    assert bucketing.mesh_tier_for(
        "scan", [np.zeros((1 << 15) + 1, np.int32)], {}) is None
    # nbody needs its full 7-array state, every array the same (N,)
    assert bucketing.mesh_tier_for(
        "nbody", [np.zeros(64, np.float32)] * 7, {}) == (4,)
    assert bucketing.mesh_tier_for(
        "nbody", [np.zeros(64, np.float32)] * 6, {}) is None
    # no usable device inventory -> no tier (a real pod admits only
    # after the worker-side probe, never from env guesswork)
    monkeypatch.setenv("XLA_FLAGS", "")
    assert bucketing.mesh_tier_for("scan", [big], {}) is None
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    assert bucketing.mesh_tier_for("scan", [big], {}) is None


def test_serve_mesh_tier_end_to_end(tmp_path):
    """ISSUE 20 acceptance: an oversized request (4x the scan avatar)
    is not rejected — it routes to the mesh tier, dispatches through
    the mesh-backed executable, and its serve_request carries the mesh
    shape; an in-avatar request on the same daemon still buckets
    normally with no mesh stamp."""
    from tpukernels.serve import client as serve_client

    with _daemon(tmp_path, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_BATCH_WINDOW_MS": "0",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }) as (sock, journal, _proc):
        x = (np.arange(32768) % 29).astype(np.int32)
        small = (np.arange(4096) % 29).astype(np.int32)
        with serve_client.ServeClient(sock, timeout_s=120) as c:
            out = c.dispatch("scan", x)
            np.testing.assert_array_equal(
                out, np.cumsum(x, dtype=np.int64).astype(np.int32))
            out2 = c.dispatch("scan", small)
            np.testing.assert_array_equal(
                out2,
                np.cumsum(small, dtype=np.int64).astype(np.int32))
    served = [e for e in _events(journal)
              if e.get("kind") == "serve_request"]
    assert len(served) == 2, served
    big = next(e for e in served if e["shapes"] == [[32768]])
    sml = next(e for e in served if e["shapes"] == [[4096]])
    assert big["mesh_shape"] == [4], big
    assert big["bucket"].endswith("|mesh4"), big["bucket"]
    assert big["ok"] and not big["bucketed"], big
    assert sml["mesh_shape"] is None and sml["bucketed"], sml
